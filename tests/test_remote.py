"""Cross-host plane tests (ISSUE 15): binary wire codec bit-equality
(standalone and against in-process ``submit_prepared``), keep-alive
connection reuse, host death → eject → reroute within the original
deadline, sha-verified resumable store pulls, scheduler hysteresis on
synthetic gauge traces, and the hung-scrape backoff regression.

Everything runs in-process and stubbed: "agents" are
:class:`~mx_rcnn_tpu.serve.agent.ReplicaAgent` + ``make_agent_server``
on loopback ports with stub run_fns (no model, no compiles), so the
whole file is quick-tier.  The multi-PROCESS version of these claims —
real ``tools/agent.py`` subprocesses, SIGKILL, the live scheduler — is
the bench's job (``tools/loadgen.py --crosshost_bench``).
"""

import hashlib
import json
import os
import socket
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.obs.collect import (Collector, HttpSource,
                                     RegistrySource)
from mx_rcnn_tpu.obs.metrics import Registry
from mx_rcnn_tpu.obs.timeseries import TimeSeriesStore
from mx_rcnn_tpu.serve.agent import (ReplicaAgent, StorePullError,
                                     make_agent_server,
                                     make_store_server, pull_store)
from mx_rcnn_tpu.serve.fleet import build_fleet
from mx_rcnn_tpu.serve.remote import (RemoteEngine,
                                      agent_urls_from_cfg,
                                      build_crosshost_router,
                                      decode_prepared, decode_result,
                                      encode_prepared, encode_result,
                                      normalize_agent_url)
from mx_rcnn_tpu.serve.scheduler import (AgentAdmin, AgentAdminError,
                                         AgentAdminTimeout,
                                         FleetScheduler, SchedulerPolicy,
                                         per_agent_backlog,
                                         per_agent_ready)
from mx_rcnn_tpu.tools.loadgen import (make_content_stub_run_fn,
                                       make_stub_run_fn)


def _cfg(**kw):
    over = {
        "bucket__scale": 128, "bucket__max_size": 160,
        "bucket__shapes": ((128, 160), (160, 128)),
        "serve__batch_size": 2, "serve__max_delay_ms": 5.0,
        "fleet__health_interval_s": 30.0,
    }
    over.update(kw)
    return generate_config("tiny", "synthetic", **over)


def _frame(cfg, seed=0, bucket=None):
    b = tuple(bucket or cfg.bucket.shapes[0])
    rng = np.random.RandomState(seed)
    return (rng.rand(*b, 3).astype(np.float32) * 255.0,
            np.array([b[0], b[1], 1.0], np.float32), b)


def _start_agent(cfg, stub="content", model_ms=0.0):
    """In-process agent + HTTP server on a free loopback port."""
    if stub == "content":
        factory = (lambda rid: make_content_stub_run_fn(cfg, model_ms))
    else:
        factory = (lambda rid: make_stub_run_fn(cfg, model_ms, seed=0))
    ag = ReplicaAgent(cfg, None, {}, run_fn_factory=factory)
    srv = make_agent_server(ag, "127.0.0.1", 0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return ag, srv, f"http://127.0.0.1:{srv.server_address[1]}"


def _stop_agent(ag, srv):
    srv.shutdown()
    srv.server_close()
    ag.close()


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------

def test_codec_prepared_round_trip_bit_equal():
    cfg = _cfg()
    data, info, _b = _frame(cfg, seed=3)
    buf = encode_prepared(data, info, 1234.5)
    out, oinfo, t = decode_prepared(buf)
    assert out.dtype == np.float32 and out.shape == data.shape
    assert out.tobytes() == data.tobytes()  # bit-equal, not just close
    assert oinfo.tobytes() == info.tobytes()
    assert t == np.float32(1234.5)


def test_codec_prepared_rejects_malformed():
    cfg = _cfg()
    data, info, _b = _frame(cfg)
    buf = encode_prepared(data, info, 0.0)
    with pytest.raises(ValueError):
        decode_prepared(buf[:10])           # truncated header
    with pytest.raises(ValueError):
        decode_prepared(b"XXXX" + buf[4:])  # bad magic
    with pytest.raises(ValueError):
        decode_prepared(buf[:-8])           # short payload
    with pytest.raises(ValueError):
        decode_prepared(buf + b"\0\0")      # trailing bytes
    with pytest.raises(ValueError):
        encode_prepared(data[..., 0], info, 0.0)  # not (h, w, c)


def test_codec_prepared_rejects_hostile_timeout():
    """Wire-supplied timeouts are sanitized AT DECODE (netio
    check_timeout_ms): an inf lands in ``Condition.wait`` as an
    OverflowError (a 500 for client bytes), a NaN poisons every
    deadline comparison, and one flipped exponent bit makes 1e38 —
    finite, but still over the C timestamp range."""
    cfg = _cfg()
    data, info, _b = _frame(cfg)
    for hostile in (float("inf"), float("nan"), -1.0, 1e38):
        buf = bytearray(encode_prepared(data, info, 0.0))
        struct.pack_into("<f", buf, 14, hostile)  # the timeout_ms field
        with pytest.raises(ValueError):
            decode_prepared(bytes(buf))


def test_codec_result_round_trip_and_malformed():
    rng = np.random.RandomState(0)
    dets = {1: rng.rand(4, 5).astype(np.float32),
            7: np.zeros((0, 5), np.float32)}
    out = decode_result(encode_result(dets))
    assert sorted(out) == [1, 7]
    for cid in dets:
        assert out[cid].tobytes() == dets[cid].tobytes()
        assert out[cid].shape == dets[cid].shape
    buf = encode_result(dets)
    with pytest.raises(ValueError):
        decode_result(buf[:4])
    with pytest.raises(ValueError):
        decode_result(b"YYYY" + buf[4:])
    with pytest.raises(ValueError):
        decode_result(buf + b"\0")
    with pytest.raises(ValueError):
        encode_result({1: np.zeros((2, 4), np.float32)})  # not (k, 5)


def test_normalize_agent_url():
    assert normalize_agent_url("127.0.0.1:9201") == "http://127.0.0.1:9201"
    assert normalize_agent_url("http://h:1/") == "http://h:1"


# ---------------------------------------------------------------------------
# remote vs in-process bit-equality + keep-alive reuse
# ---------------------------------------------------------------------------

def test_remote_submit_prepared_bit_equal_to_inprocess():
    """The tentpole pin: the same prepared frame through the binary
    wire, the JSON control arm, and the in-process router must produce
    IDENTICAL detections (the content stub is deterministic in the
    batch bytes, so any wire-layer corruption shows up as a diff)."""
    cfg = _cfg(fleet__replicas=1)
    local = build_fleet(
        cfg, None, {},
        run_fn_factory=lambda rid: make_content_stub_run_fn(cfg))
    ag, srv, url = _start_agent(cfg, stub="content")
    try:
        data, info, b = _frame(cfg, seed=11)
        want = local.submit_prepared(data, info, b,
                                     timeout_ms=10_000).wait(20.0)
        assert want, "in-process baseline produced no detections"
        for arm in ("binary", "json"):
            eng = RemoteEngine(f"t-{arm}", url, cfg, wire=arm)
            try:
                got = eng.submit_prepared(data, info, b,
                                          timeout_ms=10_000).wait(20.0)
                assert sorted(got) == sorted(want), arm
                for cid in want:
                    assert got[cid].tobytes() == np.ascontiguousarray(
                        want[cid], np.float32).tobytes(), (arm, cid)
            finally:
                eng.close()
    finally:
        _stop_agent(ag, srv)
        local.close()


def test_keep_alive_connection_reuse_pinned():
    """A burst must ride the persistent connections: exactly
    ``crosshost.connections`` sockets opened client-side, and the agent
    server accepts exactly that many — no per-request reconnects."""
    cfg = _cfg(crosshost__connections=2, crosshost__pipeline_depth=16)
    ag, srv, url = _start_agent(cfg, stub="plain")
    try:
        before = srv.connections
        eng = RemoteEngine("t-keepalive", url, cfg, probe=False)
        try:
            reqs = []
            for i in range(24):
                data, info, b = _frame(cfg, seed=i,
                                       bucket=cfg.bucket.shapes[i % 2])
                reqs.append(eng.submit_prepared(data, info, b,
                                                timeout_ms=20_000))
            for r in reqs:
                assert r.wait(30.0) is not None
            assert eng.conns_opened == 2
            assert srv.connections - before == 2
        finally:
            eng.close()
    finally:
        _stop_agent(ag, srv)


# ---------------------------------------------------------------------------
# host death → eject → reroute within the original deadline
# ---------------------------------------------------------------------------

def test_host_death_ejects_and_reroutes_within_deadline():
    cfg = _cfg(crosshost__connections=1, crosshost__pipeline_depth=16,
               crosshost__dead_after_failures=2,
               crosshost__scrape_interval_s=0.1,
               fleet__health_interval_s=0.1,
               fleet__reroute_retries=3)
    agents = [_start_agent(cfg, stub="plain", model_ms=5.0)
              for _ in range(2)]
    router, feed = build_crosshost_router(
        cfg, [a[2] for a in agents])
    try:
        # no traffic yet: the engines' worker sockets are lazy, so
        # closing the victim's listener kills the host completely
        _stop_agent(*agents[1][:2])
        t0 = time.monotonic()
        reqs = []
        for i in range(8):
            data, info, b = _frame(cfg, seed=i,
                                   bucket=cfg.bucket.shapes[i % 2])
            reqs.append(router.submit_prepared(data, info, b,
                                               timeout_ms=15_000))
        for r in reqs:
            assert r.wait(20.0) is not None  # SERVED, not failed/expired
        assert time.monotonic() - t0 < 15.0  # inside the original budget
        deadline = time.monotonic() + 10.0
        while router.manager.ejects < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert router.manager.ejects >= 1
    finally:
        feed.close()
        router.close()
        _stop_agent(*agents[0][:2])


# ---------------------------------------------------------------------------
# store pull: skip / resume / sha refusal
# ---------------------------------------------------------------------------

def _mk_store(root, sizes):
    rng = np.random.RandomState(7)
    os.makedirs(os.path.join(root, "sub"), exist_ok=True)
    for rel, n in sizes.items():
        with open(os.path.join(root, rel), "wb") as f:
            f.write(rng.bytes(n))
    with open(os.path.join(root, "manifest.json"), "w") as f:
        json.dump({"files": sorted(sizes)}, f)


def _sha(path):
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def test_store_pull_skip_resume_and_refusal(tmp_path):
    root = str(tmp_path / "store")
    _mk_store(root, {"a.bin": 1 << 16, "sub/b.bin": 1 << 12})
    srv = make_store_server(root)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        d1 = str(tmp_path / "d1")
        stats = pull_store(url, d1)
        assert stats["files"] == 3 and not stats["refused"]
        assert _sha(os.path.join(d1, "a.bin")) == _sha(
            os.path.join(root, "a.bin"))
        # idempotent re-join: everything skips, nothing transfers
        again = pull_store(url, d1)
        assert again["skipped"] == 3 and again["files"] == 0

        # resume-after-truncation: a half-written staging file picks up
        # with a Range request — the server log proves the offset
        d2 = str(tmp_path / "d2")
        os.makedirs(d2)
        with open(os.path.join(root, "a.bin"), "rb") as f:
            half = f.read((1 << 16) // 2)
        with open(os.path.join(d2, "a.bin.part"), "wb") as f:
            f.write(half)
        stats = pull_store(url, d2)
        assert stats["resumed"] == 1 and stats["refused"] == 0
        assert _sha(os.path.join(d2, "a.bin")) == _sha(
            os.path.join(root, "a.bin"))
        with srv.stats_lock:
            starts = [r["start"] for r in srv.requests
                      if r["rel"] == "a.bin" and r["start"]]
        assert starts == [len(half)]

        # corrupt staging bytes: the resumed file fails sha, the pull
        # REFUSES it, re-pulls whole, and still lands correct bytes
        d3 = str(tmp_path / "d3")
        os.makedirs(d3)
        with open(os.path.join(d3, "a.bin.part"), "wb") as f:
            f.write(b"\xff" * len(half))
        stats = pull_store(url, d3)
        assert stats["refused"] == 1
        assert _sha(os.path.join(d3, "a.bin")) == _sha(
            os.path.join(root, "a.bin"))
    finally:
        srv.shutdown()
        srv.server_close()


def test_store_pull_double_mismatch_raises(tmp_path):
    root = str(tmp_path / "store")
    _mk_store(root, {"a.bin": 1 << 12})
    srv = make_store_server(root)  # sha index frozen here...
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        # ...then the bytes change under it: every pull mismatches, and
        # after the one whole-file retry the join must fail LOUDLY
        with open(os.path.join(root, "a.bin"), "r+b") as f:
            f.write(b"\x00" * 16)
        with pytest.raises(StorePullError):
            pull_store(url, str(tmp_path / "d"))
    finally:
        srv.shutdown()
        srv.server_close()


# ---------------------------------------------------------------------------
# scheduler: synthetic gauge traces
# ---------------------------------------------------------------------------

def _sched_cfg(**kw):
    over = {"crosshost__for_samples": 2, "crosshost__idle_samples": 3,
            "crosshost__cooldown_s": 5.0, "crosshost__window_s": 3.0,
            "crosshost__min_replicas": 1, "crosshost__max_replicas": 8,
            "crosshost__up_shed_ratio": 0.05,
            "crosshost__up_backlog": 2.0}
    over.update(kw)
    return _cfg(**over)


def _snap(store, ts, ready, backlog=None, counters=None):
    snap = {"counters": dict(counters or {}), "gauges": {}}
    for src, v in ready.items():
        snap["gauges"][f"agent.replicas_ready@{src}"] = v
    for src, v in (backlog or {}).items():
        snap["gauges"][f"lane.128x160.depth@{src}"] = v
    store.append_snapshot(snap, ts=ts)


def test_per_agent_parsers_ignore_nested_labels():
    """The head re-labels agent snapshots, producing ``@router`` and
    ``@router@agent-0`` duplicates — counting those would double a
    host's capacity."""
    store = TimeSeriesStore(capacity=8)
    snap = {"counters": {}, "gauges": {
        "agent.replicas_ready@agent-0": 2.0,
        "agent.replicas_ready@router": 2.0,
        "agent.replicas_ready@router@agent-0": 2.0,
        "lane.128x160.depth@agent-0": 3.0,
        "lane.128x160.depth@serve-1@agent-0": 3.0,
    }}
    smp = store.append_snapshot(snap, ts=1.0)
    assert per_agent_ready(smp) == {"agent-0": 2.0}
    assert per_agent_backlog(smp) == {"agent-0": 3.0}


def test_scheduler_adopts_target_and_adds_on_deficit():
    cfg = _sched_cfg()
    store = TimeSeriesStore(capacity=64)
    pol = SchedulerPolicy(cfg)
    _snap(store, 0.0, {"agent-0": 1, "agent-1": 1})
    assert pol.decide(store, now=0.0) is None
    assert pol.target == 2  # adopted from the fleet, not configured
    # host death: agent-1's gauges vanish from the sample
    _snap(store, 1.0, {"agent-0": 1})
    assert pol.decide(store, now=1.0) is None  # hysteresis: 1 < 2
    _snap(store, 2.0, {"agent-0": 1})
    act = pol.decide(store, now=2.0)
    assert act and act["action"] == "add" and act["source"] == "agent-0"
    # cooldown gates the next action...
    _snap(store, 2.5, {"agent-0": 1})
    assert pol.decide(store, now=2.5) is None
    # ...but the streak keeps advancing through it, so a breach that
    # outlives the cooldown acts the moment it lifts
    _snap(store, 7.5, {"agent-0": 1})
    act = pol.decide(store, now=7.5)
    assert act and act["action"] == "add"


def test_scheduler_overload_adds_and_raises_target():
    cfg = _sched_cfg()
    store = TimeSeriesStore(capacity=64)
    pol = SchedulerPolicy(cfg)
    ready = {"agent-0": 1, "agent-1": 1}
    _snap(store, 0.0, ready, counters={"fleet.submitted": 0,
                                       "fleet.shed": 0})
    assert pol.decide(store, now=0.0) is None
    for i, ts in enumerate((1.0, 2.0)):
        _snap(store, ts, ready,
              counters={"fleet.submitted": 100 * (i + 1),
                        "fleet.shed": 50 * (i + 1)})
        act = pol.decide(store, now=ts)
    assert act and act["action"] == "add"
    assert pol.target == 3  # overload grows intent, not just capacity


def test_scheduler_idle_is_traffic_gated_and_floored():
    cfg = _sched_cfg()
    store = TimeSeriesStore(capacity=64)
    pol = SchedulerPolicy(cfg)
    ready = {"agent-0": 2, "agent-1": 1}
    # comfortable but BUSY: no backlog, no shed, traffic flowing — the
    # fleet must keep its capacity
    for i in range(6):
        _snap(store, float(i), ready,
              counters={"fleet.submitted": 100 * i, "fleet.shed": 0})
        assert pol.decide(store, now=float(i)) is None
    # truly quiet: flat counters → drain, from the agent with >1
    for i in range(6, 12):
        _snap(store, float(i), ready,
              counters={"fleet.submitted": 600, "fleet.shed": 0})
        act = pol.decide(store, now=float(i))
        if act:
            break
    assert act and act["action"] == "drain" and act["source"] == "agent-0"
    assert pol.target == 2
    # every agent at its 1-replica floor: idle never drains (and never
    # decrements the target against a resize the agent would refuse)
    pol2 = SchedulerPolicy(cfg)
    store2 = TimeSeriesStore(capacity=64)
    for i in range(10):
        _snap(store2, float(i), {"agent-0": 1, "agent-1": 1})
        assert pol2.decide(store2, now=float(i)) is None
    assert pol2.target == 2


def test_scheduler_no_flap_on_alternating_trace():
    cfg = _sched_cfg()
    store = TimeSeriesStore(capacity=64)
    pol = SchedulerPolicy(cfg)
    _snap(store, 0.0, {"agent-0": 1, "agent-1": 1})
    assert pol.decide(store, now=0.0) is None
    for i in range(1, 12):  # breach / clean / breach / clean ...
        ready = ({"agent-0": 1} if i % 2
                 else {"agent-0": 1, "agent-1": 1})
        _snap(store, float(i), ready)
        assert pol.decide(store, now=float(i)) is None


def test_agent_admin_resize_roundtrip():
    cfg = _cfg(crosshost__agent_replicas=1)
    ag, srv, url = _start_agent(cfg, stub="plain")
    try:
        admin = AgentAdmin([url])
        r = admin.resize("agent-0", +1)
        assert r and r["replicas"] == 2 and r["added"] == 1
        deadline = time.monotonic() + 20.0
        while (len(ag.manager.ready_replicas()) < 2
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert len(ag.manager.ready_replicas()) == 2
        r = admin.resize("agent-0", -1)
        assert r and r["replicas"] == 1 and r["drained"] == 1
        # the floor: an agent never resizes below one local replica
        r = admin.resize("agent-0", -5)
        assert r and r["replicas"] == 1 and r["drained"] == 0
        assert admin.resize("no-such-agent", 1) is None
    finally:
        _stop_agent(ag, srv)


def test_agent_admin_timeout_is_typed_and_tick_stays_alive():
    """ISSUE 16 satellite: every admin RPC carries a hard per-request
    deadline.  A hung (accepting-but-never-answering) agent costs one
    bounded RPC with a TYPED error on the tick record — never a wedged
    scheduler loop."""
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _HungHandler)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        admin = AgentAdmin([url], timeout_s=0.3)
        t0 = time.monotonic()
        assert admin.resize("agent-0", +1) is None
        assert time.monotonic() - t0 < 2.0  # the deadline, not the hang
        assert isinstance(admin.last_error, AgentAdminTimeout)

        # drive a real deficit tick against the hung agent (the
        # hysteresis dance from test_scheduler_adopts_target...)
        sched = FleetScheduler(TimeSeriesStore(capacity=64), admin,
                               _sched_cfg())
        _snap(sched.store, 0.0, {"agent-0": 1, "agent-1": 1})
        assert sched.tick(now=0.0) is None
        _snap(sched.store, 1.0, {"agent-0": 1})
        sched.tick(now=1.0)
        _snap(sched.store, 2.0, {"agent-0": 1})
        t0 = time.monotonic()
        act = sched.tick(now=2.0)
        assert time.monotonic() - t0 < 2.0
        assert act is not None and act["result"] is None
        assert act["error"] == "AgentAdminTimeout"
        assert sched.actions[-1] is act
    finally:
        srv.shutdown()
        srv.server_close()


def test_agent_admin_refused_socket_is_typed_not_timeout():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()  # nobody listening: connection refused, not a hang
    admin = AgentAdmin([f"http://127.0.0.1:{port}"], timeout_s=0.5)
    assert admin.resize("agent-0", 1) is None
    assert isinstance(admin.last_error, AgentAdminError)
    assert not isinstance(admin.last_error, AgentAdminTimeout)
    # a later success clears the sticky error
    cfg = _cfg(crosshost__agent_replicas=1)
    ag, asrv, aurl = _start_agent(cfg, stub="plain")
    try:
        ok_admin = AgentAdmin([aurl], timeout_s=10.0)
        ok_admin.last_error = AgentAdminError("stale")
        assert ok_admin.resize("agent-0", 0) is not None
        assert ok_admin.last_error is None
    finally:
        _stop_agent(ag, asrv)


def test_agent_admin_from_config_carries_timeout():
    cfg = _cfg(crosshost__admin_timeout_s=1.25)
    admin = AgentAdmin.from_config(["http://h:1"], cfg)
    assert admin.timeout_s == 1.25


# ---------------------------------------------------------------------------
# hung-scrape backoff (the obs/collect.py regression)
# ---------------------------------------------------------------------------

class _HungHandler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 — accepts, then never answers
        time.sleep(3.0)

    do_POST = do_GET  # admin RPCs hang the same way

    def log_message(self, *a):
        pass


def test_hung_source_backoff_bounds_the_collect_loop():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _HungHandler)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    reg = Registry()
    reg.set_gauge("ok.gauge", 1.0)
    hung = HttpSource("hung",
                      f"http://127.0.0.1:{srv.server_address[1]}",
                      timeout_s=0.3, backoff_base_s=5.0,
                      backoff_cap_s=10.0)
    col = Collector([hung, RegistrySource("good", reg)])
    try:
        t0 = time.monotonic()
        view = col.collect()
        first = time.monotonic() - t0
        assert first < 2.0  # one per-request timeout, not a 3s hang
        assert not view["sources"]["hung"]["up"]
        assert view["sources"]["good"]["up"]
        assert hung.failures() == 1
        # inside the backoff window the socket is never touched: the
        # wedged host costs the loop (and the healthy source) nothing
        t0 = time.monotonic()
        view = col.collect()
        assert time.monotonic() - t0 < 0.2
        assert not view["sources"]["hung"]["up"]
        assert view["sources"]["good"]["up"]
    finally:
        srv.shutdown()
        srv.server_close()


def test_crosshost_config_section_and_overrides():
    cfg = generate_config("tiny", "synthetic",
                          crosshost__connections=3,
                          crosshost__pipeline_depth=7,
                          crosshost__up_shed_ratio=0.2,
                          crosshost__agents="h1:1,h2:2")
    assert cfg.crosshost.connections == 3
    assert cfg.crosshost.pipeline_depth == 7
    assert cfg.crosshost.up_shed_ratio == 0.2
    assert agent_urls_from_cfg(cfg) == ["http://h1:1", "http://h2:2"]
    with pytest.raises(ValueError):
        build_crosshost_router(_cfg())  # no URLs anywhere
