"""COCO bbox evaluation validation (VERDICT r1 item 5).

pycocotools cannot be installed in this environment, so ``evaluate_bbox``
is validated two ways:

1. hand-derived golden cases encoding pycocotools' documented matching
   semantics — ``iou >= threshold`` matching, score-ordered greedy
   assignment, crowd boxes as repeatable ignore regions with
   intersection/det-area IoU, per-area-range gt ignoring, 101-point
   interpolated precision averaged over IoU .50:.05:.95;
2. an independently-written AP50 oracle compared on randomized multi-image
   multi-category cases (implementation diversity catches matching bugs a
   same-author golden cannot).

Plus a COCO annotation-loading test (VERDICT: no test touched COCO code).
"""

import json
import os

import numpy as np
import pytest

from mx_rcnn_tpu.data.coco import COCODataset
from mx_rcnn_tpu.data.coco_eval import evaluate_bbox


def _one_cat(dets, gts):
    """Wrap per-image det/gt lists for category 1."""
    d = {img: {1: np.asarray(v, np.float32).reshape(-1, 5)}
         for img, v in dets.items()}
    g = {}
    for img, entry in gts.items():
        boxes = np.asarray(entry["boxes"], np.float32).reshape(-1, 4)
        g[img] = {1: {
            "boxes": boxes,
            "iscrowd": np.asarray(entry.get("iscrowd",
                                            [False] * len(boxes)), bool),
            "area": np.asarray(entry.get(
                "area",
                (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1]))),
        }}
    return d, g


def test_perfect_detection_all_metrics():
    d, g = _one_cat({"im0": [[0, 0, 10, 10, 0.9]]},
                    {"im0": {"boxes": [[0, 0, 10, 10]]}})
    r = evaluate_bbox(d, g, [1])
    assert r["AP"] == pytest.approx(1.0)
    assert r["AP50"] == pytest.approx(1.0)
    assert r["AP75"] == pytest.approx(1.0)
    # area 100 < 32^2 → the gt counts only in 'small' (and 'all')
    assert r["AP_small"] == pytest.approx(1.0)
    assert np.isnan(r["AP_medium"])
    assert np.isnan(r["AP_large"])
    assert r["AR_100"] == pytest.approx(1.0)


def test_iou_boundary_inclusive():
    """A det at IoU exactly 0.6 matches thresholds .5/.55/.6 (pycocotools
    matching is iou >= t) → AP = 3/10, AP50 = 1, AP75 = 0."""
    d, g = _one_cat({"im0": [[0, 0, 10, 6, 0.9]]},
                    {"im0": {"boxes": [[0, 0, 10, 10]]}})
    # IoU = 60 / (100 + 60 - 60) = 0.6 exactly
    r = evaluate_bbox(d, g, [1])
    assert r["AP50"] == pytest.approx(1.0)
    assert r["AP75"] == pytest.approx(0.0)
    assert r["AP"] == pytest.approx(0.3)


def test_crowd_is_ignore_region_not_fp():
    """A higher-scoring det that only overlaps a crowd region must be
    IGNORED (excluded from PR), not counted as a false positive.  With the
    crowd rule: AP = 1.0; without it the FP outranks the TP → AP = 0.5."""
    d, g = _one_cat(
        {"im0": [[22, 2, 38, 18, 0.95],    # inside the crowd region only
                 [0, 0, 10, 10, 0.90]]},   # exact match of the real gt
        {"im0": {"boxes": [[0, 0, 10, 10], [20, 0, 40, 20]],
                 "iscrowd": [False, True]}})
    r = evaluate_bbox(d, g, [1])
    assert r["AP"] == pytest.approx(1.0)
    assert r["AR_100"] == pytest.approx(1.0)


def test_duplicate_detection_is_fp():
    """Second det on an already-matched gt is a FP: 2 gts, both dets on
    gt1 → recall caps at 0.5, precision [1, .5] → 101-pt AP = 51/101."""
    d, g = _one_cat(
        {"im0": [[0, 0, 10, 10, 0.9], [1, 0, 11, 10, 0.8]]},
        {"im0": {"boxes": [[0, 0, 10, 10], [50, 50, 60, 60]]}})
    r = evaluate_bbox(d, g, [1])
    assert r["AP50"] == pytest.approx(51 / 101)
    assert r["AP"] == pytest.approx(51 / 101)


def test_area_range_gt_ignored_outside_range():
    """A 64x64 gt (area 4096, 'medium') is ignored in the 'small' range;
    its det must then be ignored there too, not become a small-range FP."""
    d, g = _one_cat(
        {"im0": [[0, 0, 64, 64, 0.9], [100, 100, 116, 116, 0.8]]},
        {"im0": {"boxes": [[0, 0, 64, 64], [100, 100, 116, 116]]}})
    # second gt is 16x16 (area 256, small)
    r = evaluate_bbox(d, g, [1])
    assert r["AP"] == pytest.approx(1.0)
    assert r["AP_small"] == pytest.approx(1.0)   # only the 16x16 pair counts
    assert r["AP_medium"] == pytest.approx(1.0)  # only the 64x64 pair counts
    assert np.isnan(r["AP_large"])


# ---------------------------------------------------------------------------
# independent AP50 oracle
# ---------------------------------------------------------------------------

def _ap50_oracle(dets_by_img, gts_by_img):
    """Straightforward single-threshold (0.5) AP with 101-pt interpolation,
    written independently of coco_eval.py's vectorized implementation."""
    def iou(a, b):
        ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
        iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = ix * iy
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    records = []
    npos = 0
    for img in set(dets_by_img) | set(gts_by_img):
        gts = list(gts_by_img.get(img, []))
        npos += len(gts)
        used = [False] * len(gts)
        dets = sorted(dets_by_img.get(img, []), key=lambda r: -r[4])
        for det in dets:
            best, bi = 0.5, -1
            for gi, gt in enumerate(gts):
                if used[gi]:
                    continue
                v = iou(det, gt)
                if v >= best:
                    best, bi = v, gi
            if bi >= 0:
                used[bi] = True
                records.append((det[4], True))
            else:
                records.append((det[4], False))
    if npos == 0:
        return float("nan")
    records.sort(key=lambda r: -r[0])
    tp = fp = 0
    pr = []
    for _, is_tp in records:
        tp += is_tp
        fp += not is_tp
        pr.append((tp / npos, tp / (tp + fp)))
    ap = 0.0
    for r_thr in np.linspace(0, 1, 101):
        ps = [p for rec, p in pr if rec >= r_thr]
        ap += max(ps) if ps else 0.0
    return ap / 101


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_ap50_matches_independent_oracle(seed):
    rng = np.random.RandomState(seed)
    n_images, n_cats = 4, 3
    dets_all, gts_all = {}, {}
    oracle_aps = []
    for cat in range(1, n_cats + 1):
        d_img, g_img = {}, {}
        for i in range(n_images):
            img = f"im{i}"
            n_gt = rng.randint(0, 4)
            gts = []
            for _ in range(n_gt):
                x, y = rng.uniform(0, 80, 2)
                w, h = rng.uniform(10, 40, 2)
                gts.append([x, y, x + w, y + h])
            n_det = rng.randint(0, 5)
            dets = []
            for _ in range(n_det):
                if gts and rng.rand() < 0.6:  # jittered copy of a gt
                    b = list(gts[rng.randint(len(gts))])
                    jit = rng.uniform(-6, 6, 4)
                    b = [b[k] + jit[k] for k in range(4)]
                else:
                    x, y = rng.uniform(0, 80, 2)
                    w, h = rng.uniform(10, 40, 2)
                    b = [x, y, x + w, y + h]
                dets.append(b + [float(rng.uniform(0.05, 1.0))])
            if dets:
                d_img[img] = dets
            if gts:
                g_img[img] = gts
            dets_all.setdefault(img, {})
            gts_all.setdefault(img, {})
            if dets:
                dets_all[img][cat] = np.asarray(dets, np.float32)
            if gts:
                g = np.asarray(gts, np.float32)
                gts_all[img][cat] = {
                    "boxes": g,
                    "iscrowd": np.zeros(len(g), bool),
                    "area": (g[:, 2] - g[:, 0]) * (g[:, 3] - g[:, 1]),
                }
        if any(len(v) for v in g_img.values()):
            oracle_aps.append(_ap50_oracle(d_img, g_img))
    result = evaluate_bbox(dets_all, gts_all, list(range(1, n_cats + 1)))
    assert result["AP50"] == pytest.approx(np.mean(oracle_aps), abs=1e-9)


# ---------------------------------------------------------------------------
# COCO annotation loading (component 2.22)
# ---------------------------------------------------------------------------

def _mini_coco_json(tmp_path):
    ann = {
        "images": [
            {"id": 7, "file_name": "a.jpg", "width": 100, "height": 80},
            {"id": 3, "file_name": "b.jpg", "width": 50, "height": 60},
        ],
        # non-contiguous category ids, unsorted — must remap to 1..C
        "categories": [
            {"id": 18, "name": "dog"},
            {"id": 1, "name": "person"},
            {"id": 44, "name": "bottle"},
        ],
        "annotations": [
            {"image_id": 7, "category_id": 18, "bbox": [10, 10, 20, 20],
             "area": 400, "iscrowd": 0},
            {"image_id": 7, "category_id": 1, "bbox": [0, 0, 30, 15],
             "area": 450, "iscrowd": 0},
            # crowd: excluded from the training roidb
            {"image_id": 7, "category_id": 1, "bbox": [40, 40, 50, 30],
             "area": 1500, "iscrowd": 1},
            # degenerate zero-area box: dropped
            {"image_id": 3, "category_id": 44, "bbox": [5, 5, 0, 0],
             "area": 0, "iscrowd": 0},
            {"image_id": 3, "category_id": 44, "bbox": [5, 5, 10, 10],
             "area": 100, "iscrowd": 0},
        ],
    }
    ann_dir = tmp_path / "coco" / "annotations"
    os.makedirs(ann_dir)
    with open(ann_dir / "instances_minival.json", "w") as f:
        json.dump(ann, f)
    return str(tmp_path / "coco")


def test_coco_loader_parsing(tmp_path):
    path = _mini_coco_json(tmp_path)
    ds = COCODataset("minival", str(tmp_path), path)
    # categories sorted by id and remapped contiguously: 1→person(1),
    # 18→dog(2), 44→bottle(3)
    assert ds.classes == ["__background__", "person", "dog", "bottle"]
    assert ds.cat_to_class == {1: 1, 18: 2, 44: 3}
    roidb = ds._load_annotations()
    assert len(roidb) == 2
    by_index = {r["index"]: r for r in roidb}
    # image order is sorted by image id
    assert [r["index"] for r in roidb] == [3, 7]
    r7 = by_index[7]
    assert r7["height"] == 80 and r7["width"] == 100
    # crowd annotation excluded → 2 boxes
    assert len(r7["boxes"]) == 2
    assert set(r7["gt_classes"].tolist()) == {1, 2}
    # xywh → xyxy conversion (x2 = x + w - 1)
    dog = r7["boxes"][r7["gt_classes"].tolist().index(2)]
    np.testing.assert_allclose(dog, [10, 10, 29, 29])
    r3 = by_index[3]
    assert len(r3["boxes"]) == 1  # degenerate box dropped
    assert r3["gt_classes"][0] == 3
    assert r3["image"].endswith(os.path.join("minival", "b.jpg"))


def test_coco_evaluate_detections_end_to_end(tmp_path):
    """Perfect detections through COCODataset.evaluate_detections → AP 1.0
    (crowd region ignored), and the results json is written."""
    path = _mini_coco_json(tmp_path)
    ds = COCODataset("minival", str(tmp_path), path)
    roidb = ds._load_annotations()
    all_boxes = [[np.zeros((0, 5), np.float32) for _ in range(2)]
                 for _ in range(ds.num_classes)]
    for i, rec in enumerate(roidb):
        for b, c in zip(rec["boxes"], rec["gt_classes"]):
            # evaluate against the ORIGINAL xywh→xyxy (no -1) gt convention
            det = np.array([[b[0], b[1], b[2] + 1, b[3] + 1, 0.9]],
                           np.float32)
            all_boxes[c][i] = np.concatenate([all_boxes[c][i], det])
    out_dir = str(tmp_path / "results")
    r = ds.evaluate_detections(all_boxes, out_dir)
    # person + dog detect perfectly (crowd region ignored).  The degenerate
    # zero-area bottle annotation is dropped from the TRAINING roidb but —
    # exactly like pycocotools — still counts as (unmatchable) eval gt, so
    # bottle recall caps at 1/2 → AP 51/101.
    assert r["AP"] == pytest.approx((1.0 + 1.0 + 51 / 101) / 3)
    res_file = os.path.join(out_dir, "detections_results.json")
    assert os.path.exists(res_file)
    with open(res_file) as f:
        results = json.load(f)
    assert len(results) == 3
    assert {x["category_id"] for x in results} <= {1, 18, 44}

# ---- greedy matcher: fuzz vs a direct transcription -----------------------

def _evaluate_image_transcription(dets, gt_boxes, gt_ignore, iscrowd,
                                  max_dets):
    """Direct loop transcription of the published pycocotools
    ``evaluateImg`` matching rules (the pre-vectorization implementation) —
    the oracle for the vectorized ``_evaluate_image``."""
    from mx_rcnn_tpu.data.coco_eval import IOU_THRS, _iou_xyxy

    order = np.argsort(-dets[:, 4], kind="mergesort")[:max_dets]
    dets = dets[order]
    nd, ngt, t = len(dets), len(gt_boxes), len(IOU_THRS)
    matched = np.zeros((t, nd), bool)
    ignored = np.zeros((t, nd), bool)
    if ngt:
        gt_order = np.argsort(gt_ignore, kind="mergesort")
        gt_boxes = gt_boxes[gt_order]
        gt_ignore_s = gt_ignore[gt_order]
        crowd_s = iscrowd[gt_order]
        ious = _iou_xyxy(dets[:, :4], gt_boxes, crowd_s)
        for ti, thr in enumerate(IOU_THRS):
            gt_used = np.zeros(ngt, bool)
            for di in range(nd):
                best_iou = min(thr, 1 - 1e-10)
                best_g = -1
                for gi in range(ngt):
                    if gt_used[gi] and not crowd_s[gi]:
                        continue
                    if best_g > -1 and not gt_ignore_s[best_g] \
                            and gt_ignore_s[gi]:
                        break
                    if ious[di, gi] < best_iou:
                        continue
                    best_iou = ious[di, gi]
                    best_g = gi
                if best_g >= 0:
                    gt_used[best_g] = True
                    matched[ti, di] = True
                    ignored[ti, di] = gt_ignore_s[best_g]
    return dets[:, 4], matched, ignored, int((~gt_ignore).sum())


@pytest.mark.parametrize("seed", range(8))
def test_vectorized_matcher_fuzz_vs_transcription(seed):
    """The vectorized matcher must agree with the loop transcription on
    random scenes with crowds, out-of-area gts, IoU ties, and more dets
    than gts (and vice versa)."""
    from mx_rcnn_tpu.data.coco_eval import _evaluate_image

    rng = np.random.RandomState(seed)
    for _ in range(10):
        ngt = rng.randint(0, 7)
        nd = rng.randint(0, 12)
        # coarse integer grid → frequent exact IoU ties
        gt = rng.randint(0, 60, (ngt, 4)).astype(float)
        gt = np.stack([np.minimum(gt[:, 0], gt[:, 1]),
                       np.minimum(gt[:, 2], gt[:, 3]),
                       np.minimum(gt[:, 0], gt[:, 1]) + 10
                       + rng.randint(0, 30, ngt),
                       np.minimum(gt[:, 2], gt[:, 3]) + 10
                       + rng.randint(0, 30, ngt)], 1) if ngt else \
            np.zeros((0, 4))
        # dets: jittered copies of gts plus noise boxes
        rows = []
        for g in gt:
            for _ in range(rng.randint(0, 3)):
                j = rng.randint(-6, 7, 4).astype(float)
                rows.append(np.r_[g + j, rng.rand()])
        for _ in range(nd):
            x1, y1 = rng.randint(0, 50, 2)
            rows.append(np.r_[x1, y1, x1 + rng.randint(5, 40),
                              y1 + rng.randint(5, 40), rng.rand()])
        dets = (np.asarray(rows, float).reshape(-1, 5) if rows
                else np.zeros((0, 5)))
        iscrowd = rng.rand(ngt) < 0.25 if ngt else np.zeros(0, bool)
        gt_ignore = iscrowd | (rng.rand(ngt) < 0.25) if ngt \
            else np.zeros(0, bool)
        max_dets = rng.choice([3, 100])
        ref = _evaluate_image_transcription(dets, gt, gt_ignore, iscrowd,
                                            max_dets)
        new = _evaluate_image(dets, gt, gt_ignore, iscrowd, max_dets)
        for a, b, name in zip(ref, new, ["scores", "matched", "ignored",
                                         "npos"]):
            np.testing.assert_array_equal(a, b, err_msg=name)


# ---- worked goldens (hand-computed results) --------------------------------

def test_golden_fp_above_tp_is_half():
    """One fp scored above one tp: interpolated precision is 0.5 at every
    recall point and threshold → AP = AP50 = AP75 = 0.5 exactly."""
    gts = {0: {1: dict(boxes=np.array([[10.0, 10.0, 50.0, 50.0]]))}}
    dets = {0: {1: np.array([[200.0, 200.0, 240.0, 240.0, 0.9],
                             [10.0, 10.0, 50.0, 50.0, 0.8]])}}
    r = evaluate_bbox(dets, gts, [1])
    assert abs(r["AP"] - 0.5) < 1e-9
    assert abs(r["AP50"] - 0.5) < 1e-9
    assert abs(r["AR_100"] - 1.0) < 1e-9


def test_golden_fp_below_tp_is_one():
    """A fp scored BELOW a perfect tp never dents interpolated precision:
    AP = 1.0 (the classic property of the 101-point envelope)."""
    gts = {0: {1: dict(boxes=np.array([[10.0, 10.0, 50.0, 50.0]]))}}
    dets = {0: {1: np.array([[10.0, 10.0, 50.0, 50.0, 0.9],
                             [200.0, 200.0, 240.0, 240.0, 0.8]])}}
    r = evaluate_bbox(dets, gts, [1])
    assert abs(r["AP"] - 1.0) < 1e-9


def test_golden_max_dets_cap_drops_tp():
    """max_dets=1 keeps only the higher-scored fp → AP = 0."""
    gts = {0: {1: dict(boxes=np.array([[10.0, 10.0, 50.0, 50.0]]))}}
    dets = {0: {1: np.array([[200.0, 200.0, 240.0, 240.0, 0.9],
                             [10.0, 10.0, 50.0, 50.0, 0.8]])}}
    r = evaluate_bbox(dets, gts, [1], max_dets=1)
    assert r["AP"] == 0.0
    r2 = evaluate_bbox(dets, gts, [1], max_dets=2)
    assert abs(r2["AP"] - 0.5) < 1e-9


def test_golden_real_match_preferred_over_higher_iou_ignored():
    """A det overlapping BOTH a real gt (IoU ~0.55) and an ignored
    (out-of-area) gt with HIGHER IoU must match the real gt — the matcher
    stops considering ignored gts once a real match exists.  A naive
    highest-IoU matcher would ignore the det and score AP50 = 0."""
    real = [0.0, 0.0, 99.0, 9.0]            # area 891 (small)
    big = [0.0, 0.0, 99.0, 99.0]            # area 9801 (large)
    det = [0.0, 0.0, 99.0, 17.0, 0.9]       # IoU(real)=0.529, IoU(big)=0.177
    # make the ignored gt the higher-IoU one instead:
    det2 = [0.0, 0.0, 99.0, 80.0, 0.9]      # IoU(real)~0.111, IoU(big)=0.8
    gts = {0: {1: dict(boxes=np.array([real, big]),
                       area=np.array([891.0, 9801.0]))}}
    # small-area range: real stays, big is ignored
    from mx_rcnn_tpu.data.coco_eval import _evaluate_image
    boxes = np.array([det2])
    gt_ignore = np.array([False, True])
    crowd = np.zeros(2, bool)
    s, m, ig, npos = _evaluate_image(boxes, np.array([real, big]),
                                     gt_ignore, crowd, 100)
    # IoU with real (0.111) is below every threshold; IoU with ignored big
    # is 0.8 → matched to the IGNORED gt at thresholds <= 0.8
    assert ig[0, 0] and m[0, 0]
    boxes = np.array([det])
    s, m, ig, npos = _evaluate_image(boxes, np.array([real, big]),
                                     gt_ignore, crowd, 100)
    # IoU(real)=0.529 >= 0.5 → real match wins at t=0.5 even though the
    # ignored gt has IoU... (0.177 — lower here, but the break rule is
    # what's exercised: ignored candidates are never reached)
    assert m[0, 0] and not ig[0, 0]


def test_golden_equal_iou_tie_goes_to_later_gt():
    """Two real gts with EXACTLY equal IoU to the first det (identical
    boxes): the later gt index must be consumed first (the greedy matcher
    updates on equality), leaving the earlier gt for the second det — both
    dets end up matched.  Pins the tie direction against the
    transcription."""
    from mx_rcnn_tpu.data.coco_eval import _evaluate_image

    gt = np.array([[0.0, 0.0, 9.0, 9.0],
                   [0.0, 0.0, 9.0, 9.0]])   # identical gts → equal IoU
    dets = np.array([[0.0, 0.0, 9.0, 9.0, 0.9],
                     [0.0, 0.0, 9.0, 9.0, 0.8]])
    none = np.zeros(2, bool)
    s, m, ig, npos = _evaluate_image(dets, gt, none, none, 100)
    assert m[:, 0].all() and m[:, 1].all()
    ref = _evaluate_image_transcription(dets, gt, none, none, 100)
    np.testing.assert_array_equal(ref[1], m)


def test_eval_1k_images_80_cats_under_a_minute():
    """Throughput gate (VERDICT r02 item 3): 1000 images x 80 categories
    with realistic det/gt densities must evaluate in well under a minute."""
    import time

    rng = np.random.RandomState(0)
    n_img, n_cat = 1000, 80
    gts, dets = {}, {}
    for i in range(n_img):
        gts[i], dets[i] = {}, {}
        for c in rng.choice(n_cat, size=3, replace=False) + 1:
            k = rng.randint(1, 4)
            xy = rng.randint(0, 400, (k, 2)).astype(float)
            wh = rng.randint(20, 120, (k, 2)).astype(float)
            boxes = np.hstack([xy, xy + wh])
            gts[i][int(c)] = dict(boxes=boxes,
                                  iscrowd=rng.rand(k) < 0.05)
            jit = rng.randint(-10, 10, (k, 4)).astype(float)
            extra_xy = rng.randint(0, 400, (2, 2)).astype(float)
            extra = np.hstack([extra_xy, extra_xy + 30])
            d = np.vstack([boxes + jit, extra])
            dets[i][int(c)] = np.hstack([d, rng.rand(len(d), 1)])
    t0 = time.perf_counter()
    r = evaluate_bbox(dets, gts, list(range(1, n_cat + 1)))
    dt = time.perf_counter() - t0
    assert np.isfinite(r["AP"]) and r["AP"] > 0
    assert dt < 30.0, f"COCO eval too slow: {dt:.1f}s for 1k images"


# ---- segm mode (VERDICT r02 item 6) ---------------------------------------

def _rect_mask(h, w, y1, y2, x1, x2):
    m = np.zeros((h, w), np.uint8)
    m[y1:y2, x1:x2] = 1
    return m


def test_segm_perfect_match_golden():
    from mx_rcnn_tpu import native
    from mx_rcnn_tpu.data.coco_eval import evaluate_segm

    gt_rle = native.encode(_rect_mask(100, 100, 10, 60, 10, 60))
    gts = {0: {1: dict(rles=[gt_rle])}}
    dets = {0: {1: [(gt_rle, 0.9)]}}
    r = evaluate_segm(dets, gts, [1])
    assert abs(r["AP"] - 1.0) < 1e-9
    assert abs(r["AR_100"] - 1.0) < 1e-9
    # 50x50 = 2500 px: medium area range
    assert abs(r["AP_medium"] - 1.0) < 1e-9
    assert np.isnan(r["AP_small"])


def test_segm_half_overlap_exact_ap():
    """Mask IoU exactly 0.5 (det covers half the gt): TP only at threshold
    0.50 → AP = 1/10, AP50 = 1, AP75 = 0.  Hand-computed."""
    from mx_rcnn_tpu import native
    from mx_rcnn_tpu.data.coco_eval import evaluate_segm

    gt_rle = native.encode(_rect_mask(40, 40, 0, 10, 0, 10))   # 100 px
    dt_rle = native.encode(_rect_mask(40, 40, 0, 5, 0, 10))    # 50 px inside
    assert abs(native.iou(dt_rle, gt_rle) - 0.5) < 1e-12
    gts = {0: {1: dict(rles=[gt_rle])}}
    dets = {0: {1: [(dt_rle, 0.9)]}}
    r = evaluate_segm(dets, gts, [1])
    assert abs(r["AP50"] - 1.0) < 1e-9
    assert r["AP75"] == 0.0
    assert abs(r["AP"] - 0.1) < 1e-9


def test_segm_crowd_absorbs_det():
    """A det inside a crowd gt mask is ignored (IoU = inter/det_area = 1),
    not counted as fp; the real gt elsewhere still sets npos."""
    from mx_rcnn_tpu import native
    from mx_rcnn_tpu.data.coco_eval import evaluate_segm

    crowd = native.encode(_rect_mask(60, 60, 0, 30, 0, 60))
    real = native.encode(_rect_mask(60, 60, 40, 55, 10, 40))
    inside_crowd = native.encode(_rect_mask(60, 60, 5, 15, 5, 25))
    gts = {0: {1: dict(rles=[crowd, real],
                       iscrowd=np.array([True, False]))}}
    # only the crowd-absorbed det: no fp, but no tp either → AP 0
    r0 = evaluate_segm({0: {1: [(inside_crowd, 0.9)]}}, gts, [1])
    assert r0["AP"] == 0.0
    # crowd det (higher score) + real match: AP 1 — the fp-above-tp rule
    # would give 0.5 if the crowd det were counted as fp
    r1 = evaluate_segm(
        {0: {1: [(inside_crowd, 0.9), (real, 0.8)]}}, gts, [1])
    assert abs(r1["AP"] - 1.0) < 1e-9


def test_segm_discriminates_from_bbox():
    """An L-shaped gt vs a solid-rectangle det with the SAME bounding box:
    bbox eval scores a perfect match, segm eval must not (mask IoU < 0.5)."""
    from mx_rcnn_tpu import native
    from mx_rcnn_tpu.data.coco_eval import evaluate_bbox, evaluate_segm

    h = w = 50
    L = np.zeros((h, w), np.uint8)
    L[10:40, 10:14] = 1          # vertical bar: 30x4 = 120 px
    L[36:40, 10:40] = 1          # horizontal bar: 4x30, overlap 4x4
    gt_rle = native.encode(L)
    solid = native.encode(_rect_mask(h, w, 10, 40, 10, 40))  # 900 px
    # mask IoU = |L| / 900 = 224/900 ≈ 0.249 < 0.5
    assert native.iou(solid, gt_rle) < 0.5
    x, y, bw, bh = native.to_bbox(gt_rle)
    gt_box = np.array([[x, y, x + bw - 1, y + bh - 1]])
    det_box = np.hstack([gt_box[0], [0.9]])[None]

    r_box = evaluate_bbox({0: {1: det_box}},
                          {0: {1: dict(boxes=gt_box)}}, [1])
    r_seg = evaluate_segm({0: {1: [(solid, 0.9)]}},
                          {0: {1: dict(rles=[gt_rle])}}, [1])
    assert abs(r_box["AP"] - 1.0) < 1e-9
    assert r_seg["AP"] == 0.0


# ---- VOC 11-point worked golden (VERDICT r02 item 3) ----------------------

def test_voc_11pt_worked_example():
    """Hand-worked nontrivial PR curve.

    One image, 3 gts; 4 dets sorted by score: TP, FP, TP, TP.
    Cumulative: rec = [1/3, 1/3, 2/3, 1], prec = [1, 1/2, 2/3, 3/4].
    11-pt AP = mean over t in {0,.1,...,1} of max prec at rec >= t
             = (3/4 x 4 + 3/4 x 3 + 3/4 x 4) / 11 = 3/4  ... worked fully:
      t = 0..0.3  -> max prec over all = 1.0        (4 points: 0,.1,.2,.3)
      t = 0.4..0.6 -> max prec at rec >= .4 = 3/4   (3 points)
      t = 0.7..1.0 -> max prec at rec >= .7 = 3/4   (4 points)
    AP_07 = (4*1.0 + 7*0.75) / 11 = 9.25/11 = 0.840909...
    Continuous AP = sum over recall steps of prec envelope:
      envelope prec(r) = 1.0 for r <= 1/3, 0.75 beyond
      AP = 1/3 * 1.0 + 2/3 * 0.75 = 0.8333...
    """
    from mx_rcnn_tpu.data.voc_eval import voc_eval

    gts = {"im0": dict(
        boxes=np.array([[0.0, 0, 10, 10], [100.0, 0, 110, 10],
                        [200.0, 0, 210, 10]]),
        gt_classes=np.array([1, 1, 1]),
        difficult=np.zeros(3, bool))}
    dets = {"im0": np.array([
        [0.0, 0, 10, 10, 0.9],        # TP (gt 0)
        [300.0, 0, 310, 10, 0.8],     # FP
        [100.0, 0, 110, 10, 0.7],     # TP (gt 1)
        [200.0, 0, 210, 10, 0.6],     # TP (gt 2)
    ])}
    ap07 = voc_eval(dets, gts, 1, use_07_metric=True)
    ap = voc_eval(dets, gts, 1, use_07_metric=False)
    assert ap07 == pytest.approx((4 * 1.0 + 7 * 0.75) / 11, abs=1e-9)
    assert ap == pytest.approx(1 / 3 + 2 / 3 * 0.75, abs=1e-9)


# ---- from_poly deviation quantified on realistic polygons ------------------

def test_from_poly_close_to_independent_rasterizer():
    """native.from_poly's even-odd pixel-center fill vs PIL's polygon
    rasterizer: QUANTIFIES the documented boundary-ring deviation on
    realistic star polygons (VERDICT r02 weak #4).  Measured: the
    disagreement is a <=1-px boundary band — worst IoU 0.933 on 25-55 px
    radius polygons (ring/area ratio shrinks linearly with object size;
    at COCO-median object scale the band is ~3% of the mask).  The
    assertion pins that measured floor so regressions are caught."""
    from PIL import Image, ImageDraw

    from mx_rcnn_tpu import native

    rng = np.random.RandomState(0)
    h = w = 200
    worst = 1.0
    for k in range(10):
        n_v = rng.randint(5, 12)
        ang = np.sort(rng.uniform(0, 2 * np.pi, n_v))
        cx, cy = rng.uniform(60, 140, 2)
        rad = rng.uniform(25, 55, n_v)  # star-shaped (non-convex) radii
        xs = cx + rad * np.cos(ang)
        ys = cy + rad * np.sin(ang)
        poly = np.stack([xs, ys], 1).ravel().tolist()

        rle = native.from_poly(poly, h, w)
        ours = native.decode(rle).astype(bool)

        img = Image.new("1", (w, h), 0)
        ImageDraw.Draw(img).polygon(list(zip(xs, ys)), fill=1)
        ref = np.asarray(img, bool)

        inter = (ours & ref).sum()
        union = (ours | ref).sum()
        iou = inter / union if union else 1.0
        worst = min(worst, iou)
        assert union > 500, "degenerate polygon in fixture"
    assert worst > 0.92, f"from_poly deviates too much: worst IoU {worst}"


def test_coco_dataset_segm_eval_end_to_end(tmp_path):
    """COCODataset.evaluate_segmentations over all three COCO segmentation
    encodings: polygon list, uncompressed crowd RLE, and bbox fallback.
    A perfect detector (gt masks as detections) must score AP 1.0; the
    crowd region must absorb a stray det instead of counting it as fp."""
    from mx_rcnn_tpu import native

    h, w = 80, 100
    # gt mask 1: polygon rectangle ~ (10,10)-(40,30)
    poly = [10.0, 10.0, 40.0, 10.0, 40.0, 30.0, 10.0, 30.0]
    # crowd mask: uncompressed RLE of a 20x20 block at top-left corner
    crowd_mask = np.zeros((h, w), np.uint8)
    crowd_mask[0:20, 60:80] = 1
    crowd_counts = [int(c) for c in
                    np.asarray(native._counts_of(
                        native.encode(crowd_mask)), np.uint32)]
    ann = {
        "images": [{"id": 1, "file_name": "a.jpg", "width": w, "height": h}],
        "categories": [{"id": 5, "name": "thing"}],
        "annotations": [
            {"image_id": 1, "category_id": 5, "bbox": [10, 10, 31, 21],
             "area": 651, "iscrowd": 0, "segmentation": [poly]},
            {"image_id": 1, "category_id": 5, "bbox": [60, 0, 20, 20],
             "area": 400, "iscrowd": 1,
             "segmentation": {"size": [h, w], "counts": crowd_counts}},
            # no segmentation → bbox-rectangle fallback
            {"image_id": 1, "category_id": 5, "bbox": [50, 50, 10, 10],
             "area": 100, "iscrowd": 0},
        ],
    }
    ann_dir = tmp_path / "coco" / "annotations"
    os.makedirs(ann_dir)
    with open(ann_dir / "instances_val.json", "w") as f:
        json.dump(ann, f)
    ds = COCODataset("val", str(tmp_path), str(tmp_path / "coco"))

    gt_rles = [ds.ann_rle(a, 1) for a in ds.anns_by_image[1]]
    # sanity of each encoding path
    assert native.area(gt_rles[1]) == 400            # uncompressed round-trip
    assert native.area(gt_rles[2]) == 10 * 10        # bbox fallback
    assert abs(native.area(gt_rles[0]) - 31 * 21) <= 70  # polygon fill

    # perfect detector: the two real gt masks, plus one det inside the crowd
    dets = {1: {1: [(gt_rles[0], 0.9), (gt_rles[2], 0.85),
                    (gt_rles[1], 0.95)]}}
    r = ds.evaluate_segmentations(dets)
    assert r["AP"] == pytest.approx(1.0)
    assert r["AR_100"] == pytest.approx(1.0)
