"""COCO bbox evaluation validation (VERDICT r1 item 5).

pycocotools cannot be installed in this environment, so ``evaluate_bbox``
is validated two ways:

1. hand-derived golden cases encoding pycocotools' documented matching
   semantics — ``iou >= threshold`` matching, score-ordered greedy
   assignment, crowd boxes as repeatable ignore regions with
   intersection/det-area IoU, per-area-range gt ignoring, 101-point
   interpolated precision averaged over IoU .50:.05:.95;
2. an independently-written AP50 oracle compared on randomized multi-image
   multi-category cases (implementation diversity catches matching bugs a
   same-author golden cannot).

Plus a COCO annotation-loading test (VERDICT: no test touched COCO code).
"""

import json
import os

import numpy as np
import pytest

from mx_rcnn_tpu.data.coco import COCODataset
from mx_rcnn_tpu.data.coco_eval import evaluate_bbox


def _one_cat(dets, gts):
    """Wrap per-image det/gt lists for category 1."""
    d = {img: {1: np.asarray(v, np.float32).reshape(-1, 5)}
         for img, v in dets.items()}
    g = {}
    for img, entry in gts.items():
        boxes = np.asarray(entry["boxes"], np.float32).reshape(-1, 4)
        g[img] = {1: {
            "boxes": boxes,
            "iscrowd": np.asarray(entry.get("iscrowd",
                                            [False] * len(boxes)), bool),
            "area": np.asarray(entry.get(
                "area",
                (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1]))),
        }}
    return d, g


def test_perfect_detection_all_metrics():
    d, g = _one_cat({"im0": [[0, 0, 10, 10, 0.9]]},
                    {"im0": {"boxes": [[0, 0, 10, 10]]}})
    r = evaluate_bbox(d, g, [1])
    assert r["AP"] == pytest.approx(1.0)
    assert r["AP50"] == pytest.approx(1.0)
    assert r["AP75"] == pytest.approx(1.0)
    # area 100 < 32^2 → the gt counts only in 'small' (and 'all')
    assert r["AP_small"] == pytest.approx(1.0)
    assert np.isnan(r["AP_medium"])
    assert np.isnan(r["AP_large"])
    assert r["AR_100"] == pytest.approx(1.0)


def test_iou_boundary_inclusive():
    """A det at IoU exactly 0.6 matches thresholds .5/.55/.6 (pycocotools
    matching is iou >= t) → AP = 3/10, AP50 = 1, AP75 = 0."""
    d, g = _one_cat({"im0": [[0, 0, 10, 6, 0.9]]},
                    {"im0": {"boxes": [[0, 0, 10, 10]]}})
    # IoU = 60 / (100 + 60 - 60) = 0.6 exactly
    r = evaluate_bbox(d, g, [1])
    assert r["AP50"] == pytest.approx(1.0)
    assert r["AP75"] == pytest.approx(0.0)
    assert r["AP"] == pytest.approx(0.3)


def test_crowd_is_ignore_region_not_fp():
    """A higher-scoring det that only overlaps a crowd region must be
    IGNORED (excluded from PR), not counted as a false positive.  With the
    crowd rule: AP = 1.0; without it the FP outranks the TP → AP = 0.5."""
    d, g = _one_cat(
        {"im0": [[22, 2, 38, 18, 0.95],    # inside the crowd region only
                 [0, 0, 10, 10, 0.90]]},   # exact match of the real gt
        {"im0": {"boxes": [[0, 0, 10, 10], [20, 0, 40, 20]],
                 "iscrowd": [False, True]}})
    r = evaluate_bbox(d, g, [1])
    assert r["AP"] == pytest.approx(1.0)
    assert r["AR_100"] == pytest.approx(1.0)


def test_duplicate_detection_is_fp():
    """Second det on an already-matched gt is a FP: 2 gts, both dets on
    gt1 → recall caps at 0.5, precision [1, .5] → 101-pt AP = 51/101."""
    d, g = _one_cat(
        {"im0": [[0, 0, 10, 10, 0.9], [1, 0, 11, 10, 0.8]]},
        {"im0": {"boxes": [[0, 0, 10, 10], [50, 50, 60, 60]]}})
    r = evaluate_bbox(d, g, [1])
    assert r["AP50"] == pytest.approx(51 / 101)
    assert r["AP"] == pytest.approx(51 / 101)


def test_area_range_gt_ignored_outside_range():
    """A 64x64 gt (area 4096, 'medium') is ignored in the 'small' range;
    its det must then be ignored there too, not become a small-range FP."""
    d, g = _one_cat(
        {"im0": [[0, 0, 64, 64, 0.9], [100, 100, 116, 116, 0.8]]},
        {"im0": {"boxes": [[0, 0, 64, 64], [100, 100, 116, 116]]}})
    # second gt is 16x16 (area 256, small)
    r = evaluate_bbox(d, g, [1])
    assert r["AP"] == pytest.approx(1.0)
    assert r["AP_small"] == pytest.approx(1.0)   # only the 16x16 pair counts
    assert r["AP_medium"] == pytest.approx(1.0)  # only the 64x64 pair counts
    assert np.isnan(r["AP_large"])


# ---------------------------------------------------------------------------
# independent AP50 oracle
# ---------------------------------------------------------------------------

def _ap50_oracle(dets_by_img, gts_by_img):
    """Straightforward single-threshold (0.5) AP with 101-pt interpolation,
    written independently of coco_eval.py's vectorized implementation."""
    def iou(a, b):
        ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
        iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = ix * iy
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    records = []
    npos = 0
    for img in set(dets_by_img) | set(gts_by_img):
        gts = list(gts_by_img.get(img, []))
        npos += len(gts)
        used = [False] * len(gts)
        dets = sorted(dets_by_img.get(img, []), key=lambda r: -r[4])
        for det in dets:
            best, bi = 0.5, -1
            for gi, gt in enumerate(gts):
                if used[gi]:
                    continue
                v = iou(det, gt)
                if v >= best:
                    best, bi = v, gi
            if bi >= 0:
                used[bi] = True
                records.append((det[4], True))
            else:
                records.append((det[4], False))
    if npos == 0:
        return float("nan")
    records.sort(key=lambda r: -r[0])
    tp = fp = 0
    pr = []
    for _, is_tp in records:
        tp += is_tp
        fp += not is_tp
        pr.append((tp / npos, tp / (tp + fp)))
    ap = 0.0
    for r_thr in np.linspace(0, 1, 101):
        ps = [p for rec, p in pr if rec >= r_thr]
        ap += max(ps) if ps else 0.0
    return ap / 101


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_ap50_matches_independent_oracle(seed):
    rng = np.random.RandomState(seed)
    n_images, n_cats = 4, 3
    dets_all, gts_all = {}, {}
    oracle_aps = []
    for cat in range(1, n_cats + 1):
        d_img, g_img = {}, {}
        for i in range(n_images):
            img = f"im{i}"
            n_gt = rng.randint(0, 4)
            gts = []
            for _ in range(n_gt):
                x, y = rng.uniform(0, 80, 2)
                w, h = rng.uniform(10, 40, 2)
                gts.append([x, y, x + w, y + h])
            n_det = rng.randint(0, 5)
            dets = []
            for _ in range(n_det):
                if gts and rng.rand() < 0.6:  # jittered copy of a gt
                    b = list(gts[rng.randint(len(gts))])
                    jit = rng.uniform(-6, 6, 4)
                    b = [b[k] + jit[k] for k in range(4)]
                else:
                    x, y = rng.uniform(0, 80, 2)
                    w, h = rng.uniform(10, 40, 2)
                    b = [x, y, x + w, y + h]
                dets.append(b + [float(rng.uniform(0.05, 1.0))])
            if dets:
                d_img[img] = dets
            if gts:
                g_img[img] = gts
            dets_all.setdefault(img, {})
            gts_all.setdefault(img, {})
            if dets:
                dets_all[img][cat] = np.asarray(dets, np.float32)
            if gts:
                g = np.asarray(gts, np.float32)
                gts_all[img][cat] = {
                    "boxes": g,
                    "iscrowd": np.zeros(len(g), bool),
                    "area": (g[:, 2] - g[:, 0]) * (g[:, 3] - g[:, 1]),
                }
        if any(len(v) for v in g_img.values()):
            oracle_aps.append(_ap50_oracle(d_img, g_img))
    result = evaluate_bbox(dets_all, gts_all, list(range(1, n_cats + 1)))
    assert result["AP50"] == pytest.approx(np.mean(oracle_aps), abs=1e-9)


# ---------------------------------------------------------------------------
# COCO annotation loading (component 2.22)
# ---------------------------------------------------------------------------

def _mini_coco_json(tmp_path):
    ann = {
        "images": [
            {"id": 7, "file_name": "a.jpg", "width": 100, "height": 80},
            {"id": 3, "file_name": "b.jpg", "width": 50, "height": 60},
        ],
        # non-contiguous category ids, unsorted — must remap to 1..C
        "categories": [
            {"id": 18, "name": "dog"},
            {"id": 1, "name": "person"},
            {"id": 44, "name": "bottle"},
        ],
        "annotations": [
            {"image_id": 7, "category_id": 18, "bbox": [10, 10, 20, 20],
             "area": 400, "iscrowd": 0},
            {"image_id": 7, "category_id": 1, "bbox": [0, 0, 30, 15],
             "area": 450, "iscrowd": 0},
            # crowd: excluded from the training roidb
            {"image_id": 7, "category_id": 1, "bbox": [40, 40, 50, 30],
             "area": 1500, "iscrowd": 1},
            # degenerate zero-area box: dropped
            {"image_id": 3, "category_id": 44, "bbox": [5, 5, 0, 0],
             "area": 0, "iscrowd": 0},
            {"image_id": 3, "category_id": 44, "bbox": [5, 5, 10, 10],
             "area": 100, "iscrowd": 0},
        ],
    }
    ann_dir = tmp_path / "coco" / "annotations"
    os.makedirs(ann_dir)
    with open(ann_dir / "instances_minival.json", "w") as f:
        json.dump(ann, f)
    return str(tmp_path / "coco")


def test_coco_loader_parsing(tmp_path):
    path = _mini_coco_json(tmp_path)
    ds = COCODataset("minival", str(tmp_path), path)
    # categories sorted by id and remapped contiguously: 1→person(1),
    # 18→dog(2), 44→bottle(3)
    assert ds.classes == ["__background__", "person", "dog", "bottle"]
    assert ds.cat_to_class == {1: 1, 18: 2, 44: 3}
    roidb = ds._load_annotations()
    assert len(roidb) == 2
    by_index = {r["index"]: r for r in roidb}
    # image order is sorted by image id
    assert [r["index"] for r in roidb] == [3, 7]
    r7 = by_index[7]
    assert r7["height"] == 80 and r7["width"] == 100
    # crowd annotation excluded → 2 boxes
    assert len(r7["boxes"]) == 2
    assert set(r7["gt_classes"].tolist()) == {1, 2}
    # xywh → xyxy conversion (x2 = x + w - 1)
    dog = r7["boxes"][r7["gt_classes"].tolist().index(2)]
    np.testing.assert_allclose(dog, [10, 10, 29, 29])
    r3 = by_index[3]
    assert len(r3["boxes"]) == 1  # degenerate box dropped
    assert r3["gt_classes"][0] == 3
    assert r3["image"].endswith(os.path.join("minival", "b.jpg"))


def test_coco_evaluate_detections_end_to_end(tmp_path):
    """Perfect detections through COCODataset.evaluate_detections → AP 1.0
    (crowd region ignored), and the results json is written."""
    path = _mini_coco_json(tmp_path)
    ds = COCODataset("minival", str(tmp_path), path)
    roidb = ds._load_annotations()
    all_boxes = [[np.zeros((0, 5), np.float32) for _ in range(2)]
                 for _ in range(ds.num_classes)]
    for i, rec in enumerate(roidb):
        for b, c in zip(rec["boxes"], rec["gt_classes"]):
            # evaluate against the ORIGINAL xywh→xyxy (no -1) gt convention
            det = np.array([[b[0], b[1], b[2] + 1, b[3] + 1, 0.9]],
                           np.float32)
            all_boxes[c][i] = np.concatenate([all_boxes[c][i], det])
    out_dir = str(tmp_path / "results")
    r = ds.evaluate_detections(all_boxes, out_dir)
    # person + dog detect perfectly (crowd region ignored).  The degenerate
    # zero-area bottle annotation is dropped from the TRAINING roidb but —
    # exactly like pycocotools — still counts as (unmatchable) eval gt, so
    # bottle recall caps at 1/2 → AP 51/101.
    assert r["AP"] == pytest.approx((1.0 + 1.0 + 51 / 101) / 3)
    res_file = os.path.join(out_dir, "detections_results.json")
    assert os.path.exists(res_file)
    with open(res_file) as f:
        results = json.load(f)
    assert len(results) == 3
    assert {x["category_id"] for x in results} <= {1, 18, 44}