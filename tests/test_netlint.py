"""netlint + wirefuzz contract tests (ISSUE 16 tentpole), mirroring
``tests/test_persistlint.py`` / ``tests/test_threadlint.py``:

* the SHIPPED tree is clean — zero unwaived netlint findings over
  ``mx_rcnn_tpu``, every waiver reasoned;
* the fixture (``tests/fixtures/serve/netlint_bad.py``) trips EVERY NL
  rule — the linter cannot silently lose a rule;
* behavioral tests per rule (timeout inference through settimeout and
  the untimed-factory closure, exception-path close tracking and
  ownership hand-off, length-check ordering for unpacks, wire-derived
  size derivation, accumulation-loop caps, handler body bounds, the
  backoff+cap retry contract, waivers);
* the wirefuzz runtime twin: corpus determinism (same seed → the same
  fingerprint, a different seed → a different one), the typed-rejection
  outcome model (ValueError is REJECTED, anything else CRASHED, the
  allocation guard trips ALLOC), the real codec surviving its corpus,
  and PLANTED-violation sensitivity — BOTH planted decoder arms must be
  flagged; zero-sensitivity is a failure.
"""

import os
import struct
import textwrap

import pytest

from mx_rcnn_tpu.analysis import netlint
from mx_rcnn_tpu.analysis.netlint import RULES, lint_paths
from mx_rcnn_tpu.analysis.wirefuzz import (ACCEPTED_MALFORMED, ALLOC,
                                           CRASHED, REJECTED,
                                           AllocationCapExceeded,
                                           Mutation, Mutator, alloc_guard,
                                           run_case, summarize)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "mx_rcnn_tpu")
FIXTURE = os.path.join(REPO, "tests", "fixtures", "serve",
                       "netlint_bad.py")


# ---------------------------------------------------------------------------
# static pass: the shipped tree + the fixture
# ---------------------------------------------------------------------------

def test_shipped_tree_has_zero_unwaived_findings():
    findings = lint_paths([PKG])
    active = [f for f in findings if f.waived is None]
    assert active == [], "\n".join(f.render() for f in active)
    for f in findings:
        if f.waived is not None:
            assert f.waived.strip(), f.render()


def test_cli_exit_codes(capsys):
    assert netlint.main([PKG]) == 0
    assert netlint.main([FIXTURE]) == 1
    assert netlint.main(["--list-rules"]) == 0
    assert netlint.main([os.path.join(REPO, "no_such_dir")]) == 2
    capsys.readouterr()


def test_fixture_trips_every_rule():
    findings = lint_paths([FIXTURE])
    codes = {f.code for f in findings}
    assert codes == set(RULES), (
        f"missing: {set(RULES) - codes}, unexpected: {codes - set(RULES)}")
    # the reasonless NL101 waiver silences its finding but raises NL001
    assert any(f.code == "NL101" and f.waived is not None
               for f in findings)
    assert any(f.code == "NL001" for f in findings)
    assert any(f.code == "NL002" for f in findings)


def _lint_snippet(tmp_path, source, name="snippet.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return lint_paths([str(p)])


def _codes(findings):
    return [f.code for f in findings if f.waived is None]


# ---------------------------------------------------------------------------
# NL101: blocking ops need a timeout somewhere
# ---------------------------------------------------------------------------

def test_nl101_settimeout_after_alloc_clears(tmp_path):
    assert _codes(_lint_snippet(tmp_path, """\
        import socket

        def poll(addr):
            s = socket.socket()
            s.settimeout(3.0)
            try:
                s.connect(addr)
                return s.recv(16)
            finally:
                s.close()
        """)) == []


def test_nl101_settimeout_none_does_not_clear(tmp_path):
    codes = _codes(_lint_snippet(tmp_path, """\
        import socket

        def poll(addr):
            s = socket.create_connection(addr, timeout=3.0)
            s.settimeout(None)
            try:
                return s.recv(16)
            finally:
                s.close()
        """))
    # settimeout(None) means BLOCKING — it must not count as timed.
    # The alloc-time timeout already marked it timed (conservative,
    # order-insensitive), so this pins only that None never SETS it.
    assert "NL101" not in codes or codes == ["NL101"]


def test_nl101_through_untimed_factory(tmp_path):
    """The factory closure: a helper returning an untimed connection
    taints its callers' blocking ops."""
    codes = _codes(_lint_snippet(tmp_path, """\
        import socket

        def make_conn(addr):
            return socket.create_connection(addr)

        def ask(addr):
            s = make_conn(addr)
            try:
                return s.recv(16)
            finally:
                s.close()
        """))
    assert "NL101" in codes


def test_nl101_timed_factory_is_clean(tmp_path):
    assert _codes(_lint_snippet(tmp_path, """\
        import socket

        def make_conn(addr):
            return socket.create_connection(addr, timeout=2.0)

        def ask(addr):
            s = make_conn(addr)
            try:
                return s.recv(16)
            finally:
                s.close()
        """)) == []


def test_nl101_untimed_self_attr(tmp_path):
    codes = _codes(_lint_snippet(tmp_path, """\
        import socket

        class Client:
            def __init__(self):
                self.sock = socket.socket()

            def ask(self):
                return self.sock.recv(16)
        """))
    assert "NL101" in codes


def test_nl101_self_attr_settimeout_anywhere_clears(tmp_path):
    assert _codes(_lint_snippet(tmp_path, """\
        import socket

        class Client:
            def __init__(self):
                self.sock = socket.socket()
                self.sock.settimeout(2.0)

            def ask(self):
                return self.sock.recv(16)
        """)) == []


# ---------------------------------------------------------------------------
# NL102: closed on exception paths, or ownership handed off
# ---------------------------------------------------------------------------

def test_nl102_plain_close_is_not_exception_safe(tmp_path):
    codes = _codes(_lint_snippet(tmp_path, """\
        import socket

        def ask(addr):
            s = socket.create_connection(addr, timeout=2.0)
            data = s.recv(16)
            s.close()
            return data
        """))
    assert "NL102" in codes


def test_nl102_with_finally_and_handoff_are_clean(tmp_path):
    assert _codes(_lint_snippet(tmp_path, """\
        import socket

        def via_with(addr):
            with socket.create_connection(addr, timeout=2.0) as s:
                return s.recv(16)

        def via_finally(addr):
            s = socket.create_connection(addr, timeout=2.0)
            try:
                return s.recv(16)
            finally:
                s.close()

        def via_return(addr):
            s = socket.create_connection(addr, timeout=2.0)
            s.setsockopt(1, 1, 1)
            return s

        class Pool:
            def adopt(self, addr):
                s = socket.create_connection(addr, timeout=2.0)
                s.setsockopt(1, 1, 1)
                self.conn = s
        """)) == []


# ---------------------------------------------------------------------------
# NL201: length check before unpack
# ---------------------------------------------------------------------------

def test_nl201_unguarded_unpack_flagged(tmp_path):
    codes = _codes(_lint_snippet(tmp_path, """\
        import struct

        def decode(buf):
            return struct.unpack_from("<4sI", buf, 0)
        """))
    assert codes == ["NL201"]


def test_nl201_len_check_clears_including_alias(tmp_path):
    assert _codes(_lint_snippet(tmp_path, """\
        import struct

        def decode(buf):
            if len(buf) < 8:
                raise ValueError("short frame")
            return struct.unpack_from("<4sI", buf, 0)

        def decode_alias(buf):
            n = len(buf)
            if n < 8:
                raise ValueError("short frame")
            return struct.unpack_from("<4sI", buf, 0)
        """)) == []


def test_nl201_check_after_unpack_still_flagged(tmp_path):
    codes = _codes(_lint_snippet(tmp_path, """\
        import struct

        def decode(buf):
            vals = struct.unpack_from("<4sI", buf, 0)
            if len(buf) < 8:
                raise ValueError("short frame")
            return vals
        """))
    assert codes == ["NL201"]


# ---------------------------------------------------------------------------
# NL202: wire-derived lengths must be bounded before sizing anything
# ---------------------------------------------------------------------------

def test_nl202_derivation_chain_flagged_and_cleared(tmp_path):
    # the derived name (nbytes = k * 20) is still wire-tainted
    codes = _codes(_lint_snippet(tmp_path, """\
        import struct

        def decode(buf):
            if len(buf) < 4:
                raise ValueError("short")
            k, = struct.unpack_from("<I", buf, 0)
            nbytes = k * 20
            return bytearray(nbytes)
        """))
    assert "NL202" in codes
    # ...and a bound on EITHER component member clears the whole chain
    assert _codes(_lint_snippet(tmp_path, """\
        import struct

        def decode(buf):
            if len(buf) < 4:
                raise ValueError("short")
            k, = struct.unpack_from("<I", buf, 0)
            if k > 4096:
                raise ValueError("count over cap")
            nbytes = k * 20
            return bytearray(nbytes)
        """)) == []


def test_nl202_bytes_repetition_sink(tmp_path):
    codes = _codes(_lint_snippet(tmp_path, """\
        import struct

        def pad(buf):
            if len(buf) < 4:
                raise ValueError("short")
            n, = struct.unpack_from("<I", buf, 0)
            return buf + b"\\0" * n
        """))
    assert "NL202" in codes


# ---------------------------------------------------------------------------
# NL203: response reads need a byte cap
# ---------------------------------------------------------------------------

def test_nl203_sized_read_and_capped_loop_are_clean(tmp_path):
    assert _codes(_lint_snippet(tmp_path, """\
        import urllib.request

        def fetch(url):
            with urllib.request.urlopen(url, timeout=2.0) as r:
                return r.read(65536)

        def drain(sock):
            buf = b""
            while 1 == 1:
                chunk = sock.recv(4096)
                if not chunk:
                    break
                buf += chunk
                if len(buf) > 1 << 20:
                    raise ValueError("over cap")
            return buf
        """)) == []


def test_nl203_argless_read_on_derived_response(tmp_path):
    # conn.getresponse() derives a tracked response from the connection
    codes = _codes(_lint_snippet(tmp_path, """\
        import http.client

        def fetch(host):
            c = http.client.HTTPConnection(host, timeout=2.0)
            try:
                c.request("GET", "/")
                r = c.getresponse()
                return r.read()
            finally:
                c.close()
        """))
    assert "NL203" in codes


# ---------------------------------------------------------------------------
# NL204: handler bodies ride the Content-Length bound
# ---------------------------------------------------------------------------

def test_nl204_bounded_handler_read_is_clean(tmp_path):
    assert _codes(_lint_snippet(tmp_path, """\
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            if n > 1 << 20:
                raise ValueError("over cap")
            return self.rfile.read(n)
        """)) == []


def test_nl204_argless_rfile_read_flagged(tmp_path):
    codes = _codes(_lint_snippet(tmp_path, """\
        def do_POST(self):
            return self.rfile.read()
        """))
    assert codes == ["NL204"]


# ---------------------------------------------------------------------------
# NL301: retries need BOTH backoff and a cap
# ---------------------------------------------------------------------------

def test_nl301_backoff_and_cap_required(tmp_path):
    # capped but hot (no sleep): flagged
    codes = _codes(_lint_snippet(tmp_path, """\
        def pull(conn):
            for attempt in range(3):
                try:
                    conn.request("GET", "/x")
                    return conn.getresponse()
                except OSError:
                    continue
        """))
    assert "NL301" in codes
    # backoff + finite attempts: clean
    assert _codes(_lint_snippet(tmp_path, """\
        import time

        def pull(conn):
            for attempt in range(3):
                try:
                    conn.request("GET", "/x")
                    return conn.getresponse()
                except OSError:
                    time.sleep(2 ** attempt)
                    continue
        """)) == []


def test_nl301_only_fires_on_network_tries(tmp_path):
    # a parse-retry loop over strings is not this rule's business
    assert _codes(_lint_snippet(tmp_path, """\
        def first_int(lines):
            while True:
                try:
                    return int(next(lines))
                except ValueError:
                    continue
        """)) == []


# ---------------------------------------------------------------------------
# waivers
# ---------------------------------------------------------------------------

def test_waiver_on_line_and_line_above(tmp_path):
    findings = _lint_snippet(tmp_path, """\
        import struct

        def a(buf):
            return struct.unpack("<I", buf)  # netlint: disable=NL201 test

        def b(buf):
            # netlint: disable=NL201 test
            return struct.unpack("<I", buf)
        """)
    assert _codes(findings) == []
    assert sum(1 for f in findings
               if f.code == "NL201" and f.waived == "test") == 2


def test_waiver_two_lines_above_does_not_match(tmp_path):
    findings = _lint_snippet(tmp_path, """\
        import struct

        def a(buf):
            # netlint: disable=NL201 too far away
            x = 1
            return struct.unpack("<I", buf)
        """)
    assert _codes(findings) == ["NL201"]


# ---------------------------------------------------------------------------
# wirefuzz: corpus determinism + the outcome model
# ---------------------------------------------------------------------------

_SPANS = [("magic", 0, 4), ("n", 4, 8)]
_BENIGN = [("pad", 8, 10)]


def _frame():
    return struct.pack("<4sIH4s", b"TEST", 4, 0, b"pay!")


def test_corpus_same_seed_same_fingerprint():
    a = Mutator(7).corpus(_frame(), 10, _SPANS, _BENIGN)
    b = Mutator(7).corpus(_frame(), 10, _SPANS, _BENIGN)
    assert Mutator.fingerprint(a) == Mutator.fingerprint(b)
    assert [m.name for m in a] == [m.name for m in b]
    assert len(a) >= 20


def test_corpus_different_seed_different_payloads():
    a = Mutator(7).corpus(_frame(), 10, _SPANS, _BENIGN)
    b = Mutator(8).corpus(_frame(), 10, _SPANS, _BENIGN)
    assert Mutator.fingerprint(a) != Mutator.fingerprint(b)


def test_run_case_outcome_model():
    def decode(buf):
        if len(buf) < 14 or buf[:4] != b"TEST":
            raise ValueError("bad frame")
        n, = struct.unpack_from("<I", buf, 4)
        if n > 1024:
            raise ValueError("n over cap")
        return n

    # the typed rejection: ValueError (and only ValueError) is REJECTED
    rejected = run_case(decode, Mutation("m", b"xx", True))
    assert rejected["outcome"] == REJECTED
    ok = run_case(decode, Mutation("m", _frame(), False))
    assert ok["outcome"] == "accepted_valid"
    # a must-reject input the decoder swallows whole is the finding
    lax = run_case(lambda b: len(b), Mutation("m", b"xx", True))
    assert lax["outcome"] == ACCEPTED_MALFORMED

    def crashes(buf):
        return struct.unpack("<I", buf)  # struct.error on short input

    assert run_case(crashes, Mutation("m", b"xx", True))["outcome"] \
        == CRASHED


def test_alloc_guard_trips_and_restores():
    import numpy as np

    with alloc_guard(cap_bytes=1 << 20):
        np.zeros(16, np.uint8)  # under the cap: fine
        with pytest.raises(AllocationCapExceeded):
            np.zeros(1 << 22, np.uint8)
    # restored: big allocations work again outside the guard
    assert np.zeros(1 << 22, np.uint8).nbytes == 1 << 22


def test_summarize_collects_violations():
    results = [
        {"name": "a", "outcome": REJECTED, "detail": ""},
        {"name": "b", "outcome": ALLOC, "detail": "big"},
    ]
    s = summarize(results)
    assert s["cases"] == 2
    assert s["outcomes"][ALLOC] == 1
    assert [v["name"] for v in s["violations"]] == ["b"]


# ---------------------------------------------------------------------------
# wirefuzz vs the REAL codec + planted sensitivity
# ---------------------------------------------------------------------------

def test_real_codec_survives_its_corpus():
    from mx_rcnn_tpu.tools.wirefuzz import leg_codec

    leg = leg_codec(16, smoke=True)
    assert leg["violations"] == [], leg["violations"]
    assert leg["cases"] >= 40
    # the corpus actually exercises both accept and reject paths
    assert leg["outcomes"].get(REJECTED, 0) > 0
    assert leg["outcomes"].get("accepted_valid", 0) > 0


def test_planted_arms_are_both_flagged():
    """Sensitivity: a fuzzer that cannot flag KNOWN-bad decoders proves
    nothing.  The zero-fill arm pads truncated frames instead of
    rejecting; the uncapped arm trusts wire lengths into np.zeros."""
    from mx_rcnn_tpu.tools.wirefuzz import leg_planted

    planted = leg_planted(16)
    assert planted["zerofill"]["flagged"] is True
    assert planted["uncapped"]["alloc_flagged"] is True
    assert planted["ok"] is True
