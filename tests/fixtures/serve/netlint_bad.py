"""netlint fixture: every NL rule trips at least once.

NEVER imported — ``tests/test_netlint.py`` lints this file and asserts
the finding set covers the whole rule catalogue, so a rule that
silently stops firing fails the suite.  Mirrors
``tests/fixtures/serve/threadlint_bad.py`` /
``tests/fixtures/ft/persistlint_bad.py``.
"""

import socket
import struct
import urllib.request


def nl101_blocking_on_untimed(addr):
    # allocated with no timeout= and no settimeout — the recv wedges
    # this thread forever against a half-open peer
    s = socket.create_connection(addr)
    try:
        return s.recv(1024)  # NL101 fires here
    finally:
        s.close()


def nl102_leaked_on_exception(addr):
    # timed (so no NL101) but the close is unconditional code that an
    # exception skips: no with, no finally, no ownership hand-off
    s = socket.create_connection(addr, timeout=5.0)  # NL102 fires here
    s.sendall(b"hello")
    data = s.recv(64)
    s.close()
    return data


def nl201_unpack_without_length_check(buf):
    # a truncated frame dies as struct.error, not the decoder's typed
    # ValueError
    magic, n = struct.unpack("<4sI", buf[:8])  # NL201 fires here
    return magic, n


def nl202_wire_length_sizes_alloc(buf):
    if len(buf) < 8:
        raise ValueError("short frame")
    n, = struct.unpack_from("<I", buf, 4)
    return bytearray(n)  # NL202 fires here: n is wire-derived, unbounded


def nl203_argless_response_read(url):
    with urllib.request.urlopen(url, timeout=5.0) as r:
        return r.read()  # NL203 fires here: buffers unbounded bytes


def nl203_uncapped_accumulation(sock):
    buf = b""
    while 1 == 1:
        chunk = sock.recv(4096)
        if not chunk:
            break
        buf += chunk  # NL203 fires here: no max-size comparison
    return buf


def nl204_handler_read_unbounded(self):
    # an HTTP handler trusting the peer's Content-Length whole
    n = int(self.headers.get("Content-Length", 0))
    return self.rfile.read(n)  # NL204 fires here


def nl301_hot_retry_forever(conn):
    while True:  # NL301 fires here: no backoff, no attempt cap
        try:
            conn.request("GET", "/healthz")
            return conn.getresponse()
        except OSError:
            continue


def nl001_reasonless_waiver(addr):
    s = socket.create_connection(addr)
    try:
        # netlint: disable=NL101
        return s.recv(1)  # waived, but the bare waiver raises NL001
    finally:
        s.close()


def nl002_unknown_rule():
    # netlint: disable=NL999 no such rule, raises NL002
    return None
