"""threadlint fixture: every TL rule must fire in this file (pinned by
tests/test_threadlint.py — the linter cannot silently lose a rule).

Each block is the minimal BAD version of a pattern the real tree either
avoids or guards; none of this code is ever imported or run.
"""

import queue
import signal
import threading
import time


class Inverted:
    """ab() takes _a then _b; ba() takes _b then _a — the classic
    lock-order inversion: two threads running one each deadlock."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:          # TL101: edge _a -> _b
                pass

    def ba(self):
        with self._b:
            with self._a:          # TL101: edge _b -> _a closes the cycle
                pass

    def reenter(self):
        with self._a:
            with self._a:          # TL102: non-reentrant Lock re-acquired
                pass


class Shared:
    """A worker thread mutates state the main thread reads — without the
    lock the class itself owns."""

    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self.items = {}
        self._q = queue.Queue()
        self._t = threading.Thread(target=self._worker, daemon=True)

    def _worker(self):
        self.total += 1            # TL201: unguarded shared write
        if "k" not in self.items:  # TL202: check-then-act outside the lock
            self.items["k"] = 1

    def blocking(self):
        with self._lock:
            time.sleep(1.0)        # TL301: sleep while holding the lock
            self._q.get()          # TL301: unbounded Queue.get under lock

    def read(self):
        with self._lock:
            return self.total, dict(self.items)


class Waiter:
    def __init__(self):
        self._cond = threading.Condition()
        self.ready = False

    def bad_wait(self):
        with self._cond:
            if not self.ready:
                self._cond.wait()  # TL501: wait under 'if', not 'while'


def _handler(signum, frame):
    import jax

    jax.block_until_ready(None)    # TL401: jax work inside a handler


def arm():
    signal.signal(signal.SIGUSR1, _handler)


def waivers():
    s = Shared()
    with s._lock:
        time.sleep(0.1)  # threadlint: disable=TL301
    # ^ reasonless waiver: silences its TL301 but raises TL001
    with s._lock:
        time.sleep(0.1)  # threadlint: disable=TL999 no such rule
    # ^ waiver naming an unknown rule: TL002 (its TL301 stays active)
