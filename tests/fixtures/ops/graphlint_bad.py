"""graphlint test fixture: one deliberate violation per rule.

NEVER imported — ``tests/test_graphlint.py`` lints this file and pins the
exact set of rule codes it must trip.  The directory is named ``ops/`` so
the file counts as graph scope (the linter keys graph scope off path
components).
"""

import functools
import time

import numpy as np
import jax
import jax.numpy as jnp
from mx_rcnn_tpu.obs.trace import span

_BAD_CONST = jnp.zeros((4,))  # GL402: module-level jnp constant

_F64 = np.float64  # graphlint: disable=GL401
# ^ waiver with NO reason: must itself be flagged (GL001)

_ALSO_F64 = np.float64(3.0)  # graphlint: disable=GL999 bogus rule code
# ^ waiver naming an unknown rule: GL002 (and the GL401 stays active)


@functools.partial(jax.jit, static_argnames=("flags",))
def jitted(x, flags=[1, 2]):  # GL303: mutable default on a static arg
    n = np.sum(x)                     # GL101: host numpy on a traced value
    v = float(x[0])                   # GL103: scalar coercion of a tracer
    print(v)                          # GL104: host print in jit scope
    nz = jnp.nonzero(x)               # GL201: dynamic output shape
    w = jnp.where(x > 0)              # GL201: one-arg where is nonzero
    y = x[x > 0]                      # GL202: boolean-mask indexing
    if jnp.any(x > 0):                # GL203: Python `if` on a tracer
        x = x + [1.0, 2.0]            # GL403: bare list literal arithmetic
    z = x.item()                      # GL102: host materialization
    u = x.astype(float)               # GL401: float64 promotion
    t = time.perf_counter()           # GL105: host clock measures tracing
    with span("step"):                # GL105: obs span in jit scope
        x = x * 2.0
    t2 = time.time()  # graphlint: disable=GL105 demo: a REASONED waiver silences the clock rule
    return n, v, nz, w, y, z, u, t, t2


def build_and_call(xs):
    for _ in range(2):
        f = jax.jit(functools.partial(jitted))  # GL301 (+GL302: in a loop)
    out = jax.jit(lambda a: a + 1)(xs)          # GL302: jit-and-call once
    return f, out
