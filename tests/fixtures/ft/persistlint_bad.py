"""persistlint fixture: every PL rule trips at least once here
(tests/test_persistlint.py pins the full set — the linter cannot
silently lose a rule).  Each function is a minimal bad example of one
rule; the docstrings say what SHOULD have been written."""

import hashlib
import json
import os


def pl101_raw_durable_write(data: bytes) -> None:
    """Bad: a durable checkpoint artifact written with a bare open —
    a crash mid-write leaves a torn .ckpt under the committed name.
    Good: utils/checkpoint._atomic_write(path, data)."""
    path = "out/model-0001.ckpt"
    with open(path, "wb") as f:
        f.write(data)


def pl102_rename_source_not_fsynced(data: bytes) -> None:
    """Bad: the staging file is renamed without ever being fsynced —
    the rename can persist while the data does not."""
    tmp = "out/model-0002.ckpt.tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, "out/model-0002.ckpt")
        dfd = os.open("out", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        os.unlink(tmp)
        raise


def pl103_rename_without_dirfsync(data: bytes) -> None:
    """Bad: data fsynced, but no directory fsync after the rename — a
    host crash can lose the rename, so the 'committed' file vanishes."""
    tmp = "out/model-0003.ckpt.tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, "out/model-0003.ckpt")
    except OSError:
        os.unlink(tmp)
        raise


def pl104_manifest_before_payload(payload: bytes) -> None:
    """Bad: the commit-point manifest is written BEFORE the payload it
    names — a crash between the two commits a manifest for files that
    do not exist yet.  (Both writes also trip PL101: raw opens.)"""
    with open("out/snap.manifest.json", "w") as f:
        f.write('{"files": {"snap.ckpt": {}}}')
    with open("out/snap.ckpt", "wb") as f:
        f.write(payload)


def pl105_tmp_leaked_on_exception(data: bytes) -> None:
    """Bad: no try/except cleanup around the staging write — a failed
    write leaks an adoptable .tmp orphan."""
    tmp = "out/model-0005.ckpt.tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, "out/model-0005.ckpt")
    dfd = os.open("out", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def pl201_unsorted_fingerprint(recipe: dict) -> str:
    """Bad: a sha-pinned identity serialized without sort_keys — the
    fingerprint depends on dict insertion order."""
    return hashlib.sha256(json.dumps(recipe).encode()).hexdigest()


def pl001_reasonless_waiver(data: bytes) -> None:
    """A waiver with no reason silences its finding but is itself a
    finding (PL001) — and naming a rule that does not exist is PL002."""
    tmp = "out/model-0006.ckpt.tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        # persistlint: disable=PL103
        os.replace(tmp, "out/model-0006.ckpt")
    except OSError:
        os.unlink(tmp)
        raise
    # persistlint: disable=PL999 no such rule
