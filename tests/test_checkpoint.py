"""Checkpoint/resume tests.

Reference invariants (SURVEY.md §5.4): per-epoch params files, resume via
``load_param`` + ``begin_epoch``.  The TPU design strengthens this to
bit-exact resume: a restored TrainState must continue producing the exact
same parameter trajectory as an uninterrupted run (the step folds
``state.step`` into the RNG, so the sample stream is position-indexed).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.test_train_step import KEY, make_batch, tiny_setup

from mx_rcnn_tpu.core.train import make_train_step
from mx_rcnn_tpu.utils.checkpoint import (
    checkpoint_path,
    combine_model,
    latest_checkpoint,
    load_param,
    restore_state,
    save_checkpoint,
)


@pytest.mark.slow
def test_save_restore_bit_exact_resume(tmp_path):
    cfg, model, tx, state = tiny_setup()
    step = jax.jit(make_train_step(model, cfg, tx))
    batches = [make_batch(seed=s) for s in range(5)]

    # uninterrupted: 3 + 2 steps, checkpoint after step 3
    s = state
    for b in batches[:3]:
        s, _ = step(s, b, KEY)
    prefix = os.path.join(str(tmp_path), "model", "ckpt")
    save_checkpoint(prefix, 3, s)
    for b in batches[3:]:
        s, _ = step(s, b, KEY)

    # resumed: fresh template, restore epoch-3 checkpoint, same 2 steps
    _, _, _, template = tiny_setup()
    r = restore_state(template, prefix, 3)
    assert int(r.step) == 3
    for b in batches[3:]:
        r, _ = step(r, b, KEY)

    for pa, pb in zip(jax.tree.leaves(s.params), jax.tree.leaves(r.params)):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))
    for oa, ob in zip(jax.tree.leaves(s.opt_state),
                      jax.tree.leaves(r.opt_state)):
        np.testing.assert_array_equal(np.asarray(oa), np.asarray(ob))
    assert int(r.step) == int(s.step) == 5


def test_load_param_roundtrip(tmp_path):
    _, _, _, state = tiny_setup()
    prefix = str(tmp_path / "m")
    path = save_checkpoint(prefix, 1, state)
    assert path == checkpoint_path(prefix, 1)
    params, batch_stats = load_param(prefix, 1)
    orig = jax.tree.leaves(state.params)
    rest = jax.tree.leaves(params)
    assert len(orig) == len(rest)
    for a, b in zip(orig, rest):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_latest_checkpoint(tmp_path):
    _, _, _, state = tiny_setup()
    prefix = str(tmp_path / "run" / "e2e")
    assert latest_checkpoint(prefix) is None
    for e in (1, 2, 10):
        save_checkpoint(prefix, e, state)
    epoch, path = latest_checkpoint(prefix)
    assert epoch == 10 and path.endswith("e2e-0010.ckpt")


def test_combine_model():
    a = {"backbone": {"w": jnp.ones(2)}, "rpn": {"w": jnp.ones(2) * 2},
         "cls_score": {"w": jnp.ones(2) * 3}}
    b = {"backbone": {"w": jnp.zeros(2)}, "rpn": {"w": jnp.zeros(2)},
         "cls_score": {"w": jnp.zeros(2) * 0}, "bbox_pred": {"w": jnp.ones(1)}}
    merged = combine_model(a, b, from_a=("rpn", "backbone"))
    assert float(merged["rpn"]["w"][0]) == 2.0
    assert float(merged["backbone"]["w"][0]) == 1.0
    assert float(merged["cls_score"]["w"][0]) == 0.0
    assert "bbox_pred" in merged


def test_checkpoint_file_is_atomic(tmp_path):
    _, _, _, state = tiny_setup()
    prefix = str(tmp_path / "m")
    save_checkpoint(prefix, 1, state)
    assert not os.path.exists(checkpoint_path(prefix, 1) + ".tmp")


def test_atomic_write_discipline_tmp_fsync_replace_dirsync(tmp_path,
                                                           monkeypatch):
    """The durability contract of ``_atomic_write`` (docs/FT.md): the tmp
    file is fsynced BEFORE the rename, and the parent directory AFTER —
    otherwise a host crash can lose either the bytes or the rename and the
    'atomic' checkpoint silently vanishes.  Records the actual syscall
    order via monkeypatching."""
    from mx_rcnn_tpu.utils import checkpoint as ckpt

    events = []
    real_fsync, real_replace, real_open = os.fsync, os.replace, os.open

    fd_kind = {}

    def spy_open(path, flags, *a, **kw):
        fd = real_open(path, flags, *a, **kw)
        fd_kind[fd] = "dir" if os.path.isdir(path) else "file"
        return fd

    def spy_fsync(fd):
        events.append(("fsync", fd_kind.get(fd, "file")))
        return real_fsync(fd)

    def spy_replace(src, dst):
        events.append(("replace", os.path.basename(dst)))
        return real_replace(src, dst)

    monkeypatch.setattr(os, "open", spy_open)
    monkeypatch.setattr(os, "fsync", spy_fsync)
    monkeypatch.setattr(os, "replace", spy_replace)
    path = str(tmp_path / "sub" / "x.ckpt")
    ckpt._atomic_write(path, b"payload")

    # regular file open() (the tmp write) doesn't route through os.open,
    # so 'file' fsync events are the data fsync; exactly one of each step
    # in the required order: fsync(tmp) -> replace -> fsync(dir)
    assert events == [("fsync", "file"), ("replace", "x.ckpt"),
                      ("fsync", "dir")]
    with open(path, "rb") as f:
        assert f.read() == b"payload"


@pytest.mark.slow
def test_orbax_export_import_roundtrip(tmp_path):
    """Native checkpoint → orbax directory → TrainState, bit-exact
    (ecosystem interop; SURVEY §5.4 names orbax as the TPU standard)."""
    from mx_rcnn_tpu.utils.checkpoint import export_orbax, import_orbax

    cfg, model, tx, state = tiny_setup()
    prefix = str(tmp_path / "m" / "e2e")
    save_checkpoint(prefix, 1, state)
    odir = export_orbax(prefix, 1, str(tmp_path / "orbax_ckpt"))
    restored = import_orbax(state, odir)
    assert int(restored.step) == int(state.step)
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_orbax_export_refuses_foreign_dir(tmp_path):
    """export_orbax must not silently delete a non-checkpoint directory
    (ADVICE r2): re-export over a prior export is fine, but clobbering an
    arbitrary non-empty dir requires overwrite=True."""
    import pytest

    from mx_rcnn_tpu.utils.checkpoint import export_orbax

    cfg, model, tx, state = tiny_setup()
    prefix = str(tmp_path / "m" / "e2e")
    save_checkpoint(prefix, 1, state)

    victim = tmp_path / "precious"
    victim.mkdir()
    (victim / "data.txt").write_text("do not eat")
    with pytest.raises(FileExistsError):
        export_orbax(prefix, 1, str(victim))
    assert (victim / "data.txt").read_text() == "do not eat"

    # explicit overwrite works, and re-export over a prior export works
    export_orbax(prefix, 1, str(victim), overwrite=True)
    export_orbax(prefix, 1, str(victim))
