"""Streaming input plane (docs/DATA.md): shard ownership, the
topology-invariant epoch plan, cursor resume, cache budgets, and
double-buffered host→device staging."""

import os

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import load_gt_roidb
from mx_rcnn_tpu.data.loader import (AnchorLoader, StreamLoader,
                                     stream_cache_budget)


@pytest.fixture(scope="module")
def rig(tmp_path_factory):
    """24 tiny synthetic images + a config whose bucket fits them."""
    root = str(tmp_path_factory.mktemp("stream_ds"))
    cfg = generate_config(
        "tiny", "synthetic", dataset__root_path=root,
        dataset__dataset_path=os.path.join(root, "synthetic"),
        train__flip=False)
    _, roidb = load_gt_roidb(cfg, training=True, num_images=24)
    return cfg, roidb


def _epoch_ids(loader, epoch=0):
    loader.record_decodes()
    loader.set_epoch(epoch)
    for _ in loader:
        pass
    return sorted(loader.decoded_ids)


# ---------------------------------------------------------------------------
# shard determinism + epoch exactness
# ---------------------------------------------------------------------------


def test_stream_epoch_exactly_once(rig):
    cfg, roidb = rig
    L = StreamLoader(roidb, cfg, batch_images=4, shuffle=True, seed=7,
                     num_workers=0)
    ids = _epoch_ids(L)
    assert len(ids) == 24 and len(set(ids)) == 24


def test_stream_plan_deterministic_across_instances(rig):
    cfg, roidb = rig
    plans = []
    for _ in range(2):
        L = StreamLoader(roidb, cfg, batch_images=4, shuffle=True, seed=7,
                         num_workers=0)
        plans.append(L._plan(3, 4))
    assert plans[0] == plans[1]
    # and across worker counts: the plan is pure (seed, epoch)
    L = StreamLoader(roidb, cfg, batch_images=4, shuffle=True, seed=7,
                     num_workers=2)
    assert L._plan(3, 4) == plans[0]


@pytest.mark.parametrize("num_shards", [1, 2, 4])
@pytest.mark.parametrize("num_workers", [0, 2])
def test_shard_union_is_epoch_exactly_once(rig, num_shards, num_workers):
    """The tentpole invariant: N shard owners (any worker count) decode
    the epoch exactly once between them, total/N each."""
    cfg, roidb = rig
    ref = _epoch_ids(StreamLoader(roidb, cfg, batch_images=4, shuffle=True,
                                  seed=7, num_workers=0))
    union, counts = [], []
    for s in range(num_shards):
        shard = (s, num_shards) if num_shards > 1 else None
        L = StreamLoader(roidb, cfg, batch_images=4, shuffle=True, seed=7,
                         num_workers=num_workers, shard=shard)
        union += _epoch_ids(L)
        counts.append(L.images_decoded)
    assert sorted(union) == ref
    assert counts == [24 // num_shards] * num_shards


def test_anchor_loader_shard_rows_bit_identical(rig):
    """AnchorLoader row shards: the union of shard rows IS the unsharded
    batch, bit for bit (the multiproc global batch cannot change)."""
    cfg, roidb = rig
    full = AnchorLoader(roidb, cfg, batch_images=4, shuffle=True, seed=1,
                        num_workers=0)
    full.set_epoch(0)
    shards = []
    for s in range(2):
        L = AnchorLoader(roidb, cfg, batch_images=4, shuffle=True, seed=1,
                         num_workers=0, shard=(s, 2))
        L.set_epoch(0)
        shards.append(list(L))
    for bf, b0, b1 in zip(list(full), *shards):
        for leaf_f, leaf_0, leaf_1 in zip(bf, b0, b1):
            np.testing.assert_array_equal(
                np.concatenate([leaf_0, leaf_1]), leaf_f)


def test_set_shard_validates(rig):
    cfg, roidb = rig
    L = StreamLoader(roidb, cfg, batch_images=4, num_workers=0)
    with pytest.raises(ValueError, match="not divisible"):
        L.set_shard(0, 3)
    with pytest.raises(ValueError, match="out of range"):
        L.set_shard(4, 4)
    L.set_shard(0, 1)  # <= 1 clears
    assert L.shard is None


# ---------------------------------------------------------------------------
# cursor resume + elastic shrink remap
# ---------------------------------------------------------------------------


def test_resume_at_same_topology_exactly_once(rig):
    """Kill mid-epoch → resume at the cursor: each image seen exactly
    once per epoch (ISSUE 7 satellite 1)."""
    cfg, roidb = rig
    L1 = StreamLoader(roidb, cfg, batch_images=4, shuffle=True, seed=9,
                      num_workers=0)
    L1.record_decodes()
    L1.set_epoch(0)
    it = iter(L1)
    for _ in range(3):  # 12 of 24 images, then "killed"
        next(it)
    it.close()
    ref = _epoch_ids(StreamLoader(roidb, cfg, batch_images=4, shuffle=True,
                                  seed=9, num_workers=0))
    L2 = StreamLoader(roidb, cfg, batch_images=4, shuffle=True, seed=9,
                      num_workers=0)
    L2.record_decodes()
    L2.set_epoch(0)
    L2.resume_at(12)
    for _ in L2:
        pass
    assert sorted(L1.decoded_ids + L2.decoded_ids) == ref


def test_resume_at_across_topology_change(rig):
    """Elastic shrink mid-epoch: the run resumes with HALF the batch size
    (2 devices → 1, accum x2) and a remapped shard set — no image dropped
    or duplicated (ISSUE 7 satellite 3)."""
    cfg, roidb = rig
    ref = _epoch_ids(StreamLoader(roidb, cfg, batch_images=4, shuffle=True,
                                  seed=5, num_workers=0))
    # before the shrink: a 2-process world, batch 4, shards (0,2)/(1,2)
    pre = []
    for s in range(2):
        L = StreamLoader(roidb, cfg, batch_images=4, shuffle=True, seed=5,
                         num_workers=0, shard=(s, 2))
        L.record_decodes()
        L.set_epoch(0)
        it = iter(L)
        for _ in range(4):  # 4 batches x 2 rows = 8 images per shard
            next(it)
        it.close()
        pre += L.decoded_ids
    assert len(pre) == 16  # 4 global batches of 4 consumed
    # after: one survivor, batch 2 (grad-accum doubled), shard cleared —
    # resumed from the cursor the manifest recorded (16 images, old bi=4)
    L2 = StreamLoader(roidb, cfg, batch_images=2, shuffle=True, seed=5,
                      num_workers=0)
    L2.record_decodes()
    L2.set_epoch(0)
    L2.resume_at(16, old_batch_images=4)
    for _ in L2:
        pass
    assert sorted(pre + L2.decoded_ids) == ref


def _two_bucket_roidb(n_land=16, n_port=8):
    """Fabricated two-orientation roidb — plan-level tests only (the
    image files do not exist; nothing decodes them)."""
    recs = []
    for i in range(n_land):
        recs.append(dict(image=f"l{i}.png", index=i, height=300, width=400,
                         boxes=np.zeros((1, 4), np.float32),
                         gt_classes=np.ones(1, np.int32), flipped=False))
    for i in range(n_port):
        recs.append(dict(image=f"p{i}.png", index=100 + i, height=400,
                         width=300, boxes=np.zeros((1, 4), np.float32),
                         gt_classes=np.ones(1, np.int32), flipped=False))
    return recs


def test_resume_same_topology_preserves_tail_order():
    """Same-topology resume must replay the ORIGINAL plan's tail batch
    for batch (not just the same set): step-exact resume on multi-bucket
    sets depends on the order, and re-interleaving the remainder would
    reorder it."""
    cfg = generate_config("tiny", "synthetic")
    roidb = _two_bucket_roidb()
    L = StreamLoader(roidb, cfg, batch_images=4, shuffle=True, seed=3,
                     num_workers=0)
    full = L._plan(0, 4)
    L2 = StreamLoader(roidb, cfg, batch_images=4, shuffle=True, seed=3,
                      num_workers=0)
    L2.resume_at(8)  # 2 batches consumed
    assert L2._epoch_plan(0) == full[2:]


def test_resume_across_topology_exactly_once_two_buckets():
    """Cross-topology resume on a multi-bucket set: the re-chunked
    remainder plus the old prefix is the epoch exactly once."""
    cfg = generate_config("tiny", "synthetic")
    roidb = _two_bucket_roidb()
    L = StreamLoader(roidb, cfg, batch_images=4, shuffle=True, seed=3,
                     num_workers=0)
    full = L._plan(0, 4)
    consumed = [i for _, idx in full[:3] for i in idx]
    L2 = StreamLoader(roidb, cfg, batch_images=2, shuffle=True, seed=3,
                      num_workers=0)
    L2.resume_at(12, old_batch_images=4)
    rest = [i for _, idx in L2._epoch_plan(0) for i in idx]
    want = sorted(i for _, idx in full for i in idx)
    assert sorted(consumed + rest) == want


def test_resume_at_rejects_misaligned_cursor(rig):
    cfg, roidb = rig
    L = StreamLoader(roidb, cfg, batch_images=4, num_workers=0)
    with pytest.raises(ValueError, match="batch boundary"):
        L.resume_at(6, old_batch_images=4)


def test_fit_consumes_data_cursor(rig, tmp_path):
    """End to end through train_net: a streaming run killed mid-epoch
    resumes via --resume auto and the manifest's data cursor — the two
    runs together decode the epoch exactly once."""
    jax = pytest.importorskip("jax")
    del jax
    from mx_rcnn_tpu.core.fit import fit
    from mx_rcnn_tpu.core.train import setup_training
    from mx_rcnn_tpu.models import build_model
    import jax as _jax

    root = str(tmp_path)
    cfg = generate_config(
        "tiny", "synthetic", dataset__root_path=root,
        dataset__dataset_path=os.path.join(root, "synthetic"),
        train__flip=False, train__rpn_pre_nms_top_n=64,
        train__rpn_post_nms_top_n=32, train__max_gt_boxes=8,
        bucket__scale=128, bucket__max_size=160,
        bucket__shapes=((128, 160), (160, 128)),
        train__batch_images=2, data__streaming=True)
    _, roidb = load_gt_roidb(cfg, training=True, num_images=12,
                             image_size=(128, 160), max_objects=2)
    prefix = os.path.join(root, "m", "e2e")

    def make(bi):
        L = StreamLoader(roidb, cfg, batch_images=bi, shuffle=True, seed=0,
                         num_workers=0)
        L.record_decodes()
        return L

    model = build_model(cfg)
    key = _jax.random.PRNGKey(0)
    state, tx = setup_training(model, cfg, key, (2, 128, 160, 3), 6)
    # run 1: stop after 2 steps (4 of 12 images), interrupt checkpoint
    stop = {"n": 0}

    def stop_flag():
        stop["n"] += 1
        return stop["n"] >= 2

    L1 = make(2)
    fit(model, cfg, state, tx, L1, 1, key, prefix=prefix,
        stop_flag=stop_flag)
    from mx_rcnn_tpu.utils.checkpoint import interrupt_path, read_manifest
    man = read_manifest(interrupt_path(prefix))
    assert man is not None and man["data_cursor"]["batches_consumed"] == 2
    # run 2: resume from the cursor; fit positions the loader itself
    state2, tx2 = setup_training(model, cfg, key, (2, 128, 160, 3), 6)
    from mx_rcnn_tpu.utils.checkpoint import restore_interrupt
    state2, spe = restore_interrupt(state2, prefix)
    L2 = make(2)
    fit(model, cfg, state2, tx2, L2, 1, key, prefix=None,
        data_cursor={"loader_batch_images": 2})
    # run 1 DECODED ahead of the kill (the stager's read-ahead — a
    # couple of batches may be decoded twice across a kill; docs/DATA.md
    # "exactly-once" is about training CONSUMPTION).  The consumed
    # prefix is the first 2 batches = 4 ids (deterministic order:
    # num_workers=0 and one stage thread); with the resumed run it must
    # cover the epoch exactly once.
    consumed1 = L1.decoded_ids[:4]
    union = sorted(consumed1 + L2.decoded_ids)
    assert len(union) == 12 and len(set(union)) == 12


# ---------------------------------------------------------------------------
# cache budget (satellite 2)
# ---------------------------------------------------------------------------


def test_cache_budget_clamped_to_dataset():
    cfg = generate_config("tiny", "synthetic",
                          default__image_cache_mb=2048)
    img = 240 * 320 * 3
    assert stream_cache_budget(cfg, 24, img) == 24 * img


def test_cache_budget_clamped_under_ceiling():
    cfg = generate_config("tiny", "synthetic",
                          default__image_cache_mb=2048,
                          data__ram_ceiling_mb=1536)
    img = 240 * 320 * 3
    got = stream_cache_budget(cfg, 100_000, img, batch_bytes=8 * img)
    # ceiling 1536MB - 1024MB floor - window leaves well under the ask
    assert 0 < got < (600 << 20)
    # and never negative even under an impossible ceiling
    cfg2 = generate_config("tiny", "synthetic",
                           default__image_cache_mb=2048,
                           data__ram_ceiling_mb=512)
    assert stream_cache_budget(cfg2, 100_000, img) == 0


def test_cache_budget_logged_once(caplog):
    import logging

    cfg = generate_config("tiny", "synthetic",
                          default__image_cache_mb=64)
    with caplog.at_level(logging.INFO, logger="mx_rcnn_tpu"):
        stream_cache_budget(cfg, 24, 240 * 320 * 3)
    assert sum("cache budget" in r.message for r in caplog.records) == 1


# ---------------------------------------------------------------------------
# staging
# ---------------------------------------------------------------------------


def test_stager_passthrough_bit_identical(rig):
    """Staged batches are the same batches: same order, same values,
    device-resident leaves."""
    jax = pytest.importorskip("jax")
    from mx_rcnn_tpu.data.staging import DeviceStager

    cfg, roidb = rig
    ref = list(StreamLoader(roidb, cfg, batch_images=4, shuffle=True,
                            seed=2, num_workers=0))
    L = StreamLoader(roidb, cfg, batch_images=4, shuffle=True, seed=2,
                     num_workers=0)
    stager = DeviceStager(iter(L), jax.device_put, depth=2)
    staged = list(stager)
    stager.close()
    assert len(staged) == len(ref)
    for a, b in zip(staged, ref):
        for la, lb in zip(a, b):
            assert isinstance(la, jax.Array)
            np.testing.assert_array_equal(np.asarray(la), lb)


def test_stager_records_overlap(rig):
    from mx_rcnn_tpu.data.staging import DeviceStager
    from mx_rcnn_tpu.obs.metrics import Registry

    cfg, roidb = rig
    rec = Registry()
    L = StreamLoader(roidb, cfg, batch_images=4, shuffle=False,
                     num_workers=0)
    stager = DeviceStager(iter(L), lambda b: b, depth=2, rec=rec)
    import time

    n = 0
    for _ in stager:
        time.sleep(0.02)  # a busy "device": the stager should run ahead
        n += 1
    stager.close()
    assert n == 6
    assert rec.counter("loader.staged_batches") == 6
    assert rec.counter("loader.stage_hits") > 0


def test_stager_propagates_source_errors():
    from mx_rcnn_tpu.data.staging import DeviceStager

    def boom():
        yield 1
        raise RuntimeError("decode failed")

    stager = DeviceStager(boom(), lambda x: x, depth=2)
    it = iter(stager)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="decode failed"):
        next(it)
    stager.close()


def test_stager_close_releases_worker(rig):
    """Early abandonment (consumer breaks) must not wedge the thread."""
    from mx_rcnn_tpu.data.staging import DeviceStager

    cfg, roidb = rig
    L = StreamLoader(roidb, cfg, batch_images=4, shuffle=False,
                     num_workers=0)
    stager = DeviceStager(iter(L), lambda b: b, depth=1)
    next(iter(stager))
    stager.close()
    assert not stager._thread.is_alive()
