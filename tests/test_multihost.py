"""True multi-PROCESS data parallelism (the multi-host rig).

Two worker processes (2 CPU devices each) run the full e2e train step over
the global (dcn=2, ici=2) mesh with jax.distributed; the launcher asserts
every process reports identical per-step losses — i.e. gradients really
synchronized across the process (host) boundary.  Ref: MXNet
``kvstore='dist_sync'`` (present but unexercised in the reference;
SURVEY.md §5.8).
"""

import pytest

pytestmark = pytest.mark.slow

from mx_rcnn_tpu.tools.multihost_demo import launch


def test_two_process_training_losses_agree():
    assert launch(2, steps=3) == 0


def test_four_process_hierarchical_losses_agree():
    """Four processes x 2 CPU devices: the (dcn=4, ici=2) hierarchical mesh
    synchronizes gradients across all 8 devices (VERDICT r02 item 8)."""
    assert launch(4, steps=2) == 0


def test_two_process_elastic_train_completes(tmp_path):
    """REAL multi-process elastic training (docs/FT.md "Elasticity"): a
    2-process jax.distributed world runs ``tools.train --elastic`` end to
    end — both workers exit 0, only process 0 writes checkpoints, and
    the final manifest records the 2-process topology.  The storm
    (kills, shrink, grow) is ``make elastic-smoke``; this pins the
    quiet-path wiring the storm builds on."""
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_COMPILATION_CACHE_DIR", "XLA_FLAGS")}
    env["JAX_PLATFORMS"] = "cpu"
    # pin 1 device/process OURSELVES: the conftest exports an 8-device
    # XLA_FLAGS that would otherwise override --local_devices
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    prefix = str(tmp_path / "m" / "e2e")
    cmd = lambda i: [  # noqa: E731
        sys.executable, "-m", "mx_rcnn_tpu.tools.train",
        "--network", "tiny", "--dataset", "synthetic",
        "--prefix", prefix, "--end_epoch", "1", "--seed", "0",
        "--frequent", "1000", "--no_flip", "--elastic",
        "--coordinator", f"localhost:{port}",
        "--num_processes", "2", "--process_id", str(i),
        "--local_devices", "1",
        "--dataset_kw",
        repr({"num_images": 8, "image_size": (128, 160),
              "max_objects": 3}),
        "--set", "train__rpn_pre_nms_top_n=1024",
        "--set", "train__rpn_post_nms_top_n=300",
        "--set", "train__max_gt_boxes=8",
        "--set", "bucket__scale=128", "--set", "bucket__max_size=160",
        "--set", "bucket__shapes=((128,160),(160,128))",
        "--set", "elastic__base_devices=2",
        "--root_path", str(tmp_path),
        "--dataset_path", str(tmp_path / "synthetic")]
    procs = [subprocess.Popen(cmd(i), env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for i in range(2)]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=600)[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert [p.returncode for p in procs] == [0, 0], outs
    from mx_rcnn_tpu.utils.checkpoint import (checkpoint_path,
                                              read_manifest)

    m = read_manifest(checkpoint_path(prefix, 1))
    assert m is not None and m["topology"]["processes"] == 2
    assert m["topology"]["global_batch"] == 2
    # every worker ran the same generation and emitted the timeline
    for out in outs:
        assert '"event": "complete"' in out, out[-2000:]
