"""True multi-PROCESS data parallelism (the multi-host rig).

Two worker processes (2 CPU devices each) run the full e2e train step over
the global (dcn=2, ici=2) mesh with jax.distributed; the launcher asserts
every process reports identical per-step losses — i.e. gradients really
synchronized across the process (host) boundary.  Ref: MXNet
``kvstore='dist_sync'`` (present but unexercised in the reference;
SURVEY.md §5.8).
"""

import pytest

pytestmark = pytest.mark.slow

from mx_rcnn_tpu.tools.multihost_demo import launch


def test_two_process_training_losses_agree():
    assert launch(2, steps=3) == 0


def test_four_process_hierarchical_losses_agree():
    """Four processes x 2 CPU devices: the (dcn=4, ici=2) hierarchical mesh
    synchronizes gradients across all 8 devices (VERDICT r02 item 8)."""
    assert launch(4, steps=2) == 0
