"""Standalone stage CLIs (ref rcnn/tools/train_rpn.py / test_rpn.py /
train_rcnn.py): the 4-stage pipeline driven tool-by-tool through argparse,
the way the reference's shell scripts chain them."""

import pytest

pytestmark = pytest.mark.slow

import os
import pickle

import numpy as np

from mx_rcnn_tpu.tools import test_rcnn, test_rpn, train_rcnn, train_rpn


def test_stage_clis_chain(tmp_path):
    root = str(tmp_path / "data")
    common = ["--network", "tiny", "--dataset", "synthetic",
              "--root_path", root, "--no_flip"]
    rpn_prefix = str(tmp_path / "rpn")
    train_rpn.main(common + ["--prefix", rpn_prefix, "--end_epoch", "1"])
    assert os.path.exists(rpn_prefix + "-0001.ckpt")

    props = str(tmp_path / "props.pkl")
    test_rpn.main(common + ["--prefix", rpn_prefix, "--epoch", "1",
                            "--out", props])
    with open(props, "rb") as f:
        proposals = pickle.load(f)
    assert len(proposals) == 64  # synthetic train set size
    assert all(np.asarray(p).ndim == 2 and np.asarray(p).shape[1] == 5
               for p in proposals if len(p))

    rcnn_prefix = str(tmp_path / "rcnn")
    train_rcnn.main(common + [
        "--prefix", rcnn_prefix, "--end_epoch", "1",
        "--proposals", props,
        "--init_from", rpn_prefix, "--init_from_epoch", "1",
        "--frozen_shared"])
    assert os.path.exists(rcnn_prefix + "-0001.ckpt")

    # eval side of the stage (ref rcnn/tools/test_rcnn.py): dump proposals
    # over the TEST roidb, then evaluate the RCNN-only checkpoint on them
    eval_props = str(tmp_path / "props_test.pkl")
    test_rpn.main(common + ["--prefix", rpn_prefix, "--epoch", "1",
                            "--out", eval_props, "--eval_set"])
    with open(eval_props, "rb") as f:
        test_proposals = pickle.load(f)
    assert len(test_proposals) == 16  # synthetic TEST set (no flip/filter)
    test_rcnn.main(["--network", "tiny", "--dataset", "synthetic",
                    "--root_path", root, "--prefix", rcnn_prefix,
                    "--epoch", "1", "--proposals", eval_props])
