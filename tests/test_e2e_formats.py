"""End-to-end integration over the REAL dataset formats.

The synthetic-dataset loop (test_fit_e2e.py) proves train→checkpoint→eval;
these tests prove the same loop through the reference's on-disk dataset
layouts — a generated VOCdevkit (JPEG + XML + ImageSets) and a generated
COCO tree (instances json + images) — exercising gt_roidb caching, class
mapping, detection-file writing, and the dataset-specific evaluators the
way a user with real data hits them (ref ``train_end2end.py`` /
``test.py`` on VOC07/COCO).
"""



import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow

cv2 = pytest.importorskip("cv2")

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.tools.test import test_rcnn as eval_rcnn
from mx_rcnn_tpu.tools.train import train_net
from tests.conftest import shrink_tiny_cfg

H, W = 128, 160
N_IMAGES = 24
# (class name, VOC/COCO-visible) → color; distinct saturated colors make the
# task learnable in a couple of epochs with the tiny net
CLASS_COLORS = {"dog": (220, 40, 40), "person": (40, 220, 40),
                "car": (40, 40, 220)}


def _render_images(rng):
    """Deterministic rectangle scenes: [(img, [(name, box)])]."""
    scenes = []
    names = list(CLASS_COLORS)
    for _ in range(N_IMAGES):
        img = rng.randint(0, 50, size=(H, W, 3)).astype(np.uint8)
        objs = []
        for _ in range(rng.randint(1, 3)):
            bw, bh = rng.randint(40, 80), rng.randint(32, 64)
            x1 = rng.randint(0, W - bw)
            y1 = rng.randint(0, H - bh)
            name = names[rng.randint(len(names))]
            img[y1:y1 + bh, x1:x1 + bw] = CLASS_COLORS[name]
            objs.append((name, (x1, y1, x1 + bw - 1, y1 + bh - 1)))
        scenes.append((img, objs))
    return scenes


def _write_voc(root, scenes):
    voc = os.path.join(root, "VOCdevkit", "VOC2007")
    for sub in ("ImageSets/Main", "Annotations", "JPEGImages"):
        os.makedirs(os.path.join(voc, sub), exist_ok=True)
    ids = []
    for i, (img, objs) in enumerate(scenes):
        idx = f"{i:06d}"
        ids.append(idx)
        cv2.imwrite(os.path.join(voc, "JPEGImages", idx + ".jpg"),
                    img[:, :, ::-1])
        objs_xml = "".join(
            f"<object><name>{name}</name><difficult>0</difficult>"
            f"<bndbox><xmin>{b[0] + 1}</xmin><ymin>{b[1] + 1}</ymin>"
            f"<xmax>{b[2] + 1}</xmax><ymax>{b[3] + 1}</ymax></bndbox>"
            f"</object>"
            for name, b in objs)
        with open(os.path.join(voc, "Annotations", idx + ".xml"), "w") as f:
            f.write(f"<annotation><size><width>{W}</width>"
                    f"<height>{H}</height><depth>3</depth></size>"
                    f"{objs_xml}</annotation>")
    with open(os.path.join(voc, "ImageSets", "Main", "train.txt"), "w") as f:
        f.write("\n".join(ids) + "\n")
    return os.path.join(root, "VOCdevkit")


def _write_coco(root, scenes):
    ds = os.path.join(root, "coco")
    os.makedirs(os.path.join(ds, "annotations"), exist_ok=True)
    os.makedirs(os.path.join(ds, "minitrain"), exist_ok=True)
    cats = [{"id": 7 * (i + 1), "name": n}  # non-contiguous ids on purpose
            for i, n in enumerate(CLASS_COLORS)]
    name_to_cat = {c["name"]: c["id"] for c in cats}
    images, annotations = [], []
    aid = 1
    for i, (img, objs) in enumerate(scenes):
        fname = f"{i:012d}.jpg"
        cv2.imwrite(os.path.join(ds, "minitrain", fname), img[:, :, ::-1])
        images.append({"id": i + 1, "file_name": fname,
                       "width": W, "height": H})
        for name, (x1, y1, x2, y2) in objs:
            annotations.append({
                "id": aid, "image_id": i + 1,
                "category_id": name_to_cat[name],
                "bbox": [float(x1), float(y1),
                         float(x2 - x1 + 1), float(y2 - y1 + 1)],
                "area": float((x2 - x1 + 1) * (y2 - y1 + 1)),
                "iscrowd": 0,
            })
            aid += 1
    with open(os.path.join(ds, "annotations",
                           "instances_minitrain.json"), "w") as f:
        json.dump({"images": images, "annotations": annotations,
                   "categories": cats}, f)
    return ds


def _shrink(cfg):
    return shrink_tiny_cfg(cfg)


def test_voc_layout_train_eval_loop(tmp_path):
    scenes = _render_images(np.random.RandomState(0))
    devkit = _write_voc(str(tmp_path), scenes)
    cfg = generate_config("tiny", "PascalVOC",
                          dataset__root_path=str(tmp_path),
                          dataset__dataset_path=devkit,
                          dataset__image_set="2007_train",
                          dataset__test_image_set="2007_train")
    cfg = _shrink(cfg)
    prefix = str(tmp_path / "model" / "voc")
    train_net(cfg, prefix=prefix, end_epoch=16, lr=3e-3, lr_step="14",
              frequent=1000, seed=0)
    out_dir = str(tmp_path / "dets")
    results = eval_rcnn(cfg, prefix=prefix, epoch=16, out_dir=out_dir,
                        verbose=False)
    # VOC mAP averages ALL 20 classes; only 3 exist here, so judge the
    # present classes (absent-class AP = 0 by construction)
    present = float(np.mean([results[c] for c in CLASS_COLORS]))
    assert present > 0.25, results
    # comp4 per-class detection files written WITH detections: the model
    # detects dogs (per-class AP above), so the dog file must be non-empty
    dog_files = [n for n in os.listdir(out_dir) if "dog" in n]
    assert dog_files, os.listdir(out_dir)
    assert os.path.getsize(os.path.join(out_dir, dog_files[0])) > 0
    # the roidb pkl cache was written and a second load round-trips
    import glob

    cache_files = glob.glob(os.path.join(str(tmp_path), "cache",
                                         "*_gt_roidb.pkl"))
    assert cache_files
    from mx_rcnn_tpu.data import load_gt_roidb

    _, roidb2 = load_gt_roidb(cfg, training=True)
    assert len(roidb2) == N_IMAGES


def test_coco_layout_train_eval_loop(tmp_path):
    # same scene set as the VOC test: one task, two on-disk formats
    scenes = _render_images(np.random.RandomState(0))
    ds_path = _write_coco(str(tmp_path), scenes)
    cfg = generate_config("tiny", "coco",
                          dataset__root_path=str(tmp_path),
                          dataset__dataset_path=ds_path,
                          dataset__image_set="minitrain",
                          dataset__test_image_set="minitrain",
                          dataset__num_classes=4)
    cfg = _shrink(cfg)
    prefix = str(tmp_path / "model" / "coco")
    train_net(cfg, prefix=prefix, end_epoch=16, lr=3e-3, lr_step="14",
              frequent=1000, seed=0)
    out_dir = str(tmp_path / "dets")
    results = eval_rcnn(cfg, prefix=prefix, epoch=16, out_dir=out_dir,
                        verbose=False)
    # COCO evaluator reports the mAP@[.5:.95] family
    assert any(k.startswith("AP") or k == "mAP" for k in results), results
    assert results["AP50"] > 0.25, results
    # results json written (ref _write_results_json)
    assert any(n.endswith(".json") for n in os.listdir(out_dir))
