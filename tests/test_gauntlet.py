"""The accuracy gauntlet (VERDICT r03 item 3).

Quick tier: generation invariants of ``synthetic_hard`` — determinism,
class balance, the occlusion visibility floor, registration through config
and ``load_gt_roidb``.

Slow tier: the pinned end-metric regression gate.  Measured environment
sensitivity matters here: the same seed-0 run (20-epoch calibration
variant) scores 0.7632 on a plain single-CPU-device JAX and 0.7094 under
the test harness's 8-virtual-device
``xla_force_host_platform_device_count`` flag (different XLA CPU thread
partitioning → different reduction numerics accumulating over thousands
of steps).
The gate therefore pins a one-sided FLOOR in its own environment rather
than a cross-environment equality: a point-level accuracy regression (bad
target assignment, broken NMS semantics, decode drift) costs far more
than the environment wobble and lands as a hard failure.  The recorded
3-seed table (``docs/gauntlet_results.json``, rendered in
``docs/GAUNTLET.md``) is cross-checked for spread-budget compliance by a
quick test.
"""

import json
import os

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import load_gt_roidb
from mx_rcnn_tpu.data.synthetic import (_HARD_PALETTE, HardSyntheticDataset,
                                        SyntheticDataset)

# production recipe: 400 train imgs, 30 epochs, lr 3e-3, step 24, batch 2
# (20 epochs froze a slow-starting seed underconverged — see
# docs/GAUNTLET.md calibration history).  Plain-env 5-seed range is
# 0.7296-0.7648; the harness environment measures ~0.05 lower (thread
# partitioning numerics), so the floor sits at plain-min − wobble −
# margin — far above an untrained/broken model (~0.0-0.3) and any
# point-level semantic regression.
GATE_FLOOR = 0.66
# measured 5-seed spread is 0.0352; the budget matches the measurement
# (not the aspirational 0.02) with headroom for one more outlier seed
SPREAD_BUDGET = 0.05


def test_hard_dataset_generation_invariants(tmp_path):
    ds = HardSyntheticDataset("train", str(tmp_path), "")
    assert ds.num_images == 400 and ds.num_classes == 9
    ds_test = HardSyntheticDataset("test", str(tmp_path), "")
    assert ds_test.num_images == 100
    # deterministic: a fresh instance reproduces identical specs
    ds2 = HardSyntheticDataset("train", str(tmp_path), "")
    for a, b in zip(ds._specs, ds2._specs):
        np.testing.assert_array_equal(a["boxes"], b["boxes"])
        np.testing.assert_array_equal(a["gt_classes"], b["gt_classes"])
        assert a["noise_seed"] == b["noise_seed"]
    # crowding + scale variation are actually present
    nobj = [len(s["boxes"]) for s in ds._specs]
    assert max(nobj) >= 6 and min(nobj) >= 2
    widths = np.concatenate([s["boxes"][:, 2] - s["boxes"][:, 0] + 1
                             for s in ds._specs])
    assert widths.min() < 40 and widths.max() > 120
    # all 8 fg classes appear, roughly balanced (no class under 5%)
    cls = np.concatenate([s["gt_classes"] for s in ds._specs])
    hist = np.bincount(cls, minlength=9)[1:]
    assert (hist > 0.05 * len(cls) / 8).all(), hist


def test_hard_dataset_visibility_floor(tmp_path):
    """Painter's-algorithm check, recomputed independently of the
    generator: every gt box must keep >= MIN_VISIBLE of its own pixels
    after all later draws — the property that keeps the mAP ceiling
    well-defined (a buried box is unfindable by any detector)."""
    ds = HardSyntheticDataset("train", str(tmp_path), "")
    h, w = ds.image_size
    for spec in ds._specs:
        boxes = spec["boxes"].astype(int)
        owner = np.full((h, w), -1, np.int32)
        for k, (x1, y1, x2, y2) in enumerate(boxes):
            owner[y1:y2 + 1, x1:x2 + 1] = k
        for k, (x1, y1, x2, y2) in enumerate(boxes):
            area = (y2 - y1 + 1) * (x2 - x1 + 1)
            vis = (owner[y1:y2 + 1, x1:x2 + 1] == k).sum()
            assert vis / area >= HardSyntheticDataset.MIN_VISIBLE - 1e-9, (
                f"box {k} only {vis / area:.2f} visible")


def test_hard_dataset_occlusion_and_distractors_exist(tmp_path):
    """The set must actually BE hard: some boxes are partially occluded
    and every image carries distractor rectangles."""
    ds = HardSyntheticDataset("train", str(tmp_path), "")
    h, w = ds.image_size
    # distractor placement is best-effort (rejected when overlapping real
    # objects): nearly every image carries some, totalling in the hundreds
    counts = [len(s["distractors"]) for s in ds._specs]
    assert sum(c == 0 for c in counts) < 0.05 * len(counts)
    assert sum(counts) > 2 * len(counts)
    occluded = 0
    for spec in ds._specs:
        boxes = spec["boxes"].astype(int)
        owner = np.full((h, w), -1, np.int32)
        for k, (x1, y1, x2, y2) in enumerate(boxes):
            owner[y1:y2 + 1, x1:x2 + 1] = k
        for k, (x1, y1, x2, y2) in enumerate(boxes):
            area = (y2 - y1 + 1) * (x2 - x1 + 1)
            if (owner[y1:y2 + 1, x1:x2 + 1] == k).sum() < area:
                occluded += 1
    assert occluded > 100, f"only {occluded} occluded boxes in 400 images"


def test_hard_dataset_registration(tmp_path):
    cfg = generate_config("tiny", "synthetic_hard",
                          dataset__root_path=str(tmp_path))
    assert cfg.num_classes == 9
    assert cfg.bucket.shapes == ((240, 320), (320, 240))
    imdb, roidb = load_gt_roidb(cfg, training=False)
    assert isinstance(imdb, HardSyntheticDataset)
    assert len(roidb) == 100
    # train mode: flip doubles the records
    _, train_roidb = load_gt_roidb(cfg, training=True)
    assert len(train_roidb) == 800


def test_hard_dataset_render_distinct_classes(tmp_path):
    """Rendered pixels inside an UNOCCLUDED box must be dominated by the
    class hue (brightness jitter and stripes move intensity, not hue
    ordering) — the learnability contract."""
    ds = HardSyntheticDataset("train", str(tmp_path), "")
    checked = 0
    for spec in ds._specs[:40]:
        img = ds._render(spec)
        boxes = spec["boxes"].astype(int)
        for k, (x1, y1, x2, y2) in enumerate(boxes):
            # only unoccluded-by-later boxes give a clean sample
            if any(ds._iou(boxes[k], boxes[j]) > 0 for j in
                   range(k + 1, len(boxes))):
                continue
            inner = img[y1 + 2:y2 - 1, x1 + 2:x2 - 1].reshape(-1, 3)
            if len(inner) < 10:
                continue
            mean = inner.mean(axis=0)
            base = _HARD_PALETTE[spec["gt_classes"][k] - 1].astype(float)
            # hue match: the argmax channel survives jitter/stripes
            assert mean.argmax() == base.argmax(), (mean, base)
            checked += 1
    assert checked > 30


def test_recorded_gauntlet_results_within_budget():
    """The committed gauntlet table must satisfy its own contract: >= 3
    seeds for e2e/tiny, per-seed spread within SPREAD_BUDGET, and every
    seed above the gate floor."""
    from mx_rcnn_tpu.tools.gauntlet import summarize

    path = os.path.join(os.path.dirname(__file__), "..", "docs",
                        "gauntlet_results.json")
    with open(path) as f:
        records = json.load(f)
    s = summarize(records)["e2e/tiny"]
    assert len(s["seeds"]) >= 5
    assert s["spread"] <= SPREAD_BUDGET, s
    # the committed table is plain-env: every seed clears the floor with
    # the environment wobble to spare
    assert min(s["mAPs"]) >= GATE_FLOOR + 0.05, s


@pytest.mark.slow
@pytest.mark.gate
def test_gauntlet_pinned_seed0_regression_gate(tmp_path):
    """Train seed 0 with the production gauntlet recipe from scratch and
    assert the mAP floor (see module docstring for why a one-sided floor
    in this environment, not a cross-environment equality)."""
    from mx_rcnn_tpu.tools.gauntlet import main as gauntlet_main

    out = tmp_path / "results.json"
    gauntlet_main(["--root", str(tmp_path), "--workdir",
                   str(tmp_path / "work"), "--out", str(out),
                   "--seeds", "0", "--mode", "e2e"])
    with open(out) as f:
        rec = json.load(f)[0]
    assert rec["mAP"] >= GATE_FLOOR, rec


@pytest.mark.slow
@pytest.mark.gate
def test_paired_gate_fires_on_damaged_arm(tmp_path):
    """Red-team of the --compare gate (VERDICT r5 weak #4): the FAIL
    direction had only ever been exercised on fabricated records.  Here
    one arm is DELIBERATELY damaged (redteam mode: eval NMS 0.9 floods
    the AP sweep with surviving duplicates) and the gate must fire on
    the real training pair: exit 1, with every per-seed delta decisively
    negative.  The recorded plain-env run of the same recipe is
    docs/gauntlet_redteam.json (docs/GAUNTLET.md "Red-team")."""
    import io
    from contextlib import redirect_stdout

    from mx_rcnn_tpu.tools.gauntlet import main as gauntlet_main

    out = tmp_path / "results.json"
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = gauntlet_main([
            "--root", str(tmp_path), "--workdir", str(tmp_path / "w"),
            "--out", str(out), "--network", "tiny",
            "--seeds", "0", "1", "--epochs", "4", "--lr", "3e-3",
            "--lr_step", "3", "--compare", "e2e", "redteam"])
    assert rc == 1, "gate FAIL direction did not fire on a damaged arm"
    cmp = [json.loads(line) for line in buf.getvalue().splitlines()
           if '"compare"' in line][-1]
    assert cmp["compare"] == "redteam-vs-e2e"
    # the damage is not subtle: every seed must lose well past the budget
    assert all(d < -cmp["budget"] for d in cmp["deltas"]), cmp
    assert cmp["mean_delta"] < -0.05, cmp
    assert cmp["within_budget"] is False
    # and the damaged arm is labelled as such in its records
    recs = json.loads(out.read_text())
    assert all(r["damage"] == "test__nms=0.9" for r in recs
               if r["mode"] == "redteam")


def test_easy_dataset_unchanged(tmp_path):
    """The hard subclass must not perturb the easy set's generation (its
    pinned expectations elsewhere depend on byte-identical specs)."""
    ds = SyntheticDataset("train", str(tmp_path), "", num_images=4)
    # stable fingerprint of the first spec under seed crc32('train')
    s0 = ds._specs[0]
    assert s0["boxes"].shape[1] == 4
    sig = ds._spec_signature()
    ds2 = SyntheticDataset("train", str(tmp_path), "", num_images=4)
    assert ds2._spec_signature() == sig


# ---------------------------------------------------------------------------
# Paired-seed A/B compare (VERDICT r04 item 4): the contract of
# tools/gauntlet.py --compare.  Pure-function + CLI level; no training.
# ---------------------------------------------------------------------------

def _recs(mode, maps, network="tiny"):
    return [{"mode": mode, "network": network, "seed": s, "mAP": m}
            for s, m in enumerate(maps)]


def test_paired_compare_neutral_change_passes_tight_budget():
    from mx_rcnn_tpu.tools.gauntlet import paired_compare

    base = [0.7648, 0.7448, 0.7638, 0.7332, 0.7517]  # committed e2e table
    arm = [m + d for m, d in zip(base, [0.002, -0.003, 0.001, 0.0, -0.002])]
    cmp = paired_compare(_recs("e2e", base) + _recs("prenms", arm),
                         "e2e", "prenms", "tiny", budget=0.02)
    assert cmp["seeds"] == [0, 1, 2, 3, 4]
    assert cmp["deltas"] == [0.002, -0.003, 0.001, 0.0, -0.002]
    assert cmp["within_budget"] is True
    lo, hi = cmp["ci95"]
    assert -0.02 <= lo <= cmp["mean_delta"] <= hi <= 0.02
    # the absolute-spread gate could NEVER see this: the seed spread of
    # the base arm alone (0.0316) dwarfs every per-seed delta
    assert max(base) - min(base) > max(abs(d) for d in cmp["deltas"])


def test_paired_compare_small_regression_caught():
    """A uniform −0.015 regression is invisible to the ±0.035-spread
    absolute gate but must fail the paired budget: with the seed noise
    cancelled, the CI sits tightly around −0.015 and pokes out of ±0.01;
    the sign test agrees (5/5 negative, p = 0.0625)."""
    from mx_rcnn_tpu.tools.gauntlet import paired_compare

    base = [0.7648, 0.7448, 0.7638, 0.7332, 0.7517]
    jitter = [0.001, -0.002, 0.002, -0.001, 0.0]
    arm = [m - 0.015 + j for m, j in zip(base, jitter)]
    cmp = paired_compare(_recs("e2e", base) + _recs("prenms", arm),
                         "e2e", "prenms", "tiny", budget=0.01)
    assert cmp["within_budget"] is False
    assert cmp["mean_delta"] < -0.01
    assert cmp["sign_test_p"] == 0.0625  # 2 * 0.5**5
    # but it IS equivalent under a generous 0.05 budget
    loose = paired_compare(_recs("e2e", base) + _recs("prenms", arm),
                           "e2e", "prenms", "tiny", budget=0.05)
    assert loose["within_budget"] is True


def test_paired_compare_single_seed_proves_nothing():
    from mx_rcnn_tpu.tools.gauntlet import paired_compare

    cmp = paired_compare(_recs("e2e", [0.75]) + _recs("prenms", [0.75]),
                         "e2e", "prenms", "tiny")
    assert cmp["within_budget"] is False  # infinite CI: no evidence
    import pytest as _pytest
    with _pytest.raises(ValueError, match="no common seeds"):
        paired_compare(_recs("e2e", [0.75]), "e2e", "prenms", "tiny")


def test_compare_cli_reuses_records_and_gates(tmp_path, capsys):
    """--compare over an --out file whose cells all exist must not train:
    it reports the paired stats and exits by the budget gate."""
    from mx_rcnn_tpu.tools.gauntlet import main as gauntlet_main

    base = [0.7648, 0.7448, 0.7638]
    out = tmp_path / "results.json"
    with open(out, "w") as f:
        json.dump(_recs("e2e", base)
                  + _recs("prenms", [m + 0.001 for m in base]), f)
    rc = gauntlet_main(["--out", str(out), "--root", str(tmp_path),
                        "--workdir", str(tmp_path / "w"),
                        "--seeds", "0", "1", "2",
                        "--compare", "e2e", "prenms"])
    assert rc == 0
    lines = [json.loads(l) for l in
             capsys.readouterr().out.strip().splitlines()]
    cmp = [l for l in lines if "compare" in l]
    assert cmp and cmp[0]["compare"] == "prenms-vs-e2e"
    assert cmp[0]["deltas"] == [0.001, 0.001, 0.001]
    # a clear regression in one arm flips the exit code
    with open(out, "w") as f:
        json.dump(_recs("e2e", base)
                  + _recs("prenms", [m - 0.04 for m in base]), f)
    rc = gauntlet_main(["--out", str(out), "--root", str(tmp_path),
                        "--workdir", str(tmp_path / "w"),
                        "--seeds", "0", "1", "2",
                        "--compare", "e2e", "prenms"])
    assert rc == 1


def test_compare_cli_refuses_recipe_mismatch(tmp_path, capsys):
    """Existing records under a different recipe must ERROR (protecting
    committed baselines from silent retrain-and-replace), not retrain."""
    from mx_rcnn_tpu.tools.gauntlet import main as gauntlet_main

    out = tmp_path / "results.json"
    recs = _recs("e2e", [0.7, 0.71, 0.72])
    for r in recs:
        r["epochs"] = 30  # committed baseline recipe
    with open(out, "w") as f:
        json.dump(recs, f)
    with pytest.raises(SystemExit) as ex:
        gauntlet_main(["--out", str(out), "--root", str(tmp_path),
                       "--workdir", str(tmp_path / "w"),
                       "--seeds", "0", "--mode", "e2e", "--epochs", "2"])
    assert ex.value.code == 2  # argparse error exit
    assert "DIFFERENT recipe" in capsys.readouterr().err
    with open(out) as f:  # baseline untouched
        assert json.load(f) == recs


def test_gauntlet_prevalidates_all_cells_before_training(tmp_path, capsys,
                                                         monkeypatch):
    """Recipe-mismatch validation must cover every requested (mode, seed)
    cell BEFORE the first training run (ADVICE r5): the guard used to fire
    mid-loop, aborting an invocation after it had already trained and
    committed earlier cells."""
    from mx_rcnn_tpu.tools import gauntlet

    out = tmp_path / "results.json"
    recs = _recs("e2e", [0.7])     # seed 0 only
    recs[0]["epochs"] = 30         # committed baseline recipe
    with open(out, "w") as f:
        json.dump(recs, f)
    trained = []
    monkeypatch.setattr(gauntlet, "run_one",
                        lambda args, mode, seed: trained.append((mode, seed)))
    # seed 1 is missing (the old code would train it first); seed 0 exists
    # under a different recipe — the invocation must refuse up front
    with pytest.raises(SystemExit) as ex:
        gauntlet.main(["--out", str(out), "--root", str(tmp_path),
                       "--workdir", str(tmp_path / "w"),
                       "--seeds", "1", "0", "--mode", "e2e",
                       "--epochs", "2"])
    assert ex.value.code == 2
    assert "DIFFERENT recipe" in capsys.readouterr().err
    assert trained == []           # nothing ran before the refusal
    with open(out) as f:           # baseline untouched
        assert json.load(f) == recs


def test_summary_and_markdown_annotate_recipe(tmp_path):
    """summarize/render_markdown must surface the recipe of every record
    so mixed-recipe result files are visible, not silently aggregated
    (ADVICE r5)."""
    from mx_rcnn_tpu.tools.gauntlet import render_markdown, summarize

    recs = _recs("e2e", [0.70, 0.71])
    for r, ep in zip(recs, (30, 20)):
        r.update(epochs=ep, lr=3e-3, lr_step=None, batch_images=2)
    s = summarize(recs)["e2e/tiny"]
    assert s["recipes"] == ["ep20/lr0.003/stepauto/bi2",
                            "ep30/lr0.003/stepauto/bi2"]
    md = tmp_path / "t.md"
    render_markdown(recs, str(md))
    text = md.read_text()
    assert "| recipe |" in text
    assert "ep30/lr0.003/stepauto/bi2" in text
