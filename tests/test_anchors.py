"""Anchor generation golden tests.

The canonical anchor values for base_size=16, ratios (0.5,1,2), scales
(8,16,32) are fixed in the py-faster-rcnn lineage the reference inherits
(ref ``rcnn/processing/generate_anchor.py — generate_anchors``).
"""

import numpy as np

from mx_rcnn_tpu.ops.anchors import generate_anchors, generate_shifted_anchors

# The canonical 9-anchor table (documented in py-faster-rcnn's
# generate_anchors.py docstring; reproduced by the reference).
GOLDEN = np.array(
    [
        [-84., -40., 99., 55.],
        [-176., -88., 191., 103.],
        [-360., -184., 375., 199.],
        [-56., -56., 71., 71.],
        [-120., -120., 135., 135.],
        [-248., -248., 263., 263.],
        [-36., -80., 51., 95.],
        [-80., -168., 95., 183.],
        [-168., -344., 183., 359.],
    ],
    dtype=np.float32,
)


def test_generate_anchors_golden():
    got = generate_anchors(16, (0.5, 1.0, 2.0), (8, 16, 32))
    np.testing.assert_allclose(got, GOLDEN)


def test_shifted_anchor_layout():
    a = generate_shifted_anchors(2, 3, feat_stride=16)
    assert a.shape == (2 * 3 * 9, 4)
    # index (y, x, k) = (y*W + x)*A + k; shifting one cell right adds 16 to x
    np.testing.assert_allclose(a[9] - a[0], [16, 0, 16, 0])
    # one cell down adds 16 to y
    np.testing.assert_allclose(a[3 * 9] - a[0], [0, 16, 0, 16])
    # anchor 0 at cell (0,0) is the golden base anchor
    np.testing.assert_allclose(a[0], GOLDEN[0])


def test_shifted_anchor_count_stride8():
    a = generate_shifted_anchors(4, 4, feat_stride=8, scales=(4,))
    assert a.shape == (4 * 4 * 3, 4)


def test_sublane_bucket_640x1024_regenerates_valid_anchors():
    """r6 bucket experiment: switching the bucket to 640x1024 (40x64
    stride-16 grid — 40 is a whole number of 8-row sublanes, unlike the
    default 38) must regenerate anchors automatically and validly; the
    config override path is what script/perf_r6.sh leg 4 exercises."""
    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.data.image import choose_bucket

    cfg = generate_config(
        "resnet101", "coco",
        bucket__shapes=[[640, 1024], [1024, 640]])
    assert cfg.bucket.shapes == ((640, 1024), (1024, 640))
    for h, w in cfg.bucket.shapes:
        assert h % 32 == 0 and w % 32 == 0  # feature grid stays aligned
        fh, fw = h // 16, w // 16
        a = generate_shifted_anchors(fh, fw, 16)
        assert a.shape == (fh * fw * 9, 4)
        assert np.isfinite(a).all()
        # grid covers the full bucket: last cell's base anchor sits at
        # ((fw-1)*16, (fh-1)*16) offset from the golden base anchor
        np.testing.assert_allclose(
            a[-9] - GOLDEN[0],
            [(fw - 1) * 16, (fh - 1) * 16, (fw - 1) * 16, (fh - 1) * 16])
    assert 640 // 16 == 40 and 40 % 8 == 0  # the sublane-friendly point
    # a landscape VOC-scale image routes into the landscape bucket
    assert choose_bucket(600, 1000, cfg.bucket.shapes) == (640, 1024)
