"""Unified observability layer tests (ISSUE 4).

Pinned properties:

* the ``serve.metrics`` back-compat shim re-exports the PROMOTED classes
  and the ``ServeMetrics`` snapshot stays bit-identical to the
  pre-promotion implementation (vendored here verbatim as the oracle),
  so ``docs/serve_bench_*.json`` comparisons remain valid;
* the registry is exact under concurrent recorders;
* spans nest/order correctly in the exported chrome trace and carry
  bound trace ids;
* ONE trace id survives a serve request's queue → engine → respond hops
  across threads;
* SIGUSR2 opens/closes a profiler window that rolls up to a parseable,
  non-empty device-time table;
* the Speedometer registry wiring leaves its stdout line byte-identical;
* the DISABLED hot path costs near zero (the seed fit loop had no obs
  code at all, so the delta vs seed is exactly the cost of the disabled
  branches measured here), and the measured enabled-mode overhead
  recorded in docs/obs_overhead.json is inside the <2% acceptance bar.
"""

import json
import os
import signal
import threading
import time
import urllib.request

import numpy as np
import pytest

from mx_rcnn_tpu.obs import trace as obs_trace
from mx_rcnn_tpu.obs.metrics import (Histogram, Registry, ServeMetrics,
                                     registry, start_metrics_server)
from mx_rcnn_tpu.obs.runrec import RunRecord

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# back-compat shim + bit-identical snapshot format
# ---------------------------------------------------------------------------

def test_serve_metrics_shim_reexports_promoted_classes():
    import mx_rcnn_tpu.obs.metrics as obs_metrics
    import mx_rcnn_tpu.serve.metrics as serve_metrics

    assert serve_metrics.Histogram is obs_metrics.Histogram
    assert serve_metrics.ServeMetrics is obs_metrics.ServeMetrics
    assert serve_metrics.LoweringCounter is obs_metrics.LoweringCounter


class _OldHistogram:
    """The pre-promotion serve/metrics.py Histogram, verbatim — the
    oracle for bucket edges and percentile readout."""

    def __init__(self, lo=0.1, hi=30_000.0, buckets=40):
        self.bounds = np.geomspace(lo, hi, buckets)
        self.counts = np.zeros(buckets + 1, np.int64)
        self.total = 0
        self.sum = 0.0
        self.max = 0.0

    def record(self, value):
        i = int(np.searchsorted(self.bounds, value))
        self.counts[i] += 1
        self.total += 1
        self.sum += value
        self.max = max(self.max, value)

    def percentile(self, p):
        if self.total == 0:
            return None
        rank = int(np.ceil(p / 100.0 * self.total))
        rank = min(max(rank, 1), self.total)
        cum = np.cumsum(self.counts)
        i = int(np.searchsorted(cum, rank))
        if i >= len(self.bounds):
            return float(self.max)
        return float(self.bounds[i])

    @property
    def mean(self):
        return self.sum / self.total if self.total else None


def _old_snapshot(counters, hists, rows):
    """The pre-promotion ServeMetrics.snapshot(), verbatim."""
    out = {"counters": dict(counters)}
    for name, h in hists.items():
        pct = {p: h.percentile(p) for p in (50, 90, 99)}
        out[name] = {
            "count": h.total,
            "mean": None if h.mean is None else round(h.mean, 3),
            **{f"p{p}": None if v is None else round(v, 3)
               for p, v in pct.items()},
            "max": round(h.max, 3) if h.total else None,
        }
    b = counters["batches"]
    out["batch_occupancy"] = {
        "batches": b,
        "mean_rows": round(rows / b, 3) if b else None,
        "padded_rows": counters["padded_rows"],
    }
    c = counters
    out["terminated"] = c["served"] + c["shed"] + c["expired"] + c["failed"]
    out["in_flight"] = c["submitted"] - out["terminated"]
    return out


def test_serve_snapshot_bit_identical_to_old_format():
    """Feed an identical traffic pattern into the promoted ServeMetrics
    and the vendored old implementation: the JSON must match byte for
    byte (docs/serve_bench_*.json comparability)."""
    rng = np.random.RandomState(0)
    new = ServeMetrics()
    old_counters = {k: 0 for k in ("submitted", "served", "shed",
                                   "expired", "failed", "batches",
                                   "padded_rows")}
    old_hists = {"queue_wait_ms": _OldHistogram(),
                 "model_ms": _OldHistogram(), "total_ms": _OldHistogram()}
    old_rows = 0
    for i in range(500):
        new.count("submitted")
        old_counters["submitted"] += 1
        q, t = rng.uniform(0.05, 900.0, 2)
        new.observe("queue_wait_ms", q)
        old_hists["queue_wait_ms"].record(q)
        terminal = ("served", "shed", "expired", "failed")[i % 4]
        new.count(terminal)
        old_counters[terminal] += 1
        new.observe("total_ms", t)
        old_hists["total_ms"].record(t)
        if i % 3 == 0:
            rows = 1 + i % 4
            m = float(rng.uniform(1.0, 50.0))
            new.observe_batch(rows, 4, m)
            old_counters["batches"] += 1
            old_counters["padded_rows"] += 4 - rows
            old_rows += rows
            old_hists["model_ms"].record(m)
    expect = _old_snapshot(old_counters, old_hists, old_rows)
    assert json.dumps(new.snapshot(), sort_keys=True) \
        == json.dumps(expect, sort_keys=True)
    # bucket edges pinned exactly
    np.testing.assert_array_equal(Histogram().bounds,
                                  np.geomspace(0.1, 30_000.0, 40))


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_exact_under_concurrent_recorders():
    reg = Registry()
    threads, per = 8, 2000

    def worker(wid):
        for i in range(per):
            reg.inc("c.total")
            reg.observe("h.lat_ms", float(i % 7) + 0.5)
            reg.set_gauge(f"g.w{wid}", i)

    ts = [threading.Thread(target=worker, args=(w,)) for w in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert reg.counter("c.total") == threads * per
    assert reg.hist("h.lat_ms").total == threads * per
    snap = reg.snapshot()
    assert snap["counters"]["c.total"] == threads * per
    assert snap["hists"]["h.lat_ms"]["count"] == threads * per
    assert all(snap["gauges"][f"g.w{w}"] == per - 1 for w in range(threads))


def test_registry_reset_is_prefix_scoped():
    reg = Registry()
    reg.inc("serve.submitted")
    reg.inc("train.steps")
    reg.observe("serve.total_ms", 1.0)
    reg.reset("serve.")
    assert reg.counter("serve.submitted") == 0  # recreated lazily at 0
    assert reg.counter("train.steps") == 1
    assert reg.hist("serve.total_ms") is None


def test_serve_metrics_survive_registry_reset_mid_traffic():
    """Registry.reset REMOVES entries; a ServeMetrics sharing that
    registry must keep recording (keys recreate at zero) instead of
    KeyError-ing the dispatcher thread mid-traffic."""
    reg = Registry()
    m = ServeMetrics(registry=reg)
    m.count("submitted")
    m.observe_batch(2, 4, 5.0)
    reg.reset()  # e.g. a phase boundary clearing the process registry
    m.count("served")
    m.observe_batch(1, 4, 3.0)
    snap = m.snapshot()
    assert snap["counters"]["served"] == 1
    assert snap["counters"]["submitted"] == 0  # cleared, recreated at 0
    assert snap["batch_occupancy"]["batches"] == 1
    assert snap["model_ms"]["count"] == 1
    assert m.counters["shed"] == 0 and "total_ms" in m.hists


def test_serve_metrics_on_shared_registry_namespaces_cleanly():
    """A ServeMetrics on the process-style shared registry publishes
    under serve.* without clobbering other subsystems, and its reset
    leaves them alone."""
    reg = Registry()
    reg.inc("train.steps", 5)
    m = ServeMetrics(registry=reg)
    m.count("submitted")
    assert reg.counter("serve.submitted") == 1
    assert reg.counter("train.steps") == 5
    m.reset()
    assert reg.counter("serve.submitted") == 0
    assert reg.counter("train.steps") == 5


# ---------------------------------------------------------------------------
# spans + chrome trace
# ---------------------------------------------------------------------------

def test_span_nesting_and_ordering_in_chrome_trace(tmp_path):
    obs_trace.enable()
    obs_trace.reset()
    try:
        obs_trace.set_trace_id("tid-1")
        with obs_trace.span("outer"):
            time.sleep(0.002)
            with obs_trace.span("inner"):
                time.sleep(0.002)
        obs_trace.set_trace_id(None)
        with obs_trace.span("after"):
            pass
        path = obs_trace.export_chrome_trace(str(tmp_path / "t.json"))
    finally:
        obs_trace.disable()
    evs = json.load(open(path))["traceEvents"]
    by = {e["name"]: e for e in evs}
    outer, inner, after = by["outer"], by["inner"], by["after"]
    # containment: inner lies inside outer on the time axis, one deeper
    assert inner["ts"] >= outer["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1
    assert inner["args"]["depth"] == outer["args"]["depth"] + 1
    # ordering: "after" starts after outer ends, back at depth 0
    assert after["ts"] >= outer["ts"] + outer["dur"] - 1
    assert after["args"]["depth"] == outer["args"]["depth"]
    # bound trace id attached while bound, absent after clearing
    assert outer["args"]["trace_id"] == "tid-1"
    assert inner["args"]["trace_id"] == "tid-1"
    assert "trace_id" not in after["args"]
    assert all(e["tid"] == threading.get_ident() for e in evs)


def test_trace_disabled_emits_nothing():
    obs_trace.disable()
    obs_trace.reset()
    with obs_trace.span("x"):
        pass
    obs_trace.complete("y", 1.0)
    obs_trace.async_begin("z", "t1")
    assert obs_trace.events() == []


def test_trace_buffer_is_bounded():
    obs_trace.enable(cap=10)
    obs_trace.reset()
    try:
        for i in range(50):
            with obs_trace.span(f"s{i}"):
                pass
        assert len(obs_trace.events()) == 10
        assert obs_trace.dropped() == 40
    finally:
        obs_trace.disable()


# ---------------------------------------------------------------------------
# trace-id continuity across a serve request's hops
# ---------------------------------------------------------------------------

class _FakePredictor:
    _fns = {}


def _fake_run_outputs(cfg):
    n = cfg.serve.batch_size
    r, C = 4, cfg.num_classes
    return (np.zeros((n, r, C * 4), np.float32),
            np.zeros((n, r, C), np.float32),
            np.zeros((n, C, r), bool))


def test_trace_id_continuity_queue_engine_respond():
    """ONE trace id stamped at admission must appear on the queue-wait
    span (dispatcher thread), the engine batch span, and the respond-hop
    async close — the cross-thread lifecycle the chrome trace shows."""
    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.serve.engine import ServingEngine

    cfg = generate_config(
        "tiny", "synthetic",
        bucket__scale=128, bucket__max_size=160,
        bucket__shapes=((128, 160), (160, 128)),
        serve__batch_size=2, serve__max_delay_ms=5.0)
    obs_trace.enable()
    obs_trace.reset()
    eng = None
    try:
        eng = ServingEngine(_FakePredictor(), cfg, start=False)
        outs = _fake_run_outputs(cfg)
        eng._run = lambda images, im_info: outs  # no model: hops only
        eng.start()
        img = np.zeros((128, 160, 3), np.uint8)
        req = eng.submit(img, timeout_ms=0)
        req.wait(timeout=30.0)
        tid = req.trace_id
        assert tid is not None
        evs = obs_trace.events()
        begin = [e for e in evs if e["ph"] == "b"
                 and e["name"] == "serve.request" and e["id"] == tid]
        qwait = [e for e in evs if e["name"] == "serve.queue_wait"
                 and e["args"].get("trace_id") == tid]
        batch = [e for e in evs if e["name"] == "serve.batch"
                 and tid in (e["args"].get("trace_ids") or [])]
        end = [e for e in evs if e["ph"] == "e"
               and e["name"] == "serve.request" and e["id"] == tid]
        assert begin and qwait and batch and end, (
            f"missing hops for {tid}: b={len(begin)} q={len(qwait)} "
            f"batch={len(batch)} e={len(end)}")
        assert end[0]["args"]["state"] == "served"
        # the hops genuinely crossed threads: admission on this thread,
        # dispatch on the bucket's dispatcher thread
        assert begin[0]["tid"] == threading.get_ident()
        assert batch[0]["tid"] != begin[0]["tid"]
    finally:
        if eng is not None:
            eng.close()
        obs_trace.disable()


def test_shed_request_closes_its_trace_interval():
    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.serve.engine import ServingEngine

    cfg = generate_config(
        "tiny", "synthetic",
        bucket__scale=128, bucket__max_size=160,
        bucket__shapes=((128, 160), (160, 128)),
        serve__queue_depth=2, serve__shed_watermark=1)
    obs_trace.enable()
    obs_trace.reset()
    try:
        eng = ServingEngine(_FakePredictor(), cfg, start=False)
        img = np.zeros((128, 160, 3), np.uint8)
        eng.submit(img, timeout_ms=0)          # fills the watermark
        shed = eng.submit(img, timeout_ms=0)   # shed at admission
        assert shed.state == "shed"
        ends = [e for e in obs_trace.events() if e["ph"] == "e"
                and e["id"] == shed.trace_id]
        assert ends and ends[0]["args"]["state"] == "shed"
        eng.close()
    finally:
        obs_trace.disable()


# ---------------------------------------------------------------------------
# SIGUSR2 profiler window
# ---------------------------------------------------------------------------

def test_sigusr2_window_produces_parseable_rollup(tmp_path):
    """The toggle runs on a worker thread (NEVER jax.profiler inline in
    the handler — that deadlocks a busy process), so effects are polled
    with a deadline."""
    import jax
    import jax.numpy as jnp

    import mx_rcnn_tpu.obs.profiler as prof

    def wait_for(pred, what, timeout=30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return
            time.sleep(0.05)
        pytest.fail(f"timed out waiting for {what}")

    old = signal.getsignal(signal.SIGUSR2)
    try:
        prof.install_sigusr2(str(tmp_path))
        signal.raise_signal(signal.SIGUSR2)  # opens the window (async)
        wait_for(lambda: prof._active_dir is not None, "window open")

        @jax.jit
        def f(x):
            return (jnp.sin(x) @ x).sum()

        x = jnp.ones((96, 96))
        f(x).block_until_ready()
        signal.raise_signal(signal.SIGUSR2)  # closes + rolls up (async)
        rollup_path = tmp_path / "sigusr2-0" / "rollup.json"
        wait_for(rollup_path.exists, "rollup.json")
    finally:
        signal.signal(signal.SIGUSR2, old)
    roll = json.load(open(rollup_path))
    assert any(groups for groups in roll["by_op_class"].values()), roll
    total = sum(ms for groups in roll["by_op_class"].values()
                for ms in groups.values())
    assert total > 0


# ---------------------------------------------------------------------------
# run records + /metrics exporter
# ---------------------------------------------------------------------------

def test_runrec_events_schema_and_bench_summary(tmp_path):
    reg = Registry()
    reg.inc("train.steps", 7)
    rr = RunRecord("train", base_dir=str(tmp_path))
    rr.event("epoch_start", epoch=0)
    rr.event("log", epoch=0, nbatch=2, loss=np.float32(1.5))  # np degrades
    summary = rr.finish(metric="train_samples_per_sec", value=12.5,
                        unit="imgs/s", registry=reg)
    rr.close()
    lines = [json.loads(line) for line in open(rr.events_path)]
    assert len(lines) == 4  # run_start + 2 events + run_finish
    for rec in lines:
        assert isinstance(rec["ts"], float) and isinstance(rec["event"], str)
    assert [r["event"] for r in lines] == ["run_start", "epoch_start",
                                           "log", "run_finish"]
    assert lines[2]["loss"] == 1.5
    disk = json.load(open(rr.summary_path))
    for d in (summary, disk):
        assert d["metric"] == "train_samples_per_sec"
        assert d["value"] == 12.5 and d["measured"] is True
        assert d["metrics"]["counters"]["train.steps"] == 7


def test_metrics_http_scrape(tmp_path):
    reg = Registry()
    reg.inc("train.steps", 3)
    reg.observe("train.step_ms", 20.0)
    reg.set_gauge("loader.queue_depth", 4)
    srv = start_metrics_server(reg, port=0)
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
            snap = json.loads(resp.read())
        assert snap["counters"]["train.steps"] == 3
        assert snap["gauges"]["loader.queue_depth"] == 4
        assert snap["hists"]["train.step_ms"]["count"] == 1
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as resp:
            assert json.loads(resp.read())["ok"] is True
    finally:
        srv.shutdown()
        srv.server_close()


# ---------------------------------------------------------------------------
# Speedometer: registry wiring, stdout byte-identical
# ---------------------------------------------------------------------------

def test_speedometer_stdout_byte_identical_and_registry(monkeypatch):
    import mx_rcnn_tpu.core.fit as fit_mod

    lines = []
    reg = Registry()
    s = fit_mod.Speedometer(batch_size=2, frequent=2, log=lines.append,
                            registry=reg)
    s._tic = 100.0
    monkeypatch.setattr(fit_mod.time, "perf_counter", lambda: 101.0)
    s(3, 40, {"loss": 1.23456, "rpn_acc": 0.875})
    # the exact reference-port format the seed printed (regression pin:
    # the registry wiring must not perturb a byte of it)
    assert lines == ["Epoch[3] Batch [40] Speed: 2.00 samples/sec, "
                     "loss=1.2346, rpn_acc=0.8750"]
    assert reg.gauge("train.samples_per_sec") == pytest.approx(2.0)
    assert reg.gauge("train.metric.loss") == pytest.approx(1.23456)
    # non-log batches print nothing and record nothing new
    s(3, 41, {})
    assert len(lines) == 1


# ---------------------------------------------------------------------------
# disabled-mode overhead
# ---------------------------------------------------------------------------

def test_disabled_mode_overhead_near_zero():
    """The seed fit loop had NO obs code; the delta vs seed is exactly
    the disabled branches left in the hot path: two disabled span()
    calls, two `rec is None` checks and one sentinel-`next` per step.
    Budget: <=1% of the measured tiny step (12.9 ms on this box,
    docs/obs_overhead.json) = 129 µs; asserted with >2x slack at 50 µs
    for a contended box."""
    obs_trace.disable()
    rec = None
    it = iter(range(10_000))
    _END = object()
    n = 0
    t0 = time.perf_counter()
    while True:
        with obs_trace.span("train.data_wait"):
            item = next(it, _END)
        if item is _END:
            break
        if rec is not None:  # pragma: no cover - disabled path
            pass
        with obs_trace.span("train.dispatch"):
            pass
        if rec is not None:  # pragma: no cover
            pass
        n += 1
    per_step = (time.perf_counter() - t0) / n
    assert per_step < 50e-6, f"disabled obs path costs {per_step * 1e6:.1f}µs/step"


def test_recorded_overhead_inside_acceptance_bar():
    """docs/obs_overhead.json is the measured enabled-vs-disabled record
    the acceptance criterion asks for: present, well-formed, <2%."""
    path = os.path.join(REPO, "docs", "obs_overhead.json")
    rec = json.load(open(path))
    assert rec["metric"] == "obs_enabled_step_overhead_pct"
    assert rec["measured"] is True
    assert rec["disabled_step_ms_p50"] > 0
    assert abs(rec["value"]) < 2.0
