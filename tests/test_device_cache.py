"""HBM-resident epoch cache (data/device_cache.py): equivalence with the
streaming path and on-device shuffle coverage."""

import numpy as np
import jax
import pytest
import jax.numpy as jnp

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.train import make_train_step, setup_training
from mx_rcnn_tpu.data.device_cache import (DeviceEpochCache, build_caches,
                                           make_cached_step)
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.tools.profile_step import make_batch


def _tiny_setup(n_batches=3):
    cfg = generate_config("tiny", "synthetic")
    cfg = cfg.replace_in("train", batch_images=1, rpn_pre_nms_top_n=64,
                         rpn_post_nms_top_n=16, batch_rois=8, max_gt_boxes=8,
                         rpn_batch_size=16, rpn_min_size=2)
    model = build_model(cfg)
    key = jax.random.PRNGKey(0)
    batches = [make_batch(cfg, 1, 64, 96, seed=s, raw=True)
               for s in range(n_batches)]
    state, tx = setup_training(model, cfg, key, (1, 64, 96, 3),
                               steps_per_epoch=100)
    return cfg, model, tx, state, key, batches


def test_cached_step_matches_streaming_bitwise():
    """shuffle=False cached steps must reproduce the streaming step
    sequence exactly (same weights after an epoch)."""
    cfg, model, tx, state, key, batches = _tiny_setup()
    base = make_train_step(model, cfg, tx)
    step = jax.jit(base)
    s_stream = state
    for b in batches:
        s_stream, m_stream = step(s_stream, b, key)

    cache = DeviceEpochCache(batches)
    cstep = jax.jit(make_cached_step(base, cache.num_batches, shuffle=False))
    s_cache, idx = state, cache.index_handle()
    for _ in range(len(batches)):
        s_cache, idx, m_cache = cstep(s_cache, cache.data, idx, key)
    assert int(idx) == len(batches)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        s_stream.params, s_cache.params)
    np.testing.assert_array_equal(np.asarray(m_stream["loss"]),
                                  np.asarray(m_cache["loss"]))


def test_cached_step_shuffle_regroups_images_per_epoch():
    """shuffle=True must (a) visit every IMAGE exactly once per epoch and
    (b) re-GROUP images into different batches across epochs — the
    streaming loader's in-bucket semantics (r5; rounds 2-4 froze batch
    composition at staging and only permuted batch order).  Probed by
    running the REAL cached step with a spy base_step that reports the
    gathered images' tags."""
    cfg, _model, _tx, state, key, _ = _tiny_setup(n_batches=0)
    # bi=2: composition only exists with >1 image per batch
    batches = [make_batch(cfg, 2, 64, 96, seed=s, raw=True)
               for s in range(5)]
    # tag every IMAGE with a unique global id via gt_classes
    for i, b in enumerate(batches):
        tags = np.asarray(b.gt_classes).copy()
        tags[0, :] = 2 * i
        tags[1, :] = 2 * i + 1
        batches[i] = b._replace(gt_classes=jnp.asarray(tags))
    cache = DeviceEpochCache(batches)

    def spy(state, batch, key):
        return state, {"tags": batch.gt_classes[:, 0]}

    cstep = jax.jit(make_cached_step(spy, cache.num_batches, shuffle=True))
    epochs = []
    s, idx = state, cache.index_handle()
    for _e in range(3):
        groups = []
        for _p in range(5):
            s, idx, m = cstep(s, cache.data, idx, key)
            groups.append(tuple(sorted(np.asarray(m["tags"]).tolist())))
        # (a) every image exactly once per epoch
        flat = sorted(t for g in groups for t in g)
        assert flat == list(range(10)), flat
        epochs.append(sorted(groups))
    # (b) composition differs across epochs: the sorted multiset of
    # batch groupings cannot be identical for all three epochs
    assert not (epochs[0] == epochs[1] == epochs[2]), epochs
    # and differs from the staged composition itself
    staged = sorted((2 * i, 2 * i + 1) for i in range(5))
    assert any(e != staged for e in epochs), epochs


def test_build_caches_groups_by_bucket_and_budget(tmp_path):
    from mx_rcnn_tpu.data.loader import AnchorLoader
    from mx_rcnn_tpu.data.synthetic import SyntheticDataset

    cfg = generate_config("tiny", "synthetic")
    ds = SyntheticDataset("train", str(tmp_path), "", num_images=6,
                          image_size=(120, 160))
    roidb = ds.gt_roidb()
    loader = AnchorLoader(roidb, cfg, batch_images=2, shuffle=False,
                          num_workers=0)
    caches = build_caches(loader)
    assert sum(c.num_batches for c in caches) == len(loader)
    import pytest

    with pytest.raises(MemoryError):
        build_caches(loader, max_bytes=10)


@pytest.mark.slow
def test_fit_with_device_cache_matches_streaming(tmp_path):
    """fit(device_cache=True) with a shuffle=False loader must produce the
    SAME final weights as the streaming fit (bitwise) — the integration
    contract of the HBM epoch cache with the training driver."""
    from mx_rcnn_tpu.core.fit import fit
    from mx_rcnn_tpu.data.loader import AnchorLoader
    from mx_rcnn_tpu.data.synthetic import SyntheticDataset

    cfg = generate_config("tiny", "synthetic")
    cfg = cfg.replace_in("train", batch_images=2, rpn_pre_nms_top_n=64,
                         rpn_post_nms_top_n=16, batch_rois=8, max_gt_boxes=8,
                         rpn_batch_size=16, rpn_min_size=2)
    ds = SyntheticDataset("train", str(tmp_path), "", num_images=8,
                          image_size=(120, 160))
    roidb = ds.gt_roidb()
    model = build_model(cfg)
    key = jax.random.PRNGKey(0)
    bh, bw = cfg.bucket.shapes[0]

    def train(device_cache):
        loader = AnchorLoader(roidb, cfg, batch_images=2, shuffle=False,
                              num_workers=0)
        state, tx = setup_training(model, cfg, key, (2, bh, bw, 3),
                                   steps_per_epoch=len(loader))
        return fit(model, cfg, state, tx, loader, 2, key,
                   device_cache=device_cache)

    s_stream = train(False)
    s_cached = train(True)
    assert int(s_stream.step) == int(s_cached.step) == 8
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        s_stream.params, s_cached.params)


def test_fit_device_cache_rejects_multibucket(tmp_path):
    from mx_rcnn_tpu.core.fit import fit
    from mx_rcnn_tpu.data.loader import AnchorLoader
    from mx_rcnn_tpu.data.synthetic import SyntheticDataset

    cfg = generate_config("tiny", "synthetic")
    cfg = cfg.replace_in("train", batch_images=1)
    ds = SyntheticDataset("train", str(tmp_path), "", num_images=4,
                          image_size=(120, 160))
    roidb = ds.gt_roidb()
    # mixed orientations → two buckets
    roidb[1]["height"], roidb[1]["width"] = roidb[1]["width"], \
        roidb[1]["height"]
    model = build_model(cfg)
    key = jax.random.PRNGKey(0)
    loader = AnchorLoader(roidb, cfg, batch_images=1, shuffle=False,
                          num_workers=0)
    bh, bw = cfg.bucket.shapes[0]
    state, tx = setup_training(model, cfg, key, (1, bh, bw, 3),
                               steps_per_epoch=4)
    with pytest.raises(ValueError, match="bucket"):
        fit(model, cfg, state, tx, loader, 1, key, device_cache=True)


@pytest.mark.slow
def test_dp_cached_step_matches_dp_streaming(tmp_path):
    """Mesh x device_cache: the sharded-epoch cached step must reproduce
    the streaming DP step bitwise (shuffle off) on the 8-device mesh."""
    from mx_rcnn_tpu.data.device_cache import build_caches
    from mx_rcnn_tpu.data.loader import AnchorLoader
    from mx_rcnn_tpu.data.synthetic import SyntheticDataset
    from mx_rcnn_tpu.parallel.dp import (device_mesh, make_dp_cached_step,
                                         make_dp_train_step, replicate,
                                         shard_batch)

    cfg = generate_config("tiny", "synthetic")
    cfg = cfg.replace_in("train", batch_images=1, rpn_pre_nms_top_n=64,
                         rpn_post_nms_top_n=16, batch_rois=8, max_gt_boxes=8,
                         rpn_batch_size=16, rpn_min_size=2)
    ds = SyntheticDataset("train", str(tmp_path), "", num_images=16,
                          image_size=(120, 160))
    roidb = ds.gt_roidb()
    mesh = device_mesh(8)
    # global batch = 8 devices x 1 image
    loader = AnchorLoader(roidb, cfg, batch_images=8, shuffle=False,
                          num_workers=0)
    model = build_model(cfg)
    key = jax.random.PRNGKey(0)
    bh, bw = cfg.bucket.shapes[0]
    state, tx = setup_training(model, cfg, key, (1, bh, bw, 3),
                               steps_per_epoch=len(loader))

    stream = make_dp_train_step(model, cfg, tx, mesh)
    s_stream = replicate(jax.tree.map(jnp.copy, state), mesh)
    for b in loader:
        s_stream, m_stream = stream(s_stream, shard_batch(b, mesh), key)

    cache = build_caches(loader, mesh=mesh)[0]
    cstep = make_dp_cached_step(model, cfg, tx, mesh, cache.num_batches,
                                shuffle=False)
    s_cache = replicate(state, mesh)
    idx = cache.index_handle()
    for _ in range(cache.num_batches):
        s_cache, idx, m_cache = cstep(s_cache, cache.data, idx, key)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        s_stream.params, s_cache.params)
    np.testing.assert_array_equal(np.asarray(m_stream["loss"]),
                                  np.asarray(m_cache["loss"]))


def test_dp_cached_shuffle_regroups_within_shards():
    """Multi-chip shuffle semantics (r5): under shard_map with the
    P(None, data) epoch layout, the per-epoch image regroup must be
    SHARD-LOCAL — every device sees exactly its own shard's images once
    per epoch (the disclosed residual vs streaming DP: images never
    migrate across devices), deterministically given the replicated
    key."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mx_rcnn_tpu.parallel.dp import data_axes, device_mesh

    cfg, _model, _tx, state, key, _ = _tiny_setup(n_batches=0)
    mesh = device_mesh(2)
    axes = data_axes(mesh)
    nb, bi_global = 3, 4  # bi_local = 2 per device: real regrouping
    batches = [make_batch(cfg, bi_global, 64, 96, seed=s, raw=True)
               for s in range(nb)]
    # tag every image with a unique global id; device d's shard of batch
    # b holds images [d*bi_local, (d+1)*bi_local)
    for i, b in enumerate(batches):
        tags = np.asarray(b.gt_classes).copy()
        for j in range(bi_global):
            tags[j, :] = i * bi_global + j
        batches[i] = b._replace(gt_classes=jnp.asarray(tags))
    cache = DeviceEpochCache(
        batches, device=NamedSharding(mesh, P(None, axes)))

    def spy(state, batch, key):
        return state, {"tags": batch.gt_classes[:, 0]}

    from mx_rcnn_tpu.parallel.dp import shard_map_compat

    cstep = jax.jit(shard_map_compat(
        make_cached_step(spy, nb, shuffle=True),
        mesh=mesh,
        in_specs=(P(), P(None, axes), P(), P()),
        out_specs=(P(), P(), P(axes)),  # concat per-device tags
    ))
    bi_local = bi_global // mesh.size
    shard_of = {}  # device -> its staged image ids
    for d in range(mesh.size):
        shard_of[d] = sorted(b * bi_global + d * bi_local + j
                             for b in range(nb) for j in range(bi_local))
    runs = []
    for _run in range(2):  # determinism across identical runs
        s, idx = state, cache.index_handle()
        seen = {d: [] for d in range(mesh.size)}
        for _p in range(nb):
            s, idx, m = cstep(s, cache.data, idx, key)
            tags = np.asarray(m["tags"])  # (bi_global,) device-major
            for d in range(mesh.size):
                seen[d].extend(tags[d * bi_local:(d + 1) * bi_local]
                               .tolist())
        runs.append({d: list(v) for d, v in seen.items()})
        for d in range(mesh.size):
            assert sorted(seen[d]) == shard_of[d], (d, seen[d])
    assert runs[0] == runs[1]  # replicated key keeps devices in lockstep
