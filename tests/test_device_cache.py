"""HBM-resident epoch cache (data/device_cache.py): equivalence with the
streaming path and on-device shuffle coverage."""

import numpy as np
import jax
import pytest
import jax.numpy as jnp

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.train import make_train_step, setup_training
from mx_rcnn_tpu.data.device_cache import (DeviceEpochCache, build_caches,
                                           make_cached_step)
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.tools.profile_step import make_batch


def _tiny_setup(n_batches=3):
    cfg = generate_config("tiny", "synthetic")
    cfg = cfg.replace_in("train", batch_images=1, rpn_pre_nms_top_n=64,
                         rpn_post_nms_top_n=16, batch_rois=8, max_gt_boxes=8,
                         rpn_batch_size=16, rpn_min_size=2)
    model = build_model(cfg)
    key = jax.random.PRNGKey(0)
    batches = [make_batch(cfg, 1, 64, 96, seed=s, raw=True)
               for s in range(n_batches)]
    state, tx = setup_training(model, cfg, key, (1, 64, 96, 3),
                               steps_per_epoch=100)
    return cfg, model, tx, state, key, batches


def test_cached_step_matches_streaming_bitwise():
    """shuffle=False cached steps must reproduce the streaming step
    sequence exactly (same weights after an epoch)."""
    cfg, model, tx, state, key, batches = _tiny_setup()
    base = make_train_step(model, cfg, tx)
    step = jax.jit(base)
    s_stream = state
    for b in batches:
        s_stream, m_stream = step(s_stream, b, key)

    cache = DeviceEpochCache(batches)
    cstep = jax.jit(make_cached_step(base, cache.num_batches, shuffle=False))
    s_cache, idx = state, cache.index_handle()
    for _ in range(len(batches)):
        s_cache, idx, m_cache = cstep(s_cache, cache.data, idx, key)
    assert int(idx) == len(batches)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        s_stream.params, s_cache.params)
    np.testing.assert_array_equal(np.asarray(m_stream["loss"]),
                                  np.asarray(m_cache["loss"]))


def test_cached_step_shuffle_covers_epoch_and_varies():
    """shuffle=True must visit every batch exactly once per epoch, in an
    order that differs across epochs (for a nontrivial epoch count)."""
    cfg, model, tx, state, key, batches = _tiny_setup(n_batches=5)
    base = make_train_step(model, cfg, tx)
    # spy: record which batch index was gathered by tagging gt_classes
    for i, b in enumerate(batches):
        batches[i] = b._replace(
            gt_classes=np.full_like(np.asarray(b.gt_classes), i))
    cache = DeviceEpochCache(batches)

    def probe(data, idx, key):
        # replicate the gather logic to observe the order
        n = cache.num_batches
        pos = jnp.mod(idx, n)
        epoch = idx // n
        perm = jax.random.permutation(jax.random.fold_in(key, epoch), n)
        return perm[pos]

    orders = []
    for e in range(2):
        order = [int(probe(cache.data, jnp.int32(e * 5 + p), key))
                 for p in range(5)]
        orders.append(order)
        assert sorted(order) == list(range(5)), order
    assert orders[0] != orders[1]


def test_build_caches_groups_by_bucket_and_budget(tmp_path):
    from mx_rcnn_tpu.data.loader import AnchorLoader
    from mx_rcnn_tpu.data.synthetic import SyntheticDataset

    cfg = generate_config("tiny", "synthetic")
    ds = SyntheticDataset("train", str(tmp_path), "", num_images=6,
                          image_size=(120, 160))
    roidb = ds.gt_roidb()
    loader = AnchorLoader(roidb, cfg, batch_images=2, shuffle=False,
                          num_workers=0)
    caches = build_caches(loader)
    assert sum(c.num_batches for c in caches) == len(loader)
    import pytest

    with pytest.raises(MemoryError):
        build_caches(loader, max_bytes=10)


@pytest.mark.slow
def test_fit_with_device_cache_matches_streaming(tmp_path):
    """fit(device_cache=True) with a shuffle=False loader must produce the
    SAME final weights as the streaming fit (bitwise) — the integration
    contract of the HBM epoch cache with the training driver."""
    from mx_rcnn_tpu.core.fit import fit
    from mx_rcnn_tpu.data.loader import AnchorLoader
    from mx_rcnn_tpu.data.synthetic import SyntheticDataset

    cfg = generate_config("tiny", "synthetic")
    cfg = cfg.replace_in("train", batch_images=2, rpn_pre_nms_top_n=64,
                         rpn_post_nms_top_n=16, batch_rois=8, max_gt_boxes=8,
                         rpn_batch_size=16, rpn_min_size=2)
    ds = SyntheticDataset("train", str(tmp_path), "", num_images=8,
                          image_size=(120, 160))
    roidb = ds.gt_roidb()
    model = build_model(cfg)
    key = jax.random.PRNGKey(0)
    bh, bw = cfg.bucket.shapes[0]

    def train(device_cache):
        loader = AnchorLoader(roidb, cfg, batch_images=2, shuffle=False,
                              num_workers=0)
        state, tx = setup_training(model, cfg, key, (2, bh, bw, 3),
                                   steps_per_epoch=len(loader))
        return fit(model, cfg, state, tx, loader, 2, key,
                   device_cache=device_cache)

    s_stream = train(False)
    s_cached = train(True)
    assert int(s_stream.step) == int(s_cached.step) == 8
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        s_stream.params, s_cached.params)


def test_fit_device_cache_rejects_multibucket(tmp_path):
    from mx_rcnn_tpu.core.fit import fit
    from mx_rcnn_tpu.data.loader import AnchorLoader
    from mx_rcnn_tpu.data.synthetic import SyntheticDataset

    cfg = generate_config("tiny", "synthetic")
    cfg = cfg.replace_in("train", batch_images=1)
    ds = SyntheticDataset("train", str(tmp_path), "", num_images=4,
                          image_size=(120, 160))
    roidb = ds.gt_roidb()
    # mixed orientations → two buckets
    roidb[1]["height"], roidb[1]["width"] = roidb[1]["width"], \
        roidb[1]["height"]
    model = build_model(cfg)
    key = jax.random.PRNGKey(0)
    loader = AnchorLoader(roidb, cfg, batch_images=1, shuffle=False,
                          num_workers=0)
    bh, bw = cfg.bucket.shapes[0]
    state, tx = setup_training(model, cfg, key, (1, bh, bw, 3),
                               steps_per_epoch=4)
    with pytest.raises(ValueError, match="bucket"):
        fit(model, cfg, state, tx, loader, 1, key, device_cache=True)


@pytest.mark.slow
def test_dp_cached_step_matches_dp_streaming(tmp_path):
    """Mesh x device_cache: the sharded-epoch cached step must reproduce
    the streaming DP step bitwise (shuffle off) on the 8-device mesh."""
    from mx_rcnn_tpu.data.device_cache import build_caches
    from mx_rcnn_tpu.data.loader import AnchorLoader
    from mx_rcnn_tpu.data.synthetic import SyntheticDataset
    from mx_rcnn_tpu.parallel.dp import (device_mesh, make_dp_cached_step,
                                         make_dp_train_step, replicate,
                                         shard_batch)

    cfg = generate_config("tiny", "synthetic")
    cfg = cfg.replace_in("train", batch_images=1, rpn_pre_nms_top_n=64,
                         rpn_post_nms_top_n=16, batch_rois=8, max_gt_boxes=8,
                         rpn_batch_size=16, rpn_min_size=2)
    ds = SyntheticDataset("train", str(tmp_path), "", num_images=16,
                          image_size=(120, 160))
    roidb = ds.gt_roidb()
    mesh = device_mesh(8)
    # global batch = 8 devices x 1 image
    loader = AnchorLoader(roidb, cfg, batch_images=8, shuffle=False,
                          num_workers=0)
    model = build_model(cfg)
    key = jax.random.PRNGKey(0)
    bh, bw = cfg.bucket.shapes[0]
    state, tx = setup_training(model, cfg, key, (1, bh, bw, 3),
                               steps_per_epoch=len(loader))

    stream = make_dp_train_step(model, cfg, tx, mesh)
    s_stream = replicate(jax.tree.map(jnp.copy, state), mesh)
    for b in loader:
        s_stream, m_stream = stream(s_stream, shard_batch(b, mesh), key)

    cache = build_caches(loader, mesh=mesh)[0]
    cstep = make_dp_cached_step(model, cfg, tx, mesh, cache.num_batches,
                                shuffle=False)
    s_cache = replicate(state, mesh)
    idx = cache.index_handle()
    for _ in range(cache.num_batches):
        s_cache, idx, m_cache = cstep(s_cache, cache.data, idx, key)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        s_stream.params, s_cache.params)
    np.testing.assert_array_equal(np.asarray(m_stream["loss"]),
                                  np.asarray(m_cache["loss"]))
