"""Driver entry points: the multi-chip dryrun at larger scales.

The driver itself runs dryrun_multichip(8); these tests stretch the same
path to 16 virtual devices with the dcn=2 AND dcn=4 hierarchical
decompositions (VERDICT r02 item 8).  Subprocess: the device count is
fixed at backend initialization, so a 16-device run needs a fresh
interpreter.
"""

import os
import subprocess
import sys

import pytest


@pytest.mark.slow
@pytest.mark.gate
def test_dryrun_multichip_16_devices_hierarchical():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "__graft_entry__.py"), "16"],
        env=env, cwd=repo, capture_output=True, text=True, timeout=1200)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "dryrun_multichip(16): OK — step executed" in out.stdout
    assert "(dcn=2, ici=8) hierarchical step matches" in out.stdout
    assert "(dcn=4, ici=4) hierarchical step matches" in out.stdout
    # the inference certification line (VERDICT r04 item 5): eval forward,
    # postprocess, RPN-only and RCNN-only all sharded over the same mesh
    assert "eval sharded over the mesh" in out.stdout
