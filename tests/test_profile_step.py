"""Smoke test for the step profiler tool: every ablation stage must trace,
compile and execute (CPU, tiny network) — the timings themselves are only
meaningful on real hardware, so this asserts structure, not numbers."""

import pytest

pytestmark = pytest.mark.slow

from mx_rcnn_tpu.tools.profile_step import main


def test_profile_step_smoke(capsys):
    main(["--network", "tiny", "--dataset", "synthetic",
          "--shape", "128x160", "--batch_images", "1", "--iters", "2"])
    out = capsys.readouterr().out
    for label in ("backbone fwd", "proposal (decode+topk+NMS)",
                  "anchor_target", "proposal_target", "roi_align",
                  "full loss fwd+bwd (no update)", "optimizer update",
                  "FULL train step (donated)"):
        assert label in out, out
