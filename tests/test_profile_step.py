"""Smoke test for the step profiler tool: every ablation stage must trace,
compile and execute (CPU, tiny network) — the timings themselves are only
meaningful on real hardware, so this asserts structure, not numbers."""

import pytest

pytestmark = pytest.mark.slow

from mx_rcnn_tpu.tools.profile_step import main


def test_profile_step_smoke(capsys):
    main(["--network", "tiny", "--dataset", "synthetic",
          "--shape", "128x160", "--batch_images", "1", "--iters", "2"])
    out = capsys.readouterr().out
    for label in ("backbone fwd", "proposal (decode+topk+NMS)",
                  "anchor_target", "proposal_target", "roi_align",
                  "full loss fwd+bwd (no update)", "optimizer update",
                  "FULL train step (donated)"):
        assert label in out, out


def test_profile_step_check_mode_ab_flags_and_obs_gauges(capsys):
    """The r6 additions in one pass: the A/B arm flags (--roi_backend
    blocked, --nms_mode per_image) trace+run, --check passes its own
    self-test (finite stages, zero timed-pass recompiles, chain
    self-check), and the per-stage gauges land in the process obs
    registry under profile/stage_ms/* (the make perf-smoke contract,
    exercised here on the non-default arms)."""
    from mx_rcnn_tpu.obs.metrics import registry

    registry().reset("profile/")
    main(["--network", "tiny", "--dataset", "synthetic",
          "--shape", "128x160", "--batch_images", "2", "--iters", "2",
          "--check", "--roi_backend", "blocked", "--roi_chunk", "8",
          "--nms_mode", "per_image"])
    out = capsys.readouterr().out
    assert "CHECK OK" in out, out
    assert "backend=blocked" in out, out
    assert "nms=per_image" in out, out
    gauges = registry().snapshot()["gauges"]
    for key in ("profile/stage_ms/backbone_fwd",
                "profile/stage_ms/roi_align",
                "profile/stage_ms/proposal_decode_topk_nms",
                "profile/stage_ms/full_train_step_donated",
                "profile/self_check_ratio"):
        assert key in gauges, sorted(gauges)
        assert gauges[key] == gauges[key]  # not NaN
