"""Fused proposal-op tests (ref mx.symbol.Proposal / rcnn/symbol/proposal.py)."""

import numpy as np
import jax.numpy as jnp

from mx_rcnn_tpu.ops.anchors import generate_shifted_anchors
from mx_rcnn_tpu.ops.proposal import propose, propose_batch


def setup_inputs(h=8, w=8, seed=0):
    anchors = jnp.array(generate_shifted_anchors(h, w, 16))
    n = anchors.shape[0]
    rng = np.random.RandomState(seed)
    scores = jnp.array(rng.uniform(0, 1, (n,)).astype(np.float32))
    deltas = jnp.array((rng.randn(n, 4) * 0.1).astype(np.float32))
    im_info = jnp.array([128.0, 128.0, 1.0])
    return anchors, scores, deltas, im_info


def test_propose_shapes_and_validity():
    anchors, scores, deltas, im_info = setup_inputs()
    rois, rs, valid = propose(scores, deltas, anchors, im_info,
                              pre_nms_top_n=200, post_nms_top_n=50)
    assert rois.shape == (50, 4)
    assert rs.shape == (50,)
    assert bool(valid[0])
    r = np.asarray(rois)
    # clipped to image bounds
    assert (r[:, 0] >= 0).all() and (r[:, 2] <= 127).all()
    assert (r[:, 1] >= 0).all() and (r[:, 3] <= 127).all()


def test_propose_min_size_filter():
    anchors, scores, deltas, im_info = setup_inputs()
    # shrink every box below min_size by predicting a huge negative dw/dh
    deltas = jnp.zeros_like(deltas).at[:, 2:].set(-5.0)
    rois, rs, valid = propose(scores, deltas, anchors, im_info,
                              pre_nms_top_n=200, post_nms_top_n=50, min_size=16)
    assert not bool(np.asarray(valid).any())


def test_propose_scores_sorted_and_nms_applied():
    anchors, scores, deltas, im_info = setup_inputs()
    rois, rs, valid = propose(scores, deltas, anchors, im_info,
                              pre_nms_top_n=576, post_nms_top_n=100,
                              nms_thresh=0.7)
    rs = np.asarray(rs)[np.asarray(valid)]
    assert (np.diff(rs) <= 1e-6).all()  # descending
    # surviving boxes must have pairwise IoU <= 0.7
    from mx_rcnn_tpu.ops.boxes import bbox_overlaps
    r = rois[valid]
    iou = np.array(bbox_overlaps(r, r))  # copy: np.asarray of a jax array is read-only
    np.fill_diagonal(iou, 0)
    assert (iou <= 0.7 + 1e-5).all()


def test_propose_batch_vmap():
    anchors, scores, deltas, im_info = setup_inputs()
    b_scores = jnp.stack([scores, scores * 0.5])
    b_deltas = jnp.stack([deltas, deltas])
    b_info = jnp.stack([im_info, im_info])
    rois, rs, valid = propose_batch(b_scores, b_deltas, anchors, b_info,
                                    pre_nms_top_n=200, post_nms_top_n=30)
    assert rois.shape == (2, 30, 4)
    np.testing.assert_allclose(np.asarray(rois[0]), np.asarray(rois[1]), rtol=1e-5)


def test_propose_batch_batched_nms_decision_exact_vs_vmap():
    """The r6 cross-image batched NMS path must equal the vmap-of-propose
    composition on EVERY output — jitted whole (the production context;
    eager dispatch can differ by 1 ulp in fused decode arithmetic, which
    is a dispatch artifact, not a decision difference)."""
    import jax

    anchors, scores, deltas, im_info = setup_inputs()
    rng = np.random.RandomState(3)
    b = 4
    b_scores = jnp.stack([scores * float(s)
                          for s in rng.uniform(0.2, 1.0, b)])
    b_deltas = jnp.stack([deltas + float(d)
                          for d in rng.uniform(-0.1, 0.1, b)])
    b_info = jnp.tile(im_info[None], (b, 1))
    kw = dict(pre_nms_top_n=200, post_nms_top_n=30, nms_thresh=0.7,
              min_size=4)

    per_image = jax.jit(lambda s, d, i: propose_batch(
        s, d, anchors, i, batched_nms=False, **kw))
    batched = jax.jit(lambda s, d, i: propose_batch(
        s, d, anchors, i, **kw))
    a = per_image(b_scores, b_deltas, b_info)
    g = batched(b_scores, b_deltas, b_info)
    for x, y, name in zip(a, g, ("rois", "scores", "valid")):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=name)
