"""Data layer tests: image transforms, roidb, VOC parsing/eval, loaders."""

import os
import textwrap

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data.image import choose_bucket, resize_keep_ratio
from mx_rcnn_tpu.data.loader import AnchorLoader, TestLoader
from mx_rcnn_tpu.data.pascal_voc import PascalVOC
from mx_rcnn_tpu.data.roidb import IMDB, filter_roidb, merge_roidbs
from mx_rcnn_tpu.data.synthetic import SyntheticDataset
from mx_rcnn_tpu.data.voc_eval import voc_ap, voc_eval


def test_resize_keep_ratio_short_side():
    img = np.zeros((480, 640, 3), np.uint8)
    out, scale = resize_keep_ratio(img, 600, 1000)
    assert min(out.shape[:2]) == 600
    assert abs(scale - 600 / 480) < 1e-6


def test_resize_keep_ratio_long_side_cap():
    img = np.zeros((300, 900, 3), np.uint8)
    out, scale = resize_keep_ratio(img, 600, 1000)
    assert max(out.shape[:2]) <= 1000
    assert abs(scale - 1000 / 900) < 1e-6


def test_choose_bucket_orientation():
    buckets = ((608, 1024), (1024, 608))
    assert choose_bucket(600, 1000, buckets) == (608, 1024)
    assert choose_bucket(1000, 600, buckets) == (1024, 608)


def test_append_flipped_images():
    roidb = [dict(image="x.jpg", height=100, width=200,
                  boxes=np.array([[10.0, 20.0, 50.0, 60.0]], np.float32),
                  gt_classes=np.array([3], np.int32), flipped=False)]
    out = IMDB.append_flipped_images(roidb)
    assert len(out) == 2
    assert out[1]["flipped"] is True
    np.testing.assert_allclose(out[1]["boxes"], [[149.0, 20.0, 189.0, 60.0]])


def test_merge_and_filter_roidb():
    a = [dict(boxes=np.zeros((1, 4)))]
    b = [dict(boxes=np.zeros((0, 4))), dict(boxes=np.zeros((2, 4)))]
    merged = merge_roidbs([a, b])
    assert len(merged) == 3
    assert len(filter_roidb(merged)) == 2


def test_voc_ap_known_curve():
    rec = np.array([0.0, 0.5, 1.0])
    prec = np.array([1.0, 1.0, 1.0])
    assert abs(voc_ap(rec, prec, use_07_metric=True) - 1.0) < 1e-6
    assert abs(voc_ap(rec, prec, use_07_metric=False) - 1.0) < 1e-6


def test_voc_eval_perfect_and_miss():
    gt = {"img1": dict(boxes=np.array([[0.0, 0.0, 10.0, 10.0]]),
                       gt_classes=np.array([1]),
                       difficult=np.zeros(1, bool))}
    perfect = {"img1": np.array([[0.0, 0.0, 10.0, 10.0, 0.9]])}
    assert voc_eval(perfect, gt, 1) > 0.99
    miss = {"img1": np.array([[50.0, 50.0, 60.0, 60.0, 0.9]])}
    assert voc_eval(miss, gt, 1) == 0.0


def _write_fake_voc(root):
    voc = os.path.join(root, "VOCdevkit", "VOC2007")
    os.makedirs(os.path.join(voc, "ImageSets", "Main"), exist_ok=True)
    os.makedirs(os.path.join(voc, "Annotations"), exist_ok=True)
    os.makedirs(os.path.join(voc, "JPEGImages"), exist_ok=True)
    with open(os.path.join(voc, "ImageSets", "Main", "train.txt"), "w") as f:
        f.write("000001\n")
    xml = textwrap.dedent("""\
        <annotation>
          <size><width>353</width><height>500</height><depth>3</depth></size>
          <object><name>dog</name><difficult>0</difficult>
            <bndbox><xmin>48</xmin><ymin>240</ymin><xmax>195</xmax><ymax>371</ymax></bndbox>
          </object>
          <object><name>person</name><difficult>1</difficult>
            <bndbox><xmin>8</xmin><ymin>12</ymin><xmax>352</xmax><ymax>498</ymax></bndbox>
          </object>
        </annotation>""")
    with open(os.path.join(voc, "Annotations", "000001.xml"), "w") as f:
        f.write(xml)
    return os.path.join(root, "VOCdevkit")


def test_pascal_voc_parsing(tmp_path):
    devkit = _write_fake_voc(str(tmp_path))
    ds = PascalVOC("2007_train", str(tmp_path), devkit)
    roidb = ds._load_annotations()
    assert len(roidb) == 1
    rec = roidb[0]
    assert rec["width"] == 353 and rec["height"] == 500
    # difficult object excluded by default; dog = class 12 in VOC order
    assert len(rec["boxes"]) == 1
    assert rec["gt_classes"][0] == ds.classes.index("dog")
    np.testing.assert_allclose(rec["boxes"][0], [47.0, 239.0, 194.0, 370.0])


def test_synthetic_dataset_and_loaders(tmp_path):
    cfg = generate_config("tiny", "PascalVOC")
    cfg = cfg.replace_in("bucket", shapes=((128, 160), (160, 128)),
                         scale=120, max_size=160)
    cfg = cfg.replace_in("train", max_gt_boxes=8)
    ds = SyntheticDataset("train", str(tmp_path), "", num_images=6,
                          image_size=(96, 128))
    roidb = ds.gt_roidb()
    assert len(roidb) == 6
    assert all(os.path.exists(r["image"]) for r in roidb)

    loader = AnchorLoader(roidb, cfg, batch_images=2, shuffle=True)
    batches = list(loader)
    assert len(batches) == 3
    b = batches[0]
    assert b.images.shape[0] == 2
    assert b.images.shape[1:] in ((128, 160, 3), (160, 128, 3))
    assert b.gt_valid.any()
    # gt boxes scaled into resized image extent
    for j in range(2):
        h, w = b.im_info[j, 0], b.im_info[j, 1]
        valid_boxes = b.gt_boxes[j][b.gt_valid[j]]
        assert (valid_boxes[:, 2] <= w - 1 + 1e-3).all()
        assert (valid_boxes[:, 3] <= h - 1 + 1e-3).all()

    tl = TestLoader(roidb, cfg, batch_images=2)
    seen = []
    for batch, indices, scales in tl:
        seen.extend(indices)
        assert batch.images.shape[0] == len(indices) == len(scales)
    assert sorted(seen) == list(range(6))


def test_synthetic_eval_selfconsistent(tmp_path):
    """Feeding the ground truth as detections must give mAP ≈ 1."""
    ds = SyntheticDataset("train", str(tmp_path), "", num_images=4,
                          image_size=(96, 128), num_classes=5)
    all_boxes = [[np.zeros((0, 5), np.float32) for _ in range(4)]
                 for _ in range(ds.num_classes)]
    for i, spec in enumerate(ds._specs):
        for box, cls in zip(spec["boxes"], spec["gt_classes"]):
            det = np.concatenate([box, [0.99]]).astype(np.float32)[None]
            all_boxes[int(cls)][i] = np.vstack([all_boxes[int(cls)][i], det])
    res = ds.evaluate_detections(all_boxes)
    assert res["mAP"] > 0.95, res


def test_prefetch_loader_identical_batches(tmp_path):
    """Prefetched iteration must yield batches identical (content and
    order) to the synchronous path — thread-pool assembly is an overlap
    optimization, never a semantics change."""
    cfg = generate_config("tiny", "PascalVOC")
    cfg = cfg.replace_in("bucket", shapes=((128, 160), (160, 128)),
                         scale=120, max_size=160)
    cfg = cfg.replace_in("train", max_gt_boxes=8)
    ds = SyntheticDataset("train", str(tmp_path), "", num_images=10,
                          image_size=(96, 128))
    roidb = ds.gt_roidb()

    sync = AnchorLoader(roidb, cfg, batch_images=2, shuffle=True, seed=3,
                        num_workers=0)
    pre = AnchorLoader(roidb, cfg, batch_images=2, shuffle=True, seed=3,
                       num_workers=3, prefetch=4)
    sync.set_epoch(1)
    pre.set_epoch(1)
    got_s, got_p = list(sync), list(pre)
    assert len(got_s) == len(got_p) > 0
    for bs, bp in zip(got_s, got_p):
        for fs, fp in zip(bs, bp):
            np.testing.assert_array_equal(np.asarray(fs), np.asarray(fp))

    tls = TestLoader(roidb, cfg, batch_images=3, num_workers=0)
    tlp = TestLoader(roidb, cfg, batch_images=3, num_workers=3, prefetch=2)
    for (b1, i1, s1), (b2, i2, s2) in zip(tls, tlp):
        assert i1 == i2
        np.testing.assert_array_equal(s1, s2)
        np.testing.assert_array_equal(b1.images, b2.images)


def test_skip_next_batches(tmp_path):
    """skip_next_batches trims the next iteration's batch order (preemption
    resume) without touching later epochs."""
    cfg = generate_config("tiny", "PascalVOC")
    cfg = cfg.replace_in("bucket", shapes=((128, 160), (160, 128)),
                         scale=120, max_size=160)
    cfg = cfg.replace_in("train", max_gt_boxes=8)
    ds = SyntheticDataset("train", str(tmp_path), "", num_images=12,
                          image_size=(96, 128))
    roidb = ds.gt_roidb()
    ref = AnchorLoader(roidb, cfg, batch_images=2, shuffle=True, seed=5,
                       num_workers=0)
    cut = AnchorLoader(roidb, cfg, batch_images=2, shuffle=True, seed=5,
                       num_workers=0)
    ref.set_epoch(2)
    cut.set_epoch(2)
    full = list(ref)
    cut.skip_next_batches(2)
    tail = list(cut)
    assert len(tail) == len(full) - 2
    for bs, bp in zip(full[2:], tail):
        np.testing.assert_array_equal(bs.images, bp.images)
    # skip applies ONCE: the following epoch is complete again
    ref.set_epoch(3)
    cut.set_epoch(3)
    assert len(list(cut)) == len(list(ref))


def _mini_roidb(tmp_path, n=4):
    ds = SyntheticDataset("train", str(tmp_path), "", num_images=n,
                          image_size=(120, 160))
    return ds.gt_roidb()


def test_raw_loader_bitexact_vs_host_normalized(tmp_path):
    """The uint8 raw path + device normalization must reproduce the host
    fp32 mean-subtract path BITWISE (ops/normalize.py contract)."""
    import jax.numpy as jnp

    from mx_rcnn_tpu.ops.normalize import normalize_images

    cfg = generate_config("tiny", "synthetic")
    roidb = _mini_roidb(tmp_path)
    host = AnchorLoader(roidb, cfg, batch_images=2, shuffle=False,
                        num_workers=0, raw_images=False)
    raw = AnchorLoader(roidb, cfg, batch_images=2, shuffle=False,
                       num_workers=0, raw_images=True)
    for bh, br in zip(host, raw):
        assert br.images.dtype == np.uint8
        assert bh.images.dtype == np.float32
        np.testing.assert_array_equal(bh.im_info, br.im_info)
        normed = np.asarray(normalize_images(
            jnp.asarray(br.images), jnp.asarray(br.im_info),
            cfg.network.pixel_means))
        np.testing.assert_array_equal(normed, bh.images)


def test_normalize_passthrough_and_uint8_guard():
    import jax.numpy as jnp
    import pytest as _pytest

    from mx_rcnn_tpu.ops.normalize import normalize_images

    x = jnp.ones((1, 4, 4, 3), jnp.float32)
    assert normalize_images(x, None, (1.0, 2.0, 3.0)) is x
    with _pytest.raises(ValueError):
        normalize_images(x.astype(jnp.uint8), None, (1.0, 2.0, 3.0))


def test_decoded_image_cache_ram_and_disk(tmp_path):
    from mx_rcnn_tpu.data.cache import DecodedImageCache, plan_scale
    from mx_rcnn_tpu.data.image import load_resized_uint8

    cfg = generate_config("tiny", "synthetic")
    roidb = _mini_roidb(tmp_path)
    bucket = cfg.bucket.shapes[0]
    sc, ms = cfg.bucket.scale, cfg.bucket.max_size

    cache = DecodedImageCache(ram_bytes=1 << 30,
                              cache_dir=str(tmp_path / "imgcache"))
    rec = roidb[0]
    direct, direct_scale = load_resized_uint8(rec["image"], False, sc, ms,
                                              bucket)
    got = cache.load(rec["image"], False, sc, ms, bucket)
    np.testing.assert_array_equal(got, direct)
    assert cache.misses == 1 and cache.hits == 0
    # RAM hit
    got2 = cache.load(rec["image"], False, sc, ms, bucket)
    np.testing.assert_array_equal(got2, direct)
    assert cache.hits == 1
    # disk tier: a fresh cache instance over the same dir must hit disk
    cache2 = DecodedImageCache(ram_bytes=0,
                               cache_dir=str(tmp_path / "imgcache"))
    got3 = cache2.load(rec["image"], False, sc, ms, bucket)
    np.testing.assert_array_equal(got3, direct)
    assert cache2.hits == 1 and cache2.misses == 0
    # plan_scale matches the decode path's scale exactly
    assert plan_scale(rec["height"], rec["width"], sc, ms, bucket) \
        == direct_scale
    # flipped variant gets its own key
    flipped = cache.load(rec["image"], True, sc, ms, bucket)
    assert (flipped != got).any()


def test_plan_scale_matches_decode_over_random_geometries(tmp_path):
    """plan_scale must equal the im_scale the REAL decode path returns for
    randomized (h, w, scale, max_size, bucket) combos — actually decoding
    an image each time, so any future edit to load_resized_uint8's resize
    arithmetic that desyncs the cached scale fails here (advisor r3)."""
    from PIL import Image

    from mx_rcnn_tpu.data.cache import plan_scale
    from mx_rcnn_tpu.data.image import load_resized_uint8

    rng = np.random.RandomState(0)
    for i in range(25):
        h = int(rng.randint(40, 500))
        w = int(rng.randint(40, 500))
        scale = int(rng.choice([120, 240, 400]))
        max_size = int(rng.choice([200, 320, 640]))
        bucket = (int(rng.choice([128, 256, 416])),
                  int(rng.choice([128, 256, 416])))
        flipped = bool(rng.randint(2))
        p = tmp_path / f"g{i}.png"
        Image.fromarray(rng.randint(0, 255, (h, w, 3), dtype=np.uint8)
                        ).save(p)
        img, s_decode = load_resized_uint8(str(p), flipped, scale, max_size,
                                           bucket)
        s_plan = plan_scale(h, w, scale, max_size, bucket)
        assert s_plan == s_decode, (h, w, scale, max_size, bucket, flipped)
        # and the decoded image always fits the bucket
        assert img.shape[0] <= bucket[0] and img.shape[1] <= bucket[1]


def test_torn_disk_cache_falls_through_to_decode(tmp_path):
    """The ISSUE-12 triage decision behind cache.py's PL102/PL103
    waiver: the decoded-image cache commits via os.replace WITHOUT an
    fsync because it is rebuildable, not durable state — a crash-torn
    (truncated) or zero-length .npy must fail np.load's own validation,
    fall through to a fresh decode, and be overwritten with a good
    entry.  If this stops holding, the waiver (and the fsync-free
    commit) must go."""
    from PIL import Image

    from mx_rcnn_tpu.data.cache import DecodedImageCache

    p = tmp_path / "img.png"
    Image.fromarray(np.full((40, 60, 3), 77, np.uint8)).save(p)
    cache = DecodedImageCache(ram_bytes=0, cache_dir=str(tmp_path / "c"))
    good = cache.load(str(p), False, 32, 64, (32, 64))
    assert cache.misses == 1
    import glob as _glob
    (entry,) = _glob.glob(str(tmp_path / "c" / "*.npy"))
    full = open(entry, "rb").read()
    for torn in (full[: len(full) // 2], b""):
        with open(entry, "wb") as f:   # simulate the crash state
            f.write(torn)
        fresh = DecodedImageCache(ram_bytes=0,
                                  cache_dir=str(tmp_path / "c"))
        got = fresh.load(str(p), False, 32, 64, (32, 64))
        np.testing.assert_array_equal(got, good)
        assert fresh.misses == 1, "torn entry must MISS, not serve"
        # and the re-decode repaired the on-disk entry
        assert open(entry, "rb").read() == full


def test_cache_invalidates_on_source_file_change(tmp_path):
    """Replacing a source image must invalidate its disk-cache entry
    (advisor r3: the key previously hashed only path + geometry)."""
    from PIL import Image

    from mx_rcnn_tpu.data.cache import DecodedImageCache

    p = tmp_path / "img.png"
    a = np.full((40, 60, 3), 10, np.uint8)
    Image.fromarray(a).save(p)
    cache = DecodedImageCache(ram_bytes=0, cache_dir=str(tmp_path / "c"))
    got = cache.load(str(p), False, 32, 64, (32, 64))
    assert got.mean() > 5
    # replace the file with different pixels (force a distinct mtime_ns)
    b = np.full((40, 60, 3), 200, np.uint8)
    Image.fromarray(b).save(p)
    os.utime(p, ns=(1, 1))
    got2 = cache.load(str(p), False, 32, 64, (32, 64))
    assert got2.mean() > 100, "stale cache entry served after file change"
    assert cache.misses == 2
    # the superseded on-disk version was evicted, not orphaned
    import glob as _glob
    assert len(_glob.glob(str(tmp_path / "c" / "*.npy"))) == 1
    # a pre-versioning legacy file (digest-stem.npy, no version segment)
    # is also swept when its entry is rewritten
    cur = _glob.glob(str(tmp_path / "c" / "*.npy"))[0]
    stable = os.path.basename(cur).rsplit(".", 2)[0]
    legacy = tmp_path / "c" / (stable + ".npy")
    legacy.write_bytes(b"old-format")
    os.utime(p, ns=(2, 2))  # force yet another version
    cache.load(str(p), False, 32, 64, (32, 64))
    assert not legacy.exists(), "legacy versionless entry not evicted"
    assert len(_glob.glob(str(tmp_path / "c" / "*.npy"))) == 1


def test_decode_pool_from_config():
    from mx_rcnn_tpu.data import decode_pool_from_config

    cfg = generate_config("tiny", "synthetic")
    assert decode_pool_from_config(cfg) is None  # default: in-thread
    pool = decode_pool_from_config(
        generate_config("tiny", "synthetic", default__decode_procs=1))
    try:
        assert pool is not None and pool.num_procs == 1
    finally:
        pool.close()


@pytest.mark.slow
def test_decode_pool_identical_batches(tmp_path):
    """A DecodePool-backed loader must yield batches identical to the
    in-thread loader (pixels AND im_info), and the pool must be spawn-safe
    (workers never import JAX).  Slow tier: spawning interpreters costs
    seconds."""
    from mx_rcnn_tpu.data.decode_pool import DecodePool
    from mx_rcnn_tpu.data.roidb import IMDB

    cfg = generate_config("tiny", "synthetic")
    roidb = IMDB.append_flipped_images(_mini_roidb(tmp_path))
    plain = AnchorLoader(roidb, cfg, batch_images=2, shuffle=True, seed=7,
                         num_workers=0)
    with DecodePool(2, cache_dir=str(tmp_path / "pc")) as pool:
        pooled = AnchorLoader(roidb, cfg, batch_images=2, shuffle=True,
                              seed=7, num_workers=2, decode_pool=pool)
        for bp, bc in zip(plain, pooled):
            np.testing.assert_array_equal(bp.images, bc.images)
            np.testing.assert_array_equal(bp.im_info, bc.im_info)
            np.testing.assert_array_equal(bp.gt_boxes, bc.gt_boxes)
        # second epoch rides the shared disk cache written by the workers
        for bp, bc in zip(plain, pooled):
            np.testing.assert_array_equal(bp.images, bc.images)
    with pytest.raises(ValueError):
        DecodePool(0)


def test_cached_loader_identical_batches(tmp_path):
    """A cache-backed loader must yield batches identical to the direct
    loader, epoch after epoch (including flip keys)."""
    from mx_rcnn_tpu.data.cache import DecodedImageCache
    from mx_rcnn_tpu.data.roidb import IMDB

    cfg = generate_config("tiny", "synthetic")
    roidb = IMDB.append_flipped_images(_mini_roidb(tmp_path))
    cache = DecodedImageCache(ram_bytes=1 << 30)
    plain = AnchorLoader(roidb, cfg, batch_images=2, shuffle=True, seed=7,
                         num_workers=0)
    cached = AnchorLoader(roidb, cfg, batch_images=2, shuffle=True, seed=7,
                          num_workers=0, cache=cache)
    for _ in range(2):  # second epoch runs fully from cache
        for bp, bc in zip(plain, cached):
            np.testing.assert_array_equal(bp.images, bc.images)
            np.testing.assert_array_equal(bp.im_info, bc.im_info)
            np.testing.assert_array_equal(bp.gt_boxes, bc.gt_boxes)
    assert cache.hits > 0


def test_ram_cache_eviction_budget():
    from mx_rcnn_tpu.data.cache import DecodedImageCache

    c = DecodedImageCache(ram_bytes=100)
    a = np.zeros((5, 8, 3), np.uint8)  # 120 bytes > budget: never stored
    c._ram_put("a", a)
    assert c._ram_used == 0
    b = np.zeros((4, 4, 3), np.uint8)  # 48 bytes
    c._ram_put("b", b)
    c._ram_put("c", b.copy())
    assert c._ram_used == 96
    c._ram_put("d", b.copy())  # evicts the LRU entry ("b")
    assert c._ram_used == 96 and "b" not in c._ram and "d" in c._ram


def test_config_from_args_set_overrides():
    """--set section__field=value parses literals and rejects bad keys."""
    import argparse

    from mx_rcnn_tpu.tools.train import config_from_args

    ns = argparse.Namespace(network="tiny", dataset="synthetic",
                            set=["train__rpn_pre_nms_top_n=6000",
                                 "bucket__scale=600",
                                 "default__prefix=model/x"])
    cfg = config_from_args(ns)
    assert cfg.train.rpn_pre_nms_top_n == 6000
    assert cfg.bucket.scale == 600
    assert cfg.default.prefix == "model/x"  # literal_eval fallback → str
    with pytest.raises(ValueError, match="section__field"):
        config_from_args(argparse.Namespace(
            network="tiny", dataset="synthetic", set=["badkey"]))


def test_set_override_type_coercion():
    """--set values coerce to the field's declared type; bad types are
    rejected loudly (the string 'false' must never become a truthy flag)."""
    from mx_rcnn_tpu.config import generate_config

    cfg = generate_config("tiny", "synthetic", train__shuffle="false")
    assert cfg.train.shuffle is False
    cfg = generate_config("tiny", "synthetic", train__shuffle="True")
    assert cfg.train.shuffle is True
    cfg = generate_config("tiny", "synthetic", default__e2e_lr="0.01")
    assert cfg.default.e2e_lr == 0.01
    cfg = generate_config("tiny", "synthetic",
                          bucket__shapes=[[320, 416]])
    assert cfg.bucket.shapes == ((320, 416),)  # deep tuple conversion
    with pytest.raises(TypeError, match="expects a float"):
        generate_config("tiny", "synthetic", default__e2e_lr=True)
    with pytest.raises(TypeError, match="expects a bool"):
        generate_config("tiny", "synthetic", train__shuffle="maybe")
    with pytest.raises(TypeError, match="expects an int"):
        generate_config("tiny", "synthetic", train__batch_images="two")
    with pytest.raises(TypeError, match="expects an int"):
        generate_config("tiny", "synthetic", train__batch_images=1.5)


def test_coerce_override_none_current_uses_annotation():
    """A known field whose CURRENT value is None must still coerce/reject
    by its declared (resolved) type (advisor r3: None used to skip all
    type checks); unknown fields (no annotation) still pass through."""
    from typing import Optional, Tuple, Union

    from mx_rcnn_tpu.config import _coerce_override

    assert _coerce_override(None, "false", "s__f", bool) is False
    assert _coerce_override(None, "7", "s__f", int) == 7
    assert _coerce_override(None, "0.5", "s__f", float) == 0.5
    assert _coerce_override(None, [[1, 2]], "s__f",
                            Tuple[Tuple[int, int], ...]) == ((1, 2),)
    assert _coerce_override(None, "x", "s__f", Optional[str]) == "x"
    # every Optional/Union spelling resolves to the same union form
    assert _coerce_override(None, "3", "s__f", Optional[int]) == 3
    assert _coerce_override(None, "3", "s__f", Union[int, None]) == 3
    assert _coerce_override(None, "3", "s__f", eval("int | None")) == 3
    with pytest.raises(TypeError, match="expects an int"):
        _coerce_override(None, "two", "s__f", Optional[int])
    with pytest.raises(TypeError, match="expects a bool"):
        _coerce_override(None, "maybe", "s__f", bool)
    # genuinely multi-typed union: stored as-is (no exemplar)
    assert _coerce_override(None, "raw", "s__f", Union[int, str]) == "raw"
    # unknown field: passes through so replace_in raises its own error
    assert _coerce_override(None, "raw", "s__f", None) == "raw"
    # None value always passes through (meaning "unset")
    assert _coerce_override(None, None, "s__f", int) is None


def test_test_cli_consumes_set_overrides(tmp_path, monkeypatch):
    """tools/test.py must actually APPLY --set overrides (regression: the
    flag was once registered but ignored)."""
    from mx_rcnn_tpu.tools import test as test_tool

    seen = {}

    def fake_test_rcnn(cfg, **kw):
        seen["thresh"] = cfg.test.score_thresh
        return {}

    monkeypatch.setattr(test_tool, "test_rcnn", fake_test_rcnn)
    test_tool.main(["--network", "tiny", "--dataset", "synthetic",
                    "--epoch", "1", "--set", "test__score_thresh=0.25"])
    assert seen["thresh"] == 0.25


def test_decode_pool_small_cache_budget_clamped(monkeypatch, caplog):
    """image_cache_mb < decode_procs used to floor the per-worker RAM
    share to 0, silently disabling the cache the config asked for
    (ADVICE r5): now it clamps to 1 MB and says so."""
    import logging

    from mx_rcnn_tpu.data import loader as loader_mod

    built = {}

    class FakePool:
        def __init__(self, procs, cache_dir=None, ram_bytes=None):
            built.update(procs=procs, cache_dir=cache_dir,
                         ram_bytes=ram_bytes)

    monkeypatch.setattr("mx_rcnn_tpu.data.decode_pool.DecodePool", FakePool)
    cfg = generate_config("tiny", "synthetic", default__decode_procs=8,
                          default__image_cache_mb=4)
    with caplog.at_level(logging.WARNING, logger="mx_rcnn_tpu"):
        loader_mod.decode_pool_from_config(cfg)
    assert built["ram_bytes"] == 1 << 20
    assert "cache budget 4 MB" in caplog.text
    assert "decode_procs=8" in caplog.text
    # a healthy budget still splits undisturbed, without the warning
    built.clear()
    caplog.clear()
    cfg = generate_config("tiny", "synthetic", default__decode_procs=4,
                          default__image_cache_mb=12)
    with caplog.at_level(logging.WARNING, logger="mx_rcnn_tpu"):
        loader_mod.decode_pool_from_config(cfg)
    assert built["ram_bytes"] == 3 << 20
    assert "clamping" not in caplog.text
