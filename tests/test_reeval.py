"""tools/reeval.py: re-score saved detections (ref rcnn/tools/reeval.py)."""

import pickle

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data.synthetic import SyntheticDataset
from mx_rcnn_tpu.tools.reeval import reeval


def _perfect_dets(ds):
    num_images = ds.num_images
    all_boxes = [[np.zeros((0, 5), np.float32) for _ in range(num_images)]
                 for _ in range(ds.num_classes)]
    for i, spec in enumerate(ds._specs):
        for box, c in zip(spec["boxes"], spec["gt_classes"]):
            det = np.concatenate([box, [0.99]]).astype(np.float32)
            all_boxes[int(c)][i] = np.vstack([all_boxes[int(c)][i], det])
    return all_boxes


def test_reeval_roundtrip(tmp_path):
    cfg = generate_config("tiny", "synthetic")
    cfg = cfg.replace_in("dataset", root_path=str(tmp_path))
    ds = SyntheticDataset("test", str(tmp_path), "", num_images=8,
                          num_classes=cfg.dataset.num_classes)
    dets = tmp_path / "dets.pkl"
    with open(dets, "wb") as f:
        pickle.dump({"all_boxes": _perfect_dets(ds),
                     "classes": ds.classes}, f)
    results = reeval(cfg, str(dets), dataset_kw={"num_images": 8})
    assert results["mAP"] > 0.99


def test_reeval_rejects_wrong_classes(tmp_path):
    cfg = generate_config("tiny", "synthetic")
    cfg = cfg.replace_in("dataset", root_path=str(tmp_path))
    ds = SyntheticDataset("test", str(tmp_path), "", num_images=8,
                          num_classes=cfg.dataset.num_classes)
    dets = tmp_path / "dets.pkl"
    with open(dets, "wb") as f:
        pickle.dump({"all_boxes": _perfect_dets(ds),
                     "classes": ["__background__", "cat"]}, f)
    with pytest.raises(ValueError, match="classes"):
        reeval(cfg, str(dets), dataset_kw={"num_images": 8})
