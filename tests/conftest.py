"""Test harness configuration.

All tests run on CPU with 8 virtual XLA devices — the TPU-native answer to
"test multi-chip without a cluster" (SURVEY.md §4): sharding/collective
code is exercised on a real 8-device mesh, just a slow one.

Must set the env vars before the first ``import jax`` anywhere.
"""

import os

# Overwrite, not setdefault: the machine env pins JAX_PLATFORMS=axon (the
# single real TPU chip) via a sitecustomize hook that caches the platform at
# interpreter startup, so the env var alone is ignored — the jax.config
# update below is what actually forces CPU.  Unit tests must run on the
# virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the slow tier is compile-dominated
# (training-loop tests re-jit the same tiny programs every run), so a
# warm cache cuts repeat `make test-all` wall-clock several-fold — the
# re-runnability VERDICT r04 item 8 asks for.  Safe to share across runs:
# entries key on the full HLO + flags; delete the dir to force cold.
_cache_dir = os.environ.get("MXRCNN_TEST_JAX_CACHE",
                            "/tmp/mxrcnn_jax_test_cache")
try:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    # subprocess tests (stage CLIs, supervisor children, graft dryruns)
    # start fresh interpreters that never read this conftest — the env
    # vars route them to the same cache WITH the same thresholds (the
    # dir alone would leave children at jax's 1.0 s min-compile-time
    # default and skip exactly the tiny programs this suite compiles).
    # Exception: the jax.distributed multihost workers strip the cache
    # dir (tools/multihost_demo.py — cache-hit ranks racing compile-miss
    # ranks deadlocked the collective-init barrier).
    os.environ["JAX_COMPILATION_CACHE_DIR"] = _cache_dir
    os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0.5"
    os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "0"
except Exception:  # older jax without the knobs: cold compiles only
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(0)


def shrink_tiny_cfg(cfg):
    """Shared miniature-e2e hyperparameters for the tiny network on a
    128x160 canvas (used by test_fit_e2e and test_e2e_formats — keep the
    two e2e suites on one tuning)."""
    cfg = cfg.replace_in("train", rpn_pre_nms_top_n=1024,
                         rpn_post_nms_top_n=300, batch_rois=128,
                         max_gt_boxes=8, flip=False)
    cfg = cfg.replace_in("test", rpn_pre_nms_top_n=1024,
                         rpn_post_nms_top_n=100)
    cfg = cfg.replace_in("bucket", scale=128, max_size=160,
                         shapes=((128, 160), (160, 128)))
    return cfg


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: training-loop / subprocess / e2e tests excluded from the "
        "quick tier (run with `make test-all` or `-m slow`)")
    config.addinivalue_line(
        "markers",
        "gate: the two multi-minute end-metric gates (30-epoch gauntlet "
        "seed-0 train-from-scratch, 16-device hierarchical dryrun) — "
        "excluded from `make test-all` so the full tier stays "
        "independently re-runnable in ~15 min on one core; run with "
        "`make test-gate`")
