"""Rollout-plane tests (ISSUE 18, docs/SERVING.md "Rollout tier").

Pins the pieces the live smoke (``tools/rollout.py``) measures:

* :func:`paired_stats` — the equivalence judgment the online canary
  gate SHARES with ``tools/gauntlet.py paired_compare`` (CI-inside-
  ±budget, exact sign test, n<2 never judges);
* lineage admission — unknown-parent / unrooted / fingerprint-mismatch
  refusals and the legacy version-less back-compat rule;
* the bundled-weights round trip, including LEAF-LESS subtrees (a
  BN-free model's empty ``batch_stats`` must survive the npz flatten —
  the calling-convention regression the first live swap hit);
* the router's deterministic canary lane and per-version exactly-once
  accounting published into the scrape-visible registry;
* :class:`RolloutController` over a fake port: happy path, gate-refusal
  auto-rollback, rollback idempotence (including rolling back a fleet
  that COMPLETED the swap), kill-mid-rollout deferral with FINALIZE
  re-convergence and the bounded abandon grace;
* the sim's canary_rollout scenario end to end in virtual time:
  byte-reproducible decision log, shipped arm lands v2, red-team arm is
  refused and rolled back.
"""

import math
from types import SimpleNamespace

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.obs.health import CRITICAL
from mx_rcnn_tpu.obs.metrics import Registry
from mx_rcnn_tpu.serve.export import (ExportMismatch, ExportStore,
                                      variables_fingerprint)
from mx_rcnn_tpu.serve.fleet import FleetRequest, FleetRouter
from mx_rcnn_tpu.serve.rollout import (DONE, ROLLED_BACK, ROLLING_BACK,
                                       OnlinePairedGate,
                                       RolloutController, detection_score,
                                       paired_stats, rollout_rules,
                                       version_label)


# ---------------------------------------------------------------------------
# paired_stats — the shared gate/gauntlet judgment
# ---------------------------------------------------------------------------

class TestPairedStats:
    def test_one_delta_proves_nothing(self):
        for deltas in ([], [0.0]):
            st = paired_stats(deltas, budget=0.02)
            assert st["ci95"] is None
            assert st["within_budget"] is False

    def test_zero_deltas_pass_equivalence(self):
        st = paired_stats([0.0] * 8, budget=0.02)
        assert st["mean_delta"] == 0.0
        assert st["ci95"] == [0.0, 0.0]
        assert st["within_budget"] is True
        assert st["sign_test_p"] == 1.0  # zeros dropped, no evidence

    def test_damaged_arm_fails_equivalence_and_sign_test(self):
        st = paired_stats([-0.7, -0.75, -0.8, -0.72, -0.78], budget=0.02)
        assert st["within_budget"] is False
        assert st["mean_delta"] < -0.5
        # exact one-sided-extreme binomial: 2 * (1/2)^5
        assert st["sign_test_p"] == pytest.approx(2 * 0.5 ** 5)

    def test_ci_is_students_t_by_hand(self):
        deltas = [0.01, -0.01, 0.02, 0.0]
        st = paired_stats(deltas, budget=0.05)
        mean = float(np.mean(deltas))
        sem = float(np.std(deltas, ddof=1)) / math.sqrt(4)
        lo, hi = mean - 3.182 * sem, mean + 3.182 * sem  # t.975 df=3
        assert st["ci95"] == [round(lo, 4), round(hi, 4)]
        assert st["within_budget"] == (-0.05 <= lo and hi <= 0.05)

    def test_wide_ci_not_within_budget_even_with_zero_mean(self):
        # equivalence is CI-inside-bounds, NOT failure-to-reject: a
        # noisy symmetric sample with mean 0 must NOT pass
        st = paired_stats([0.5, -0.5, 0.4, -0.4], budget=0.02)
        assert abs(st["mean_delta"]) < 0.01
        assert st["within_budget"] is False


class TestDetectionScore:
    def test_identical_arms_score_identically(self):
        dets = {"cat": np.array([[0, 0, 10, 10, 0.9],
                                 [1, 1, 5, 5, 0.8]])}
        assert detection_score(dets) == detection_score(dict(dets))

    def test_empty_scores_zero(self):
        assert detection_score({}) == 0.0
        assert detection_score({"cat": np.zeros((0, 5))}) == 0.0

    def test_confidence_collapse_lowers_score(self):
        strong = {"c": np.array([[0, 0, 9, 9, 0.95]])}
        weak = {"c": np.array([[0, 0, 9, 9, 0.05]])}
        assert detection_score(weak) < detection_score(strong)

    def test_junk_box_explosion_lowers_score(self):
        one = {"c": np.array([[0, 0, 9, 9, 0.9]])}
        junk = {"c": np.tile([0, 0, 9, 9, 0.1], (50, 1))}
        # a broken NMS floods low-confidence boxes: total confidence
        # grows slower than the (1+count) normalizer, so the score
        # drops below the single clean detection
        assert detection_score(junk) < detection_score(one)


class TestOnlinePairedGate:
    def test_not_judged_below_min_pairs(self):
        gate = OnlinePairedGate(budget=0.02, min_pairs=4)
        for _ in range(3):
            gate.add_pair(0.8, 0.8)
        v = gate.verdict()
        assert not v["judged"] and not v["refused"]

    def test_healthy_canary_passes(self):
        gate = OnlinePairedGate(budget=0.02, min_pairs=4)
        for _ in range(4):
            gate.add_pair(0.8, 0.8)
        v = gate.verdict()
        assert v["judged"] and not v["refused"]

    def test_damaged_canary_refused(self):
        gate = OnlinePairedGate(budget=0.02, min_pairs=4)
        for _ in range(4):
            gate.add_pair(0.8, 0.05)  # delta = canary - base < 0
        v = gate.verdict()
        assert v["judged"] and v["refused"]
        assert v["mean_delta"] == pytest.approx(-0.75)


class TestVersionLabel:
    def test_base_for_versionless(self):
        assert version_label(None) == "base"
        assert version_label("") == "base"

    def test_metric_unsafe_chars_sanitized(self):
        assert version_label("v2@candidate/1") == "v2_candidate_1"

    def test_rules_reference_labelled_series(self):
        cfg = generate_config("tiny", "synthetic")
        rules = rollout_rules(cfg, "v2")
        metrics = " ".join(r.metric for r in rules)
        assert "fleet.ver.v2.total_ms" in metrics
        assert "fleet.ver.v2.failed/fleet.ver.v2.dispatched" in metrics
        assert all(r.severity == CRITICAL for r in rules)


# ---------------------------------------------------------------------------
# lineage admission + the bundled-weights round trip
# ---------------------------------------------------------------------------

def _store_with_manifest(tmp_path, name, manifest):
    root = tmp_path / name
    root.mkdir()
    store = ExportStore(str(root))
    store._manifest = dict(manifest)
    return store


class TestLineage:
    PARENT = "a" * 64

    def _child(self, tmp_path, **extra):
        m = {"kind": "mx_rcnn_tpu_export_store", "entries": {},
             "version": "v2", "parent_sha": self.PARENT,
             "train_fingerprint": "f" * 64}
        m.update(extra)
        return _store_with_manifest(tmp_path, "child", m)

    def test_known_parent_admits(self, tmp_path):
        got = self._child(tmp_path).check_lineage(
            known_parents={self.PARENT})
        assert got == {"version": "v2", "parent_sha": self.PARENT,
                       "train_fingerprint": "f" * 64, "legacy": False}

    def test_unknown_parent_refused(self, tmp_path):
        with pytest.raises(ExportMismatch, match="unknown parent"):
            self._child(tmp_path).check_lineage(known_parents={"b" * 64})

    def test_unrooted_version_refused_when_lineage_required(self, tmp_path):
        store = self._child(tmp_path, parent_sha=None)
        with pytest.raises(ExportMismatch, match="unrooted"):
            store.check_lineage(known_parents={self.PARENT})
        # with no parent requirement the same store admits
        assert store.check_lineage()["version"] == "v2"

    def test_fingerprint_mismatch_refused(self, tmp_path):
        with pytest.raises(ExportMismatch, match="train_fingerprint"):
            self._child(tmp_path).check_lineage(
                known_parents={self.PARENT},
                expect_train_fingerprint="deadbeef" * 8)

    def test_fingerprint_match_admits(self, tmp_path):
        got = self._child(tmp_path).check_lineage(
            known_parents={self.PARENT},
            expect_train_fingerprint="f" * 64)
        assert not got["legacy"]

    def test_legacy_versionless_store_admits_unchanged(self, tmp_path):
        # every store exported before the rollout plane: no "version"
        # key at all — carries no claims, admits even under a required
        # parent set (the quant-admission back-compat idiom)
        store = _store_with_manifest(
            tmp_path, "old", {"kind": "mx_rcnn_tpu_export_store",
                              "entries": {}})
        got = store.check_lineage(known_parents={self.PARENT},
                                  expect_train_fingerprint="x" * 64)
        assert got == {"version": None, "parent_sha": None,
                       "legacy": True}


class TestVariablesBundle:
    def test_round_trip_preserves_empty_subtrees(self, tmp_path):
        # a BN-free model's variables are {"params": ..., "batch_stats":
        # {}} — the empty subtree has NO leaves, so a plain
        # flatten→npz→unflatten drops it, and exported programs (traced
        # WITH it) then refuse the pytree at call time.  The manifest's
        # empty_subtrees record must rebuild it on load.
        cfg = generate_config("tiny", "synthetic")
        variables = {
            "params": {"conv": {"kernel": np.ones((3, 3), np.float32),
                                "bias": np.zeros((3,), np.float32)}},
            "batch_stats": {},
        }
        root = str(tmp_path / "store")
        store = ExportStore.create(root, cfg)
        store.add_variables(variables)
        store.finish()

        loaded = ExportStore(root).load_variables()
        assert loaded["batch_stats"] == {}
        assert set(loaded) == {"params", "batch_stats"}
        np.testing.assert_array_equal(
            loaded["params"]["conv"]["kernel"],
            variables["params"]["conv"]["kernel"])
        # and the weights identity survives the trip
        assert variables_fingerprint(loaded) == \
            variables_fingerprint(variables)

    def test_versioned_store_without_bundle_refuses(self, tmp_path):
        store = _store_with_manifest(
            tmp_path, "nobundle", {"entries": {}, "version": "v2"})
        with pytest.raises(ExportMismatch, match="no variables payload"):
            store.load_variables()


# ---------------------------------------------------------------------------
# canary lane + per-version accounting (the router half of the plane)
# ---------------------------------------------------------------------------

class TestCanaryLane:
    def _make(self, registry=None):
        cfg = generate_config("tiny", "synthetic")
        return FleetRouter(SimpleNamespace(registry=registry), cfg)

    def test_fraction_is_deterministic_accumulator(self):
        router = self._make()
        base = SimpleNamespace(version=None, id=0)
        canary = SimpleNamespace(version="v2", id=1)
        router.set_canary("v2", 0.25)
        lanes = [router._canary_lane([base, canary]) for _ in range(8)]
        picks = [lane == [canary] for lane in lanes]
        # exactly 1-in-4, at requests 4 and 8 — not a coin flip
        assert picks == [False, False, False, True,
                         False, False, False, True]
        assert all(lane == [base] for i, lane in enumerate(lanes)
                   if not picks[i])

    def test_clearing_the_lane_restores_version_blind_jsq(self):
        router = self._make()
        base = SimpleNamespace(version=None, id=0)
        canary = SimpleNamespace(version="v2", id=1)
        router.set_canary("v2", 1.0)
        assert router._canary_lane([base, canary]) == [canary]
        router.set_canary(None, 0.0)
        assert router._canary_lane([base, canary]) == [base, canary]

    def test_empty_lane_falls_back_and_is_counted(self):
        # availability outranks canary purity: the canary fraction
        # outrunning v2 capacity must never fail a servable request
        router = self._make()
        base = SimpleNamespace(version=None, id=0)
        router.set_canary("v2", 1.0)
        assert router._canary_lane([base]) == [base]
        assert router.metrics.registry.counter(
            "fleet.canary_fallback") == 1


class TestPerVersionAccounting:
    def _freq(self, version="v2"):
        freq = FleetRequest(np.zeros((4, 4, 3), np.uint8), None, 0.0)
        freq.replica_id = 0
        freq.version = version
        return freq

    def test_counts_reach_the_scrape_visible_registry(self):
        # an agent's canary series must land in the manager's (shared,
        # scraped) registry — NOT the router's private fleet registry —
        # or rollout_rules judge a series that never reaches /metrics
        shared = Registry()
        cfg = generate_config("tiny", "synthetic")
        router = FleetRouter(SimpleNamespace(registry=shared), cfg)
        router._count_version(self._freq(), "served", ms=12.0)
        snap = shared.snapshot()
        assert snap["counters"]["fleet.ver.v2.served"] == 1
        assert "fleet.ver.v2.total_ms" in snap["hists"]
        assert router.metrics.registry.counter("fleet.ver.v2.served") == 0

    def test_falls_back_to_private_registry_in_process(self):
        cfg = generate_config("tiny", "synthetic")
        router = FleetRouter(SimpleNamespace(registry=None), cfg)
        router._count_version(self._freq(version=None), "expired")
        assert router.metrics.registry.counter(
            "fleet.ver.base.expired") == 1

    def test_undispatched_request_counts_nowhere(self):
        # exactly-once per version is "of the LAST dispatch target":
        # a request that never reached a replica has no version row
        cfg = generate_config("tiny", "synthetic")
        shared = Registry()
        router = FleetRouter(SimpleNamespace(registry=shared), cfg)
        freq = self._freq()
        freq.replica_id = None
        router._count_version(freq, "failed")
        assert shared.snapshot()["counters"] == {}


# ---------------------------------------------------------------------------
# the controller over a fake port
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def tick(self, dt=1.0):
        self.now += dt


class FakeHost:
    def __init__(self):
        self.versions_d = {"base": 1}
        self.pulls = 0
        self.down = False


class FakePort:
    """Port-protocol fake: each host swaps base→v2 in two pump calls
    (add the v2 replica, then drain base) — the same side-by-side
    then-drain shape the real agent pump walks."""

    def __init__(self, names, pair=(0.8, 0.8)):
        self.hosts = {n: FakeHost() for n in names}
        self.canary_calls = []
        self.pair = pair

    def sources(self):
        return sorted(self.hosts)

    def pull(self, source, url, version):
        h = self.hosts[source]
        if h.down:
            return None
        h.pulls += 1
        return {"already": h.pulls > 1}

    def versions(self, source):
        h = self.hosts[source]
        return None if h.down else dict(h.versions_d)

    def swap_next(self, source, version):
        h = self.hosts[source]
        if h.down:
            return None
        if h.versions_d.get(version, 0) < 1:
            h.versions_d[version] = 1
            return {"added": 1, "remaining": 1, "pending": False}
        if h.versions_d.get("base", 0) > 0:
            del h.versions_d["base"]
            return {"swapped": 1, "remaining": 0, "pending": False}
        return {"remaining": 0, "pending": False}

    def rollback(self, source):
        h = self.hosts[source]
        if h.down:
            return None
        h.versions_d = {"base": 1}
        return {"remaining": 0, "pending": False}

    def set_canary(self, version, fraction):
        self.canary_calls.append((version, fraction))

    def shadow_pair(self):
        return self.pair


def _cfg():
    return generate_config(
        "tiny", "synthetic", rollout__gate_min_pairs=3,
        rollout__gate_sample_every=1, rollout__bake_s=4.0,
        rollout__step_timeout_s=10.0, rollout__canary_fraction=0.25)


def _drive(ctrl, clock, max_steps=200, on_tick=None):
    steps = 0
    while ctrl.phase not in (DONE, ROLLED_BACK) and steps < max_steps:
        ctrl.step()
        clock.tick(1.0)
        steps += 1
        if on_tick is not None:
            on_tick(ctrl)
    return ctrl.phase


def _kinds(ctrl):
    return [e["kind"] for e in ctrl.events]


class TestController:
    def test_happy_path_lands_v2_everywhere(self):
        port = FakePort(["a", "b", "c"])
        clock = FakeClock()
        ctrl = RolloutController(port, _cfg(), version="v2",
                                 clock=clock)
        ctrl.start()
        assert _drive(ctrl, clock) == DONE
        for h in port.hosts.values():
            assert h.versions_d == {"v2": 1}
            assert h.pulls == 1  # one transfer per host, ever
        kinds = _kinds(ctrl)
        assert kinds[0] == "start" and kinds[-1] == "done"
        assert kinds.count("pulled") == 3
        assert kinds.count("host_rolled") == 3
        assert "gate_passed" in kinds and "gate_refused" not in kinds
        # lane opens at the canary fraction, closes at gate pass
        assert port.canary_calls[0] == ("v2", 0.25)
        assert (None, 0.0) in port.canary_calls[1:]
        # rolling starts only after the gate verdict
        assert kinds.index("gate_passed") < kinds.index("host_rolling")

    def test_gate_refusal_auto_rolls_back(self):
        port = FakePort(["a", "b"], pair=(0.8, 0.05))  # damaged canary
        clock = FakeClock()
        ctrl = RolloutController(port, _cfg(), version="v2",
                                 clock=clock)
        ctrl.start()
        assert _drive(ctrl, clock) == ROLLED_BACK
        assert ctrl._rollback_reason == "gate_refused"
        assert ctrl.rollback_s is not None
        v = ctrl.gate.verdict()
        assert v["refused"] and v["mean_delta"] < -0.5
        for h in port.hosts.values():
            assert h.versions_d == {"base": 1}
        kinds = _kinds(ctrl)
        assert "gate_refused" in kinds and "rolled_back" in kinds
        assert "host_rolling" not in kinds  # refused BEFORE rolling

    def test_rollback_is_idempotent(self):
        port = FakePort(["a"], pair=(0.8, 0.05))
        clock = FakeClock()
        ctrl = RolloutController(port, _cfg(), version="v2",
                                 clock=clock)
        ctrl.start()
        _drive(ctrl, clock)
        assert ctrl.phase == ROLLED_BACK
        # operator on top of the gate's rollback: a recorded no-op
        res = ctrl.rollback("operator")
        assert res == {"phase": ROLLED_BACK, "noop": True}
        assert ctrl._rollback_reason == "gate_refused"  # unchanged

    def test_rollback_returns_a_completed_fleet_to_base(self):
        # first-class rollback AFTER the swap completed: hosts hold
        # ONLY v2 (no canary replica left) and must still pump back —
        # the consistency check is "boot-only", not "holds canary"
        port = FakePort(["a", "b"])
        clock = FakeClock()
        ctrl = RolloutController(port, _cfg(), version="v2",
                                 clock=clock)
        ctrl.start()
        assert _drive(ctrl, clock) == DONE
        res = ctrl.rollback("operator")
        assert res["noop"] is False
        while ctrl.phase == ROLLING_BACK:
            ctrl.step()
            clock.tick(1.0)
        assert ctrl.phase == ROLLED_BACK
        for h in port.hosts.values():
            assert h.versions_d == {"base": 1}

    def test_health_critical_rolls_back(self):
        port = FakePort(["a"])
        clock = FakeClock()
        health = SimpleNamespace(verdict=CRITICAL)
        ctrl = RolloutController(port, _cfg(), version="v2",
                                 clock=clock, health=health)
        ctrl.start()
        assert _drive(ctrl, clock) == ROLLED_BACK
        assert ctrl._rollback_reason == "health_critical"

    def test_killed_host_defers_then_finalize_reconverges(self):
        port = FakePort(["a", "b", "c"])
        clock = FakeClock()
        ctrl = RolloutController(port, _cfg(), version="v2",
                                 clock=clock)
        ctrl.start()

        def kill_then_relaunch(c):
            # SIGKILL "b" the moment it starts rolling; "relaunch" it
            # (back on boot, pull state wiped like a fresh process)
            # once the controller has deferred it
            if (not port.hosts["b"].down
                    and any(e["kind"] == "host_rolling"
                            and e.get("source") == "b"
                            for e in c.events)
                    and "host_deferred" not in _kinds(c)):
                port.hosts["b"].down = True
                port.hosts["b"].versions_d = {"base": 1}
            if "host_deferred" in _kinds(c):
                port.hosts["b"].down = False

        assert _drive(ctrl, clock, on_tick=kill_then_relaunch) == DONE
        kinds = _kinds(ctrl)
        assert "host_deferred" in kinds
        assert "finalize_abandoned" not in kinds
        for h in port.hosts.values():
            assert h.versions_d == {"v2": 1}

    def test_host_down_forever_is_abandoned_after_grace(self):
        port = FakePort(["a", "b"])
        clock = FakeClock()
        ctrl = RolloutController(port, _cfg(), version="v2",
                                 clock=clock)
        ctrl.start()

        def kill_for_good(c):
            if any(e["kind"] == "host_rolling" and e.get("source") == "b"
                   for e in c.events):
                port.hosts["b"].down = True

        assert _drive(ctrl, clock, on_tick=kill_for_good) == DONE
        kinds = _kinds(ctrl)
        assert "host_deferred" in kinds
        assert "finalize_abandoned" in kinds
        # the live fleet still converged — the down host is an
        # operator problem, not a hung rollout
        assert port.hosts["a"].versions_d == {"v2": 1}

    def test_unpullable_host_defers_without_blocking_the_fleet(self):
        port = FakePort(["a", "b"])
        port.hosts["b"].down = True
        clock = FakeClock()
        ctrl = RolloutController(port, _cfg(), version="v2",
                                 clock=clock)
        ctrl.start()
        assert _drive(ctrl, clock) == DONE
        kinds = _kinds(ctrl)
        assert "pull_deferred" in kinds
        assert "finalize_abandoned" in kinds
        assert port.hosts["a"].versions_d == {"v2": 1}
        assert port.hosts["b"].pulls == 0

    def test_decision_log_is_deterministic(self):
        def one():
            port = FakePort(["a", "b"])
            clock = FakeClock()
            ctrl = RolloutController(port, _cfg(), version="v2",
                                     clock=clock)
            ctrl.start()
            _drive(ctrl, clock)
            return ctrl.events

        assert one() == one()


# ---------------------------------------------------------------------------
# the sim scenario: the REAL controller at fleet scale in virtual time
# ---------------------------------------------------------------------------

class TestSimCanaryRollout:
    @pytest.fixture(scope="class")
    def cfg(self):
        return generate_config("tiny", "synthetic")

    @pytest.fixture(scope="class")
    def shipped(self, cfg):
        from mx_rcnn_tpu.sim.control import SimRun
        from mx_rcnn_tpu.sim.score import decision_log_bytes
        from mx_rcnn_tpu.sim.traffic import generate
        out = []
        for _ in range(2):
            run = SimRun(generate("canary_rollout", cfg, 6, seed=3),
                         cfg, label="shipped")
            score = run.run()
            out.append((score, decision_log_bytes(run.log)))
        return out

    def test_shipped_lands_v2_with_zero_lost(self, shipped):
        score, _ = shipped[0]
        assert score["rollout"]["phase"] == "done"
        assert score["lost"] == 0
        assert score["submitted"] == (score["served"] + score["shed"]
                                      + score["expired"]
                                      + score["failed"])

    def test_decision_log_byte_identical(self, shipped):
        (s1, b1), (s2, b2) = shipped
        assert b1 == b2
        assert s1["decision_log_sha256"] == s2["decision_log_sha256"]

    def test_redteam_arm_refused_and_rolled_back(self, cfg, shipped):
        from mx_rcnn_tpu.sim.control import SimRun
        from mx_rcnn_tpu.sim.traffic import generate
        run = SimRun(generate("canary_rollout", cfg, 6, seed=3), cfg,
                     label="mistuned",
                     arm_overrides={"rollout__redteam_damage": 0.35})
        score = run.run()
        assert score["rollout"]["phase"] == "rolled_back"
        assert score["rollout"]["reason"] == "gate_refused"
        assert score["rollout"]["gate"]["refused"] is True
        assert score["lost"] == 0  # refusal must not cost requests
        # same trace, same seed: the divergence is the damage alone
        assert score["decision_log_sha256"] != \
            shipped[0][0]["decision_log_sha256"]
