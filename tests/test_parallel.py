"""Data-parallel tests on the 8-virtual-device CPU mesh (conftest.py).

This is the framework's "multi-node without a cluster" strategy
(SURVEY.md §4): collective code paths run on a real 8-device mesh.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.optim import make_optimizer
from mx_rcnn_tpu.core.train import Batch, init_state, make_train_step
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.parallel import device_mesh, make_dp_train_step, replicate, shard_batch
from tests.test_train_step import make_batch, tiny_setup

KEY = jax.random.PRNGKey(7)


def test_eight_virtual_devices_present():
    assert jax.device_count() == 8


def stack_batches(n, size=128):
    bs = [make_batch(1, size, seed=s) for s in range(n)]
    return Batch(*[jnp.concatenate([getattr(b, f) for b in bs]) for f in Batch._fields])


def test_dp_step_runs_and_replicas_agree():
    cfg, model, tx, state = tiny_setup()
    mesh = device_mesh(8)
    step = make_dp_train_step(model, cfg, tx, mesh)
    state_r = replicate(state, mesh)
    batch = shard_batch(stack_batches(8), mesh)
    new_state, metrics = step(state_r, batch, KEY)
    assert int(new_state.step) == 1
    for k, v in metrics.items():
        assert np.isfinite(float(v)), k
    # updated params are a single replicated array — fetching works and is finite
    leaf = new_state.params["backbone"]["conv1"]["kernel"]
    assert np.isfinite(np.asarray(leaf)).all()


@pytest.mark.slow
def test_dp_grad_sync_matches_single_device_global_batch():
    """DP over 8 shards must equal a single-device step on the global batch
    when sampling randomness is aligned: here we verify the *deterministic*
    part by overfitting both for several steps and comparing the loss scale
    (exact bitwise equality is not expected because per-shard RNG folding
    intentionally differs from single-device per-image splits)."""
    cfg, model, tx, state = tiny_setup()
    mesh = device_mesh(8)
    dp_step = make_dp_train_step(model, cfg, tx, mesh)
    single_step = jax.jit(make_train_step(model, cfg, tx))

    global_batch = stack_batches(8)
    # the DP step donates its state; replicate() may alias the source
    # buffers, so give it an independent copy to keep `state` usable
    s_dp = replicate(jax.tree.map(jnp.copy, state), mesh)
    b_dp = shard_batch(global_batch, mesh)
    s_sd = state
    for i in range(3):
        s_dp, m_dp = dp_step(s_dp, b_dp, KEY)
        s_sd, m_sd = single_step(s_sd, global_batch, KEY)
    # same data, same lr → losses must track closely
    assert abs(float(m_dp["loss"]) - float(m_sd["loss"])) < 0.35 * float(m_sd["loss"]) + 0.1
    # parameter trajectories stay within a loose envelope
    d = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))), s_dp.params, s_sd.params)
    assert max(jax.tree.leaves(d)) < 0.15


@pytest.mark.slow
def test_dp_grad_sync_exact_vs_manual_average():
    """Aligned-RNG exact equivalence (VERDICT r1 item 10): the DP step must
    produce the SAME parameters as manually computing each shard's gradient
    with the shard's exact folded key, averaging on host, and applying one
    optimizer update.  This fails if the in-step pmean is removed, averages
    over the wrong axis, or the per-shard RNG folding changes silently."""
    import optax

    from mx_rcnn_tpu.core.train import loss_and_metrics

    cfg, model, tx, state = tiny_setup()
    mesh = device_mesh(8)
    dp_step = make_dp_train_step(model, cfg, tx, mesh)
    global_batch = stack_batches(8)
    s_dp, _ = dp_step(replicate(jax.tree.map(jnp.copy, state), mesh),
                      shard_batch(global_batch, mesh), KEY)

    # manual reference: replicate the DP key derivation exactly —
    # shard i folds axis_index first, the base step then folds state.step=0
    @jax.jit
    def shard_grads(params, batch_stats, sl, key_i):
        return jax.grad(
            lambda p: loss_and_metrics(model, p, batch_stats, sl, key_i,
                                       cfg)[0])(params)

    grads = []
    for i in range(8):
        sl = Batch(*[getattr(global_batch, f)[i:i + 1]
                     for f in Batch._fields])
        key_i = jax.random.fold_in(jax.random.fold_in(KEY, i), 0)
        grads.append(shard_grads(state.params, state.batch_stats, sl, key_i))
    gmean = jax.tree.map(lambda *gs: jnp.mean(jnp.stack(gs), axis=0), *grads)
    updates, _ = tx.update(gmean, state.opt_state, state.params)
    params_ref = optax.apply_updates(state.params, updates)

    for a, b in zip(jax.tree.leaves(s_dp.params),
                    jax.tree.leaves(params_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_dp_uneven_rng_decorrelated():
    """Different shards must sample different ROIs — metrics must not be the
    trivial value they'd have if every shard saw identical RNG *and* data."""
    cfg, model, tx, state = tiny_setup()
    mesh = device_mesh(8)
    step = make_dp_train_step(model, cfg, tx, mesh)
    # identical images on all shards, but per-shard RNG folding differs
    b = make_batch(1, 128, seed=0)
    batch = Batch(*[jnp.concatenate([getattr(b, f)] * 8) for f in Batch._fields])
    _, metrics = step(replicate(state, mesh), shard_batch(batch, mesh), KEY)
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow
def test_checkpoint_portable_across_mesh_sizes(tmp_path):
    """Cross-mesh checkpoint portability (VERDICT r03 item 6): real TPU
    operations resume on different topologies, so a TrainState saved from
    an 8-device run must restore and CONTINUE on (a) a hierarchical
    (dcn=2, ici=4) reshape — bit-level continuation, since that step is
    proven identical to the flat step — and (b) 4 devices and (c) a single
    device, where the documented per-shard RNG fold-in means trajectories
    diverge in sampling but must stay in the converged band (a broken
    restore resets to init-level loss immediately)."""
    from mx_rcnn_tpu.utils.checkpoint import restore_state, save_checkpoint

    cfg, model, tx, state = tiny_setup()
    prefix = str(tmp_path / "xmesh")
    mesh8 = device_mesh(8)
    step8 = make_dp_train_step(model, cfg, tx, mesh8)
    batch = stack_batches(8)
    b8 = shard_batch(batch, mesh8)
    s = replicate(jax.tree.map(jnp.copy, state), mesh8)
    for _ in range(6):
        s, m = step8(s, b8, KEY)
    loss_pre = float(m["loss"])
    save_checkpoint(prefix, 1, s)
    saved_step = int(s.step)

    # (a) flat-8 → (dcn=2, ici=4): restored run must match the
    # uninterrupted flat run EXACTLY (the hier step ≡ flat step)
    hier = device_mesh(8, dcn_size=2)
    steph = make_dp_train_step(model, cfg, tx, hier)
    sh = replicate(restore_state(jax.tree.map(jnp.copy, state), prefix, 1),
                   hier)
    assert int(sh.step) == saved_step
    s_cont, m_cont = step8(s, b8, KEY)
    sh, m_h = steph(sh, shard_batch(batch, hier), KEY)
    np.testing.assert_allclose(float(m_h["loss"]), float(m_cont["loss"]),
                               rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s_cont.params),
                    jax.tree.leaves(sh.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    # (b) resume on a 4-device mesh (2 images per shard)
    mesh4 = device_mesh(4)
    step4 = make_dp_train_step(model, cfg, tx, mesh4)
    s4 = replicate(restore_state(jax.tree.map(jnp.copy, state), prefix, 1),
                   mesh4)
    assert int(s4.step) == saved_step
    b4 = shard_batch(batch, mesh4)
    for _ in range(3):
        s4, m4 = step4(s4, b4, KEY)
    assert int(s4.step) == saved_step + 3
    # continuation, not a reset: stays in the trained band (init-level
    # loss on this setup is >3x the converged loss)
    assert float(m4["loss"]) < 2.0 * loss_pre + 0.2

    # (c) resume on a single device with the same global batch
    from mx_rcnn_tpu.core.train import make_train_step

    step1 = jax.jit(make_train_step(model, cfg, tx))
    s1 = restore_state(jax.tree.map(jnp.copy, state), prefix, 1)
    assert int(s1.step) == saved_step
    for _ in range(3):
        s1, m1 = step1(s1, batch, KEY)
    assert int(s1.step) == saved_step + 3
    assert float(m1["loss"]) < 2.0 * loss_pre + 0.2


@pytest.mark.slow
def test_hierarchical_dcn_mesh_matches_flat_mesh():
    """A 2x4 (dcn, ici) mesh must produce the SAME step as the flat 8-device
    mesh: axis_index over both axes linearizes identically, so per-image
    RNG keys agree, and pmean over both axes equals pmean over 'data'.
    This validates the multi-host gradient-sync path without a cluster."""
    cfg, model, tx, state = tiny_setup()
    global_batch = stack_batches(8)

    flat = device_mesh(8)
    step_f = make_dp_train_step(model, cfg, tx, flat)
    s_f = replicate(jax.tree.map(jnp.copy, state), flat)
    out_f, m_f = step_f(s_f, shard_batch(global_batch, flat), KEY)

    hier = device_mesh(8, dcn_size=2)
    assert hier.axis_names == ("dcn", "ici")
    step_h = make_dp_train_step(model, cfg, tx, hier)
    s_h = replicate(jax.tree.map(jnp.copy, state), hier)
    out_h, m_h = step_h(s_h, shard_batch(global_batch, hier), KEY)

    for k in m_f:
        np.testing.assert_allclose(float(m_f[k]), float(m_h[k]), rtol=1e-5,
                                   err_msg=k)
    flat_leaves = jax.tree_util.tree_leaves(out_f.params)
    hier_leaves = jax.tree_util.tree_leaves(out_h.params)
    for a, b in zip(flat_leaves, hier_leaves):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)
