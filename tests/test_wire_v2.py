"""Wire data-plane v2 tests (ISSUE 20): u8 source-pixel frames
(bit-equal canvas via the shared ``data/image.py pad_normalize``),
frame coalescing into count-prefixed envelopes, the result envelope's
per-frame terminal statuses, reroute-after-death of a coalesced
envelope (every frame terminates exactly once, the trace stays ONE
N-attempt tree), the AIMD pipeline-depth controller on synthetic RTT
traces, and the scraped-lane-hint ttl decay regression.

Everything runs in-process and stubbed (quick tier) — the
multi-PROCESS versions of these claims (real agent subprocesses,
SIGKILL mid-envelope, measured bytes/image and throughput) are the
bench's job (``tools/loadgen.py --wire_bench`` → docs/WIRE_r20.json).
"""

import http.client
import struct
import threading
import time

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data.image import pad_normalize
from mx_rcnn_tpu.obs import trace as obs_trace
from mx_rcnn_tpu.obs.trace import merge_fleet_trace, tree_complete
from mx_rcnn_tpu.serve.agent import ReplicaAgent, make_agent_server
from mx_rcnn_tpu.serve.fleet import build_fleet
from mx_rcnn_tpu.serve.remote import (DTYPE_F32, DTYPE_U8, ENV_FAILED,
                                      ENV_SERVED, ENVELOPE_CTYPE,
                                      MAX_ENV_FRAMES, WIRE_VERSION,
                                      WIRE_VERSION_SRC, _ENV_HEAD,
                                      _ENV_LEN, _REQ_HEAD2,
                                      PipelineController, RemoteEngine,
                                      build_crosshost_router,
                                      decode_envelope, decode_frame_ex,
                                      decode_prepared_ex,
                                      decode_result,
                                      decode_result_envelope,
                                      encode_envelope_parts,
                                      encode_prepared,
                                      encode_prepared_parts,
                                      encode_result_envelope,
                                      encode_source,
                                      encode_source_parts)
from mx_rcnn_tpu.tools.loadgen import make_content_stub_run_fn


@pytest.fixture(autouse=True)
def _clean_distributed_state():
    obs_trace.reset_distributed()
    yield
    obs_trace.reset_distributed()


def _cfg(**kw):
    over = {
        "bucket__scale": 128, "bucket__max_size": 160,
        "bucket__shapes": ((128, 160), (160, 128)),
        "serve__batch_size": 2, "serve__max_delay_ms": 5.0,
        "fleet__health_interval_s": 30.0,
    }
    over.update(kw)
    return generate_config("tiny", "synthetic", **over)


def _src(seed=0, hw=(120, 150)):
    """A sub-bucket u8 source image + its head-computed im_info."""
    rng = np.random.RandomState(seed)
    img = rng.randint(0, 256, size=(*hw, 3), dtype=np.uint8)
    return img, np.array([hw[0], hw[1], 1.0], np.float32)


def _start_agent(cfg, model_ms=0.0):
    ag = ReplicaAgent(cfg, None, {}, run_fn_factory=(
        lambda rid: make_content_stub_run_fn(cfg, model_ms)))
    srv = make_agent_server(ag, "127.0.0.1", 0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return ag, srv, f"http://127.0.0.1:{srv.server_address[1]}"


def _stop_agent(ag, srv):
    srv.shutdown()
    srv.server_close()
    ag.close()


def _det_key(dets):
    return b"".join(np.ascontiguousarray(dets[c], np.float32).tobytes()
                    for c in sorted(dets))


# ---------------------------------------------------------------------------
# v2 frame codec
# ---------------------------------------------------------------------------

def test_codec_source_round_trip_bit_equal():
    img, info = _src(seed=3)
    b = (128, 160)
    buf = encode_source(img, info, b, 1234.5)
    f = decode_frame_ex(buf)
    assert f.version == WIRE_VERSION_SRC and f.dtype == DTYPE_U8
    assert f.data.dtype == np.uint8 and f.data.shape == img.shape
    assert f.data.tobytes() == img.tobytes()   # bit-equal, not close
    assert f.bucket == b
    assert f.im_info.tobytes() == info.tobytes()
    assert f.timeout_ms == np.float32(1234.5)
    assert f.ctx is None
    # 1 B/px on the wire: header + h*w*3, nothing else
    assert len(buf) == _REQ_HEAD2.size + img.size


def test_codec_source_parts_are_zero_copy():
    """The pixel payload rides as a memoryview of the caller's array —
    sendmsg iovecs, no staging copy."""
    img, info = _src(seed=4)
    parts = encode_source_parts(img, info, (128, 160), 0.0)
    assert len(parts) == 2
    assert isinstance(parts[1], memoryview)
    assert np.shares_memory(np.frombuffer(parts[1], np.uint8), img)
    assert b"".join(parts) == encode_source(img, info, (128, 160), 0.0)


def test_codec_frame_ex_decodes_v1_identically():
    """decode_frame_ex is the version dispatcher: a v1 frame through it
    must equal the pinned v1-only decode_prepared_ex, tagged fp32."""
    rng = np.random.RandomState(5)
    data = (rng.rand(128, 160, 3) * 255.0).astype(np.float32)
    info = np.array([128, 160, 1.0], np.float32)
    buf = encode_prepared(data, info, 777.0)
    f = decode_frame_ex(buf)
    d1, i1, t1, c1 = decode_prepared_ex(buf)
    assert f.version == WIRE_VERSION and f.dtype == DTYPE_F32
    assert f.data.tobytes() == d1.tobytes()
    assert f.bucket == (128, 160)
    assert f.im_info.tobytes() == i1.tobytes()
    assert f.timeout_ms == t1 and f.ctx is None and c1 is None


def test_codec_source_rejects_malformed():
    img, info = _src(seed=6, hw=(12, 20))
    b = (16, 24)
    buf = encode_source(img, info, b, 0.0)
    want = len(buf)

    def patched(off, fmt, val):
        m = bytearray(buf)
        struct.pack_into(fmt, m, off, val)
        return bytes(m)

    with pytest.raises(ValueError):
        decode_frame_ex(buf[:6])                  # truncated head
    with pytest.raises(ValueError):
        decode_frame_ex(buf[:want - 1])           # truncated payload
    with pytest.raises(ValueError):
        decode_frame_ex(buf + b"\0")              # trailing byte
    with pytest.raises(ValueError):
        decode_frame_ex(b"XXXX" + buf[4:])        # bad magic
    with pytest.raises(ValueError):
        decode_frame_ex(patched(4, "<H", 9))      # unknown version
    with pytest.raises(ValueError):
        decode_frame_ex(patched(6, "<H", 7))      # unknown dtype tag
    with pytest.raises(ValueError):
        decode_frame_ex(patched(12, "<H", 4))     # c != 3
    with pytest.raises(ValueError):
        decode_frame_ex(patched(18, "<H", 0x80))  # unknown flags
    with pytest.raises(ValueError):
        decode_frame_ex(patched(8, "<H", 17))     # h > bh
    # dtype/length confusion: a u8 frame retagged fp32 must never be
    # reinterpreted (length disagrees), and padding a u8 frame out to
    # the fp32 length must not make the retag acceptable either
    with pytest.raises(ValueError):
        decode_frame_ex(patched(6, "<H", DTYPE_F32))
    inflated = bytearray(patched(6, "<H", DTYPE_F32))
    inflated += b"\0" * (img.size * 3)            # now fp32-sized
    with pytest.raises(ValueError):               # ...but partial canvas
        decode_frame_ex(bytes(inflated))
    with pytest.raises(ValueError):               # u8 with fp32 length
        decode_frame_ex(buf + b"\0" * (img.size * 3))
    # fp32 v2 frames must be FULL canvases
    full = np.zeros((16, 24, 3), np.float32)
    head = _REQ_HEAD2.pack(b"MXR1", WIRE_VERSION_SRC, DTYPE_F32,
                           12, 20, 3, 16, 24, 0, 0.0, 12.0, 20.0, 1.0)
    with pytest.raises(ValueError):
        decode_frame_ex(head + full[:12, :20].tobytes())
    # trace flag without the extension blob
    with pytest.raises(ValueError):
        decode_frame_ex(patched(18, "<H", 0x1))
    # encoder-side validations
    with pytest.raises(ValueError):
        encode_source(img.astype(np.float32), info, b, 0.0)
    with pytest.raises(ValueError):
        encode_source(img[..., 0], info, b, 0.0)
    with pytest.raises(ValueError):
        encode_source(img, info, (8, 8), 0.0)     # does not fit


def test_codec_source_trace_extension_round_trip():
    obs_trace.configure_distributed(sample=1.0, ring=64, host="head")
    ctx = obs_trace.sample_trace()
    assert ctx is not None
    img, info = _src(seed=7, hw=(12, 20))
    f = decode_frame_ex(encode_source(img, info, (16, 24), 50.0,
                                      ctx=ctx))
    assert f.ctx is not None and f.ctx.trace_id == ctx.trace_id
    assert f.data.tobytes() == img.tobytes()


# ---------------------------------------------------------------------------
# envelopes
# ---------------------------------------------------------------------------

def _envelope(frames):
    parts = encode_envelope_parts([[f] for f in frames])
    return b"".join(bytes(p) for p in parts)


def test_codec_envelope_round_trip_mixed_versions():
    img, sinfo = _src(seed=8, hw=(12, 20))
    v2 = encode_source(img, sinfo, (16, 24), 10.0)
    rng = np.random.RandomState(9)
    v1 = encode_prepared((rng.rand(16, 24, 3) * 255).astype(np.float32),
                         np.array([16, 24, 1.0], np.float32), 20.0)
    buf = _envelope([v2, v1, v2])
    members = decode_envelope(buf)
    assert members == [v2, v1, v2]
    kinds = [decode_frame_ex(m).dtype for m in members]
    assert kinds == [DTYPE_U8, DTYPE_F32, DTYPE_U8]
    # coalescing overhead is exactly the head + one length per frame
    assert len(buf) == (_ENV_HEAD.size + 3 * _ENV_LEN.size
                        + len(v2) * 2 + len(v1))


def test_codec_envelope_rejects_malformed():
    img, info = _src(seed=10, hw=(12, 20))
    fr = encode_source(img, info, (16, 24), 0.0)
    buf = _envelope([fr, fr])

    def patched(off, fmt, val):
        m = bytearray(buf)
        struct.pack_into(fmt, m, off, val)
        return bytes(m)

    with pytest.raises(ValueError):
        decode_envelope(buf[:4])                    # truncated head
    with pytest.raises(ValueError):
        decode_envelope(b"XXXX" + buf[4:])          # bad magic
    with pytest.raises(ValueError):
        decode_envelope(patched(4, "<H", 2))        # bad version
    with pytest.raises(ValueError):
        decode_envelope(patched(6, "<H", 0))        # count = 0
    with pytest.raises(ValueError):
        decode_envelope(patched(6, "<H", 3))        # count lies high
    with pytest.raises(ValueError):
        decode_envelope(patched(6, "<H", 1))        # count lies low
    with pytest.raises(ValueError):
        decode_envelope(patched(6, "<H", MAX_ENV_FRAMES + 1))
    with pytest.raises(ValueError):                 # length-prefix lie
        decode_envelope(patched(_ENV_HEAD.size, "<I", len(fr) + 1000))
    with pytest.raises(ValueError):
        decode_envelope(buf[:-3])                   # member truncated
    with pytest.raises(ValueError):
        decode_envelope(buf + b"\0\0")              # trailing bytes
    with pytest.raises(ValueError):
        encode_envelope_parts([])                   # empty envelope
    with pytest.raises(ValueError):
        encode_envelope_parts([[fr]] * (MAX_ENV_FRAMES + 1))
    # a malformed MEMBER survives the envelope layer but fails the
    # per-frame decode the caller runs
    poisoned = _envelope([fr, fr[:-2]])   # member 1 short of its header
    with pytest.raises(ValueError):
        [decode_frame_ex(m) for m in decode_envelope(poisoned)]


def test_codec_result_envelope_round_trip_and_rejections():
    entries = [(ENV_SERVED, b"payload"), (ENV_FAILED, b"err"),
               (ENV_SERVED, b"")]
    buf = encode_result_envelope(entries)
    assert decode_result_envelope(buf) == entries

    def patched(off, fmt, val):
        m = bytearray(buf)
        struct.pack_into(fmt, m, off, val)
        return bytes(m)

    with pytest.raises(ValueError):
        decode_result_envelope(buf[:5])
    with pytest.raises(ValueError):                 # request magic
        decode_result_envelope(b"MXE1" + buf[4:])
    with pytest.raises(ValueError):                 # unknown status
        decode_result_envelope(patched(_ENV_HEAD.size, "<H", 9))
    with pytest.raises(ValueError):                 # count lies high
        decode_result_envelope(patched(6, "<H", 4))
    with pytest.raises(ValueError):
        decode_result_envelope(buf[:-1])
    with pytest.raises(ValueError):
        decode_result_envelope(buf + b"\0")


# ---------------------------------------------------------------------------
# pad_normalize bit-equality: head-built canvas ≡ agent-built canvas
# ---------------------------------------------------------------------------

def test_source_path_bit_equal_to_prepared_and_inprocess():
    """THE v2 correctness pin: the same u8 pixels through (a) the local
    router with a head-side pad_normalize, (b) the remote v1 prepared
    path, and (c) the remote v2 source path must produce IDENTICAL
    detections — the content stub hashes the batch bytes, so a single
    differing canvas byte shows up as a diff."""
    cfg = _cfg(fleet__replicas=1)
    local = build_fleet(cfg, None, {}, run_fn_factory=(
        lambda rid: make_content_stub_run_fn(cfg)))
    ag, srv, url = _start_agent(cfg)
    try:
        b = tuple(cfg.bucket.shapes[0])
        img, info = _src(seed=11)
        canvas = pad_normalize(img, cfg.network.pixel_means, b)
        want = local.submit_prepared(canvas, info, b,
                                     timeout_ms=10_000).wait(20.0)
        assert want, "in-process baseline produced no detections"
        eng = RemoteEngine("t-v2eq", url, cfg)
        try:
            got_v1 = eng.submit_prepared(canvas, info, b,
                                         timeout_ms=10_000).wait(20.0)
            got_v2 = eng.submit_source(img, info, b,
                                       timeout_ms=10_000).wait(20.0)
            assert _det_key(got_v1) == _det_key(want)
            assert _det_key(got_v2) == _det_key(want)
        finally:
            eng.close()
    finally:
        _stop_agent(ag, srv)
        local.close()


def test_submit_source_validations():
    cfg = _cfg()
    ag, srv, url = _start_agent(cfg)
    eng = RemoteEngine("t-v2val", url, cfg)
    try:
        b = tuple(cfg.bucket.shapes[0])
        img, info = _src(seed=12)
        with pytest.raises(ValueError):             # fp32 source image
            eng.submit_source(img.astype(np.float32), info, b)
        with pytest.raises(ValueError):             # not (h, w, 3)
            eng.submit_source(img[..., 0], info, b)
        with pytest.raises(ValueError):             # does not fit
            eng.submit_source(img, info, (64, 64))
    finally:
        eng.close()
        _stop_agent(ag, srv)


# ---------------------------------------------------------------------------
# coalescing
# ---------------------------------------------------------------------------

def test_coalescing_packs_envelopes_on_one_connection():
    """A burst behind one connection must coalesce: frames queued while
    a send is in flight pack into envelopes (serve.envelopes > 0), the
    keep-alive pin holds (conns_opened == 1), and every frame is
    accounted exactly once on the wire counters."""
    cfg = _cfg(crosshost__connections=1, crosshost__pipeline_depth=16,
               crosshost__frames_per_send=4)
    ag, srv, url = _start_agent(cfg, model_ms=2.0)
    eng = RemoteEngine("t-coalesce", url, cfg)
    try:
        b = tuple(cfg.bucket.shapes[0])
        reqs = []
        for i in range(16):
            img, info = _src(seed=20 + i)
            reqs.append(eng.submit_source(img, info, b,
                                          timeout_ms=20_000))
        for r in reqs:
            assert r.wait(30.0) is not None
        assert eng.conns_opened == 1
        reg = eng.metrics.registry
        assert reg.counter("serve.envelopes") >= 1
        assert reg.counter("serve.wire_frames") == 16
        assert reg.counter("serve.wire_sends") < 16  # amortized sends
        assert eng.metrics.in_flight() == 0
    finally:
        eng.close()
        _stop_agent(ag, srv)


def test_agent_envelope_member_failure_is_isolated():
    """A well-formed frame the agent cannot serve (unconfigured bucket)
    fails ALONE inside its envelope — its mates still serve (per-frame
    terminal statuses, satellite of ISSUE 20)."""
    cfg = _cfg()
    ag, srv, url = _start_agent(cfg)
    try:
        b = tuple(cfg.bucket.shapes[0])
        img, info = _src(seed=30)
        good = encode_source(img, info, b, 15_000.0)
        odd_img, odd_info = _src(seed=31, hw=(60, 60))
        odd = encode_source(odd_img, odd_info, (96, 96), 15_000.0)
        body = _envelope([good, odd, good])
        host, port = srv.server_address
        conn = http.client.HTTPConnection(host, port, timeout=30.0)
        try:
            conn.request("POST", "/frames", body=body,
                         headers={"Content-Type": ENVELOPE_CTYPE})
            resp = conn.getresponse()
            payload = resp.read()
        finally:
            conn.close()
        assert resp.status == 200
        entries = decode_result_envelope(payload)
        assert [s for s, _ in entries] == [ENV_SERVED, ENV_FAILED,
                                           ENV_SERVED]
        for status, p in entries:
            if status == ENV_SERVED:
                assert decode_result(p)   # a real MXD1 result frame
    finally:
        _stop_agent(ag, srv)


# ---------------------------------------------------------------------------
# reroute after host death mid-coalesced-envelope (satellite c)
# ---------------------------------------------------------------------------

def test_envelope_reroute_after_host_death_single_trace():
    """Kill a host holding coalesced envelopes: every member frame must
    terminate EXACTLY once (rerouted to the survivor, served inside the
    original deadline — no loss, no duplicate terminals) and each
    request's trace stays ONE tree holding both the failed wire attempt
    and the served one."""
    cfg = _cfg(crosshost__connections=1, crosshost__pipeline_depth=16,
               crosshost__frames_per_send=4,
               crosshost__dead_after_failures=2,
               crosshost__scrape_interval_s=0.1,
               fleet__health_interval_s=0.1,
               fleet__reroute_retries=3,
               obs__trace_sample=1.0, obs__trace_slow_pct=0.0)
    obs_trace.configure_distributed(sample=1.0, ring=256, slow_pct=0.0,
                                    host="head")
    agents = [_start_agent(cfg) for _ in range(2)]
    router, feed = build_crosshost_router(cfg, [a[2] for a in agents])
    try:
        # no traffic yet: the engines' worker sockets are lazy, so
        # closing the victim's listener kills the host completely —
        # its first envelope send fails in flight and must reroute
        _stop_agent(*agents[1][:2])
        t0 = time.monotonic()
        n = 8
        reqs = []
        for i in range(n):
            img, info = _src(seed=40 + i)
            reqs.append(router.submit_source(
                img, info, tuple(cfg.bucket.shapes[0]),
                timeout_ms=15_000))
        for r in reqs:
            assert r.wait(20.0) is not None   # SERVED, never lost
        assert time.monotonic() - t0 < 15.0   # original budget held
        snap = router.metrics.snapshot()["counters"]
        assert snap["served"] == n            # exactly once each
        assert snap["failed"] == 0 and snap["expired"] == 0
        # every trace settles as ONE tree: the rerouted requests carry
        # BOTH wire attempts (a transport_error span and a served one)
        deadline = time.monotonic() + 5.0
        rerouted_trees = 0
        while time.monotonic() < deadline:
            doc = merge_fleet_trace(obs_trace.kept_trees(), {}, {})
            settled = {
                tid: spans for tid, spans in doc["traces"].items()
                if any(s["name"] == "request" and s["hop"] == 0
                       for s in spans)}
            rerouted_trees = sum(
                1 for spans in settled.values()
                if any(s["name"] == "remote.wire"
                       and s.get("args", {}).get("outcome")
                       == "transport_error"
                       for s in spans)
                and any(s["name"] == "terminal.served" for s in spans))
            if len(settled) >= n and rerouted_trees >= 1:
                break
            time.sleep(0.02)
        assert len(settled) >= n
        assert rerouted_trees >= 1, \
            "no single tree holds both the failed and served attempts"
        for tid, spans in settled.items():
            assert tree_complete(spans), f"incomplete tree {tid}"
    finally:
        feed.close()
        router.close()
        _stop_agent(*agents[0][:2])


# ---------------------------------------------------------------------------
# adaptive pipeline depth (AIMD controller, synthetic clock)
# ---------------------------------------------------------------------------

def test_pipeline_controller_aimd_on_synthetic_rtts():
    c = PipelineController(2, 8, clock=lambda: 0.0)
    assert c.current() == 2

    def interval(rtt, t_retune, full, reps=3):
        if full:
            c.note_full()
        # samples inside the interval, then one that crosses it (the
        # windowed p50 judges the OBSERVATION mix, so congested
        # intervals feed more samples to dominate the window)
        for _ in range(reps):
            assert c.note_rtt(rtt, now=t_retune - 0.1) is False
        assert c.note_rtt(rtt, now=t_retune) is True

    # healthy + full → additive increase
    interval(10.0, 0.30, full=True)
    assert c.current() == 3
    interval(10.0, 0.60, full=True)
    assert c.current() == 4
    # healthy but NOT full → no growth (no appetite signal)
    interval(10.0, 0.90, full=False)
    assert c.current() == 4
    # sustained RTT blow-up over the window → multiplicative decrease
    interval(200.0, 1.20, full=True, reps=24)
    assert c.current() == 2
    interval(200.0, 1.50, full=True, reps=24)
    assert c.current() == 1
    # at depth 1 queueing cannot be self-induced: the controller still
    # probes upward when the pipeline filled, even under a congested
    # verdict — refusing would pin the depth at 1 forever
    interval(200.0, 1.80, full=True, reps=24)
    assert c.current() == 2
    assert c.depth_peak == 4
    assert c.retunes == 6


def test_pipeline_controller_clamps():
    assert PipelineController(16, 4).current() == 4   # depth ≤ max
    assert PipelineController(0, 4).current() == 1    # depth ≥ 1
    c = PipelineController(4, 4, clock=lambda: 0.0)
    c.note_full()
    c.note_rtt(10.0, now=0.1)
    c.note_rtt(10.0, now=0.3)
    assert c.current() == 4                           # grow capped


# ---------------------------------------------------------------------------
# scraped-lane-hint staleness (satellite b)
# ---------------------------------------------------------------------------

def test_backlog_hints_decay_and_stamps_are_monotonic():
    cfg = _cfg(crosshost__scrape_interval_s=0.1)   # ttl = 0.6 s
    ag, srv, url = _start_agent(cfg)
    eng = RemoteEngine("t-lanes", url, cfg)
    try:
        b = tuple(cfg.bucket.shapes[0])
        assert eng.bucket_depth(b) == 0
        assert eng.backlog_age() == float("inf")
        now = time.monotonic()
        # a snapshot already older than the ttl DECAYS at read time: a
        # dead feed must not pin phantom depth that misroutes JSQ, and
        # reading the depth never blocks on a scrape
        eng.update_backlog({b: 3.0}, at=now - eng._lane_ttl_s - 0.1)
        assert eng.bucket_depth(b) == 0
        assert eng.backlog_age() > eng._lane_ttl_s
        # a fresh snapshot replaces the stale one
        eng.update_backlog({b: 5.0}, at=now)
        assert eng.bucket_depth(b) == 5
        assert eng.backlog_age() < 0.5
        # an OLDER snapshot must never override a newer one...
        eng.update_backlog({b: 99.0}, at=now - 0.2)
        assert eng.bucket_depth(b) == 5
        # ...and a future stamp is clamped to now (honest age)
        eng.update_backlog({b: 7.0}, at=now + 100.0)
        assert eng.bucket_depth(b) == 7
        assert eng.backlog_age() < 1.0
    finally:
        eng.close()
        _stop_agent(ag, srv)
