"""persistlint + crashsim contract tests (ISSUE 12 tentpole), mirroring
``tests/test_graphlint.py`` / ``tests/test_threadlint.py``:

* the SHIPPED tree is clean — zero unwaived persistlint findings over
  ``mx_rcnn_tpu``, every waiver reasoned;
* the fixture (``tests/fixtures/ft/persistlint_bad.py``) trips EVERY PL
  rule — the linter cannot silently lose a rule;
* behavioral tests per rule (durable-path inference through naming
  helpers, the staging-write exemption, rename/fsync ordering, the
  manifest-last rule, tmp cleanup, sort_keys pinning, waivers);
* the crashsim runtime twin: op-log capture of the real atomic-write
  idiom, fsync/dir-fsync barrier semantics under enumeration (forced
  vs in-flight vs torn), recover-or-refuse verdicts on the real
  snapshotter/bulk recovery paths, the runrec summary/events crash
  contract, and PLANTED-violation sensitivity (a removed-fsync arm
  must be flagged — zero-sensitivity is a failure);
* the export store commits through the shared ``_atomic_write`` with
  the pinned syscall order (the satellite-1 regression mirroring
  ``test_checkpoint.py — test_atomic_write_discipline``).
"""

import json
import os
import textwrap

import numpy as np
import pytest

from mx_rcnn_tpu.analysis import persistlint
from mx_rcnn_tpu.analysis.crashsim import (CrashRecorder, crash_states,
                                           simulate)
from mx_rcnn_tpu.analysis.persistlint import RULES, lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "mx_rcnn_tpu")
FIXTURE = os.path.join(REPO, "tests", "fixtures", "ft",
                       "persistlint_bad.py")


# ---------------------------------------------------------------------------
# static pass: the shipped tree + the fixture
# ---------------------------------------------------------------------------

def test_shipped_tree_has_zero_unwaived_findings():
    findings = lint_paths([PKG])
    active = [f for f in findings if f.waived is None]
    assert active == [], "\n".join(f.render() for f in active)
    for f in findings:
        if f.waived is not None:
            assert f.waived.strip(), f.render()


def test_cli_exit_codes(capsys):
    assert persistlint.main([PKG]) == 0
    assert persistlint.main([FIXTURE]) == 1
    assert persistlint.main(["--list-rules"]) == 0
    assert persistlint.main([os.path.join(REPO, "no_such_dir")]) == 2
    capsys.readouterr()


def test_fixture_trips_every_rule():
    findings = lint_paths([FIXTURE])
    codes = {f.code for f in findings}
    assert codes == set(RULES), (
        f"missing: {set(RULES) - codes}, unexpected: {codes - set(RULES)}")
    # the reasonless PL103 waiver silences its finding but raises PL001
    assert any(f.code == "PL103" and f.waived is not None
               for f in findings)
    assert any(f.code == "PL001" for f in findings)


def _lint_snippet(tmp_path, source, name="snippet.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return lint_paths([str(p)])


def test_durable_path_inference_through_naming_helper(tmp_path):
    """The call-graph closure: an open() of helper(x) is durable when
    the HELPER's return expression carries a durable fragment."""
    findings = _lint_snippet(tmp_path, """\
        def ckpt_path(prefix, epoch):
            return f"{prefix}-{epoch:04d}.ckpt"

        def save(prefix, epoch, data):
            with open(ckpt_path(prefix, epoch), "wb") as f:
                f.write(data)
        """)
    assert [f.code for f in findings] == ["PL101"]


def test_ephemeral_writes_are_not_flagged(tmp_path):
    """Bench reports / rerunnable artifacts sit OUTSIDE the durable
    surface — the triage line the docs argue."""
    findings = _lint_snippet(tmp_path, """\
        import json

        def write_report(out, record):
            with open(out, "w") as f:
                json.dump(record, f, indent=1)

        def write_eval_dump(path, blob):
            with open("results/dets.pkl", "wb") as f:
                f.write(blob)
        """)
    assert findings == []


def test_staging_write_is_exempt_but_ordering_rules_fire(tmp_path):
    """An open() whose path is later an os.replace SOURCE is the staging
    write of the atomic idiom — no PL101; PL102/PL103 govern it."""
    findings = _lint_snippet(tmp_path, """\
        import os

        def commit(path, data):
            tmp = path + ".manifest.json.tmp"
            try:
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, path + ".manifest.json")
            except OSError:
                os.unlink(tmp)
                raise
        """)
    assert sorted(f.code for f in findings) == ["PL102", "PL103"]


def test_full_atomic_idiom_is_clean(tmp_path):
    findings = _lint_snippet(tmp_path, """\
        import os

        def _atomic_write(path, data):
            tmp = path + ".tmp"
            try:
                with open(tmp, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        """)
    assert findings == []


def test_atomic_channel_calls_are_not_raw_writes(tmp_path):
    """A function routing a durable path through (a transitive caller
    of) _atomic_write is clean, and manifest-last ordering via the
    closure is enforced (PL104)."""
    good = _lint_snippet(tmp_path, """\
        def _atomic_write(path, data):
            pass

        def write_manifest(path, data):
            _atomic_write(path + ".manifest.json", data)

        def commit(path, data):
            _atomic_write(path, data)
            write_manifest(path, b"{}")
        """)
    assert good == []
    bad = _lint_snippet(tmp_path, """\
        def _atomic_write(path, data):
            pass

        def write_manifest(path, data):
            _atomic_write(path + ".manifest.json", data)

        def commit(path, data):
            write_manifest(path, b"{}")
            _atomic_write(path, data)
        """, name="bad.py")
    assert [f.code for f in bad] == ["PL104"]


def test_pl102_one_fsync_does_not_vouch_for_a_second_staged_file(
        tmp_path):
    """An fsync bound to staged file A must not clear the rename of
    staged file B (fsync is per-file; code-review regression)."""
    findings = _lint_snippet(tmp_path, """\
        import os

        def commit_two(data):
            tmp1 = "out/a.ckpt.tmp"
            tmp2 = "out/b.ckpt.tmp"
            try:
                with open(tmp1, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                with open(tmp2, "wb") as g:
                    g.write(data)
                os.replace(tmp1, "out/a.ckpt")
                os.replace(tmp2, "out/b.ckpt")
                dfd = os.open("out", os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except OSError:
                os.unlink(tmp1)
                os.unlink(tmp2)
                raise
        """)
    assert [f.code for f in findings] == ["PL102"], findings
    # and it anchors at tmp2's rename (line 14), not tmp1's (line 13)
    assert findings[0].line == 14, findings[0].render()


def test_pl201_sorted_dump_clean_unsorted_flagged(tmp_path):
    bad = _lint_snippet(tmp_path, """\
        import hashlib
        import json

        def fingerprint(ident):
            return hashlib.sha256(json.dumps(ident).encode()).hexdigest()
        """)
    assert [f.code for f in bad] == ["PL201"]
    good = _lint_snippet(tmp_path, """\
        import hashlib
        import json

        def fingerprint(ident):
            return hashlib.sha256(
                json.dumps(ident, sort_keys=True).encode()).hexdigest()
        """, name="good.py")
    assert good == []


def test_waiver_with_reason_silences(tmp_path):
    findings = _lint_snippet(tmp_path, """\
        def append_events(path):
            # persistlint: disable=PL101 line-granular stream, readers tolerate a torn tail
            f = open("runs/x/events.jsonl", "a")
            f.write("{}")
        """)
    active = [f for f in findings if f.waived is None]
    assert active == []
    assert any(f.code == "PL101" and f.waived for f in findings)


def test_list_rules_names_every_code(capsys):
    persistlint.main(["--list-rules"])
    out = capsys.readouterr().out
    for code in RULES:
        assert code in out


# ---------------------------------------------------------------------------
# crashsim: op-log capture
# ---------------------------------------------------------------------------

def test_recorder_captures_atomic_write_op_sequence(tmp_path):
    from mx_rcnn_tpu.utils.checkpoint import _atomic_write

    root = str(tmp_path / "w")
    os.makedirs(root)
    with CrashRecorder(root) as rec:
        _atomic_write(os.path.join(root, "a.ckpt"), b"payload")
        rec.mark_commit("a")
    kinds = [op.kind for op in rec.ops]
    assert kinds == ["write", "fsync", "rename", "dirfsync", "commit"]
    assert rec.ops[0].data == b"payload"
    assert rec.ops[2].dst.endswith("a.ckpt")
    # arming is fully reversible
    import builtins
    assert builtins.open is not None and not rec._armed


def test_recorder_ignores_out_of_root_and_drop_modes(tmp_path):
    from mx_rcnn_tpu.utils.checkpoint import _atomic_write

    root = str(tmp_path / "w")
    os.makedirs(root)
    outside = str(tmp_path / "elsewhere.ckpt")
    with CrashRecorder(root, drop=("fsync", "dirfsync")) as rec:
        _atomic_write(outside, b"x")                      # not under root
        _atomic_write(os.path.join(root, "a.ckpt"), b"y")
    kinds = [op.kind for op in rec.ops]
    assert "fsync" not in kinds and "dirfsync" not in kinds
    assert kinds == ["write", "rename"]
    assert all(op.path.startswith(root) for op in rec.ops)
    # the real syscalls still ran: both files exist complete
    assert open(outside, "rb").read() == b"x"


# ---------------------------------------------------------------------------
# crashsim: barrier / reordering semantics of the enumerator
# ---------------------------------------------------------------------------

def _states(ops, root):
    return [st for st in crash_states(ops, root)
            if st.decisions != ("CAPPED",)]


def test_unfsynced_write_can_drop_or_tear_fsynced_is_forced(tmp_path):
    from mx_rcnn_tpu.analysis.crashsim import Op

    root = str(tmp_path)
    f1 = os.path.join(root, "f1")
    ops = [Op("write", path=f1, data=b"AAAABBBB"),
           Op("fsync", path=f1)]
    # crash right after the write, before the barrier: absent, torn and
    # full variants all reachable
    pre = [st.fs.get("f1") for st in _states(ops[:1], root)]
    assert None in pre and b"AAAABBBB" in pre and b"AAAA" in pre
    # after the fsync: forced — exactly one full state at that point
    post = [st.fs.get("f1") for st in _states(ops, root)
            if st.point == 2]
    assert post == [b"AAAABBBB"]


def test_rename_before_dirfsync_droppable_after_forced(tmp_path):
    from mx_rcnn_tpu.analysis.crashsim import Op

    root = str(tmp_path)
    tmp, dst = os.path.join(root, "x.tmp"), os.path.join(root, "x")
    ops = [Op("write", path=tmp, data=b"D" * 8),
           Op("fsync", path=tmp),
           Op("rename", path=tmp, dst=dst),
           Op("dirfsync", path=root)]
    # between rename and dirfsync the rename is in flight: states both
    # with and without the published name
    mid = [st.fs for st in _states(ops[:3], root) if st.point == 3]
    assert any("x" in fs for fs in mid) and any("x" not in fs
                                                for fs in mid)
    # after the dirfsync the publish is forced everywhere
    post = [st.fs for st in _states(ops, root) if st.point == 4]
    assert post and all(fs.get("x") == b"D" * 8 for fs in post)


def test_rename_persisting_without_unfsynced_data_is_torn_publish(
        tmp_path):
    """The classic ALICE state: the dir entry makes it, the data does
    not — reachable exactly when the source was never fsynced."""
    from mx_rcnn_tpu.analysis.crashsim import Op

    root = str(tmp_path)
    tmp, dst = os.path.join(root, "x.tmp"), os.path.join(root, "x")
    ops = [Op("write", path=tmp, data=b"D" * 8),
           Op("rename", path=tmp, dst=dst)]
    fss = [st.fs for st in _states(ops, root) if st.point == 2]
    assert any(fs.get("x") == b"" for fs in fss), \
        "torn publish (rename without data) must be enumerated"


def test_verdict_engine_flags_refusal_after_commit(tmp_path):
    """simulate(): refusing while a durable floor exists is a
    violation; refusing before any commit is legal."""
    from mx_rcnn_tpu.analysis.crashsim import Op

    root = str(tmp_path)
    f1 = os.path.join(root, "art")
    # no barriers at all, then a commit marker: the classic planted bug
    ops = [Op("write", path=f1, data=b"A" * 8),
           Op("commit", ident="a")]
    scratch = str(tmp_path / "_s")

    def recover(d):
        p = os.path.join(d, "art")
        if os.path.exists(p) and open(p, "rb").read() == b"A" * 8:
            return ("recovered", "a")
        return ("refused", "artifact missing or torn")

    rep = simulate(ops, root, recover, ["a"], scratch)
    assert not rep["ok"]
    assert any("durably committed" in v["problem"]
               for v in rep["violations"])

    # an UNTYPED crash in the recovery path is a recorded violation,
    # never an aborted enumeration (code-review regression)
    def crashy_recover(d):
        raise AttributeError("manifest shape surprised the loader")

    rep_crash = simulate(ops, root, crashy_recover, ["a"], scratch)
    assert not rep_crash["ok"]
    assert any("UNTYPED exception" in v["problem"]
               for v in rep_crash["violations"])
    # with the barrier the same workload is clean
    ops_ok = [Op("write", path=f1, data=b"A" * 8),
              Op("fsync", path=f1), Op("commit", ident="a")]
    rep_ok = simulate(ops_ok, root, recover, ["a"], scratch)
    assert rep_ok["ok"] and rep_ok["states_total"] > 0


# ---------------------------------------------------------------------------
# crashsim: the real recovery paths (tool workloads, gate-speed slices)
# ---------------------------------------------------------------------------

def test_snapshotter_workload_recovers_or_refuses_every_state(tmp_path):
    from mx_rcnn_tpu.tools.crashsim import run_snapshotter

    rep = run_snapshotter(str(tmp_path / "w"), max_states=64)
    assert rep["ok"], rep["violations"][:3]
    assert rep["states_total"] > 50
    assert rep["recovered"] > 0 and rep["refused"] > 0


def test_bulk_workload_recovers_or_refuses_every_state(tmp_path):
    from mx_rcnn_tpu.tools.crashsim import run_bulk

    rep = run_bulk(str(tmp_path / "w"), max_states=64)
    assert rep["ok"], rep["violations"][:3]
    assert rep["states_total"] > 20


def test_planted_removed_fsync_arm_is_flagged(tmp_path):
    """Sensitivity: the snapshotter workload with its fsync barriers
    removed from the log MUST produce recover-or-refuse violations —
    a crashsim that passes this arm is a rubber stamp."""
    from mx_rcnn_tpu.tools.crashsim import run_snapshotter

    rep = run_snapshotter(str(tmp_path / "w"),
                          drop=("fsync", "dirfsync"), max_states=16)
    assert not rep["ok"]
    assert any("durably committed" in v["problem"]
               for v in rep["violations"])


def test_export_store_missing_dirfsync_arm_reproduces_old_bug(tmp_path):
    """The pre-ISSUE-12 ``ExportStore.finish`` skipped the dir-fsync;
    the dirfsync-dropped arm reproduces the lost-commit state crashsim
    exists to catch (and the fixed code's real arm is clean — covered
    by crashsim-smoke, which runs the full export workload)."""
    from mx_rcnn_tpu.tools.crashsim import run_export

    rep = run_export(str(tmp_path / "w"), drop=("dirfsync",),
                     max_states=32)
    assert not rep["ok"]
    assert any(v["floor"] == "store" for v in rep["violations"])


# ---------------------------------------------------------------------------
# satellite regressions
# ---------------------------------------------------------------------------

def test_export_store_commit_syscall_order(tmp_path, monkeypatch):
    """Satellite 1: ExportStore.add/finish route through the SHARED
    ``_atomic_write`` — fsync(file) → replace → fsync(dir) for the
    program AND the manifest (mirrors test_checkpoint.py's
    test_atomic_write_discipline; the manifest commit previously
    skipped the dir fsync)."""
    import jax

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.serve.export import ExportStore

    cfg = generate_config("tiny", "synthetic")
    events = []
    real_fsync, real_replace, real_open = os.fsync, os.replace, os.open
    real_close = os.close
    fd_kind = {}

    def spy_open(path, flags, *a, **kw):
        fd = real_open(path, flags, *a, **kw)
        if isinstance(path, (str, os.PathLike)):
            fd_kind[fd] = "dir" if os.path.isdir(path) else "file"
        return fd

    def spy_close(fd):
        # fd numbers are recycled: a closed dir fd must not mislabel
        # the next regular file that lands on the same number
        fd_kind.pop(fd, None)
        return real_close(fd)

    def spy_fsync(fd):
        events.append(("fsync", fd_kind.get(fd, "file")))
        return real_fsync(fd)

    def spy_replace(src, dst):
        events.append(("replace", os.path.basename(dst)))
        return real_replace(src, dst)

    fn = jax.jit(lambda v: v * 2.0)
    x = np.arange(4, dtype=np.float32)
    store = ExportStore.create(str(tmp_path / "store"), cfg)
    monkeypatch.setattr(os, "open", spy_open)
    monkeypatch.setattr(os, "close", spy_close)
    monkeypatch.setattr(os, "fsync", spy_fsync)
    monkeypatch.setattr(os, "replace", spy_replace)
    store.add("double", fn, (x,))
    store.finish()
    monkeypatch.undo()
    assert events == [
        ("fsync", "file"), ("replace", "double.jaxexp"), ("fsync", "dir"),
        ("fsync", "file"), ("replace", "manifest.json"), ("fsync", "dir"),
    ], events
    # and the committed store still loads + admits
    store2 = ExportStore(str(tmp_path / "store"))
    store2.check(cfg)
    got = np.asarray(store2.load("double")(x))
    np.testing.assert_array_equal(got, x * 2.0)


def test_runrec_summary_atomic_and_events_line_granular(tmp_path):
    """Satellite 3 via crashsim: across EVERY crash state of a runrec
    session, summary.json is all-or-nothing (atomic write) and
    events.jsonl honors the line-granular contract — every complete
    line parses and is a prefix of the true event stream; only the
    tail line may tear."""
    from mx_rcnn_tpu.obs.runrec import RunRecord

    root = str(tmp_path / "w")
    os.makedirs(root)
    with CrashRecorder(root) as rec:
        r = RunRecord("crashsimtest", base_dir=os.path.join(root, "runs"),
                      run_id="r1")
        for i in range(5):
            r.event("tick", i=i)
        r.finish(metric="ticks", value=5, registry=_EmptyRegistry())
        r.close()
        rec.mark_commit("final")
    run_dir = os.path.join("runs", "r1")
    true_lines = None
    problems = []

    def recover(d):
        nonlocal true_lines
        sp = os.path.join(d, run_dir, "summary.json")
        ep = os.path.join(d, run_dir, "events.jsonl")
        if os.path.exists(ep):
            raw = open(ep, "rb").read().decode("utf-8", "replace")
            complete = raw.split("\n")[:-1]
            for ln in complete:
                try:
                    json.loads(ln)
                except ValueError:
                    return ("corrupt", f"complete event line torn: {ln!r}")
        if not os.path.exists(sp):
            return ("refused", "no summary yet")
        try:
            summary = json.load(open(sp))
        except ValueError:
            return ("corrupt", "summary.json torn — atomicity violated")
        assert summary["value"] == 5
        return ("recovered", "final")

    rep = simulate(rec.ops, root, recover, ["final"],
                   str(tmp_path / "_s"))
    assert rep["ok"], rep["violations"][:3]
    assert rep["states_total"] > 5


class _EmptyRegistry:
    def snapshot(self):
        return {}
