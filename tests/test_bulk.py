"""Bulk-inference plane tests (ISSUE 11): StreamTestLoader eval-mode
plan, the prepared-admission seam, sink atomicity + misaligned-cursor
rejection, kill-mid-corpus resume bit-identity, and the exactly-once
accounting invariant under a replica eject.

Runner/fleet tests use the content-dependent stub
(``loadgen.make_content_stub_run_fn`` — every output row a pure
function of its own pixels, so byte-identity comparisons are
meaningful) over millisecond stub replicas: no model compiles anywhere
in this file.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import load_gt_roidb
from mx_rcnn_tpu.data.image import imread_rgb, resize_to_bucket
from mx_rcnn_tpu.data.loader import StreamTestLoader
from mx_rcnn_tpu.serve.bulk import (BulkAborted, BulkRunner, BulkSink,
                                    BulkSinkMismatch, auto_inflight,
                                    corpus_fingerprint, detections_line,
                                    make_sink_manifest)
from mx_rcnn_tpu.serve.engine import ServingEngine
from mx_rcnn_tpu.serve.fleet import build_fleet
from mx_rcnn_tpu.tools.loadgen import make_content_stub_run_fn


def _cfg(tmp_root, **kw):
    over = dict(
        dataset__root_path=str(tmp_root),
        dataset__dataset_path=os.path.join(str(tmp_root), "synthetic"),
        bucket__scale=128, bucket__max_size=160,
        bucket__shapes=((128, 160), (160, 128)),
        test__rpn_pre_nms_top_n=512, test__rpn_post_nms_top_n=64,
        serve__batch_size=2, serve__max_delay_ms=5.0,
        fleet__replicas=2, fleet__health_interval_s=0.2,
        bulk__shard_batches=2, data__streaming=True)
    over.update(kw)
    return generate_config("tiny", "synthetic", **over)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """(cfg, roidb): a 16-image 128x160 synthetic corpus on disk."""
    root = tmp_path_factory.mktemp("bulk_data")
    cfg = _cfg(root)
    _, roidb = load_gt_roidb(cfg, training=True, flip=False,
                             num_images=16, image_size=(128, 160),
                             max_objects=2)
    return cfg, roidb


def _stub_predictor(cfg):
    from mx_rcnn_tpu.core.tester import Predictor

    return Predictor(None, {}, cfg)


def _stub_fleet(cfg, model_ms=0.0, run_fn=None):
    factory = (lambda rid: run_fn) if run_fn is not None else (
        lambda rid: make_content_stub_run_fn(cfg, model_ms))
    return build_fleet(cfg, None, {}, run_fn_factory=factory)


def _run_bulk(cfg, roidb, sink_dir, router=None, fault=None, seed=0,
              batch_images=2):
    own = router is None
    if own:
        router = _stub_fleet(cfg)
    try:
        loader = StreamTestLoader(roidb, cfg, batch_images=batch_images,
                                  shuffle=False, seed=seed,
                                  raw_images=False, num_workers=0)
        sink = BulkSink(str(sink_dir),
                        make_sink_manifest(cfg, roidb, seed, batch_images))
        return BulkRunner(router, loader, sink, cfg, fault=fault).run()
    finally:
        if own:
            router.close()


# ---------------------------------------------------------------------------
# StreamTestLoader: eval-mode plan
# ---------------------------------------------------------------------------

def test_stream_test_loader_covers_every_image_once(corpus):
    cfg, roidb = corpus
    loader = StreamTestLoader(roidb, cfg, batch_images=3, shuffle=False,
                              num_workers=0)
    # 16 images / batch 3 → 5 full + 1 tail of 1: the tail StreamLoader
    # would drop must be a partial final batch here
    plan = loader._plan(0, 3)
    assert [len(idx) for _, idx in plan] == [3, 3, 3, 3, 3, 1]
    assert len(loader) == 6
    seen = []
    loader.set_epoch(0)
    for batch, indices, scales in loader:
        assert batch.images.shape[0] == len(indices) == len(scales)
        assert batch.images.dtype == np.uint8  # raw_images default
        seen.extend(indices)
    assert sorted(seen) == list(range(16))


def test_stream_test_loader_skip_batches_resumes_identically(corpus):
    cfg, roidb = corpus
    mk = lambda: StreamTestLoader(roidb, cfg, batch_images=3,  # noqa: E731
                                  shuffle=False, num_workers=0)
    full, resumed = mk(), mk()
    full.set_epoch(0)
    ref = [(idx, b.im_info.copy()) for b, idx, _ in full]
    resumed.set_epoch(0)
    resumed.skip_next_batches(2)
    got = [(idx, b.im_info.copy()) for b, idx, _ in resumed]
    assert [i for i, _ in got] == [i for i, _ in ref[2:]]
    for (_, a), (_, b) in zip(got, ref[2:]):
        np.testing.assert_array_equal(a, b)


def test_loader_fp32_rows_match_serve_preprocess(corpus):
    """The prepared-admission contract: a raw_images=False loader row is
    BIT-identical to what ``ServingEngine.preprocess`` would build for
    the same source image — submit_prepared may skip the resize."""
    cfg, roidb = corpus
    loader = StreamTestLoader(roidb, cfg, batch_images=2, shuffle=False,
                              raw_images=False, num_workers=0)
    loader.set_epoch(0)
    batch, indices, scales = next(iter(loader))
    for j, i in enumerate(indices):
        img = imread_rgb(roidb[i]["image"])
        canvas, im_scale, bucket = resize_to_bucket(
            img, cfg.network.pixel_means, cfg.bucket.scale,
            cfg.bucket.max_size, [tuple(b) for b in cfg.bucket.shapes])
        assert bucket == tuple(batch.images.shape[1:3])
        np.testing.assert_array_equal(batch.images[j], canvas)
        assert batch.im_info[j][2] == np.float32(im_scale)


# ---------------------------------------------------------------------------
# prepared admission seam
# ---------------------------------------------------------------------------

def test_submit_prepared_matches_submit(corpus):
    cfg, roidb = corpus
    run_fn = make_content_stub_run_fn(cfg)
    engine = ServingEngine(_stub_predictor(cfg), cfg, run_fn=run_fn)
    try:
        img = imread_rgb(roidb[0]["image"])
        via_submit = engine.detect(img, timeout_ms=0)
        data, im_info, bucket = engine.preprocess(img)
        via_prepared = engine.submit_prepared(
            data, im_info, bucket, timeout_ms=0).wait(timeout=20.0)
        assert sorted(via_submit) == sorted(via_prepared)
        for c in via_submit:
            np.testing.assert_array_equal(via_submit[c], via_prepared[c])
    finally:
        engine.close()


def test_submit_prepared_refuses_wrong_shape_and_bucket(corpus):
    cfg, _ = corpus
    engine = ServingEngine(_stub_predictor(cfg), cfg,
                           run_fn=make_content_stub_run_fn(cfg),
                           start=False)
    info = np.array([128, 160, 1.0], np.float32)
    with pytest.raises(ValueError, match="float32"):
        engine.submit_prepared(
            np.zeros((128, 160, 3), np.uint8), info, (128, 160))
    with pytest.raises(ValueError, match="bucket"):
        engine.submit_prepared(
            np.zeros((64, 64, 3), np.float32), info, (64, 64))


# ---------------------------------------------------------------------------
# sink: manifest admission + atomic commits
# ---------------------------------------------------------------------------

def test_sink_manifest_mismatch_rejected(tmp_path, corpus):
    cfg, roidb = corpus
    m = make_sink_manifest(cfg, roidb, seed=0, batch_images=2)
    BulkSink(str(tmp_path), m)
    BulkSink(str(tmp_path), dict(m))  # identical recipe resumes fine
    with pytest.raises(BulkSinkMismatch, match="batch_images"):
        BulkSink(str(tmp_path),
                 make_sink_manifest(cfg, roidb, seed=0, batch_images=4))
    with pytest.raises(BulkSinkMismatch, match="corpus"):
        BulkSink(str(tmp_path),
                 make_sink_manifest(cfg, roidb[:8], seed=0,
                                    batch_images=2))
    # different WEIGHTS may not resume this sink (they would splice two
    # models' detections) ...
    with pytest.raises(BulkSinkMismatch, match="model|corpus"):
        BulkSink(str(tmp_path),
                 make_sink_manifest(cfg, roidb, seed=0, batch_images=2,
                                    model="ckpt/e2e@5"))
    # ... nor may a different proposal-stage size (different programs,
    # different detections)
    with pytest.raises(BulkSinkMismatch, match="rpn_pre_nms|corpus"):
        BulkSink(str(tmp_path), make_sink_manifest(
            cfg.replace_in("test", rpn_pre_nms_top_n=128), roidb,
            seed=0, batch_images=2))


def test_corpus_fingerprint_tracks_recipe(corpus):
    cfg, roidb = corpus
    base = corpus_fingerprint(cfg, roidb, 0, 2)
    assert base == corpus_fingerprint(cfg, roidb, 0, 2)
    assert base != corpus_fingerprint(cfg, roidb, 1, 2)
    assert base != corpus_fingerprint(cfg, roidb[:-1], 0, 2)
    qcfg = cfg.replace_in("quant", enabled=True)
    assert base != corpus_fingerprint(qcfg, roidb, 0, 2)


def test_sink_commit_prefix_and_tmp_cleanup(tmp_path, corpus):
    cfg, roidb = corpus
    m = make_sink_manifest(cfg, roidb, 0, 2)
    sink = BulkSink(str(tmp_path), m)
    assert sink.committed_shards() == 0
    sink.commit(0, [detections_line(0, {1: np.ones((1, 5))})])
    sink.commit(1, [detections_line(1, {})])
    assert sink.committed_shards() == 2
    # an orphaned tmp (pre-rename kill) is cleaned at reopen, never data
    orphan = os.path.join(str(tmp_path), "shard-00002.jsonl.tmp")
    with open(orphan, "w") as f:
        f.write("torn")
    sink2 = BulkSink(str(tmp_path), m)
    assert not os.path.exists(orphan)
    assert sink2.committed_shards() == 2
    # a gap means foreign interference: refuse, don't guess
    with open(os.path.join(str(tmp_path), "shard-00005.jsonl"), "w"):
        pass
    with pytest.raises(BulkSinkMismatch, match="non-contiguous"):
        sink2.committed_shards()


# ---------------------------------------------------------------------------
# runner: exactly-once accounting + resume bit-identity
# ---------------------------------------------------------------------------

def test_bulk_exactly_once_accounting(tmp_path, corpus):
    cfg, roidb = corpus
    stats = _run_bulk(cfg, roidb, tmp_path / "sink")
    assert stats["planned_images"] == 16
    assert stats["accounted_images"] == 16
    assert stats["lost"] == 0
    sink = BulkSink(str(tmp_path / "sink"))
    seen = []
    for k in range(sink.committed_shards()):
        for line in sink.read_lines(k):
            rec = json.loads(line)
            seen.append(rec["i"])
            assert "dets" in rec
    assert sorted(seen) == sorted(
        int(r.get("index", -1)) for r in roidb)


def test_kill_mid_corpus_resume_is_bit_identical(tmp_path, corpus):
    cfg, roidb = corpus
    _run_bulk(cfg, roidb, tmp_path / "control")

    class _Stop(Exception):
        pass

    def fault(shard):
        if shard == 1:
            raise _Stop()  # in-process stand-in for the SIGKILL rig

    with pytest.raises(_Stop):
        _run_bulk(cfg, roidb, tmp_path / "kr", fault=fault)
    killed = BulkSink(str(tmp_path / "kr"))
    assert killed.committed_shards() == 2  # shards 0..1 landed, then died
    stats = _run_bulk(cfg, roidb, tmp_path / "kr")  # resume
    assert stats["resumed_shards"] == 2
    assert stats["accounted_images"] == 16
    ctrl = BulkSink(str(tmp_path / "control"))
    assert ctrl.committed_shards() == killed.committed_shards()
    for k in range(ctrl.committed_shards()):
        a = open(ctrl.shard_path(k), "rb").read()
        b = open(killed.shard_path(k), "rb").read()
        assert a == b, f"shard {k} differs after kill+resume"


def test_misaligned_cursor_rejected_at_resume(tmp_path, corpus):
    cfg, roidb = corpus
    _run_bulk(cfg, roidb, tmp_path / "sink")
    with pytest.raises(BulkSinkMismatch):
        _run_bulk(cfg, roidb, tmp_path / "sink", batch_images=4)


def test_accounting_under_replica_eject_mid_corpus(tmp_path, corpus):
    """A replica dies mid-corpus: its stranded work FAILs → the router
    reroutes → the runner's resubmit budget absorbs the transient — and
    the final accounting still reads N in = N accounted, with the sink
    byte-identical to an undisturbed control (per-image determinism
    means an eject may change WHO scored an image, never the bytes)."""
    cfg, roidb = corpus
    _run_bulk(cfg, roidb, tmp_path / "control", batch_images=2)

    router = _stub_fleet(cfg, model_ms=20.0)
    try:
        def fault(shard):
            if shard == 0:  # mid-corpus: shards 1.. still to score
                router.manager.replicas[0].engine.kill()

        stats = _run_bulk(cfg, roidb, tmp_path / "ejected",
                          router=router, fault=fault)
        deadline = time.monotonic() + 10.0
        while router.manager.ejects == 0 and time.monotonic() < deadline:
            router.manager.tick()
            time.sleep(0.05)
        assert router.manager.ejects >= 1
        assert stats["accounted_images"] == stats["planned_images"] == 16
        assert stats["lost"] == 0
    finally:
        router.close()
    ctrl, ej = BulkSink(str(tmp_path / "control")), \
        BulkSink(str(tmp_path / "ejected"))
    assert ej.committed_shards() == ctrl.committed_shards()
    for k in range(ctrl.committed_shards()):
        assert (open(ctrl.shard_path(k), "rb").read()
                == open(ej.shard_path(k), "rb").read())


def test_unservable_image_aborts_instead_of_dropping(tmp_path, corpus):
    cfg, roidb = corpus
    cfg = cfg.replace_in("bulk", retries=1)
    cfg = cfg.replace_in("fleet", relaunch=False, reroute_retries=0)
    router = _stub_fleet(cfg)
    try:
        for r in router.manager.replicas:
            r.engine.kill()  # nothing left to serve
        with pytest.raises(BulkAborted):
            _run_bulk(cfg, roidb, tmp_path / "sink", router=router)
    finally:
        router.close()


# ---------------------------------------------------------------------------
# sink atomicity under a REAL SIGKILL
# ---------------------------------------------------------------------------

def test_sink_atomic_under_sigkill(tmp_path, corpus):
    """A real SIGKILL mid-run leaves exactly a contiguous committed
    prefix — every landed shard complete and parseable, no torn files —
    and a fresh process resumes it to a complete sink."""
    cfg, roidb = corpus
    data_root = cfg.dataset.root_path
    script = tmp_path / "child.py"
    script.write_text(textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {os.getcwd()!r})
        from tests.test_bulk import _cfg, _run_bulk
        from mx_rcnn_tpu.data import load_gt_roidb
        from mx_rcnn_tpu.tools.bulk import parse_fault

        cfg = _cfg({data_root!r})
        _, roidb = load_gt_roidb(cfg, training=True, flip=False,
                                 num_images=16, image_size=(128, 160),
                                 max_objects=2)
        fault = parse_fault(sys.argv[2] if len(sys.argv) > 2 else "")
        _run_bulk(cfg, roidb, sys.argv[1], fault=fault)
    """))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    sink_dir = tmp_path / "sink"
    out = subprocess.run(
        [sys.executable, str(script), str(sink_dir), "kill@shard=1"],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == -signal.SIGKILL, out.stderr[-2000:]
    sink = BulkSink(str(sink_dir))
    n = sink.committed_shards()
    assert n == 2
    for k in range(n):
        for line in sink.read_lines(k):
            json.loads(line)  # every committed line is complete JSON
    # resume in a fresh process → complete, exactly-once
    out = subprocess.run(
        [sys.executable, str(script), str(sink_dir)],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    seen = []
    sink = BulkSink(str(sink_dir))
    for k in range(sink.committed_shards()):
        seen += [json.loads(ln)["i"] for ln in sink.read_lines(k)]
    assert sorted(seen) == sorted(int(r.get("index", -1)) for r in roidb)


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------

def test_bulk_config_section_and_auto_inflight():
    cfg = generate_config("tiny", "synthetic", bulk__max_inflight=7,
                          bulk__shard_batches=8, bulk__retries=3)
    assert cfg.bulk.max_inflight == 7
    assert auto_inflight(cfg) == 7
    cfg = generate_config("tiny", "synthetic", fleet__replicas=2,
                          serve__batch_size=4, serve__shed_watermark=32)
    # auto: 2 batches x 2 replicas, under the watermark
    assert auto_inflight(cfg) == 16
    cfg = generate_config("tiny", "synthetic", fleet__replicas=8,
                          serve__batch_size=8, serve__shed_watermark=16)
    assert auto_inflight(cfg) == 15  # clamped under the lane watermark
