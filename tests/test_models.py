"""Model zoo tests: shapes, strides, dtype policy, full test-mode forward."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.models.resnet import ResNetBackbone
from mx_rcnn_tpu.models.rpn import RPNHead
from mx_rcnn_tpu.models.vgg import VGGBackbone

KEY = jax.random.PRNGKey(0)


@pytest.mark.slow
def test_vgg_backbone_stride16():
    m = VGGBackbone()
    x = jnp.zeros((1, 64, 96, 3))
    v = m.init(KEY, x)
    y = m.apply(v, x)
    assert y.shape == (1, 4, 6, 512)
    # VGG has no BN — no batch_stats collection
    assert "batch_stats" not in v


@pytest.mark.slow
def test_resnet_backbone_stride16_and_width():
    m = ResNetBackbone(depth=50)
    x = jnp.zeros((1, 64, 64, 3))
    v = m.init(KEY, x)
    y = m.apply(v, x)
    assert y.shape == (1, 4, 4, 1024)
    assert "batch_stats" in v  # frozen BN stats present


def test_resnet101_param_structure():
    m = ResNetBackbone(depth=101)
    v = m.init(KEY, jnp.zeros((1, 32, 32, 3)))
    names = set(v["params"].keys())
    assert "conv0" in names and "stage3_unit23" in names  # 23 units in stage3
    assert "stage3_unit24" not in names


def test_rpn_head_layout():
    m = RPNHead(num_anchors=9)
    feat = jnp.zeros((2, 5, 7, 64))
    v = m.init(KEY, feat)
    cls, box = m.apply(v, feat)
    assert cls.shape == (2, 5 * 7 * 9, 2)
    assert box.shape == (2, 5 * 7 * 9, 4)


def test_bf16_dtype_policy():
    m = ResNetBackbone(depth=50, dtype=jnp.bfloat16)
    x = jnp.zeros((1, 32, 32, 3))
    v = m.init(KEY, x)
    y = m.apply(v, x)
    assert y.dtype == jnp.bfloat16
    # params stay fp32
    leaves = jax.tree.leaves(v["params"])
    assert all(l.dtype == jnp.float32 for l in leaves)


def test_full_model_test_forward_tiny():
    cfg = generate_config("tiny", "PascalVOC")
    model = build_model(cfg)
    images = jnp.zeros((2, 128, 128, 3))
    im_info = jnp.tile(jnp.array([[128.0, 128.0, 1.0]]), (2, 1))
    variables = model.init(KEY, images, im_info)
    rois, roi_valid, cls_prob, deltas = model.apply(variables, images, im_info)
    r = cfg.test.rpn_post_nms_top_n
    assert rois.shape == (2, r, 4)
    assert roi_valid.shape == (2, r)
    assert cls_prob.shape == (2, r, 21)
    assert deltas.shape == (2, r, 84)
    np.testing.assert_allclose(np.asarray(cls_prob.sum(-1)), 1.0, rtol=1e-4)


def test_unknown_network_raises():
    cfg = generate_config("tiny", "PascalVOC")
    from mx_rcnn_tpu.models.faster_rcnn import FasterRCNN
    bad = FasterRCNN(network="alexnet")
    with pytest.raises(ValueError, match="unknown network"):
        bad.init(KEY, jnp.zeros((1, 64, 64, 3)), jnp.zeros((1, 3)))
