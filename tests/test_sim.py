"""Sim tier tests (ISSUE 17): virtual-time kernel determinism, clock
seams through the production control plane, trace-generator
invariants, scorer exactness on a hand-computed mini-trace, the
never-sampled == downed regression, and the policy gauntlet's
discrimination contract (shipped clean, mistuned breaches) on a
seconds-scale mini storm.

Everything here runs in virtual time — no sleeps, no wall-clock
dependence — so the whole file is quick-tier.
"""

import hashlib
import json
import logging
import time

import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.ft.supervisor import RestartPolicy
from mx_rcnn_tpu.obs.collect import Collector, RegistrySource
from mx_rcnn_tpu.obs.health import CRITICAL, HealthEngine, Rule
from mx_rcnn_tpu.obs.metrics import Registry
from mx_rcnn_tpu.obs.timeseries import TimeSeriesStore
from mx_rcnn_tpu.serve.fleet import jsq_key
from mx_rcnn_tpu.serve.scheduler import SchedulerPolicy
from mx_rcnn_tpu.sim.control import MISTUNED_OVERRIDES, SimRun
from mx_rcnn_tpu.sim.kernel import SimKernel, VirtualClock
from mx_rcnn_tpu.sim.score import decision_log_bytes, score_run
from mx_rcnn_tpu.sim.traffic import (SCENARIOS, bucket_weights,
                                     fleet_capacity_rps, generate,
                                     rate_at)
from mx_rcnn_tpu.tools.sim import check_gauntlet

logging.getLogger("mx_rcnn_tpu").setLevel(logging.ERROR)


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------

class TestKernel:
    def test_same_instant_fires_in_scheduling_order(self):
        k = SimKernel(seed=0)
        order = []
        k.at(5.0, lambda: order.append("a"))
        k.at(5.0, lambda: order.append("b"))
        k.at(2.0, lambda: order.append("c"))
        k.run_until(10.0)
        assert order == ["c", "a", "b"]
        assert k.clock.now == 10.0
        assert k.fired == 3

    def test_past_scheduling_clamps_to_now(self):
        k = SimKernel(seed=0)
        k.run_until(7.0)
        fired = []
        k.at(3.0, lambda: fired.append(k.clock.now))
        k.run_until(7.0)
        assert fired == [7.0]  # not time travel

    def test_callback_may_schedule_at_current_instant(self):
        k = SimKernel(seed=0)
        order = []
        def outer():
            order.append("outer")
            k.at(k.clock.now, lambda: order.append("inner"))
        k.at(1.0, outer)
        k.run_until(1.0)
        assert order == ["outer", "inner"]

    def test_rng_substreams_stable_and_independent(self):
        a1 = SimKernel(seed=3).rng("arrivals").random_sample(4)
        a2 = SimKernel(seed=3).rng("arrivals").random_sample(4)
        assert list(a1) == list(a2)
        # a DIFFERENT consumer draws a different stream and never
        # perturbs the first one
        k = SimKernel(seed=3)
        other = k.rng("buckets").random_sample(4)
        assert list(k.rng("arrivals").random_sample(4)) == list(a1)
        assert list(other) != list(a1)
        assert list(SimKernel(seed=4).rng("arrivals").random_sample(4)) \
            != list(a1)


# ---------------------------------------------------------------------------
# clock seams: the production classes on an injected clock
# ---------------------------------------------------------------------------

class TestClockSeams:
    def test_store_and_collector_stamp_virtual_time(self):
        clk = VirtualClock(100.0)
        store = TimeSeriesStore(capacity=8, clock=clk)
        reg = Registry()
        reg.set_gauge("g", 1.0)
        smp = store.sample(reg)
        assert smp["ts"] == 100.0
        coll = Collector([RegistrySource("a", lambda: (reg, {}))],
                         clock=clk)
        clk._now = 107.0
        assert coll.collect()["ts"] == 107.0
        assert store.append_snapshot({"gauges": {}})["ts"] == 107.0

    def test_store_default_clock_is_wall_time(self):
        store = TimeSeriesStore(capacity=4)
        reg = Registry()
        t0 = time.time()
        smp = store.sample(reg)
        assert abs(smp["ts"] - t0) < 5.0

    def test_health_engine_verdict_ts_from_clock(self):
        clk = VirtualClock(42.0)
        store = TimeSeriesStore(capacity=8, clock=clk)
        store.append_snapshot({"gauges": {"x": 1.0}})
        eng = HealthEngine(
            [Rule("r", "x", "gauge", ">", 0.0, severity=CRITICAL)],
            store, clock=clk)
        assert eng.evaluate()["ts"] == 42.0

    def test_scheduler_cooldown_runs_on_injected_clock(self):
        cfg = generate_config(
            "tiny", "synthetic", crosshost__for_samples=1,
            crosshost__cooldown_s=30.0, crosshost__target_replicas=2,
            crosshost__min_replicas=1)
        clk = VirtualClock(0.0)
        pol = SchedulerPolicy(cfg, clock=clk)
        store = TimeSeriesStore(capacity=8, clock=clk)
        store.append_snapshot(
            {"gauges": {"agent.replicas_ready@agent-0": 1.0}})
        act = pol.decide(store)
        assert act is not None and act["action"] == "add"
        # inside the virtual cooldown: silent; after it: acts again
        clk._now = 29.0
        store.append_snapshot(
            {"gauges": {"agent.replicas_ready@agent-0": 1.0}})
        assert pol.decide(store) is None
        clk._now = 31.0
        store.append_snapshot(
            {"gauges": {"agent.replicas_ready@agent-0": 1.0}})
        assert pol.decide(store) is not None

    def test_restart_policy_ready_at_from_clock(self):
        clk = VirtualClock(50.0)
        pol = RestartPolicy(base_s=4.0, factor=2.0, cap_s=60.0,
                            give_up_after=3, seed=1, clock=clk)
        delay, give_up = pol.record(("boom", 1), made_progress=False)
        assert not give_up
        assert pol.ready_at == pytest.approx(50.0 + delay)


# ---------------------------------------------------------------------------
# never-sampled == downed (the missing-gauge deficit path)
# ---------------------------------------------------------------------------

class TestAbsentEqualsDown:
    def test_gauge_window_ages_out_stale_sources(self):
        clk = VirtualClock(0.0)
        store = TimeSeriesStore(capacity=16, clock=clk)
        store.append_snapshot({"gauges": {"g@agent-1": 3.0}})
        for t in (10.0, 20.0, 30.0):
            clk._now = t
            store.append_snapshot({"gauges": {}})  # agent-1 went dark
        # unbounded read keeps the stale value; a windowed read ages it
        # out — indistinguishable from a gauge that never existed
        assert store.gauge("g@agent-1") == 3.0
        assert store.gauge("g@agent-1", window_s=15.0) is None
        assert store.gauge("never-produced", window_s=15.0) is None

    def test_scheduler_deficit_same_for_never_sampled_and_downed(self):
        cfg = generate_config(
            "tiny", "synthetic", crosshost__for_samples=1,
            crosshost__cooldown_s=0.0, crosshost__target_replicas=4,
            crosshost__min_replicas=1)

        def decide_with(gauges):
            clk = VirtualClock(0.0)
            store = TimeSeriesStore(capacity=8, clock=clk)
            store.append_snapshot({"gauges": dict(gauges)})
            return SchedulerPolicy(cfg, clock=clk).decide(store)

        # agent-1 NEVER produced the ready gauge vs. agent-1 produced
        # it in an older sample but is absent from the latest: the
        # policy reads the latest sample only, so both are a deficit
        # of identical size with identical placement
        never = decide_with({"agent.replicas_ready@agent-0": 2.0})
        clk = VirtualClock(0.0)
        store = TimeSeriesStore(capacity=8, clock=clk)
        store.append_snapshot(
            {"gauges": {"agent.replicas_ready@agent-0": 2.0,
                        "agent.replicas_ready@agent-1": 2.0}})
        clk._now = 10.0
        store.append_snapshot(
            {"gauges": {"agent.replicas_ready@agent-0": 2.0}})
        downed = SchedulerPolicy(cfg, clock=clk).decide(store)
        assert never is not None and downed is not None
        for k in ("action", "source", "ready"):
            assert never[k] == downed[k]
        assert never["action"] == "add"

    def test_run_check_reports_never_up_sources(self):
        from mx_rcnn_tpu.tools.obs import run_check
        cfg = generate_config("tiny", "synthetic")
        reg = Registry()
        reg.set_gauge("serve.replicas_ready", 1.0)
        coll = Collector([RegistrySource("live", lambda: (reg, {})),
                          RegistrySource("dead", lambda: None)])
        verdict = run_check(coll, cfg, samples=2, interval_s=0.0)
        assert verdict["never_up"] == ["dead"]
        assert verdict["sources_up"] == 1
        assert verdict["view"]["dead"] == {"up": False}


# ---------------------------------------------------------------------------
# traces
# ---------------------------------------------------------------------------

class TestTraffic:
    @pytest.fixture(scope="class")
    def cfg(self):
        return generate_config("tiny", "synthetic")

    @pytest.mark.parametrize("name", SCENARIOS)
    def test_generator_invariants(self, cfg, name):
        hosts = 20
        tr = generate(name, cfg, hosts, seed=5)
        assert tr["name"] == name
        assert tr["hosts"] == hosts and tr["seed"] == 5
        T = tr["duration_s"]
        assert T > 0
        # rate curve: time-sorted, non-negative, starts inside [0, T)
        times = [t for t, _ in tr["rate"]]
        assert times == sorted(times)
        assert all(0.0 <= t < T for t in times)
        assert all(r >= 0.0 for _, r in tr["rate"])
        # events: known kinds, in-range hosts, time-sorted
        for ev in tr["events"]:
            assert ev["kind"] in ("host_down", "host_flap",
                                  "drain_host")
            assert 0 <= ev["host"] < hosts
            assert 0.0 <= ev["t"] < T
        # the fleet-shape knobs every scenario must pin for both arms
        for key in ("crosshost__target_replicas",
                    "crosshost__max_replicas",
                    "crosshost__min_replicas"):
            assert key in tr["overrides"]
        # deterministic: byte-equal JSON and a stable fingerprint
        again = generate(name, cfg, hosts, seed=5)
        assert json.dumps(tr, sort_keys=True) == \
            json.dumps(again, sort_keys=True)
        assert tr["fingerprint"] == again["fingerprint"]
        assert generate(name, cfg, hosts, seed=6)["fingerprint"] \
            != tr["fingerprint"]

    def test_storm_kills_fifteen_percent_with_capped_flappers(self, cfg):
        tr = generate("failure_storm", cfg, 40, seed=0)
        kills = [e for e in tr["events"]
                 if e["kind"] in ("host_down", "host_flap")]
        assert len(kills) == 6  # 15% of 40
        assert sum(e["kind"] == "host_flap" for e in kills) == 3

    def test_rolling_update_drains_every_host_once(self, cfg):
        hosts = 16
        tr = generate("rolling_update", cfg, hosts, seed=0)
        drained = [e["host"] for e in tr["events"]
                   if e["kind"] == "drain_host"]
        assert sorted(drained) == list(range(hosts))

    def test_rate_at_piecewise_constant_and_zero_past_end(self, cfg):
        tr = {"duration_s": 100.0,
              "rate": [[0.0, 5.0], [40.0, 9.0], [70.0, 2.0]]}
        assert rate_at(tr, 0.0) == 5.0
        assert rate_at(tr, 39.9) == 5.0
        assert rate_at(tr, 40.0) == 9.0
        assert rate_at(tr, 99.9) == 2.0
        assert rate_at(tr, 100.0) == 0.0

    def test_bucket_weights_normalized(self, cfg):
        w = bucket_weights(cfg)
        assert sum(frac for _, frac in w) == pytest.approx(1.0)
        assert fleet_capacity_rps(cfg, 10) > 0.0


# ---------------------------------------------------------------------------
# scorer: exact on a hand-computed mini-trace
# ---------------------------------------------------------------------------

class TestScore:
    def test_score_run_exact(self):
        stats = {"submitted": 10, "served": 6, "shed": 2,
                 "expired": 1, "failed": 1, "rerouted": 3}
        log = [{"t": 1, "kind": "action"}, {"kind": "health", "t": 2}]
        s = score_run(stats, critical_s=90.0, warn_s=30.0,
                      wasted_replica_s=12.34, wait_ms_max=55.57,
                      p99_ms=432.1, log=log)
        assert s["lost"] == 2                      # expired + failed
        assert s["slo_critical_minutes"] == 1.5    # 90 s
        assert s["slo_warn_minutes"] == 0.5
        assert s["capacity_wasted_replica_s"] == 12.3
        assert s["wait_ms_max"] == 55.6
        assert s["served_p99_ms"] == 432.1
        assert s["actions"] == 1
        assert s["decision_log_entries"] == 2
        # the canonical byte form is pinned by hand — one sorted-key
        # JSON object per line, trailing newline
        blob = (b'{"kind": "action", "t": 1}\n'
                b'{"kind": "health", "t": 2}\n')
        assert decision_log_bytes(log) == blob
        assert s["decision_log_sha256"] == \
            hashlib.sha256(blob).hexdigest()

    def test_empty_log_scores(self):
        stats = {"submitted": 0, "served": 0, "shed": 0,
                 "expired": 0, "failed": 0, "rerouted": 0}
        s = score_run(stats, 0.0, 0.0, 0.0, 0.0, None, [])
        assert s["lost"] == 0 and s["served_p99_ms"] is None
        assert decision_log_bytes([]) == b""


# ---------------------------------------------------------------------------
# the routing key the cluster shares with the production router
# ---------------------------------------------------------------------------

class TestJsqKey:
    def test_cycles_quantize_by_batch(self):
        # lane depths 0..3 all cost one dispatch cycle at batch 4;
        # depth 4 starts the second cycle
        assert jsq_key(0, 9, 0, 0, 4, 4)[0] == 1
        assert jsq_key(3, 9, 0, 0, 4, 4)[0] == 1
        assert jsq_key(4, 9, 0, 0, 4, 4)[0] == 2

    def test_rotation_breaks_ties_fairly(self):
        a = jsq_key(2, 5, 0, 1, 3, 4)
        b = jsq_key(2, 5, 1, 1, 3, 4)
        assert a[:2] == b[:2] and a[2] != b[2]
        assert jsq_key(2, 5, 2, 1, 3, 4)[2] == 0  # (2+1) % 3


# ---------------------------------------------------------------------------
# the gauntlet contract on a seconds-scale mini storm
# ---------------------------------------------------------------------------

def _mini_storm(cfg, hosts=10, duration_s=90.0, seed=7):
    """A hand-built failure_storm at test scale: 40% of the fleet
    preempted under ~70% base load — shipped re-places the capacity;
    a policy blind to the deficit overloads the survivors past the
    deadline."""
    cap = fleet_capacity_rps(cfg, hosts)
    return {
        "name": "mini_storm", "seed": seed, "hosts": hosts,
        "duration_s": duration_s,
        "rate": [[0.0, round(0.7 * cap, 3)]],
        "bucket_weights": [[list(s), w] for s, w in bucket_weights(cfg)],
        "events": [{"t": 15.0 + 2.5 * j, "kind": "host_down",
                    "host": hosts - 1 - j} for j in range(4)],
        "overrides": {
            "crosshost__target_replicas": hosts,
            "crosshost__max_replicas": hosts * 4,
            "crosshost__min_replicas": hosts,
            "crosshost__up_backlog": 50.0,
            "serve__default_timeout_ms": 6000.0,
            "serve__shed_watermark": 96,
            "fleet__reroute_retries": 2,
        },
        "fingerprint": "test-mini-storm",
    }


class TestGauntlet:
    @pytest.fixture(scope="class")
    def cfg(self):
        return generate_config("tiny", "synthetic")

    @pytest.fixture(scope="class")
    def shipped_runs(self, cfg):
        """The same trace + seed, twice — the determinism substrate."""
        out = []
        for _ in range(2):
            run = SimRun(_mini_storm(cfg), cfg, label="shipped")
            score = run.run()
            out.append((score, decision_log_bytes(run.log)))
        return out

    def test_decision_log_byte_identical(self, shipped_runs):
        (s1, b1), (s2, b2) = shipped_runs
        assert b1 == b2
        assert s1 == s2
        assert s1["decision_log_sha256"] == s2["decision_log_sha256"]

    def test_shipped_clean_and_acts(self, shipped_runs):
        s, _ = shipped_runs[0]
        assert s["lost"] == 0 and s["expired"] == 0 \
            and s["failed"] == 0
        assert s["slo_critical_minutes"] == 0.0
        assert s["actions"] > 0  # it re-placed the killed capacity
        # conservation: every accepted request reached ONE terminal
        assert s["submitted"] == (s["served"] + s["shed"]
                                  + s["expired"] + s["failed"])

    def test_mistuned_measurably_breaches(self, cfg, shipped_runs):
        run = SimRun(_mini_storm(cfg), cfg, label="mistuned",
                     arm_overrides=MISTUNED_OVERRIDES)
        s = run.run()
        assert s["actions"] == 0            # blind, as sabotaged
        assert s["lost"] > 0                # and it pays for it
        assert s["slo_critical_minutes"] > 0.0
        assert s["submitted"] == (s["served"] + s["shed"]
                                  + s["expired"] + s["failed"])
        # same trace, same seed: the divergence is the policy alone
        assert s["decision_log_sha256"] != \
            shipped_runs[0][0]["decision_log_sha256"]


# ---------------------------------------------------------------------------
# the driver's acceptance predicate
# ---------------------------------------------------------------------------

class TestCheckGauntlet:
    @staticmethod
    def _record(shipped_lost=0, shipped_crit=0.0, mistuned_lost=5,
                mistuned_crit=0.3, hosts=100, det=True):
        arm = lambda lost, crit: {"lost": lost, "expired": lost,
                                  "failed": 0,
                                  "slo_critical_minutes": crit}
        return {
            "scenarios": {"s": {
                "hosts": hosts,
                "arms": {"shipped": arm(shipped_lost, shipped_crit),
                         "mistuned": arm(mistuned_lost,
                                         mistuned_crit)}}},
            "determinism": {"log_identical": det,
                            "score_identical": det},
        }

    def test_clean_record_passes(self):
        assert check_gauntlet(self._record()) == []

    def test_shipped_loss_fails(self):
        assert any("LOST" in p
                   for p in check_gauntlet(self._record(shipped_lost=3)))

    def test_no_discrimination_fails(self):
        probs = check_gauntlet(self._record(mistuned_lost=0,
                                            mistuned_crit=0.0))
        assert any("discrimination" in p for p in probs)

    def test_small_fleet_fails(self):
        assert any(">= 100" in p
                   for p in check_gauntlet(self._record(hosts=20)))

    def test_broken_determinism_fails(self):
        probs = check_gauntlet(self._record(det=False))
        assert any("determinism" in p for p in probs)
