"""Demo tool tests (VERDICT r1 item 9): detect_image + draw_detections +
the end-to-end demo() path writing an annotated image."""

import os

import numpy as np
import pytest

from mx_rcnn_tpu.tools.demo import demo, draw_detections


def test_draw_detections_marks_pixels():
    img = np.zeros((60, 80, 3), np.uint8)
    dets = {1: np.array([[10, 10, 40, 30, 0.9]], np.float32)}
    out = draw_detections(img, dets, ["bg", "thing"])
    assert out.shape == img.shape
    assert out.sum() > 0  # something was drawn
    # box outline touches the expected rows/cols
    assert out[10, 10:41].sum() > 0


def test_demo_end_to_end(tmp_path):
    """Train-free demo run: random-init tiny model on a synthetic image —
    must produce a valid annotated file regardless of detection count."""
    import jax

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.core.train import setup_training
    from mx_rcnn_tpu.data import get_dataset
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.utils.checkpoint import save_checkpoint

    cfg = generate_config("tiny", "synthetic",
                          dataset__root_path=str(tmp_path),
                          dataset__dataset_path=str(tmp_path / "synthetic"),
                          dataset__num_classes=4)
    cfg = cfg.replace_in("test", rpn_pre_nms_top_n=256, rpn_post_nms_top_n=32)
    cfg = cfg.replace_in("bucket", scale=128, max_size=160,
                         shapes=((128, 160), (160, 128)))
    ds = get_dataset("synthetic", "demo", str(tmp_path),
                     str(tmp_path / "synthetic"), num_images=1,
                     num_classes=4, image_size=(128, 160))
    roidb = ds.gt_roidb()
    model = build_model(cfg)
    state, _ = setup_training(model, cfg, jax.random.PRNGKey(0),
                              (1, 128, 160, 3), steps_per_epoch=1)
    prefix = str(tmp_path / "m")
    save_checkpoint(prefix, 1, state)
    out_path = str(tmp_path / "annotated.png")
    dets = demo(cfg, prefix=prefix, epoch=1, image=roidb[0]["image"],
                out_path=out_path, vis_thresh=0.05)
    assert os.path.exists(out_path)
    from PIL import Image

    with Image.open(out_path) as im:
        assert im.size == (160, 128)
    assert isinstance(dets, dict)
