"""Fault-tolerance subsystem tests (mx_rcnn_tpu/ft/, docs/FT.md).

Covers the four layers without subprocesses so the whole file runs under
the CPU tier-1 environment: async snapshot equivalence (background-written
checkpoint bit-equal to a synchronous one), manifest commit-point +
corrupt/truncated/manifest-less fallback ordering, retention GC keep-set,
fault-plan determinism, writer-failure surfacing, the cached-path
determinism pin (the double-donation aliasing fix in core/fit.py), and an
in-process kill/resume → bit-exact-params case.  The real-process version
of the last one — actual SIGKILLs, torn files, subprocess restarts — is
``make ft-smoke`` / ``tools/crashloop.py``.
"""

import os
import sys
import threading

import jax
import numpy as np
import pytest

from tests.test_train_step import KEY, make_batch, tiny_setup

from mx_rcnn_tpu.core.fit import fit
from mx_rcnn_tpu.ft.faults import Fault, FaultInjector, parse_plan
from mx_rcnn_tpu.ft.integrity import (gc_checkpoints, latest_valid_checkpoint,
                                      retention_keep_set, scan_candidates,
                                      verify_checkpoint)
from mx_rcnn_tpu.ft.snapshot import (AsyncSnapshotter, SnapshotError,
                                     SyncSnapshotter)
from mx_rcnn_tpu.utils.checkpoint import (checkpoint_path, interrupt_path,
                                          list_checkpoints, manifest_path,
                                          read_manifest, restore_interrupt,
                                          restore_state, save_checkpoint,
                                          save_interrupt)


class FakeLoader:
    """Deterministic in-memory loader: len + iteration, single bucket."""

    shuffle = False

    def __init__(self, batches):
        self.batches = list(batches)

    def __len__(self):
        return len(self.batches)

    def __iter__(self):
        return iter(self.batches)


def _read(path):
    with open(path, "rb") as f:
        return f.read()


# ---- snapshot.py ----------------------------------------------------------


def test_async_snapshot_bit_equal_to_sync(tmp_path):
    cfg, model, tx, state = tiny_setup()
    pa = str(tmp_path / "a" / "m")
    pb = str(tmp_path / "b" / "m")
    a = AsyncSnapshotter(pa, cfg, steps_per_epoch=7)
    a.save_epoch(1, state)
    a.save_interrupt(state)
    a.close()
    b = SyncSnapshotter(pb, cfg, steps_per_epoch=7)
    b.save_epoch(1, state)
    b.save_interrupt(state)
    assert _read(checkpoint_path(pa, 1)) == _read(checkpoint_path(pb, 1))
    assert _read(interrupt_path(pa)) == _read(interrupt_path(pb))
    # manifests identical up to the file-name key
    ma, mb = read_manifest(checkpoint_path(pa, 1)), \
        read_manifest(checkpoint_path(pb, 1))
    assert list(ma["files"].values()) == list(mb["files"].values())
    assert (ma["step"], ma["epoch"], ma["steps_per_epoch"]) == \
        (mb["step"], mb["epoch"], mb["steps_per_epoch"])
    assert ma["config_fingerprint"] == mb["config_fingerprint"]


def test_snapshotter_writes_survive_donation(tmp_path):
    """The snapshot must OWN its bytes: overwrite the live state's buffers
    right after save_epoch returns (what the next donating train step
    does) and the committed file must still hold the old values."""
    cfg, model, tx, state = tiny_setup()
    prefix = str(tmp_path / "m")
    snap = AsyncSnapshotter(prefix, cfg, steps_per_epoch=7)
    leaves = jax.tree.leaves(state.params)
    before = np.asarray(leaves[0]).copy()
    snap.save_epoch(1, state)
    # clobber the host views of every param buffer (CPU backend: numpy
    # views alias device memory — see ft/snapshot.py fetch_owned)
    for leaf in leaves:
        arr = np.asarray(jax.device_get(leaf))
        if arr.flags.writeable:
            arr.fill(123.0)
    snap.close()
    restored = restore_state(tiny_setup()[3], prefix, 1)
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(restored.params)[0]), before)


def test_writer_failure_surfaces_on_training_thread(tmp_path, monkeypatch):
    import mx_rcnn_tpu.ft.snapshot as snapmod

    cfg, model, tx, state = tiny_setup()

    def boom(job, prefix):
        raise OSError("disk on fire")

    monkeypatch.setattr(snapmod, "_write_job", boom)
    snap = AsyncSnapshotter(str(tmp_path / "m"), cfg, steps_per_epoch=7)
    snap.save_epoch(1, state)  # enqueues; failure lands in the writer
    with pytest.raises(SnapshotError):
        snap.flush()
    snap.close()


def test_slot_blocks_then_fails_loudly(tmp_path, monkeypatch):
    """Bounded in-flight window (one writing + one queued): while a write
    is stuck, the SECOND pending snapshot fills the queue slot and the
    request for a third must block and then raise after slot_timeout_s —
    never an unbounded backlog of host copies."""
    import threading

    import mx_rcnn_tpu.ft.snapshot as snapmod

    cfg, model, tx, state = tiny_setup()
    release = threading.Event()
    orig = snapmod._write_job

    def slow(job, prefix):
        release.wait(10.0)
        return orig(job, prefix)

    monkeypatch.setattr(snapmod, "_write_job", slow)
    snap = AsyncSnapshotter(str(tmp_path / "m"), cfg, steps_per_epoch=7,
                            slot_timeout_s=0.2)
    snap.save_epoch(1, state)   # writer picks this up and blocks
    snap.save_epoch(2, state)   # fills the depth-1 slot
    with pytest.raises(SnapshotError):
        snap.save_epoch(3, state)
    release.set()
    snap.close()


# ---- integrity.py ---------------------------------------------------------


def _save_epochs(prefix, state, epochs, spe=7):
    for e in epochs:
        save_checkpoint(prefix, e, state, steps_per_epoch=spe)


def test_verify_checkpoint_catches_each_corruption(tmp_path):
    _, _, _, state = tiny_setup()
    prefix = str(tmp_path / "m")
    path = save_checkpoint(prefix, 1, state)
    assert verify_checkpoint(path) == (True, "ok")

    # truncation: size mismatch
    data = _read(path)
    with open(path, "wb") as f:
        f.write(data[: len(data) // 2])
    ok, reason = verify_checkpoint(path)
    assert not ok and "truncated" in reason

    # flip: same size, sha mismatch
    bad = bytearray(data)
    bad[len(bad) // 2] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(bad))
    ok, reason = verify_checkpoint(path)
    assert not ok and "sha256" in reason

    # manifest-less: uncommitted
    with open(path, "wb") as f:
        f.write(data)
    os.unlink(manifest_path(path))
    ok, reason = verify_checkpoint(path)
    assert not ok and "manifest" in reason


def test_fallback_ordering_newest_to_oldest(tmp_path, caplog):
    """Corrupt the two newest checkpoints: the scanner must walk past both
    (loudly) and return the newest clean one."""
    import logging

    _, _, _, state = tiny_setup()
    prefix = str(tmp_path / "m")
    _save_epochs(prefix, state, (1, 2, 3))
    # epoch 3: truncated; epoch 2: byte flipped
    p3, p2 = checkpoint_path(prefix, 3), checkpoint_path(prefix, 2)
    with open(p3, "r+b") as f:
        f.truncate(100)
    with open(p2, "r+b") as f:
        f.seek(50)
        b = f.read(1)
        f.seek(50)
        f.write(bytes([b[0] ^ 0xFF]))
    with caplog.at_level(logging.WARNING, logger="mx_rcnn_tpu"):
        ref = latest_valid_checkpoint(prefix)
    assert ref is not None and ref.kind == "epoch" and ref.epoch == 1
    assert sum("SKIPPING" in r.message for r in caplog.records) == 2


def test_scan_prefers_interrupt_by_step_not_name(tmp_path):
    """A FRESH interrupt (higher step) outranks epoch checkpoints; a STALE
    one (step <= newest epoch) loses to the epoch file."""
    _, _, _, state = tiny_setup()
    prefix = str(tmp_path / "m")
    state5 = state._replace(step=np.int32(5))
    state7 = state._replace(step=np.int32(7))
    save_checkpoint(prefix, 1, state5, steps_per_epoch=5)
    save_interrupt(prefix, state7, 5)
    ref = latest_valid_checkpoint(prefix)
    assert ref.kind == "interrupt" and ref.step == 7

    # stale: interrupt at the same step as the epoch file → epoch wins
    save_interrupt(prefix, state5, 5)
    ref = latest_valid_checkpoint(prefix)
    assert ref.kind == "epoch" and ref.epoch == 1
    # candidates stay ordered best-first
    kinds = [c.kind for c in scan_candidates(prefix)]
    assert kinds == ["epoch", "interrupt"]


def test_retention_keep_set():
    assert retention_keep_set(range(1, 13), keep_last=3, keep_every=5) == \
        {5, 10, 11, 12}
    assert retention_keep_set([1, 2, 3], keep_last=0, keep_every=2) == {2}
    assert retention_keep_set([1, 2, 3], keep_last=2, keep_every=0) == {2, 3}
    # keep_every=1 (the config DEFAULT): every epoch is a keeper
    assert retention_keep_set([1, 2, 3], keep_last=1, keep_every=1) == \
        {1, 2, 3}


def test_gc_checkpoints_deletes_outside_keep_set(tmp_path):
    _, _, _, state = tiny_setup()
    prefix = str(tmp_path / "m")
    _save_epochs(prefix, state, range(1, 13))
    deleted = gc_checkpoints(prefix, keep_last=3, keep_every=5)
    assert len(deleted) == 8
    kept = [e for e, _ in list_checkpoints(prefix)]
    assert kept == [5, 10, 11, 12]
    for _, path in list_checkpoints(prefix):
        assert os.path.exists(manifest_path(path))
    # manifests of deleted checkpoints are gone too
    assert not os.path.exists(manifest_path(checkpoint_path(prefix, 1)))


# ---- faults.py ------------------------------------------------------------


def test_parse_plan_deterministic_and_loud():
    plan = parse_plan("kill@step=9@sig=TERM, flip-byte@step=3@offset=64,"
                      "truncate-last-ckpt@step=5")
    assert plan == (
        Fault("flip-byte", 3, "KILL", 64),
        Fault("truncate-last-ckpt", 5, "KILL", None),
        Fault("kill", 9, "TERM", None),
    )
    assert parse_plan("kill@step=9@sig=TERM") == plan[2:]
    for bad in ("explode@step=1", "kill", "kill@step=1@sig=HUP",
                "kill@step=2@what=3", "kill@step"):
        with pytest.raises(ValueError):
            parse_plan(bad)


def test_fault_injector_fires_each_fault_once(tmp_path):
    killed = []
    inj = FaultInjector(parse_plan("kill@step=4@sig=TERM,kill@step=6"),
                        str(tmp_path / "m"), kill_fn=killed.append)
    for step in range(1, 10):
        inj.on_step(step)
    import signal as sigmod

    assert killed == [sigmod.SIGTERM, sigmod.SIGKILL]


def test_truncate_and_stale_interrupt_faults(tmp_path):
    _, _, _, state = tiny_setup()
    prefix = str(tmp_path / "m")
    save_checkpoint(prefix, 1, state, steps_per_epoch=7)
    inj = FaultInjector(parse_plan("truncate-last-ckpt@step=2"), prefix,
                        kill_fn=lambda s: None)
    inj.on_step(2)
    ok, reason = verify_checkpoint(checkpoint_path(prefix, 1))
    assert not ok and "truncated" in reason

    # stale-interrupt plants a VALID manifest recording the old step
    save_checkpoint(prefix, 2, state, steps_per_epoch=7)
    inj = FaultInjector(parse_plan("stale-interrupt@step=3"), prefix,
                        kill_fn=lambda s: None)
    inj.on_step(3)
    assert verify_checkpoint(interrupt_path(prefix))[0]
    ref = latest_valid_checkpoint(prefix)
    assert ref.kind == "epoch" and ref.epoch == 2  # stale one out-ranked


# ---- fit integration: kill/resume bit-exact, cached-path determinism ------


def _fit_tiny(prefix, state, epochs, loader_batches, cfg, model, tx,
              stop_after=None, device_cache=False):
    loader = FakeLoader(loader_batches)
    counter = {"n": 0}

    def stop():
        counter["n"] += 1
        return stop_after is not None and counter["n"] > stop_after

    return fit(model, cfg, state, tx, loader, epochs, KEY, prefix=prefix,
               frequent=1000, stop_flag=stop if stop_after else None,
               device_cache=device_cache)


def _assert_states_bit_equal(a, b):
    assert int(a.step) == int(b.step)
    for x, y in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for x, y in zip(jax.tree.leaves(a.opt_state),
                    jax.tree.leaves(b.opt_state)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_in_process_kill_resume_bit_exact(tmp_path):
    """Interrupt mid-epoch via stop_flag (the SIGTERM path), resume through
    the INTEGRITY SCANNER, finish — final TrainState bit-identical to an
    uninterrupted run.  The subprocess/SIGKILL version is `make ft-smoke`."""
    cfg, model, tx, state0 = tiny_setup()
    batches = [make_batch(seed=s) for s in range(3)]  # 3 steps/epoch

    ref = _fit_tiny(None, state0, 2, batches, cfg, model, tx)

    prefix = str(tmp_path / "m" / "e2e")
    _, _, _, s1 = tiny_setup()
    # stop fires after step 2 of 3 — mid-epoch, so an interrupt checkpoint
    # (not an epoch one) must be the resume point
    _fit_tiny(prefix, s1, 2, batches, cfg, model, tx, stop_after=1)
    ref_ckpt = latest_valid_checkpoint(prefix)
    assert ref_ckpt.kind == "interrupt" and ref_ckpt.step == 2

    # resume exactly like tools/train.py --resume auto
    _, _, _, template = tiny_setup()
    resumed, spe = restore_interrupt(template, prefix)
    assert spe == 3
    final = _fit_tiny(prefix, resumed, 2, batches, cfg, model, tx)
    _assert_states_bit_equal(ref, final)
    # the epoch checkpoint superseded the interrupt (cleared post-commit)
    assert not os.path.exists(interrupt_path(prefix))


def test_resume_falls_back_past_corrupt_epoch_bit_exact(tmp_path):
    """Corrupt the NEWEST epoch checkpoint after a finished run: resume via
    the scanner lands on the previous epoch, re-trains the lost epoch, and
    reproduces the pristine final checkpoint BIT-EXACTLY (deterministic
    replay is what makes torn-write recovery lossless here)."""
    cfg, model, tx, state0 = tiny_setup()
    batches = [make_batch(seed=s) for s in range(3)]
    prefix = str(tmp_path / "m" / "e2e")
    final = _fit_tiny(prefix, state0, 2, batches, cfg, model, tx)

    p2 = checkpoint_path(prefix, 2)
    pristine = _read(p2)
    with open(p2, "r+b") as f:
        f.truncate(64)
    ref = latest_valid_checkpoint(prefix)
    assert ref.kind == "epoch" and ref.epoch == 1

    _, _, _, template = tiny_setup()
    resumed = restore_state(template, prefix, ref.epoch)
    refit = _fit_tiny(prefix, resumed, 2, batches, cfg, model, tx)
    _assert_states_bit_equal(final, refit)
    assert _read(p2) == pristine  # the re-written checkpoint byte-matches


@pytest.mark.slow
def test_resume_auto_falls_back_to_legacy_for_premanifest_dirs(tmp_path,
                                                               caplog):
    """A run dir from before the manifest era has valid checkpoints but
    nothing that VERIFIES; --resume auto must fall back to the unverified
    legacy resume with a loud warning — never silently start over and
    overwrite them (code-review finding)."""
    import glob
    import logging

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.tools.train import train_net
    from tests.conftest import shrink_tiny_cfg

    cfg = shrink_tiny_cfg(generate_config(
        "tiny", "synthetic", dataset__root_path=str(tmp_path),
        dataset__dataset_path=str(tmp_path / "synthetic"),
        dataset__num_classes=4))
    kw = dict(lr=0.001, seed=0, frequent=1000,
              dataset_kw=dict(num_images=16, image_size=(128, 160),
                              max_objects=3))
    prefix = str(tmp_path / "m" / "e2e")
    train_net(cfg, prefix=prefix, end_epoch=1, **kw)
    for m in glob.glob(prefix + "*manifest.json"):
        os.unlink(m)  # simulate a pre-manifest run directory
    with caplog.at_level(logging.WARNING, logger="mx_rcnn_tpu"):
        final = train_net(cfg, prefix=prefix, end_epoch=2, resume="auto",
                          **kw)
    assert any("UNVERIFIED legacy resume" in r.message
               for r in caplog.records)
    assert int(final.step) == 32  # resumed from epoch 1, trained epoch 2
    assert os.path.exists(checkpoint_path(prefix, 2))


# ---- supervisor restart policy (elastic era) -------------------------------


def test_restart_policy_backoff_schedule():
    """Regression pin for the restart schedule: exponential growth to the
    cap, deterministic jitter within ±jitter_frac, progress resets, and
    the give-up verdict fires only on IDENTICAL consecutive failures."""
    from mx_rcnn_tpu.ft.supervisor import RestartPolicy

    a = RestartPolicy(base_s=0.25, factor=2.0, cap_s=30.0,
                      jitter_frac=0.25, give_up_after=3, seed=7)
    b = RestartPolicy(base_s=0.25, factor=2.0, cap_s=30.0,
                      jitter_frac=0.25, give_up_after=3, seed=7)
    raw = [0.25 * 2.0 ** (n - 1) for n in range(1, 12)]
    for n, r in enumerate(raw, start=1):
        d = a.delay_s(n)
        assert d == b.delay_s(n)                    # deterministic
        capped = min(r, 30.0)
        assert 0.75 * capped <= d <= 1.25 * capped  # jitter bounds
    assert a.delay_s(0) == 0.0
    # growth up to the cap region
    assert a.delay_s(2) > a.delay_s(1)
    assert a.delay_s(9) <= 30.0 * 1.25

    # give-up: 3 IDENTICAL no-progress failures, but a different
    # signature (or any progress) resets the identical count
    p = RestartPolicy(give_up_after=3, seed=0)
    assert p.record(("KILL", 5), made_progress=False) [1] is False
    assert p.record(("KILL", 5), made_progress=False) [1] is False
    _, give_up = p.record(("TERM", 5), made_progress=False)  # different
    assert not give_up
    assert p.record(("TERM", 5), made_progress=False)[1] is False
    delay, give_up = p.record(("TERM", 5), made_progress=False)
    assert give_up                                   # 3rd identical
    # progress resets everything
    p2 = RestartPolicy(give_up_after=2, seed=0)
    p2.record(("KILL", 5), made_progress=False)
    delay, give_up = p2.record(("KILL", 9), made_progress=True)
    assert delay == 0.0 and not give_up and p2.failures == 0


def test_restart_policy_record_is_thread_safe():
    """Regression for the ISSUE-10 threadlint TL201 fix: one policy is
    shared between the fleet health monitor and the per-replica relaunch
    threads (serve/fleet.py), and record()'s unguarded counter updates
    lost counts under interleaving — which skews both the backoff
    schedule and the give-up verdict.  With the policy lock, N threads x
    M no-progress records land exactly N*M failures."""
    from mx_rcnn_tpu.ft.supervisor import RestartPolicy
    from mx_rcnn_tpu.obs.metrics import Registry

    # factor=1 keeps delay_s finite at thousands of failures (2.0**n
    # overflows float); the huge give_up_after keeps the verdict away
    p = RestartPolicy(base_s=0.0, factor=1.0, give_up_after=10**9, seed=0,
                      registry=Registry())
    n_threads, per = 8, 400
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)  # force frequent interleaving
    try:
        def hammer(t):
            for i in range(per):
                p.record((t, i), made_progress=False)

        threads = [threading.Thread(target=hammer, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        sys.setswitchinterval(old)
    assert p.failures == n_threads * per


# ---- manifest topology + resume admission (elastic era) --------------------


def test_manifest_records_topology_and_data_cursor(tmp_path):
    from mx_rcnn_tpu.utils.checkpoint import make_topology, save_interrupt

    _, _, _, state = tiny_setup()
    prefix = str(tmp_path / "m")
    topo = make_topology(4, num_processes=2, grad_accum=2, batch_images=1)
    assert topo["global_batch"] == 8
    state = state._replace(step=np.int32(10))
    path = save_interrupt(prefix, state, 7, topology=topo)
    m = read_manifest(path)
    assert m["topology"] == topo
    assert m["data_cursor"] == {"epoch": 1, "steps_in_epoch": 3,
                                "batches_consumed": 6,
                                "images_consumed": 80}


def test_resume_topology_check_hard_errors_without_override(tmp_path):
    """A resume that would silently change the effective global batch is
    a HARD error; ft.allow_resize_resume downgrades it to a warning (the
    elastic controller's supervised-resize path); a preserved global
    batch (grad-accum rescale) passes without any override."""
    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.tools.train import _check_topology

    cfg = generate_config("tiny", "PascalVOC")
    manifest = {"topology": {"devices": 8, "processes": 1, "grad_accum": 1,
                             "global_batch": 8}}
    # same global batch on fewer devices via accumulation: fine
    _check_topology(manifest, cfg, num_devices=4, grad_accum=2, path="x")
    # silent change: 4 devices, no rescale -> global batch 4 != 8
    with pytest.raises(ValueError, match="global batch"):
        _check_topology(manifest, cfg, num_devices=4, grad_accum=1,
                        path="x")
    # override downgrades to a warning
    cfg_ok = cfg.replace_in("ft", allow_resize_resume=True)
    _check_topology(manifest, cfg_ok, num_devices=4, grad_accum=1,
                    path="x")
    # pre-topology manifests have nothing to check against
    _check_topology({}, cfg, num_devices=4, grad_accum=1, path="x")
    _check_topology(None, cfg, num_devices=4, grad_accum=1, path="x")


# ---- cross-mesh reshard round-trips (elastic state surgery) ----------------


@pytest.mark.parametrize("hops", [(8, 4, 8), (8, 2), (4, 8)],
                         ids=["8-4-8", "8-2", "4-8"])
def test_reshard_roundtrip_tree_equal_and_step_bit_match(tmp_path, hops):
    """The elastic restore path, property-tested across mesh resizes:
    train one DP step on mesh A, checkpoint, restore + respec onto mesh
    B (for every hop in the chain) — the restored tree must be
    VALUE-EQUAL to the saved one (lossless surgery), ``state.step`` must
    never move backwards, and ONE post-restore step on the new mesh must
    bit-match a control state placed directly on that mesh."""
    from mx_rcnn_tpu.ft.elastic import respec
    from mx_rcnn_tpu.parallel.dp import (device_mesh, make_dp_train_step,
                                         shard_batch)
    from tests.test_train_step import make_batch as mk

    cfg, model, tx, state = tiny_setup()
    prefix = str(tmp_path / "xmesh")
    batch = mk(n=8)

    mesh0 = device_mesh(hops[0])
    step0 = make_dp_train_step(model, cfg, tx, mesh0)
    s, _ = step0(respec(jax.device_get(state), mesh0),
                 shard_batch(_take(batch, hops[0]), mesh0), KEY)
    host = jax.device_get(s)
    save_checkpoint(prefix, 1, host, steps_per_epoch=100)
    prev_step = int(np.asarray(host.step))

    for n_dev in hops[1:]:
        _, _, _, template = tiny_setup()
        restored = restore_state(jax.tree.map(np.zeros_like,
                                              jax.device_get(template)),
                                 prefix, 1)
        # lossless: tree-equal to the saved host state
        for a, b in zip(jax.tree.leaves(host), jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # step monotonicity across the resize
        assert int(np.asarray(restored.step)) >= prev_step
        prev_step = int(np.asarray(restored.step))

        mesh = device_mesh(n_dev)
        stepN = make_dp_train_step(model, cfg, tx, mesh)
        bN = shard_batch(_take(batch, n_dev), mesh)
        s_restored, m_r = stepN(respec(restored, mesh), bN, KEY)
        s_direct, m_d = stepN(respec(jax.tree.map(np.copy, host), mesh),
                              bN, KEY)
        assert float(m_r["loss"]) == float(m_d["loss"])
        _assert_states_bit_equal(s_direct, s_restored)
        # the next hop restores the same checkpoint; re-save the stepped
        # state so the chain keeps moving forward
        host = jax.device_get(s_restored)
        save_checkpoint(prefix, 1, host, steps_per_epoch=100)


def _take(batch, n):
    """First n images of a host batch (the per-mesh global batch)."""
    return jax.tree.map(lambda x: np.asarray(x)[:n], batch)


def test_cached_fit_is_deterministic(tmp_path):
    """Regression pin for the double-donation aliasing bug: the cached
    step's gather index was built as a zero-copy view of state.step, and
    donating both (argnums 0 and 2) made training NONDETERMINISTIC on the
    CPU backend.  Two identical cached fits must now be bit-identical."""
    batches = [make_batch(seed=s) for s in range(3)]

    def run():
        cfg, model, tx, state = tiny_setup()
        return _fit_tiny(None, state, 2, batches, cfg, model, tx,
                         device_cache=True)

    _assert_states_bit_equal(run(), run())
