"""Pallas NMS kernel vs the jnp suppression sweep (the oracle).

The kernel must reproduce sequential greedy NMS decision-for-decision; on
CPU it runs under interpret=True (correctness only — the perf claim is
checked on real TPU by tools/profile_step.py / bench.py).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from mx_rcnn_tpu.ops.nms import nms, nms_mask


def _rand(rng, k):
    xy = rng.uniform(0, 200, (k, 2)).astype(np.float32)
    wh = rng.uniform(5, 80, (k, 2)).astype(np.float32)
    boxes = np.hstack([xy, xy + wh])
    scores = rng.uniform(size=k).astype(np.float32)
    return jnp.asarray(boxes), jnp.asarray(scores)


@pytest.mark.parametrize("k,tile", [(256, 128), (512, 128), (384, 128)])
def test_pallas_matches_jnp_nms_mask(k, tile):
    rng = np.random.RandomState(k)
    boxes, scores = _rand(rng, k)
    want = nms_mask(boxes, scores, 0.5, tile_size=tile, backend="jnp")
    got = nms_mask(boxes, scores, 0.5, tile_size=tile, backend="pallas")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_pallas_matches_jnp_nms_indices():
    rng = np.random.RandomState(7)
    boxes, scores = _rand(rng, 512)
    valid = jnp.asarray(rng.uniform(size=512) > 0.1)
    want_i, want_v = nms(boxes, scores, 0.7, 100, valid=valid,
                         tile_size=128, backend="jnp")
    got_i, got_v = nms(boxes, scores, 0.7, 100, valid=valid,
                       tile_size=128, backend="pallas")
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(want_i))
    np.testing.assert_array_equal(np.asarray(got_v), np.asarray(want_v))


def test_pallas_dense_cluster():
    """Heavy-overlap chains exercise the within-tile fixed point across
    tile boundaries."""
    rng = np.random.RandomState(3)
    base = rng.uniform(0, 40, (16, 2))
    boxes = []
    for bx, by in base:
        for _ in range(16):
            j = rng.uniform(-3, 3, 2)
            boxes.append([bx + j[0], by + j[1], bx + 30 + j[0], by + 30 + j[1]])
    boxes = jnp.asarray(np.asarray(boxes, np.float32))
    scores = jnp.asarray(rng.uniform(size=len(boxes)).astype(np.float32))
    want = nms_mask(boxes, scores, 0.5, tile_size=128, backend="jnp")
    got = nms_mask(boxes, scores, 0.5, tile_size=128, backend="pallas")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_set_nms_backend_validation():
    import importlib

    # ops/__init__ re-exports the nms FUNCTION over the module name
    nms_mod = importlib.import_module("mx_rcnn_tpu.ops.nms")

    before = nms_mod._BACKEND
    try:
        with pytest.raises(ValueError, match="unknown NMS backend"):
            nms_mod.set_nms_backend("cuda")
        nms_mod.set_nms_backend("jnp")
        assert nms_mod._BACKEND == "jnp"
    finally:
        nms_mod.set_nms_backend(before)


def test_resolve_backend_guards(monkeypatch):
    """Auto selection requires TPU + 128-lane-aligned tiles + a bounded
    (T, K) VMEM slab; anything else falls back to jnp."""
    import importlib

    nms_mod = importlib.import_module("mx_rcnn_tpu.ops.nms")
    monkeypatch.setattr(nms_mod.jax, "default_backend", lambda: "tpu")
    r = nms_mod._resolve_backend
    assert r(None, 12032, 256) == "pallas"      # production proposal shape
    assert r(None, 512, 128) == "pallas"
    assert r(None, 500, 100) == "jnp"           # tile not lane-aligned
    assert r(None, 513, 128) == "jnp"           # K not a tile multiple
    assert r(None, 40000, 256) == "jnp"         # slab over the VMEM guard
    assert r("jnp", 12032, 256) == "jnp"        # explicit override wins
    assert r("pallas", 500, 100) == "pallas"    # explicit override wins
    monkeypatch.setattr(nms_mod.jax, "default_backend", lambda: "cpu")
    assert r(None, 12032, 256) == "jnp"         # no TPU -> jnp
