"""anchor_target / proposal_target behavioral tests.

These check the invariants the reference establishes in
``rcnn/io/rpn.py — assign_anchor`` and ``rcnn/io/rcnn.py — sample_rois``:
label semantics, sampling quotas, target normalization, gt-append.
"""

import numpy as np
import jax
import jax.numpy as jnp

from mx_rcnn_tpu.ops.anchors import generate_shifted_anchors
from mx_rcnn_tpu.ops.boxes import bbox_transform
from mx_rcnn_tpu.ops.targets import anchor_target, proposal_target

KEY = jax.random.PRNGKey(0)


def make_gt(boxes, max_gt=8):
    g = np.zeros((max_gt, 4), np.float32)
    v = np.zeros((max_gt,), bool)
    for i, b in enumerate(boxes):
        g[i] = b
        v[i] = True
    return jnp.array(g), jnp.array(v)


def test_anchor_target_basic_labels():
    # NB: the smallest default anchor is 96x184 px, so the test image must be
    # a few hundred px for any anchor to be fully inside (ref allowed_border=0).
    anchors = jnp.array(generate_shifted_anchors(20, 20, 16))
    gt, gtv = make_gt([[100.0, 100.0, 220.0, 190.0]])
    im_info = jnp.array([320.0, 320.0, 1.0])
    out = anchor_target(anchors, gt, gtv, im_info, KEY)
    labels = np.asarray(out.labels)
    assert set(np.unique(labels)).issubset({-1, 0, 1})
    # the gt's best anchor must be positive even if IoU < 0.7
    assert (labels == 1).sum() >= 1
    # quota: at most 256 participating, at most 128 positive
    assert (labels >= 0).sum() <= 256
    assert (labels == 1).sum() <= 128


def test_anchor_target_outside_anchors_ignored():
    anchors = jnp.array(generate_shifted_anchors(8, 8, 16))
    gt, gtv = make_gt([[10.0, 10.0, 60.0, 60.0]])
    im_info = jnp.array([64.0, 64.0, 1.0])  # only a corner of the grid inside
    out = anchor_target(anchors, gt, gtv, im_info, KEY)
    labels = np.asarray(out.labels)
    a = np.asarray(anchors)
    outside = (a[:, 0] < 0) | (a[:, 1] < 0) | (a[:, 2] >= 64) | (a[:, 3] >= 64)
    assert (labels[outside] == -1).all()


def test_anchor_target_weights_only_on_positives():
    anchors = jnp.array(generate_shifted_anchors(20, 20, 16))
    gt, gtv = make_gt([[20.0, 20.0, 140.0, 110.0]])
    im_info = jnp.array([320.0, 320.0, 1.0])
    out = anchor_target(anchors, gt, gtv, im_info, KEY)
    labels = np.asarray(out.labels)
    w = np.asarray(out.bbox_weights)
    assert (labels == 1).sum() >= 1
    assert (w[labels == 1] == 1.0).all()
    assert (w[labels != 1] == 0.0).all()


def test_anchor_target_negative_balance():
    # no gt → everything inside should be negative, capped at 256
    anchors = jnp.array(generate_shifted_anchors(20, 20, 16))
    gt, gtv = make_gt([])
    im_info = jnp.array([320.0, 320.0, 1.0])
    out = anchor_target(anchors, gt, gtv, im_info, KEY)
    labels = np.asarray(out.labels)
    assert (labels == 1).sum() == 0
    assert (labels == 0).sum() == 256


def test_anchor_target_targets_match_transform():
    anchors = jnp.array(generate_shifted_anchors(20, 20, 16))
    gt_box = [30.0, 40.0, 170.0, 150.0]
    gt, gtv = make_gt([gt_box])
    im_info = jnp.array([320.0, 320.0, 1.0])
    out = anchor_target(anchors, gt, gtv, im_info, KEY)
    labels = np.asarray(out.labels)
    pos = np.flatnonzero(labels == 1)
    want = np.asarray(bbox_transform(anchors[pos], jnp.tile(jnp.array([gt_box]), (len(pos), 1))))
    np.testing.assert_allclose(np.asarray(out.bbox_targets)[pos], want, rtol=1e-5, atol=1e-5)


def _make_rois(n=64):
    rng = np.random.RandomState(1)
    r = rng.uniform(0, 150, (n, 4)).astype(np.float32)
    r[:, 2:] = r[:, :2] + rng.uniform(10, 60, (n, 2))
    return jnp.array(r), jnp.ones((n,), bool)


def test_proposal_target_shapes_and_quota():
    rois, rv = _make_rois()
    gt, gtv = make_gt([[10.0, 10.0, 60.0, 60.0], [80.0, 80.0, 140.0, 140.0]])
    gtc = jnp.array([3, 7] + [0] * 6)
    out = proposal_target(rois, rv, gt, gtc, gtv, KEY, num_classes=21, batch_rois=128)
    assert out.rois.shape == (128, 4)
    assert out.labels.shape == (128,)
    assert out.bbox_targets.shape == (128, 84)
    # fg quota: at most 32 foreground (0.25 * 128)
    assert int(out.fg_mask.sum()) <= 32
    labels = np.asarray(out.labels)
    # fg labels are the matched gt classes
    assert set(labels[np.asarray(out.fg_mask)]).issubset({3, 7})


def test_proposal_target_gt_append_guarantees_fg():
    # no proposal overlaps the gt, but gt-append provides a perfect fg ROI
    rois = jnp.tile(jnp.array([[200.0, 200.0, 250.0, 250.0]]), (32, 1))
    rv = jnp.ones((32,), bool)
    gt, gtv = make_gt([[10.0, 10.0, 60.0, 60.0]])
    gtc = jnp.array([5] + [0] * 7)
    out = proposal_target(rois, rv, gt, gtc, gtv, KEY, num_classes=21, batch_rois=128)
    assert int(out.fg_mask.sum()) >= 1
    fg_rois = np.asarray(out.rois)[np.asarray(out.fg_mask)]
    np.testing.assert_allclose(fg_rois[0], [10.0, 10.0, 60.0, 60.0])


def test_proposal_target_bbox_normalization():
    # a fg roi exactly equal to its gt → raw deltas 0 → normalized = -mean/std
    rois = jnp.tile(jnp.array([[10.0, 10.0, 60.0, 60.0]]), (16, 1))
    rv = jnp.ones((16,), bool)
    gt, gtv = make_gt([[10.0, 10.0, 60.0, 60.0]])
    gtc = jnp.array([2] + [0] * 7)
    means = (0.1, 0.1, 0.1, 0.1)
    stds = (0.2, 0.2, 0.2, 0.2)
    out = proposal_target(
        rois, rv, gt, gtc, gtv, KEY, num_classes=21, batch_rois=128,
        bbox_means=means, bbox_stds=stds,
    )
    fg = np.asarray(out.fg_mask)
    tgt = np.asarray(out.bbox_targets)[fg][:, 8:12]  # class 2 slot
    np.testing.assert_allclose(tgt, -0.5, atol=1e-5)
    w = np.asarray(out.bbox_weights)[fg]
    assert (w[:, 8:12] == 1.0).all()
    assert (w[:, :8] == 0.0).all() and (w[:, 12:] == 0.0).all()


def test_proposal_target_background_only():
    rois, rv = _make_rois(32)
    gt, gtv = make_gt([])
    gtc = jnp.zeros((8,), jnp.int32)
    out = proposal_target(rois, rv, gt, gtc, gtv, KEY, num_classes=21, batch_rois=128)
    assert int(out.fg_mask.sum()) == 0
    labels = np.asarray(out.labels)
    # 32 genuine background rois; the 96 filler slots must be ignore (-1),
    # never background (training on filler as bg poisons the classifier)
    assert (labels == 0).sum() == 32
    assert (labels == -1).sum() == 96
    assert (np.asarray(out.bbox_weights) == 0).all()


def test_proposal_target_no_fg_bg_confusion_at_scale():
    # regression test for the priority-overflow bug: with a 2000-roi pool and
    # many fg candidates, no IoU>=0.5 roi may be labelled background
    rng = np.random.RandomState(3)
    n = 2000
    r = rng.uniform(0, 500, (n, 4)).astype(np.float32)
    r[:, 2:] = r[:, :2] + rng.uniform(10, 100, (n, 2))
    gt_box = np.array([100.0, 100.0, 300.0, 300.0], np.float32)
    r[:300] = gt_box + rng.uniform(-8, 8, (300, 4)).astype(np.float32)  # fg-ish
    rois = jnp.array(r)
    rv = jnp.ones((n,), bool)
    gt, gtv = make_gt([gt_box.tolist()])
    gtc = jnp.array([4] + [0] * 7)
    out = proposal_target(rois, rv, gt, gtc, gtv, KEY, num_classes=21, batch_rois=128)
    from mx_rcnn_tpu.ops.boxes import bbox_overlaps
    iou = np.asarray(bbox_overlaps(out.rois, jnp.array(gt_box)[None, :]))[:, 0]
    labels = np.asarray(out.labels)
    assert int(out.fg_mask.sum()) == 32
    assert not ((labels == 0) & (iou >= 0.5)).any()
    # selection is exhaustive: 32 fg + 96 bg, no filler needed
    assert (labels >= 0).all()


def test_choose_k_exact_count_and_subset():
    """_choose_k must select exactly min(quota, count(mask)) elements,
    all inside the mask (ADVICE r5: the old value-threshold selection
    could exceed the quota on fp32 ties)."""
    from mx_rcnn_tpu.ops.targets import _choose_k

    mask = jnp.array([True] * 10 + [False] * 6)
    for i in range(8):
        sel = _choose_k(jax.random.PRNGKey(i), mask, 8, 8)
        assert int(sel.sum()) == 8
        assert not bool((sel & ~mask).any())
    # quota above count(mask): every mask element, nothing else
    sel = _choose_k(KEY, jnp.array([True] * 3 + [False] * 13), 8, 8)
    assert int(sel.sum()) == 3
    # zero quota selects nothing
    assert int(_choose_k(KEY, mask, 8, 0).sum()) == 0


def test_choose_k_exact_under_fp32_ties(monkeypatch):
    """Force every uniform draw to collide: the old ``r <= thr`` selection
    then kept ALL mask elements; scatter-at-top_k-indices must still
    return exactly quota Trues (ADVICE r5 regression)."""
    from mx_rcnn_tpu.ops import targets

    monkeypatch.setattr(targets.jax.random, "uniform",
                        lambda key, shape: jnp.full(shape, 0.5))
    mask = jnp.array([True] * 12 + [False] * 4)
    sel = targets._choose_k(KEY, mask, 8, 5)
    assert int(sel.sum()) == 5
    assert not bool((sel & ~mask).any())
    # duplicated values tied across the mask boundary must not leak
    # masked-out slots into the selection either
    sel_all = targets._choose_k(KEY, mask, 16, 16)
    assert int(sel_all.sum()) == 12
    assert not bool((sel_all & ~mask).any())
