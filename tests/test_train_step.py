"""End-to-end train step tests: single jitted step, freezing, overfit.

The overfit test is the framework's "is it learning" proxy (SURVEY.md §4:
the reference's signal was RPNAcc≈0.9+/RCNNAcc≈0.8+ early in training).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.optim import frozen_mask, make_optimizer
from mx_rcnn_tpu.core.train import (
    Batch,
    init_state,
    loss_and_metrics,
    make_train_step,
    setup_training,
)
from mx_rcnn_tpu.models import build_model

KEY = jax.random.PRNGKey(42)


def tiny_setup(batch_images=1, size=128):
    cfg = generate_config("tiny", "PascalVOC")
    cfg = cfg.replace_in("train", rpn_pre_nms_top_n=256, rpn_post_nms_top_n=64,
                         batch_rois=32, max_gt_boxes=8, rpn_min_size=2)
    model = build_model(cfg)
    state, tx = setup_training(model, cfg, KEY, (batch_images, size, size, 3),
                               steps_per_epoch=100)
    return cfg, model, tx, state




def make_batch(n=1, size=128, seed=0):
    rng = np.random.RandomState(seed)
    images = jnp.array(rng.randn(n, size, size, 3).astype(np.float32))
    im_info = jnp.tile(jnp.array([[float(size), float(size), 1.0]]), (n, 1))
    g = 8
    gt_boxes = jnp.zeros((n, g, 4))
    gt_classes = jnp.zeros((n, g), jnp.int32)
    gt_valid = jnp.zeros((n, g), bool)
    for i in range(n):
        gt_boxes = gt_boxes.at[i, 0].set(jnp.array([20.0, 24.0, 70.0, 90.0]))
        gt_classes = gt_classes.at[i, 0].set(7)
        gt_valid = gt_valid.at[i, 0].set(True)
        gt_boxes = gt_boxes.at[i, 1].set(jnp.array([80.0, 30.0, 120.0, 70.0]))
        gt_classes = gt_classes.at[i, 1].set(12)
        gt_valid = gt_valid.at[i, 1].set(True)
    return Batch(images, im_info, gt_boxes, gt_classes, gt_valid)


def test_loss_and_metrics_finite():
    cfg, model, tx, state = tiny_setup()
    batch = make_batch()
    loss, metrics = loss_and_metrics(model, state.params, state.batch_stats,
                                     batch, KEY, cfg)
    assert np.isfinite(float(loss))
    for k, v in metrics.items():
        assert np.isfinite(float(v)), k
    assert 0.0 <= float(metrics["rpn_acc"]) <= 1.0
    assert float(metrics["num_fg"]) >= 1  # gt-append guarantees fg


def test_train_step_updates_params_and_step():
    cfg, model, tx, state = tiny_setup()
    step = jax.jit(make_train_step(model, cfg, tx))
    batch = make_batch()
    new_state, metrics = step(state, batch, KEY)
    assert int(new_state.step) == 1
    # some parameter must have moved
    diff = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()),
                        state.params, new_state.params)
    assert max(jax.tree.leaves(diff)) > 0
    # batch_stats are frozen — must be bit-identical
    same = jax.tree.map(lambda a, b: bool((a == b).all()),
                        state.batch_stats, new_state.batch_stats)
    assert all(jax.tree.leaves(same))


def test_frozen_params_do_not_move():
    cfg = generate_config("tiny", "PascalVOC")
    cfg = cfg.replace_in("network", fixed_params=("conv1",))
    cfg = cfg.replace_in("train", rpn_pre_nms_top_n=256, rpn_post_nms_top_n=64,
                         batch_rois=32, max_gt_boxes=8, rpn_min_size=2)
    model = build_model(cfg)
    state, tx = setup_training(model, cfg, KEY, (1, 128, 128, 3),
                               steps_per_epoch=100)
    step = jax.jit(make_train_step(model, cfg, tx))
    new_state, _ = step(state, make_batch(), KEY)
    frozen_before = state.params["backbone"]["conv1"]["kernel"]
    frozen_after = new_state.params["backbone"]["conv1"]["kernel"]
    assert bool((frozen_before == frozen_after).all())
    live_before = state.params["backbone"]["conv2"]["kernel"]
    live_after = new_state.params["backbone"]["conv2"]["kernel"]
    assert float(jnp.abs(live_before - live_after).max()) > 0


def test_frozen_mask_prefixes():
    cfg = generate_config("resnet101", "PascalVOC")
    fake_params = {
        "backbone": {
            "conv0": {"kernel": jnp.zeros(1)},
            "stage1_unit1": {"conv1": {"kernel": jnp.zeros(1)}},
            "stage2_unit1": {"conv1": {"kernel": jnp.zeros(1)}},
            "bn_data": {"scale": jnp.zeros(1)},
        },
        "rpn": {"rpn_conv_3x3": {"kernel": jnp.zeros(1)}},
    }
    mask = frozen_mask(fake_params, cfg.network.fixed_params)
    assert mask["backbone"]["conv0"]["kernel"] is False
    assert mask["backbone"]["stage1_unit1"]["conv1"]["kernel"] is False
    assert mask["backbone"]["bn_data"]["scale"] is False
    assert mask["backbone"]["stage2_unit1"]["conv1"]["kernel"] is True
    assert mask["rpn"]["rpn_conv_3x3"]["kernel"] is True


def test_frozen_mask_bn_affine_network_wide():
    """Ref ResNet FIXED_PARAMS lists 'gamma'/'beta': EVERY BatchNorm affine
    is frozen (ADVICE r1 medium), including unfrozen stages and the head —
    but not conv kernels there, and not non-BN biases."""
    cfg = generate_config("resnet101", "PascalVOC")
    fake_params = {
        "backbone": {
            "stage3_unit5": {
                "bn1": {"scale": jnp.zeros(1), "bias": jnp.zeros(1)},
                "conv1": {"kernel": jnp.zeros(1)},
            },
        },
        "head": {
            "stage4_unit1": {"bn2": {"scale": jnp.zeros(1)}},
            "bn1": {"scale": jnp.zeros(1), "bias": jnp.zeros(1)},
        },
        "cls_score": {"kernel": jnp.zeros(1), "bias": jnp.zeros(1)},
    }
    mask = frozen_mask(fake_params, cfg.network.fixed_params)
    assert mask["backbone"]["stage3_unit5"]["bn1"]["scale"] is False
    assert mask["backbone"]["stage3_unit5"]["bn1"]["bias"] is False
    assert mask["backbone"]["stage3_unit5"]["conv1"]["kernel"] is True
    assert mask["head"]["stage4_unit1"]["bn2"]["scale"] is False
    assert mask["head"]["bn1"]["scale"] is False
    # dense bias is NOT a BN beta
    assert mask["cls_score"]["bias"] is True
    assert mask["cls_score"]["kernel"] is True
    # shared-stage freezing must leave stage4 trainable (ADVICE r1 low)
    shared = frozen_mask(fake_params, cfg.network.fixed_params_shared)
    assert shared["head"]["stage4_unit1"]["bn2"]["scale"] is False  # BN affine
    assert shared["backbone"]["stage3_unit5"]["conv1"]["kernel"] is False


@pytest.mark.slow
def test_overfit_single_batch():
    """~40 SGD steps on one synthetic image must drive the losses down and
    the accuracies up — the smoke signal that gradients flow end-to-end."""
    cfg, model, tx, state = tiny_setup()
    cfg2 = cfg.replace_in("default", e2e_lr=0.02)
    tx2 = make_optimizer(cfg2, state.params, steps_per_epoch=10_000)
    state = init_state(model, KEY, tx2, (1, 128, 128, 3))
    step = jax.jit(make_train_step(model, cfg2, tx2))
    batch = make_batch()
    first = None
    for i in range(40):
        state, metrics = step(state, batch, KEY)
        if first is None:
            first = {k: float(v) for k, v in metrics.items()}
    last = {k: float(v) for k, v in metrics.items()}
    assert last["loss"] < first["loss"] * 0.7, (first, last)
    assert last["rpn_acc"] >= 0.9, (first, last)
    assert last["rcnn_acc"] >= 0.8, (first, last)


def test_lr_schedule_warmup_and_decay():
    """Linear warmup ramps warmup_lr -> base_lr, then step decay applies at
    epoch boundaries counted from global step 0 (ref
    WarmupMultiFactorScheduler semantics)."""
    import numpy as np

    from mx_rcnn_tpu.core.optim import lr_schedule

    sched = lr_schedule(0.01, (2,), steps_per_epoch=100, factor=0.1,
                        warmup_step=50, warmup_lr=0.001)
    np.testing.assert_allclose(float(sched(0)), 0.001)
    np.testing.assert_allclose(float(sched(25)), 0.0055, rtol=1e-6)
    np.testing.assert_allclose(float(sched(50)), 0.01, rtol=1e-6)
    np.testing.assert_allclose(float(sched(199)), 0.01, rtol=1e-6)
    np.testing.assert_allclose(float(sched(200)), 0.001, rtol=1e-6)
    # warmup off: plain step decay
    plain = lr_schedule(0.01, (2,), steps_per_epoch=100, factor=0.1)
    np.testing.assert_allclose(float(plain(0)), 0.01, rtol=1e-6)
    np.testing.assert_allclose(float(plain(200)), 0.001, rtol=1e-6)


def test_remat_backbone_identical_gradients():
    """remat_backbone=True must produce the SAME gradients as the plain
    path (jax.checkpoint recomputes, it does not approximate) — the knob
    is a pure memory/FLOPs trade (VERDICT r03 weak #1 MFU lever)."""
    from mx_rcnn_tpu.core.train import loss_and_metrics

    cfg, model, tx, state = tiny_setup()
    cfg_r = cfg.replace_in("train", remat_backbone=True)
    batch = make_batch(1, 128, seed=3)

    def grads(c):
        return jax.jit(jax.grad(
            lambda p: loss_and_metrics(model, p, state.batch_stats, batch,
                                       KEY, c)[0]))(state.params)

    g_plain = grads(cfg)
    g_remat = grads(cfg_r)
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_remat)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_bf16_momentum_state_and_training():
    """momentum_dtype='bfloat16' halves the accumulator dtype (checked in
    opt_state) and trains to a loss trajectory close to fp32 momentum —
    same data/RNG, only the accumulator rounds."""
    from mx_rcnn_tpu.core.train import make_train_step, setup_training

    # build cfg/model directly — tiny_setup's state/tx would be discarded
    # and rebuilt per-config inside run()
    cfg = generate_config("tiny", "PascalVOC")
    cfg = cfg.replace_in("train", rpn_pre_nms_top_n=256,
                         rpn_post_nms_top_n=64, batch_rois=32,
                         max_gt_boxes=8, rpn_min_size=2)
    model = build_model(cfg)
    # both arms explicit: the shipped DEFAULT is bfloat16 (adopted from the
    # r5 A/B — docs/PERF.md), so the fp32 arm must be requested
    cfg = cfg.replace_in("default", momentum_dtype="float32")
    cfg16 = cfg.replace_in("default", momentum_dtype="bfloat16")
    batch = make_batch(1, 128, seed=5)

    def run(c):
        state, tx = setup_training(model, c, KEY, (1, 128, 128, 3),
                                   steps_per_epoch=100)
        step = jax.jit(make_train_step(model, c, tx))
        losses = []
        for _ in range(4):
            state, m = step(state, batch, KEY)
            losses.append(float(m["loss"]))
        return state, losses

    s32, l32 = run(cfg)
    s16, l16 = run(cfg16)
    # accumulator dtype is actually bfloat16 (trace momentum leaves)
    momenta16 = [leaf for leaf in jax.tree.leaves(s16.opt_state)
                 if hasattr(leaf, "dtype") and leaf.dtype == jnp.bfloat16]
    assert momenta16, "no bfloat16 accumulator found in opt_state"
    momenta32 = [leaf for leaf in jax.tree.leaves(s32.opt_state)
                 if hasattr(leaf, "dtype") and leaf.dtype == jnp.bfloat16]
    assert not momenta32, "fp32 config grew bfloat16 state"
    # trajectories agree closely (bf16 has ~3 decimal digits)
    for a, b in zip(l32, l16):
        assert abs(a - b) < 0.05 * abs(a) + 0.02, (l32, l16)


def test_dtype_strings_validated():
    """Typos like 'bf16' must raise, not silently fall back to float32."""
    import pytest as _pytest

    from mx_rcnn_tpu.core.optim import make_optimizer
    from mx_rcnn_tpu.core.train import init_variables

    cfg = generate_config("tiny", "PascalVOC")
    params, _ = init_variables(build_model(cfg), KEY, (1, 64, 64, 3))
    bad = cfg.replace_in("default", momentum_dtype="bf16")
    with _pytest.raises(ValueError, match="momentum_dtype"):
        make_optimizer(bad, params, steps_per_epoch=10)
    with _pytest.raises(ValueError, match="compute_dtype"):
        build_model(cfg.replace_in("network", compute_dtype="bfloat"))
