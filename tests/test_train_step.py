"""End-to-end train step tests: single jitted step, freezing, overfit.

The overfit test is the framework's "is it learning" proxy (SURVEY.md §4:
the reference's signal was RPNAcc≈0.9+/RCNNAcc≈0.8+ early in training).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.optim import frozen_mask, make_optimizer
from mx_rcnn_tpu.core.train import (
    Batch,
    init_state,
    loss_and_metrics,
    make_train_step,
    setup_training,
)
from mx_rcnn_tpu.models import build_model

KEY = jax.random.PRNGKey(42)


def tiny_setup(batch_images=1, size=128):
    cfg = generate_config("tiny", "PascalVOC")
    cfg = cfg.replace_in("train", rpn_pre_nms_top_n=256, rpn_post_nms_top_n=64,
                         batch_rois=32, max_gt_boxes=8, rpn_min_size=2)
    model = build_model(cfg)
    state, tx = setup_training(model, cfg, KEY, (batch_images, size, size, 3),
                               steps_per_epoch=100)
    return cfg, model, tx, state




def make_batch(n=1, size=128, seed=0):
    rng = np.random.RandomState(seed)
    images = jnp.array(rng.randn(n, size, size, 3).astype(np.float32))
    im_info = jnp.tile(jnp.array([[float(size), float(size), 1.0]]), (n, 1))
    g = 8
    gt_boxes = jnp.zeros((n, g, 4))
    gt_classes = jnp.zeros((n, g), jnp.int32)
    gt_valid = jnp.zeros((n, g), bool)
    for i in range(n):
        gt_boxes = gt_boxes.at[i, 0].set(jnp.array([20.0, 24.0, 70.0, 90.0]))
        gt_classes = gt_classes.at[i, 0].set(7)
        gt_valid = gt_valid.at[i, 0].set(True)
        gt_boxes = gt_boxes.at[i, 1].set(jnp.array([80.0, 30.0, 120.0, 70.0]))
        gt_classes = gt_classes.at[i, 1].set(12)
        gt_valid = gt_valid.at[i, 1].set(True)
    return Batch(images, im_info, gt_boxes, gt_classes, gt_valid)


def test_loss_and_metrics_finite():
    cfg, model, tx, state = tiny_setup()
    batch = make_batch()
    loss, metrics = loss_and_metrics(model, state.params, state.batch_stats,
                                     batch, KEY, cfg)
    assert np.isfinite(float(loss))
    for k, v in metrics.items():
        assert np.isfinite(float(v)), k
    assert 0.0 <= float(metrics["rpn_acc"]) <= 1.0
    assert float(metrics["num_fg"]) >= 1  # gt-append guarantees fg


def test_train_step_updates_params_and_step():
    cfg, model, tx, state = tiny_setup()
    step = jax.jit(make_train_step(model, cfg, tx))
    batch = make_batch()
    new_state, metrics = step(state, batch, KEY)
    assert int(new_state.step) == 1
    # some parameter must have moved
    diff = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()),
                        state.params, new_state.params)
    assert max(jax.tree.leaves(diff)) > 0
    # batch_stats are frozen — must be bit-identical
    same = jax.tree.map(lambda a, b: bool((a == b).all()),
                        state.batch_stats, new_state.batch_stats)
    assert all(jax.tree.leaves(same))


def test_frozen_params_do_not_move():
    cfg = generate_config("tiny", "PascalVOC")
    cfg = cfg.replace_in("network", fixed_params=("conv1",))
    cfg = cfg.replace_in("train", rpn_pre_nms_top_n=256, rpn_post_nms_top_n=64,
                         batch_rois=32, max_gt_boxes=8, rpn_min_size=2)
    model = build_model(cfg)
    state, tx = setup_training(model, cfg, KEY, (1, 128, 128, 3),
                               steps_per_epoch=100)
    step = jax.jit(make_train_step(model, cfg, tx))
    new_state, _ = step(state, make_batch(), KEY)
    frozen_before = state.params["backbone"]["conv1"]["kernel"]
    frozen_after = new_state.params["backbone"]["conv1"]["kernel"]
    assert bool((frozen_before == frozen_after).all())
    live_before = state.params["backbone"]["conv2"]["kernel"]
    live_after = new_state.params["backbone"]["conv2"]["kernel"]
    assert float(jnp.abs(live_before - live_after).max()) > 0


def test_frozen_mask_prefixes():
    cfg = generate_config("resnet101", "PascalVOC")
    fake_params = {
        "backbone": {
            "conv0": {"kernel": jnp.zeros(1)},
            "stage1_unit1": {"conv1": {"kernel": jnp.zeros(1)}},
            "stage2_unit1": {"conv1": {"kernel": jnp.zeros(1)}},
            "bn_data": {"scale": jnp.zeros(1)},
        },
        "rpn": {"rpn_conv_3x3": {"kernel": jnp.zeros(1)}},
    }
    mask = frozen_mask(fake_params, cfg.network.fixed_params)
    assert mask["backbone"]["conv0"]["kernel"] is False
    assert mask["backbone"]["stage1_unit1"]["conv1"]["kernel"] is False
    assert mask["backbone"]["bn_data"]["scale"] is False
    assert mask["backbone"]["stage2_unit1"]["conv1"]["kernel"] is True
    assert mask["rpn"]["rpn_conv_3x3"]["kernel"] is True


def test_frozen_mask_bn_affine_network_wide():
    """Ref ResNet FIXED_PARAMS lists 'gamma'/'beta': EVERY BatchNorm affine
    is frozen (ADVICE r1 medium), including unfrozen stages and the head —
    but not conv kernels there, and not non-BN biases."""
    cfg = generate_config("resnet101", "PascalVOC")
    fake_params = {
        "backbone": {
            "stage3_unit5": {
                "bn1": {"scale": jnp.zeros(1), "bias": jnp.zeros(1)},
                "conv1": {"kernel": jnp.zeros(1)},
            },
        },
        "head": {
            "stage4_unit1": {"bn2": {"scale": jnp.zeros(1)}},
            "bn1": {"scale": jnp.zeros(1), "bias": jnp.zeros(1)},
        },
        "cls_score": {"kernel": jnp.zeros(1), "bias": jnp.zeros(1)},
    }
    mask = frozen_mask(fake_params, cfg.network.fixed_params)
    assert mask["backbone"]["stage3_unit5"]["bn1"]["scale"] is False
    assert mask["backbone"]["stage3_unit5"]["bn1"]["bias"] is False
    assert mask["backbone"]["stage3_unit5"]["conv1"]["kernel"] is True
    assert mask["head"]["stage4_unit1"]["bn2"]["scale"] is False
    assert mask["head"]["bn1"]["scale"] is False
    # dense bias is NOT a BN beta
    assert mask["cls_score"]["bias"] is True
    assert mask["cls_score"]["kernel"] is True
    # shared-stage freezing must leave stage4 trainable (ADVICE r1 low)
    shared = frozen_mask(fake_params, cfg.network.fixed_params_shared)
    assert shared["head"]["stage4_unit1"]["bn2"]["scale"] is False  # BN affine
    assert shared["backbone"]["stage3_unit5"]["conv1"]["kernel"] is False


@pytest.mark.slow
def test_overfit_single_batch():
    """~40 SGD steps on one synthetic image must drive the losses down and
    the accuracies up — the smoke signal that gradients flow end-to-end."""
    cfg, model, tx, state = tiny_setup()
    cfg2 = cfg.replace_in("default", e2e_lr=0.02)
    tx2 = make_optimizer(cfg2, state.params, steps_per_epoch=10_000)
    state = init_state(model, KEY, tx2, (1, 128, 128, 3))
    step = jax.jit(make_train_step(model, cfg2, tx2))
    batch = make_batch()
    first = None
    for i in range(40):
        state, metrics = step(state, batch, KEY)
        if first is None:
            first = {k: float(v) for k, v in metrics.items()}
    last = {k: float(v) for k, v in metrics.items()}
    assert last["loss"] < first["loss"] * 0.7, (first, last)
    assert last["rpn_acc"] >= 0.9, (first, last)
    assert last["rcnn_acc"] >= 0.8, (first, last)


def test_lr_schedule_warmup_and_decay():
    """Linear warmup ramps warmup_lr -> base_lr, then step decay applies at
    epoch boundaries counted from global step 0 (ref
    WarmupMultiFactorScheduler semantics)."""
    import numpy as np

    from mx_rcnn_tpu.core.optim import lr_schedule

    sched = lr_schedule(0.01, (2,), steps_per_epoch=100, factor=0.1,
                        warmup_step=50, warmup_lr=0.001)
    np.testing.assert_allclose(float(sched(0)), 0.001)
    np.testing.assert_allclose(float(sched(25)), 0.0055, rtol=1e-6)
    np.testing.assert_allclose(float(sched(50)), 0.01, rtol=1e-6)
    np.testing.assert_allclose(float(sched(199)), 0.01, rtol=1e-6)
    np.testing.assert_allclose(float(sched(200)), 0.001, rtol=1e-6)
    # warmup off: plain step decay
    plain = lr_schedule(0.01, (2,), steps_per_epoch=100, factor=0.1)
    np.testing.assert_allclose(float(plain(0)), 0.01, rtol=1e-6)
    np.testing.assert_allclose(float(plain(200)), 0.001, rtol=1e-6)
