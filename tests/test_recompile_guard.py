"""Recompile/leak guard — the RUNTIME twin of graphlint (ISSUE 1).

graphlint catches graph-hygiene bugs the AST can see; this harness
catches what it cannot: the canonical tiny train step must compile
**exactly once per (mode, shape bucket)**, and the fused proposal/target
ops must not leak tracers.

Compiles are counted two ways, because they fail differently:

* ``jitted._cache_size()`` — entries in the callable's in-memory pjit
  cache.  Immune to the persistent XLA compilation cache the test
  harness keeps warm (``tests/conftest.py``), so the per-bucket budget
  is exact across cold and warm runs.
* ``jax.monitoring`` lowering events
  (``/jax/core/compile/jaxpr_to_mlir_module_duration``) — fired on every
  pjit cache MISS regardless of whether the backend compile later hits
  the persistent cache.  This is the detector that catches the per-call
  ``jax.jit(functools.partial(...))`` anti-pattern (graphlint GL301): a
  fresh wrapper per step keeps each wrapper's ``_cache_size()`` at 1
  while re-tracing and re-lowering every call.
"""

import functools

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.train import Batch, make_train_step, setup_training
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.ops.anchors import generate_shifted_anchors
from mx_rcnn_tpu.ops.proposal import propose
from mx_rcnn_tpu.ops.targets import anchor_target, proposal_target

KEY = jax.random.PRNGKey(0)

# the two shape buckets of the canonical tiny recipe: one XLA program
# per bucket serves the whole epoch (docs/DESIGN.md)
BUCKETS = ((64, 64), (64, 96))

_EVENTS = {"lowerings": 0}


def _on_event_duration(event, duration, **kw):
    if event == "/jax/core/compile/jaxpr_to_mlir_module_duration":
        _EVENTS["lowerings"] += 1


jax.monitoring.register_event_duration_secs_listener(_on_event_duration)


class lowering_count:
    """Counts pjit lowerings (cache misses) inside a ``with`` block."""

    def __enter__(self):
        self._start = _EVENTS["lowerings"]
        return self

    def __exit__(self, *exc):
        return False

    @property
    def n(self) -> int:
        return _EVENTS["lowerings"] - self._start


def _tiny_setup(batch_images=1):
    cfg = generate_config("tiny", "PascalVOC")
    cfg = cfg.replace_in("train", rpn_pre_nms_top_n=64,
                         rpn_post_nms_top_n=16, batch_rois=8,
                         max_gt_boxes=4, rpn_min_size=2, rpn_batch_size=32)
    model = build_model(cfg)
    state, tx = setup_training(
        model, cfg, KEY, (batch_images,) + BUCKETS[0] + (3,),
        steps_per_epoch=10)
    return cfg, model, tx, state


def _bucket_batch(h, w, n=1, seed=0):
    rng = np.random.RandomState(seed)
    images = jnp.asarray(rng.randn(n, h, w, 3).astype(np.float32))
    im_info = jnp.tile(jnp.asarray([[float(h), float(w), 1.0]]), (n, 1))
    g = 4
    gt_boxes = jnp.zeros((n, g, 4)).at[:, 0].set(
        jnp.asarray([8.0, 8.0, 40.0, 36.0]))
    gt_classes = jnp.zeros((n, g), jnp.int32).at[:, 0].set(3)
    gt_valid = jnp.zeros((n, g), bool).at[:, 0].set(True)
    return Batch(images, im_info, gt_boxes, gt_classes, gt_valid)


@pytest.mark.slow
def test_train_step_compiles_once_per_mode_and_bucket():
    """The pinned budget: ONE compile per (mode, shape bucket), zero
    recompiles on every later step."""
    cfg, model, tx, state = _tiny_setup()
    batches = {b: _bucket_batch(*b) for b in BUCKETS}

    for mode in ("e2e", "rpn"):
        step = jax.jit(make_train_step(model, cfg, tx, mode=mode))
        for b in BUCKETS:
            for _ in range(2):  # second pass must hit the cache
                state2, metrics = step(state, batches[b], KEY)
                jax.block_until_ready(metrics)
        assert step._cache_size() == len(BUCKETS), (
            f"mode={mode}: {step._cache_size()} compiles for "
            f"{len(BUCKETS)} buckets")
        # warm steps must not lower anything — the whole-epoch invariant
        with lowering_count() as lc:
            for b in BUCKETS:
                _, metrics = step(state, batches[b], KEY)
                jax.block_until_ready(metrics)
        assert lc.n == 0, f"mode={mode}: {lc.n} recompiles on warm steps"


@pytest.mark.slow
def test_per_call_jit_partial_trips_the_guard():
    """The deliberately injected anti-pattern (graphlint GL301 at
    runtime): wrapping the step in a FRESH ``jax.jit(partial(...))``
    every call re-traces per step.  ``_cache_size()`` on each fresh
    wrapper stays 1 — only the monitoring counter sees the churn, which
    is why the guard watches lowering events."""
    cfg, model, tx, state = _tiny_setup()
    step_fn = make_train_step(model, cfg, tx)
    batch = _bucket_batch(*BUCKETS[0])

    # healthy pattern first: jit once, warm it, then measure zero
    good = jax.jit(step_fn)
    _, m = good(state, batch, KEY)
    jax.block_until_ready(m)
    with lowering_count() as lc:
        for _ in range(3):
            _, m = good(state, batch, KEY)
            jax.block_until_ready(m)
    assert lc.n == 0

    calls = 3
    with lowering_count() as lc:
        for _ in range(calls):
            bad = jax.jit(functools.partial(step_fn))
            _, m = bad(state, batch, KEY)
            jax.block_until_ready(m)
            assert bad._cache_size() == 1  # blind to the churn
    assert lc.n >= calls, (
        f"guard missed the per-call jit churn: {lc.n} lowerings "
        f"for {calls} calls")


def test_fused_ops_trace_without_leaks():
    """``jax.checking_leaks`` over the fused proposal/target ops — odd
    grid sizes force a fresh trace (cached signatures would make the
    check vacuous)."""
    anchors = jnp.asarray(generate_shifted_anchors(5, 7, 16))
    n = anchors.shape[0]
    gt = jnp.asarray([[8.0, 8.0, 60.0, 52.0], [0.0, 0.0, 0.0, 0.0]])
    gt_valid = jnp.asarray([True, False])
    gt_classes = jnp.asarray([3, 0], jnp.int32)
    im_info = jnp.asarray([80.0, 112.0, 1.0])
    with jax.checking_leaks():
        at = anchor_target(anchors, gt, gt_valid, im_info, KEY,
                           rpn_batch_size=16)
        jax.block_until_ready(at.labels)
        scores = jax.random.uniform(KEY, (n,))
        deltas = jnp.zeros((n, 4))
        rois, roi_scores, roi_valid = propose(
            scores, deltas, anchors, im_info, pre_nms_top_n=32,
            post_nms_top_n=8, min_size=2)
        jax.block_until_ready(rois)
        pt = proposal_target(rois, roi_valid, gt, gt_classes, gt_valid,
                             KEY, num_classes=5, batch_rois=8)
        jax.block_until_ready(pt.rois)


def test_leak_guard_detects_a_planted_leak():
    """Sensitivity check: the harness must actually catch a leaked
    tracer, or the clean run above proves nothing."""
    stash = []

    @jax.jit
    def leaky(x):
        stash.append(x)  # the classic bug: tracer escapes via closure
        return x * 2

    with pytest.raises(Exception, match="[Ll]eak"):
        with jax.checking_leaks():
            jax.block_until_ready(leaky(jnp.ones((4,))))
