"""graphlint contract tests (ISSUE 1 tentpole).

Two pinned properties:
* the SHIPPED tree is clean — zero unwaived findings over ``mx_rcnn_tpu``
  (every waiver carries a written reason), so ``make lint`` gates PRs;
* the fixture file (``tests/fixtures/ops/graphlint_bad.py``) trips EVERY
  rule — the linter cannot silently lose a rule.

Plus behavioral tests of the parts that make the tool trustworthy: the
static-expression classifier (what it must NOT flag), the jit-scope
closure (host helpers called from traced code ARE flagged), and the
waiver mechanism (reasoned waivers silence, bare waivers are findings).
"""

import os
import textwrap

import pytest

from mx_rcnn_tpu.analysis.graphlint import RULES, lint_paths, main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "mx_rcnn_tpu")
FIXTURE = os.path.join(REPO, "tests", "fixtures", "ops", "graphlint_bad.py")


def test_shipped_tree_has_zero_unwaived_findings():
    findings = lint_paths([PKG])
    active = [f for f in findings if f.waived is None]
    assert active == [], "\n".join(f.render() for f in active)
    # waivers that do exist all carry reasons
    for f in findings:
        if f.waived is not None:
            assert f.waived.strip(), f.render()


def test_cli_exit_codes(capsys):
    assert main([PKG]) == 0
    assert main([FIXTURE]) == 1
    capsys.readouterr()


def test_fixture_trips_every_rule():
    findings = lint_paths([FIXTURE])
    codes = {f.code for f in findings}
    assert codes == set(RULES), (
        f"missing: {set(RULES) - codes}, unexpected: {codes - set(RULES)}")
    # the reasonless GL401 waiver silences its finding but surfaces GL001
    waived = [f for f in findings if f.waived is not None]
    assert any(f.code == "GL401" for f in waived)
    assert any(f.code == "GL001" for f in findings)


def _lint_snippet(tmp_path, source):
    d = tmp_path / "ops"
    d.mkdir(exist_ok=True)
    p = d / "snippet.py"
    p.write_text(textwrap.dedent(source))
    return lint_paths([str(p)])


def test_static_expressions_are_not_flagged(tmp_path):
    """Trace-time-static coercions, shape arithmetic, static branches and
    host numpy over static values are all legitimate — zero findings."""
    findings = _lint_snippet(tmp_path, """\
        import functools
        from typing import Tuple

        import numpy as np
        import jax
        import jax.numpy as jnp

        def blocks(r: int) -> Tuple[int, int]:
            return (8 if r >= 8 else r), 128

        @functools.partial(jax.jit, static_argnames=("k", "flag"))
        def fine(x, k: int = 4, flag: bool = False):
            n = x.shape[0]
            quota = int(round(0.5 * k))          # statics: no GL103
            denom = float(x.size)                # .size is static
            rb, cb = blocks(n)                   # static via return ann
            if rb > 4:                           # static test: no GL203
                x = x * 2.0
            if flag:                             # static arg: no GL203
                x = x + 1.0
            grid = np.arange(k) * n              # numpy on statics: no GL101
            pad = (-n) % rb
            two = x[x.shape[0] - n]              # static index: no GL202
            shp = (1,) + x.shape                 # tuple concat: no GL403
            return x / denom, quota, grid, pad, two, shp
        """)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_jit_closure_reaches_helpers(tmp_path):
    """A helper CALLED from a jitted function inherits jit scope — the
    host-sync in it is flagged even though the helper itself carries no
    decorator."""
    findings = _lint_snippet(tmp_path, """\
        import numpy as np
        import jax
        import jax.numpy as jnp

        def helper(x):
            return np.sum(x)     # traced caller -> GL101 here

        @jax.jit
        def entry(x):
            return helper(x)
        """)
    assert [f.code for f in findings] == ["GL101"]
    assert "helper" in findings[0].func


def test_pragma_marks_factory_closures(tmp_path):
    """``# graphlint: jit`` covers functions traced through indirection
    (factory-returned closures); ``# graphlint: host`` opts a function
    out of jit analysis entirely."""
    findings = _lint_snippet(tmp_path, """\
        import jax.numpy as jnp

        def make_step():
            # graphlint: jit
            def step(x):
                return float(x)          # GL103
            return step

        def host_tool(x):  # graphlint: host
            return float(x)              # host scope: clean
        """)
    assert [f.code for f in findings] == ["GL103"]
    assert "step" in findings[0].func


def test_waiver_requires_reason(tmp_path):
    reasoned = _lint_snippet(tmp_path, """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return float(x)  # graphlint: disable=GL103 benchmark scaffold
        """)
    assert [f.code for f in reasoned] == ["GL103"]
    assert reasoned[0].waived == "benchmark scaffold"
    bare = _lint_snippet(tmp_path, """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return float(x)  # graphlint: disable=GL103
        """)
    codes = {f.code for f in bare}
    assert "GL001" in codes  # the bare waiver is itself a finding


def test_flax_methods_are_jit_scope(tmp_path):
    findings = _lint_snippet(tmp_path, """\
        import flax.linen as nn
        import jax.numpy as jnp

        class Net(nn.Module):
            def __call__(self, x):
                return jnp.nonzero(x)    # GL201 inside a module method
        """)
    assert [f.code for f in findings] == ["GL201"]


def test_list_rules_names_every_code(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for code in RULES:
        assert code in out


def test_checkout_path_does_not_leak_into_graph_scope(tmp_path):
    """A checkout under a directory named 'models' (or ops/core/parallel)
    must not classify host modules as graph scope: scope derives from the
    path relative to the linted root, not absolute components."""
    pkg = tmp_path / "models" / "pkg"
    (pkg / "data").mkdir(parents=True)
    (pkg / "data" / "host.py").write_text(
        "import numpy as np\nX = np.float64(1.0)\n")
    findings = lint_paths([str(pkg)])
    assert findings == [], "\n".join(f.render() for f in findings)
    # ...while a real graph-scope dir under the same root still counts
    (pkg / "ops").mkdir()
    (pkg / "ops" / "g.py").write_text(
        "import numpy as np\nX = np.float64(1.0)\n")
    codes = [f.code for f in lint_paths([str(pkg)])]
    assert codes == ["GL401"]


def test_cli_fails_on_missing_or_empty_paths(tmp_path, capsys):
    """A typo'd path must fail the gate, not lint zero files and pass."""
    assert main([str(tmp_path / "no_such_dir")]) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert main([str(empty)]) == 2
    capsys.readouterr()
