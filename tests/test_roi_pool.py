"""ROIAlign / ROIPool tests vs small hand-checkable feature maps."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mx_rcnn_tpu.ops.roi_pool import roi_align, roi_pool


def ramp_feature(h, w, c=1):
    """feature[y, x, 0] = y * w + x — linear in both axes."""
    return jnp.arange(h * w, dtype=jnp.float32).reshape(h, w, 1).repeat(c, axis=2)


def test_roi_align_constant_map():
    feat = jnp.ones((16, 16, 3))
    rois = jnp.array([[0.0, 0.0, 63.0, 63.0]])  # image coords, stride 4
    out = roi_align(feat, rois, (7, 7), spatial_scale=0.25)
    assert out.shape == (1, 7, 7, 3)
    np.testing.assert_allclose(np.asarray(out), 1.0, atol=1e-5)


def test_roi_align_linear_map_is_exact():
    # bilinear sampling of a linear function reproduces it exactly at bin centers
    h = w = 32
    feat = ramp_feature(h, w)
    # roi covering feature region [4, 20] x [8, 24] at stride 1
    rois = jnp.array([[8.0, 4.0, 24.0, 20.0]])
    ph = pw = 4
    out = np.asarray(roi_align(feat, rois, (ph, pw), spatial_scale=1.0))[0, :, :, 0]
    bin_h = 16.0 / ph
    bin_w = 16.0 / pw
    for p in range(ph):
        for q in range(pw):
            cy = 4.0 + (p + 0.5) * bin_h - 0.5
            cx = 8.0 + (q + 0.5) * bin_w - 0.5
            want = cy * w + cx
            np.testing.assert_allclose(out[p, q], want, rtol=1e-5)


def test_roi_align_batched_rois_shapes():
    feat = jnp.ones((38, 64, 8))
    rois = jnp.tile(jnp.array([[0.0, 0.0, 100.0, 100.0]]), (5, 1))
    out = roi_align(feat, rois, (14, 14), 1.0 / 16)
    assert out.shape == (5, 14, 14, 8)


def test_roi_pool_max_semantics():
    feat = jnp.zeros((8, 8, 1)).at[2, 3, 0].set(7.0).at[6, 6, 0].set(5.0)
    rois = jnp.array([[0.0, 0.0, 7.0, 7.0]])  # whole map, stride 1
    out = np.asarray(roi_pool(feat, rois, (2, 2), 1.0))[0, :, :, 0]
    # quadrant maxes: TL contains (2,3)->7; BR contains (6,6)->5
    assert out[0, 0] == 7.0
    assert out[1, 1] == 5.0
    assert out[0, 1] == 0.0 and out[1, 0] == 0.0


def test_roi_pool_single_cell_roi():
    feat = ramp_feature(8, 8)
    rois = jnp.array([[3.0, 2.0, 3.0, 2.0]])  # one pixel at (y=2, x=3)
    out = np.asarray(roi_pool(feat, rois, (2, 2), 1.0))[0]
    # all bins cover the same single pixel (value 2*8+3=19)
    np.testing.assert_allclose(out[..., 0], 19.0)


def test_roi_align_bf16_passthrough():
    feat = jnp.ones((16, 16, 4), dtype=jnp.bfloat16)
    rois = jnp.array([[0.0, 0.0, 32.0, 32.0]])
    out = roi_align(feat, rois, (7, 7), 0.25)
    assert out.dtype == jnp.bfloat16


def test_roi_align_bf16_close_to_fp32():
    """The bf16 fast path (default precision, folded-mean matrices) must
    track the fp32 'highest' path within bf16 quantization error."""
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    feat = rng.randn(24, 32, 16).astype(np.float32)
    rois = np.array([[10.0, 8.0, 200.0, 150.0],
                     [0.0, 0.0, 511.0, 383.0],
                     [33.3, 21.7, 95.2, 64.9]], np.float32)
    out32 = np.asarray(roi_align(jnp.asarray(feat), rois, (7, 7), 1 / 16.0))
    out16 = np.asarray(roi_align(jnp.asarray(feat, jnp.bfloat16), rois,
                                 (7, 7), 1 / 16.0)).astype(np.float32)
    # bf16 has ~2-3 significant decimal digits; interpolated activations are
    # O(1), so 3% absolute tolerance is ~4x the expected rounding noise
    np.testing.assert_allclose(out16, out32, atol=3e-2)


# ---------------------------------------------------------------------------
# Pallas fused ROIAlign (ops/roi_align_pallas.py): parity vs the einsum
# oracle in interpreter mode (r5 — removes the HBM inter-matmul
# intermediate measured at 5.84 ms of the 26.44 ms train step).
# ---------------------------------------------------------------------------

def _rand_rois(rng, n, r, h_img, w_img):
    x1 = rng.uniform(0, w_img * 0.7, (n, r))
    y1 = rng.uniform(0, h_img * 0.7, (n, r))
    bw = rng.uniform(8, w_img * 0.4, (n, r))
    bh = rng.uniform(8, h_img * 0.4, (n, r))
    return np.stack([x1, y1, x1 + bw, y1 + bh], axis=-1).astype(np.float32)


def test_roi_align_pallas_forward_matches_einsum():
    from mx_rcnn_tpu.ops.roi_align_pallas import roi_align_pallas
    from mx_rcnn_tpu.ops.roi_pool import roi_align

    rng = np.random.RandomState(0)
    n, h, w, c, r = 2, 19, 32, 64, 12  # r NOT a multiple of RB=8: pad path
    feat = rng.randn(n, h, w, c).astype(np.float32)
    rois = _rand_rois(rng, n, r, h * 16, w * 16)
    want = jax.vmap(lambda f, b: roi_align(f, b, (7, 7), 1 / 16.0))(
        jnp.asarray(feat), jnp.asarray(rois))
    got = roi_align_pallas(jnp.asarray(feat), jnp.asarray(rois), (7, 7),
                           1 / 16.0, 2, True)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_roi_align_pallas_grad_matches_einsum():
    """d(pooled)/d(features) must match the einsum path's autodiff — the
    custom VJP re-derives the transposed contractions by hand."""
    from mx_rcnn_tpu.ops.roi_align_pallas import roi_align_pallas
    from mx_rcnn_tpu.ops.roi_pool import roi_align

    rng = np.random.RandomState(1)
    n, h, w, c, r = 2, 10, 16, 32, 8
    feat = jnp.asarray(rng.randn(n, h, w, c).astype(np.float32))
    rois = jnp.asarray(_rand_rois(rng, n, r, h * 16, w * 16))
    cot = jnp.asarray(rng.randn(n, r, 7, 7, c).astype(np.float32))

    def loss_ein(f):
        p = jax.vmap(lambda fi, b: roi_align(fi, b, (7, 7), 1 / 16.0))(
            f, rois)
        return jnp.sum(p * cot)

    def loss_pal(f):
        p = roi_align_pallas(f, rois, (7, 7), 1 / 16.0, 2, True)
        return jnp.sum(p * cot)

    g_ein = jax.grad(loss_ein)(feat)
    g_pal = jax.grad(loss_pal)(feat)
    np.testing.assert_allclose(np.asarray(g_pal), np.asarray(g_ein),
                               atol=1e-4, rtol=1e-4)


def test_roi_align_batched_dispatch():
    """backend='jnp' and 'pallas' (interpret via CPU default resolve →
    jnp; explicit pallas exercised above) agree; unknown backend raises."""
    from mx_rcnn_tpu.ops.roi_pool import roi_align_batched

    rng = np.random.RandomState(2)
    feat = jnp.asarray(rng.randn(1, 8, 8, 16).astype(np.float32))
    rois = jnp.asarray(_rand_rois(rng, 1, 4, 128, 128))
    out = roi_align_batched(feat, rois, (7, 7), 1 / 16.0)
    assert out.shape == (1, 4, 7, 7, 16)
    with pytest.raises(ValueError, match="unknown roi_align backend"):
        roi_align_batched(feat, rois, backend="cuda")


def test_roi_align_pallas_rois_grad_is_explicit_zeros():
    """ADVICE r5: the custom-VJP bwd must return a zeros cotangent for
    rois, not bare None — grads w.r.t. rois then trace cleanly while rois
    stay non-differentiable data (like the reference ROIPooling)."""
    from mx_rcnn_tpu.ops.roi_align_pallas import roi_align_pallas

    rng = np.random.RandomState(3)
    n, h, w, c, r = 1, 8, 8, 16, 4
    feat = jnp.asarray(rng.randn(n, h, w, c).astype(np.float32))
    rois = jnp.asarray(_rand_rois(rng, n, r, h * 16, w * 16))

    g_feat, g_rois = jax.grad(
        lambda f, b: jnp.sum(roi_align_pallas(f, b, (7, 7), 1 / 16.0, 2,
                                              True)),
        argnums=(0, 1))(feat, rois)
    assert g_rois.shape == rois.shape
    assert g_rois.dtype == rois.dtype
    assert not np.any(np.asarray(g_rois))
    assert np.any(np.asarray(g_feat))


# ---------------------------------------------------------------------------
# Blocked ROIAlign (r6 tentpole, ops/roi_pool.py — roi_align_blocked): the
# einsum pair run lax.map-chunked over ROIs, bit-equal forward (the ROI
# axis is a batch axis of both contractions — chunking it cannot change any
# per-element reduction), custom-VJP backward blocked the same way.
# ---------------------------------------------------------------------------

from mx_rcnn_tpu.ops.roi_pool import roi_align_batched, roi_align_blocked


@pytest.mark.parametrize("r,chunk", [(13, 4), (8, 8), (5, 64), (1, 4)])
def test_roi_align_blocked_forward_bit_equal_fp32(r, chunk):
    """Odd ROI counts vs chunk size: forward must be BIT-equal to the
    einsum pair, including when padding rounds R up and when one chunk
    covers everything."""
    rng = np.random.RandomState(0)
    feat = jnp.asarray(rng.randn(19, 32, 16).astype(np.float32))
    rois = jnp.asarray(_rand_rois(rng, 1, r, 19 * 16, 32 * 16)[0])
    want = roi_align(feat, rois, (7, 7), 1 / 16.0)
    got = roi_align_blocked(feat, rois, (7, 7), 1 / 16.0, 2, chunk)
    assert got.dtype == want.dtype
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_roi_align_blocked_forward_bit_equal_bf16():
    """The bf16 fast path (default precision) is chunked identically."""
    rng = np.random.RandomState(1)
    feat = jnp.asarray(rng.randn(24, 16, 8).astype(np.float32),
                       jnp.bfloat16)
    rois = jnp.asarray(_rand_rois(rng, 1, 11, 24 * 16, 16 * 16)[0])
    want = roi_align(feat, rois, (7, 7), 1 / 16.0)
    got = roi_align_blocked(feat, rois, (7, 7), 1 / 16.0, 2, 4)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(want.astype(jnp.float32)),
        np.asarray(got.astype(jnp.float32)))


def _dyadic_case():
    """Inputs on which every product and partial sum is exactly
    representable (small integers, power-of-two ROI geometry at pooled
    size 4 → dyadic bilinear weights): fp addition is then associative,
    so chunked and monolithic backward reductions must agree BIT-for-bit
    — this pins the contract (same math) independently of XLA's
    reduction-order freedom on general inputs."""
    rng = np.random.RandomState(2)
    feat = rng.randint(-4, 5, (16, 16, 8)).astype(np.float32)
    rois = np.array([[0, 0, 64, 64], [16, 32, 80, 96], [8, 8, 40, 72],
                     [32, 0, 96, 32], [0, 16, 32, 48]], np.float32)
    cot = rng.randint(-2, 3, (5, 4, 4, 8)).astype(np.float32)
    return feat, rois, cot


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_roi_align_blocked_grads_bit_equal_exact_vectors(dtype):
    """Custom-VJP grads vs einsum autodiff, both dtype paths, BIT-equal
    on reduction-order-insensitive vectors (odd chunking: 5 ROIs, chunk
    2 → 3 chunks with padding)."""
    feat_np, rois_np, cot_np = _dyadic_case()
    feat = jnp.asarray(feat_np).astype(
        jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    rois, cot = jnp.asarray(rois_np), jnp.asarray(cot_np)

    g_ein = jax.grad(lambda f: jnp.sum(
        roi_align(f, rois, (4, 4), 1 / 16.0).astype(jnp.float32)
        * cot))(feat)
    g_blk = jax.grad(lambda f: jnp.sum(
        roi_align_blocked(f, rois, (4, 4), 1 / 16.0, 2,
                          2).astype(jnp.float32) * cot))(feat)
    assert g_blk.dtype == g_ein.dtype
    np.testing.assert_array_equal(
        np.asarray(g_ein.astype(jnp.float32)),
        np.asarray(g_blk.astype(jnp.float32)))


def test_roi_align_blocked_grads_close_random():
    """On general random vectors the chunked backward accumulates the
    same sum in a different association — grads agree to float tolerance
    (measured ~1 ulp of O(1) values), while the FORWARD stays bit-equal
    even here."""
    rng = np.random.RandomState(3)
    feat = jnp.asarray(rng.randn(19, 32, 16).astype(np.float32))
    rois = jnp.asarray(_rand_rois(rng, 1, 13, 19 * 16, 32 * 16)[0])
    cot = jnp.asarray(rng.randn(13, 7, 7, 16).astype(np.float32))

    g_ein = jax.grad(lambda f: jnp.sum(
        roi_align(f, rois, (7, 7), 1 / 16.0) * cot))(feat)
    g_blk = jax.grad(lambda f: jnp.sum(
        roi_align_blocked(f, rois, (7, 7), 1 / 16.0, 2, 4) * cot))(feat)
    np.testing.assert_allclose(np.asarray(g_blk), np.asarray(g_ein),
                               atol=1e-5, rtol=1e-5)


def test_roi_align_blocked_single_chunk_grads_bit_equal_random():
    """chunk >= R is ONE chunk of the identical einsums — grads bit-equal
    even on random vectors (no cross-chunk accumulation exists)."""
    rng = np.random.RandomState(4)
    feat = jnp.asarray(rng.randn(12, 20, 8).astype(np.float32))
    rois = jnp.asarray(_rand_rois(rng, 1, 7, 12 * 16, 20 * 16)[0])
    cot = jnp.asarray(rng.randn(7, 7, 7, 8).astype(np.float32))
    g_ein = jax.grad(lambda f: jnp.sum(
        roi_align(f, rois, (7, 7), 1 / 16.0) * cot))(feat)
    g_blk = jax.grad(lambda f: jnp.sum(
        roi_align_blocked(f, rois, (7, 7), 1 / 16.0, 2, 64) * cot))(feat)
    np.testing.assert_array_equal(np.asarray(g_ein), np.asarray(g_blk))


def test_roi_align_blocked_rois_grad_is_explicit_zeros():
    """Same contract as the Pallas backend (and the reference ROIPooling):
    rois are non-differentiable data — zeros cotangent, clean trace."""
    rng = np.random.RandomState(5)
    feat = jnp.asarray(rng.randn(8, 8, 16).astype(np.float32))
    rois = jnp.asarray(_rand_rois(rng, 1, 4, 128, 128)[0])
    g_feat, g_rois = jax.grad(
        lambda f, b: jnp.sum(roi_align_blocked(f, b, (7, 7), 1 / 16.0, 2,
                                               2)),
        argnums=(0, 1))(feat, rois)
    assert g_rois.shape == rois.shape
    assert not np.any(np.asarray(g_rois))
    assert np.any(np.asarray(g_feat))


def test_roi_align_batched_blocked_dispatch():
    """backend='blocked' routes through roi_align_blocked and matches the
    default batched einsum path bit-for-bit."""
    rng = np.random.RandomState(6)
    feat = jnp.asarray(rng.randn(2, 9, 12, 8).astype(np.float32))
    rois = jnp.asarray(_rand_rois(rng, 2, 5, 9 * 16, 12 * 16))
    want = roi_align_batched(feat, rois, (7, 7), 1 / 16.0)
    got = roi_align_batched(feat, rois, (7, 7), 1 / 16.0,
                            backend="blocked", chunk=2)
    assert got.shape == (2, 5, 7, 7, 8)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))
