"""Quantized inference forward path (ops/quant.py + the model threading,
Predictor quant mode, export-store admission — docs/PERF.md "Quantized
inference").

The contracts pinned here, in the ISSUE-9 acceptance order:

* calibration determinism — the same calibration set produces
  BIT-identical activation scales (absmax AND percentile estimators);
* fake-quant (sim) vs real-int8 (native int32-accumulate) equivalence —
  BIT-equal at tile sizes where fp32 accumulation of integer products is
  exact, for both ``dot_general`` and conv;
* fp-path bit-identity with quant off — ``conv()/dense()`` return the
  UNCHANGED flax modules, the quant model's param tree equals the fp
  model's (fp32 checkpoints load unchanged), and Predictor outputs are
  bit-equal to a direct jitted apply;
* export-store admission refusal on any quant-knob mismatch (fp↔quant,
  dtype, estimator, calibration fingerprint);
* the paired gauntlet gate FAILS on the red-team over-quantized arm
  (record-level here; the real-training twin is the gate-marked test at
  the bottom and ``make quant-smoke``).
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.ops.quant import (QuantSpec, calibration_fingerprint,
                                   fake_quant, finalize_calibration, qconv,
                                   qdot, quant_manifest_meta,
                                   quant_program_tag, quantize_act,
                                   quantize_weight, spec_from_config)

from tests.conftest import shrink_tiny_cfg


def _tiny_cfg(**quant_kw):
    cfg = shrink_tiny_cfg(generate_config("tiny", "synthetic"))
    if quant_kw:
        cfg = cfg.replace_in("quant", **quant_kw)
    return cfg


def _tiny_state(cfg, batch=2):
    from mx_rcnn_tpu.core.train import setup_training
    from mx_rcnn_tpu.models import build_model

    model = build_model(cfg)
    state, _ = setup_training(model, cfg, jax.random.PRNGKey(0),
                              (batch, 128, 160, 3), steps_per_epoch=10)
    return model, state.params, state.batch_stats


def _images(n=2, seed=0):
    rng = np.random.RandomState(seed)
    images = (rng.rand(n, 128, 160, 3) * 255.0).astype(np.float32)
    im_info = np.tile(np.array([128, 160, 1.0], np.float32), (n, 1))
    return images, im_info


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------

def test_weight_quant_per_channel_symmetric(rng):
    w = jnp.asarray(rng.randn(3, 3, 8, 16).astype(np.float32)) * \
        jnp.arange(1, 17, dtype=jnp.float32)  # per-channel spread
    spec = QuantSpec()
    q, unit = quantize_weight(w, spec)
    assert q.dtype == jnp.int8 and unit.shape == (16,)
    # symmetric, zero-point 0: zero quantizes to exactly 0
    qz, _ = quantize_weight(jnp.zeros_like(w), spec)
    assert (np.asarray(qz) == 0).all()
    # reconstruction within half a step everywhere (no clipping inside
    # the absmax range by construction)
    err = np.abs(np.asarray(q, np.float32) * np.asarray(unit)
                 - np.asarray(w))
    assert (err <= np.asarray(unit) / 2 + 1e-6).all()
    # per-CHANNEL: each channel's scale tracks its own absmax
    expect = np.abs(np.asarray(w)).max(axis=(0, 1, 2)) / 127.0
    np.testing.assert_allclose(np.asarray(unit), expect, rtol=1e-6)


def test_weight_bits_shrink_the_grid(rng):
    w = jnp.asarray(rng.randn(4, 8).astype(np.float32))
    q8, _ = quantize_weight(w, QuantSpec(weight_bits=8))
    q2, _ = quantize_weight(w, QuantSpec(weight_bits=2))
    assert np.abs(np.asarray(q8)).max() > 1
    assert set(np.unique(np.asarray(q2))) <= {-1, 0, 1}


def test_fake_quant_round_trip_is_idempotent(rng):
    x = jnp.asarray(rng.randn(64).astype(np.float32))
    est = jnp.max(jnp.abs(x))
    spec = QuantSpec(mode="sim")
    once = fake_quant(x, est, spec)
    twice = fake_quant(once, est, spec)
    assert (np.asarray(once) == np.asarray(twice)).all()


@pytest.mark.parametrize("dtype", ["int8", "fp8"])
def test_sim_equals_native_dot_at_tile_level(rng, dtype):
    """The sim/native pin: with K=64 every int32-accumulated sum is
    exactly representable in fp32 (64·127² < 2²⁴), so the two paths are
    BIT-equal; fp8 accumulates fp32 in both paths."""
    x = jnp.asarray(rng.randn(5, 64).astype(np.float32)) * 3.0
    w = jnp.asarray(rng.randn(64, 7).astype(np.float32))
    est = jnp.max(jnp.abs(x))
    sim = qdot(x, w, est, QuantSpec(dtype=dtype, mode="sim"))
    native = qdot(x, w, est, QuantSpec(dtype=dtype, mode="native"))
    assert sim.dtype == native.dtype == jnp.float32
    assert (np.asarray(sim) == np.asarray(native)).all()


def test_sim_equals_native_conv_at_tile_level(rng):
    """Conv tile pin: 3·3·8 = 72 products per output < the fp32-exact
    bound, so int32 and fp32 accumulation agree bit for bit."""
    x = jnp.asarray(rng.randn(2, 10, 12, 8).astype(np.float32)) * 2.0
    k = jnp.asarray(rng.randn(3, 3, 8, 16).astype(np.float32))
    est = jnp.max(jnp.abs(x))
    sim = qconv(x, k, est, QuantSpec(mode="sim"), (1, 1), "SAME")
    native = qconv(x, k, est, QuantSpec(mode="native"), (1, 1), "SAME")
    assert (np.asarray(sim) == np.asarray(native)).all()


def test_quant_spec_validates_knobs():
    with pytest.raises(ValueError, match="dtype"):
        QuantSpec(dtype="int4")
    with pytest.raises(ValueError, match="mode"):
        QuantSpec(mode="fake")
    with pytest.raises(ValueError, match="estimator"):
        QuantSpec(estimator="minmax")
    with pytest.raises(ValueError, match="weight_bits"):
        QuantSpec(weight_bits=1)
    with pytest.raises(ValueError, match="phase"):
        QuantSpec(phase="train")
    # fp8's qmax is the format's own max — narrowed weight_bits would be
    # silently ignored (an fp8 red-team arm must refuse, not no-op)
    with pytest.raises(ValueError, match="weight_bits"):
        QuantSpec(dtype="fp8", weight_bits=2)
    QuantSpec(dtype="fp8")  # full-width fp8 stays valid


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("estimator", ["absmax", "percentile"])
def test_calibration_deterministic(estimator):
    """Same calibration set (same order) ⇒ BIT-identical scales and
    fingerprint — twice in-process and against a freshly built model."""
    from mx_rcnn_tpu.core.tester import calibrate_quant

    cfg = _tiny_cfg(enabled=True, estimator=estimator)
    _, params, bs = _tiny_state(cfg)
    batches = [_images(seed=0), _images(seed=1)]
    a = calibrate_quant(cfg, params, bs, batches=batches)
    b = calibrate_quant(cfg, params, bs, batches=batches)
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb) and all(
        (np.asarray(x) == np.asarray(y)).all() for x, y in zip(la, lb))
    assert calibration_fingerprint(a, cfg.quant) == \
        calibration_fingerprint(b, cfg.quant)


def test_calibration_fingerprint_tracks_knobs_and_scales():
    from mx_rcnn_tpu.core.tester import calibrate_quant

    cfg = _tiny_cfg(enabled=True)
    _, params, bs = _tiny_state(cfg)
    col = calibrate_quant(cfg, params, bs, batches=[_images()])
    fp = calibration_fingerprint(col, cfg.quant)
    # estimator knob changes the fingerprint even at equal scales
    other = cfg.replace_in("quant", estimator="percentile")
    assert calibration_fingerprint(col, other.quant) != fp
    # a different calibration set changes the scales -> the fingerprint
    col2 = calibrate_quant(cfg, params, bs, batches=[_images(seed=7)])
    assert calibration_fingerprint(col2, cfg.quant) != fp


def test_estimators_differ_and_percentile_clips():
    """percentile < absmax on heavy-tailed activations (that's the
    point of the estimator), and both produce a scale per quant layer."""
    from mx_rcnn_tpu.core.tester import calibrate_quant

    base = _tiny_cfg(enabled=True)
    _, params, bs = _tiny_state(base)
    batches = [_images()]
    col_a = calibrate_quant(base, params, bs, batches=batches)
    col_p = calibrate_quant(
        base.replace_in("quant", estimator="percentile", percentile=90.0),
        params, bs, batches=batches)
    la = jax.tree_util.tree_leaves(col_a)
    lp = jax.tree_util.tree_leaves(col_p)
    assert len(la) == len(lp) == 3  # conv1, conv2, head fc
    assert all(float(p) <= float(a) + 1e-6 for a, p in zip(la, lp))
    assert any(float(p) < float(a) for a, p in zip(la, lp))


def test_finalize_calibration_shapes():
    stats = {"layer": {"amax": jnp.asarray(4.0), "psum": jnp.asarray(6.0),
                       "pcnt": jnp.asarray(2.0)}}
    cfg = _tiny_cfg(enabled=True)
    col = finalize_calibration(stats, cfg.quant)
    assert float(col["layer"]["act_scale"]) == 4.0
    colp = finalize_calibration(
        stats, cfg.replace_in("quant", estimator="percentile").quant)
    assert float(colp["layer"]["act_scale"]) == 3.0


# ---------------------------------------------------------------------------
# fp-path bit-identity with quant off + checkpoint compatibility
# ---------------------------------------------------------------------------

def test_fp_path_bit_identical_when_off():
    """With quant disabled the construction path returns the UNCHANGED
    flax modules and the Predictor's outputs equal a direct jitted
    apply bit for bit — the 'every existing fp serving/eval output is
    bit-identical to HEAD' pin."""
    import flax.linen as nn

    from mx_rcnn_tpu.core.tester import Predictor
    from mx_rcnn_tpu.models.layers import conv, dense

    assert type(conv(8)) is nn.Conv
    assert type(dense(8)) is nn.Dense
    cfg = _tiny_cfg()
    assert not cfg.quant.enabled  # off by default
    model, params, bs = _tiny_state(cfg)
    images, im_info = _images()
    pred = Predictor(model, {"params": params, "batch_stats": bs}, cfg)
    assert pred.quant_fingerprint is None
    via_pred = [np.asarray(o) for o in pred.raw(images, im_info)]
    direct = [np.asarray(o) for o in jax.jit(model.apply)(
        {"params": params, "batch_stats": bs}, images, im_info)]
    for a, b in zip(via_pred, direct):
        assert a.dtype == b.dtype and (a == b).all()


def test_quant_model_param_tree_matches_fp():
    """fp32 checkpoints load into the quantized model unchanged: same
    param names, same shapes, same dtypes."""
    from mx_rcnn_tpu.models import build_model

    cfg = _tiny_cfg()
    _, params, _ = _tiny_state(cfg)
    qmodel = build_model(cfg.replace_in("quant", enabled=True))
    images, im_info = _images(1)
    q_init = qmodel.init(jax.random.PRNGKey(0), images, im_info)
    assert jax.tree_util.tree_structure(params) == \
        jax.tree_util.tree_structure(q_init["params"])
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(q_init["params"])):
        assert a.shape == b.shape and a.dtype == b.dtype


def test_quant_predictor_runs_and_redteam_collapses():
    """int8 inference stays close to fp at the feature level; the 2-bit
    red-team arm is catastrophically far — the fast twin of the gate
    direction (`make quant-smoke` / the gate test below measure mAP)."""
    from mx_rcnn_tpu.core.tester import calibrate_quant
    from mx_rcnn_tpu.models import build_model

    cfg = _tiny_cfg()
    model, params, bs = _tiny_state(cfg)
    images, im_info = _images()
    feat_fp = np.asarray(model.apply(
        {"params": params, "batch_stats": bs}, jnp.asarray(images),
        jnp.asarray(im_info), method=model.features), np.float32)
    scale = np.abs(feat_fp).max()

    def feat_q(**kw):
        qcfg = cfg.replace_in("quant", enabled=True, **kw)
        col = calibrate_quant(qcfg, params, bs, batches=[(images, im_info)])
        qm = build_model(qcfg)
        return np.asarray(qm.apply(
            {"params": params, "batch_stats": bs, "quant": col},
            jnp.asarray(images), jnp.asarray(im_info),
            method=qm.features), np.float32)

    rel_int8 = np.abs(feat_q() - feat_fp).max() / scale
    rel_2bit = np.abs(feat_q(weight_bits=2) - feat_fp).max() / scale
    assert rel_int8 < 0.05, rel_int8
    assert rel_2bit > 0.5, rel_2bit
    assert rel_2bit > 10 * rel_int8


def test_quant_program_keys_cannot_collide():
    """A quantized Predictor tags every program key with the recipe +
    calibration fingerprint, so fp and quant programs never share a
    cache (or export) slot."""
    from mx_rcnn_tpu.core.tester import Predictor, quant_predictor

    cfg = _tiny_cfg()
    model, params, bs = _tiny_state(cfg)
    images, im_info = _images()
    fp_pred = Predictor(model, {"params": params, "batch_stats": bs}, cfg)
    qcfg = cfg.replace_in("quant", enabled=True)
    qpred = quant_predictor(qcfg, params, bs, batches=[(images, im_info)])
    k_fp = fp_pred.program_key("rpn", (images, im_info))
    k_q = qpred.program_key("rpn", (images, im_info))
    assert k_fp != k_q
    assert k_q[0].startswith("quant[int8:native:absmax:b8:")
    assert qpred.quant_fingerprint in k_q[0]
    # and the tag helper agrees with the manifest block
    tag = quant_program_tag(qcfg.quant, qpred.quant_fingerprint)
    assert k_q[0] == tag + ":rpn"
    meta = quant_manifest_meta(qcfg.quant, qpred.quant_fingerprint)
    assert meta["calibration_fingerprint"] == qpred.quant_fingerprint


def test_quant_predictor_refuses_uncalibrated_variables():
    from mx_rcnn_tpu.core.tester import Predictor

    cfg = _tiny_cfg(enabled=True)
    from mx_rcnn_tpu.models import build_model

    model = build_model(cfg)
    with pytest.raises(ValueError, match="calibrate first"):
        Predictor(model, {"params": {}, "batch_stats": {}}, cfg)


def test_stem_channel_pad_bit_identity():
    """The layout lever: conv0 padded 3→4 input channels with zero
    inputs produces BIT-identical features when the first 3 kernel
    channels are shared (zero channels contribute exact 0 to every
    conv sum)."""
    from mx_rcnn_tpu.models import build_model

    cfg = _tiny_cfg()
    model, params, bs = _tiny_state(cfg)
    pmodel = build_model(cfg.replace_in("network", stem_channel_pad=4))
    images, im_info = _images()
    p_init = pmodel.init(jax.random.PRNGKey(0), images[:1], im_info[:1])
    p_params = jax.device_get(p_init["params"])
    k3 = np.asarray(params["backbone"]["conv1"]["kernel"])
    k4 = np.array(p_params["backbone"]["conv1"]["kernel"])
    assert k4.shape[2] == 4 and k3.shape[2] == 3
    k4[:, :, :3, :] = k3  # share the real channels; ch 3 sees zeros
    p_params["backbone"]["conv1"]["kernel"] = jnp.asarray(k4)
    for name in ("conv2",):
        p_params["backbone"][name] = params["backbone"][name]
    feat = model.apply({"params": params, "batch_stats": bs},
                       jnp.asarray(images), jnp.asarray(im_info),
                       method=model.features)
    feat_p = pmodel.apply({"params": p_params, "batch_stats": bs},
                          jnp.asarray(images), jnp.asarray(im_info),
                          method=pmodel.features)
    assert (np.asarray(feat) == np.asarray(feat_p)).all()


def test_stem_channel_pad_default_keeps_fingerprint():
    """The layout lever must not invalidate pre-existing manifests /
    export stores at its default: stem_channel_pad=0 stays OUT of the
    config fingerprint, a set lever lands in it."""
    from mx_rcnn_tpu.utils.checkpoint import (_fingerprint_repr,
                                              config_fingerprint)

    cfg = _tiny_cfg()
    assert "stem_channel_pad" not in _fingerprint_repr(cfg.network)
    padded = cfg.replace_in("network", stem_channel_pad=4)
    assert "stem_channel_pad=4" in _fingerprint_repr(padded.network)
    assert config_fingerprint(cfg) != config_fingerprint(padded)


def test_train_refuses_quant_config():
    """Quantization is inference-only: the training entry refuses a
    quant-enabled config up front (the quantized model needs the
    calibrated 'quant' collection a train step never carries)."""
    from mx_rcnn_tpu.tools.train import train_net

    cfg = _tiny_cfg().replace_in("quant", enabled=True)
    with pytest.raises(ValueError, match="inference-only"):
        train_net(cfg, prefix="/nonexistent/never-written")


# ---------------------------------------------------------------------------
# export-store admission
# ---------------------------------------------------------------------------

def _make_store(tmp_path, cfg, quant_meta):
    from mx_rcnn_tpu.serve.export import ExportStore

    store = ExportStore.create(str(tmp_path / "store"), cfg,
                               extra_meta={"quant": quant_meta})
    store.finish()
    return ExportStore(str(tmp_path / "store"))


def test_export_admission_refuses_quant_mismatch(tmp_path):
    """The manifest quant block must equal the loading process's —
    fp↔quant in either direction, dtype, estimator and calibration
    fingerprint mismatches are all refusals."""
    from mx_rcnn_tpu.serve.export import ExportMismatch

    cfg = _tiny_cfg()
    qcfg = cfg.replace_in("quant", enabled=True)
    meta = quant_manifest_meta(qcfg.quant, "f" * 16)
    qstore = _make_store(tmp_path, qcfg, meta)
    # quant store + matching quant process: admitted
    qstore.check(qcfg, quant_fingerprint="f" * 16)
    # fp process against a quant store: refused
    with pytest.raises(ExportMismatch, match="quant"):
        qstore.check(cfg)
    # fingerprint drift: refused
    with pytest.raises(ExportMismatch, match="quant"):
        qstore.check(qcfg, quant_fingerprint="0" * 16)
    # estimator drift: refused
    with pytest.raises(ExportMismatch, match="quant"):
        qstore.check(qcfg.replace_in("quant", estimator="percentile"),
                     quant_fingerprint="f" * 16)
    # dtype drift: refused
    with pytest.raises(ExportMismatch, match="quant"):
        qstore.check(qcfg.replace_in("quant", dtype="fp8"),
                     quant_fingerprint="f" * 16)
    # fp store (records quant: None) + quant process: refused;
    # + fp process: admitted (and old manifests without the key too)
    fstore = _make_store(tmp_path / "fp", cfg, None)
    with pytest.raises(ExportMismatch, match="quant"):
        fstore.check(qcfg, quant_fingerprint="f" * 16)
    fstore.check(cfg)


@pytest.mark.slow
def test_quant_export_round_trip_serves_bit_stable(tmp_path):
    """Quantized AOT export: export_serve_programs over a quant
    predictor verifies bit-equality inside; a second predictor from the
    SAME calibration warms from the store (fingerprint admission) and
    serves the exported programs."""
    from mx_rcnn_tpu.serve.engine import ServingEngine
    from mx_rcnn_tpu.serve.export import ExportStore, export_serve_programs
    from mx_rcnn_tpu.core.tester import quant_predictor

    cfg = _tiny_cfg(enabled=True)
    cfg = cfg.replace_in("serve", batch_size=2, max_delay_ms=5.0)
    _, params, bs = _tiny_state(cfg)
    batches = [_images()]
    qpred = quant_predictor(cfg, params, bs, batches=batches)
    report = export_serve_programs(qpred, cfg, str(tmp_path / "store"))
    assert report["bit_equal"] is True
    assert json.load(open(report["manifest"]))["quant"][
        "calibration_fingerprint"] == qpred.quant_fingerprint
    qpred2 = quant_predictor(cfg, params, bs, batches=batches)
    engine = ServingEngine(qpred2, cfg, start=True)
    join = engine.warm_from_export(ExportStore(str(tmp_path / "store")))
    assert join["programs"] >= 2
    rng = np.random.RandomState(3)
    img = rng.randint(0, 256, (100, 130, 3), np.uint8)
    dets = engine.detect(img, timeout_ms=0)
    assert isinstance(dets, dict)
    engine.close()


# ---------------------------------------------------------------------------
# the accuracy gate (record-level; the real-training twin is gate-marked)
# ---------------------------------------------------------------------------

def test_paired_gate_fires_on_quant_redteam_records():
    """Record-level pin of the FAIL direction: a quant_redteam arm that
    collapses mAP must leave the paired CI far outside the budget."""
    from mx_rcnn_tpu.tools.gauntlet import paired_compare

    base = [0.7648, 0.7448, 0.7638, 0.7332, 0.7517]
    recs = [{"mode": "e2e", "network": "tiny", "seed": s, "mAP": m}
            for s, m in enumerate(base)]
    recs += [{"mode": "quant_redteam", "network": "tiny", "seed": s,
              "mAP": round(m * 0.1, 4)} for s, m in enumerate(base)]
    cmp = paired_compare(recs, "e2e", "quant_redteam", "tiny", budget=0.05)
    assert cmp["within_budget"] is False
    assert cmp["mean_delta"] < -0.5
    # and the neutral direction still passes: a faithful quant arm
    recs2 = [r for r in recs if r["mode"] == "e2e"]
    recs2 += [{"mode": "quant", "network": "tiny", "seed": s,
               "mAP": round(m - 0.008, 4)} for s, m in enumerate(base)]
    ok = paired_compare(recs2, "e2e", "quant", "tiny", budget=0.05)
    assert ok["within_budget"] is True


def test_gauntlet_quant_modes_registered():
    from mx_rcnn_tpu.tools import gauntlet

    assert "quant" in gauntlet._MODES
    assert "quant_redteam" in gauntlet._MODES
    assert gauntlet._QUANT_REDTEAM_BITS == 2


@pytest.mark.slow
@pytest.mark.gate
def test_paired_gate_fires_on_quant_redteam_arm(tmp_path):
    """Red-team of the quantization accuracy gate on a REAL training
    pair (the quant analog of test_paired_gate_fires_on_damaged_arm):
    e2e and quant_redteam share seeds (training bit-identical), the
    2-bit eval arm collapses mAP, and `--compare e2e quant_redteam`
    must exit 1 with decisively negative per-seed deltas."""
    import io
    from contextlib import redirect_stdout

    from mx_rcnn_tpu.tools.gauntlet import main as gauntlet_main

    out = tmp_path / "results.json"
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = gauntlet_main([
            "--root", str(tmp_path), "--workdir", str(tmp_path / "w"),
            "--out", str(out), "--network", "tiny",
            "--seeds", "0", "1", "--epochs", "4", "--lr", "3e-3",
            "--lr_step", "3", "--compare", "e2e", "quant_redteam"])
    assert rc == 1, "quant gate FAIL direction did not fire"
    cmp = [json.loads(line) for line in buf.getvalue().splitlines()
           if '"compare"' in line][-1]
    assert cmp["compare"] == "quant_redteam-vs-e2e"
    assert all(d < -cmp["budget"] for d in cmp["deltas"]), cmp
    assert cmp["within_budget"] is False
    recs = json.loads(out.read_text())
    assert all(r["damage"] == "quant__weight_bits=2" for r in recs
               if r["mode"] == "quant_redteam")
