"""Geometry unit tests vs tiny hand-computed cases and a NumPy oracle.

The reference has no test suite (SURVEY.md §4); these are the golden tests
it lacked, covering ``rcnn/processing/bbox_transform.py`` semantics.
"""

import numpy as np
import jax.numpy as jnp

from mx_rcnn_tpu.ops.boxes import bbox_overlaps, bbox_pred, bbox_transform, clip_boxes


def np_overlaps(boxes, query):
    """NumPy oracle: literal translation of the reference's bbox_overlaps."""
    n, k = boxes.shape[0], query.shape[0]
    out = np.zeros((n, k), dtype=np.float32)
    for ki in range(k):
        qa = (query[ki, 2] - query[ki, 0] + 1) * (query[ki, 3] - query[ki, 1] + 1)
        for ni in range(n):
            iw = min(boxes[ni, 2], query[ki, 2]) - max(boxes[ni, 0], query[ki, 0]) + 1
            if iw > 0:
                ih = min(boxes[ni, 3], query[ki, 3]) - max(boxes[ni, 1], query[ki, 1]) + 1
                if ih > 0:
                    ba = (boxes[ni, 2] - boxes[ni, 0] + 1) * (boxes[ni, 3] - boxes[ni, 1] + 1)
                    out[ni, ki] = iw * ih / (ba + qa - iw * ih)
    return out


def test_overlaps_identity():
    b = jnp.array([[0.0, 0.0, 9.0, 9.0]])
    assert np.allclose(bbox_overlaps(b, b), 1.0)


def test_overlaps_hand_case():
    # 10x10 box vs 10x10 box shifted by 5: inter 5x10=50, union 150
    a = jnp.array([[0.0, 0.0, 9.0, 9.0]])
    b = jnp.array([[5.0, 0.0, 14.0, 9.0]])
    got = np.asarray(bbox_overlaps(a, b))[0, 0]
    assert abs(got - 50.0 / 150.0) < 1e-6


def test_overlaps_disjoint_and_degenerate():
    a = jnp.array([[0.0, 0.0, 4.0, 4.0], [10.0, 10.0, 5.0, 5.0]])  # 2nd degenerate
    b = jnp.array([[100.0, 100.0, 110.0, 110.0]])
    got = np.asarray(bbox_overlaps(a, b))
    assert got[0, 0] == 0.0
    assert got[1, 0] == 0.0


def test_overlaps_vs_numpy_oracle(rng):
    boxes = rng.uniform(0, 100, (40, 4)).astype(np.float32)
    boxes[:, 2:] += boxes[:, :2]
    query = rng.uniform(0, 100, (17, 4)).astype(np.float32)
    query[:, 2:] += query[:, :2]
    got = np.asarray(bbox_overlaps(jnp.array(boxes), jnp.array(query)))
    want = np_overlaps(boxes, query)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_transform_pred_roundtrip(rng):
    ex = rng.uniform(0, 200, (30, 4)).astype(np.float32)
    ex[:, 2:] += ex[:, :2] + 5
    gt = rng.uniform(0, 200, (30, 4)).astype(np.float32)
    gt[:, 2:] += gt[:, :2] + 5
    deltas = bbox_transform(jnp.array(ex), jnp.array(gt))
    rec = bbox_pred(jnp.array(ex), deltas)
    np.testing.assert_allclose(np.asarray(rec), gt, rtol=1e-3, atol=1e-2)


def test_transform_zero_for_identical():
    b = jnp.array([[10.0, 20.0, 50.0, 80.0]])
    d = np.asarray(bbox_transform(b, b))
    np.testing.assert_allclose(d, 0.0, atol=1e-6)


def test_clip_boxes():
    b = jnp.array([[-10.0, -5.0, 700.0, 300.0]])
    out = np.asarray(clip_boxes(b, (256, 512)))
    np.testing.assert_allclose(out, [[0.0, 0.0, 511.0, 255.0]])


def test_clip_boxes_multiclass_layout():
    b = jnp.array([[-1.0, -1.0, 600.0, 600.0, 5.0, 5.0, 10.0, 10.0]])
    out = np.asarray(clip_boxes(b, (100, 100)))
    np.testing.assert_allclose(out, [[0.0, 0.0, 99.0, 99.0, 5.0, 5.0, 10.0, 10.0]])
