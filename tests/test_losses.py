"""Loss function unit tests."""

import numpy as np
import jax.numpy as jnp

from mx_rcnn_tpu.ops.losses import (
    accuracy_with_ignore,
    smooth_l1,
    softmax_cross_entropy_with_ignore,
    weighted_smooth_l1,
)


def test_smooth_l1_quadratic_zone():
    # sigma=1: |x| < 1 → 0.5 x^2
    x = jnp.array([0.5])
    np.testing.assert_allclose(smooth_l1(x, jnp.zeros(1), 1.0), 0.125, rtol=1e-6)


def test_smooth_l1_linear_zone():
    x = jnp.array([3.0])
    np.testing.assert_allclose(smooth_l1(x, jnp.zeros(1), 1.0), 2.5, rtol=1e-6)


def test_smooth_l1_sigma3_transition():
    # sigma=3 → transition at 1/9; check both sides
    s = 3.0
    lo = jnp.array([0.05])
    hi = jnp.array([0.5])
    np.testing.assert_allclose(smooth_l1(lo, jnp.zeros(1), s), 0.5 * 9 * 0.05**2, rtol=1e-5)
    np.testing.assert_allclose(smooth_l1(hi, jnp.zeros(1), s), 0.5 - 0.5 / 9, rtol=1e-5)


def test_ce_ignore_and_normalization():
    logits = jnp.array([[10.0, 0.0], [0.0, 10.0], [5.0, 5.0]])
    labels = jnp.array([0, 1, -1])
    loss_valid = softmax_cross_entropy_with_ignore(logits, labels, -1, "valid")
    # two confident correct predictions → tiny loss; ignored row contributes 0
    assert float(loss_valid) < 1e-3
    loss_batch = softmax_cross_entropy_with_ignore(logits, labels, -1, "batch")
    np.testing.assert_allclose(float(loss_batch), float(loss_valid) * 2 / 3, rtol=1e-5)


def test_ce_uniform_logits():
    logits = jnp.zeros((4, 21))
    labels = jnp.array([0, 3, 7, 20])
    loss = softmax_cross_entropy_with_ignore(logits, labels)
    np.testing.assert_allclose(float(loss), np.log(21), rtol=1e-4)


def test_weighted_smooth_l1():
    pred = jnp.array([[1.0, 0.0], [0.0, 0.0]])
    tgt = jnp.zeros((2, 2))
    w = jnp.array([[1.0, 1.0], [0.0, 0.0]])
    # only element (0,0) contributes: 0.5*1^2 = 0.5; /256
    got = weighted_smooth_l1(pred, tgt, w, sigma=1.0, grad_norm=256)
    np.testing.assert_allclose(float(got), 0.5 / 256, rtol=1e-6)


def test_accuracy_with_ignore():
    logits = jnp.array([[1.0, 0.0], [0.0, 1.0], [1.0, 0.0], [9.0, 0.0]])
    labels = jnp.array([0, 1, 1, -1])
    np.testing.assert_allclose(float(accuracy_with_ignore(logits, labels)), 2 / 3, rtol=1e-6)
