"""NMS tests: exact parity with a sequential greedy NumPy oracle.

The oracle is the reference algorithm (``rcnn/cython/cpu_nms.pyx`` /
``rcnn/processing/nms.py — py_nms``): sort by score, greedily keep the best
remaining box and suppress everything above the IoU threshold.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from mx_rcnn_tpu.ops.nms import nms, nms_mask


def greedy_nms_oracle(boxes, scores, thresh):
    """Sequential greedy NMS; returns kept indices in score order."""
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    areas = (x2 - x1 + 1) * (y2 - y1 + 1)
    order = scores.argsort()[::-1]
    keep = []
    while order.size > 0:
        i = order[0]
        keep.append(i)
        xx1 = np.maximum(x1[i], x1[order[1:]])
        yy1 = np.maximum(y1[i], y1[order[1:]])
        xx2 = np.minimum(x2[i], x2[order[1:]])
        yy2 = np.minimum(y2[i], y2[order[1:]])
        w = np.maximum(0.0, xx2 - xx1 + 1)
        h = np.maximum(0.0, yy2 - yy1 + 1)
        inter = w * h
        ovr = inter / (areas[i] + areas[order[1:]] - inter)
        order = order[1:][ovr <= thresh]
    return keep


def random_boxes(rng, n, span=200):
    b = rng.uniform(0, span, (n, 4)).astype(np.float32)
    b[:, 2:] = b[:, :2] + rng.uniform(5, 80, (n, 2)).astype(np.float32)
    s = rng.uniform(0.01, 1.0, (n,)).astype(np.float32)
    return b, s


def test_nms_hand_case():
    boxes = jnp.array(
        [
            [0.0, 0.0, 99.0, 99.0],    # score .9  — kept
            [5.0, 5.0, 104.0, 104.0],  # score .8  — IoU .73 with #0 → suppressed
            [200.0, 200.0, 250.0, 250.0],  # score .7 — kept
            [0.0, 0.0, 99.0, 99.0],    # score .6  — dup of #0 → suppressed
        ]
    )
    scores = jnp.array([0.9, 0.8, 0.7, 0.6])
    idx, valid = nms(boxes, scores, 0.5, 4)
    assert list(np.asarray(idx[valid])) == [0, 2]
    assert int(valid.sum()) == 2


@pytest.mark.parametrize("n", [17, 64, 300, 777])
@pytest.mark.parametrize("thresh", [0.3, 0.5, 0.7])
def test_nms_matches_oracle(rng, n, thresh):
    boxes, scores = random_boxes(rng, n)
    want = greedy_nms_oracle(boxes, scores, thresh)
    idx, valid = nms(jnp.array(boxes), jnp.array(scores), thresh, n, tile_size=64)
    got = list(np.asarray(idx[valid]))
    assert got == want


def test_nms_max_output_truncates(rng):
    boxes, scores = random_boxes(rng, 200)
    want = greedy_nms_oracle(boxes, scores, 0.5)[:10]
    idx, valid = nms(jnp.array(boxes), jnp.array(scores), 0.5, 10)
    assert list(np.asarray(idx[valid])) == want


def test_nms_respects_valid_mask(rng):
    boxes, scores = random_boxes(rng, 50)
    valid_in = np.ones(50, bool)
    valid_in[25:] = False
    want = greedy_nms_oracle(boxes[:25], scores[:25], 0.5)
    idx, valid = nms(
        jnp.array(boxes), jnp.array(scores), 0.5, 50, valid=jnp.array(valid_in)
    )
    got = list(np.asarray(idx[valid]))
    assert got == want
    assert all(g < 25 for g in got)


def test_nms_mask_original_order(rng):
    boxes, scores = random_boxes(rng, 120)
    want = sorted(greedy_nms_oracle(boxes, scores, 0.4))
    keep = np.asarray(nms_mask(jnp.array(boxes), jnp.array(scores), 0.4, tile_size=64))
    assert sorted(np.flatnonzero(keep).tolist()) == want


def test_nms_all_identical_boxes():
    boxes = jnp.tile(jnp.array([[10.0, 10.0, 50.0, 50.0]]), (32, 1))
    scores = jnp.linspace(1.0, 0.1, 32)
    idx, valid = nms(boxes, scores, 0.5, 32)
    assert int(valid.sum()) == 1
    assert int(idx[0]) == 0
