"""NMS tests: exact parity with a sequential greedy NumPy oracle.

The oracle is the reference algorithm (``rcnn/cython/cpu_nms.pyx`` /
``rcnn/processing/nms.py — py_nms``): sort by score, greedily keep the best
remaining box and suppress everything above the IoU threshold.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from mx_rcnn_tpu.ops.nms import nms, nms_batch, nms_mask, nms_mask_batch


def greedy_nms_oracle(boxes, scores, thresh):
    """Sequential greedy NMS; returns kept indices in score order."""
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    areas = (x2 - x1 + 1) * (y2 - y1 + 1)
    order = scores.argsort()[::-1]
    keep = []
    while order.size > 0:
        i = order[0]
        keep.append(i)
        xx1 = np.maximum(x1[i], x1[order[1:]])
        yy1 = np.maximum(y1[i], y1[order[1:]])
        xx2 = np.minimum(x2[i], x2[order[1:]])
        yy2 = np.minimum(y2[i], y2[order[1:]])
        w = np.maximum(0.0, xx2 - xx1 + 1)
        h = np.maximum(0.0, yy2 - yy1 + 1)
        inter = w * h
        ovr = inter / (areas[i] + areas[order[1:]] - inter)
        order = order[1:][ovr <= thresh]
    return keep


def random_boxes(rng, n, span=200):
    b = rng.uniform(0, span, (n, 4)).astype(np.float32)
    b[:, 2:] = b[:, :2] + rng.uniform(5, 80, (n, 2)).astype(np.float32)
    s = rng.uniform(0.01, 1.0, (n,)).astype(np.float32)
    return b, s


def test_nms_hand_case():
    boxes = jnp.array(
        [
            [0.0, 0.0, 99.0, 99.0],    # score .9  — kept
            [5.0, 5.0, 104.0, 104.0],  # score .8  — IoU .73 with #0 → suppressed
            [200.0, 200.0, 250.0, 250.0],  # score .7 — kept
            [0.0, 0.0, 99.0, 99.0],    # score .6  — dup of #0 → suppressed
        ]
    )
    scores = jnp.array([0.9, 0.8, 0.7, 0.6])
    idx, valid = nms(boxes, scores, 0.5, 4)
    assert list(np.asarray(idx[valid])) == [0, 2]
    assert int(valid.sum()) == 2


@pytest.mark.parametrize("n", [17, 64, 300, 777])
@pytest.mark.parametrize("thresh", [0.3, 0.5, 0.7])
def test_nms_matches_oracle(rng, n, thresh):
    boxes, scores = random_boxes(rng, n)
    want = greedy_nms_oracle(boxes, scores, thresh)
    idx, valid = nms(jnp.array(boxes), jnp.array(scores), thresh, n, tile_size=64)
    got = list(np.asarray(idx[valid]))
    assert got == want


def test_nms_max_output_truncates(rng):
    boxes, scores = random_boxes(rng, 200)
    want = greedy_nms_oracle(boxes, scores, 0.5)[:10]
    idx, valid = nms(jnp.array(boxes), jnp.array(scores), 0.5, 10)
    assert list(np.asarray(idx[valid])) == want


def test_nms_respects_valid_mask(rng):
    boxes, scores = random_boxes(rng, 50)
    valid_in = np.ones(50, bool)
    valid_in[25:] = False
    want = greedy_nms_oracle(boxes[:25], scores[:25], 0.5)
    idx, valid = nms(
        jnp.array(boxes), jnp.array(scores), 0.5, 50, valid=jnp.array(valid_in)
    )
    got = list(np.asarray(idx[valid]))
    assert got == want
    assert all(g < 25 for g in got)


def test_nms_mask_original_order(rng):
    boxes, scores = random_boxes(rng, 120)
    want = sorted(greedy_nms_oracle(boxes, scores, 0.4))
    keep = np.asarray(nms_mask(jnp.array(boxes), jnp.array(scores), 0.4, tile_size=64))
    assert sorted(np.flatnonzero(keep).tolist()) == want


def test_nms_all_identical_boxes():
    boxes = jnp.tile(jnp.array([[10.0, 10.0, 50.0, 50.0]]), (32, 1))
    scores = jnp.linspace(1.0, 0.1, 32)
    idx, valid = nms(boxes, scores, 0.5, 32)
    assert int(valid.sum()) == 1
    assert int(idx[0]) == 0


# ---------------------------------------------------------------------------
# Cross-backend tie robustness (VERDICT r04 weak item 5): the host NMS
# (native.cpu_nms) breaks score ties HIGHER-original-index-first (matching
# the reference's scores.argsort()[::-1]) while the in-graph NMS breaks
# them lower-index-first (mx_rcnn_tpu/native/__init__.py docstring).  Tied
# detections may therefore survive differently per backend — these tests
# pin what that is allowed to do to the END metric (AP on the eval path).
# ---------------------------------------------------------------------------

def _ap_of_kept(per_image_dets, gt_by_image):
    from mx_rcnn_tpu.data.voc_eval import voc_eval

    return voc_eval(per_image_dets, gt_by_image, class_id=1,
                    use_07_metric=False)


def _keep_host(dets, thresh):
    from mx_rcnn_tpu.native import cpu_nms

    return np.sort(np.asarray(cpu_nms(dets, thresh)))


def _keep_device(dets, thresh):
    # backend pinned to jnp exactly as the eval path pins it
    # (core/tester.py — _postprocess_batch)
    mask = np.asarray(nms_mask(jnp.asarray(dets[:, :4]),
                               jnp.asarray(dets[:, 4]), thresh,
                               backend="jnp"))
    return np.flatnonzero(mask)


def test_nms_backend_tie_duplicate_ap_invariance():
    """EXACT-duplicate boxes at tied scores: the two backends may keep a
    different *index*, but the surviving geometry is identical, so the
    eval-path AP must match bit-for-bit."""
    gt = {"im0": dict(boxes=np.array([[10, 10, 60, 60],
                                      [100, 100, 160, 170]], np.float32),
                      gt_classes=np.array([1, 1]),
                      difficult=np.zeros(2, bool))}
    # two tied duplicates on the first gt, a tied duplicate pair of a
    # slightly-off box on the second, and a tied duplicated false positive
    dets = np.array([
        [10, 10, 60, 60, 0.9],
        [10, 10, 60, 60, 0.9],
        [101, 101, 161, 171, 0.8],
        [101, 101, 161, 171, 0.8],
        # the FP pair scores 0.79, not 0.8: voc_eval ranks with a
        # non-stable argsort, so a tp/fp SCORE tie would make the AP
        # margin below depend on numpy sort internals, not on the NMS
        # behavior under test
        [200, 10, 240, 40, 0.79],
        [200, 10, 240, 40, 0.79],
    ], np.float32)
    aps = []
    for keep_fn in (_keep_host, _keep_device):
        keep = keep_fn(dets, 0.3)
        aps.append(_ap_of_kept({"im0": dets[keep]}, gt))
    assert aps[0] == aps[1]
    assert aps[0] > 0.9  # both gts found; the FP ranks after both tps


def test_nms_backend_tie_rich_ap_bound():
    """General tie-rich inputs (scores quantized to 8 levels, clustered
    non-identical boxes): the backends may keep geometrically different
    survivors among ties, so AP need not be bit-equal.  On a SINGLE image
    the divergence can be large (a tp<->fp flip on a 6-gt image measured
    ΔAP up to 0.17 while writing this test) — but evaluation is always an
    aggregate over an image SET, where the per-image flips decorrelate.
    This pins the eval-set-level bound: AP over 16 tie-rich images per
    seed, 8 seeds, paired across backends."""
    rng = np.random.RandomState(7)
    deltas = []
    for _seed in range(8):
        gt, dets_host, dets_dev = {}, {}, {}
        for im in range(16):
            key = f"im{im}"
            gt_boxes = []
            for _g in range(6):
                x, y = rng.uniform(0, 400, 2)
                w, h = rng.uniform(30, 90, 2)
                gt_boxes.append([x, y, x + w, y + h])
            gt_boxes = np.asarray(gt_boxes, np.float32)
            gt[key] = dict(boxes=gt_boxes,
                           gt_classes=np.ones(len(gt_boxes), np.int64),
                           difficult=np.zeros(len(gt_boxes), bool))
            dets = []
            for b in gt_boxes:
                for _d in range(rng.randint(2, 6)):
                    jit = rng.uniform(-12, 12, 4)
                    dets.append(np.concatenate(
                        [b + jit, [rng.randint(1, 9) / 8.0]]))
            for _fp in range(8):  # tied distractors
                x, y = rng.uniform(0, 450, 2)
                dets.append([x, y, x + 40, y + 40,
                             rng.randint(1, 9) / 8.0])
            dets = np.asarray(dets, np.float32)
            dets_host[key] = dets[_keep_host(dets, 0.3)]
            dets_dev[key] = dets[_keep_device(dets, 0.3)]
        deltas.append(abs(_ap_of_kept(dets_host, gt)
                          - _ap_of_kept(dets_dev, gt)))
    # measured on this ADVERSARIALLY tie-dense input (every score one of
    # just 8 levels, so ~every suppression decision involves a tie): the
    # 16-image paired AP delta maxes at 0.037, mean 0.015 — an order of
    # magnitude under the 0.17 single-image worst case, and real
    # detectors emit continuous softmax scores where non-duplicate ties
    # have measure zero (the duplicate case is pinned exactly above).
    # Real eval sets (4952 VOC images) average further still.
    assert max(deltas) < 0.05, deltas
    assert float(np.mean(deltas)) < 0.02, deltas


# ---------------------------------------------------------------------------
# Cross-image batched sweep (r6 tentpole): nms_batch / nms_mask_batch run
# B images through ONE tile-sweep loop nest.  Contract: decision-exact per
# image against the per-image sweep (which the oracle tests above pin to
# sequential greedy NMS) — every output array identical.
# ---------------------------------------------------------------------------

def _batch_boxes(rng, b, n, span=200):
    boxes = np.stack([random_boxes(rng, n, span)[0] for _ in range(b)])
    scores = np.stack([random_boxes(rng, n, span)[1] for _ in range(b)])
    return boxes, scores


@pytest.mark.parametrize("n,tile", [(17, 64), (64, 64), (300, 64),
                                    (128, 128), (256, 128), (777, 256)])
def test_nms_batch_matches_per_image(rng, n, tile):
    """Shape sweep including K exactly at the lane-guard boundary
    (K=128=tile: single-tile peeled path; K=256, tile 128: the guard's
    k % tile == 0 case) and padded odd K."""
    boxes, scores = _batch_boxes(rng, 4, n)
    idx_b, val_b = nms_batch(jnp.asarray(boxes), jnp.asarray(scores), 0.5,
                             n, tile_size=tile)
    for i in range(4):
        idx_i, val_i = nms(jnp.asarray(boxes[i]), jnp.asarray(scores[i]),
                           0.5, n, tile_size=tile)
        np.testing.assert_array_equal(np.asarray(idx_b[i]),
                                      np.asarray(idx_i))
        np.testing.assert_array_equal(np.asarray(val_b[i]),
                                      np.asarray(val_i))


def test_nms_batch_matches_oracle_rows(rng):
    """Each row of the batched result equals the sequential greedy NumPy
    oracle directly (not only via the per-image implementation)."""
    boxes, scores = _batch_boxes(rng, 6, 97)
    idx_b, val_b = nms_batch(jnp.asarray(boxes), jnp.asarray(scores), 0.4,
                             97, tile_size=64)
    for i in range(6):
        want = greedy_nms_oracle(boxes[i], scores[i], 0.4)
        got = list(np.asarray(idx_b[i][val_b[i]]))
        assert got == want


def test_nms_mask_batch_matches_per_image(rng):
    boxes, scores = _batch_boxes(rng, 5, 120)
    mask_b = nms_mask_batch(jnp.asarray(boxes), jnp.asarray(scores), 0.4,
                            tile_size=64)
    for i in range(5):
        mask_i = nms_mask(jnp.asarray(boxes[i]), jnp.asarray(scores[i]),
                          0.4, tile_size=64)
        np.testing.assert_array_equal(np.asarray(mask_b[i]),
                                      np.asarray(mask_i))


def test_nms_batch_tie_cases():
    """Tie-break parity: exact-duplicate boxes at tied scores and
    quantized score levels — the batched sweep must make the SAME
    tie decisions (same sorted order, same suppressor) per image."""
    rng = np.random.RandomState(11)
    b, n = 4, 80
    boxes = np.zeros((b, n, 4), np.float32)
    scores = np.zeros((b, n), np.float32)
    for i in range(b):
        bx, _ = random_boxes(rng, n // 2)
        # every box duplicated, every score snapped to 8 levels
        boxes[i] = np.concatenate([bx, bx])
        scores[i] = np.repeat(rng.randint(1, 9, n // 2) / 8.0,
                              2).astype(np.float32)
    idx_b, val_b = nms_batch(jnp.asarray(boxes), jnp.asarray(scores), 0.3,
                             n, tile_size=64)
    mask_b = nms_mask_batch(jnp.asarray(boxes), jnp.asarray(scores), 0.3,
                            tile_size=64)
    for i in range(b):
        idx_i, val_i = nms(jnp.asarray(boxes[i]), jnp.asarray(scores[i]),
                           0.3, n, tile_size=64)
        np.testing.assert_array_equal(np.asarray(idx_b[i]),
                                      np.asarray(idx_i))
        mask_i = nms_mask(jnp.asarray(boxes[i]), jnp.asarray(scores[i]),
                          0.3, tile_size=64)
        np.testing.assert_array_equal(np.asarray(mask_b[i]),
                                      np.asarray(mask_i))


def test_nms_batch_max_output_and_valid(rng):
    boxes, scores = _batch_boxes(rng, 3, 200)
    valid = np.ones((3, 200), bool)
    valid[:, 150:] = False
    idx_b, val_b = nms_batch(jnp.asarray(boxes), jnp.asarray(scores), 0.5,
                             10, valid=jnp.asarray(valid))
    for i in range(3):
        want = greedy_nms_oracle(boxes[i][:150], scores[i][:150], 0.5)[:10]
        got = list(np.asarray(idx_b[i][val_b[i]]))
        assert got == want
        assert all(g < 150 for g in got)
