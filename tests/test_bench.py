"""bench.py outage protocol (VERDICT r03 item 1).

Round 3 ended with ``BENCH_r03.json rc=1, parsed=null``: the tunneled chip
hung during backend init and the bench died with a bare traceback.  These
tests pin the supervisor contract with FAKE child commands — no device:

* a healthy child's JSON line passes through unchanged,
* a transiently failing child is retried in a fresh process and the later
  success wins,
* a hung child is killed at the per-attempt timeout and retried,
* when the retry window closes (or the error is non-transient) the
  supervisor emits a structured degraded line — never a traceback.
"""

import json
import subprocess
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
import bench  # noqa: E402


GOOD = {"metric": "imgs_per_sec_per_chip", "value": 75.0,
        "unit": "imgs/s", "vs_baseline": 25.0}


def _child(script: str):
    return [sys.executable, "-c", script]


def test_success_passes_through(monkeypatch):
    monkeypatch.setenv("BENCH_RETRY_WINDOW_S", "60")
    out = bench.supervise(_child(
        f"import json; print('noise'); print(json.dumps({GOOD!r}))"))
    assert out == GOOD
    assert "degraded" not in out


def test_transient_failure_then_success(tmp_path, monkeypatch):
    """First attempt dies with an Unavailable error; the retry (a FRESH
    process) succeeds.  State crosses attempts via a marker file."""
    monkeypatch.setenv("BENCH_RETRY_WINDOW_S", "120")
    marker = tmp_path / "tried"
    script = (
        "import json, os, sys\n"
        f"m = {str(marker)!r}\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').close()\n"
        "    sys.stderr.write('TPU backend setup/compile error "
        "(Unavailable)\\n')\n"
        "    sys.exit(1)\n"
        f"print(json.dumps({GOOD!r}))\n"
    )
    # shrink the backoff so the test is fast
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    out = bench.supervise(_child(script))
    assert out == GOOD
    assert marker.exists()


def test_hung_child_is_killed_and_degraded(monkeypatch):
    """A child that never returns (round 3's hanging backend init) is
    killed at the attempt timeout; with the window closed the supervisor
    emits the structured degraded line."""
    monkeypatch.setenv("BENCH_RETRY_WINDOW_S", "0")
    monkeypatch.setenv("BENCH_ATTEMPT_TIMEOUT_S", "2")
    out = bench.supervise(_child("import time; time.sleep(600)"))
    assert out["degraded"] is True
    assert "timeout" in out["failure"]
    assert out["metric"] == "imgs_per_sec_per_chip"
    # value is null on the degraded path so naive metric/value consumers
    # cannot mistake a historical number for a live one (advisor r4); the
    # historical figures live under explicit last_verified_* keys
    assert out["value"] is None
    assert out["measured"] is False
    assert out["last_verified_value"] == bench._LAST_VERIFIED["value"]
    assert out["last_verified_sustained_imgs_per_sec"] == \
        bench._LAST_VERIFIED["sustained"]
    assert "value_source" in out
    json.dumps(out)  # the degraded line must itself be valid JSON content


def test_non_transient_error_bails_immediately(monkeypatch):
    """A real bug (ImportError etc.) must not burn the retry window: one
    attempt, then the degraded line with the failure recorded."""
    monkeypatch.setenv("BENCH_RETRY_WINDOW_S", "3600")
    out = bench.supervise(_child(
        "import sys; sys.stderr.write('ImportError: no module nope\\n'); "
        "sys.exit(1)"))
    assert out["degraded"] is True
    assert "ImportError" in out["failure"]
    assert out["failure"].startswith("attempt 1 ")  # no retries happened


def test_transient_retries_until_window_then_degraded(monkeypatch):
    monkeypatch.setenv("BENCH_RETRY_WINDOW_S", "0")
    out = bench.supervise(_child(
        "import sys; sys.stderr.write('UNAVAILABLE: tunnel down\\n'); "
        "sys.exit(1)"))
    assert out["degraded"] is True
    assert "UNAVAILABLE" in out["failure"]


def test_signal_death_is_transient(tmp_path, monkeypatch):
    """A child killed by a signal (OOM, runtime abort — rc<0) is
    environment trouble, not a code bug: retry, don't bail."""
    monkeypatch.setenv("BENCH_RETRY_WINDOW_S", "120")
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    marker = tmp_path / "tried"
    script = (
        "import json, os, signal, sys\n"
        f"m = {str(marker)!r}\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').close()\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n"
        f"print(json.dumps({GOOD!r}))\n"
    )
    out = bench.supervise(_child(script))
    assert out == GOOD


def test_timed_out_child_with_result_is_salvaged(monkeypatch):
    """A child that printed its complete JSON and then hung in teardown
    (the tunnel's known pathology) still measured — its result must be
    used, not thrown away."""
    monkeypatch.setenv("BENCH_RETRY_WINDOW_S", "0")
    # generous attempt timeout: the child must manage to PRINT its JSON
    # before the kill, and interpreter startup alone can exceed 3 s when
    # the box is loaded (observed under the on-chip battery)
    monkeypatch.setenv("BENCH_ATTEMPT_TIMEOUT_S", "15")
    script = (
        "import json, sys, time\n"
        f"print(json.dumps({GOOD!r}), flush=True)\n"
        "time.sleep(600)\n"  # hang in 'teardown'
    )
    out = bench.supervise(_child(script))
    assert out == GOOD
    assert "degraded" not in out


def test_sigterm_during_supervision_emits_degraded_line():
    """A harness that loses patience and SIGTERMs the supervisor must
    still get a parseable degraded JSON line, not silence."""
    import os
    import signal
    import time as _time

    repo = __file__.rsplit("/tests/", 1)[0]
    code = (
        "import sys\n"
        "sys.argv = ['bench.py']\n"
        f"sys.path.insert(0, {repo!r})\n"
        "import bench\n"
        "bench.supervise = lambda: (_ for _ in ()).throw(SystemExit)  # unused\n"
        "import json, types\n"
        "def fake_supervise(child_cmd=None):\n"
        "    import time\n"
        # handler is installed by main() BEFORE supervise runs, so this
        # marker tells the parent it is safe to fire the SIGTERM — a fixed
        # pre-signal sleep flakes when the box is loaded (chip battery
        # saturating the single core slowed interpreter startup past 3 s)
        "    print('READY', flush=True)\n"
        "    time.sleep(120)\n"
        "bench.supervise = fake_supervise\n"
        "bench.main()\n"
    )
    env = {**os.environ, "BENCH_RETRY_WINDOW_S": "0"}
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, text=True, env=env)
    assert proc.stdout.readline().strip() == "READY"
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=30)
    assert proc.returncode == 0
    lines = [ln for ln in out.strip().splitlines() if ln.strip()]
    assert len(lines) == 1
    parsed = json.loads(lines[0])
    assert parsed["degraded"] is True
    assert "signal" in parsed["failure"]


def test_parse_result_rejects_garbage():
    assert bench._parse_result("") is None
    assert bench._parse_result("not json\nstill not json") is None
    assert bench._parse_result('["a", "list"]') is None
    assert bench._parse_result('{"no_metric": 1}') is None
    good = json.dumps(GOOD)
    assert bench._parse_result(f"stderr-ish noise\n{good}\n") == GOOD


def test_cli_emits_single_json_line_on_persistent_failure(tmp_path):
    """End to end through ``python bench.py``: with an unusable child the
    process must still exit 0 and print exactly one parseable JSON line on
    stdout (the driver's contract)."""
    env = {"BENCH_RETRY_WINDOW_S": "0", "BENCH_ATTEMPT_TIMEOUT_S": "2",
           "PATH": "/usr/bin:/bin"}
    # force run_once to hang instantly by pointing JAX at a bad coordinator?
    # simpler: run the supervisor with a child that hangs, via a wrapper
    code = (
        "import json, sys\n"
        "sys.path.insert(0, sys.argv[1])\n"
        "import bench\n"
        "print(json.dumps(bench.supervise("
        "[sys.executable, '-c', 'import time; time.sleep(60)'])))\n"
    )
    repo = __file__.rsplit("/tests/", 1)[0]
    r = subprocess.run([sys.executable, "-c", code, repo],
                       capture_output=True, text=True, env=env, timeout=60)
    assert r.returncode == 0, r.stderr
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1
    parsed = json.loads(lines[0])
    assert parsed["degraded"] is True and "value" in parsed
