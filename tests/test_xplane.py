"""utils/xplane.py — the hand-rolled XSpace protobuf reader.

Correctness anchor: ``jax.profiler.trace`` writes BOTH the xplane.pb and a
lossy chrome-trace JSON of the same events; every XLA op duration decoded
from the protobuf must match the JSON's record exactly.  That
cross-validation catches any wire-format misread (field numbers, varint
handling, interned-string refs) without depending on TensorFlow.
"""

import glob
import gzip
import json
import os

import jax
import jax.numpy as jnp
import pytest

from mx_rcnn_tpu.utils.xplane import (category_of, device_planes,
                                      event_rows, parse_xspace,
                                      summarize_device_time)


@pytest.fixture(scope="module")
def trace_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("trace"))

    @jax.jit
    def f(x):
        return (jnp.sin(x) @ x).sum()

    x = jnp.ones((128, 128))
    f(x).block_until_ready()  # compile outside the trace
    with jax.profiler.trace(d):
        f(x).block_until_ready()
    return d


def _pb_and_json(trace_dir):
    pb = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.xplane.pb")))[-1]
    js = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.trace.json.gz")))[-1]
    return pb, js


def test_xplane_ops_match_chrome_trace(trace_dir):
    pb, js = _pb_and_json(trace_dir)
    chrome = json.load(gzip.open(js))
    chrome_durs = {}
    for e in chrome["traceEvents"]:
        if e.get("ph") == "X" and isinstance(e.get("args"), dict) \
                and "hlo_op" in e["args"]:
            chrome_durs.setdefault(e["name"], []).append(e["dur"])
    assert chrome_durs, "chrome trace carries no XLA op events"

    planes = parse_xspace(pb)
    assert any(p.get("name") for p in planes)
    got = {}
    for plane in device_planes(planes):
        for row in event_rows(plane):
            if "hlo_op" in row["stats"]:
                got.setdefault(row["name"], []).append(
                    row["duration_ps"] / 1e6)  # ps -> us
    assert got, "no XLA op events decoded from the protobuf"
    # every chrome op event is present with a matching duration
    for name, durs in chrome_durs.items():
        assert name in got, name
        for d in durs:
            assert any(abs(g - d) < 1e-3 for g in got[name]), (name, d,
                                                               got[name])
    # interned-string refs resolved: hlo_op stat is a real string
    row = next(r for p in device_planes(planes) for r in event_rows(p)
               if "hlo_op" in r["stats"])
    assert isinstance(row["stats"]["hlo_op"], str)
    assert row["stats"]["hlo_op"]


def test_summarize_device_time_op_classes(trace_dir):
    pb, _ = _pb_and_json(trace_dir)
    summary = summarize_device_time(pb, key=category_of)
    groups = {}
    for plane_groups in summary.values():
        for g, ms in plane_groups.items():
            groups[g] = groups.get(g, 0.0) + ms
    assert groups
    # the matmul must appear as a dot-class op and dominate this program
    dot_ms = sum(ms for g, ms in groups.items() if g.startswith("dot"))
    assert dot_ms > 0
    assert all(ms >= 0 for ms in groups.values())
    # SSA suffixes are stripped into classes (no trailing .N digits)
    assert not any(g.rstrip("0123456789") != g and g[-1].isdigit()
                   and "." in g for g in groups)
