"""Serving subsystem tests (ISSUE 2): admission control, micro-batching,
deadline/shed semantics, eval-parity of results, metrics, and the
zero-recompile steady-state invariant.

The engine under test is the tiny network on the quick-tier 128x160
buckets; one module-scoped Predictor shares its per-shape jit cache
across every engine instance, so the whole file compiles a handful of
tiny programs once.
"""

import base64
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.tester import _postprocess_batch, detections_from_keep
from mx_rcnn_tpu.serve.engine import ServingEngine
from mx_rcnn_tpu.serve.metrics import Histogram, LoweringCounter
from mx_rcnn_tpu.serve.queue import (EXPIRED, SERVED, SHED, BoundedQueue,
                                     DeadlineExceeded, ServeRequest,
                                     ShedError)
from mx_rcnn_tpu.tools.loadgen import init_predictor, synthetic_images


def _serve_cfg(**serve_kw):
    cfg = generate_config(
        "tiny", "synthetic",
        bucket__scale=128, bucket__max_size=160,
        bucket__shapes=((128, 160), (160, 128)),
        test__rpn_pre_nms_top_n=512, test__rpn_post_nms_top_n=64)
    if serve_kw:
        cfg = cfg.replace_in("serve", **serve_kw)
    return cfg


@pytest.fixture(scope="module")
def predictor():
    return init_predictor(_serve_cfg())


@pytest.fixture(scope="module")
def engine(predictor):
    """Warmed steady-state engine shared by the read-mostly tests."""
    eng = ServingEngine(predictor,
                        _serve_cfg(batch_size=2, max_delay_ms=30.0))
    eng.warmup()
    yield eng
    eng.close()


def _img(landscape=True, seed=0):
    rng = np.random.RandomState(seed)
    h, w = (128, 160) if landscape else (160, 128)
    return rng.randint(0, 256, size=(h, w, 3), dtype=np.uint8)


# ---------------------------------------------------------------------------
# config + primitives
# ---------------------------------------------------------------------------

def test_serve_config_section_and_overrides():
    cfg = generate_config("tiny", "synthetic", serve__batch_size=8,
                          serve__max_delay_ms=3.5)
    assert cfg.serve.batch_size == 8
    assert cfg.serve.max_delay_ms == 3.5
    # string CLI values coerce like every other section
    cfg = generate_config("tiny", "synthetic", serve__queue_depth="16")
    assert cfg.serve.queue_depth == 16


def test_engine_rejects_inconsistent_policy(predictor):
    bad = _serve_cfg(shed_watermark=100, queue_depth=10)
    with pytest.raises(ValueError, match="shed_watermark"):
        ServingEngine(predictor, bad, start=False)
    with pytest.raises(ValueError, match="batch_size"):
        ServingEngine(predictor, _serve_cfg(batch_size=0), start=False)


def test_histogram_percentiles_conservative():
    """Bucket-upper-bound percentiles never understate and overstate by
    at most one log-bucket (x1.39 at the default resolution)."""
    h = Histogram()
    vals = np.random.RandomState(0).uniform(1.0, 500.0, size=1000)
    for v in vals:
        h.record(v)
    for p in (50, 90, 99):
        true = float(np.percentile(vals, p))
        est = h.percentile(p)
        assert est >= true * 0.999, (p, est, true)
        assert est <= true * 1.40, (p, est, true)
    # overflow bucket reports the observed max, not +inf
    h.record(1e9)
    assert h.percentile(100) == 1e9
    assert Histogram().percentile(50) is None


def test_bounded_queue_sheds_at_watermark():
    q = BoundedQueue(depth=8, shed_watermark=2)
    reqs = [ServeRequest(None, None, (1, 1), None, 0.0) for _ in range(3)]
    assert q.offer(reqs[0]) and q.offer(reqs[1])
    assert not q.offer(reqs[2])  # at watermark: shed
    assert len(q) == 2


def test_bounded_queue_cancels_expired_before_dispatch():
    q = BoundedQueue(depth=8, shed_watermark=8)
    now = time.monotonic()
    dead = ServeRequest(None, None, (1, 1), now - 1.0, now - 2.0)
    live = ServeRequest(None, None, (1, 1), now + 60.0, now)
    q.offer(dead)
    q.offer(live)
    expired = []
    batch = q.take_batch(4, 0.0, on_expire=expired.append)
    assert batch == [live]
    assert dead.state == EXPIRED and expired == [dead]
    with pytest.raises(DeadlineExceeded):
        dead.wait(timeout=0)


def test_request_terminates_exactly_once():
    req = ServeRequest(None, None, (1, 1), None, 0.0)
    assert req._finish(SERVED, result={}) is True
    assert req._finish(SHED) is False  # already terminal
    assert req.state == SERVED and req.wait(timeout=0) == {}


# ---------------------------------------------------------------------------
# engine behavior
# ---------------------------------------------------------------------------

def test_bucket_routing(engine):
    """Landscape/portrait images route to their static buckets and both
    serve successfully."""
    _, _, b_land = engine.preprocess(_img(landscape=True))
    _, _, b_port = engine.preprocess(_img(landscape=False))
    assert b_land == (128, 160) and b_port == (160, 128)
    # oversized input shrinks-to-fit but stays in the orientation bucket
    big = np.zeros((640, 800, 3), np.uint8)
    _, info, b = engine.preprocess(big)
    assert b == (128, 160) and info[0] <= 128 and info[1] <= 160
    for landscape in (True, False):
        dets = engine.detect(_img(landscape))
        assert isinstance(dets, dict)
        for arr in dets.values():
            assert arr.shape[1] == 5


def test_batch_coalescing_under_max_delay(predictor):
    """Requests arriving inside the coalescing window ride ONE
    micro-batch; a full batch dispatches without waiting the window
    out."""
    eng = ServingEngine(predictor,
                        _serve_cfg(batch_size=4, max_delay_ms=1000.0))
    try:
        # timeout_ms=0 (no deadline): the first batch on this unwarmed
        # engine pays the batch-4 jit compile, which would otherwise trip
        # the completion-time deadline re-check — not this test's subject
        reqs = [eng.submit(_img(seed=i), timeout_ms=0) for i in range(3)]
        for r in reqs:
            r.wait(timeout=30.0)
        snap = eng.metrics.snapshot()
        assert snap["counters"]["batches"] == 1, snap
        assert snap["counters"]["served"] == 3
        assert all(r.batch_rows == 3 for r in reqs)
        assert snap["batch_occupancy"]["mean_rows"] == 3.0

        # full batch: 4 requests must NOT stall for the 1 s window
        t0 = time.monotonic()
        reqs = [eng.submit(_img(seed=i), timeout_ms=0) for i in range(4)]
        for r in reqs:
            r.wait(timeout=30.0)
        assert time.monotonic() - t0 < 0.9, "full batch waited the window"
        assert eng.metrics.snapshot()["counters"]["batches"] == 2
    finally:
        eng.close()


def test_deadline_expiry_and_watermark_shedding(predictor):
    """Admission control end to end: over-watermark requests shed with
    429 semantics, expired requests cancel BEFORE dispatch, live ones
    serve — and every request reaches exactly one terminal state."""
    eng = ServingEngine(
        predictor,
        _serve_cfg(batch_size=4, max_delay_ms=50.0, queue_depth=4,
                   shed_watermark=2),
        start=False)  # hold dispatch so the queue fills deterministically
    img = _img()
    r_expire = eng.submit(img, timeout_ms=30.0)
    r_live = eng.submit(img, timeout_ms=0)      # 0 = no deadline
    r_shed = eng.submit(img)                     # queue at watermark
    assert r_shed.state == SHED
    with pytest.raises(ShedError):
        r_shed.wait(timeout=0)
    time.sleep(0.06)                             # r_expire's deadline passes
    eng.start()
    assert r_live.wait(timeout=30.0) is not None
    with pytest.raises(DeadlineExceeded):
        r_expire.wait(timeout=30.0)
    snap = eng.metrics.snapshot()
    c = snap["counters"]
    assert (c["submitted"], c["served"], c["shed"], c["expired"]) \
        == (3, 1, 1, 1)
    assert snap["in_flight"] == 0 and snap["terminated"] == 3
    eng.close()
    # closed engine sheds new work instead of hanging it
    r_after = eng.submit(img)
    assert r_after.state == SHED


def test_deadline_expiring_during_coalescing_window(predictor):
    """A request ALIVE when collected but expiring while the dispatcher
    holds the partial batch for stragglers must terminate EXPIRED (504),
    never as a late success — the completion-time re-check."""
    eng = ServingEngine(predictor,
                        _serve_cfg(batch_size=4, max_delay_ms=400.0),
                        start=False)
    r = eng.submit(_img(), timeout_ms=100.0)
    eng.start()  # pops r immediately, then waits ~400 ms for company
    with pytest.raises(DeadlineExceeded):
        r.wait(timeout=30.0)
    c = eng.metrics.snapshot()["counters"]
    assert c["expired"] == 1 and c["served"] == 0
    eng.close()


def test_engine_detections_bit_equal_predictor(predictor, engine):
    """The acceptance parity check: an engine response must be BIT-EQUAL
    to composing the same padded micro-batch by hand and running the
    offline Predictor + eval postprocess + shared demux."""
    import jax.numpy as jnp

    cfg = engine.cfg
    img = _img(seed=7)
    dets = engine.detect(img)

    canvas, info, bucket = engine.preprocess(img)
    bh, bw = bucket
    n = cfg.serve.batch_size
    images = np.zeros((n, bh, bw, 3), np.float32)
    im_info = np.tile(np.array([bh, bw, 1.0], np.float32), (n, 1))
    images[0], im_info[0] = canvas, info
    rois, roi_valid, cls_prob, deltas = predictor.raw(images, im_info)
    boxes_b, scores_b, keep_b = map(np.asarray, _postprocess_batch(
        rois, roi_valid, cls_prob, deltas, jnp.asarray(im_info),
        jnp.asarray(im_info[:, 2]), engine._stds, engine._means,
        nms_thresh=cfg.test.nms, score_thresh=cfg.serve.score_thresh))
    expected = detections_from_keep(boxes_b, scores_b, keep_b, 0)

    assert sorted(dets) == sorted(expected)
    for c in expected:
        np.testing.assert_array_equal(dets[c], expected[c])
    assert expected, "degenerate check: random-init net emitted nothing"


def test_warmed_engine_mixed_buckets_zero_recompiles(engine):
    """THE serving recompile guard: after warmup, mixed landscape and
    portrait traffic (full and partial batches) must lower ZERO new
    programs — the serving analog of the train-step compile budget."""
    engine.detect(_img(True))   # both buckets already warm; settle once
    engine.detect(_img(False))
    programs_before = engine.program_count()
    with LoweringCounter() as lc:
        for i in range(6):
            dets = engine.detect(_img(landscape=i % 2 == 0, seed=i))
            assert isinstance(dets, dict)
    assert lc.n == 0, f"{lc.n} recompiles while serving warmed buckets"
    # the shared-predictor jit cache must not have grown either (the
    # module-scoped predictor may carry other engines' batch shapes, so
    # the budget is zero GROWTH, not an absolute count)
    assert engine.program_count() == programs_before


def test_metrics_snapshot_sanity(engine):
    snap = engine.metrics.snapshot()
    c = snap["counters"]
    assert c["served"] > 0 and c["failed"] == 0
    assert snap["terminated"] + snap["in_flight"] == c["submitted"]
    for hist in ("queue_wait_ms", "model_ms", "total_ms"):
        h = snap[hist]
        assert h["count"] > 0
        assert h["p50"] <= h["p90"] <= h["p99"], h
    occ = snap["batch_occupancy"]["mean_rows"]
    assert 0 < occ <= engine.cfg.serve.batch_size


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------

@pytest.fixture()
def http_server(engine):
    from mx_rcnn_tpu.serve.server import make_server

    srv = make_server(engine, port=0, class_names=None)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    host, port = srv.server_address[:2]
    yield f"http://{host}:{port}"
    srv.shutdown()
    srv.server_close()


def _http(url, payload=None):
    if payload is None:
        req = urllib.request.Request(url)
    else:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_http_detect_healthz_metrics(http_server):
    img = _img(seed=3)
    status, body = _http(http_server + "/detect", {
        "pixels_b64": base64.b64encode(img.tobytes()).decode(),
        "shape": list(img.shape)})
    assert status == 200
    assert "latency_ms" in body
    assert 1 <= body["batch_rows"] <= 2  # the documented wire field
    for det in body["detections"]:
        assert set(det) == {"class_id", "class", "score", "box"}
        assert len(det["box"]) == 4
    scores = [d["score"] for d in body["detections"]]
    assert scores == sorted(scores, reverse=True)

    status, health = _http(http_server + "/healthz")
    assert status == 200 and health["ok"] is True
    assert health["programs"] >= len(health["buckets"])

    status, snap = _http(http_server + "/metrics")
    assert status == 200 and snap["counters"]["served"] > 0

    status, err = _http(http_server + "/detect", {"shape": [2, 2, 3]})
    assert status == 400 and "error" in err
    # valid JSON that is not an object must 400, not drop the connection
    status, err = _http(http_server + "/detect", "image_b64")
    assert status == 400 and "JSON object" in err["error"]
    status, err = _http(http_server + "/nope")
    assert status == 404


def test_http_oversized_image_shrinks_to_fit(http_server):
    """An image whose resize target exceeds every bucket must be shrunk
    to fit (choose_bucket's contract, same step as the loader path) and
    served — historically it escaped as a raw ValueError that killed the
    handler thread (dropped connection, replica thread dead)."""
    rng = np.random.default_rng(7)
    img = rng.integers(0, 255, (900, 1400, 3), dtype=np.uint8)
    status, body = _http(http_server + "/detect", {
        "pixels_b64": base64.b64encode(img.tobytes()).decode(),
        "shape": list(img.shape)})
    assert status == 200 and "detections" in body


def test_http_image_b64_roundtrip(http_server):
    """The encoded-file payload path decodes through the same BGR→RGB
    convention as ``imread_rgb``."""
    import cv2

    img = _img(seed=11)
    ok, buf = cv2.imencode(".png", img[:, :, ::-1])  # encode as BGR file
    assert ok
    status, body = _http(http_server + "/detect", {
        "image_b64": base64.b64encode(buf.tobytes()).decode()})
    assert status == 200 and "detections" in body


def test_http_body_admission_bounds(http_server):
    """ISSUE 16 satellite: the 411/413 refusal contract (netio).  A
    peer CLAIMING a multi-GB Content-Length costs a 413 off the claim
    alone — before a single body byte is read — and a body with no
    Content-Length at all (chunked transfer included) is a 411."""
    from mx_rcnn_tpu.analysis.wirefuzz import http_post_raw

    host, _, port = http_server.removeprefix("http://").partition(":")
    t0 = time.monotonic()
    res = http_post_raw(host, int(port), "/detect", b"{}",
                        ctype="application/json",
                        content_length=3 << 30)
    assert res["status"] == 413
    assert time.monotonic() - t0 < 5.0  # refused, not buffered
    res = http_post_raw(host, int(port), "/detect", b"",
                        ctype="application/json",
                        content_length="absent")
    assert res["status"] == 411


def test_http_trickled_body_is_408_at_the_deadline(engine):
    """The slow-loris bound: per-recv socket timeouts never trip on a
    one-byte-per-tick sender, so the WHOLE body read carries a
    wall-clock deadline (server.body_deadline_s → 408)."""
    from mx_rcnn_tpu.analysis.wirefuzz import http_post_raw
    from mx_rcnn_tpu.serve.server import make_server

    srv = make_server(engine, port=0, class_names=None)
    srv.body_deadline_s = 1.0
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    host, port = srv.server_address[:2]
    try:
        res = http_post_raw(host, port, "/detect", b"x" * 400,
                            ctype="application/json", mode="trickle",
                            trickle_bytes=10 ** 9,
                            trickle_delay_s=0.05, timeout_s=20.0)
        assert res["status"] == 408
        assert res["elapsed_s"] < 10.0
    finally:
        srv.shutdown()
        srv.server_close()


def test_http_hostile_timeout_ms_is_400(http_server):
    """A peer-supplied inf/NaN/negative timeout_ms dies at admission as
    a 400 — wirefuzz found inf reaching ``Condition.wait`` as an
    OverflowError (a 500 for client bytes)."""
    img = _img(seed=5)
    payload = {"pixels_b64": base64.b64encode(img.tobytes()).decode(),
               "shape": list(img.shape)}
    for hostile in (float("inf"), float("nan"), -3.0, 1e38, "soon"):
        status, err = _http(http_server + "/detect",
                            dict(payload, timeout_ms=hostile))
        assert status == 400, (hostile, status, err)
        assert "timeout_ms" in err["error"]
    # a sane value still serves
    status, body = _http(http_server + "/detect",
                         dict(payload, timeout_ms=30000.0))
    assert status == 200 and "detections" in body


# ---------------------------------------------------------------------------
# loadgen
# ---------------------------------------------------------------------------

def test_loadgen_smoke_checks_pass(capsys):
    """The `make serve-smoke` path in miniature: closed loop on the tiny
    canvas, asserting the acceptance invariants (zero lost, zero
    recompiles) via --check."""
    from mx_rcnn_tpu.tools.loadgen import main

    rc = main(["--smoke", "--duration", "2", "--check",
               "--concurrency", "3"])
    out = capsys.readouterr().out.strip().splitlines()
    rec = json.loads(out[-1])
    assert rc == 0
    assert rec["lost"] == 0
    assert rec["recompiles_after_warmup"] == 0
    assert rec["served"] > 0 and rec["measured"] is True
    assert rec["submitted"] == (rec["served"] + rec["shed"]
                                + rec["expired"] + rec["failed"])
    assert rec["p50_ms"] <= rec["p99_ms"]
    assert rec["shed_rate"] == 0.0  # closed loop cannot overrun the queue


def test_loadgen_open_loop_sheds_gracefully_when_overdriven(capsys):
    """Open-loop arrivals far past capacity must terminate EVERY request
    (served, shed, or expired — none lost, none failed) with a tight
    admission queue — overload degrades by rejection, not collapse."""
    from mx_rcnn_tpu.tools.loadgen import main

    rc = main(["--smoke", "--mode", "open", "--duration", "2",
               "--qps", "400", "--timeout_ms", "250",
               "--set", "serve__queue_depth=8",
               "--set", "serve__shed_watermark=4"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert rec["lost"] == 0 and rec["failed"] == 0
    assert rec["submitted"] == rec["served"] + rec["shed"] + rec["expired"]
    # at 400 qps against a ~300 imgs/s engine with a depth-4 watermark,
    # admission control MUST have engaged
    assert rec["shed"] + rec["expired"] > 0, rec
    assert rec["recompiles_after_warmup"] == 0
