"""Distributed-tracing tests (ISSUE 19): wire-propagated trace context
(compact frame extension + ``X-MXR-Trace`` header codec, back-compat
bit-identity pinned), tail sampling and forced terminal retention,
per-terminal-state span audit, NTP-style skew estimation with a
monotonic skew-corrected merge, decision-log correlation ids, the
flight-recorder schema /2 trace-tree tail, and the doctor primitives
(`tools/trace.py`).

Everything here is in-process and stub-driven (quick tier).  The
multi-PROCESS claims — 100%-complete span trees across real agent
subprocesses, the SIGKILL-reroute single-trace view, the <2% overhead
A/B — are ``tools/trace.py --check``'s job (docs/TRACE_r19.json).
"""

import json
import struct
import threading
import time

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.obs import trace as obs_trace
from mx_rcnn_tpu.obs.trace import (TraceContext, decode_ctx, encode_ctx,
                                   format_header, merge_fleet_trace,
                                   parse_header, tree_complete,
                                   tree_monotonic)
from mx_rcnn_tpu.serve.queue import SERVED, ServeRequest
from mx_rcnn_tpu.serve.remote import (_REQ_HEAD, WIRE_MAGIC,
                                      WIRE_VERSION, decode_prepared,
                                      decode_prepared_ex,
                                      decode_result_ex, encode_prepared,
                                      encode_result)
from mx_rcnn_tpu.tools.trace import (attribution_table, decision_query,
                                     format_tree, load_traces)


@pytest.fixture(autouse=True)
def _clean_distributed_state():
    """Every test starts and ends with the distributed plane unarmed —
    the module mutates process-global sampling/ring/skew state."""
    obs_trace.reset_distributed()
    yield
    obs_trace.reset_distributed()


def _cfg(**kw):
    over = {
        "bucket__scale": 128, "bucket__max_size": 160,
        "bucket__shapes": ((128, 160), (160, 128)),
        "serve__batch_size": 2, "serve__max_delay_ms": 5.0,
        "fleet__health_interval_s": 30.0,
    }
    over.update(kw)
    return generate_config("tiny", "synthetic", **over)


def _frame_parts(seed=0, shape=(16, 20)):
    rng = np.random.RandomState(seed)
    data = (rng.rand(*shape, 3) * 255.0).astype(np.float32)
    info = np.array([shape[0], shape[1], 1.0], np.float32)
    return data, info


# ---------------------------------------------------------------------------
# context codec: blob + header
# ---------------------------------------------------------------------------

def test_ctx_blob_round_trip():
    for ctx in (TraceContext("abc123", parent=0, hop=0, sampled=True),
                TraceContext("de.ad-be_ef:0", parent=(1 << 64) - 1,
                             hop=65535, sampled=False),
                TraceContext("f" * 64, parent=7, hop=3, sampled=True)):
        assert decode_ctx(encode_ctx(ctx)) == ctx


def test_ctx_blob_rejects_malformed():
    blob = encode_ctx(TraceContext("abc123", parent=5, hop=1))
    cases = [
        blob[:3],                      # truncated header
        blob[:-1],                     # short of declared id length
        blob + b"0",                   # trailing bytes past declared
        b"\x02" + blob[1:],            # unknown version
        blob[:12] + b"\x00" + blob[13:],   # idlen 0
        blob[:12] + b"\xff" + blob[13:],   # idlen over cap
        blob[:13] + b"!" + blob[14:],  # charset violation
        blob[:13] + b"\xff" + blob[14:],   # non-ascii id byte
    ]
    for buf in cases:
        with pytest.raises(ValueError):
            decode_ctx(buf)
    # unknown FLAG bits are the pinned forward-compat carve-out
    fwd = blob[:1] + bytes([blob[1] | 0x80]) + blob[2:]
    assert decode_ctx(fwd).trace_id == "abc123"


def test_ctx_header_round_trip_and_malformed():
    ctx = TraceContext("abc.123", parent=0xBEEF, hop=2, sampled=False)
    assert parse_header(format_header(ctx)) == ctx
    for bad in ("", "v2;id=a;parent=0;hop=0;s=1",
                "v1;id=a;parent=0;hop=0",          # missing s
                "v1;id=a;parent=zz;hop=0;s=1",     # bad hex
                "v1;id=a;parent=0;hop=0;s=2",      # bad sampling bit
                "v1;id=a;parent=0;hop=99999;s=1",  # hop out of range
                "v1;id=nope!;parent=0;hop=0;s=1",  # charset
                "v1;garbage",                      # field w/o '='
                "v1;id=" + "a" * 400):             # oversized
        with pytest.raises(ValueError):
            parse_header(bad)


# ---------------------------------------------------------------------------
# frame extension: back-compat bit-identity + typed rejection
# ---------------------------------------------------------------------------

def test_untraced_frame_bit_identical_to_pr15_layout():
    """The back-compat pin: ``ctx=None`` produces EXACTLY the pre-trace
    bytes — header flags 0, nothing appended — reconstructed here from
    the frozen struct layout, not from the encoder under test."""
    data, info = _frame_parts(seed=3)
    buf = encode_prepared(data, info, 250.0, ctx=None)
    h, w, c = data.shape
    golden = _REQ_HEAD.pack(WIRE_MAGIC, WIRE_VERSION, h, w, c, 0,
                            250.0, *[float(v) for v in info]
                            ) + data.tobytes()
    assert buf == golden


def test_traced_frame_round_trip_and_untraced_decode():
    data, info = _frame_parts(seed=4)
    ctx = TraceContext("abc.def", parent=0x1234, hop=1, sampled=True)
    buf = encode_prepared(data, info, 500.0, ctx=ctx)
    out, oinfo, t, octx = decode_prepared_ex(buf)
    assert out.tobytes() == data.tobytes()
    assert octx == ctx
    # flag-less frames decode with ctx None through the same surface
    plain = encode_prepared(data, info, 500.0)
    assert decode_prepared_ex(plain)[3] is None
    # the PR-15 decode surface still accepts BOTH layouts
    assert decode_prepared(buf)[0].tobytes() == data.tobytes()


def test_frame_trace_flag_malformations_reject():
    data, info = _frame_parts(seed=5)
    ctx = TraceContext("abc", parent=1, hop=0)
    traced = encode_prepared(data, info, 0.0, ctx=ctx)
    plain = encode_prepared(data, info, 0.0)
    ext = traced[len(plain):]

    def with_flags(buf, flags):
        d = bytearray(buf)
        struct.pack_into("<H", d, 12, flags)
        return bytes(d)

    with pytest.raises(ValueError):       # unknown flag bit
        decode_prepared_ex(with_flags(plain, 0x2))
    with pytest.raises(ValueError):       # flag set, extension absent
        decode_prepared_ex(with_flags(plain, 0x1))
    with pytest.raises(ValueError):       # extension without the flag
        decode_prepared_ex(plain + ext)
    with pytest.raises(ValueError):       # truncated extension
        decode_prepared_ex(traced[:-1])
    with pytest.raises(ValueError):       # inflated extension
        decode_prepared_ex(traced + b"\0")


def test_result_skew_extension_round_trip_and_malformed():
    rng = np.random.RandomState(0)
    dets = {1: rng.rand(3, 5).astype(np.float32)}
    v1 = encode_result(dets)
    v2 = encode_result(dets, ts_pair=(1_000_000, 1_000_750))
    out, ts = decode_result_ex(v2)
    assert ts == (1_000_000.0, 1_000_750.0)
    assert out[1].tobytes() == dets[1].tobytes()
    assert decode_result_ex(v1)[1] is None
    assert v2[:len(v1)] != v1            # version byte differs
    with pytest.raises(ValueError):      # send precedes receive
        decode_result_ex(encode_result(dets, ts_pair=(200, 100)))
    with pytest.raises(ValueError):      # truncated extension
        decode_result_ex(v2[:-1])
    with pytest.raises(ValueError):      # v1 with trailing ext bytes
        decode_result_ex(v1 + v2[-16:])


# ---------------------------------------------------------------------------
# sampling + retention policy
# ---------------------------------------------------------------------------

def test_sample_trace_is_deterministic_fraction():
    obs_trace.configure_distributed(sample=0.25, ring=64, host="head")
    picks = [obs_trace.sample_trace() is not None for _ in range(100)]
    assert sum(picks) == 25
    # exactly 1-in-4, not a coin flip: every 4th admission is sampled
    assert picks[3::4] == [True] * 25
    obs_trace.configure_distributed(sample=0.0)
    assert obs_trace.sample_trace() is None


def test_retain_trace_forces_terminals_and_keeps_tail():
    obs_trace.configure_distributed(sample=1.0, ring=64, slow_pct=90.0)
    # every non-SERVED terminal and every rerouted request is forced
    for state in ("EXPIRED", "FAILED", "SHED"):
        assert obs_trace.retain_trace(state, total_ms=1.0)
    assert obs_trace.retain_trace("SERVED", total_ms=1.0, attempts=2)
    # warmup keeps everything until the window has 32 samples
    assert obs_trace.retain_trace("SERVED", total_ms=1.0)
    for _ in range(50):
        obs_trace.retain_trace("SERVED", total_ms=10.0)
    # now a fast request drops, a slow one stays
    assert not obs_trace.retain_trace("SERVED", total_ms=1.0)
    assert obs_trace.retain_trace("SERVED", total_ms=500.0)


def test_admin_trace_gated_on_armed_plane():
    assert obs_trace.admin_trace() is None       # unarmed
    obs_trace.configure_distributed(sample=0.01, ring=16)
    ctx = obs_trace.admin_trace()                # armed: ALWAYS sampled
    assert ctx is not None and ctx.sampled and ctx.hop == 0


def test_correlation_id_deterministic():
    assert obs_trace.correlation_id(12.345) == obs_trace.correlation_id(
        12.345)
    assert obs_trace.correlation_id(1.0) == "w3e8"


# ---------------------------------------------------------------------------
# terminal-span audit (queue level) + ring close semantics
# ---------------------------------------------------------------------------

def test_every_terminal_transition_records_exactly_one_terminal_span():
    obs_trace.configure_distributed(sample=1.0, ring=64, slow_pct=0.0)
    for state in (SERVED, "shed", "expired", "failed"):
        ctx = TraceContext(f"t.{state}", parent=obs_trace.new_span_id(),
                           hop=0)
        req = ServeRequest(np.zeros((2, 2, 3), np.float32),
                           np.array([2, 2, 1], np.float32), (2, 2),
                           None, time.monotonic())
        req.tctx = ctx
        assert req._finish(state)
        assert not req._finish(state)        # exactly-once: no 2nd span
        obs_trace.close_trace(ctx, keep=True, state=state)
        tree = obs_trace.kept_trees()[-1]
        names = [s["name"] for s in tree["spans"]]
        assert names.count(f"terminal.{state}") == 1


def test_span_ring_close_drops_or_keeps_and_bounds():
    ring = obs_trace.SpanRing(cap=2, cap_spans=4)
    for tid, keep in (("a", True), ("b", False), ("c", True),
                      ("d", True)):
        ring.record(tid, {"name": "x", "span": 1, "parent": 0,
                          "ts": 0.0, "dur": 1.0})
        ring.close(tid, keep=keep)
    trees = ring.trees()
    assert [t["trace"] for t in trees] == ["c", "d"]  # cap 2, b dropped
    ring.close("never-opened", keep=True)             # no-op, no raise
    assert ring.open_count() == 0


# ---------------------------------------------------------------------------
# skew estimation + corrected merge
# ---------------------------------------------------------------------------

def test_skew_estimator_recovers_known_offset():
    est = obs_trace.SkewEstimator(window=64)
    # agent clock runs 5 ms AHEAD; symmetric 1 ms one-way delay
    for k in range(20):
        t0 = k * 10_000
        t1 = t0 + 1_000 + 5_000
        t2 = t1 + 2_000
        t3 = t0 + 4_000
        est.note("remote-0", t0, t1, t2, t3)
    # queueing-noise samples with inflated rtt must not move the median
    for k in range(10):
        t0 = 1_000_000 + k * 10_000
        est.note("remote-0", t0, t0 + 90_000, t0 + 91_000, t0 + 100_000)
    assert est.offset_ms("remote-0") == pytest.approx(5.0, abs=0.01)
    g = est.gauges()
    assert g["obs.skew_ms.remote-0"] == pytest.approx(5.0, abs=0.01)
    assert g["obs.skew_ms.max"] == pytest.approx(5.0, abs=0.01)
    assert est.offset_ms("never-seen") is None


def test_merge_corrects_remote_clocks_and_stays_monotonic(tmp_path):
    # head tree: root at t=10ms, wire child at 11ms (head clock, µs)
    root, wire, aroot = 0x10, 0x11, 0x12
    local = [{"trace": "m.1", "host": "head", "spans": [
        {"name": "request", "span": root, "parent": 0, "ts": 10_000.0,
         "dur": 8_000.0, "host": "head", "hop": 0},
        {"name": "remote.wire", "span": wire, "parent": root,
         "ts": 11_000.0, "dur": 6_000.0, "host": "head", "hop": 1}]}]
    # agent clock runs 30 ms ahead: uncorrected, its span would start
    # BEFORE the head root in head time order after correction test
    remote = {"remote-0": [{"trace": "m.1", "host": "agent", "spans": [
        {"name": "agent.request", "span": aroot, "parent": wire,
         "ts": 12_000.0 + 30_000.0, "dur": 4_000.0, "host": "agent",
         "hop": 2}]}]}
    out = tmp_path / "merged.json"
    doc = merge_fleet_trace(local, remote, {"remote-0": 30.0},
                            path=str(out))
    spans = doc["traces"]["m.1"]
    assert tree_complete(spans) and tree_monotonic(spans)
    byid = {s["span"]: s for s in spans}
    assert byid[aroot]["ts"] == pytest.approx(12_000.0)  # corrected
    assert doc["metadata"]["offsets_ms"] == {"remote-0": 30.0}
    # an OVER-estimated offset inverts the edge; the clamp repairs it
    # and counts the repair honestly
    doc2 = merge_fleet_trace(local, remote, {"remote-0": 35.0})
    spans2 = doc2["traces"]["m.1"]
    assert tree_monotonic(spans2)
    assert doc2["metadata"]["clamped"] >= 1
    # the chrome file round-trips through the doctor's loader with the
    # same span/parent structure
    loaded = load_traces(str(out))
    assert {s["span"] for s in loaded["m.1"]} == {root, wire, aroot}
    assert tree_complete(loaded["m.1"])


# ---------------------------------------------------------------------------
# in-process two-hop completeness (head fleet -> agent server)
# ---------------------------------------------------------------------------

@pytest.mark.usefixtures("_clean_distributed_state")
def test_two_hop_span_tree_is_complete_in_process():
    from mx_rcnn_tpu.serve.agent import ReplicaAgent, make_agent_server
    from mx_rcnn_tpu.serve.remote import build_crosshost_router
    from mx_rcnn_tpu.tools.loadgen import make_content_stub_run_fn

    cfg = _cfg(crosshost__connections=1, crosshost__pipeline_depth=4,
               crosshost__scrape_interval_s=30.0,
               obs__trace_sample=1.0, obs__trace_slow_pct=0.0)
    ag = ReplicaAgent(cfg, None, {}, run_fn_factory=(
        lambda rid: make_content_stub_run_fn(cfg)))
    srv = make_agent_server(ag, "127.0.0.1", 0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    obs_trace.configure_distributed(sample=1.0, ring=256, slow_pct=0.0,
                                    host="head")
    router = feed = None
    try:
        router, feed = build_crosshost_router(cfg, [url])
        b = tuple(cfg.bucket.shapes[0])
        rng = np.random.RandomState(7)
        reqs = [router.submit_prepared(
            (rng.rand(*b, 3) * 255.0).astype(np.float32),
            np.array([b[0], b[1], 1.0], np.float32), b,
            timeout_ms=30_000) for _ in range(4)]
        for r in reqs:
            assert r.wait(timeout=30.0) is not None
        # wait() unblocks inside the terminal transition, BEFORE the
        # worker thread records the root span and closes the trace —
        # poll until every trace settled (a "request" span landed)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            trees = obs_trace.kept_trees()
            settled = sum(
                1 for t in trees
                if any(s["name"] == "request" and s["hop"] == 0
                       for s in t["spans"]))
            if settled >= 4:
                break
            time.sleep(0.02)
        # head and (in-process) agent share the ring: fold every kept
        # tree by trace id, exactly what merge_fleet_trace does
        doc = merge_fleet_trace(obs_trace.kept_trees(), {}, {})
        assert len(doc["traces"]) >= 4
        for tid, spans in doc["traces"].items():
            names = {s["name"] for s in spans}
            assert tree_complete(spans), f"incomplete tree {tid}"
            assert {"request", "remote.wire", "agent.request",
                    "serve.compute", "terminal.served"} <= names
    finally:
        if feed is not None:
            feed.close()
        if router is not None:
            router.close()
        srv.shutdown()
        srv.server_close()
        ag.close()


def test_untraced_hot_path_stays_cold():
    """``obs.trace_sample = 0`` (the default) must leave serving
    untouched: no contexts minted, no trees kept, wire bytes
    bit-identical (the encoder pin above) — the hot path pays exactly
    the ``ctx is None`` checks."""
    assert obs_trace.sample_trace() is None
    assert obs_trace.admin_trace() is None
    assert obs_trace.kept_trees() == []
    obs_trace.configure_distributed(sample=0.0, ring=64)
    assert obs_trace.sample_trace() is None     # armed ring, zero rate


# ---------------------------------------------------------------------------
# flight recorder schema /2
# ---------------------------------------------------------------------------

def test_flight_dump_carries_trace_tree_tail(tmp_path):
    from mx_rcnn_tpu.obs.flightrec import FlightRecorder
    from mx_rcnn_tpu.obs.timeseries import TimeSeriesStore

    obs_trace.configure_distributed(sample=1.0, ring=16, slow_pct=0.0,
                                    host="head")
    ctx = TraceContext("fl.1", parent=obs_trace.new_span_id(), hop=0)
    obs_trace.record_span(ctx, "request", 5.0, span_id=ctx.parent,
                          parent=0)
    obs_trace.close_trace(ctx, keep=True, state="served")
    rec = FlightRecorder(TimeSeriesStore(capacity=8),
                         str(tmp_path / "run"), trace_tree_tail=8)
    path = rec.dump("test", force=True)
    with open(path) as f:
        record = json.load(f)
    assert record["schema"] == "mx_rcnn_tpu.flight/2"
    assert [t["trace"] for t in record["trace_trees"]] == ["fl.1"]


# ---------------------------------------------------------------------------
# doctor primitives
# ---------------------------------------------------------------------------

def test_format_tree_nests_children_and_marks_orphans():
    spans = [
        {"name": "request", "span": 1, "parent": 0, "ts": 0.0,
         "dur": 9_000.0, "host": "head", "hop": 0},
        {"name": "fleet.attempt", "span": 2, "parent": 1, "ts": 100.0,
         "dur": 8_000.0, "host": "head", "hop": 0},
        {"name": "agent.request", "span": 3, "parent": 99,  # lost hop
         "ts": 200.0, "dur": 7_000.0, "host": "agent", "hop": 2},
    ]
    lines = format_tree(spans)
    assert lines[0].startswith("request")
    assert lines[1].startswith("  fleet.attempt")   # nested one level
    assert any("(orphan)" in ln and "agent.request" in ln
               for ln in lines)


def test_attribution_table_percentiles():
    traces = {"t1": [{"name": "serve.compute", "span": 1, "parent": 0,
                      "ts": 0.0, "dur": d * 1e3}
                     for d in (1.0, 2.0, 3.0, 100.0)]}
    tab = attribution_table(traces)
    assert tab["serve.compute"]["n"] == 4
    assert tab["serve.compute"]["p50_ms"] == pytest.approx(3.0)
    assert tab["serve.compute"]["p99_ms"] == pytest.approx(100.0)


def test_decision_query_walks_nested_docs():
    doc = {"actions": [{"action": "add", "corr": "w1a"},
                       {"action": "rollback", "corr": "w2b",
                        "nested": [{"corr": "w1a", "kind": "inner"}]}],
           "events": {"deep": [{"corr": "w1a"}]}}
    hits = decision_query(doc, "w1a")
    assert len(hits) == 3
    assert all(h["corr"] == "w1a" for h in hits)
    assert decision_query(doc, "w9z") == []


def test_scheduler_actions_carry_correlation_ids():
    """Every scheduler decision (and rollback) carries the triggering
    sample's ``w<epoch-ms hex>`` correlation id — the join key
    ``tools/trace.py --decision`` queries on."""
    from mx_rcnn_tpu.obs.timeseries import TimeSeriesStore
    from mx_rcnn_tpu.serve.scheduler import SchedulerPolicy

    cfg = _cfg(crosshost__for_samples=2, crosshost__idle_samples=3,
               crosshost__cooldown_s=5.0, crosshost__window_s=3.0,
               crosshost__min_replicas=1, crosshost__max_replicas=8,
               crosshost__up_shed_ratio=0.05, crosshost__up_backlog=2.0)
    store = TimeSeriesStore(capacity=64)
    pol = SchedulerPolicy(cfg)

    def snap(ts, ready):
        store.append_snapshot(
            {"counters": {}, "gauges": {
                f"agent.replicas_ready@{src}": v
                for src, v in ready.items()}}, ts=ts)

    snap(0.0, {"agent-0": 1, "agent-1": 1})
    assert pol.decide(store, now=0.0) is None   # target adopted: 2
    snap(1.0, {"agent-0": 1})                   # host death → deficit
    assert pol.decide(store, now=1.0) is None   # hysteresis
    snap(2.0, {"agent-0": 1})
    act = pol.decide(store, now=2.0)
    assert act and act["action"] == "add"
    assert act["corr"] == obs_trace.correlation_id(2.0)
