"""Fleet tier tests (ISSUE 8): AOT export round-trip bit-equality,
batch-aware JSQ routing, deadline/shed composition at fleet scope,
replica crash → eject → relaunch → rejoin, and the fleet-wide
terminate-exactly-once accounting invariant.

Routing/lifecycle tests run stub-model fleets (``make_stub_run_fn``
gated by an event — no compiles, millisecond launches); the export
tests use the module-scoped tiny Predictor so the whole file traces a
handful of quick-tier programs once.
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.serve.engine import ServingEngine
from mx_rcnn_tpu.serve.export import (ExportMismatch, ExportStore,
                                      export_serve_programs,
                                      serve_fwd_name)
from mx_rcnn_tpu.serve.fleet import (R_DEAD, R_READY, R_RELAUNCHING,
                                     FleetRouter, ReplicaManager,
                                     build_fleet, partition_devices)
from mx_rcnn_tpu.serve.queue import (EXPIRED, FAILED, PENDING, SERVED,
                                     SHED, ServeRequest)
from mx_rcnn_tpu.tools.loadgen import init_predictor, make_stub_run_fn


def _fleet_cfg(replicas=2, **kw):
    cfg = generate_config(
        "tiny", "synthetic",
        bucket__scale=128, bucket__max_size=160,
        bucket__shapes=((128, 160), (160, 128)),
        test__rpn_pre_nms_top_n=512, test__rpn_post_nms_top_n=64,
        serve__batch_size=2, serve__max_delay_ms=20.0,
        fleet__replicas=replicas, fleet__health_interval_s=30.0)
    for sec in ("serve", "fleet"):
        sub = {k.split("__", 1)[1]: v for k, v in kw.items()
               if k.startswith(sec + "__")}
        if sub:
            cfg = cfg.replace_in(sec, **sub)
    return cfg


@pytest.fixture(scope="module")
def predictor():
    return init_predictor(_fleet_cfg())


def _img(landscape=True, seed=0):
    rng = np.random.RandomState(seed)
    h, w = (128, 160) if landscape else (160, 128)
    return rng.randint(0, 256, size=(h, w, 3), dtype=np.uint8)


class _Gate:
    """Per-fleet stub gate: replicas serve instantly while ``open``;
    ``close()`` makes every subsequent batch block until reopened —
    the controlled-backlog knob for routing tests."""

    def __init__(self):
        self._ev = threading.Event()
        self._ev.set()

    def close(self):
        self._ev.clear()

    def open(self):
        self._ev.set()

    def factory(self, cfg):
        def make(rid):
            inner = make_stub_run_fn(cfg, model_ms=1.0)

            def run_fn(images, im_info):
                self._ev.wait(timeout=30.0)
                return inner(images, im_info)

            return run_fn

        return make


def _stub_fleet(predictor, cfg, gate=None):
    gate = gate or _Gate()
    router = build_fleet(cfg, predictor.model, predictor.variables,
                         run_fn_factory=gate.factory(cfg))
    return router, gate


def _drain(router, timeout_s=20.0):
    deadline = time.monotonic() + timeout_s
    while (router.metrics.snapshot()["in_flight"] > 0
           and time.monotonic() < deadline):
        time.sleep(0.02)


# ---------------------------------------------------------------------------
# config + device partitioning
# ---------------------------------------------------------------------------

def test_fleet_config_section_and_overrides():
    cfg = generate_config("tiny", "synthetic", fleet__replicas=4,
                          fleet__reroute_retries=3,
                          fleet__export_dir="/tmp/x")
    assert cfg.fleet.replicas == 4
    assert cfg.fleet.reroute_retries == 3
    assert cfg.fleet.export_dir == "/tmp/x"
    with pytest.raises(ValueError):
        ReplicaManager(lambda rid: None,
                       generate_config("tiny", "synthetic",
                                       fleet__replicas=0))


def test_partition_devices_shares_scarce_supply():
    devs = ["d0"]
    subsets = partition_devices(3, devices=devs)
    assert subsets == [["d0"], ["d0"], ["d0"]]
    subsets = partition_devices(2, devices=["d0", "d1", "d2", "d3"])
    assert subsets == [["d0", "d1"], ["d2", "d3"]]
    with pytest.raises(ValueError):
        partition_devices(0, devices=devs)


# ---------------------------------------------------------------------------
# AOT export: round trip, admission checks, corruption
# ---------------------------------------------------------------------------

def test_export_round_trip_bit_equal_and_warm_start(predictor, tmp_path):
    """The tentpole pin: exported programs verify bit-equal at export
    time, AND an export-warmed engine's end-to-end detections are
    bit-identical to a trace-warmed engine's on the same images."""
    cfg = _fleet_cfg()
    root = str(tmp_path / "store")
    report = export_serve_programs(predictor, cfg, root)
    assert report["bit_equal"] is True
    assert sorted(e["name"] for e in report["programs"]) == sorted(
        [serve_fwd_name(tuple(b), cfg.serve.batch_size)
         for b in cfg.bucket.shapes] + ["serve_post"])

    live = ServingEngine(predictor, cfg)
    live.warmup()
    from mx_rcnn_tpu.core.tester import Predictor
    cold_pred = Predictor(predictor.model, predictor.variables, cfg)
    warm = ServingEngine(cold_pred, cfg, start=True)
    join = warm.warm_from_export(ExportStore(root))
    assert join["programs"] == len(cfg.bucket.shapes)
    try:
        for seed in range(3):
            for landscape in (True, False):
                img = _img(landscape, seed)
                a = live.detect(img, timeout_ms=30_000)
                b = warm.detect(img, timeout_ms=30_000)
                assert set(a) == set(b)
                for cls in a:
                    np.testing.assert_array_equal(a[cls], b[cls])
    finally:
        live.close()
        warm.close()


def test_export_store_refuses_mismatched_config(predictor, tmp_path):
    cfg = _fleet_cfg()
    root = str(tmp_path / "store")
    export_serve_programs(predictor, cfg, root, verify=False)
    other = generate_config(
        "tiny", "synthetic", bucket__scale=96, bucket__max_size=128,
        bucket__shapes=((96, 128),))
    store = ExportStore(root)
    with pytest.raises(ExportMismatch):
        store.check(other)
    store.check(other, allow_mismatch=True)  # explicit downgrade only
    # serving-semantics knobs sit OUTSIDE the train-config fingerprint
    # but are baked into the exported postprocess as static args — a
    # drifted value must refuse too, not silently serve different boxes
    drifted = cfg.replace_in("serve", score_thresh=cfg.serve.score_thresh
                             + 0.2)
    with pytest.raises(ExportMismatch, match="serve_score_thresh"):
        store.check(drifted)


def test_export_store_refuses_corrupt_program(predictor, tmp_path):
    cfg = _fleet_cfg()
    root = str(tmp_path / "store")
    export_serve_programs(predictor, cfg, root, verify=False)
    store = ExportStore(root)
    name = store.names()[0]
    path = os.path.join(root, store.manifest()["entries"][name]["file"])
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(blob))
    with pytest.raises(ExportMismatch):
        store.load(name)


def test_install_program_refuses_resident_slot(predictor):
    cfg = _fleet_cfg()
    from mx_rcnn_tpu.core.tester import Predictor
    pred = Predictor(predictor.model, predictor.variables, cfg)
    key = pred.program_key("rpn", (np.zeros((2, 128, 160, 3), np.float32),
                                   np.zeros((2, 3), np.float32)))
    pred.install_program(key, lambda *a: None)
    with pytest.raises(ValueError):
        pred.install_program(key, lambda *a: None)


# ---------------------------------------------------------------------------
# routing: batch-aware JSQ, deadline, shed composition
# ---------------------------------------------------------------------------

def test_jsq_avoids_backlogged_bucket_lane(predictor):
    """The convoy-stall pin: a replica whose lane for THIS bucket is
    cycles deep loses to one with an idle lane, even when total depths
    would say otherwise (total-depth JSQ measured a ~5-cycle lane stall
    in the fleet bench — serve/fleet.py ``_dispatch``)."""
    cfg = _fleet_cfg(replicas=2)
    router, gate = _stub_fleet(predictor, cfg)
    try:
        gate.close()
        r0, r1 = router.manager.replicas
        # jam replica 0's landscape lane 2 batch-cycles deep
        for seed in range(5):
            req = r0.engine.submit(_img(True, seed), timeout_ms=0)
            assert req.state not in (SHED,)
        assert r0.engine.bucket_depth((128, 160)) >= 3
        assert r0.depth() > r1.depth()
        freq = router.submit(_img(True, 99), timeout_ms=30_000)
        assert freq.replica_id == r1.id
        # the portrait bucket is idle on BOTH replicas: depth tiebreak
        # must send it to the emptier replica 1
        freq2 = router.submit(_img(False, 7), timeout_ms=30_000)
        assert freq2.replica_id == r1.id
    finally:
        gate.open()
        _drain(router)
        router.close()


def test_request_expired_during_routing_terminates_expired(predictor):
    """Deadline composition: a request already past its deadline when
    routing runs terminates EXPIRED and never consumes a replica slot."""
    cfg = _fleet_cfg(replicas=2)
    router, gate = _stub_fleet(predictor, cfg)
    try:
        from mx_rcnn_tpu.serve.fleet import FleetRequest
        now = time.monotonic()
        freq = FleetRequest(_img(), now - 1.0, now)  # born expired
        before = [r.engine.metrics.counters["submitted"]
                  for r in router.manager.replicas]
        router._dispatch(freq)
        assert freq.state == EXPIRED
        after = [r.engine.metrics.counters["submitted"]
                 for r in router.manager.replicas]
        assert after == before
        assert router.metrics.counters["expired"] == 1
    finally:
        router.close()


def test_fleet_shed_requires_every_replica_saturated(predictor):
    """Watermark composition: JSQ routes to the least-loaded replica, so
    a fleet-level SHED means every replica was at its watermark; while
    ANY replica has room the fleet must keep admitting."""
    cfg = _fleet_cfg(replicas=2, serve__shed_watermark=2)
    router, gate = _stub_fleet(predictor, cfg)
    try:
        gate.close()
        handles = []
        shed_at = None
        for seed in range(12):  # 2 replicas x (1 lane watermark 2 + batch)
            freq = router.submit(_img(True, seed), timeout_ms=0)
            handles.append(freq)
            if freq.state == SHED:
                shed_at = seed
                break
        assert shed_at is not None, "fleet never shed at tiny watermark"
        # the shed decision was made with BOTH replicas' landscape lanes
        # at the watermark
        for r in router.manager.replicas:
            assert r.engine.bucket_depth((128, 160)) >= 2
        gate.open()
        _drain(router)
        snap = router.metrics.snapshot()
        assert snap["counters"]["submitted"] == snap["terminated"]
    finally:
        gate.open()
        router.close()


def test_reroute_does_not_extend_deadline(predictor):
    """A replica death mid-request must not grant the rider more time:
    the reroute path re-checks expiry first and terminates EXPIRED (the
    dispatcher would have cancelled the queued request at take had the
    replica lived — deadline authority outranks the death)."""
    cfg = _fleet_cfg(replicas=2, fleet__reroute_retries=1)
    router, gate = _stub_fleet(predictor, cfg)
    try:
        gate.close()
        # occupy both replicas' landscape dispatchers so the victim
        # request stays QUEUED (kill only strands queued work; a batch
        # already mid-model completes, like a real preemption)
        for r in router.manager.replicas:
            for s in range(2):
                r.engine.submit(_img(True, s), timeout_ms=0)
        time.sleep(0.15)  # dispatchers take their batches and block
        freq = router.submit(_img(True, 9), timeout_ms=150.0)
        target = router.manager.replicas[freq.replica_id]
        time.sleep(0.25)  # deadline passes while queued
        target.engine.kill()  # queued → FAILED → reroute → expiry check
        deadline = time.monotonic() + 5.0
        while freq.state == PENDING and time.monotonic() < deadline:
            time.sleep(0.01)
        assert freq.state == EXPIRED
    finally:
        gate.open()
        router.close()


# ---------------------------------------------------------------------------
# lifecycle: crash → eject → reroute → relaunch → rejoin
# ---------------------------------------------------------------------------

def test_crash_eject_reroute_relaunch_rejoin(predictor):
    cfg = _fleet_cfg(replicas=2)
    router, gate = _stub_fleet(predictor, cfg)
    try:
        gate.close()
        victim = router.manager.replicas[0]
        survivor = router.manager.replicas[1]
        # strand work on the victim: jam its landscape lane
        riders = []
        while victim.engine.bucket_depth((128, 160)) < 3:
            freq = router.submit(_img(True, len(riders)),
                                 timeout_ms=30_000)
            riders.append(freq)
        victim.engine.kill()
        assert not victim.engine.alive()
        router.manager.tick(now=time.monotonic())
        assert victim.state in (R_RELAUNCHING, R_READY)
        assert router.manager.ejects == 1
        gate.open()
        _drain(router)
        # every stranded rider reached exactly one terminal state, and
        # the reroutes landed somewhere that served them
        assert all(f.state == SERVED for f in riders)
        assert router.rerouted() > 0
        # drive the health loop until the relaunch rejoins
        deadline = time.monotonic() + 15.0
        while victim.generation < 2 and time.monotonic() < deadline:
            router.manager.tick(now=time.monotonic() + 10.0)
            time.sleep(0.02)
        assert victim.generation == 2 and victim.ready()
        # the rejoined replica serves again
        freq = router.submit(_img(True, 123), timeout_ms=30_000)
        freq.wait(timeout=10.0)
        assert freq.state == SERVED
    finally:
        gate.open()
        router.close()


def test_manager_counters_are_thread_safe():
    """Regression for the ISSUE-10 threadlint TL201 fix: ejects (health
    monitor thread) and relaunches (per-replica rebuild threads) are
    bumped concurrently; unguarded += on a plain int loses updates under
    interleaving.  48 concurrent ejects must count exactly 48."""
    cfg = _fleet_cfg(replicas=48, fleet__relaunch=False)
    manager = ReplicaManager(lambda rid: (None, {}), cfg)
    for r in manager.replicas:
        r.state = R_READY          # stub: never launched, engine None
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)    # force frequent interleaving
    try:
        threads = [threading.Thread(target=manager.eject, args=(r, "test"))
                   for r in manager.replicas]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        sys.setswitchinterval(old)
    assert manager.ejects == len(manager.replicas)
    assert all(r.state == R_DEAD for r in manager.replicas)


def test_crash_loop_becomes_verdict_not_infinite_relaunch(predictor):
    """A replica whose build ALWAYS fails must end R_DEAD via the
    RestartPolicy give-up, not relaunch forever."""
    cfg = _fleet_cfg(replicas=1)

    def bad_build(rid):
        raise RuntimeError("no devices for you")

    manager = ReplicaManager(bad_build, cfg)
    for r in manager.replicas:
        r.policy.give_up_after = 3
    # boot failure + identical relaunch failures until the verdict
    if not manager.replicas[0].launch():
        manager._schedule_relaunch(manager.replicas[0], ("boot-failed",),
                                   made_progress=False)
    r = manager.replicas[0]
    for _ in range(10):
        # wait for the (a)sync failure handling to settle: either the
        # verdict landed (R_DEAD) or the next relaunch is scheduled
        deadline = time.monotonic() + 5.0
        while r.state != R_DEAD and not (
                r.state == R_RELAUNCHING and r.relaunch_at is not None) \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        if r.state == R_DEAD:
            break
        manager.tick(now=time.monotonic() + 3600.0)
    assert r.state == R_DEAD
    manager.close()


def test_relaunch_disabled_goes_dead(predictor):
    cfg = _fleet_cfg(replicas=2, fleet__relaunch=False)
    router, gate = _stub_fleet(predictor, cfg)
    try:
        victim = router.manager.replicas[0]
        victim.engine.kill()
        router.manager.tick()
        assert victim.state == R_DEAD
        # the fleet keeps serving on the survivor
        freq = router.submit(_img(True, 5), timeout_ms=30_000)
        freq.wait(timeout=10.0)
        assert freq.state == SERVED
        assert freq.replica_id == router.manager.replicas[1].id
    finally:
        router.close()


# ---------------------------------------------------------------------------
# fleet-wide terminate-exactly-once
# ---------------------------------------------------------------------------

def test_fleet_terminate_exactly_once_under_kill(predictor):
    """The accounting invariant under the worst case: a replica dies
    mid-burst, work reroutes, and still every fleet request reaches
    EXACTLY one terminal state — counted both per-handle (double
    transitions raise in _finish's guard) and in the roll-up."""
    cfg = _fleet_cfg(replicas=2, fleet__health_interval_s=0.1)
    router, gate = _stub_fleet(predictor, cfg)
    terminal_counts = {}
    lock = threading.Lock()

    def on_done(req):
        with lock:
            terminal_counts[id(req)] = terminal_counts.get(id(req), 0) + 1

    try:
        handles = []
        stop = time.monotonic() + 2.0
        killed = False
        seed = 0
        while time.monotonic() < stop:
            freq = router.submit(_img(seed % 2 == 0, seed),
                                 timeout_ms=10_000)
            freq.add_done_callback(on_done)
            handles.append(freq)
            seed += 1
            if not killed and time.monotonic() > stop - 1.5:
                router.manager.replicas[0].engine.kill()
                killed = True
            time.sleep(0.005)
        _drain(router)
        snap = router.metrics.snapshot()
        c = snap["counters"]
        assert c["submitted"] == len(handles)
        assert snap["terminated"] == c["submitted"], "lost requests"
        assert all(n == 1 for n in terminal_counts.values())
        assert len(terminal_counts) == len(handles)
        assert all(f.state in (SERVED, SHED, EXPIRED, FAILED)
                   for f in handles)
        assert c["served"] > 0
    finally:
        router.close()


def test_done_callback_fires_for_already_terminal_request():
    """The router attaches its callback AFTER submit returns; a request
    shed inside submit must still fire the hook exactly once."""
    req = ServeRequest(None, None, (128, 160), None, time.monotonic())
    req._finish(SHED)
    fired = []
    req.add_done_callback(lambda r: fired.append(r.state))
    assert fired == [SHED]


def test_fleet_healthz_surface(predictor):
    cfg = _fleet_cfg(replicas=2)
    router, gate = _stub_fleet(predictor, cfg)
    try:
        h = router.healthz()
        assert h["ok"] and h["fleet"] and h["ready"] == 2
        states = [r["state"] for r in h["replicas"]]
        assert states == [R_READY, R_READY]
        assert h["batch_size"] == cfg.serve.batch_size
    finally:
        router.close()
