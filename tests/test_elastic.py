"""Elastic-training unit tests (mx_rcnn_tpu/ft/elastic.py + the
grad-accumulation step; docs/FT.md "Elasticity").

Everything here runs in-process on the CPU tier-1 rig: topology
directive plumbing, the controller's poll/pending state machine, the
accumulating train step's EXACT semantics (average of per-microbatch
gradients, one optimizer update), schedule invariance of the fit loop
under accumulation, and interrupt-resume bit-exactness with
``grad_accum > 1``.  The multi-process storm (real SIGKILLs, world
relaunches, live SIGUSR1 resizes) is ``make elastic-smoke`` /
``tools/crashloop.py --elastic``.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tests.test_train_step import KEY, make_batch, tiny_setup

from mx_rcnn_tpu.core.fit import fit
from mx_rcnn_tpu.core.train import make_train_step
from mx_rcnn_tpu.ft.elastic import (ElasticController, Topology,
                                    parse_events, read_topology, respec,
                                    topology_path, write_topology)
from mx_rcnn_tpu.parallel.dp import stack_microbatches


class FakeLoader:
    shuffle = False

    def __init__(self, batches):
        self.batches = list(batches)

    def __len__(self):
        return len(self.batches)

    def __iter__(self):
        return iter(self.batches)


# ---- topology directives ---------------------------------------------------


def test_topology_directive_roundtrip(tmp_path):
    p = str(tmp_path / "m.topology.json")
    write_topology(p, 3, 4, num_processes=2, ts=123.5)
    topo = read_topology(p)
    assert topo == Topology(3, 4, 2, 123.5)


def test_topology_torn_file_reads_none(tmp_path):
    p = str(tmp_path / "m.topology.json")
    assert read_topology(p) is None  # absent
    with open(p, "w") as f:
        f.write('{"generation": 3, "num_dev')  # torn mid-write
    assert read_topology(p) is None
    with open(p, "w") as f:
        f.write('{"num_devices": 4}')  # missing required key
    assert read_topology(p) is None


def test_topology_path_override(tmp_path):
    from mx_rcnn_tpu.config import generate_config

    cfg = generate_config("tiny", "PascalVOC")
    assert topology_path("/runs/m/e2e", cfg) == "/runs/m/e2e.topology.json"
    cfg = cfg.replace_in("elastic", topology_path="/etc/topo.json")
    assert topology_path("/runs/m/e2e", cfg) == "/etc/topo.json"


def test_parse_events_skips_torn_lines():
    text = ("noise\n"
            'ELASTIC_EVENT {"ts": 1.0, "event": "mesh", "num_devices": 4}\n'
            'ELASTIC_EVENT {"ts": 2.0, "event": "first_st\n'   # killed
            'ELASTIC_EVENT {"ts": 3.0, "event": "restore"}\n')
    events = parse_events(text)
    assert [e["event"] for e in events] == ["mesh", "restore"]


# ---- controller state machine ----------------------------------------------


def _controller(tmp_path):
    from mx_rcnn_tpu.config import generate_config

    cfg = generate_config("tiny", "PascalVOC")
    prefix = str(tmp_path / "m" / "e2e")
    os.makedirs(os.path.dirname(prefix), exist_ok=True)
    return ElasticController(cfg, prefix, install_signal=False), prefix


def test_controller_polls_and_caches_pending(tmp_path, capsys):
    ctrl, prefix = _controller(tmp_path)
    ctrl.mark_applied(Topology(0, 8, 1))
    assert not ctrl.resize_requested()          # no directive file yet
    write_topology(ctrl.path, 1, 4, 1)
    assert ctrl.resize_requested()              # poll_steps=1: seen now
    assert ctrl.pending() == read_topology(ctrl.path)
    # the emitted transition is machine-readable on stdout
    events = parse_events(capsys.readouterr().out)
    assert events and events[-1]["event"] == "resize_requested"
    assert events[-1]["num_devices"] == 4
    # applying the directive clears pending
    ctrl.mark_applied(ctrl.pending())
    assert not ctrl.resize_requested()


def test_controller_ignores_stale_generations(tmp_path):
    ctrl, _ = _controller(tmp_path)
    write_topology(ctrl.path, 2, 4, 1)
    ctrl.mark_applied(Topology(2, 4, 1))
    write_topology(ctrl.path, 1, 8, 1)          # older generation
    assert not ctrl.resize_requested()
    write_topology(ctrl.path, 3, 8, 1)          # newer: fires
    assert ctrl.resize_requested()


def test_controller_stop_flag_composes_user_stop(tmp_path):
    ctrl, _ = _controller(tmp_path)
    ctrl.mark_applied(Topology(0, 8, 1))
    user = {"stop": False}
    flag = ctrl.make_stop_flag(lambda: user["stop"])
    assert not flag()
    user["stop"] = True
    assert flag()                                # SIGTERM path
    user["stop"] = False
    write_topology(ctrl.path, 1, 4, 1)
    assert flag()                                # resize path


def test_infer_base_devices_prefers_checkpoint_topology(tmp_path):
    """A relaunched world must recover the RECIPE base from the
    checkpoint's recorded topology, not from the (possibly shrunken)
    current directive — otherwise a shrink would silently become the new
    recipe and halve the effective global batch (code-review finding)."""
    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.ft.elastic import infer_base_devices
    from mx_rcnn_tpu.utils.checkpoint import make_topology, save_checkpoint

    cfg = generate_config("tiny", "PascalVOC")
    prefix = str(tmp_path / "m")
    shrunk = Topology(3, 4, 1)  # directive AFTER a shrink from 8

    # explicit config wins
    cfg8 = cfg.replace_in("elastic", base_devices=8)
    assert infer_base_devices(cfg8, prefix, shrunk) == 8
    # no checkpoint yet (fresh run): the directive is all there is
    assert infer_base_devices(cfg, prefix, shrunk) == 4
    # checkpoint written by the ORIGINAL 8-device recipe: authoritative
    _, _, _, state = tiny_setup()
    save_checkpoint(prefix, 1, state, steps_per_epoch=10,
                    topology=make_topology(8, grad_accum=1,
                                           batch_images=1))
    assert infer_base_devices(cfg, prefix, shrunk) == 8


# ---- the accumulating train step -------------------------------------------


def test_grad_accum_step_is_exact_average_of_microbatch_grads():
    """``grad_accum=2`` must equal: per-microbatch gradients with the
    documented key derivation (fold step, fold microbatch index),
    averaged, then ONE tx.update — replicated here leaf by leaf."""
    from mx_rcnn_tpu.core.train import loss_and_metrics

    cfg, model, tx, state = tiny_setup()
    b0, b1 = make_batch(seed=0), make_batch(seed=1)
    acc = jax.tree.map(jnp.asarray, stack_microbatches([b0, b1]))

    step = jax.jit(make_train_step(model, cfg, tx, grad_accum=2))
    got, _ = step(state, acc, KEY)

    base_key = jax.random.fold_in(KEY, state.step)
    grads = []
    for i, mb in enumerate((b0, b1)):
        k = jax.random.fold_in(base_key, jnp.int32(i))
        g = jax.grad(
            lambda p: loss_and_metrics(model, p, state.batch_stats, mb,
                                       k, cfg)[0])(state.params)
        grads.append(g)
    mean_g = jax.tree.map(lambda a, b: (jnp.stack([a, b]).mean(0)),
                          *grads)
    updates, _ = tx.update(mean_g, state.opt_state, state.params)
    want_params = optax.apply_updates(state.params, updates)

    assert int(got.step) == 1
    for a, b in zip(jax.tree.leaves(got.params),
                    jax.tree.leaves(want_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=1e-6)


def test_grad_accum_fit_preserves_schedule_and_resume(tmp_path):
    """Accumulation-invariance of the bookkeeping: 4 loader batches with
    ``grad_accum=2`` is 2 OPTIMIZER steps/epoch (manifest agrees), and a
    mid-epoch interrupt + resume reproduces the uninterrupted run
    bit-exactly (the skip math consumes skip*accum loader batches)."""
    from mx_rcnn_tpu.utils.checkpoint import (checkpoint_path,
                                              read_manifest,
                                              restore_interrupt)

    batches = [make_batch(seed=s) for s in range(4)]

    cfg, model, tx, s0 = tiny_setup()
    ref = fit(model, cfg, s0, tx, FakeLoader(batches), 2, KEY,
              frequent=1000, grad_accum=2)
    assert int(ref.step) == 4                    # 2 epochs x 2 opt steps

    prefix = str(tmp_path / "m" / "e2e")
    _, _, _, s1 = tiny_setup()
    fit(model, cfg, s1, tx, FakeLoader(batches), 2, KEY, prefix=prefix,
        frequent=1000, grad_accum=2,
        stop_flag=lambda: True)   # fires after step 1 of 2 — mid-epoch,
    # so the drain writes an interrupt checkpoint, not an epoch one
    _, _, _, template = tiny_setup()
    resumed, spe = restore_interrupt(template, prefix)
    assert spe == 2 and int(resumed.step) == 1
    final = fit(model, cfg, resumed, tx, FakeLoader(batches), 2, KEY,
                prefix=prefix, frequent=1000, grad_accum=2)
    assert int(final.step) == 4
    for a, b in zip(jax.tree.leaves(ref.params),
                    jax.tree.leaves(final.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    m = read_manifest(checkpoint_path(prefix, 2))
    assert m["steps_per_epoch"] == 2
    assert m["topology"]["grad_accum"] == 2
    assert m["topology"]["global_batch"] == 2


def test_device_cache_refuses_grad_accum():
    cfg, model, tx, state = tiny_setup()
    with pytest.raises(ValueError, match="device_cache"):
        fit(model, cfg, state, tx, FakeLoader([make_batch()]), 1, KEY,
            grad_accum=2, device_cache=True)


# ---- respec (the state-surgery primitive) ----------------------------------


def test_respec_replicates_onto_target_mesh():
    from mx_rcnn_tpu.parallel.dp import device_mesh

    _, _, _, state = tiny_setup()
    host = jax.device_get(state)
    mesh4 = device_mesh(4)
    moved = respec(host, mesh4)
    leaf = jax.tree.leaves(moved.params)[0]
    assert len(leaf.sharding.device_set) == 4
    for a, b in zip(jax.tree.leaves(host), jax.tree.leaves(moved)):
        np.testing.assert_array_equal(np.asarray(a),
                                      np.asarray(jax.device_get(b)))
