"""threadlint + lock-sanitizer + configlint contract tests (ISSUE 10
tentpole), mirroring ``tests/test_graphlint.py``:

* the SHIPPED tree is clean — zero unwaived findings over
  ``mx_rcnn_tpu`` for both new linters, every waiver reasoned;
* the fixture (``tests/fixtures/serve/threadlint_bad.py``) trips EVERY
  TL rule — the linter cannot silently lose a rule;
* behavioral tests per rule family (lock-cycle detection incl. the
  cross-function call closure, blocking-under-lock, thread-shared
  writes, signal handlers, Condition predicates, waivers);
* the lock-order graph dump carries the tree's real, cycle-free edges;
* the runtime sanitizer catches a REAL two-thread order inversion,
  wraps package-allocated locks transparently (BoundedQueue keeps
  working sanitized), raises in strict mode, and records hold-budget
  violations and watchdog trips;
* configlint: typo'd reads flagged, alias/getattr idioms followed,
  dead keys reported at their config.py definition line.
"""

import os
import sys
import textwrap
import threading
import time

import pytest

from mx_rcnn_tpu.analysis import sanitizer as san
from mx_rcnn_tpu.analysis import configlint, threadlint
from mx_rcnn_tpu.analysis.threadlint import RULES, lint_paths, lock_graph

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "mx_rcnn_tpu")
FIXTURE = os.path.join(REPO, "tests", "fixtures", "serve",
                       "threadlint_bad.py")


# ---------------------------------------------------------------------------
# static pass: the shipped tree + the fixture
# ---------------------------------------------------------------------------

def test_shipped_tree_has_zero_unwaived_findings():
    findings = lint_paths([PKG])
    active = [f for f in findings if f.waived is None]
    assert active == [], "\n".join(f.render() for f in active)
    for f in findings:
        if f.waived is not None:
            assert f.waived.strip(), f.render()


def test_cli_exit_codes(capsys):
    assert threadlint.main([PKG]) == 0
    assert threadlint.main([FIXTURE]) == 1
    capsys.readouterr()


def test_fixture_trips_every_rule():
    findings = lint_paths([FIXTURE])
    codes = {f.code for f in findings}
    assert codes == set(RULES), (
        f"missing: {set(RULES) - codes}, unexpected: {codes - set(RULES)}")
    # the reasonless TL301 waiver silences its finding but raises TL001
    assert any(f.code == "TL301" and f.waived is not None for f in findings)
    assert any(f.code == "TL001" for f in findings)


def _lint_snippet(tmp_path, source, name="snippet.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return lint_paths([str(p)])


def test_lock_cycle_detected_and_consistent_order_clean(tmp_path):
    bad = _lint_snippet(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._x = threading.Lock()
                self._y = threading.Lock()

            def xy(self):
                with self._x:
                    with self._y:
                        pass

            def yx(self):
                with self._y:
                    with self._x:
                        pass
        """)
    assert {f.code for f in bad} == {"TL101"}
    assert len([f for f in bad if f.code == "TL101"]) == 2  # both edges
    good = _lint_snippet(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._x = threading.Lock()
                self._y = threading.Lock()

            def a(self):
                with self._x:
                    with self._y:
                        pass

            def b(self):
                with self._x:
                    with self._y:
                        pass
        """, name="good.py")
    assert [f for f in good if f.code == "TL101"] == []


def test_lock_cycle_through_call_closure(tmp_path):
    """The order graph follows calls: A holds lock1 and CALLS a helper
    that takes lock2 while B nests them the other way lexically."""
    findings = _lint_snippet(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._one = threading.Lock()
                self._two = threading.Lock()

            def grab_two(self):
                with self._two:
                    return 1

            def a(self):
                with self._one:
                    self.grab_two()

            def b(self):
                with self._two:
                    with self._one:
                        pass
        """)
    assert any(f.code == "TL101" for f in findings), \
        "\n".join(f.render() for f in findings)


def test_same_basename_modules_do_not_shadow_closure(tmp_path):
    """Regression (code-review r10): the corpus is keyed by a UNIQUE
    module id, so a sibling directory's same-named module defining a
    same-named lockless helper must not shadow the one that closes a
    deadlock cycle (the tree has serve/fleet.py vs tools/fleet.py)."""
    cyclic = """\
        import threading

        ONE = threading.Lock()
        TWO = threading.Lock()

        def grab_two():
            with TWO:
                return 1

        def a():
            with ONE:
                grab_two()

        def b():
            with TWO:
                with ONE:
                    pass
        """
    (tmp_path / "x").mkdir()
    (tmp_path / "y").mkdir()
    (tmp_path / "x" / "mod.py").write_text(textwrap.dedent(cyclic))
    (tmp_path / "y" / "mod.py").write_text(
        "def grab_two():\n    return 2\n")
    findings = lint_paths([str(tmp_path)])
    assert any(f.code == "TL101" for f in findings), \
        "\n".join(f.render() for f in findings)


def test_blocking_under_lock_flagged_outside_clean(tmp_path):
    findings = _lint_snippet(tmp_path, """\
        import threading
        import time

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    time.sleep(0.5)

            def good(self):
                with self._lock:
                    x = 1
                time.sleep(0.5)
                return x
        """)
    assert [f.code for f in findings] == ["TL301"]
    assert "bad" in findings[0].func


def test_thread_shared_write_flagged_guarded_clean(tmp_path):
    findings = _lint_snippet(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                self.m = 0
                self._t = threading.Thread(target=self._work)

            def _work(self):
                self.n += 1          # unguarded -> TL201
                with self._lock:
                    self.m += 1      # guarded -> clean

            def read(self):
                return self.n + self.m
        """)
    assert [f.code for f in findings] == ["TL201"]
    assert "self.n" in findings[0].message


def test_signal_handler_rules(tmp_path):
    findings = _lint_snippet(tmp_path, """\
        import signal
        import threading

        _lock = threading.Lock()

        def bad_handler(signum, frame):
            with _lock:              # TL401: lock in a handler
                pass

        def good_handler(signum, frame):
            state["flag"] = True     # flag flip only: clean

        state = {"flag": False}
        signal.signal(signal.SIGTERM, bad_handler)
        signal.signal(signal.SIGUSR1, good_handler)
        """)
    assert [f.code for f in findings] == ["TL401"]
    assert "bad_handler" in findings[0].func


def test_signal_handler_worker_thread_pattern_is_clean(tmp_path):
    """Regression (code-review r10): the documented FIX pattern — the
    handler only spawns a worker thread that does the jax work — must
    NOT be flagged (obs/profiler.py install_sigusr2 is this shape)."""
    findings = _lint_snippet(tmp_path, """\
        import signal
        import threading

        def handler(signum, frame):
            def work():
                import jax
                jax.block_until_ready(None)
            threading.Thread(target=work, daemon=True).start()

        signal.signal(signal.SIGUSR2, handler)
        """)
    assert [f for f in findings if f.code == "TL401"] == [], \
        "\n".join(f.render() for f in findings)


def test_annotated_write_does_not_dodge_tl201(tmp_path):
    """Regression (code-review r10): `self.n: int = 1` is a write like
    any other — AnnAssign must reach the shared-state check."""
    findings = _lint_snippet(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                self._t = threading.Thread(target=self._work)

            def _work(self):
                self.n: int = 1      # annotated, still unguarded

            def read(self):
                return self.n
        """)
    assert [f.code for f in findings] == ["TL201"]


def test_condition_wait_predicate_loop(tmp_path):
    findings = _lint_snippet(tmp_path, """\
        import threading

        class C:
            def __init__(self):
                self._cond = threading.Condition()
                self.ready = False

            def good(self):
                with self._cond:
                    while not self.ready:
                        self._cond.wait()

            def bad(self):
                with self._cond:
                    if not self.ready:
                        self._cond.wait()
        """)
    assert [f.code for f in findings] == ["TL501"]
    assert "bad" in findings[0].func


def test_waiver_requires_reason(tmp_path):
    reasoned = _lint_snippet(tmp_path, """\
        import threading
        import time

        L = threading.Lock()

        def f():
            with L:
                time.sleep(1)  # threadlint: disable=TL301 bench scaffold
        """)
    assert [f.code for f in reasoned] == ["TL301"]
    assert reasoned[0].waived == "bench scaffold"
    bare = _lint_snippet(tmp_path, """\
        import threading
        import time

        L = threading.Lock()

        def f():
            with L:
                time.sleep(1)  # threadlint: disable=TL301
        """, name="bare.py")
    assert "TL001" in {f.code for f in bare}


def test_lock_graph_dump_has_tree_edges_and_no_cycles():
    g = lock_graph([PKG])
    assert g["cycles"] == [], g["cycles"]
    edges = {(e["held"], e["acquired"]) for e in g["edges"]}
    # the serving queue's documented ordering: requests terminate while
    # the dispatcher holds the bucket condition (take_batch expiry)
    assert ("BoundedQueue._cond", "ServeRequest._lock") in edges, edges
    kinds = {n["id"]: n["kind"] for n in g["nodes"]}
    assert kinds.get("BoundedQueue._cond") == "Condition"


def test_list_rules_names_every_code(capsys):
    assert threadlint.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for code in RULES:
        assert code in out


def test_cli_fails_on_missing_or_empty_paths(tmp_path, capsys):
    assert threadlint.main([str(tmp_path / "nope")]) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert threadlint.main([str(empty)]) == 2
    capsys.readouterr()


# ---------------------------------------------------------------------------
# runtime sanitizer
# ---------------------------------------------------------------------------

@pytest.fixture
def armed_sanitizer():
    san.install(strict=False)
    san.reset()
    yield san
    san.reset()
    san.uninstall()


def test_sanitizer_catches_two_thread_order_inversion(armed_sanitizer):
    """A REAL inversion: thread 1 takes a->b, thread 2 takes b->a
    (sequenced so the test itself cannot deadlock)."""
    a = san.SanLock(threading.Lock(), "LockA")
    b = san.SanLock(threading.Lock(), "LockB")
    first_done = threading.Event()

    def t1():
        with a:
            with b:
                pass
        first_done.set()

    def t2():
        first_done.wait(5)
        with b:
            with a:
                pass

    th1, th2 = threading.Thread(target=t1), threading.Thread(target=t2)
    th1.start(), th2.start()
    th1.join(5), th2.join(5)
    rep = san.report()
    assert len(rep["inversions"]) == 1, rep
    inv = rep["inversions"][0]
    assert inv["held"] == "LockB" and inv["acquired"] == "LockA"
    assert not san.check_clean()
    assert san.check_problems()  # --check integration


def test_sanitizer_strict_mode_raises(armed_sanitizer):
    san._S.strict = True
    a = san.SanLock(threading.Lock(), "SA")
    b = san.SanLock(threading.Lock(), "SB")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(san.SanitizerError):
            a.acquire()
        # the rejected acquire must UNWIND: the inner lock released and
        # the held-list clean, so other threads can't hang behind a
        # lock nobody will ever release (code-review r10 fix)
        assert not a.locked()
    assert [name for _, name in san._S.held()] == []


def test_sanitizer_wraps_package_locks_transparently(armed_sanitizer):
    """install() monkey-patches the factories: a BoundedQueue built
    AFTER arming carries a sanitized condition lock and keeps its full
    semantics (offer/take_batch roundtrip)."""
    import numpy as np

    from mx_rcnn_tpu.serve.queue import BoundedQueue, ServeRequest

    q = BoundedQueue(depth=4)
    assert type(q._cond._lock).__name__ == "SanRLock"
    req = ServeRequest(np.zeros((4, 4, 3), np.float32),
                       np.zeros(3, np.float32), (4, 4), None, 0.0)
    assert type(req._lock).__name__ == "SanLock"
    assert q.offer(req)
    batch = q.take_batch(max_n=1, max_delay_s=0.01)
    assert batch == [req]
    assert san.check_clean(), san.report()


def test_sanitizer_budget_and_watchdog(armed_sanitizer):
    san._S.budget_ms = 30.0
    lk = san.SanLock(threading.Lock(), "BudgetLock")
    with lk:
        time.sleep(0.06)
    rep = san.report()
    assert rep["budget_violations"], rep
    assert rep["budget_violations"][0]["lock"] == "BudgetLock"
    # watchdog: a blocked acquire past the stall threshold trips
    san._S.stall_s = 0.2
    holder_has_it = threading.Event()
    release = threading.Event()

    def holder():
        with lk:
            holder_has_it.set()
            release.wait(5)

    def blocked():
        with lk:
            pass

    th = threading.Thread(target=holder)
    tb = threading.Thread(target=blocked)
    th.start()
    holder_has_it.wait(5)
    tb.start()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and \
            not san.report()["watchdog_trips"]:
        time.sleep(0.05)
    release.set()
    th.join(5), tb.join(5)
    trips = san.report()["watchdog_trips"]
    assert trips and trips[0]["lock"] == "BudgetLock", trips
    assert not san.check_clean()


def test_sanitizer_off_by_default_and_env_arming(monkeypatch):
    assert not san.armed()
    assert threading.Lock is san._RAW_LOCK
    monkeypatch.setenv("MXRCNN_THREAD_SANITIZER", "0")
    assert san.maybe_install_from_env() is False
    monkeypatch.setenv("MXRCNN_THREAD_SANITIZER", "1")
    try:
        assert san.maybe_install_from_env() is True
        assert san.armed()
    finally:
        san.reset()
        san.uninstall()
    assert threading.Lock is san._RAW_LOCK


# ---------------------------------------------------------------------------
# configlint
# ---------------------------------------------------------------------------

def test_configlint_tree_clean():
    findings = configlint.lint_paths([PKG])
    active = [f for f in findings if f.waived is None]
    assert active == [], "\n".join(f.render() for f in active)
    for f in findings:
        if f.waived is not None:
            assert f.waived.strip(), f.render()


def _configlint_snippet(tmp_path, source):
    p = tmp_path / "snippet.py"
    p.write_text(textwrap.dedent(source))
    return configlint.lint_paths([str(p)])


def test_configlint_catches_typo_read(tmp_path):
    findings = _configlint_snippet(tmp_path, """\
        def f(cfg):
            return cfg.serve.batch_sz  # typo: batch_size
        """)
    bad = [f for f in findings if f.code == "CL101"]
    assert len(bad) == 1 and "serve.batch_sz" in bad[0].message


def test_configlint_follows_alias_and_getattr(tmp_path):
    findings = _configlint_snippet(tmp_path, """\
        def f(cfg):
            s = cfg.serve
            ok = s.batch_size            # valid via alias
            bad = s.wattermark           # CL101 via alias
            o = getattr(cfg, "obs", None)
            ok2 = o.enabled              # valid via getattr alias
            return ok, bad, ok2
        """)
    bad = [f for f in findings if f.code == "CL101"]
    assert len(bad) == 1 and "serve.wattermark" in bad[0].message


def test_configlint_getattr_key_matching_a_section_name(tmp_path):
    """Regression (code-review r10): a typo'd 2-arg getattr whose key
    happens to equal a SECTION name ('data') must still be CL101."""
    findings = _configlint_snippet(tmp_path, """\
        def f(cfg):
            s = cfg.serve
            return getattr(s, "data")    # typo, raises at runtime
        """)
    bad = [f for f in findings if f.code == "CL101"]
    assert len(bad) == 1 and "serve.data" in bad[0].message


def test_configlint_reports_dead_keys_at_definition(tmp_path):
    """A tree reading only serve.batch_size leaves (among much else)
    serve.max_delay_ms dead — reported at its config.py line."""
    findings = _configlint_snippet(tmp_path, """\
        def f(cfg):
            return cfg.serve.batch_size
        """)
    dead = {f.message.split("'")[1] for f in findings
            if f.code == "CL201" and f.waived is None}
    assert "serve.max_delay_ms" in dead
    assert "serve.batch_size" not in dead
    cl201 = [f for f in findings
             if f.code == "CL201" and f.waived is None][0]
    assert cl201.path.endswith("config.py") and cl201.line > 0


def test_configlint_property_keys_are_valid(tmp_path):
    """Derived keys (properties like network.num_anchors) are legal
    reads, not typos."""
    findings = _configlint_snippet(tmp_path, """\
        def f(cfg):
            return cfg.network.num_anchors
        """)
    assert [f for f in findings if f.code == "CL101"] == []


def test_configlint_list_rules_and_missing_paths(tmp_path, capsys):
    assert configlint.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for code in configlint.RULES:
        assert code in out
    assert configlint.main([str(tmp_path / "nope")]) == 2
    capsys.readouterr()
