"""Fleet-wide time-series plane (ISSUE 14): store/sampler exactness,
cross-process collection across replica churn, health-rule hysteresis,
and the flight recorder's schema + trigger paths."""

import json
import os
import sys
import threading

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.obs import collect as obs_collect
from mx_rcnn_tpu.obs import flightrec
from mx_rcnn_tpu.obs import health as obs_health
from mx_rcnn_tpu.obs import timeseries as obs_ts
from mx_rcnn_tpu.obs.collect import (Collector, HttpSource,
                                     RegistrySource, sources_from_urls,
                                     view_to_snapshot)
from mx_rcnn_tpu.obs.flightrec import FlightRecorder
from mx_rcnn_tpu.obs.health import (CRITICAL, EXIT_BY_VERDICT, OK, WARN,
                                    HealthEngine, Rule, default_rules)
from mx_rcnn_tpu.obs.metrics import Registry, start_metrics_server
from mx_rcnn_tpu.obs.timeseries import Sampler, TimeSeriesStore


# ---------------------------------------------------------------------------
# TimeSeriesStore: windowed queries on synthetic samples
# ---------------------------------------------------------------------------


def _fill(store, reg, points):
    """points: list of (ts, mutate_fn) — mutate the registry, sample."""
    for ts, fn in points:
        fn(reg)
        store.sample(reg, ts=ts)


class TestStoreQueries:
    def test_delta_rate_gauge_over_window(self):
        reg, store = Registry(), TimeSeriesStore(capacity=16)
        _fill(store, reg, [
            (100.0, lambda r: (r.inc("c", 10), r.set_gauge("g", 5.0))),
            (110.0, lambda r: (r.inc("c", 20), r.set_gauge("g", 3.0))),
            (120.0, lambda r: (r.inc("c", 30), r.set_gauge("g", 9.0))),
        ])
        # full window: counter went 10 -> 60 over 20 s
        assert store.delta("c") == 50.0
        assert store.rate("c") == pytest.approx(2.5)
        # trailing 10 s window cuts the first sample
        assert store.delta("c", 10.0) == 30.0
        assert store.rate("c", 10.0) == pytest.approx(3.0)
        assert store.gauge("g") == 9.0
        assert store.gauge_min("g") == 3.0
        assert store.gauge_max("g", 10.0) == 9.0
        assert store.gauge_min("g", 10.0) == 3.0
        # absent names read None, not 0 (missing_ok rules depend on it)
        assert store.delta("nope") is None
        assert store.gauge("nope") is None
        assert store.rate("c", 0.0) is None  # single-sample window

    def test_series_and_ring_bound(self):
        reg, store = Registry(), TimeSeriesStore(capacity=4)
        for i in range(10):
            reg.set_gauge("g", float(i))
            store.sample(reg, ts=100.0 + i)
        assert len(store) == 4
        assert store.dropped == 6
        assert store.series("g") == [(106.0, 6.0), (107.0, 7.0),
                                     (108.0, 8.0), (109.0, 9.0)]

    def test_windowed_percentile_exact(self):
        """The windowed p99 must reflect ONLY the window's observations:
        an old latency spike outside the window cannot poison it."""
        reg, store = Registry(), TimeSeriesStore(capacity=16)
        store.sample(reg, ts=100.0)
        reg.observe("lat", 5000.0)  # old spike: lands between 100 and 125
        store.sample(reg, ts=125.0)  # window edge: spike is cumulative
        for v in (10.0, 12.0, 11.0, 13.0):
            reg.observe("lat", v)
        store.sample(reg, ts=130.0)
        # full history (edges 100/130) sees the spike; the trailing 10 s
        # window (edges 125/130 — the count DIFFERENCE) must not
        assert store.pctl("lat", 99) >= 5000.0 * 0.9
        p99_win = store.pctl("lat", 99, 10.0)
        assert p99_win is not None and p99_win < 100.0
        hw = store.hist_window("lat", 10.0)
        assert hw["count"] == 4 and hw["windowed"] is True

    def test_summary_only_fallback(self):
        """Remote scrapes carry summaries, not bucket counts — pctl
        degrades to the scraped value instead of failing."""
        store = TimeSeriesStore(capacity=8)
        snap = {"counters": {"c": 5}, "gauges": {},
                "hists": {"lat": {"count": 3, "p50": 10.0, "p99": 40.0,
                                  "max": 41.0}}}
        store.append_snapshot(snap, ts=100.0)
        store.append_snapshot(snap, ts=101.0)
        assert store.pctl("lat", 99, 60.0) == 40.0
        assert store.hist_window("lat", 60.0)["windowed"] is False

    def test_scrape_section_shape(self):
        reg, store = Registry(), TimeSeriesStore(capacity=8)
        _fill(store, reg, [
            (100.0, lambda r: (r.inc("c", 5), r.observe("lat", 10.0))),
            (110.0, lambda r: (r.inc("c", 15), r.observe("lat", 20.0))),
        ])
        sec = store.scrape_section(window_s=60.0)
        assert sec["samples"] == 2 and sec["dropped"] == 0
        assert sec["span_s"] == 10.0
        assert sec["rates_per_s"]["c"] == pytest.approx(1.5)
        assert "lat" in sec["p99"]


class TestSamplerExactness:
    def test_concurrent_sampling_is_consistent_and_exact(self):
        """Writers hammer the registry while the sampler rings it: every
        sample must be internally consistent (hist total == bucket sum)
        and the final sample must carry the EXACT totals."""
        reg = Registry()
        store = TimeSeriesStore(capacity=512)
        sampler = Sampler(store, interval_s=0.002, reg=reg)
        N, THREADS = 400, 4
        barrier = threading.Barrier(THREADS + 1)

        def writer(seed):
            barrier.wait()
            for i in range(N):
                reg.inc("w.count")
                reg.observe("w.lat", float((seed * N + i) % 97) + 1.0)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(THREADS)]
        for t in threads:
            t.start()
        sampler.start()
        barrier.wait()
        # the writers can outrun the daemon's first wakeup on a fast box:
        # drive extra ticks from this thread (same code path the daemon
        # runs) so samples land WHILE the registry is being hammered
        while any(t.is_alive() for t in threads):
            sampler.tick()
        for t in threads:
            t.join()
        sampler.stop(final_sample=True)

        samples = store.window(None)
        assert len(samples) >= 2
        for smp in samples:
            h = smp["hists"].get("w.lat")
            if h is not None:
                # the under-lock copy: a torn sample would break this
                assert int(h["counts"].sum()) == h["total"]
                assert h["total"] <= smp["counters"].get("w.count", 0) \
                    + THREADS * N  # sanity bound
        final = samples[-1]
        assert final["counters"]["w.count"] == THREADS * N
        assert final["hists"]["w.lat"]["total"] == THREADS * N
        assert store.delta("w.count") == float(
            THREADS * N - samples[0]["counters"].get("w.count", 0))

    def test_after_sample_hook_failure_disables_not_kills(self):
        reg = Registry()
        store = TimeSeriesStore(capacity=8)
        calls = []

        def bad_hook(smp):
            calls.append(1)
            raise RuntimeError("boom")

        sampler = Sampler(store, interval_s=1.0, reg=reg,
                          after_sample=bad_hook)
        sampler.tick()
        sampler.tick()  # hook disabled after the first failure
        assert len(calls) == 1
        assert len(store) == 2

    def test_active_store_registration(self):
        store = TimeSeriesStore()
        obs_ts.set_active(store)
        try:
            assert obs_ts.active() is store
        finally:
            obs_ts.set_active(None)
        assert obs_ts.active() is None


# ---------------------------------------------------------------------------
# Collector: churn-tolerant cross-process merge
# ---------------------------------------------------------------------------


class TestCollector:
    def test_merge_labels_and_agg(self):
        r1, r2 = Registry(), Registry()
        r1.inc("serve.served", 10)
        r1.set_gauge("depth", 3.0)
        r2.inc("serve.served", 7)
        r2.set_gauge("depth", 5.0)
        col = Collector([
            RegistrySource("replica-0", r1, labels={"zone": "a"}),
            RegistrySource("replica-1", r2),
        ])
        view = col.collect()
        assert view["up"] == 2
        assert view["sources"]["replica-0"]["labels"] == {
            "source": "replica-0", "zone": "a"}
        # counters SUM, gauges stay per-source
        assert view["agg"]["counters"]["serve.served"] == 17
        assert view["agg"]["gauges"]["depth"] == {
            "replica-0": 3.0, "replica-1": 5.0}

    def test_resolver_churn_eject_relaunch(self):
        """The eject→relaunch lifecycle: resolver returns None (down),
        then a NEW registry with a bumped generation — the collector
        follows without rebuilding, and counters never double-count."""
        state = {"reg": Registry(), "gen": 1}
        state["reg"].inc("serve.served", 5)

        def resolve():
            if state["reg"] is None:
                return None
            return state["reg"], {"generation": state["gen"]}

        col = Collector([RegistrySource("replica-0", resolve)])
        v1 = col.collect()
        assert v1["sources"]["replica-0"]["labels"]["generation"] == 1
        assert v1["agg"]["counters"]["serve.served"] == 5

        state["reg"] = None  # ejected: mid-relaunch
        v2 = col.collect()
        assert v2["sources"]["replica-0"] == {"up": False}
        assert v2["up"] == 0
        assert v2["agg"]["counters"] == {}  # down ≠ zero: absent

        fresh = Registry()  # relaunched: new engine, new registry
        fresh.inc("serve.served", 2)
        state.update(reg=fresh, gen=2)
        v3 = col.collect()
        assert v3["sources"]["replica-0"]["labels"]["generation"] == 2
        assert v3["agg"]["counters"]["serve.served"] == 2

    def test_http_source_real_server_and_down(self):
        reg = Registry()
        reg.inc("elastic.steps", 4)
        reg.set_gauge("elastic.generation", 2)
        srv = start_metrics_server(reg, port=0)
        try:
            url = "%s:%d" % srv.server_address[:2]
            col = Collector([HttpSource("elastic-0", url)])
            view = col.collect()
            src = view["sources"]["elastic-0"]
            assert src["up"] and src["counters"]["elastic.steps"] == 4
            assert src["labels"]["source"] == "elastic-0"
        finally:
            srv.shutdown()
            srv.server_close()
        # server gone: down, not an exception
        view = col.collect()
        assert view["sources"]["elastic-0"] == {"up": False}

    def test_sources_from_urls_parsing(self):
        out = sources_from_urls(
            "127.0.0.1:9090, worker=http://h:1/metrics, train=9137,")
        assert [s.name for s in out] == ["source-0", "worker", "train"]
        assert out[0].url == "http://127.0.0.1:9090/metrics"
        assert out[1].url == "http://h:1/metrics"
        # a bare port (the documented `--url 9101` form) is this host
        assert out[2].url == "http://127.0.0.1:9137/metrics"

    def test_view_to_snapshot_semantics(self):
        r1, r2 = Registry(), Registry()
        r1.inc("served", 10)
        r1.set_gauge("ready", 2.0)
        r1.observe("lat", 10.0)
        r2.inc("served", 5)
        r2.set_gauge("ready", 1.0)
        r2.observe("lat", 500.0)
        col = Collector([RegistrySource("a", r1),
                         RegistrySource("b", r2)])
        snap = view_to_snapshot(col.collect())
        assert snap["counters"]["served"] == 15          # fleet total
        assert snap["gauges"]["ready"] == 1.0            # worst source
        assert snap["gauges"]["ready@a"] == 2.0          # labeled copy
        assert snap["gauges"]["ready@b"] == 1.0
        lat = snap["hists"]["lat"]
        assert lat["count"] == 2
        assert lat["p99"] >= 500.0 * 0.9                 # worst tail

    def test_collector_for_fleet_shapes(self):
        """Duck-typed fleet: collector_for_fleet reads manager.replicas
        via each replica's lock/engine/generation/state fields."""

        class FakeMetrics:
            def __init__(self, reg):
                self.registry = reg

        class FakeEngine:
            def __init__(self, reg):
                self.metrics = FakeMetrics(reg)

        class FakeReplica:
            def __init__(self, rid, reg):
                self.id = rid
                self._lock = threading.Lock()
                self.engine = FakeEngine(reg)
                self.generation = 3
                self.state = "ready"

        class FakeManager:
            pass

        class FakeRouter:
            pass

        reg = Registry()
        reg.inc("serve.served", 9)
        router_reg = Registry()
        router_reg.set_gauge("fleet.replicas_ready", 1.0)
        router = FakeRouter()
        router.manager = FakeManager()
        router.manager.replicas = [FakeReplica(0, reg)]
        router.manager.registry = router_reg
        col = obs_collect.collector_for_fleet(router)
        view = col.collect()
        assert view["sources"]["replica-0"]["labels"] == {
            "source": "replica-0", "generation": 3, "state": "ready"}
        assert view["sources"]["router"]["gauges"][
            "fleet.replicas_ready"] == 1.0
        # replica dies: resolver reads engine=None as down
        router.manager.replicas[0].engine = None
        assert col.collect()["sources"]["replica-0"] == {"up": False}


# ---------------------------------------------------------------------------
# HealthEngine: hysteresis, verdicts, publication
# ---------------------------------------------------------------------------


def _gauge_store(values, name="g"):
    """A store whose gauge reads back each value in sequence per tick."""
    reg, store = Registry(), TimeSeriesStore(capacity=64)
    ts = 100.0
    for v in values:
        reg.set_gauge(name, v)
        store.sample(reg, ts=ts)
        ts += 1.0
    return store


class TestHealthEngine:
    def test_single_bad_sample_does_not_flap(self):
        """for_samples=2: one breaching evaluation must NOT change the
        verdict (the hysteresis acceptance assertion)."""
        reg, store = Registry(), TimeSeriesStore(capacity=64)
        rule = Rule("hot", "g", "gauge", ">", 10.0, severity=WARN,
                    for_samples=2, clear_samples=2)
        eng = HealthEngine([rule], store)
        ts = [100.0]

        def feed(v):
            reg.set_gauge("g", v)
            store.sample(reg, ts=ts[0])
            ts[0] += 1.0
            return eng.evaluate()

        assert feed(5.0)["verdict"] == OK
        assert feed(99.0)["verdict"] == OK      # 1st breach: held
        v = feed(99.0)                          # 2nd consecutive: fires
        assert v["verdict"] == WARN and v["changed"]
        assert feed(5.0)["verdict"] == WARN     # 1st clean: held
        v = feed(5.0)                           # 2nd clean: clears
        assert v["verdict"] == OK and v["changed"]

    def test_breach_counter_resets_on_clean(self):
        reg, store = Registry(), TimeSeriesStore(capacity=64)
        rule = Rule("hot", "g", "gauge", ">", 10.0, for_samples=2)
        eng = HealthEngine([rule], store)
        ts = [100.0]

        def feed(v):
            reg.set_gauge("g", v)
            store.sample(reg, ts=ts[0])
            ts[0] += 1.0
            return eng.evaluate()

        # breach, clean, breach, clean... never 2 consecutive → never
        # fires (a flapping metric stays OK)
        for v in (99.0, 5.0, 99.0, 5.0, 99.0):
            assert feed(v)["verdict"] == OK

    def test_missing_metric_holds_state(self):
        store = _gauge_store([])  # empty store: every query reads None
        rule = Rule("r", "absent", "gauge", ">", 1.0, for_samples=1)
        eng = HealthEngine([rule], store)
        v = eng.evaluate()
        assert v["verdict"] == OK
        assert v["rules"][0]["value"] is None
        assert v["rules"][0]["breaching"] is None

    def test_severity_and_exit_codes(self):
        store = _gauge_store([100.0, 100.0])
        rules = [Rule("warny", "g", "gauge", ">", 10.0, severity=WARN,
                      for_samples=1),
                 Rule("crit", "g", "gauge", ">", 50.0, severity=CRITICAL,
                      for_samples=1)]
        eng = HealthEngine(rules, store)
        v = eng.evaluate()
        assert v["verdict"] == CRITICAL and v["code"] == 2
        assert set(v["firing"]) == {"warny", "crit"}
        assert eng.exit_code() == 2
        assert EXIT_BY_VERDICT == {"OK": 0, "WARN": 1, "CRITICAL": 2}

    def test_ratio_kind_and_rate_kind(self):
        reg, store = Registry(), TimeSeriesStore(capacity=8)
        _fill(store, reg, [
            (100.0, lambda r: (r.inc("shed", 0), r.inc("sub", 100))),
            (110.0, lambda r: (r.inc("shed", 20), r.inc("sub", 100))),
        ])
        ratio = Rule("shed-frac", "shed/sub", "ratio", ">", 0.05,
                     for_samples=1)
        assert ratio.value(store) == pytest.approx(0.2)
        rate = Rule("rps", "sub", "rate", "<", 50.0, for_samples=1)
        assert rate.value(store) == pytest.approx(10.0)

    def test_publish_record_and_transition_callback(self):
        reg, store = Registry(), TimeSeriesStore(capacity=8)
        events, transitions = [], []

        class FakeRecord:
            def event(self, kind, **kw):
                events.append((kind, kw))

        eng = HealthEngine(
            [Rule("crit", "g", "gauge", ">", 10.0, severity=CRITICAL,
                  for_samples=1, clear_samples=1)],
            store, registry=reg, record=FakeRecord(),
            on_transition=lambda p, n, v: transitions.append((p, n)))
        reg.set_gauge("g", 99.0)
        store.sample(reg, ts=100.0)
        eng.evaluate()
        assert reg.gauge("health.verdict") == 2.0
        assert reg.gauge("health.rule.crit") == 1.0
        assert events == [("health_transition",
                           {"prev": "OK", "verdict": "CRITICAL",
                            "firing": ["crit"]})]
        assert transitions == [("OK", "CRITICAL")]
        # recovery publishes + notifies the other direction
        reg.set_gauge("g", 1.0)
        store.sample(reg, ts=101.0)
        eng.evaluate()
        assert reg.gauge("health.verdict") == 0.0
        assert transitions[-1] == ("CRITICAL", "OK")

    def test_default_rules_read_config(self):
        cfg = generate_config("tiny", "synthetic",
                              obs__health_window_s=45.0,
                              fleet__replicas=3)
        rules = {r.name: r for r in default_rules(cfg)}
        assert rules["serve-p99-budget"].window_s == 45.0
        assert rules["serve-p99-budget"].threshold == pytest.approx(
            0.9 * cfg.serve.default_timeout_ms)
        assert rules["fleet-degraded"].threshold == 3.0
        assert rules["fleet-degraded"].severity == CRITICAL
        # every rule is missing_ok: partial deployments stay judgeable
        assert all(r.missing_ok for r in rules.values())

    def test_fleet_degraded_fires_on_one_lost_replica(self):
        """The kill-mid-burst acceptance: ready < configured is
        CRITICAL immediately (the router masks, health must not)."""
        cfg = generate_config("tiny", "synthetic", fleet__replicas=2)
        reg, store = Registry(), TimeSeriesStore(capacity=8)
        eng = HealthEngine(default_rules(cfg), store)
        reg.set_gauge("fleet.replicas_ready", 2.0)
        store.sample(reg, ts=100.0)
        assert eng.evaluate()["verdict"] == OK
        reg.set_gauge("fleet.replicas_ready", 1.0)
        store.sample(reg, ts=101.0)
        assert eng.evaluate()["verdict"] == CRITICAL
        reg.set_gauge("fleet.replicas_ready", 2.0)
        store.sample(reg, ts=102.0)
        assert eng.evaluate()["verdict"] == OK

    def test_active_engine_verdict_surface(self):
        store = _gauge_store([99.0], name="g")
        eng = HealthEngine([Rule("r", "g", "gauge", ">", 1.0,
                                 for_samples=1)], store)
        eng.evaluate()
        obs_health.set_active_engine(eng)
        try:
            v = obs_health.active_verdict()
            assert v["verdict"] == WARN
        finally:
            obs_health.set_active_engine(None)
        assert obs_health.active_verdict() is None


# ---------------------------------------------------------------------------
# FlightRecorder: schema + triggers
# ---------------------------------------------------------------------------


@pytest.fixture
def flight_rig(tmp_path):
    reg, store = Registry(), TimeSeriesStore(capacity=32)
    reg.inc("serve.served", 5)
    reg.observe("serve.total_ms", 12.0)
    store.sample(reg, ts=100.0)
    store.sample(reg, ts=101.0)
    rec = FlightRecorder(store, str(tmp_path), window_s=60.0,
                         min_gap_s=0.0)
    return reg, store, rec, tmp_path


class TestFlightRecorder:
    def test_dump_schema_and_context(self, flight_rig):
        reg, store, rec, tmp = flight_rig
        rec.note_event({"event": "fleet_eject", "replica": 0})
        rec.add_context("fleet", lambda: {"replicas": [
            {"id": 0, "state": "ejected"}]})
        rec.add_context("broken", lambda: 1 / 0)
        path = rec.dump("manual", detail="test")
        assert path and os.path.exists(path)
        with open(path) as f:
            doc = json.load(f)
        assert doc["schema"] == "mx_rcnn_tpu.flight/2"
        assert doc["reason"] == "manual"
        assert doc["pid"] == os.getpid()
        assert len(doc["samples"]) == 2
        s = doc["samples"][-1]
        assert s["counters"]["serve.served"] == 5
        # ndarray bucket counts serialized as lists
        assert isinstance(
            s["hists"]["serve.total_ms"]["counts"], list)
        assert doc["events"] == [{"event": "fleet_eject", "replica": 0}]
        assert doc["context"]["fleet"]["replicas"][0]["id"] == 0
        assert "error" in doc["context"]["broken"]  # fail-soft provider
        assert doc["extra"]["detail"] == "test"
        assert rec.dumps == [path]

    def test_rate_limit_per_reason(self, tmp_path):
        store = TimeSeriesStore(capacity=4)
        rec = FlightRecorder(store, str(tmp_path), min_gap_s=3600.0)
        p1 = rec.dump("watchdog")
        assert p1 is not None
        assert rec.dump("watchdog") is None          # rate-limited
        assert rec.dump("crash") is not None         # distinct reason
        assert rec.dump("watchdog", force=True) is not None

    def test_excepthook_trigger_chains(self, flight_rig):
        reg, store, rec, tmp = flight_rig
        seen = []
        prev = sys.excepthook
        sys.excepthook = lambda *a: seen.append(a)
        try:
            rec.arm(signals=False, excepthook=True, watchdog=False)
            try:
                raise ValueError("boom")
            except ValueError:
                sys.excepthook(*sys.exc_info())
        finally:
            rec.disarm()
            sys.excepthook = prev
        assert len(seen) == 1                        # chained through
        assert any("crash" in p for p in rec.dumps)
        with open(rec.dumps[0]) as f:
            doc = json.load(f)
        assert "ValueError: boom" in doc["extra"]["error"]

    def test_watchdog_trip_listener(self, flight_rig):
        reg, store, rec, tmp = flight_rig
        from mx_rcnn_tpu.analysis import sanitizer
        rec.arm(signals=False, excepthook=False, watchdog=True)
        try:
            sanitizer._notify_trip({"kind": "stall", "held_ms": 31000})
        finally:
            rec.disarm()
        assert any("watchdog" in p for p in rec.dumps)
        with open(rec.dumps[0]) as f:
            doc = json.load(f)
        assert doc["events"][-1]["event"] == "watchdog_trip"
        # disarm really unhooks: another trip dumps nothing new
        n = len(rec.dumps)
        sanitizer._notify_trip({"kind": "stall"})
        assert len(rec.dumps) == n

    def test_health_transition_trigger(self, flight_rig):
        reg, store, rec, tmp = flight_rig
        rec.on_health_transition("OK", "WARN", {"firing": ["w"]})
        assert rec.dumps == []                       # WARN only rings
        rec.on_health_transition("WARN", "CRITICAL",
                                 {"firing": ["fleet-degraded"]})
        assert len(rec.dumps) == 1
        with open(rec.dumps[0]) as f:
            doc = json.load(f)
        assert doc["reason"] == "health-critical"
        assert doc["extra"]["firing"] == ["fleet-degraded"]
        events = [e["event"] for e in doc["events"]]
        assert events == ["health_transition", "health_transition"]

    def test_module_trigger_and_active(self, flight_rig):
        reg, store, rec, tmp = flight_rig
        assert flightrec.trigger("elastic-peer-failure") is None
        flightrec.set_active(rec)
        try:
            path = flightrec.trigger("elastic-peer-failure", rank=1)
            assert path is not None
            with open(path) as f:
                assert json.load(f)["extra"]["rank"] == 1
        finally:
            flightrec.set_active(None)

    def test_runrec_listener_feeds_flight(self, tmp_path):
        """RunRecord.add_listener → note_event: every runrec event lands
        in the black box with zero emit-site instrumentation."""
        from mx_rcnn_tpu.obs.runrec import RunRecord
        store = TimeSeriesStore(capacity=4)
        rr = RunRecord("t", base_dir=str(tmp_path))
        rec = FlightRecorder(store, rr.dir, min_gap_s=0.0)
        rr.add_listener(rec.note_event)
        try:
            rr.event("fleet_eject", replica=2, reason="engine-dead")
        finally:
            rr.close()
        path = rec.dump("manual")
        with open(path) as f:
            doc = json.load(f)
        ejects = [e for e in doc["events"]
                  if e.get("event") == "fleet_eject"]
        assert ejects and ejects[0]["replica"] == 2
        # listener removal stops the feed
        rec2 = FlightRecorder(store, rr.dir, min_gap_s=0.0)
        rr.add_listener(rec2.note_event)
        rr.remove_listener(rec2.note_event)


# ---------------------------------------------------------------------------
# CliObs wiring: config → live plane → teardown
# ---------------------------------------------------------------------------


class TestCliObsWiring:
    def test_full_plane_build_and_teardown(self, tmp_path):
        from mx_rcnn_tpu.obs.runrec import cli_obs
        cfg = generate_config(
            "tiny", "synthetic", obs__enabled=True,
            obs__run_dir=str(tmp_path), obs__timeseries=True,
            obs__sample_interval_s=0.05, obs__health=True,
            obs__flight=True)
        sess = cli_obs(cfg, "test")
        try:
            assert sess.store is not None and sess.sampler is not None
            assert sess.health is not None and sess.flight is not None
            assert obs_ts.active() is sess.store
            assert obs_health.active_engine() is sess.health
            assert flightrec.active() is sess.flight
            # the sampler thread is really ringing the registry
            import time as _time
            deadline = _time.monotonic() + 5.0
            while len(sess.store) < 2 and _time.monotonic() < deadline:
                _time.sleep(0.05)
            assert len(sess.store) >= 2
        finally:
            sess.close(metric="t", value=1, unit="x")
        assert obs_ts.active() is None
        assert obs_health.active_engine() is None
        assert flightrec.active() is None

    def test_off_by_default(self):
        from mx_rcnn_tpu.obs.runrec import cli_obs
        cfg = generate_config("tiny", "synthetic")
        assert cli_obs(cfg, "test") is None
        assert cfg.obs.timeseries is False
        assert cfg.obs.health is False
        assert cfg.obs.flight is False

    def test_metrics_exporter_attaches_timeseries_and_health(self):
        import urllib.request
        reg = Registry()
        reg.inc("c", 3)
        store = TimeSeriesStore(capacity=8)
        store.sample(reg, ts=100.0)
        store.sample(reg, ts=101.0)
        eng = HealthEngine([Rule("r", "g", "gauge", ">", 1.0,
                                 for_samples=1)], store)
        eng.evaluate()
        srv = start_metrics_server(reg, port=0)
        obs_ts.set_active(store)
        obs_health.set_active_engine(eng)
        try:
            url = "http://%s:%d" % srv.server_address[:2]
            with urllib.request.urlopen(url + "/metrics",
                                        timeout=5) as r:
                snap = json.loads(r.read())
            assert snap["timeseries"]["samples"] == 2
            with urllib.request.urlopen(url + "/healthz",
                                        timeout=5) as r:
                hz = json.loads(r.read())
            assert hz["health"]["verdict"] == OK
        finally:
            obs_ts.set_active(None)
            obs_health.set_active_engine(None)
            srv.shutdown()
            srv.server_close()

    def test_healthz_503_on_critical(self):
        import urllib.error
        import urllib.request
        reg = Registry()
        store = _gauge_store([99.0])
        eng = HealthEngine([Rule("r", "g", "gauge", ">", 1.0,
                                 severity=CRITICAL, for_samples=1)],
                           store)
        eng.evaluate()
        srv = start_metrics_server(reg, port=0)
        obs_health.set_active_engine(eng)
        try:
            url = "http://%s:%d/healthz" % srv.server_address[:2]
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url, timeout=5)
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert body["health"]["verdict"] == CRITICAL
        finally:
            obs_health.set_active_engine(None)
            srv.shutdown()
            srv.server_close()
