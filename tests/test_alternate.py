"""Alternate (4-stage) training tests — VERDICT r1 item 6.

Runs the miniature full schedule on the synthetic set and checks the
stage artifacts plus two sharp invariants: stage 3 (RPN retrain with
FIXED_PARAMS_SHARED) must leave the shared convs bit-identical to its
rcnn1 init, and stage 4 likewise vs rpn2 — that is the property that makes
the final combine valid (ref ``train_alternate.py`` stages 3/4 freeze
shared convs so RPN and RCNN agree on features).
"""



import os
import pickle

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.train import RCNNBatch
from mx_rcnn_tpu.data import load_gt_roidb
from mx_rcnn_tpu.data.loader import ROIIter
from mx_rcnn_tpu.tools.test import test_rcnn as eval_rcnn
from mx_rcnn_tpu.tools.train_alternate import alternate_train
from mx_rcnn_tpu.utils.checkpoint import load_param


def _cfg(tmp_path):
    cfg = generate_config(
        "tiny", "synthetic",
        dataset__root_path=str(tmp_path),
        dataset__dataset_path=str(tmp_path / "synthetic"),
        dataset__num_classes=4,
    )
    cfg = cfg.replace_in("train", rpn_pre_nms_top_n=512,
                         rpn_post_nms_top_n=128, batch_rois=64,
                         max_gt_boxes=8, flip=False)
    cfg = cfg.replace_in("test", rpn_pre_nms_top_n=512,
                         rpn_post_nms_top_n=64,
                         proposal_pre_nms_top_n=512,
                         proposal_post_nms_top_n=96)
    cfg = cfg.replace_in("bucket", scale=128, max_size=160,
                         shapes=((128, 160), (160, 128)))
    return cfg


KW = dict(num_images=24, image_size=(128, 160), max_objects=3)


def test_roiiter_packs_scaled_padded_proposals(tmp_path):
    cfg = _cfg(tmp_path)
    _, roidb = load_gt_roidb(cfg, training=True, **KW)
    rng = np.random.RandomState(0)
    proposals = []
    for rec in roidb:
        k = rng.randint(1, 6)
        x1 = rng.uniform(0, 60, k)
        y1 = rng.uniform(0, 60, k)
        p = np.stack([x1, y1, x1 + 20, y1 + 20,
                      np.sort(rng.uniform(size=k))[::-1]], axis=1)
        proposals.append(p.astype(np.float32))
    it = ROIIter(roidb, cfg, proposals, batch_images=2, shuffle=False,
                 max_rois=8)
    batch = next(iter(it))
    assert isinstance(batch, RCNNBatch)
    assert batch.rois.shape == (2, 8, 4)
    assert batch.rois_valid.shape == (2, 8)
    # valid count matches the proposal count, padding is invalid
    # (loader is unshuffled: batch j=0 is roidb[0] of its bucket)
    j = 0
    n_valid = int(batch.rois_valid[j].sum())
    assert 1 <= n_valid <= 8
    # rois are scaled into input coordinates by im_scale
    scale = batch.im_info[j, 2]
    assert batch.rois[j, 0, 2] - batch.rois[j, 0, 0] == pytest.approx(
        20 * scale, rel=1e-5)
    # mismatched lengths are rejected
    with pytest.raises(ValueError):
        ROIIter(roidb, cfg, proposals[:-1])


def test_alternate_four_stages_and_combine(tmp_path):
    cfg = _cfg(tmp_path)
    prefix = str(tmp_path / "model" / "alt")
    final = alternate_train(cfg, prefix=prefix, rpn_epoch=4, rcnn_epoch=4,
                            rpn_lr=3e-3, rcnn_lr=3e-3, rpn_lr_step="3",
                            rcnn_lr_step="3", frequent=1000, seed=0,
                            dataset_kw=KW)
    # all stage artifacts exist
    for stage in ("rpn1", "rcnn1", "rpn2", "rcnn2"):
        assert os.path.exists(f"{prefix}-{stage}-0004.ckpt"), stage
    for pkl in ("rpn1-proposals.pkl", "rpn2-proposals.pkl"):
        with open(f"{prefix}-{pkl}", "rb") as f:
            props = pickle.load(f)
        assert len(props) == KW["num_images"]
    assert final == f"{prefix}-final"
    assert os.path.exists(f"{prefix}-final-0001.ckpt")

    # frozen-shared-conv invariants: stage3 backbone == rcnn1 backbone,
    # stage4 backbone == rpn2 backbone (bit-identical)
    p_rcnn1, _ = load_param(f"{prefix}-rcnn1", 4)
    p_rpn2, _ = load_param(f"{prefix}-rpn2", 4)
    p_rcnn2, _ = load_param(f"{prefix}-rcnn2", 4)
    for a, b in zip(jax.tree.leaves(p_rcnn1["backbone"]),
                    jax.tree.leaves(p_rpn2["backbone"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(p_rpn2["backbone"]),
                    jax.tree.leaves(p_rcnn2["backbone"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # but stage3 DID train the RPN head (it must differ from rcnn1's)
    moved = [not np.array_equal(np.asarray(a), np.asarray(b))
             for a, b in zip(jax.tree.leaves(p_rcnn1["rpn"]),
                             jax.tree.leaves(p_rpn2["rpn"]))]
    assert any(moved)

    # the combined model is evaluable end to end
    results = eval_rcnn(cfg, prefix=final, epoch=1, verbose=False,
                        dataset_kw=dict(num_images=8, image_size=(128, 160),
                                        max_objects=3))
    assert "mAP" in results and np.isfinite(results["mAP"])
    # final params: rpn from rpn2, head from rcnn2 (combine semantics)
    p_final, _ = load_param(final, 1)
    for a, b in zip(jax.tree.leaves(p_final["rpn"]),
                    jax.tree.leaves(p_rpn2["rpn"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(p_final["cls_score"]),
                    jax.tree.leaves(p_rcnn2["cls_score"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # VERDICT r03 item 4: the stage-4 RCNN checkpoint evaluated on dumped
    # rpn2 proposals (the tools/test_rcnn path) must match the combined
    # model's mAP within noise — the combine IS rpn2's RPN + rcnn2's head,
    # so with the dump pinned to the test-time proposal params the only
    # differences are the raw-coordinate roundtrip of the pkl format
    from mx_rcnn_tpu.core.tester import generate_proposals
    from mx_rcnn_tpu.data import TestLoader
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.tools.test_rcnn import test_rcnn_stage

    kw_eval = dict(num_images=8, image_size=(128, 160), max_objects=3)
    cfg_dump = cfg.replace_in(
        "test", proposal_pre_nms_top_n=cfg.test.rpn_pre_nms_top_n,
        proposal_post_nms_top_n=cfg.test.rpn_post_nms_top_n)
    _, test_roidb = load_gt_roidb(cfg_dump, training=False, **kw_eval)
    params, bs = load_param(f"{prefix}-rpn2", 4)
    props = generate_proposals(
        build_model(cfg_dump), {"params": params, "batch_stats": bs},
        TestLoader(test_roidb, cfg_dump), cfg_dump)
    stage = test_rcnn_stage(cfg_dump, prefix=f"{prefix}-rcnn2", epoch=4,
                            proposals=props, verbose=False,
                            dataset_kw=kw_eval)
    assert stage["mAP"] == pytest.approx(results["mAP"], abs=0.05)


def test_stage2_init_knob(tmp_path):
    """stage2_init='rpn1' must seed stage 2 from the rpn1 backbone;
    the default 'fresh' must not (docs/ROUND3.md item-5 ablation)."""
    cfg = _cfg(tmp_path)
    prefix = str(tmp_path / "model" / "alt2")
    alternate_train(cfg, prefix=prefix, rpn_epoch=1, rcnn_epoch=1,
                    rpn_lr=3e-3, rcnn_lr=0.0, frequent=1000, seed=0,
                    dataset_kw=KW, stage2_init="rpn1")
    # rpn trains (lr>0) so rpn1 != the seed-0 init; rcnn lr 0 keeps stage-2
    # weights at their init → rcnn1 backbone == the TRAINED rpn1 backbone
    p_rpn1, _ = load_param(f"{prefix}-rpn1", 1)
    p_rcnn1, _ = load_param(f"{prefix}-rcnn1", 1)
    for a, b in zip(jax.tree.leaves(p_rpn1["backbone"]),
                    jax.tree.leaves(p_rcnn1["backbone"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    prefix = str(tmp_path / "model" / "alt3")
    alternate_train(cfg, prefix=prefix, rpn_epoch=1, rcnn_epoch=1,
                    rpn_lr=3e-3, rcnn_lr=0.0, frequent=1000, seed=0,
                    dataset_kw=KW)  # default: fresh
    p_rpn1, _ = load_param(f"{prefix}-rpn1", 1)
    p_rcnn1, _ = load_param(f"{prefix}-rcnn1", 1)
    same = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(p_rpn1["backbone"]),
                        jax.tree.leaves(p_rcnn1["backbone"])))
    assert not same
