"""Pretrained backbone import tests.

Covers (VERDICT r1 item 4): the MXNet ``.params`` container parser
(round-tripped against a writer of the documented layout), the
MXNet-name → Flax-tree mapping with full backbone coverage (params AND
frozen-BN statistics), the torchvision VGG16 mapping incl. the fc6
CHW→HWC kernel permutation (verified functionally), and the
refuse-partial-backbone guard.
"""

import struct

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.train import setup_training
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.utils.pretrained import (
    _parse_mxnet_params,
    load_pretrained_into,
    map_mxnet_resnet,
    map_vgg16,
)

KEY = jax.random.PRNGKey(0)


def write_mxnet_params(path, named):
    """Writer for the documented MXNet NDArray container layout (V2)."""
    with open(path, "wb") as f:
        f.write(struct.pack("<QQ", 0x112, 0))
        f.write(struct.pack("<Q", len(named)))
        for arr in named.values():
            arr = np.asarray(arr, np.float32)
            f.write(struct.pack("<I", 0xF993FAC9))
            f.write(struct.pack("<i", -1))
            f.write(struct.pack("<I", arr.ndim))
            f.write(struct.pack(f"<{arr.ndim}q", *arr.shape))
            f.write(struct.pack("<iii", 1, 0, 0))
            f.write(arr.astype("<f4").tobytes())
        f.write(struct.pack("<Q", len(named)))
        for name in named:
            b = name.encode()
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def _mxnet_names_from_tree(params, batch_stats):
    """Inverse mapping: our ResNet tree → MXNet zoo names with MXNet
    layouts (kernels OIHW), random values."""
    rng = np.random.RandomState(0)
    named = {}

    def walk(prefix, node, aux):
        for k, v in node.items():
            if isinstance(v, dict):
                walk(prefix + [k], v, aux)
                continue
            scope = "_".join(prefix)  # stage1_unit1 + bn1 → stage1_unit1_bn1
            arr = rng.randn(*np.shape(v)).astype(np.float32)
            is_bn = prefix[-1].startswith("bn") if prefix else False
            if k == "kernel":
                named[f"arg:{scope}_weight"] = arr.transpose(3, 2, 0, 1)
            elif k == "scale":
                named[f"arg:{scope}_gamma"] = arr
            elif k == "bias" and is_bn:
                named[f"arg:{scope}_beta"] = arr
            elif k == "bias":
                named[f"arg:{scope}_bias"] = arr
            elif k == "mean":
                named[f"aux:{scope}_moving_mean"] = np.abs(arr)
            elif k == "var":
                named[f"aux:{scope}_moving_var"] = np.abs(arr) + 0.5

    for module in ("backbone", "head"):
        walk([], {**params[module]}, aux=False)
        walk([], {**batch_stats.get(module, {})}, aux=True)
    return named


@pytest.fixture(scope="module")
def resnet50_state():
    cfg = generate_config("resnet50", "PascalVOC")
    cfg = cfg.replace_in("network", compute_dtype="float32")
    cfg = cfg.replace_in("train", rpn_pre_nms_top_n=64, rpn_post_nms_top_n=16,
                         batch_rois=8, max_gt_boxes=4)
    model = build_model(cfg)
    state, tx = setup_training(model, cfg, KEY, (1, 64, 64, 3),
                               steps_per_epoch=10)
    return cfg, state


def test_mxnet_params_roundtrip(tmp_path):
    named = {
        "arg:conv0_weight": np.random.RandomState(0).randn(8, 3, 7, 7)
        .astype(np.float32),
        "aux:bn0_moving_mean": np.arange(8, dtype=np.float32),
    }
    path = str(tmp_path / "m-0000.params")
    write_mxnet_params(path, named)
    out = _parse_mxnet_params(path)
    assert set(out) == set(named)
    for k in named:
        np.testing.assert_array_equal(out[k], named[k])


def test_resnet_full_coverage_and_layout(tmp_path, resnet50_state):
    cfg, state = resnet50_state
    named = _mxnet_names_from_tree(state.params, state.batch_stats)
    path = str(tmp_path / "resnet-50-0000.params")
    write_mxnet_params(path, named)

    new_state = load_pretrained_into(state, str(tmp_path / "resnet-50"), 0,
                                     cfg)
    # every backbone+head leaf replaced, with the OIHW→HWIO transpose
    k_new = np.asarray(new_state.params["backbone"]["conv0"]["kernel"])
    np.testing.assert_allclose(
        k_new, named["arg:conv0_weight"].transpose(2, 3, 1, 0))
    m_new = np.asarray(new_state.batch_stats["backbone"]["bn0"]["mean"])
    np.testing.assert_array_equal(m_new, named["aux:bn0_moving_mean"])
    # deep leaf in a stage unit
    g = np.asarray(
        new_state.params["backbone"]["stage2_unit1"]["bn1"]["scale"])
    np.testing.assert_array_equal(g, named["arg:stage2_unit1_bn1_gamma"])
    # head (per-ROI stage4) is covered too
    h = np.asarray(new_state.params["head"]["stage4_unit1"]["conv1"]["kernel"])
    np.testing.assert_allclose(
        h, named["arg:stage4_unit1_conv1_weight"].transpose(2, 3, 1, 0))
    # detection layers are untouched
    for scope in ("rpn", "cls_score", "bbox_pred"):
        for a, b in zip(jax.tree.leaves(state.params[scope]),
                        jax.tree.leaves(new_state.params[scope])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # no leaf of the backbone kept its random init
    changed = jax.tree.map(
        lambda a, b: not np.array_equal(np.asarray(a), np.asarray(b)),
        state.params["backbone"], new_state.params["backbone"])
    assert all(jax.tree.leaves(changed))


def test_partial_backbone_refused(tmp_path, resnet50_state):
    cfg, state = resnet50_state
    named = _mxnet_names_from_tree(state.params, state.batch_stats)
    # drop one backbone array → must refuse
    named.pop("arg:stage2_unit1_bn1_gamma")
    path = str(tmp_path / "partial-0000.params")
    write_mxnet_params(path, named)
    with pytest.raises(ValueError, match="backbone leaves"):
        load_pretrained_into(state, str(tmp_path / "partial"), 0, cfg)
    # a checkpoint missing the per-ROI head trunk is refused too
    named2 = _mxnet_names_from_tree(state.params, state.batch_stats)
    named2 = {k: v for k, v in named2.items() if "stage4" not in k}
    write_mxnet_params(str(tmp_path / "nohead-0000.params"), named2)
    with pytest.raises(ValueError, match="head leaves"):
        load_pretrained_into(state, str(tmp_path / "nohead"), 0, cfg)


def test_vgg16_torchvision_mapping_functional(tmp_path):
    """The fc6 CHW→HWC permutation must preserve the function: torch
    Linear(flatten_CHW(x)) == our kernel.T @ flatten_HWC(x)."""
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(1)
    sd = {}
    # features: all 13 convs with torchvision indices
    from mx_rcnn_tpu.utils.pretrained import _TV_VGG16

    in_ch = 3
    for idx in sorted(_TV_VGG16):
        name = _TV_VGG16[idx]
        out_ch = {"conv1": 64, "conv2": 128, "conv3": 256, "conv4": 512,
                  "conv5": 512}[name.split("_")[0]]
        sd[f"features.{idx}.weight"] = torch.tensor(
            rng.randn(out_ch, in_ch, 3, 3).astype(np.float32))
        sd[f"features.{idx}.bias"] = torch.tensor(
            rng.randn(out_ch).astype(np.float32))
        in_ch = out_ch
    sd["classifier.0.weight"] = torch.tensor(
        rng.randn(4096, 512 * 7 * 7).astype(np.float32))
    sd["classifier.0.bias"] = torch.tensor(
        rng.randn(4096).astype(np.float32))
    sd["classifier.3.weight"] = torch.tensor(
        rng.randn(4096, 4096).astype(np.float32))
    sd["classifier.3.bias"] = torch.tensor(rng.randn(4096).astype(np.float32))

    p_up, s_up = map_vgg16({k: v.numpy() for k, v in sd.items()})
    assert not s_up
    assert set(p_up["backbone"]) == set(_TV_VGG16.values())
    assert set(p_up["head"]) == {"fc6", "fc7"}

    # functional equivalence of the fc6 permutation
    x_hwc = rng.randn(7, 7, 512).astype(np.float32)
    x_chw = x_hwc.transpose(2, 0, 1)
    ours = x_hwc.reshape(-1) @ p_up["head"]["fc6"]["kernel"] \
        + p_up["head"]["fc6"]["bias"]
    theirs = sd["classifier.0.weight"].numpy() @ x_chw.reshape(-1) \
        + sd["classifier.0.bias"].numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-3)

    # conv kernel transpose is functionally right: spot-check conv1_1 via
    # explicit correlation at one output position
    k = p_up["backbone"]["conv1_1"]["kernel"]  # HWIO
    img = rng.randn(5, 5, 3).astype(np.float32)
    patch = img[1:4, 1:4, :]
    ours_px = np.tensordot(patch, k, axes=([0, 1, 2], [0, 1, 2]))[0]
    w_t = sd["features.0.weight"].numpy()[0]  # (3, 3, 3) OIHW → I H W
    theirs_px = float((patch.transpose(2, 0, 1) * w_t).sum())
    np.testing.assert_allclose(ours_px, theirs_px, rtol=1e-4, atol=1e-4)
