"""Pretrained backbone import tests.

Covers (VERDICT r1 item 4): the MXNet ``.params`` container parser
(round-tripped against a writer of the documented layout), the
MXNet-name → Flax-tree mapping with full backbone coverage (params AND
frozen-BN statistics), the torchvision VGG16 mapping incl. the fc6
CHW→HWC kernel permutation (verified functionally), and the
refuse-partial-backbone guard.
"""

import struct

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.train import setup_training
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.utils.pretrained import (
    _parse_mxnet_params,
    load_pretrained_into,
    map_mxnet_resnet,
    map_vgg16,
)

KEY = jax.random.PRNGKey(0)


def write_mxnet_params(path, named):
    """Writer for the documented MXNet NDArray container layout (V2)."""
    with open(path, "wb") as f:
        f.write(struct.pack("<QQ", 0x112, 0))
        f.write(struct.pack("<Q", len(named)))
        for arr in named.values():
            arr = np.asarray(arr, np.float32)
            f.write(struct.pack("<I", 0xF993FAC9))
            f.write(struct.pack("<i", -1))
            f.write(struct.pack("<I", arr.ndim))
            f.write(struct.pack(f"<{arr.ndim}q", *arr.shape))
            f.write(struct.pack("<iii", 1, 0, 0))
            f.write(arr.astype("<f4").tobytes())
        f.write(struct.pack("<Q", len(named)))
        for name in named:
            b = name.encode()
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def _mxnet_names_from_tree(params, batch_stats):
    """Inverse mapping: our ResNet tree → MXNet zoo names with MXNet
    layouts (kernels OIHW), random values."""
    rng = np.random.RandomState(0)
    named = {}

    def walk(prefix, node, aux):
        for k, v in node.items():
            if isinstance(v, dict):
                walk(prefix + [k], v, aux)
                continue
            scope = "_".join(prefix)  # stage1_unit1 + bn1 → stage1_unit1_bn1
            arr = rng.randn(*np.shape(v)).astype(np.float32)
            is_bn = prefix[-1].startswith("bn") if prefix else False
            if k == "kernel":
                named[f"arg:{scope}_weight"] = arr.transpose(3, 2, 0, 1)
            elif k == "scale":
                named[f"arg:{scope}_gamma"] = arr
            elif k == "bias" and is_bn:
                named[f"arg:{scope}_beta"] = arr
            elif k == "bias":
                named[f"arg:{scope}_bias"] = arr
            elif k == "mean":
                named[f"aux:{scope}_moving_mean"] = np.abs(arr)
            elif k == "var":
                named[f"aux:{scope}_moving_var"] = np.abs(arr) + 0.5

    for module in ("backbone", "head"):
        walk([], {**params[module]}, aux=False)
        walk([], {**batch_stats.get(module, {})}, aux=True)
    return named


@pytest.fixture(scope="module")
def resnet50_state():
    cfg = generate_config("resnet50", "PascalVOC")
    cfg = cfg.replace_in("network", compute_dtype="float32")
    cfg = cfg.replace_in("train", rpn_pre_nms_top_n=64, rpn_post_nms_top_n=16,
                         batch_rois=8, max_gt_boxes=4)
    model = build_model(cfg)
    state, tx = setup_training(model, cfg, KEY, (1, 64, 64, 3),
                               steps_per_epoch=10)
    return cfg, state


def test_mxnet_params_roundtrip(tmp_path):
    named = {
        "arg:conv0_weight": np.random.RandomState(0).randn(8, 3, 7, 7)
        .astype(np.float32),
        "aux:bn0_moving_mean": np.arange(8, dtype=np.float32),
    }
    path = str(tmp_path / "m-0000.params")
    write_mxnet_params(path, named)
    out = _parse_mxnet_params(path)
    assert set(out) == set(named)
    for k in named:
        np.testing.assert_array_equal(out[k], named[k])


def test_resnet_full_coverage_and_layout(tmp_path, resnet50_state):
    cfg, state = resnet50_state
    named = _mxnet_names_from_tree(state.params, state.batch_stats)
    path = str(tmp_path / "resnet-50-0000.params")
    write_mxnet_params(path, named)

    new_state = load_pretrained_into(state, str(tmp_path / "resnet-50"), 0,
                                     cfg)
    # every backbone+head leaf replaced, with the OIHW→HWIO transpose
    k_new = np.asarray(new_state.params["backbone"]["conv0"]["kernel"])
    np.testing.assert_allclose(
        k_new, named["arg:conv0_weight"].transpose(2, 3, 1, 0))
    m_new = np.asarray(new_state.batch_stats["backbone"]["bn0"]["mean"])
    np.testing.assert_array_equal(m_new, named["aux:bn0_moving_mean"])
    # deep leaf in a stage unit
    g = np.asarray(
        new_state.params["backbone"]["stage2_unit1"]["bn1"]["scale"])
    np.testing.assert_array_equal(g, named["arg:stage2_unit1_bn1_gamma"])
    # head (per-ROI stage4) is covered too
    h = np.asarray(new_state.params["head"]["stage4_unit1"]["conv1"]["kernel"])
    np.testing.assert_allclose(
        h, named["arg:stage4_unit1_conv1_weight"].transpose(2, 3, 1, 0))
    # detection layers are untouched
    for scope in ("rpn", "cls_score", "bbox_pred"):
        for a, b in zip(jax.tree.leaves(state.params[scope]),
                        jax.tree.leaves(new_state.params[scope])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # no leaf of the backbone kept its random init
    changed = jax.tree.map(
        lambda a, b: not np.array_equal(np.asarray(a), np.asarray(b)),
        state.params["backbone"], new_state.params["backbone"])
    assert all(jax.tree.leaves(changed))


def test_partial_backbone_refused(tmp_path, resnet50_state):
    cfg, state = resnet50_state
    named = _mxnet_names_from_tree(state.params, state.batch_stats)
    # drop one backbone array → must refuse
    named.pop("arg:stage2_unit1_bn1_gamma")
    path = str(tmp_path / "partial-0000.params")
    write_mxnet_params(path, named)
    with pytest.raises(ValueError, match="backbone leaves"):
        load_pretrained_into(state, str(tmp_path / "partial"), 0, cfg)
    # a checkpoint missing the per-ROI head trunk is refused too
    named2 = _mxnet_names_from_tree(state.params, state.batch_stats)
    named2 = {k: v for k, v in named2.items() if "stage4" not in k}
    write_mxnet_params(str(tmp_path / "nohead-0000.params"), named2)
    with pytest.raises(ValueError, match="head leaves"):
        load_pretrained_into(state, str(tmp_path / "nohead"), 0, cfg)


def test_vgg16_torchvision_mapping_functional(tmp_path):
    """The fc6 CHW→HWC permutation must preserve the function: torch
    Linear(flatten_CHW(x)) == our kernel.T @ flatten_HWC(x)."""
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(1)
    sd = {}
    # features: all 13 convs with torchvision indices
    from mx_rcnn_tpu.utils.pretrained import _TV_VGG16

    in_ch = 3
    for idx in sorted(_TV_VGG16):
        name = _TV_VGG16[idx]
        out_ch = {"conv1": 64, "conv2": 128, "conv3": 256, "conv4": 512,
                  "conv5": 512}[name.split("_")[0]]
        sd[f"features.{idx}.weight"] = torch.tensor(
            rng.randn(out_ch, in_ch, 3, 3).astype(np.float32))
        sd[f"features.{idx}.bias"] = torch.tensor(
            rng.randn(out_ch).astype(np.float32))
        in_ch = out_ch
    sd["classifier.0.weight"] = torch.tensor(
        rng.randn(4096, 512 * 7 * 7).astype(np.float32))
    sd["classifier.0.bias"] = torch.tensor(
        rng.randn(4096).astype(np.float32))
    sd["classifier.3.weight"] = torch.tensor(
        rng.randn(4096, 4096).astype(np.float32))
    sd["classifier.3.bias"] = torch.tensor(rng.randn(4096).astype(np.float32))

    p_up, s_up, leftover = map_vgg16({k: v.numpy() for k, v in sd.items()})
    assert not s_up and not leftover
    assert set(p_up["backbone"]) == set(_TV_VGG16.values())
    assert set(p_up["head"]) == {"fc6", "fc7"}

    # functional equivalence of the fc6 permutation
    x_hwc = rng.randn(7, 7, 512).astype(np.float32)
    x_chw = x_hwc.transpose(2, 0, 1)
    ours = x_hwc.reshape(-1) @ p_up["head"]["fc6"]["kernel"] \
        + p_up["head"]["fc6"]["bias"]
    theirs = sd["classifier.0.weight"].numpy() @ x_chw.reshape(-1) \
        + sd["classifier.0.bias"].numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-3)

    # conv kernel transpose is functionally right: spot-check conv1_1 via
    # explicit correlation at one output position
    k = p_up["backbone"]["conv1_1"]["kernel"]  # HWIO
    img = rng.randn(5, 5, 3).astype(np.float32)
    patch = img[1:4, 1:4, :]
    ours_px = np.tensordot(patch, k, axes=([0, 1, 2], [0, 1, 2]))[0]
    w_t = sd["features.0.weight"].numpy()[0]  # (3, 3, 3) OIHW → I H W
    theirs_px = float((patch.transpose(2, 0, 1) * w_t).sum())
    np.testing.assert_allclose(ours_px, theirs_px, rtol=1e-4, atol=1e-4)


# ---- VERDICT r02 item 4: independent zoo name set + activation check -------

def _zoo_resnet_v2_names(units, filters=(256, 512, 1024, 2048)):
    """The FULL name/shape set of an MXNet ResNet-v2 zoo checkpoint
    (tornadomeet/ResNet layout, the files the reference trains from, e.g.
    ``resnet-101-0000.params``), generated from the PUBLISHED naming
    convention — deliberately independent of this repo's model tree, so
    coverage is checked against reality instead of circularly.

    Returns {mxnet_name: shape}; `arg:`/`aux:` prefixes included.
    """
    shapes = {}

    def bn(scope, c):
        shapes[f"arg:{scope}_gamma"] = (c,)
        shapes[f"arg:{scope}_beta"] = (c,)
        shapes[f"aux:{scope}_moving_mean"] = (c,)
        shapes[f"aux:{scope}_moving_var"] = (c,)

    bn("bn_data", 3)
    shapes["arg:conv0_weight"] = (64, 3, 7, 7)
    bn("bn0", 64)
    in_ch = 64
    for si, (n_unit, f) in enumerate(zip(units, filters), start=1):
        m = f // 4
        for u in range(1, n_unit + 1):
            s = f"stage{si}_unit{u}"
            bn(f"{s}_bn1", in_ch)
            shapes[f"arg:{s}_conv1_weight"] = (m, in_ch, 1, 1)
            bn(f"{s}_bn2", m)
            shapes[f"arg:{s}_conv2_weight"] = (m, m, 3, 3)
            bn(f"{s}_bn3", m)
            shapes[f"arg:{s}_conv3_weight"] = (f, m, 1, 1)
            if u == 1:
                shapes[f"arg:{s}_sc_weight"] = (f, in_ch, 1, 1)
            in_ch = f
    bn("bn1", filters[-1])
    shapes["arg:fc1_weight"] = (1000, filters[-1])
    shapes["arg:fc1_bias"] = (1000,)
    return shapes


@pytest.mark.slow
def test_resnet101_zoo_nameset_zero_unmatched_both_directions(tmp_path):
    """A synthesized FULL resnet-101 zoo checkpoint must load with zero
    unmatched keys in BOTH directions: every non-classifier zoo array is
    consumed (leftover == []) and every model backbone/head leaf is
    covered (enforced inside load_pretrained_into)."""
    shapes = _zoo_resnet_v2_names(units=(3, 4, 23, 3))
    rng = np.random.RandomState(0)
    named = {}
    for k, shp in shapes.items():
        a = rng.randn(*shp).astype(np.float32)
        if k.endswith("_moving_var"):
            a = np.abs(a) + 0.5
        named[k] = a
    path = str(tmp_path / "resnet-101-0000.params")
    write_mxnet_params(path, named)

    cfg = generate_config("resnet101", "PascalVOC")
    cfg = cfg.replace_in("network", compute_dtype="float32")
    cfg = cfg.replace_in("train", rpn_pre_nms_top_n=64, rpn_post_nms_top_n=16,
                         batch_rois=8, max_gt_boxes=4)
    model = build_model(cfg)
    state, tx = setup_training(model, cfg, KEY, (1, 64, 64, 3),
                               steps_per_epoch=10)
    new_state = load_pretrained_into(state, str(tmp_path / "resnet-101"), 0,
                                     cfg)
    # direction 1: zero leftover — map consumed every non-classifier array
    _, _, leftover = map_mxnet_resnet(named)
    assert leftover == []
    # direction 2: every backbone/head leaf was replaced
    for module in ("backbone", "head"):
        changed = jax.tree.map(
            lambda a, b: not np.array_equal(np.asarray(a), np.asarray(b)),
            state.params[module], new_state.params[module])
        assert all(jax.tree.leaves(changed)), module
    # count parity: zoo arrays (minus fc1) == model leaves touched
    n_zoo = len([k for k in named if not k.startswith("arg:fc1")])
    n_model = (len(jax.tree.leaves(new_state.params["backbone"]))
               + len(jax.tree.leaves(new_state.params["head"]))
               + len(jax.tree.leaves(new_state.batch_stats["backbone"]))
               + len(jax.tree.leaves(new_state.batch_stats["head"])))
    assert n_zoo == n_model
    # an extra array with no recognizable suffix → leftover, refused
    bad = dict(named)
    bad["arg:mystery_blob"] = np.zeros((3, 3), np.float32)
    write_mxnet_params(str(tmp_path / "bad-0000.params"), bad)
    with pytest.raises(ValueError, match="map to nothing"):
        load_pretrained_into(state, str(tmp_path / "bad"), 0, cfg)
    # an extra array with a known suffix but unknown scope → graft refuses
    bad2 = dict(named)
    bad2["arg:mystery_weight"] = np.zeros((3, 3, 1, 1), np.float32)
    write_mxnet_params(str(tmp_path / "bad2-0000.params"), bad2)
    with pytest.raises(KeyError, match="mystery"):
        load_pretrained_into(state, str(tmp_path / "bad2"), 0, cfg)


def _np_conv2d_same(x, k_oihw, stride=1):
    """Plain-NumPy NHWC conv with SAME padding from an OIHW kernel —
    independent of jax/flax layout conventions."""
    kh, kw = k_oihw.shape[2], k_oihw.shape[3]
    h, w, _ = x.shape
    oh = (h + stride - 1) // stride
    ow = (w + stride - 1) // stride
    pad_h = max((oh - 1) * stride + kh - h, 0)
    pad_w = max((ow - 1) * stride + kw - w, 0)
    xp = np.pad(x, ((pad_h // 2, pad_h - pad_h // 2),
                    (pad_w // 2, pad_w - pad_w // 2), (0, 0)))
    out = np.zeros((oh, ow, k_oihw.shape[0]), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = xp[i * stride:i * stride + kh,
                       j * stride:j * stride + kw, :]  # (kh, kw, C)
            # OIHW → sum over H, W, I
            out[i, j] = np.einsum("hwc,ochw->o", patch, k_oihw)
    return out


def _np_bn(x, gamma, beta, mean, var, eps=2e-5):
    return gamma * (x - mean) / np.sqrt(var + eps) + beta


def test_bottleneck_activation_matches_numpy_reference(tmp_path):
    """Activation-level pin of the OIHW→HWIO + BN mapping: a full
    pre-activation bottleneck unit loaded from MXNet-named weights must
    reproduce a plain-NumPy forward of the same OIHW weights (the v2
    residual_unit: bn→relu→1x1 → bn→relu→3x3 → bn→relu→1x1 + proj
    shortcut from the first activation)."""
    from mx_rcnn_tpu.models.resnet import BottleneckUnit
    from mx_rcnn_tpu.utils.pretrained import _graft

    rng = np.random.RandomState(7)
    in_ch, f = 8, 16
    m = f // 4
    named = {}
    for scope, c in (("stage1_unit1_bn1", in_ch), ("stage1_unit1_bn2", m),
                     ("stage1_unit1_bn3", m)):
        named[f"arg:{scope}_gamma"] = rng.randn(c).astype(np.float32)
        named[f"arg:{scope}_beta"] = rng.randn(c).astype(np.float32)
        named[f"aux:{scope}_moving_mean"] = rng.randn(c).astype(np.float32)
        named[f"aux:{scope}_moving_var"] = (
            np.abs(rng.randn(c)) + 0.5).astype(np.float32)
    named["arg:stage1_unit1_conv1_weight"] = rng.randn(
        m, in_ch, 1, 1).astype(np.float32)
    named["arg:stage1_unit1_conv2_weight"] = rng.randn(
        m, m, 3, 3).astype(np.float32)
    named["arg:stage1_unit1_conv3_weight"] = rng.randn(
        f, m, 1, 1).astype(np.float32)
    named["arg:stage1_unit1_sc_weight"] = rng.randn(
        f, in_ch, 1, 1).astype(np.float32)

    p_up, s_up, leftover = map_mxnet_resnet(named)
    assert leftover == []
    unit = BottleneckUnit(filters=f, stride=1, dim_match=False,
                          dtype=jnp.float32)
    x = rng.randn(1, 6, 6, in_ch).astype(np.float32)
    variables = unit.init(KEY, jnp.asarray(x))
    params = jax.tree.map(np.asarray, variables["params"])
    stats = jax.tree.map(np.asarray, variables["batch_stats"])
    _graft(params, p_up["backbone"]["stage1_unit1"])
    _graft(stats, s_up["backbone"]["stage1_unit1"])
    got = np.asarray(unit.apply(
        {"params": params, "batch_stats": stats}, jnp.asarray(x)))[0]

    # independent NumPy forward from the ORIGINAL OIHW arrays
    def g(n):
        return named[f"arg:stage1_unit1_{n}"]

    def st(n):
        return (named[f"arg:stage1_unit1_{n}_gamma"],
                named[f"arg:stage1_unit1_{n}_beta"],
                named[f"aux:stage1_unit1_{n}_moving_mean"],
                named[f"aux:stage1_unit1_{n}_moving_var"])

    a1 = np.maximum(_np_bn(x[0], *st("bn1")), 0)
    c1 = _np_conv2d_same(a1, g("conv1_weight"))
    a2 = np.maximum(_np_bn(c1, *st("bn2")), 0)
    c2 = _np_conv2d_same(a2, g("conv2_weight"))
    a3 = np.maximum(_np_bn(c2, *st("bn3")), 0)
    c3 = _np_conv2d_same(a3, g("conv3_weight"))
    sc = _np_conv2d_same(a1, g("sc_weight"))
    want = c3 + sc
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
