"""Unit tests for the eval core (``core/tester.py``) — VERDICT r1 item 3.

Covers the decode math (de-normalize → bbox_pred → clip → unscale), the
Predictor per-shape jit cache, ``_postprocess_batch`` NMS/threshold
semantics, ``pred_eval`` end-to-end against a fabricated perfect predictor
(must score mAP=1.0), the ``max_per_image`` cap, and
``generate_proposals`` output structure/ordering.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.tester import (
    Predictor,
    _postprocess_batch,
    generate_proposals,
    im_detect_batch,
    pred_eval,
)
from mx_rcnn_tpu.data import TestLoader, load_gt_roidb
from mx_rcnn_tpu.models import build_model


def _toy_cfg(num_classes=3):
    cfg = generate_config("tiny", "synthetic",
                          dataset__num_classes=num_classes)
    cfg = cfg.replace_in("bucket", scale=128, max_size=160,
                         shapes=((128, 160), (160, 128)))
    cfg = cfg.replace_in("test", rpn_pre_nms_top_n=256, rpn_post_nms_top_n=32)
    return cfg


def test_im_detect_batch_golden():
    """Hand-computed decode: delta de-normalization, identity decode for
    zero deltas, clipping, and un-scaling back to raw coordinates."""
    cfg = _toy_cfg(num_classes=2)
    # one image, two ROIs, two classes (bg + 1)
    rois = np.array([[[10.0, 10.0, 29.0, 29.0],
                      [0.0, 0.0, 19.0, 39.0]]], np.float32)
    roi_valid = np.array([[True, False]])
    cls_prob = np.array([[[0.1, 0.9], [0.2, 0.8]]], np.float32)
    # zero deltas → decoded box == roi for every class
    deltas = np.zeros((1, 2, 8), np.float32)
    # normalized dx=1 for class1 of roi0: raw dx = 1*std_x(0.1)+mean(0) = 0.1
    deltas[0, 0, 4] = 1.0
    im_info = np.array([[100.0, 100.0, 2.0]], np.float32)
    scales = np.array([2.0], np.float32)
    (boxes, scores), = im_detect_batch(rois, roi_valid, cls_prob, deltas,
                                       im_info, scales, cfg)
    # class 0 of roi0: identity decode, then /2 scale
    np.testing.assert_allclose(boxes[0, 0:4], rois[0, 0] / 2.0, atol=1e-5)
    # class 1 of roi0: dx=0.1 shifts the center by 0.1*width (width=20)
    w = 20.0
    expected = (rois[0, 0] + np.array([0.1 * w, 0, 0.1 * w, 0])) / 2.0
    np.testing.assert_allclose(boxes[0, 4:8], expected, atol=1e-4)
    # invalid ROI slot → zero scores
    np.testing.assert_allclose(scores[1], 0.0)
    assert scores[0, 1] == pytest.approx(0.9)


def test_im_detect_batch_clips_to_image():
    cfg = _toy_cfg(num_classes=1)
    rois = np.array([[[-5.0, -7.0, 200.0, 300.0]]], np.float32)
    roi_valid = np.array([[True]])
    cls_prob = np.ones((1, 1, 1), np.float32)
    deltas = np.zeros((1, 1, 4), np.float32)
    im_info = np.array([[50.0, 60.0, 1.0]], np.float32)
    scales = np.array([1.0], np.float32)
    (boxes, _), = im_detect_batch(rois, roi_valid, cls_prob, deltas,
                                  im_info, scales, cfg)
    assert boxes[0, 0] >= 0 and boxes[0, 1] >= 0
    assert boxes[0, 2] <= 59.0 and boxes[0, 3] <= 49.0


def test_postprocess_batch_nms_and_threshold():
    cfg = _toy_cfg(num_classes=2)
    # three ROIs: two overlapping (IoU>0.3), one distant low-score
    rois = np.array([[[10, 10, 50, 50], [12, 12, 52, 52],
                      [80, 80, 120, 120]]], np.float32)
    roi_valid = np.array([[True, True, True]])
    cls_prob = np.array([[[0.1, 0.9], [0.4, 0.6], [1.0, 1e-5]]], np.float32)
    deltas = np.zeros((1, 3, 8), np.float32)
    im_info = np.array([[160.0, 160.0, 1.0]], np.float32)
    scales = np.array([1.0], np.float32)
    stds = jnp.tile(jnp.asarray(cfg.train.bbox_stds), 2)
    means = jnp.tile(jnp.asarray(cfg.train.bbox_means), 2)
    boxes, scores, keep = map(np.asarray, _postprocess_batch(
        jnp.asarray(rois), jnp.asarray(roi_valid), jnp.asarray(cls_prob),
        jnp.asarray(deltas), jnp.asarray(im_info), jnp.asarray(scales),
        stds, means, nms_thresh=0.3, score_thresh=1e-3))
    k = keep[0, 1]  # class 1
    assert k[0]          # highest score survives
    assert not k[1]      # suppressed by overlap with roi0
    assert not k[2]      # below score threshold
    # a padded (invalid) ROI can never be kept
    roi_valid2 = np.array([[True, True, False]])
    cls_prob2 = np.array([[[0.1, 0.9], [0.4, 0.6], [0.0, 1.0]]], np.float32)
    _, _, keep2 = map(np.asarray, _postprocess_batch(
        jnp.asarray(rois), jnp.asarray(roi_valid2), jnp.asarray(cls_prob2),
        jnp.asarray(deltas), jnp.asarray(im_info), jnp.asarray(scales),
        stds, means, nms_thresh=0.3, score_thresh=1e-3))
    assert not keep2[0, 1, 2]


def test_predictor_shape_cache():
    cfg = _toy_cfg()
    model = build_model(cfg)
    images = np.zeros((1, 128, 160, 3), np.float32)
    im_info = np.array([[128.0, 160.0, 1.0]], np.float32)
    variables = jax.jit(model.init)(jax.random.PRNGKey(0),
                                    jnp.asarray(images),
                                    jnp.asarray(im_info))
    pred = Predictor(model, variables, cfg)
    pred(images, im_info)
    assert len(pred._fns) == 1
    pred(images, im_info)  # same shape → cached
    assert len(pred._fns) == 1
    pred(np.zeros((1, 160, 128, 3), np.float32),
         np.array([[160.0, 128.0, 1.0]], np.float32))
    assert len(pred._fns) == 2
    rois, roi_valid, cls_prob, deltas = pred(images, im_info)
    r = cfg.test.rpn_post_nms_top_n
    assert rois.shape == (1, r, 4)
    assert cls_prob.shape == (1, r, cfg.num_classes)
    assert deltas.shape == (1, r, 4 * cfg.num_classes)


class _PerfectPredictor:
    """Fabricated predictor: emits every gt box of the image with an
    almost-one-hot class probability — pred_eval must score mAP = 1.0."""

    def __init__(self, roidb, cfg, r=16):
        self.roidb = roidb
        self.cfg = cfg
        self.r = r
        self._cursor = 0

    def raw(self, images, im_info):
        n = images.shape[0]
        c = self.cfg.num_classes
        rois = np.zeros((n, self.r, 4), np.float32)
        valid = np.zeros((n, self.r), bool)
        prob = np.zeros((n, self.r, c), np.float32)
        prob[:, :, 0] = 1.0  # default: confident background
        deltas = np.zeros((n, self.r, 4 * c), np.float32)
        for j in range(n):
            rec = self.roidb[self._cursor + j]
            scale = im_info[j, 2]
            k = len(rec["boxes"])
            rois[j, :k] = rec["boxes"] * scale
            valid[j, :k] = True
            for t in range(k):
                prob[j, t, :] = 0.0
                # distinct scores: the max_per_image cap keeps score TIES
                # (>= threshold, matching the reference), so equal scores
                # would defeat it
                prob[j, t, rec["gt_classes"][t]] = 0.95 - 0.01 * t
        self._cursor += n
        return (jnp.asarray(rois), jnp.asarray(valid), jnp.asarray(prob),
                jnp.asarray(deltas))


def test_pred_eval_perfect_predictor_scores_map_1(tmp_path):
    cfg = _toy_cfg(num_classes=4)
    cfg = cfg.replace_in(
        "dataset", root_path=str(tmp_path),
        dataset_path=str(tmp_path / "synthetic"))
    kw = dict(num_images=6, image_size=(128, 160), max_objects=3)
    imdb, roidb = load_gt_roidb(cfg, training=False, **kw)
    loader = TestLoader(roidb, cfg)
    pred = _PerfectPredictor(roidb, cfg)
    results = pred_eval(pred, loader, imdb, cfg, verbose=False)
    assert results["mAP"] == pytest.approx(1.0)


def test_pred_eval_max_per_image_cap(tmp_path):
    cfg = _toy_cfg(num_classes=4)
    cfg = cfg.replace_in(
        "dataset", root_path=str(tmp_path),
        dataset_path=str(tmp_path / "synthetic"))
    cfg = cfg.replace_in("test", max_per_image=1)
    kw = dict(num_images=4, image_size=(128, 160), max_objects=3)
    imdb, roidb = load_gt_roidb(cfg, training=False, **kw)
    loader = TestLoader(roidb, cfg)
    pred = _PerfectPredictor(roidb, cfg)
    # run the loop manually to inspect detection counts per image
    num_classes = imdb.num_classes
    results = pred_eval(pred, loader, imdb, cfg, verbose=False)
    # with at most 1 det/image, images holding >1 object cannot all be
    # found: mAP must drop below 1 iff some image has 2+ objects
    multi = any(len(r["boxes"]) > 1 for r in roidb)
    if multi:
        assert results["mAP"] < 1.0
    else:  # degenerate draw — still a valid run
        assert results["mAP"] == pytest.approx(1.0)


def test_detect_rois_matches_full_forward():
    """The RCNN-only path (``detect_rois``, ref test_rcnn.py's
    HAS_RPN=False symbol) fed the model's OWN RPN proposals must reproduce
    ``__call__``'s cls_prob/deltas exactly — same features, same pooling,
    same head, just without re-running the proposal machinery."""
    cfg = _toy_cfg()
    model = build_model(cfg)
    rng = np.random.RandomState(3)
    images = rng.uniform(0, 50, (2, 128, 160, 3)).astype(np.float32)
    im_info = np.array([[128.0, 160.0, 1.0]] * 2, np.float32)
    variables = jax.jit(model.init)(jax.random.PRNGKey(0),
                                    jnp.asarray(images),
                                    jnp.asarray(im_info))
    rois, valid, prob, deltas = model.apply(
        variables, jnp.asarray(images), jnp.asarray(im_info))
    rois2, valid2, prob2, deltas2 = model.apply(
        variables, jnp.asarray(images), jnp.asarray(im_info), rois, valid,
        method=model.detect_rois)
    np.testing.assert_array_equal(np.asarray(rois), np.asarray(rois2))
    np.testing.assert_array_equal(np.asarray(valid), np.asarray(valid2))
    np.testing.assert_allclose(np.asarray(prob), np.asarray(prob2),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(deltas), np.asarray(deltas2),
                               atol=1e-6)


def test_predictor_raw_batch_dispatch(tmp_path):
    """Predictor.raw_batch routes RCNNBatch → detect_rois and Batch → the
    full forward; the ROITestLoader feeds the former end to end through
    pred_eval."""
    from mx_rcnn_tpu.core.train import Batch, RCNNBatch
    from mx_rcnn_tpu.data import ROITestLoader

    cfg = _toy_cfg(num_classes=4)
    cfg = cfg.replace_in(
        "dataset", root_path=str(tmp_path),
        dataset_path=str(tmp_path / "synthetic"))
    cfg = cfg.replace_in("test", proposal_post_nms_top_n=16)
    kw = dict(num_images=4, image_size=(128, 160), max_objects=3)
    imdb, roidb = load_gt_roidb(cfg, training=False, **kw)
    model = build_model(cfg)
    images = np.zeros((1, 128, 160, 3), np.float32)
    im_info = np.array([[128.0, 160.0, 1.0]], np.float32)
    variables = jax.jit(model.init)(jax.random.PRNGKey(0),
                                    jnp.asarray(images),
                                    jnp.asarray(im_info))
    pred = Predictor(model, variables, cfg)

    # gt boxes as proposals: an untrained head scores garbage, but every
    # shape/ordering contract is exercised
    proposals = [
        np.hstack([rec["boxes"],
                   np.linspace(1, .5, len(rec["boxes"]))[:, None]]
                  ).astype(np.float32)
        for rec in roidb
    ]
    loader = ROITestLoader(roidb, cfg, proposals, batch_images=2)
    batch, indices, scales = next(iter(loader))
    assert isinstance(batch, RCNNBatch)
    assert batch.rois.shape == (2, 16, 4)
    out = pred.raw_batch(batch)
    r = cfg.test.proposal_post_nms_top_n
    assert np.asarray(out[2]).shape == (2, r, cfg.num_classes)
    # given rois pass through unchanged
    np.testing.assert_array_equal(np.asarray(out[0]), batch.rois)
    # plain Batch routes to the RPN path (R = rpn_post_nms_top_n)
    plain = Batch(batch.images, batch.im_info, batch.gt_boxes,
                  batch.gt_classes, batch.gt_valid)
    out_rpn = pred.raw_batch(plain)
    assert np.asarray(out_rpn[0]).shape == (2, cfg.test.rpn_post_nms_top_n, 4)
    # end to end: pred_eval over the ROI loader produces a finite mAP
    results = pred_eval(pred, loader, imdb, cfg, verbose=False)
    assert np.isfinite(results["mAP"])
    # mismatched proposal list length is rejected
    with pytest.raises(ValueError):
        ROITestLoader(roidb, cfg, proposals[:-1])


def test_generate_proposals_structure(tmp_path):
    cfg = _toy_cfg(num_classes=4)
    cfg = cfg.replace_in(
        "dataset", root_path=str(tmp_path),
        dataset_path=str(tmp_path / "synthetic"))
    kw = dict(num_images=3, image_size=(128, 160), max_objects=2)
    imdb, roidb = load_gt_roidb(cfg, training=False, **kw)
    loader = TestLoader(roidb, cfg)
    model = build_model(cfg)
    b = next(iter(loader))[0]
    variables = jax.jit(model.init)(
        jax.random.PRNGKey(0), jnp.asarray(b.images),
        jnp.asarray(b.im_info))
    props = generate_proposals(model, variables, loader, cfg)
    assert len(props) == len(roidb)
    for p in props:
        assert p.ndim == 2 and p.shape[1] == 5
        if len(p) > 1:  # scores are descending (ref pkl ordering)
            assert (np.diff(p[:, 4]) <= 1e-6).all()
        # boxes are in raw image coordinates
        assert (p[:, 2] <= 160.0).all() and (p[:, 3] <= 128.0).all()


def test_predictor_sharded_matches_single_device():
    """Mesh-sharded eval forward (multi-chip eval) must produce the same
    outputs as the single-device predictor, including the short-batch
    padding path (5 images on an 8-device mesh)."""
    from mx_rcnn_tpu.parallel.dp import device_mesh

    cfg = _toy_cfg()
    model = build_model(cfg)
    rng = np.random.RandomState(0)
    n = 5
    images = rng.randn(n, 128, 160, 3).astype(np.float32)
    im_info = np.tile(np.array([[128.0, 160.0, 1.0]], np.float32), (n, 1))
    variables = jax.jit(model.init)(jax.random.PRNGKey(0),
                                    jnp.asarray(images[:1]),
                                    jnp.asarray(im_info[:1]))
    single = Predictor(model, variables, cfg)
    sharded = Predictor(model, variables, cfg, mesh=device_mesh(8))
    outs_s = single(images, im_info)
    outs_m = sharded(images, im_info)
    for a, b, name in zip(outs_s, outs_m,
                          ("rois", "valid", "cls_prob", "deltas")):
        assert a.shape == b.shape, name
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5, err_msg=name)


def test_predictor_rpn_sharded_matches_single_device():
    """The RPN-only proposal forward (Predictor.rpn, backing
    generate_proposals) must be mesh-invariant like the full eval
    forward, including the pad-and-trim path (5 images, 8 devices)."""
    from mx_rcnn_tpu.parallel.dp import device_mesh

    cfg = _toy_cfg()
    model = build_model(cfg)
    rng = np.random.RandomState(3)
    n = 5
    images = rng.randn(n, 128, 160, 3).astype(np.float32)
    im_info = np.tile(np.array([[128.0, 160.0, 1.0]], np.float32), (n, 1))
    variables = jax.jit(model.init)(jax.random.PRNGKey(0),
                                    jnp.asarray(images[:1]),
                                    jnp.asarray(im_info[:1]))
    single = Predictor(model, variables, cfg)
    sharded = Predictor(model, variables, cfg, mesh=device_mesh(8))
    outs_s = single.rpn(images, im_info)
    outs_m = sharded.rpn(images, im_info)
    for a, b, name in zip(outs_s, outs_m, ("rois", "scores", "valid")):
        a, b = np.asarray(a), np.asarray(b)
        assert a.shape == b.shape, name
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5, err_msg=name)


def test_generate_proposals_mesh_matches_host(tmp_path):
    """generate_proposals(mesh=...) — multi-chip proposal dump for the
    alternate schedule — must return the single-device proposals."""
    from mx_rcnn_tpu.data import load_gt_roidb
    from mx_rcnn_tpu.parallel.dp import device_mesh

    cfg = _toy_cfg(num_classes=4)
    cfg = cfg.replace_in(
        "dataset", root_path=str(tmp_path),
        dataset_path=str(tmp_path / "synthetic"))
    kw = dict(num_images=3, image_size=(128, 160), max_objects=2)
    imdb, roidb = load_gt_roidb(cfg, training=False, **kw)
    model = build_model(cfg)
    loader = TestLoader(roidb, cfg)
    b = next(iter(loader))[0]
    variables = jax.jit(model.init)(
        jax.random.PRNGKey(0), jnp.asarray(b.images),
        jnp.asarray(b.im_info))
    base = generate_proposals(model, variables, TestLoader(roidb, cfg), cfg)
    mesh = generate_proposals(model, variables, TestLoader(roidb, cfg), cfg,
                              mesh=device_mesh(8))
    assert len(base) == len(mesh)
    for p0, p1 in zip(base, mesh):
        np.testing.assert_allclose(p0, p1, atol=1e-5, rtol=1e-5)
