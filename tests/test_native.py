"""Tests for the native C++ host kernels (mx_rcnn_tpu/native).

Covers both backends: every op is checked native-vs-NumPy-fallback (they
must agree exactly) and against independent dense/oracle computations.
Reference parity targets: ``rcnn/cython/cpu_nms.pyx``,
``rcnn/cython/bbox.pyx``, ``rcnn/pycocotools/maskApi.c``.
"""

import numpy as np
import pytest

from mx_rcnn_tpu import native


@pytest.fixture(scope="module")
def has_native():
    return native.ensure_built()


def _numpy_backend(monkeypatch):
    """Force the NumPy fallback paths."""
    monkeypatch.setattr(native, "_load", lambda: None)


def _greedy_nms_oracle(dets, thresh):
    # tie-break matches cpu_nms / ref scores.argsort()[::-1]: higher index first
    order = dets[:, 4].argsort(kind="stable")[::-1]
    keep, live = [], np.ones(len(dets), bool)
    for i in order:
        if not live[i]:
            continue
        keep.append(i)
        for j in order:
            if not live[j] or j == i:
                continue
            xx1 = max(dets[i, 0], dets[j, 0])
            yy1 = max(dets[i, 1], dets[j, 1])
            xx2 = min(dets[i, 2], dets[j, 2])
            yy2 = min(dets[i, 3], dets[j, 3])
            w, h = max(0.0, xx2 - xx1 + 1), max(0.0, yy2 - yy1 + 1)
            inter = w * h
            a = lambda d: (d[2] - d[0] + 1) * (d[3] - d[1] + 1)
            if inter / (a(dets[i]) + a(dets[j]) - inter) > thresh:
                live[j] = False
    return np.asarray(keep, np.int64)


def _rand_dets(rng, n):
    xy = rng.uniform(0, 80, (n, 2)).astype(np.float32)
    wh = rng.uniform(5, 40, (n, 2)).astype(np.float32)
    scores = rng.uniform(size=(n, 1)).astype(np.float32)
    return np.hstack([xy, xy + wh, scores])


def test_cpu_nms_matches_oracle(has_native):
    rng = np.random.RandomState(0)
    for n in (1, 7, 50, 300):
        dets = _rand_dets(rng, n)
        keep = native.cpu_nms(dets, 0.3)
        np.testing.assert_array_equal(keep, _greedy_nms_oracle(dets, 0.3))


def test_cpu_nms_backends_agree(has_native, monkeypatch):
    if not has_native:
        pytest.skip("native lib unavailable")
    rng = np.random.RandomState(1)
    dets = _rand_dets(rng, 200)
    got_native = native.cpu_nms(dets, 0.5)
    _numpy_backend(monkeypatch)
    got_numpy = native.cpu_nms(dets, 0.5)
    np.testing.assert_array_equal(got_native, got_numpy)


def test_cpu_nms_empty():
    assert native.cpu_nms(np.zeros((0, 5), np.float32), 0.3).size == 0


def test_cpu_nms_tie_break_matches_reference(has_native, monkeypatch):
    """Among equal scores the reference's ``scores.argsort()[::-1]`` visits
    the HIGHER original index first (ADVICE r2).  Two disjoint boxes with
    identical scores: both kept, higher index reported first."""
    dets = np.array([[0, 0, 10, 10, 0.5],
                     [100, 100, 110, 110, 0.5]], np.float32)
    np.testing.assert_array_equal(native.cpu_nms(dets, 0.3), [1, 0])
    if has_native:
        _numpy_backend(monkeypatch)
        np.testing.assert_array_equal(native.cpu_nms(dets, 0.3), [1, 0])


def test_bbox_overlaps_against_jnp(has_native):
    from mx_rcnn_tpu.ops.boxes import bbox_overlaps as jnp_overlaps

    rng = np.random.RandomState(2)
    a = _rand_dets(rng, 40)[:, :4]
    b = _rand_dets(rng, 17)[:, :4]
    got = native.bbox_overlaps(a, b)
    want = np.asarray(jnp_overlaps(a, b))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_bbox_overlaps_backends_agree(has_native, monkeypatch):
    if not has_native:
        pytest.skip("native lib unavailable")
    rng = np.random.RandomState(3)
    a = _rand_dets(rng, 25)[:, :4]
    b = _rand_dets(rng, 31)[:, :4]
    got_native = native.bbox_overlaps(a, b)
    _numpy_backend(monkeypatch)
    np.testing.assert_allclose(got_native, native.bbox_overlaps(a, b),
                               atol=1e-6)


# ---- RLE --------------------------------------------------------------------


def _rand_mask(rng, h, w):
    # blobby mask: a few rectangles
    m = np.zeros((h, w), np.uint8)
    for _ in range(rng.randint(1, 4)):
        y, x = rng.randint(0, h), rng.randint(0, w)
        m[y:y + rng.randint(1, h + 1), x:x + rng.randint(1, w + 1)] = 1
    return m


def test_rle_roundtrip_and_area(has_native):
    rng = np.random.RandomState(4)
    for h, w in ((1, 1), (5, 7), (33, 21), (64, 64)):
        m = _rand_mask(rng, h, w)
        rle = native.encode(m)
        assert rle["size"] == [h, w]
        np.testing.assert_array_equal(native.decode(rle), m)
        assert native.area(rle) == int(m.sum())


def test_rle_golden_string():
    """Hand-verified COCO-format compressed counts (5-bit chunks + 48
    offset, delta-coded from index 3): a 3x3 block in a 5x7 canvas."""
    m = np.zeros((5, 7), np.uint8)
    m[1:4, 2:5] = 1
    rle = native.encode(m)
    # col-major counts: [11, 3, 2, 3, 2, 3, 11]
    assert rle["counts"] == b";320009"
    np.testing.assert_array_equal(native.decode(rle), m)


def test_rle_backends_agree(has_native, monkeypatch):
    if not has_native:
        pytest.skip("native lib unavailable")
    rng = np.random.RandomState(5)
    m1, m2 = _rand_mask(rng, 40, 30), _rand_mask(rng, 40, 30)
    r1n, r2n = native.encode(m1), native.encode(m2)
    iou_n = native.iou(r1n, r2n)
    merge_n = native.decode(native.merge([r1n, r2n]))
    bb_n = native.to_bbox(r1n)
    _numpy_backend(monkeypatch)
    r1p, r2p = native.encode(m1), native.encode(m2)
    assert r1n["counts"] == r1p["counts"]
    assert abs(iou_n - native.iou(r1p, r2p)) < 1e-12
    np.testing.assert_array_equal(merge_n,
                                  native.decode(native.merge([r1p, r2p])))
    np.testing.assert_array_equal(bb_n, native.to_bbox(r1p))


def test_rle_iou_dense_check(has_native):
    rng = np.random.RandomState(6)
    m1, m2 = _rand_mask(rng, 25, 25), _rand_mask(rng, 25, 25)
    r1, r2 = native.encode(m1), native.encode(m2)
    inter = np.logical_and(m1, m2).sum()
    union = np.logical_or(m1, m2).sum()
    assert abs(native.iou(r1, r2) - inter / union) < 1e-12
    # crowd semantics: denominator is the dt area
    assert abs(native.iou(r1, r2, iscrowd=True) - inter / m1.sum()) < 1e-12


def test_rle_merge_union_and_intersection(has_native):
    rng = np.random.RandomState(7)
    m1, m2 = _rand_mask(rng, 18, 22), _rand_mask(rng, 18, 22)
    r1, r2 = native.encode(m1), native.encode(m2)
    np.testing.assert_array_equal(
        native.decode(native.merge([r1, r2])), np.logical_or(m1, m2))
    np.testing.assert_array_equal(
        native.decode(native.merge([r1, r2], intersect=True)),
        np.logical_and(m1, m2))


def test_rle_to_bbox(has_native):
    m = np.zeros((10, 12), np.uint8)
    m[3:8, 4:9] = 1
    np.testing.assert_array_equal(native.to_bbox(native.encode(m)),
                                  [4, 3, 5, 5])
    # empty mask
    np.testing.assert_array_equal(
        native.to_bbox(native.encode(np.zeros((4, 4), np.uint8))),
        [0, 0, 0, 0])


def test_rle_from_bbox_and_poly(has_native):
    # integer-aligned box: exact pixel coverage
    rle = native.from_bbox([2, 1, 3, 4], 8, 10)
    m = native.decode(rle)
    want = np.zeros((8, 10), np.uint8)
    want[1:5, 2:5] = 1
    np.testing.assert_array_equal(m, want)
    # triangle: area approximately half the bounding square
    tri = native.from_poly([0, 0, 0, 20, 20, 20], 20, 20)
    a = native.area(tri)
    assert abs(a - 200) < 25


def test_rle_string_codec_large_counts(has_native):
    """Counts that need multiple 5-bit chunks (and negative deltas)."""
    m = np.zeros((100, 90), np.uint8)
    m[50:, :] = 1
    m[0, 0] = 1
    rle = native.encode(m)
    np.testing.assert_array_equal(native.decode(rle), m)
    assert native.area(rle) == int(m.sum())


@pytest.mark.parametrize("backend", ["native", "numpy"])
def test_iou_matrix_matches_pairwise(backend, monkeypatch):
    """Batched rle_iou_matrix == pairwise iou, incl. crowd columns, on
    random masks; empty-side cases return empty matrices.  Runs on both
    the native and the NumPy-fallback backend."""
    from mx_rcnn_tpu import native

    rng = np.random.RandomState(3)
    h = w = 40

    def rand_rle():
        m = np.zeros((h, w), np.uint8)
        x1, y1 = rng.randint(0, 25, 2)
        m[y1:y1 + rng.randint(5, 15), x1:x1 + rng.randint(5, 15)] = 1
        return native.encode(m)

    dts = [rand_rle() for _ in range(5)]
    gts = [rand_rle() for _ in range(4)]
    crowd = np.array([False, True, False, True])
    want = np.array([[native.iou(d, g, bool(c))
                      for g, c in zip(gts, crowd)] for d in dts])
    if backend == "numpy":
        _numpy_backend(monkeypatch)
    got = native.iou_matrix(dts, gts, crowd)
    np.testing.assert_allclose(got, want, rtol=0, atol=0)
    assert native.iou_matrix([], gts).shape == (0, 4)
    assert native.iou_matrix(dts, []).shape == (5, 0)
    with pytest.raises(ValueError, match="crowd flags"):
        native.iou_matrix(dts, gts, [True])
