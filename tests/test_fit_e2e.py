"""The closed loop: train -> checkpoint -> evaluate -> mAP, end to end.

This is the framework's integration gate (VERDICT r1 item 1): a miniature
version of ``tools/train.py`` + ``tools/test.py`` on the synthetic dataset.
The full-size recipe (same code path, bigger canvas/epochs) reaches
mAP ≈ 0.84 (measured on a real v5e chip, 2026-07-30; occlusion between
solid rectangles caps the synthetic task's ceiling):

    python -m mx_rcnn_tpu.tools.train --network tiny --dataset synthetic \
        --end_epoch 48 --lr 0.003 --lr_step 40 --prefix model/syn
    python -m mx_rcnn_tpu.tools.test --network tiny --dataset synthetic \
        --prefix model/syn --epoch 48

The miniature here trains a few epochs on a small canvas and asserts the
loop produces real detections and a non-trivial mAP (loose bar: CI noise).
"""



import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.tools.test import test_rcnn as eval_rcnn
from mx_rcnn_tpu.tools.train import train_net
from mx_rcnn_tpu.utils.checkpoint import checkpoint_path
from tests.conftest import shrink_tiny_cfg


def _cfg(tmp_path):
    cfg = generate_config(
        "tiny", "synthetic",
        dataset__root_path=str(tmp_path),
        dataset__dataset_path=str(tmp_path / "synthetic"),
        dataset__num_classes=4,
    )
    return shrink_tiny_cfg(cfg)


TRAIN_KW = dict(num_images=32, image_size=(128, 160), max_objects=3)
TEST_KW = dict(num_images=8, image_size=(128, 160), max_objects=3)


def test_train_checkpoint_eval_map(tmp_path):
    cfg = _cfg(tmp_path)
    prefix = str(tmp_path / "model" / "e2e")
    epochs = 16
    train_net(cfg, prefix=prefix, end_epoch=epochs, lr=3e-3,
              lr_step="14", frequent=1000, seed=0, dataset_kw=TRAIN_KW)
    # per-epoch checkpoints exist
    for e in (1, epochs):
        assert os.path.exists(checkpoint_path(prefix, e))
    results = eval_rcnn(cfg, prefix=prefix, epoch=epochs, verbose=False,
                        dataset_kw=TEST_KW)
    assert "mAP" in results
    # loose learning bar: untrained models measure ~0.0; the full-size
    # recipe reaches 0.86+ (docstring), the miniature must clear real signal
    assert results["mAP"] >= 0.25, results
    # eval must also run (and be worse) on an early checkpoint
    early = eval_rcnn(cfg, prefix=prefix, epoch=1, verbose=False,
                      dataset_kw=TEST_KW)
    assert early["mAP"] <= results["mAP"] + 0.15


def test_resume_continues_training(tmp_path):
    cfg = _cfg(tmp_path)
    prefix = str(tmp_path / "model" / "res")
    train_net(cfg, prefix=prefix, end_epoch=2, lr=1e-3, lr_step="10",
              frequent=1000, seed=0, dataset_kw=TRAIN_KW)
    # resume from epoch 2 and run one more epoch
    state = train_net(cfg, prefix=prefix, begin_epoch=2, end_epoch=3,
                      lr=1e-3, lr_step="10", frequent=1000, seed=0,
                      dataset_kw=TRAIN_KW)
    assert os.path.exists(checkpoint_path(prefix, 3))
    steps_per_epoch = 32  # 32 images, batch 1
    assert int(state.step) == 3 * steps_per_epoch


@pytest.mark.parametrize("device_cache", [False, True])
def test_sigterm_interrupt_resume_bit_exact(tmp_path, device_cache):
    """Preemption path: stop mid-epoch via stop_flag, restore the interrupt
    checkpoint with --resume semantics, continue — final params must be
    BIT-IDENTICAL to an uninterrupted run (deterministic shuffle + RNG
    folded on state.step make mid-epoch resume exact).  Runs for both the
    streaming loader and the HBM epoch cache (whose gather index IS
    state.step, so the restored run replays the exact batch sequence and
    the epoch-keyed on-device shuffle is deterministic across the
    interruption)."""
    import jax

    from mx_rcnn_tpu.utils.checkpoint import interrupt_path

    cfg = _cfg(tmp_path)
    kw = dict(end_epoch=2, lr=0.001, dataset_kw=TRAIN_KW, seed=3,
              device_cache=device_cache)

    # uninterrupted reference run
    ref = train_net(cfg, prefix=str(tmp_path / "m" / "ref"), **kw)

    # interrupted run: stop after 5 steps of epoch 0
    counter = {"n": 0}

    def stop_after_5():
        counter["n"] += 1
        return counter["n"] > 5

    prefix = str(tmp_path / "m" / "pre")
    train_net(cfg, prefix=prefix, stop_flag=stop_after_5, **kw)
    assert os.path.exists(interrupt_path(prefix))

    # resume and finish
    final = train_net(cfg, prefix=prefix, resume=True, **kw)
    assert not os.path.exists(interrupt_path(prefix))  # superseded

    assert int(final.step) == int(ref.step)
    assert jax.tree.structure(ref.params) == jax.tree.structure(final.params)
    for a, b in zip(jax.tree.leaves(ref.params),
                    jax.tree.leaves(final.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_stop_on_last_batch_of_epoch_writes_epoch_checkpoint(tmp_path):
    """SIGTERM landing on an epoch's last batch must finish the epoch
    normally (epoch checkpoint written, no interrupt file) and stop at the
    boundary — resume then starts cleanly at the next epoch."""
    from mx_rcnn_tpu.utils.checkpoint import interrupt_path

    cfg = _cfg(tmp_path)
    prefix = str(tmp_path / "m" / "edge")
    counter = {"n": 0}
    # 32 images / batch 1 = 32 steps/epoch; fire exactly on step 32
    def stop_on_last(spe=32):
        counter["n"] += 1
        return counter["n"] >= spe

    state = train_net(cfg, prefix=prefix, stop_flag=stop_on_last,
                      end_epoch=3, lr=0.001, dataset_kw=TRAIN_KW, seed=1)
    assert int(state.step) == 32
    assert os.path.exists(checkpoint_path(prefix, 1))
    assert not os.path.exists(interrupt_path(prefix))
    # resume continues from epoch 1 without skipping
    final = train_net(cfg, prefix=prefix, resume=True, end_epoch=2,
                      lr=0.001, dataset_kw=TRAIN_KW, seed=1)
    assert int(final.step) == 64
    assert os.path.exists(checkpoint_path(prefix, 2))

