"""Benchmark: ResNet-101 Faster R-CNN end-to-end training throughput.

Prints ONE JSON line:
  {"metric": "imgs_per_sec_per_chip", "value": N, "unit": "imgs/s",
   "vs_baseline": N, "measured": true}
(``"measured"`` is the provenance discriminator: false — with
``"value": null`` — on the degraded path below.)

Outage protocol (VERDICT r03 item 1): the tunneled chip can hang during
backend init or go Unavailable for hours; round 3's bench died with a bare
traceback and produced no number.  The default entry point is therefore a
SUPERVISOR that runs the measurement in a fresh subprocess per attempt
(``bench.py --once``) with a hard per-attempt timeout (a hung backend init
cannot wedge the run), retries transient failures with backoff across a
long window (``BENCH_RETRY_WINDOW_S``, default 1 h — kept short because
the driver's own bench timeout would kill a longer wait anyway; raise it
for unattended captures, see ``supervise()``), and — if the window closes
without a measurement — emits a STRUCTURED degraded line instead of a
traceback: ``"value": null`` with ``"measured": false``/``"degraded":
true``, the last independently verified numbers under ``last_verified_*``
keys, plus ``"failure"`` and ``"value_source"`` so the record is honest
about its provenance.  Non-transient child errors (real bugs)
bail to the degraded line immediately instead of burning the window.

Baseline (BASELINE.md): the reference's community-reported throughput on a
P100-class GPU for ResNet-101 @ short-side 600 is ~2-4 img/s; the north star
is >= 1x P100 imgs/sec/chip, so vs_baseline is measured against 3.0 img/s
(the midpoint).

Config matches BASELINE.json config 5 per chip: ResNet-101 end2end, COCO
81 classes, per-chip batch 2, 608x1024 bucket, bf16 activations, full train
step (anchor targets, proposal NMS 6000->2000 — the adopted recipe default
since round 4; rounds <=3 benched the ref's 12000 — ROI sampling, ROIAlign,
backward, SGD) — all in one XLA program, synthetic data.

Timing notes: steps chain through the donated TrainState, so the loop is
device-serialized; the measured host<->device round-trip (~100 ms on a
tunneled chip) is subtracted once.

After the headline, a SUSTAINED end-to-end section runs the full input
pipeline (decoded-uint8 host cache -> HBM-resident epoch cache -> cached
train step with on-device reshuffle, data/device_cache.py) for 3 epochs
and reports imgs/s next to the device-only number, plus the standalone
host-loader rate and the one-time staging cost on stderr; the JSON line
gains a "sustained_imgs_per_sec" key (VERDICT r02 item 1).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

# Last independently verified numbers, reported (with provenance) only on
# the degraded path when no live measurement could be captured.
_LAST_VERIFIED = {
    "value": 76.9,              # r5 chip_battery live capture, 2026-07-31
    "sustained": 76.7,          # same run (HBM epoch cache, 1.00x device)
    "source": ("last verified: round-5 chip_battery live capture "
               "(76.9 imgs/s headline / 76.7 sustained, adopted pre-NMS "
               "6000 recipe); post-capture in-session bests reached "
               "79-81 imgs/s after the r5 anchor-subsample fix "
               "(docs/PERF.md round-5 section)"),
}


def bench_loader(loader) -> float:
    """Standalone host input pipeline imgs/s (no device in the loop)."""
    n = sum(b.images.shape[0] for b in loader)  # warm cache + page cache
    t0 = time.perf_counter()
    n = sum(b.images.shape[0] for b in loader)
    dt = time.perf_counter() - t0
    return n / dt


def _transient(e: Exception) -> bool:
    """Errors worth retrying on the tunneled chip; real configuration
    errors (unknown backend, bad flags) must surface immediately."""
    msg = str(e)
    return ("Unavailable" in msg or "UNAVAILABLE" in msg
            or "remote_compile" in msg or "response body" in msg)


def _wait_for_device(max_wait_s: float = 300.0):
    """The tunneled chip intermittently reports 'TPU backend setup/compile
    error (Unavailable)'; retry backend init for a few minutes before
    giving up so a transient outage doesn't void the whole benchmark."""
    import jax

    deadline = time.monotonic() + max_wait_s
    while True:
        try:
            return jax.devices()
        except RuntimeError as e:
            if time.monotonic() > deadline or not _transient(e):
                raise
            first = (str(e).splitlines() or [""])[0][:80]
            print(f"device unavailable ({first}); retrying...",
                  file=sys.stderr)
            time.sleep(20.0)


def run_once() -> None:
    """One full measurement attempt (runs in a fresh subprocess)."""
    import jax
    import jax.numpy as jnp

    print(f"devices: {_wait_for_device()}", file=sys.stderr)

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.core.train import make_train_step, setup_training
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.tools.profile_step import make_batch

    batch_images = 2
    h, w = 608, 1024
    cfg = generate_config("resnet101", "coco")
    # pre-NMS 6000 is the adopted recipe default (script/resnet_coco.sh):
    # measured mAP-neutral and ~16% faster than the ref's 12000 on this
    # stack (docs/PERF.md round 3) — the bench measures what the recipe
    # ships
    cfg = cfg.replace_in("train", batch_images=batch_images,
                         rpn_pre_nms_top_n=6000)
    model = build_model(cfg)

    key = jax.random.PRNGKey(0)
    # uint8 raw batch — the production loader layout (device-side
    # normalization); headline and sustained sections share ONE program
    batch = make_batch(cfg, batch_images, h, w, seed=0, raw=True)

    def fetch(x):
        return np.asarray(x).ravel()[:1]

    # host<->device round-trip floor (tunneled devices: ~100 ms); min of a
    # few probes — a single sample is jittery and would skew the subtraction
    tiny = jax.jit(lambda c: c + 1.0)
    fetch(tiny(jnp.float32(0)))
    probes = []
    for _ in range(3):
        t0 = time.perf_counter()
        fetch(tiny(jnp.float32(0)))
        probes.append(time.perf_counter() - t0)
    rtt = min(probes)
    print(f"fetch round-trip: {rtt * 1e3:.1f} ms", file=sys.stderr)

    print("initializing model...", file=sys.stderr)
    state, tx = setup_training(model, cfg, key, (batch_images, h, w, 3),
                               steps_per_epoch=10_000)
    # donate the state: updates happen in place in HBM, no copy per step
    step = jax.jit(make_train_step(model, cfg, tx), donate_argnums=(0,))

    print("compiling + warmup...", file=sys.stderr)
    t0 = time.perf_counter()
    # the donated state needs a fresh copy per retry attempt
    for attempt in range(3):
        try:
            s2, metrics = step(jax.tree.map(jnp.copy, state), batch, key)
            fetch(metrics["loss"])
            state = s2
            break
        except Exception as e:
            if attempt == 2 or not _transient(e):
                raise
            print(f"warmup retry ({e})", file=sys.stderr)
            time.sleep(10.0)
    for _ in range(2):
        state, metrics = step(state, batch, key)
    fetch(metrics["loss"])
    print(f"warmup done in {time.perf_counter() - t0:.1f}s; "
          f"loss={float(metrics['loss']):.3f}", file=sys.stderr)

    iters = 50
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch, key)
    fetch(metrics["loss"])
    dt = time.perf_counter() - t0 - rtt

    imgs_per_sec = batch_images * iters / dt
    print(f"step time: {dt / iters * 1e3:.2f} ms", file=sys.stderr)

    # ---- sustained end-to-end: full input pipeline in the loop ---------
    # Host: decoded-uint8 image cache (data/cache.py) assembles batches.
    # Device: the epoch is staged ONCE in HBM (data/device_cache.py) and
    # each step gathers its batch on device — steady-state host↔device
    # traffic is one dispatch RPC per step, which is what a high-latency
    # tunneled link needs (docs/PERF.md "input pipeline").  VERDICT r02
    # item 1: sustained must be reported next to the device-only number.
    sustained = None
    try:
        import tempfile

        from mx_rcnn_tpu.core.train import make_train_step
        from mx_rcnn_tpu.data.cache import DecodedImageCache
        from mx_rcnn_tpu.data.device_cache import (build_caches,
                                                   make_cached_step)
        from mx_rcnn_tpu.data.loader import AnchorLoader
        from mx_rcnn_tpu.data.synthetic import SyntheticDataset

        with tempfile.TemporaryDirectory() as root:
            ds = SyntheticDataset("train", root, "", num_images=64,
                                  image_size=(600, 800))
            roidb = ds.gt_roidb()
            cache = DecodedImageCache(ram_bytes=1 << 30)
            loader = AnchorLoader(roidb, cfg, shuffle=False, cache=cache)
            loader_ips = bench_loader(loader)
            print(f"host loader (cached): {loader_ips:.1f} imgs/s "
                  f"({loader_ips / imgs_per_sec:.1f}x device rate)",
                  file=sys.stderr)
            # stage the epoch in HBM; first upload of a new shape compiles
            # a layout program — warm it before timing (compile, not
            # steady state)
            epoch = build_caches(loader)[0]
            print(f"epoch cache: {epoch.num_batches} batches, "
                  f"{epoch.nbytes / 1e6:.0f} MB HBM", file=sys.stderr)
            cstep = jax.jit(
                make_cached_step(make_train_step(model, cfg, tx),
                                 epoch.num_batches),
                donate_argnums=(0, 2))
            idx = epoch.index_handle()
            # compile + warm; the tunneled remote-compile endpoint is
            # occasionally flaky — retry before giving up on sustained.
            # cstep donates state+idx, so every attempt gets fresh copies
            # (a failed attempt leaves donated buffers deleted)
            for attempt in range(3):
                try:
                    s2, i2, metrics = cstep(
                        jax.tree.map(jnp.copy, state), epoch.data,
                        jnp.copy(idx), key)
                    state, idx = s2, i2
                    fetch(metrics["loss"])
                    break
                except Exception as e:
                    if attempt == 2 or not _transient(e):
                        raise
                    print(f"cached-step warmup retry ({e})", file=sys.stderr)
                    time.sleep(5.0)
            # free the warmup epoch before staging the timed one: keeping
            # both alive doubles resident HBM and tunnel upload for no
            # benefit (advisor r3)
            for leaf in jax.tree.leaves(epoch.data):
                leaf.delete()
            epoch = None
            # one-time staging cost (host assembly + upload of FRESH bytes;
            # the tunnel moves new data at ~11 MB/s, so this is the run's
            # fixed cost — disclosed, then amortized away by multi-epoch
            # training from the resident copy)
            t0 = time.perf_counter()
            epoch2 = build_caches(loader)[0]
            jax.block_until_ready(epoch2.data)
            stage_s = time.perf_counter() - t0
            print(f"one-time staging: {stage_s:.1f}s for "
                  f"{epoch2.nbytes / 1e6:.0f} MB "
                  f"({epoch2.nbytes / 1e6 / stage_s:.1f} MB/s tunnel)",
                  file=sys.stderr)
            epochs = 3
            n_steps = epochs * epoch2.num_batches
            t0 = time.perf_counter()
            for _ in range(n_steps):
                state, idx, metrics = cstep(state, epoch2.data, idx, key)
            fetch(metrics["loss"])
            dt_s = time.perf_counter() - t0 - rtt
            sustained = batch_images * n_steps / dt_s
            print(f"sustained e2e ({epochs} epochs from the HBM-resident "
                  f"set, on-device reshuffle): {sustained:.1f} imgs/s "
                  f"({sustained / imgs_per_sec:.2f}x device rate)",
                  file=sys.stderr)
    except Exception as e:  # auxiliary — never fail the headline
        print(f"sustained bench skipped: {e}", file=sys.stderr)

    p100_baseline = 3.0
    out = {
        "metric": "imgs_per_sec_per_chip",
        "value": round(imgs_per_sec, 3),
        "unit": "imgs/s",
        "vs_baseline": round(imgs_per_sec / p100_baseline, 3),
        "measured": True,
    }
    if sustained is not None:
        out["sustained_imgs_per_sec"] = round(sustained, 3)
    print(json.dumps(out))


def _parse_result(stdout: str):
    """The child's result is its last stdout line iff it parses as a JSON
    object with the expected metric key."""
    lines = [ln for ln in stdout.strip().splitlines() if ln.strip()]
    if not lines:
        return None
    try:
        obj = json.loads(lines[-1])
    except json.JSONDecodeError:
        return None
    return obj if isinstance(obj, dict) and "metric" in obj else None


def _degraded(failure: str) -> dict:
    # ``value`` is null, NOT the historical number: a consumer keying on
    # metric/value alone must not record an unmeasured figure as if live
    # (advisor r4).  The last independently verified numbers move to
    # explicit ``last_verified_*`` keys with their provenance.
    return {
        "metric": "imgs_per_sec_per_chip",
        "value": None,
        "unit": "imgs/s",
        "vs_baseline": None,
        "measured": False,
        "degraded": True,
        "last_verified_value": _LAST_VERIFIED["value"],
        "last_verified_vs_baseline": round(_LAST_VERIFIED["value"] / 3.0, 3),
        "last_verified_sustained_imgs_per_sec": _LAST_VERIFIED["sustained"],
        "value_source": _LAST_VERIFIED["source"],
        "failure": failure[:500],
    }


def _run_attempt(cmd, timeout: float):
    """Run one child, streaming its stderr through LIVE (an operator must
    be able to tell a hung backend from a slow warmup) while keeping a tail
    for failure classification.  Returns (rc, stdout, tail, timed_out);
    ``timed_out`` is the authoritative kill indicator (after the kill the
    child's rc reads -SIGKILL, a plain signal death)."""
    import collections
    import threading

    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    tail: "collections.deque[str]" = collections.deque(maxlen=40)
    out_chunks = []

    def pump(stream, sink):
        for line in stream:
            sink(line)

    def err_sink(line):
        sys.stderr.write(line)
        tail.append(line.rstrip("\n"))

    threads = [threading.Thread(target=pump, args=(proc.stderr, err_sink),
                                daemon=True),
               threading.Thread(target=pump,
                                args=(proc.stdout, out_chunks.append),
                                daemon=True)]
    for t in threads:
        t.start()
    timed_out = False
    try:
        proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        timed_out = True
        proc.kill()
        proc.wait()
    for t in threads:
        t.join(timeout=5.0)
    return proc.returncode, "".join(out_chunks), "\n".join(tail), timed_out


def supervise(child_cmd=None) -> dict:
    """Run measurement attempts in fresh subprocesses until one succeeds,
    the retry window closes, or a non-transient error appears.  Returns the
    dict to print (never raises).  ``child_cmd`` is overridable for tests.
    """
    # window default 1 h (not longer): a harness running this bench may
    # have its own timeout, and a kill beats a degraded line — the SIGTERM
    # trap in main() guarantees the line on a polite kill, but nothing
    # survives SIGKILL, so the default stays inside common patience;
    # raise BENCH_RETRY_WINDOW_S for long unattended captures
    window = float(os.environ.get("BENCH_RETRY_WINDOW_S", "3600"))
    attempt_timeout = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT_S", "2400"))
    deadline = time.monotonic() + window
    cmd = child_cmd or [sys.executable, os.path.abspath(__file__), "--once"]
    attempt = 0
    while True:
        attempt += 1
        rc, stdout, tail, timed_out = _run_attempt(cmd, attempt_timeout)
        result = _parse_result(stdout)
        if result is not None:
            # accept even from a killed/failed child: run_once prints its
            # JSON only after a complete measurement, so a child that hung
            # in TEARDOWN (the tunnel's known pathology) still measured
            return result
        if timed_out:
            # a hung backend init — round 3's actual failure mode
            last_failure = (f"attempt {attempt} exceeded the "
                            f"{attempt_timeout:.0f}s per-attempt timeout "
                            f"(hung backend?)")
            transient = True
        else:
            last_failure = f"attempt {attempt} rc={rc}: " + tail[-400:]
            # signal deaths (rc<0: OOM-kill, runtime abort) and silent
            # crashes carry no diagnosable message — treat as environment
            # trouble and keep retrying; only a recognizable non-transient
            # Python error (ImportError etc.) stops burning the window
            transient = rc < 0 or not tail.strip() or _transient(tail)
        print(f"bench: {last_failure.splitlines()[0][:120]}",
              file=sys.stderr)
        if not transient:
            print("bench: error looks non-transient; not retrying",
                  file=sys.stderr)
            return _degraded(last_failure)
        # escalating backoff, capped; a fast crash-loop still paces itself
        backoff = min(300.0, 15.0 * attempt)
        remaining = deadline - time.monotonic()
        if remaining <= backoff + 30.0:
            # not enough window left for a sleep AND a meaningful attempt —
            # don't overshoot the window by another full attempt_timeout
            print("bench: retry window exhausted", file=sys.stderr)
            return _degraded(last_failure)
        print(f"bench: retrying in {backoff:.0f}s "
              f"({remaining:.0f}s left in window)", file=sys.stderr)
        time.sleep(backoff)


def main() -> None:
    if "--once" in sys.argv:
        run_once()
        return
    # a harness impatient with the retry window may SIGTERM the
    # supervisor: emit the degraded line on the way out so the run STILL
    # produces a parseable record (SIGKILL is unsurvivable — the default
    # window stays modest for that reason)
    import signal

    def on_term(signum, frame):
        print(json.dumps(_degraded(
            f"supervisor received signal {signum} before a measurement "
            f"completed")))
        sys.stdout.flush()
        os._exit(0)

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    print(json.dumps(supervise()))


if __name__ == "__main__":
    main()

