"""Benchmark: ResNet-101 Faster R-CNN end-to-end training throughput.

Prints ONE JSON line:
  {"metric": "imgs_per_sec_per_chip", "value": N, "unit": "imgs/s", "vs_baseline": N}

Baseline (BASELINE.md): the reference's community-reported throughput on a
P100-class GPU for ResNet-101 @ short-side 600 is ~2-4 img/s; the north star
is >= 1x P100 imgs/sec/chip, so vs_baseline is measured against 3.0 img/s
(the midpoint).

Config matches BASELINE.json config 5 per chip: ResNet-101 end2end, COCO
81 classes, per-chip batch 2, 608x1024 bucket, bf16 activations, full train
step (anchor targets, proposal NMS 12000->2000, ROI sampling, ROIAlign,
backward, SGD) — all in one XLA program, synthetic data.

Timing notes: steps chain through the donated TrainState, so the loop is
device-serialized; the measured host<->device round-trip (~100 ms on a
tunneled chip) is subtracted once.  Auxiliary lines on stderr report the
host loader's standalone throughput (images decoded+assembled per second)
so loader-vs-device headroom is visible (VERDICT r01 item 8).
"""

import json
import sys
import time

import numpy as np


def bench_loader() -> float:
    """Host input pipeline imgs/s on synthetic data (decode+resize+pad)."""
    import tempfile

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.data.loader import AnchorLoader
    from mx_rcnn_tpu.data.synthetic import SyntheticDataset

    cfg = generate_config("resnet101", "coco")
    cfg = cfg.replace_in("train", batch_images=2)
    with tempfile.TemporaryDirectory() as root:
        ds = SyntheticDataset("train", root, "", num_images=64,
                              image_size=(600, 800))
        roidb = ds.gt_roidb()
        loader = AnchorLoader(roidb, cfg, shuffle=False)
        n = sum(b.images.shape[0] for b in loader)  # warm page cache
        t0 = time.perf_counter()
        n = sum(b.images.shape[0] for b in loader)
        dt = time.perf_counter() - t0
    return n / dt


def main() -> None:
    import jax
    import jax.numpy as jnp

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.core.train import make_train_step, setup_training
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.tools.profile_step import make_batch

    batch_images = 2
    h, w = 608, 1024
    cfg = generate_config("resnet101", "coco")
    cfg = cfg.replace_in("train", batch_images=batch_images)
    model = build_model(cfg)

    key = jax.random.PRNGKey(0)
    batch = make_batch(cfg, batch_images, h, w, seed=0)

    def fetch(x):
        return np.asarray(x).ravel()[:1]

    # host<->device round-trip floor (tunneled devices: ~100 ms); min of a
    # few probes — a single sample is jittery and would skew the subtraction
    tiny = jax.jit(lambda c: c + 1.0)
    fetch(tiny(jnp.float32(0)))
    probes = []
    for _ in range(3):
        t0 = time.perf_counter()
        fetch(tiny(jnp.float32(0)))
        probes.append(time.perf_counter() - t0)
    rtt = min(probes)
    print(f"fetch round-trip: {rtt * 1e3:.1f} ms", file=sys.stderr)

    print("initializing model...", file=sys.stderr)
    state, tx = setup_training(model, cfg, key, (batch_images, h, w, 3),
                               steps_per_epoch=10_000)
    # donate the state: updates happen in place in HBM, no copy per step
    step = jax.jit(make_train_step(model, cfg, tx), donate_argnums=(0,))

    print("compiling + warmup...", file=sys.stderr)
    t0 = time.perf_counter()
    for _ in range(3):
        state, metrics = step(state, batch, key)
    fetch(metrics["loss"])
    print(f"warmup done in {time.perf_counter() - t0:.1f}s; "
          f"loss={float(metrics['loss']):.3f}", file=sys.stderr)

    iters = 50
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch, key)
    fetch(metrics["loss"])
    dt = time.perf_counter() - t0 - rtt

    imgs_per_sec = batch_images * iters / dt
    print(f"step time: {dt / iters * 1e3:.2f} ms", file=sys.stderr)

    try:
        loader_ips = bench_loader()
        print(f"host loader: {loader_ips:.1f} imgs/s "
              f"({loader_ips / imgs_per_sec:.1f}x device rate)",
              file=sys.stderr)
    except Exception as e:  # loader bench is auxiliary — never fail the run
        print(f"loader bench skipped: {e}", file=sys.stderr)

    p100_baseline = 3.0
    out = {
        "metric": "imgs_per_sec_per_chip",
        "value": round(imgs_per_sec, 3),
        "unit": "imgs/s",
        "vs_baseline": round(imgs_per_sec / p100_baseline, 3),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
