#!/usr/bin/env bash
# Accuracy gauntlet recipe: seed-stable mAP on the hard synthetic set plus
# the two ablations (no downloads needed; CPU-runnable).  Results append to
# data/gauntlet/results.json / ablations.json; --markdown renders the
# docs table.  See docs/GAUNTLET.md for the recorded numbers and the
# environment-sensitivity note before comparing across machines.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m mx_rcnn_tpu.tools.gauntlet \
  --seeds 0 1 2 --mode e2e \
  --markdown docs/GAUNTLET.md "$@"

python -m mx_rcnn_tpu.tools.gauntlet \
  --seeds 0 1 2 --mode prenms \
  --out data/gauntlet/ablations.json "$@"

python -m mx_rcnn_tpu.tools.gauntlet \
  --seeds 0 1 --mode alternate \
  --out data/gauntlet/ablations.json "$@"
