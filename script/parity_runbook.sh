#!/usr/bin/env bash
# parity_runbook.sh — the ONE command from "real data appeared" to a
# mAP-parity verdict (VERDICT r5 next-round item 8).
#
# The north-star metric (BASELINE.md): VOC07-test mAP, ResNet-101 end2end
# trained on VOC07+12, within 0.5 pt of the reference's ~79.3.  It has
# been environment-blocked every round (no VOCdevkit, no network, no real
# ImageNet weights).  This script IS the unblock path: run it each round;
# while the environment is still blocked it reports exactly what is
# missing and exits 2; the day the mounts are populated it runs the whole
# pipeline — pretrained import (zero-unmatched gate) → train → eval →
# ±0.5 pt comparison — and exits 0/1 on the verdict.
#
# Usage:
#   bash script/parity_runbook.sh [--quick]
# Env overrides:
#   PRETRAINED=<mxnet .params path/prefix for the resnet-101 backbone>
#   PRETRAINED_EPOCH=<epoch suffix, default 0>
#   REF_MAP=<reference mAP to compare against, default 79.3>
#   TOLERANCE=<points, default 0.5>
#   QUANT=0           skip the quantized-parity leg (step 5; default on)
#   QUANT_TOLERANCE=<points the int8 eval may lose vs the fp eval, 0.5>
set -uo pipefail
cd "$(dirname "$0")/.."

REF_MAP="${REF_MAP:-79.3}"
TOLERANCE="${TOLERANCE:-0.5}"
QUANT="${QUANT:-1}"
QUANT_TOLERANCE="${QUANT_TOLERANCE:-0.5}"
PRETRAINED="${PRETRAINED:-data/pretrained/resnet-101}"
PRETRAINED_EPOCH="${PRETRAINED_EPOCH:-0}"
PREFIX="model/parity_resnet101_voc0712"
EPOCHS=10

# --------------------------------------------------------------------------
# Step 0: each-round environment check (kept here so the check cannot rot)
# --------------------------------------------------------------------------
blocked=0
if [ ! -d data/VOCdevkit/VOC2007 ] || [ ! -d data/VOCdevkit/VOC2012 ]; then
  echo "BLOCKED: data/VOCdevkit/{VOC2007,VOC2012} not found (need the"
  echo "         reference devkit layout: Annotations/ ImageSets/ JPEGImages/)"
  blocked=1
fi
if ! ls "${PRETRAINED}"* >/dev/null 2>&1; then
  echo "BLOCKED: no pretrained backbone at '${PRETRAINED}*'"
  echo "         (set PRETRAINED=<prefix of an MXNet resnet-101 .params>)"
  blocked=1
fi
if [ -d /root/reference ] && [ -z "$(find /root/reference -type f 2>/dev/null | head -1)" ]; then
  echo "note: /root/reference mount is still empty (SURVEY §0 re-run pends)"
fi
if [ "$blocked" -ne 0 ]; then
  echo
  echo "parity verdict: BLOCKED — populate the paths above and re-run."
  echo "Nothing else is required; this script performs import, training,"
  echo "eval and the ±${TOLERANCE} pt comparison end to end."
  exit 2
fi

# --------------------------------------------------------------------------
# Step 1: pretrained import with the zero-unmatched gate
# (utils/pretrained.py raises unless EVERY backbone leaf is covered, both
# directions — a cheap dry run before committing to training)
# --------------------------------------------------------------------------
echo "== step 1/5: pretrained import gate =="
python - "$PRETRAINED" "$PRETRAINED_EPOCH" <<'EOF' || exit 1
import sys
import jax
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.train import setup_training
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.utils.pretrained import load_pretrained_into

cfg = generate_config("resnet101", "PascalVOC")
model = build_model(cfg)
state, _ = setup_training(model, cfg, jax.random.PRNGKey(0),
                          (1, 608, 1024, 3), steps_per_epoch=1)
load_pretrained_into(state, sys.argv[1], int(sys.argv[2]), cfg)
print("pretrained import: zero-unmatched gate PASSED")
EOF

# --------------------------------------------------------------------------
# Step 2: train the canonical VOC07+12 recipe (script/resnet_voc0712.sh
# schedule; --quick shrinks epochs for a pipeline shakeout, NOT a verdict)
# --------------------------------------------------------------------------
if [ "${1:-}" = "--quick" ]; then EPOCHS=1; fi
echo "== step 2/5: training resnet101 VOC07+12 e2e (${EPOCHS} epochs) =="
python -m mx_rcnn_tpu.tools.train \
  --network resnet101 --dataset PascalVOC \
  --image_set 2007_trainval+2012_trainval \
  --pretrained "$PRETRAINED" --pretrained_epoch "$PRETRAINED_EPOCH" \
  --prefix "$PREFIX" --end_epoch "$EPOCHS" --lr 0.001 --lr_step 7 \
  || exit 1

# --------------------------------------------------------------------------
# Step 3: evaluate on VOC07 test
# --------------------------------------------------------------------------
echo "== step 3/5: evaluating on 2007_test =="
MAP_LINE=$(python -m mx_rcnn_tpu.tools.test \
  --network resnet101 --dataset PascalVOC --image_set 2007_test \
  --prefix "$PREFIX" --epoch "$EPOCHS" | tee /dev/stderr | grep '^mAP = ')
MAP=$(echo "$MAP_LINE" | sed 's/mAP = //')

# --------------------------------------------------------------------------
# Step 4: the verdict
# --------------------------------------------------------------------------
echo "== step 4/5: parity verdict =="
python - "$MAP" "$REF_MAP" "$TOLERANCE" <<'EOF' || exit 1
import sys
map_pct, ref, tol = float(sys.argv[1]) * 100, float(sys.argv[2]), \
    float(sys.argv[3])
delta = map_pct - ref
print(f"measured mAP {map_pct:.2f} vs reference {ref:.2f} "
      f"(delta {delta:+.2f} pt, tolerance ±{tol} pt)")
if delta >= -tol:
    print("parity verdict: PASS")
    sys.exit(0)
print("parity verdict: FAIL")
sys.exit(1)
EOF

# --------------------------------------------------------------------------
# Step 5: quantized-parity leg (docs/PERF.md "Quantized inference") —
# the SAME checkpoint evaluated through the int8 inference forward
# (calibration sweep on the training split), gated at ±QUANT_TOLERANCE
# of the fp mAP just measured.  This is the real-data twin of the
# synthetic quant gauntlet (`make quant-smoke`; tools/gauntlet.py
# --compare e2e quant) that runs the day data/weights appear.
# --------------------------------------------------------------------------
if [ "$QUANT" = "0" ]; then
  echo "== step 5/5: quantized-parity leg SKIPPED (QUANT=0) =="
  exit 0
fi
echo "== step 5/5: quantized-parity leg (int8 eval of the same ckpt) =="
QMAP_LINE=$(python -m mx_rcnn_tpu.tools.test \
  --network resnet101 --dataset PascalVOC --image_set 2007_test \
  --prefix "$PREFIX" --epoch "$EPOCHS" \
  --set quant__enabled=true | tee /dev/stderr | grep '^mAP = ')
QMAP=$(echo "$QMAP_LINE" | sed 's/mAP = //')
python - "$MAP" "$QMAP" "$QUANT_TOLERANCE" <<'EOF'
import sys
fp, q, tol = (float(v) for v in sys.argv[1:4])
delta = (q - fp) * 100
print(f"quantized mAP {q * 100:.2f} vs fp {fp * 100:.2f} "
      f"(delta {delta:+.2f} pt, tolerance -{tol} pt)")
if delta >= -tol:
    print("quantized-parity verdict: PASS")
    sys.exit(0)
print("quantized-parity verdict: FAIL")
sys.exit(1)
EOF
