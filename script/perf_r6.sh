#!/usr/bin/env bash
# Round-6 queued perf captures (fire the moment the chip answers):
#
#   1. batch-8 stage table, N=16 unrolled chains (VERDICT r5 weak #2):
#      per-stage attribution for the 92.7 imgs/s batch-8 headline, so the
#      next perf lever is a measurement, not a guess.
#   2. refresh the r2-era "Other configs" rows (VERDICT r5 weak #3):
#      VGG16 VOC07 (BASELINE config 1) and ResNet-50 under the CURRENT
#      recipe (pre-NMS 6000, bf16 momentum, anchor-subsample fix).
#
# Both are single commands over existing tools; results go into
# docs/PERF.md ("Round-6" section).  Run on a host that sees the v5e
# chip (this repo's dev box lost it mid-round — see PERF.md).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== waiting for a non-CPU jax device =="
python - <<'EOF'
import jax
d = jax.devices()[0]
print("device:", d.platform, d.device_kind)
assert d.platform != "cpu", "no accelerator visible — do not record CPU numbers"
EOF

echo "== 1. batch-8 stage table (N=16, adopted 6000 recipe) =="
python -m mx_rcnn_tpu.tools.profile_step --network resnet101 --dataset coco \
    --batch_images 8 --iters 16 --prenms 6000

echo "== 2a. VGG16 VOC07 row refresh (current recipe) =="
python -m mx_rcnn_tpu.tools.profile_step --network vgg --dataset PascalVOC \
    --batch_images 2 --iters 16 --prenms 6000

echo "== 2b. ResNet-50 row refresh (current recipe) =="
python -m mx_rcnn_tpu.tools.profile_step --network resnet50 --dataset coco \
    --batch_images 2 --iters 16 --prenms 6000
