#!/usr/bin/env bash
# Round-6 queued perf battery — fire the moment the chip answers.
#
# Every A/B needed to adopt-or-refuse each r6 lever with FULL-STEP deltas
# (PERF.md "Round-6" has the designs; VERDICT r5 ranks the motivation):
#
#   1. batch-8 stage table, N=16 unrolled chains (VERDICT r5 weak #2):
#      per-stage attribution for the 92.7 imgs/s batch-8 headline.
#   2. blocked-ROIAlign A/B (the r6 tentpole lever): einsum pair vs the
#      ROI-chunked blocked backend at chunks 32/64/128 — stage AND full
#      step, batch 2 and batch 8 (the per-image-linear stages matter most
#      where the batch multiplies them).
#   3. batched-NMS A/B, THREE arms per batch size: the jnp backend must
#      be FORCED for the batched-vs-per_image arms because 'auto'
#      resolves both to the per-image Pallas kernel at these shapes
#      (k=6144, t=256 pass the lane/VMEM guards) — an auto-vs-auto A/B
#      would measure pallas-vs-pallas and report a vacuous ~0 delta:
#        a) per_image/auto   — the current champion (Pallas kernel),
#        b) per_image/jnp    — isolates the backend effect,
#        c) batched/jnp      — the r6 cross-image lever.
#      Adopt the batched sweep iff (c) beats (a) on the full step;
#      (c) vs (b) attributes how much comes from cross-image batching.
#   4. sublane-friendly bucket A/B: 608x1024 (38x64 feature grid — 38 is
#      sublane-hostile on the 8-sublane VPU) vs 640x1024 (40x64, +5.3%
#      pixels).  Adopt 640x1024 as the documented secondary bucket iff
#      the full step is ≥5% faster (beating the pixel tax); otherwise
#      record the refusal with both numbers.  Anchors/buckets regenerate
#      from the feature shape automatically (ops/anchors.py).
#   5. refresh the r2-era "Other configs" rows (VERDICT r5 weak #3):
#      VGG16 VOC07 (BASELINE config 1) and ResNet-50 under the CURRENT
#      recipe (pre-NMS 6000, bf16 momentum, anchor-subsample fix).
#
# All legs are single `tools/profile_step.py` invocations over landed
# tooling; results go into docs/PERF.md "Round-6".  Run on a host that
# sees the v5e chip.
#
# DEGRADED MODE (no accelerator): instead of dying, the script runs the
# CPU perf-smoke sanity leg (tiny model, N=2, --check: chain self-check +
# zero recompiles) and emits a BENCH-style outage record on stdout
# (`"measured": false, "degraded": true`, with the queued legs listed) so
# the capture queue is machine-readable — the bench outage protocol
# (bench.py _degraded) applied to the stage battery.
set -euo pipefail
cd "$(dirname "$0")/.."

if ! python - <<'EOF'
import jax
d = jax.devices()[0]
print("device:", d.platform, d.device_kind)
raise SystemExit(0 if d.platform != "cpu" else 1)
EOF
then
    echo "== no accelerator: degraded mode (CPU sanity + outage record) =="
    JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.profile_step \
        --network tiny --dataset synthetic --shape 128x160 \
        --batch_images 2 --iters 2 --check
    python - <<'EOF'
import json
print(json.dumps({
    "metric": "stage_ms_battery_r6",
    "value": None,
    "measured": False,
    "degraded": True,
    "failure": "no accelerator visible - do not record CPU numbers",
    "cpu_sanity": "perf-smoke passed (chain self-check + zero recompiles)",
    "queued": [
        "batch-8 stage table (N=16, prenms 6000)",
        "blocked ROIAlign A/B (chunk 32/64/128, batch 2+8, stage+full-step)",
        "batched NMS A/B (batched vs per_image, batch 2+8, full-step)",
        "bucket A/B 608x1024 vs 640x1024 (38x64 vs 40x64 grid)",
        "r2-era row refresh: VGG16 VOC07 + ResNet-50 (current recipe)",
    ],
}))
EOF
    exit 0
fi

echo "== 1. batch-8 stage table (N=16, adopted 6000 recipe) =="
python -m mx_rcnn_tpu.tools.profile_step --network resnet101 --dataset coco \
    --batch_images 8 --iters 16 --prenms 6000

echo "== 2. blocked ROIAlign A/B (stage + full step) =="
for bi in 2 8; do
    echo "-- batch ${bi}, einsum (baseline arm)"
    python -m mx_rcnn_tpu.tools.profile_step --network resnet101 \
        --dataset coco --batch_images "$bi" --iters 16 --prenms 6000 \
        --roi_backend jnp
    for chunk in 32 64 128; do
        echo "-- batch ${bi}, blocked chunk ${chunk}"
        python -m mx_rcnn_tpu.tools.profile_step --network resnet101 \
            --dataset coco --batch_images "$bi" --iters 16 --prenms 6000 \
            --roi_backend blocked --roi_chunk "$chunk"
    done
done

echo "== 3. batched NMS A/B (full step, 3 arms — see header) =="
for bi in 2 8; do
    for arm in "per_image auto" "per_image jnp" "batched jnp"; do
        set -- $arm
        echo "-- batch ${bi}, nms_mode $1, nms_backend $2"
        python -m mx_rcnn_tpu.tools.profile_step --network resnet101 \
            --dataset coco --batch_images "$bi" --iters 16 --prenms 6000 \
            --nms_mode "$1" --nms_backend "$2"
    done
done

echo "== 4. sublane-friendly bucket A/B: 608x1024 vs 640x1024 =="
for shape in 608x1024 640x1024; do
    echo "-- bucket ${shape}"
    python -m mx_rcnn_tpu.tools.profile_step --network resnet101 \
        --dataset coco --batch_images 2 --iters 16 --prenms 6000 \
        --shape "$shape"
done

echo "== 5a. VGG16 VOC07 row refresh (current recipe) =="
python -m mx_rcnn_tpu.tools.profile_step --network vgg --dataset PascalVOC \
    --batch_images 2 --iters 16 --prenms 6000

echo "== 5b. ResNet-50 row refresh (current recipe) =="
python -m mx_rcnn_tpu.tools.profile_step --network resnet50 --dataset coco \
    --batch_images 2 --iters 16 --prenms 6000
