#!/usr/bin/env bash
# Canonical recipe (ref script/vgg_voc07.sh): VGG16 Faster R-CNN end2end on
# VOC07 trainval, evaluated on VOC07 test.  BASELINE.json config 1/2.
# Expects VOCdevkit under data/ (ref layout: data/VOCdevkit/VOC2007).
set -euo pipefail
cd "$(dirname "$0")/.."

python -m mx_rcnn_tpu.tools.train \
  --network vgg --dataset PascalVOC --image_set 2007_trainval \
  --prefix model/vgg_voc07_e2e --end_epoch 10 --lr 0.001 --lr_step 7 \
  "$@"

python -m mx_rcnn_tpu.tools.test \
  --network vgg --dataset PascalVOC --image_set 2007_test \
  --prefix model/vgg_voc07_e2e --epoch 10
