#!/usr/bin/env bash
# Round-9 queued perf battery — the SUPERSET that owns every pending A/B.
# Fire the moment the chip answers.  Supersedes script/perf_r6.sh (its
# five legs are legs 1-5 here verbatim, still queued — the chip has been
# out since r6); legs 6-8 add the round-9 quantized-inference and
# backbone-layout levers (docs/PERF.md "Quantized inference").
#
#   1. batch-8 stage table, N=16 unrolled chains (VERDICT r5 weak #2).
#   2. blocked-ROIAlign A/B: einsum pair vs ROI-chunked blocked backend,
#      chunks 32/64/128, batch 2+8, stage AND full step.
#   3. batched-NMS A/B, THREE arms per batch size (jnp backend FORCED
#      for the batched arms — 'auto' resolves both to the per-image
#      Pallas kernel and would measure a vacuous pallas-vs-pallas ~0):
#        a) per_image/auto  b) per_image/jnp  c) batched/jnp.
#   4. sublane-friendly bucket A/B: 608x1024 (38x64 grid) vs 640x1024
#      (40x64, +5.3% pixels); adopt iff the full step wins >=5%.
#   5. r2-era "Other configs" row refresh (PERF.md table): VGG16 VOC07 +
#      ResNet-50 + the batch-4/8 rows under the CURRENT recipe.
#   6. QUANTIZED INFERENCE forward A/B (the r9 tentpole lever): fp vs
#      int8/native test-mode forward, batch 2 and 8, ResNet-101 AND
#      ResNet-50 — the serving-side fp8/int8 matmul path ROADMAP item 1
#      names.  Record imgs/s and the fp:int8 ratio per config; the
#      accuracy side is chip-independent and already gated on this box
#      (make quant-smoke; tools/gauntlet.py --compare e2e quant).
#      An fp8 arm rides along at batch 2 (e4m3, fp32-accumulate).
#   7. stem channel-padding layout A/B: conv0 with 3 vs 4 input
#      channels (zero-padded — bit-identical output, pinned by test);
#      adopt iff the backbone chain or full step wins measurably.
#   8. conv-fusion inspection: one traced run per network with the obs
#      profiler rollup (--trace_summary: device time by HLO op class) —
#      the evidence base for the next layout/fusion lever.
#
# All legs are single `tools/profile_step.py` invocations over landed
# tooling; results go into docs/PERF.md "Quantized inference" and
# "Round-6" tables.  Run on a host that sees the v5e chip.
#
# DEGRADED MODE (no accelerator): runs the CPU perf-smoke sanity leg
# PLUS the quant-arm sanity leg (tiny model, --quant --check: quant
# stages finite, zero recompiles), then emits a BENCH-style outage
# record listing every queued leg (`"measured": false, "degraded":
# true`) — the bench outage protocol applied to the stage battery.
set -euo pipefail
cd "$(dirname "$0")/.."

if ! python - <<'EOF'
import jax
d = jax.devices()[0]
print("device:", d.platform, d.device_kind)
raise SystemExit(0 if d.platform != "cpu" else 1)
EOF
then
    echo "== no accelerator: degraded mode (CPU sanity + outage record) =="
    JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.profile_step \
        --network tiny --dataset synthetic --shape 128x160 \
        --batch_images 2 --iters 2 --check
    echo "-- quant-arm sanity (int8 + fp8 chains, zero recompiles)"
    JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.profile_step \
        --network tiny --dataset synthetic --shape 128x160 \
        --batch_images 2 --iters 2 --check --quant
    JAX_PLATFORMS=cpu python -m mx_rcnn_tpu.tools.profile_step \
        --network tiny --dataset synthetic --shape 128x160 \
        --batch_images 2 --iters 2 --check --quant --quant_dtype fp8
    python - <<'EOF'
import json
print(json.dumps({
    "metric": "stage_ms_battery_r9",
    "value": None,
    "measured": False,
    "degraded": True,
    "failure": "no accelerator visible - do not record CPU numbers",
    "cpu_sanity": "perf-smoke + quant int8/fp8 arms passed "
                  "(chains finite, zero timed-pass recompiles)",
    "queued": [
        "batch-8 stage table (N=16, prenms 6000)",
        "blocked ROIAlign A/B (chunk 32/64/128, batch 2+8, stage+full-step)",
        "batched NMS A/B (batched vs per_image, batch 2+8, full-step)",
        "bucket A/B 608x1024 vs 640x1024 (38x64 vs 40x64 grid)",
        "r2-era row refresh: VGG16 VOC07 + ResNet-50 + batch 4/8 "
        "(current recipe)",
        "quantized inference fwd A/B: fp vs int8/native, batch 2+8, "
        "ResNet-101 + ResNet-50; fp8 arm at batch 2",
        "stem channel-pad layout A/B: conv0 3 vs 4 input channels",
        "conv-fusion inspection: traced rollup by HLO op class per network",
        "cross-host v2-wire A/B against chip-backed agents: v1-fp32 vs "
        "v2-u8 +coalesce +adaptive at the production bucket (WIRE_r20 "
        "protocol, real model instead of the content stub)",
    ],
}))
EOF
    exit 0
fi

echo "== 1. batch-8 stage table (N=16, adopted 6000 recipe) =="
python -m mx_rcnn_tpu.tools.profile_step --network resnet101 --dataset coco \
    --batch_images 8 --iters 16 --prenms 6000

echo "== 2. blocked ROIAlign A/B (stage + full step) =="
for bi in 2 8; do
    echo "-- batch ${bi}, einsum (baseline arm)"
    python -m mx_rcnn_tpu.tools.profile_step --network resnet101 \
        --dataset coco --batch_images "$bi" --iters 16 --prenms 6000 \
        --roi_backend jnp
    for chunk in 32 64 128; do
        echo "-- batch ${bi}, blocked chunk ${chunk}"
        python -m mx_rcnn_tpu.tools.profile_step --network resnet101 \
            --dataset coco --batch_images "$bi" --iters 16 --prenms 6000 \
            --roi_backend blocked --roi_chunk "$chunk"
    done
done

echo "== 3. batched NMS A/B (full step, 3 arms — see header) =="
for bi in 2 8; do
    for arm in "per_image auto" "per_image jnp" "batched jnp"; do
        set -- $arm
        echo "-- batch ${bi}, nms_mode $1, nms_backend $2"
        python -m mx_rcnn_tpu.tools.profile_step --network resnet101 \
            --dataset coco --batch_images "$bi" --iters 16 --prenms 6000 \
            --nms_mode "$1" --nms_backend "$2"
    done
done

echo "== 4. sublane-friendly bucket A/B: 608x1024 vs 640x1024 =="
for shape in 608x1024 640x1024; do
    echo "-- bucket ${shape}"
    python -m mx_rcnn_tpu.tools.profile_step --network resnet101 \
        --dataset coco --batch_images 2 --iters 16 --prenms 6000 \
        --shape "$shape"
done

echo "== 5a. VGG16 VOC07 row refresh (current recipe) =="
python -m mx_rcnn_tpu.tools.profile_step --network vgg --dataset PascalVOC \
    --batch_images 2 --iters 16 --prenms 6000

echo "== 5b. ResNet-50 row refresh (current recipe) =="
python -m mx_rcnn_tpu.tools.profile_step --network resnet50 --dataset coco \
    --batch_images 2 --iters 16 --prenms 6000

echo "== 5c. batch-4/8 row refresh (current recipe) =="
for bi in 4 8; do
    python -m mx_rcnn_tpu.tools.profile_step --network resnet101 \
        --dataset coco --batch_images "$bi" --iters 16 --prenms 6000
done

echo "== 6. quantized inference fwd A/B (fp vs int8, + fp8 arm) =="
for net in resnet101 resnet50; do
    for bi in 2 8; do
        echo "-- ${net}, batch ${bi}, int8/native"
        python -m mx_rcnn_tpu.tools.profile_step --network "$net" \
            --dataset coco --batch_images "$bi" --iters 16 --prenms 6000 \
            --quant --quant_dtype int8 --quant_mode native
    done
done
echo "-- resnet101, batch 2, fp8 arm"
python -m mx_rcnn_tpu.tools.profile_step --network resnet101 --dataset coco \
    --batch_images 2 --iters 16 --prenms 6000 --quant --quant_dtype fp8

echo "== 7. stem channel-pad layout A/B (3 vs 4 input channels) =="
for pad in 0 4; do
    echo "-- pad_stem ${pad}"
    python -m mx_rcnn_tpu.tools.profile_step --network resnet101 \
        --dataset coco --batch_images 2 --iters 16 --prenms 6000 \
        --pad_stem "$pad"
done

echo "== 8. conv-fusion inspection (traced rollup by HLO op class) =="
python -m mx_rcnn_tpu.tools.profile_step --network resnet101 --dataset coco \
    --batch_images 2 --iters 4 --prenms 6000 \
    --trace_dir /tmp/perf_r9_trace --trace_summary

echo "== 9. cross-host v2-wire A/B (chip-backed agents, real model) =="
# the CPU-measured WIRE_r20 protocol (docs/SERVING.md "Binary wire
# format") re-run with the agents serving the real checkpointed model:
# the bytes/image ratio is codec math either way, but the wire-leg
# speedup and the adaptive depth trajectory depend on real compute
# latencies behind the wire
python -m mx_rcnn_tpu.tools.loadgen --wire_bench --check \
    --out WIRE_r9_chip.json
