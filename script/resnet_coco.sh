#!/usr/bin/env bash
# Canonical recipe (ref script/resnet_coco.sh): ResNet-101 Faster R-CNN
# end2end on COCO (BASELINE.json configs 5/6: v5e-8 DP, per-chip batch 2).
# Expects COCO under data/coco (annotations/ + train2017/ / val2017/).
set -euo pipefail
cd "$(dirname "$0")/.."

# TRAIN pre-NMS 6000 (not the ref's 12000): measured 9% faster per step
# paired in one process, and mAP-neutral to the bit — 3 paired seeds at
# the production 608x1024 canvas all scored identical mAP in both arms
# (docs/PERF.md round 5, docs/neut608_records.json).  Pass
# --set train__rpn_pre_nms_top_n=12000 for strict reference parity.
#
# Throughput-optimal secondary config (r5 batch sweep): per-chip batch 8
# measured 92.7 imgs/s vs 79.5 at the contract batch 2 — use
# --batch_images 8 when fewer, larger gradient steps are acceptable.
python -m mx_rcnn_tpu.tools.train \
  --network resnet101 --dataset coco \
  --prefix model/resnet_coco_e2e --end_epoch 8 --lr 0.001 --lr_step 6 \
  --batch_images 2 --num_devices "${NUM_DEVICES:-8}" \
  --set train__rpn_pre_nms_top_n=6000 \
  "$@"

python -m mx_rcnn_tpu.tools.test \
  --network resnet101 --dataset coco \
  --prefix model/resnet_coco_e2e --epoch 8
