#!/usr/bin/env bash
# Canonical recipe (ref script/resnet_coco.sh): ResNet-101 Faster R-CNN
# end2end on COCO (BASELINE.json configs 5/6: v5e-8 DP, per-chip batch 2).
# Expects COCO under data/coco (annotations/ + train2017/ / val2017/).
set -euo pipefail
cd "$(dirname "$0")/.."

python -m mx_rcnn_tpu.tools.train \
  --network resnet101 --dataset coco \
  --prefix model/resnet_coco_e2e --end_epoch 8 --lr 0.001 --lr_step 6 \
  --batch_images 2 --num_devices "${NUM_DEVICES:-8}" \
  "$@"

python -m mx_rcnn_tpu.tools.test \
  --network resnet101 --dataset coco \
  --prefix model/resnet_coco_e2e --epoch 8
