#!/usr/bin/env bash
# Canonical recipe (ref script/resnet_coco.sh): ResNet-101 Faster R-CNN
# end2end on COCO (BASELINE.json configs 5/6: v5e-8 DP, per-chip batch 2).
# Expects COCO under data/coco (annotations/ + train2017/ / val2017/).
set -euo pipefail
cd "$(dirname "$0")/.."

# TRAIN pre-NMS 6000 (not the ref's 12000): measured mAP-neutral on this
# stack and ~16% faster per step (docs/PERF.md round 3) — adopted as the
# recipe default; pass --set train__rpn_pre_nms_top_n=12000 for strict
# reference parity.
python -m mx_rcnn_tpu.tools.train \
  --network resnet101 --dataset coco \
  --prefix model/resnet_coco_e2e --end_epoch 8 --lr 0.001 --lr_step 6 \
  --batch_images 2 --num_devices "${NUM_DEVICES:-8}" \
  --set train__rpn_pre_nms_top_n=6000 \
  "$@"

python -m mx_rcnn_tpu.tools.test \
  --network resnet101 --dataset coco \
  --prefix model/resnet_coco_e2e --epoch 8
