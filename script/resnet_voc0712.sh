#!/usr/bin/env bash
# Canonical recipe (ref script/resnet_voc0712.sh): ResNet-101 Faster R-CNN
# end2end on VOC07+12 trainval, evaluated on VOC07 test — the mAP north-star
# config (BASELINE.json config 3: ~79.3 reference mAP).
set -euo pipefail
cd "$(dirname "$0")/.."

python -m mx_rcnn_tpu.tools.train \
  --network resnet101 --dataset PascalVOC \
  --image_set 2007_trainval+2012_trainval \
  --prefix model/resnet_voc0712_e2e --end_epoch 10 --lr 0.001 --lr_step 7 \
  "$@"

python -m mx_rcnn_tpu.tools.test \
  --network resnet101 --dataset PascalVOC --image_set 2007_test \
  --prefix model/resnet_voc0712_e2e --epoch 10
