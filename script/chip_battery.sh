#!/bin/bash
# On-chip measurement battery (written round 4, chip down all round) — run
# when the tunneled chip is UP to capture everything the outage blocked.
# Order: cheapest/most-important first, so a re-outage mid-battery still
# leaves the headline captured.
set -uo pipefail
cd /root/repo
LOG=${CHIP_BATTERY_LOG:-/tmp/chip_battery.log}
exec > >(tee -a "$LOG") 2>&1
echo "=== chip battery start $(date) ==="

echo "--- 1. live bench (headline + sustained) ---"
BENCH_RETRY_WINDOW_S=1800 BENCH_ATTEMPT_TIMEOUT_S=1500 timeout 2100 python bench.py

echo "--- 2. stage table (unrolled chains, N=4) + trace summary ---"
timeout 2400 python -m mx_rcnn_tpu.tools.profile_step --network resnet101 \
  --iters 4 --trace_dir /tmp/r4_trace --trace_summary

echo "--- 3. remat / bf16-momentum A/B (full-step timing only) ---"
timeout 1800 python - <<'EOF'
import time, sys
import numpy as np
import jax, jax.numpy as jnp
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.train import make_train_step, setup_training
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.tools.profile_step import make_batch

def fetch(x): return np.asarray(x).ravel()[:1]

for name, over in (("base", {}),
                   ("remat", {"train__remat_backbone": True}),
                   ("bf16mom", {"default__momentum_dtype": "bfloat16"}),
                   ("batch4+remat", {"train__batch_images": 4,
                                     "train__remat_backbone": True})):
    n = over.get("train__batch_images", 2)
    cfg = generate_config("resnet101", "coco",
                          train__rpn_pre_nms_top_n=6000, **over)
    cfg = cfg.replace_in("train", batch_images=n)
    model = build_model(cfg)
    batch = make_batch(cfg, n, 608, 1024, raw=True)
    key = jax.random.PRNGKey(0)
    try:
        state, tx = setup_training(model, cfg, key, (n, 608, 1024, 3),
                                   steps_per_epoch=10_000)
        step = jax.jit(make_train_step(model, cfg, tx), donate_argnums=(0,))
        state, m = step(state, batch, key); fetch(m["loss"])
        for _ in range(2): state, m = step(state, batch, key)
        fetch(m["loss"])
        t0 = time.perf_counter()
        for _ in range(30): state, m = step(state, batch, key)
        fetch(m["loss"])
        dt = (time.perf_counter() - t0 - 0.1) / 30
        print(f"A/B {name}: {dt*1e3:.2f} ms/step  {n/dt:.1f} imgs/s", flush=True)
    except Exception as e:
        print(f"A/B {name}: FAILED {e}", flush=True)
EOF

echo "--- 4. model-zoo sweep on synthetic_hard (NO pretrained weights on this box, so this verifies every backbone trains+evals; the pretrained ordering premise is environment-blocked) ---"
timeout 5400 python - <<'EOF'
import logging; logging.basicConfig(level=logging.WARNING)
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.tools.train import train_net
from mx_rcnn_tpu.tools.test import test_rcnn as eval_rcnn
# anchors (8,16,32)@stride16 are too big for 240x320 objects; use the
# proportional (2,4,8) the tiny net uses so the comparison is fair
for net in ("vgg", "resnet50", "resnet101"):
    try:
        cfg = generate_config(net, "synthetic_hard",
                              dataset__root_path="/tmp/g400",
                              dataset__dataset_path="/tmp/g400/synthetic_hard",
                              train__batch_images=2)
        cfg = cfg.replace_in("network", anchor_scales=(2, 4, 8))
        prefix = f"/tmp/g400/order-{net}"
        train_net(cfg, prefix=prefix, end_epoch=8, lr=1e-3, lr_step="6",
                  frequent=100000, seed=0)
        r = eval_rcnn(cfg, prefix=prefix, epoch=8, verbose=False)
        print(f"ORDER {net}: mAP {r['mAP']:.4f}", flush=True)
    except Exception as e:
        print(f"ORDER {net}: FAILED {e}", flush=True)
EOF

echo "=== chip battery done $(date) ==="
