#!/usr/bin/env bash
# Framework smoke recipe (no reference equivalent): train->eval->demo on the
# self-generating synthetic dataset; runs on CPU in minutes, no downloads.
# This is the recipe CI (and the judge) can actually execute end to end.
set -euo pipefail
cd "$(dirname "$0")/.."

ROOT="${ROOT:-data/synthetic_smoke}"

python -m mx_rcnn_tpu.tools.train \
  --network tiny --dataset synthetic --root_path "$ROOT" \
  --prefix "$ROOT/model/e2e" --end_epoch 4 --no_flip \
  "$@"

python -m mx_rcnn_tpu.tools.test \
  --network tiny --dataset synthetic --root_path "$ROOT" \
  --prefix "$ROOT/model/e2e" --epoch 4

python -m mx_rcnn_tpu.tools.demo \
  --network tiny --dataset synthetic \
  --prefix "$ROOT/model/e2e" --epoch 4 \
  --image "$ROOT/synthetic/test/test_00000.png" \
  --out "$ROOT/demo_out.png"
