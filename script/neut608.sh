#!/bin/bash
# VERDICT r5 item 3: multi-seed mAP neutrality of TRAIN pre-NMS 6000 at
# PRODUCTION scale (608x1024 canvas, 21888 anchors, production (8,16,32)
# anchor scales), judged by the paired-seed CI gate.
# Recipe notes: resnet50 + lr 1e-3 — the battery-1 zoo sweep measured
# resnet50-from-scratch learns (mAP 0.69 on synthetic_hard) while the
# first attempt (resnet101, lr 3e-3) scored 0.0000 in BOTH arms at this
# canvas — a vacuous comparison.  12 epochs / decay at 10: past the decay
# so seeds are settled (docs/GAUNTLET.md calibration history).
set -uo pipefail
cd /root/repo
LOG=${NEUT_LOG:-/tmp/neut608.log}
exec > >(tee -a "$LOG") 2>&1
echo "=== neut608 start $(date) ==="
timeout 10000 python - <<'EOF'
import json
import logging; logging.basicConfig(level=logging.WARNING)
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.tools.train import train_net
from mx_rcnn_tpu.tools.test import test_rcnn as eval_rcnn
from mx_rcnn_tpu.tools.gauntlet import paired_compare

KW = dict(image_size=(608, 1024))
records = []
for seed in (0, 1, 2):
    for mode, prenms in (("e2e", 12000), ("prenms", 6000)):
        cfg = generate_config(
            "resnet50", "synthetic_hard",
            dataset__root_path="/tmp/neut608",
            dataset__dataset_path="/tmp/neut608/synthetic_hard",
            train__rpn_pre_nms_top_n=prenms,
            train__batch_images=2)
        prefix = f"/tmp/neut608/m-{prenms}-s{seed}"
        train_net(cfg, prefix=prefix, end_epoch=12, lr=1e-3, lr_step="10",
                  frequent=100000, seed=seed, dataset_kw=KW,
                  device_cache=True)
        r = eval_rcnn(cfg, prefix=prefix, epoch=12, verbose=False,
                      dataset_kw=KW)
        rec = {"mode": mode, "network": "resnet50", "seed": seed,
               "mAP": round(float(r["mAP"]), 4)}
        records.append(rec)
        print(f"NEUT608 {mode} prenms={prenms} seed={seed}: "
              f"mAP {rec['mAP']:.4f}", flush=True)
        with open("/tmp/neut608/records.json", "w") as f:
            json.dump(records, f)
cmp = paired_compare(records, "e2e", "prenms", "resnet50", budget=0.02)
print("NEUT608 paired:", json.dumps(cmp), flush=True)
EOF
echo "=== neut608 done $(date) ==="
