#!/usr/bin/env bash
# Canonical recipe (ref script/vgg_alternate.sh): the paper's 4-stage
# alternate training schedule (BASELINE.json config 4): RPN -> proposals ->
# Fast R-CNN -> RPN (shared convs frozen) -> Fast R-CNN -> combined model.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m mx_rcnn_tpu.tools.train_alternate \
  --network vgg --dataset PascalVOC --image_set 2007_trainval \
  --prefix model/vgg_voc07_alt \
  "$@"

python -m mx_rcnn_tpu.tools.test \
  --network vgg --dataset PascalVOC --image_set 2007_test \
  --prefix model/vgg_voc07_alt-final --epoch 1
