"""Ablation driver for the alternate-training mAP gap (VERDICT r02 item 5).

Round-2 hardware runs on the full-size synthetic recipe scored e2e 0.84
vs alternate 0.68.  This script reruns the 4-stage schedule under
controlled variants to localize the loss:

  e2e        — end-to-end baseline (10 epochs).
  alt        — alternate with stage2_init='rpn1' (the round-2 default,
               the 0.68 configuration).
  alt-nofreeze — stages 3/4 train the shared convs instead of freezing
               them.  The paper freezes ImageNet-initialized shared convs;
               with no pretrained weights (this machine), the frozen
               features are whatever 8 epochs of from-scratch RPN+RCNN
               produced — hypothesis: freezing THOSE is the gap.
  alt-fresh2 — stage 2 initializes fresh instead of from rpn1 (now the
               tool's default, adopted FROM this ablation).
  alt-long   — stages run e2e-length (10 epochs each).

Each variant trains, combines, and evaluates with tools.test; 'alt'
additionally evaluates the mid-schedule rpn1+rcnn1 combination so
stage-3/4 regressions are visible separately.

Usage:  python script/ablate_alternate.py [--variants alt,e2e,...]
        [--root data/ablate_alt]
Writes <root>/results.json and prints one line per variant.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

# run on CPU: ablations must not contend with benchmarks for the chip,
# and the machine sitecustomize pins the axon platform ahead of the env
# var — jax.config is the override that sticks
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax

if os.environ["JAX_PLATFORMS"] == "cpu":
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.tester import Predictor, pred_eval
from mx_rcnn_tpu.core.train import TrainState
from mx_rcnn_tpu.data import TestLoader, load_gt_roidb
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.tools.train import train_net
from mx_rcnn_tpu.tools.train_alternate import alternate_train
from mx_rcnn_tpu.utils.checkpoint import (combine_model, load_param,
                                          save_checkpoint)

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(message)s")
log = logging.getLogger("ablate")


def evaluate(cfg, prefix: str, epoch: int) -> float:
    imdb, roidb = load_gt_roidb(cfg, training=False)
    loader = TestLoader(roidb, cfg)
    model = build_model(cfg)
    params, batch_stats = load_param(prefix, epoch)
    predictor = Predictor(model, {"params": params,
                                  "batch_stats": batch_stats}, cfg)
    results = pred_eval(predictor, loader, imdb, cfg, verbose=False)
    return float(results["mAP"])


def combine_eval(cfg, rpn_prefix, rpn_epoch, rcnn_prefix, rcnn_epoch,
                 out_prefix) -> float:
    p_rpn, s_rpn = load_param(rpn_prefix, rpn_epoch)
    p_rcnn, s_rcnn = load_param(rcnn_prefix, rcnn_epoch)
    params = combine_model(p_rpn, p_rcnn, from_a=("rpn", "backbone"))
    stats = combine_model(s_rpn, s_rcnn, from_a=("backbone",))
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       batch_stats=stats, opt_state={})
    save_checkpoint(out_prefix, 1, state)
    return evaluate(cfg, out_prefix, 1)


def run_variant(name: str, root: str, seed: int = 0) -> dict:
    cfg = generate_config("tiny", "synthetic")
    cfg = cfg.replace_in("dataset", root_path=root)
    prefix = os.path.join(root, f"model/{name}-s{seed}")
    os.makedirs(os.path.dirname(prefix), exist_ok=True)
    out = {"variant": name, "seed": seed}

    if name == "e2e":
        train_net(cfg, prefix=prefix, end_epoch=10, seed=seed)
        out["mAP"] = evaluate(cfg, prefix, 10)
        return out

    kw = {}
    if name == "alt":
        kw = dict(stage2_init="rpn1")  # the round-2 default under test
    elif name == "alt-nofreeze":
        # stages 3/4 keep training the shared convs: replace the shared
        # freeze set with the ordinary FIXED_PARAMS set
        cfg = cfg.replace_in("network",
                             fixed_params_shared=cfg.network.fixed_params,
                             )
        kw = dict(stage2_init="rpn1")
    elif name == "alt-long":
        kw = dict(rpn_epoch=10, rcnn_epoch=10, stage2_init="rpn1")
    # alt-fresh2: the tool default (stage2_init='fresh'), no kw needed

    d = cfg.default
    rpn_ep = kw.get("rpn_epoch", d.rpn_epoch)
    rcnn_ep = kw.get("rcnn_epoch", d.rcnn_epoch)
    final = alternate_train(cfg, prefix=prefix, seed=seed, **kw)
    out["mAP"] = evaluate(cfg, final, 1)
    if name == "alt":
        # mid-schedule diagnostic: rpn1 + rcnn1 combined
        out["mAP_rpn1_rcnn1"] = combine_eval(
            cfg, f"{prefix}-rpn1", rpn_ep, f"{prefix}-rcnn1", rcnn_ep,
            f"{prefix}-mid")
    return out







def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--root", default="data/ablate_alt")
    p.add_argument("--variants",
                   default="e2e,alt,alt-nofreeze,alt-fresh2,alt-long")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    results = []
    res_path = os.path.join(args.root, "results.json")
    if os.path.exists(res_path):
        results = json.load(open(res_path))
    done = {(r["variant"], r.get("seed", 0)) for r in results}
    for name in args.variants.split(","):
        if (name, args.seed) in done:
            log.info("skip %s (already in results.json)", name)
            continue
        log.info("=== variant %s ===", name)
        r = run_variant(name, args.root, seed=args.seed)
        results.append(r)
        os.makedirs(args.root, exist_ok=True)
        with open(res_path, "w") as f:
            json.dump(results, f, indent=1)
        log.info("RESULT %s", r)
    for r in results:
        print(json.dumps(r))


if __name__ == "__main__":
    main()
