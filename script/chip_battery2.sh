#!/bin/bash
# Round-5 follow-up battery (runs after script/chip_battery.sh):
#   A. stage table at the ADOPTED recipe (pre-NMS 6000) with N=16 unrolled
#      chains — the N=4 table was noise-dominated (±25 ms error bars from
#      the ~101 ms tunnel RTT; it printed negative stage times).
#   B. 12000-vs-6000 full-step A/B at the bench config (one process, so
#      the compile cache is shared and only the recipe differs).
#   C. VERDICT r05 item 3: multi-seed mAP neutrality of TRAIN pre-NMS
#      6000 at PRODUCTION scale (608x1024 canvas, 21888 anchors) — 3
#      paired seeds x 2 arms of resnet101 on synthetic_hard@608x1024,
#      judged by tools/gauntlet.py paired_compare (the new CI gate).
#   D. batch sweep 2/4/8 WITHOUT remat (battery 1 only measured
#      batch4+remat, and remat measured strictly slower) — the MFU
#      headroom record.
set -uo pipefail
cd /root/repo
LOG=${CHIP_BATTERY2_LOG:-/tmp/chip_battery2.log}
exec > >(tee -a "$LOG") 2>&1
echo "=== chip battery 2 start $(date) ==="

# SKIP_A=1 skips the stage tables (already captured in a previous run)
if [ -z "${SKIP_A:-}" ]; then
  echo "--- A. stage table N=16, adopted recipe (prenms 6000) ---"
  timeout 3000 python -m mx_rcnn_tpu.tools.profile_step --network resnet101 \
    --iters 16 --prenms 6000
  echo "--- A2. stage table N=16, ref recipe (prenms 12000) ---"
  timeout 3000 python -m mx_rcnn_tpu.tools.profile_step --network resnet101 \
    --iters 16 --prenms 12000
fi

echo "--- B. full-step 12000 vs 6000 (shared process) ---"
timeout 1800 python - <<'EOF'
import time
import numpy as np
import jax
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.train import make_train_step, setup_training
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.tools.profile_step import make_batch

def fetch(x): return np.asarray(x).ravel()[:1]

for prenms in (12000, 6000):
    cfg = generate_config("resnet101", "coco",
                          train__rpn_pre_nms_top_n=prenms,
                          train__batch_images=2)
    model = build_model(cfg)
    batch = make_batch(cfg, 2, 608, 1024, raw=True)
    key = jax.random.PRNGKey(0)
    state, tx = setup_training(model, cfg, key, (2, 608, 1024, 3),
                               steps_per_epoch=10_000)
    step = jax.jit(make_train_step(model, cfg, tx), donate_argnums=(0,))
    state, m = step(state, batch, key); fetch(m["loss"])
    for _ in range(2): state, m = step(state, batch, key)
    fetch(m["loss"])
    t0 = time.perf_counter()
    for _ in range(30): state, m = step(state, batch, key)
    fetch(m["loss"])
    dt = (time.perf_counter() - t0 - 0.1) / 30
    print(f"A/B prenms={prenms}: {dt*1e3:.2f} ms/step  {2/dt:.1f} imgs/s",
          flush=True)
EOF

echo "--- C. pre-NMS 6000 neutrality, 3 paired seeds @ 608x1024 ---"
timeout 7200 python - <<'EOF'
import json
import logging; logging.basicConfig(level=logging.WARNING)
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.tools.train import train_net
from mx_rcnn_tpu.tools.test import test_rcnn as eval_rcnn
from mx_rcnn_tpu.tools.gauntlet import paired_compare

# production-scale canvas: same 608x1024 bucket, stride-16 anchor grid
# (21888 anchors) and (8,16,32) anchor scales as the BASELINE config; the
# dataset's log-uniform object sizes (canvas/12..canvas/2 = 85..512 px)
# land inside the production anchor range, so the proposal stage operates
# in its production regime — unlike the 240x320 gauntlet canvas whose
# 2700 anchors made every cap >= 2700 vacuous (docs/ROUND4.md §3)
KW = dict(image_size=(608, 1024))
records = []
for seed in (0, 1, 2):
    for mode, prenms in (("e2e", 12000), ("prenms", 6000)):
        cfg = generate_config(
            "resnet101", "synthetic_hard",
            dataset__root_path="/tmp/neut608",
            dataset__dataset_path="/tmp/neut608/synthetic_hard",
            train__rpn_pre_nms_top_n=prenms,
            train__batch_images=2)
        prefix = f"/tmp/neut608/m-{prenms}-s{seed}"
        train_net(cfg, prefix=prefix, end_epoch=10, lr=3e-3, lr_step="8",
                  frequent=100000, seed=seed, dataset_kw=KW,
                  device_cache=True)
        r = eval_rcnn(cfg, prefix=prefix, epoch=10, verbose=False,
                      dataset_kw=KW)
        rec = {"mode": mode, "network": "resnet101", "seed": seed,
               "mAP": round(float(r["mAP"]), 4)}
        records.append(rec)
        print(f"NEUT608 {mode} prenms={prenms} seed={seed}: "
              f"mAP {rec['mAP']:.4f}", flush=True)
        with open("/tmp/neut608/records.json", "w") as f:
            json.dump(records, f)
cmp = paired_compare(records, "e2e", "prenms", "resnet101", budget=0.02)
print("NEUT608 paired:", json.dumps(cmp), flush=True)
EOF

echo "--- D. batch sweep 2/4/8 plain (no remat), adopted recipe ---"
timeout 2400 python - <<'EOF'
import time
import numpy as np
import jax
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.train import make_train_step, setup_training
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.tools.profile_step import make_batch

def fetch(x): return np.asarray(x).ravel()[:1]

for n in (2, 4, 8):
    try:
        cfg = generate_config("resnet101", "coco",
                              train__rpn_pre_nms_top_n=6000,
                              train__batch_images=n)
        model = build_model(cfg)
        batch = make_batch(cfg, n, 608, 1024, raw=True)
        key = jax.random.PRNGKey(0)
        state, tx = setup_training(model, cfg, key, (n, 608, 1024, 3),
                                   steps_per_epoch=10_000)
        step = jax.jit(make_train_step(model, cfg, tx), donate_argnums=(0,))
        state, m = step(state, batch, key); fetch(m["loss"])
        for _ in range(2): state, m = step(state, batch, key)
        fetch(m["loss"])
        t0 = time.perf_counter()
        for _ in range(30): state, m = step(state, batch, key)
        fetch(m["loss"])
        dt = (time.perf_counter() - t0 - 0.1) / 30
        print(f"SWEEP batch={n}: {dt*1e3:.2f} ms/step  {n/dt:.1f} imgs/s",
              flush=True)
    except Exception as e:
        print(f"SWEEP batch={n}: FAILED {type(e).__name__} {e}", flush=True)
EOF

echo "=== chip battery 2 done $(date) ==="
