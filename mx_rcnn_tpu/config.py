"""Immutable configuration system.

Reference: ``rcnn/config.py`` — the reference keeps a global mutable easydict
singleton (``config``/``default``) mutated by ``generate_config(network,
dataset)`` and argparse overrides.  A hidden mutable global is hostile to XLA
tracing and to reproducibility, so here the same three-level precedence
(hardcoded defaults < network/dataset presets < CLI overrides) is realized
with **frozen dataclasses**: ``generate_config`` returns a new immutable
``Config`` that is threaded explicitly through every function.

Key names and default values mirror the reference 1:1 wherever a reference
key exists (``config.TRAIN.*``, ``config.TEST.*``, per-network and
per-dataset dicts, ``default.*``) so they can be audited side by side.
TPU-specific additions (shape buckets, compute dtype, padded sizes) are
grouped at the bottom of each dataclass and commented as such.
"""

from __future__ import annotations

import dataclasses
import types
import typing
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence, Tuple


@dataclass(frozen=True)
class TrainConfig:
    """Mirrors reference ``config.TRAIN``."""

    # -- whole-pipeline switches --------------------------------------------
    batch_images: int = 1          # images per device (ref: BATCH_IMAGES, per GPU)
    # configlint: disable=CL201 ref TRAIN.END2END mirrored 1:1 for side-by-side audit; this port is statically end-to-end (alternate training is its own CLI)
    end2end: bool = True           # ref: END2END
    flip: bool = True              # ref: FLIP — append horizontally flipped roidb
    shuffle: bool = True           # ref: SHUFFLE
    # configlint: disable=CL201 ref ASPECT_GROUPING mirrored 1:1; grouping is realized structurally by the landscape/portrait buckets (BucketConfig)
    aspect_grouping: bool = True   # ref: ASPECT_GROUPING — group wide/tall images

    # -- R-CNN ROI sampling (ref rcnn/io/rcnn.py — sample_rois) --------------
    batch_rois: int = 128          # ref: BATCH_ROIS — ROIs per image
    fg_fraction: float = 0.25      # ref: FG_FRACTION — max fg fraction
    fg_thresh: float = 0.5         # ref: FG_THRESH — fg IoU threshold
    bg_thresh_hi: float = 0.5      # ref: BG_THRESH_HI
    bg_thresh_lo: float = 0.0      # ref: BG_THRESH_LO

    # -- bbox regression target normalization (ref: BBOX_* keys) -------------
    # configlint: disable=CL201 ref BBOX_REGRESSION_THRESH mirrored 1:1 for audit; the fused proposal-target op keys fg on fg_thresh alone, as the ref e2e path does
    bbox_regression_thresh: float = 0.5            # ref: BBOX_REGRESSION_THRESH
    bbox_means: Tuple[float, ...] = (0.0, 0.0, 0.0, 0.0)   # ref: BBOX_MEANS
    bbox_stds: Tuple[float, ...] = (0.1, 0.1, 0.2, 0.2)    # ref: BBOX_STDS

    # -- RPN anchor target assignment (ref rcnn/io/rpn.py — assign_anchor) ---
    rpn_batch_size: int = 256          # ref: RPN_BATCH_SIZE — anchors per image
    rpn_fg_fraction: float = 0.5       # ref: RPN_FG_FRACTION
    rpn_positive_overlap: float = 0.7  # ref: RPN_POSITIVE_OVERLAP
    rpn_negative_overlap: float = 0.3  # ref: RPN_NEGATIVE_OVERLAP
    rpn_clobber_positives: bool = False  # ref: RPN_CLOBBER_POSITIVES
    rpn_allowed_border: int = 0        # ref: assign_anchor(allowed_border=0)
    rpn_bbox_weights: Tuple[float, ...] = (1.0, 1.0, 1.0, 1.0)  # ref: RPN_BBOX_WEIGHTS

    # -- RPN proposal generation at TRAIN time (ref mx.symbol.Proposal args) -
    rpn_pre_nms_top_n: int = 12000  # ref: RPN_PRE_NMS_TOP_N
    rpn_post_nms_top_n: int = 2000  # ref: RPN_POST_NMS_TOP_N
    rpn_nms_thresh: float = 0.7     # ref: RPN_NMS_THRESH
    rpn_min_size: int = 16          # ref: RPN_MIN_SIZE (pixels, at input scale)

    # -- TPU additions -------------------------------------------------------
    max_gt_boxes: int = 100        # static pad for per-image gt boxes
    gt_append: bool = True         # append gt boxes to sampled ROI pool (ref does)
    # rematerialize backbone activations in the backward pass
    # (jax.checkpoint): trades recompute FLOPs for HBM capacity/bandwidth —
    # numerically identical gradients (pinned by test); enables larger
    # per-chip batches when activations are the memory wall
    remat_backbone: bool = False
    # ROIAlign backend for the TRAIN step: 'auto'/'jnp' → the einsum pair
    # (measured FASTER in the full step: the fused kernel wins isolated
    # but pays ~13 ms in custom-call boundary layout copies + lost XLA
    # fusion — ops/roi_pool.py roi_align_batched docstring has the
    # numbers); 'blocked' → the ROI-chunked einsum pair (bit-equal
    # forward, live (R,·,·,C) intermediate shrunk by roi_align_chunk/R,
    # stays inside the XLA program so it pays none of the custom-call
    # tax — the r6 lever, full-step A/B queued in script/perf_r6.sh);
    # 'pallas' → the experimental VMEM-fused kernel
    roi_align_backend: str = "auto"
    # ROI block size for the 'blocked' backend (ignored by the others):
    # 64 splits the production 256-ROI batch into 4 chunks → ~70 MB live
    # intermediate instead of ~280 MB
    roi_align_chunk: int = 64
    # proposal-stage NMS composition: True → the batched nms_batch path
    # (ops/nms.py — when the jnp sweep backend is selected this is ONE
    # cross-image tile sweep per step, decision-exact vs the per-image
    # sweep; when the auto-guards select the Pallas kernel on TPU, the
    # kernel still runs per image under vmap); False → vmap of per-image
    # nms calls (the pre-r6 composition, the A/B arm for
    # script/perf_r6.sh leg 3, which forces the jnp backend to actually
    # engage the cross-image sweep)
    nms_batched: bool = True


@dataclass(frozen=True)
class TestConfig:
    """Mirrors reference ``config.TEST``."""

    # configlint: disable=CL201 ref TEST.HAS_RPN mirrored 1:1; every model in this port carries an RPN
    has_rpn: bool = True            # ref: HAS_RPN (True for end2end models)
    batch_images: int = 1           # ref: BATCH_IMAGES
    nms: float = 0.3                # ref: NMS — per-class NMS threshold at eval
    score_thresh: float = 1e-3      # ref: pred_eval thresh
    max_per_image: int = 100        # ref: pred_eval max_per_image
    # RPN proposal generation at TEST time
    rpn_pre_nms_top_n: int = 6000   # ref: RPN_PRE_NMS_TOP_N
    rpn_post_nms_top_n: int = 300   # ref: RPN_POST_NMS_TOP_N
    rpn_nms_thresh: float = 0.7     # ref: RPN_NMS_THRESH
    rpn_min_size: int = 16          # ref: RPN_MIN_SIZE
    # proposal-generation mode for alternate training (ref tools/test_rpn.py)
    # configlint: disable=CL201 ref key mirrored for audit; the alternate-training proposal dump reads the pre/post top_n pair and shares rpn_nms_thresh
    proposal_nms_thresh: float = 0.7
    proposal_pre_nms_top_n: int = 20000
    proposal_post_nms_top_n: int = 2000


@dataclass(frozen=True)
class NetworkConfig:
    """Per-network preset. Mirrors the reference's per-network dict in
    ``rcnn/config.py`` (pretrained prefix, anchor geometry, strides,
    FIXED_PARAMS)."""

    name: str = "resnet101"
    # configlint: disable=CL201 ref per-network dict keys mirrored 1:1; the live values come from the --pretrained/--pretrained_epoch CLI flags
    pretrained: str = ""                 # path prefix of pretrained backbone
    pretrained_epoch: int = 0  # configlint: disable=CL201 see pretrained above
    pixel_means: Tuple[float, ...] = (123.68, 116.779, 103.939)  # RGB; ref: PIXEL_MEANS
    # configlint: disable=CL201 ref IMAGE_STRIDE mirrored 1:1; stride padding is realized by the static buckets (multiples of 32)
    image_stride: int = 0                # ref: IMAGE_STRIDE (VGG 0, pad multiple)
    rpn_feat_stride: int = 16            # ref: RPN_FEAT_STRIDE
    # configlint: disable=CL201 ref RCNN_FEAT_STRIDE mirrored 1:1; both stages share one stride here and code derives from rpn_feat_stride
    rcnn_feat_stride: int = 16           # ref: RCNN_FEAT_STRIDE
    anchor_scales: Tuple[int, ...] = (8, 16, 32)       # ref: ANCHOR_SCALES
    anchor_ratios: Tuple[float, ...] = (0.5, 1.0, 2.0)  # ref: ANCHOR_RATIOS
    rcnn_pooled_size: Tuple[int, int] = (14, 14)  # ref: VGG 7x7, ResNet 14x14
    # Parameter-name prefixes frozen during training (ref: FIXED_PARAMS) and
    # the larger set frozen in alternate-training shared-conv stages
    # (ref: FIXED_PARAMS_SHARED).  'gamma'/'beta' are the reference's
    # freeze-every-BN-affine tokens (see core/optim.frozen_mask); stage4 is
    # the per-ROI head and must stay trainable in shared-conv stages.
    fixed_params: Tuple[str, ...] = (
        "conv0", "stage1", "bn0", "bn_data", "gamma", "beta")
    fixed_params_shared: Tuple[str, ...] = (
        "conv0", "stage1", "stage2", "stage3", "bn0", "bn_data",
        "gamma", "beta")
    # -- TPU additions -------------------------------------------------------
    # configlint: disable=CL201 preset documentation; faster_rcnn.setup derives depth from the network NAME so name and depth cannot disagree
    depth: int = 101                     # resnet depth (50 / 101 / 152)
    compute_dtype: str = "bfloat16"      # MXU-friendly activation dtype
    # backbone layout lever (docs/PERF.md "Quantized inference"):
    # zero-pad the stem's 3 input channels up to this count before conv0
    # (4 aligns the channel axis; padded channels are exact zeros so the
    # output is bit-identical — pinned by test).  Changes the conv0
    # kernel's param shape, so it is a profile_step A/B lever
    # (``--pad_stem``), not a checkpoint-compatible default.  0 = off.
    stem_channel_pad: int = 0

    @property
    def num_anchors(self) -> int:
        """Ref NUM_ANCHORS — derived, so it can never desynchronize from the
        scale/ratio presets."""
        return len(self.anchor_scales) * len(self.anchor_ratios)


@dataclass(frozen=True)
class DatasetConfig:
    """Per-dataset preset. Mirrors the reference's per-dataset dict."""

    name: str = "PascalVOC"
    image_set: str = "2007_trainval"
    test_image_set: str = "2007_test"
    root_path: str = "data"
    dataset_path: str = "data/VOCdevkit"
    num_classes: int = 21                # ref: NUM_CLASSES (VOC 21 / COCO 81)


@dataclass(frozen=True)
class DefaultConfig:
    """Mirrors reference ``default.*`` (training-schedule defaults)."""

    frequent: int = 20            # ref: default.frequent — Speedometer period
    # configlint: disable=CL201 ref default.kvstore kept for CLI parity; DP-over-ICI (parallel/dp.py) replaces the kvstore concept wholesale
    kvstore: str = "device"       # kept for CLI parity; maps to DP-over-ICI
    # configlint: disable=CL201 ref default.prefix/begin_epoch mirrored 1:1; the --prefix/--begin_epoch CLI flags own the live values
    prefix: str = "model/e2e"
    begin_epoch: int = 0  # configlint: disable=CL201 see prefix above
    e2e_epoch: int = 10           # ref: default.e2e_epoch
    e2e_lr: float = 0.001         # ref: default.e2e_lr
    e2e_lr_step: str = "7"        # ref: default.e2e_lr_step (epoch for x0.1)
    # alternate training stage schedules (ref: default.rpn_*/rcnn_*)
    rpn_epoch: int = 8
    rpn_lr: float = 0.001
    rpn_lr_step: str = "6"
    rcnn_epoch: int = 8
    rcnn_lr: float = 0.001
    rcnn_lr_step: str = "6"
    # optimizer constants (ref train_end2end.py — train_net: sgd with
    # momentum 0.9, wd 5e-4, elementwise clip_gradient=5)
    momentum: float = 0.9
    wd: float = 0.0005
    lr_factor: float = 0.1
    clip_gradient: float = 5.0
    # linear LR warmup (upstream WarmupMultiFactorScheduler; off by default
    # to match the reference scripts — enable at large DP batch)
    warmup_step: int = 0
    warmup_lr: float = 0.0
    # TPU addition: SGD momentum accumulator dtype.  Default ADOPTED as
    # "bfloat16" from the r5 on-chip A/B (25.66 ms vs 25.77 ms fp32 —
    # speed-neutral — with momentum HBM and its per-step read/write
    # bandwidth halved; docs/PERF.md "Lever A/Bs" + adoption note).
    # "float32" restores the reference-exact accumulator
    # (``--set default__momentum_dtype=float32``).  Params themselves
    # always stay float32.
    momentum_dtype: str = "bfloat16"
    # host input pipeline (TPU addition; the ref loader is synchronous —
    # SURVEY.md §7 "Hard parts": cv2 decode must overlap device steps)
    num_workers: int = 4
    prefetch: int = 4
    # ship uint8 batches and normalize on device (ops/normalize.py) —
    # bit-identical to host normalization, 4x less host bandwidth
    raw_images: bool = True
    # decoded-uint8 image cache (data/cache.py): RAM-tier budget in MiB
    # (0 disables), plus an optional disk tier directory
    image_cache_mb: int = 2048
    image_cache_dir: str = ""
    # process-parallel decode pool (data/decode_pool.py): worker process
    # count, 0 = decode in-thread.  Workers share image_cache_dir's disk
    # tier; pointless on a 1-core host (docs/PERF.md scaling table) but
    # the lever for feeding multiple chips from a many-core host
    decode_procs: int = 0


@dataclass(frozen=True)
class BucketConfig:
    """TPU addition (no reference equivalent — replaces the dynamic-shape
    rebinding of ref ``rcnn/core/module.py — MutableModule``).

    The reference resizes short side to SCALES[0][0]=600 capped at 1000 and
    rebinds executors per batch shape.  XLA requires static shapes, so images
    are resized the same way then padded into one of a small set of static
    buckets; aspect-ratio grouping (ref ASPECT_GROUPING) maps each image to
    the landscape or portrait bucket.

    Sublane note (r6): the default 608×1024 bucket yields a 38×64 stride-16
    feature grid, and 38 rows is hostile to the 8-sublane VPU register
    shape (38 = 4×8 + 6 — every (H-minor) retile pads ~5%).  The
    sublane-friendly alternative is 640×1024 (40×64 grid, 40 = 5×8) at
    +5.3% pixels — select it per run with
    ``--set bucket__shapes='[[640,1024],[1024,640]]'`` (anchors and bucket
    padding regenerate from the feature shape automatically; pinned by
    tests/test_anchors.py).  Whether the alignment win beats the pixel tax
    is a measured chip decision: script/perf_r6.sh leg 4 runs the A/B and
    docs/PERF.md "Round-6" records the adopt-or-refuse verdict.
    """

    scale: int = 600            # ref: SCALES[0][0] — target short side
    max_size: int = 1000        # ref: SCALES[0][1] — cap on long side
    # (H, W) static buckets, multiples of 32 to keep feature grids aligned.
    shapes: Tuple[Tuple[int, int], ...] = ((608, 1024), (1024, 608))


@dataclass(frozen=True)
class DataConfig:
    """TPU addition (no reference equivalent — the reference loader is a
    synchronous in-process iterator over a fully-materialized roidb):
    policy knobs for the STREAMING input plane (docs/DATA.md) — sharded
    loaders, bounded-memory decode windows, and double-buffered
    host→device staging.

    Same 3-level precedence as every section (hardcoded defaults <
    presets < ``--set data__field=value`` CLI overrides).
    """

    # use the topology-invariant streaming loader (``data/loader.py —
    # StreamLoader``) for training: image-granular epoch plan that is a
    # pure function of (seed, epoch), so shard unions and mid-epoch
    # resumes stay exactly-once across ANY worker/process/accum
    # topology.  DEFAULT FLIPPED to true at PR 11 after the soak leg
    # (train data-smoke + elastic-smoke green with streaming on —
    # docs/DATA.md "Streaming by default"); ``--set
    # data__streaming=false`` is the escape hatch back to the classic
    # AnchorLoader plan (bit-pinned by the pre-r7 resume tests).
    # Multi-process worlds shard the plan by batch ROWS either way, so
    # N processes decode 1/N of the data in both modes.
    streaming: bool = True
    # double-buffered host→device staging (``data/staging.py``): a
    # background thread assembles + device_puts the NEXT batch(es)
    # while the in-flight step runs, so the fit loop's data_wait gauge
    # goes to ~0 even when the dataset does not fit in HBM.  The
    # device-cache path (``--device_cache``) stays the small-set fast
    # path and bypasses staging entirely.
    staging: bool = True
    # device-resident batches kept in flight by the stager (>= 1;
    # each costs one batch of HBM — 2 = classic double buffering)
    stage_depth: int = 2
    # RAM ceiling for the host input plane in MiB (0 = unlimited): the
    # decoded-image cache budget and the per-worker shares are clamped
    # so cache + prefetch window + process floor fit under it
    # (``data/loader.py — stream_cache_budget``, logged once at loader
    # build), and ``tools/data_bench.py --check`` fails if measured RSS
    # exceeds it.
    ram_ceiling_mb: int = 0
    # NOTE deliberately NO shard_id/num_shards knobs here: training
    # derives loader-shard ownership from the process topology alone
    # (``tools/train.py`` — a lone training process given a shard would
    # silently train on 1/N of every batch), and bench rigs pass shard
    # ownership explicitly (``tools/data_bench.py --shard_id/--num_shards``
    # CLI, ``StreamLoader(shard=...)`` API).


@dataclass(frozen=True)
class ServeConfig:
    """TPU addition (no reference equivalent — the reference has no online
    inference path at all): policy knobs for the ``mx_rcnn_tpu/serve/``
    request/response engine (docs/SERVING.md).

    The engine coalesces single-image requests into per-bucket micro-batches
    and ALWAYS pads the batch to ``batch_size`` rows before dispatch, so one
    XLA program per (bucket, dtype) serves all traffic — the serving analog
    of the static train/eval buckets.
    """

    batch_size: int = 4         # static micro-batch rows per dispatch
    max_delay_ms: float = 10.0  # max wait to fill a micro-batch before
                                # dispatching it partial (tail-latency cap)
    queue_depth: int = 64       # hard per-bucket admission cap
    shed_watermark: int = 32    # shed (HTTP 429) once a bucket queue holds
                                # this many waiting requests (<= queue_depth)
    default_timeout_ms: float = 2000.0  # per-request deadline; 0 disables.
                                # Expired requests are cancelled BEFORE
                                # dispatch so dead work never occupies a
                                # batch slot
    score_thresh: float = 0.05  # serving detection floor (eval's 1e-3
                                # keeps near-zero boxes the AP sweep needs;
                                # a response wants confident boxes only)
    # request-body admission cap (MB): a claimed Content-Length above
    # this is refused 413 BEFORE any body byte is read; an absent one
    # (incl. chunked transfer) is 411 (netio.read_request_body)
    max_body_mb: float = 64.0


@dataclass(frozen=True)
class FleetConfig:
    """TPU addition (no reference equivalent): policy knobs for the
    ``mx_rcnn_tpu/serve/fleet.py`` serving fleet — N replica engines over
    device subsets behind a join-shortest-queue router, warmed from
    AOT-exported programs (``serve/export.py``) so a cold replica joins
    in seconds instead of paying trace+compile (docs/SERVING.md "Fleet
    tier").

    Same 3-level precedence as every section (hardcoded defaults <
    presets < ``--set fleet__field=value`` CLI overrides).
    """

    # replica engines in the fleet (each one full ServingEngine over its
    # own Predictor; tools/fleet.py serve --replicas overrides)
    replicas: int = 1
    # AOT export store directory ("" = trace-warm: every replica pays
    # the classic trace+compile warmup).  Written by
    # ``tools/fleet.py export``; holds serialized per-bucket programs +
    # manifest + the bundled XLA persistent cache.
    export_dir: str = ""
    # devices per replica (0 = divide jax.devices() evenly; replicas
    # beyond the device supply share the remainder round-robin).  A
    # subset of size > 1 becomes that replica's 1-D data mesh — the
    # mesh-sharded Predictor math from core/tester.py, per replica.
    devices_per_replica: int = 0
    # replica health monitor cadence: dead/unhealthy replicas are
    # ejected from the routing set and (when ``relaunch``) rebuilt via
    # the ft/supervisor.py RestartPolicy backoff schedule
    health_interval_s: float = 1.0
    # how many times the router re-dispatches a request whose replica
    # died before serving it (0 = fail straight to the client); reroutes
    # never extend the request's deadline
    reroute_retries: int = 1
    # relaunch crashed replicas (RestartPolicy paces retries and turns
    # repeated identical failures into a crash-loop verdict)
    relaunch: bool = True


@dataclass(frozen=True)
class CrosshostConfig:
    """TPU addition (no reference equivalent — the reference is strictly
    single-process): policy knobs for the cross-host serving plane
    (``serve/remote.py`` + ``serve/agent.py`` + ``serve/scheduler.py``,
    docs/SERVING.md "Cross-host tier") — per-host replica agents behind
    the fleet's ``Replica`` seam, dispatched over persistent keep-alive
    HTTP with a binary prepared-path wire format, an export-store
    distribution plane (one sha-verified resumable pull per joining
    host), and a gauge-driven scheduler that adds/drains replicas
    against traffic and re-places capacity when a host dies.

    Same 3-level precedence as every section (hardcoded defaults <
    presets < ``--set crosshost__field=value`` CLI overrides).
    """

    # comma-separated agent base URLs ("host:port,host:port" or
    # "name=url"); non-empty = tools/fleet.py serve builds a cross-host
    # router of RemoteReplicas instead of in-process engines
    agents: str = ""
    # persistent keep-alive HTTP connections per remote replica — each
    # one an independent request pipeline to the agent, so a remote
    # replica serves up to connections x pipeline_depth frames in flight
    connections: int = 2
    # in-flight frames admitted per connection (the bounded pipeline:
    # the frame that would exceed connections x pipeline_depth sheds at
    # the head instead of queueing unboundedly toward a slow host)
    pipeline_depth: int = 4
    # frames coalesced into one count-prefixed MXE1 envelope per send
    # (1 = every frame ships alone, the PR-15 behavior).  A wire worker
    # that finds several binary frames queued packs up to this many
    # into one vectored sendmsg / one HTTP round trip / one agent
    # wakeup — the burst-rate header+syscall amortization
    # tools/loadgen.py --wire_bench measures (serve/remote.py)
    frames_per_send: int = 1
    # adaptive per-connection pipelining: 0 keeps the fixed
    # pipeline_depth above; >= 1 lets each RemoteEngine self-tune its
    # depth in [1, pipeline_depth_max] by AIMD over windowed wire-RTT
    # samples (serve/remote.py PipelineController) — a slow or skewed
    # agent stops accumulating in-flight frames instead of inflating
    # fleet p99
    pipeline_depth_max: int = 0
    # socket-level I/O timeout for agent RPCs — a transport backstop
    # strictly above any request deadline (deadlines are enforced by the
    # agent's own admission path; this catches dead-host half-opens)
    io_timeout_s: float = 60.0
    # backlog-feed scrape cadence: the head polls each agent's /metrics
    # this often for bucket-lane depths (the JSQ routing signal) and
    # fleet gauges (the scheduler signal)
    scrape_interval_s: float = 0.25
    # consecutive transport/scrape failures before a remote replica
    # reads not-alive and the manager ejects it (single blips — one lost
    # frame, one slow scrape — must not eject a healthy host)
    dead_after_failures: int = 3
    # export-store distribution endpoint ("" = agents expect a local
    # fleet.export_dir already in place).  Set to the head's StoreServer
    # URL: a joining agent pulls the store ONCE (sha-verified,
    # resumable), then every local replica export-warms from disk.
    store_url: str = ""
    # replica engines each agent starts locally
    agent_replicas: int = 1
    # wire-body cap (MB), both directions: the agent refuses request
    # bodies claiming more (413), the head caps what it will buffer of
    # an agent response (RemoteTransportError past it).  Sized well
    # above the largest legitimate frame (a 1024x1024x3 fp32 prepared
    # canvas is 12 MB) and well below harm
    max_body_mb: float = 64.0
    # scheduler actuation RPC deadline: a hung agent costs one resize
    # call this much, surfaced as the typed AgentAdminTimeout — it can
    # never wedge the scheduler tick (serve/scheduler.py)
    admin_timeout_s: float = 5.0
    # per-request deadline on every store-pull HTTP call (/index and
    # each /f/<rel>); expiry surfaces as the typed StorePullError so a
    # dead store endpoint fails the join loudly instead of hanging it
    pull_timeout_s: float = 30.0
    # --- scheduler (serve/scheduler.py) ----------------------------------
    # fleet-wide ready-replica target (0 = adopt hosts x agent_replicas
    # at scheduler start); the host-death re-place signal: ready < target
    target_replicas: int = 0
    min_replicas: int = 1            # never drain below
    max_replicas: int = 8            # never add above
    # scale-up triggers, judged over window_s: shed ratio
    # (delta shed / delta submitted) above this...
    up_shed_ratio: float = 0.05
    # ...or mean bucket-lane backlog per ready replica above this many
    # images
    up_backlog: float = 2.0
    # hysteresis (the obs/health.py idiom): a trigger must hold for this
    # many consecutive decide() ticks to act...
    for_samples: int = 2
    # ...and the fleet must be fully idle (no backlog, no shed, ready >
    # min) for this many consecutive ticks before a drain
    idle_samples: int = 8
    # post-action cooldown: no further add/drain until the last action
    # is this old (lets the fleet absorb the resize before re-judging)
    cooldown_s: float = 5.0
    interval_s: float = 0.5          # scheduler tick cadence
    window_s: float = 10.0           # rate/ratio judgment window


@dataclass(frozen=True)
class BulkConfig:
    """TPU addition (no reference equivalent — the reference scores a
    corpus through a synchronous single-GPU eval loop): policy knobs for
    the offline bulk-inference plane (``serve/bulk.py``,
    docs/SERVING.md "Bulk tier") — a StreamLoader-fed corpus driven
    through the serving fleet's bucket lanes with backpressure-bounded
    in-flight depth and exactly-once sink accounting.

    Same 3-level precedence as every section (hardcoded defaults <
    presets < ``--set bulk__field=value`` CLI overrides).
    """

    # in-flight images admitted to the fleet at once (the backpressure
    # bound: the feeder blocks once this many images are between
    # submit_prepared and their terminal state).  0 = auto:
    # 2 x serve.batch_size x fleet.replicas, clamped under the per-lane
    # shed watermark so steady-state bulk traffic never sheds.
    max_inflight: int = 0
    # plan batches per committed sink shard — the atomicity AND resume
    # unit: a shard lands via tmp → fsync → rename (all-or-nothing under
    # SIGKILL) and the resume cursor is the contiguous committed-shard
    # prefix, so a killed run restarts exactly-once at the first
    # uncommitted shard.
    shard_batches: int = 16
    # resubmit budget per image for replica-death / shed transients (the
    # fleet router's own reroute_retries sit BELOW this — a resubmit is
    # a fresh fleet request).  Exhausting it aborts the whole run loudly:
    # bulk never silently drops an image (N in = N accounted).
    retries: int = 8


@dataclass(frozen=True)
class FTConfig:
    """TPU addition (no reference equivalent — the reference dies on
    preemption and restarts at the last epoch boundary): policy knobs for
    the ``mx_rcnn_tpu/ft/`` fault-tolerance layer (docs/FT.md).

    Same 3-level precedence as every section (hardcoded defaults <
    presets < ``--set ft__field=value`` CLI overrides).
    """

    # serialize+write+fsync checkpoints on a background writer thread so
    # the training step only pays the device_get; False restores the
    # fully synchronous write-on-the-training-thread path
    async_snapshots: bool = True
    # the writer admits one snapshot being written + one queued (at most
    # two fetched host copies alive); the request that would make a third
    # blocks up to this long, then fails loudly (never an unbounded
    # backlog of multi-hundred-MB serializations)
    slot_timeout_s: float = 120.0
    # retention GC (ft/integrity.py — gc_checkpoints): keep the newest
    # keep_last epoch checkpoints plus every keep_every-th epoch.  The
    # DEFAULT keep_every=1 marks every epoch as a keeper, i.e. nothing is
    # ever deleted — reference parity (the reference keeps all per-epoch
    # params files); raise it (e.g. ``--set ft__keep_every=5``) to thin
    # long runs.  keep_last=0 disables GC entirely.
    keep_last: int = 3
    keep_every: int = 1
    # ``--resume auto`` HARD-FAILS when the checkpoint manifest records a
    # different effective global batch (device count x batch_images x
    # grad_accum) than this run would train with — a silent batch change
    # alters the LR-schedule semantics and the experiment.  True downgrades
    # the error to a WARNING; the elastic controller (ft/elastic.py) sets
    # it for its own supervised restores, where the resize is the point.
    allow_resize_resume: bool = False
    # persistent XLA compilation cache directory ("" = off).  Wired at
    # CLI startup (tools/train.py, tools/serve.py, tools/fleet.py —
    # ``serve/export.py — enable_compile_cache``) into BOTH the live
    # process config and the child environment, so elastic relaunches
    # (EXIT_RESIZE/EXIT_PEER_FAILURE supervisor restarts) skip XLA
    # re-compilation and pay tracing only — the ROADMAP item 5
    # recovery-time lever (measured deltas: docs/FT.md "Recovery time").
    compile_cache_dir: str = ""


@dataclass(frozen=True)
class ElasticConfig:
    """TPU addition (no reference equivalent — the reference assumes a
    fixed device set for the whole run): policy knobs for the
    ``mx_rcnn_tpu/ft/elastic.py`` elastic run controller (docs/FT.md
    "Elasticity"), which turns preemption into a mesh resize: drain →
    restore the latest valid checkpoint onto the new mesh → rescale
    grad accumulation so the effective global batch stays on-recipe →
    keep stepping.

    Same 3-level precedence as every section (hardcoded defaults <
    presets < ``--set elastic__field=value`` CLI overrides).
    """

    # master switch (tools/train.py --elastic): wrap training in the
    # generation loop that watches topology directives and resizes live
    enabled: bool = False
    # the RECIPE's reference device count: effective global batch =
    # base_devices x batch_images (x process count folded in).  A mesh of
    # K devices trains with grad_accum = base_devices / K so the
    # optimizer-step cadence and LR schedule never leave the recipe.
    # 0 = adopt the first generation's device count as the base.
    base_devices: int = 0
    # where topology directives land ("" = <prefix>.topology.json); the
    # supervisor (or any scheduler) atomically writes
    # {"generation": G, "num_devices": D, "num_processes": P} here and
    # optionally SIGUSR1s the process to poll immediately
    topology_path: str = ""
    # directive poll cadence in optimizer steps (a stat() per poll; 1 =
    # every step — detection latency is bounded by one step either way
    # because SIGUSR1 forces an immediate poll)
    poll_steps: int = 1
    # runaway guard: a generation loop that resizes more than this many
    # times in one run aborts loudly instead of thrashing forever
    max_generations: int = 64


@dataclass(frozen=True)
class QuantConfig:
    """TPU addition (no reference equivalent — the reference serves
    fp32): policy knobs for the post-training quantized INFERENCE
    forward (``ops/quant.py``, docs/PERF.md "Quantized inference").
    Applies the Jacob et al. 2018 PTQ playbook to the serving/eval
    forward: per-output-channel symmetric weight quantization +
    per-tensor activation scales from an offline calibration sweep.

    OFF by default; with ``enabled=False`` every fp serving/eval output
    is BIT-identical to a build without the subsystem (pinned by
    ``tests/test_quant.py``).  Training always runs fp — this section
    is deliberately OUTSIDE the config fingerprint (like ``serve``/
    ``test``), and the export-store manifest records the knobs plus the
    calibration fingerprint instead, so a fleet replica can never mix
    quantized and fp programs unknowingly (``serve/export.py``).

    Same 3-level precedence as every section (hardcoded defaults <
    presets < ``--set quant__field=value`` CLI overrides).
    """

    # master switch: quantize the inference forward (eval Predictor,
    # serving engine, AOT exports); training is never quantized
    enabled: bool = False
    # container dtype: 'int8' (the int32-accumulate integer path) or
    # 'fp8' (e4m3, fp32-accumulate)
    dtype: str = "int8"
    # 'native' runs the real low-precision program (int8×int8 →
    # int32-accumulate dot/conv); 'sim' runs the same quantized integer
    # values in fp32 arithmetic (the fake-quant proxy — pinned
    # tile-level-equivalent to native by test)
    mode: str = "native"
    # activation-scale estimator over the calibration sweep: 'absmax'
    # (running max of |x|) or 'percentile' (mean of the per-batch
    # ``percentile``-th percentile of |x| — clips outlier tails)
    estimator: str = "absmax"
    percentile: float = 99.9
    # effective integer bits of the int8 container, SHARED by the weight
    # channels and the activation grid (both quantize to
    # qmax = 2^(b-1)-1).  8 = production; lower values are the red-team
    # over-quantization arm the accuracy gate must catch
    # (tools/gauntlet.py quant_redteam)
    weight_bits: int = 8
    # calibration sweep: how many held-out TRAINING batches feed the
    # activation statistics, and the seed of the deterministic
    # subsample of the training roidb they are drawn from
    calibration_batches: int = 2
    calibration_seed: int = 0
    # accuracy gate: |paired mAP delta| bound for the quantized arm,
    # consumed by make quant-smoke.  The full gauntlet takes its own
    # --budget flag (default 0.02) and the parity runbook its
    # QUANT_TOLERANCE env — pass matching values there when gating
    # `--compare e2e quant` on real data
    map_delta_budget: float = 0.05


@dataclass(frozen=True)
class ObsConfig:
    """TPU addition (no reference equivalent — the reference's only
    instrument is the Speedometer stdout line): policy knobs for the
    ``mx_rcnn_tpu/obs/`` unified observability layer
    (docs/OBSERVABILITY.md).

    Same 3-level precedence as every section (hardcoded defaults <
    presets < ``--set obs__field=value`` CLI overrides).  Everything is
    OFF by default; the disabled hot-path cost is pinned near zero by
    ``tests/test_obs.py``.
    """

    # master switch: wire the process metrics registry into the fit
    # loop, data loaders and snapshotter, and have the CLIs write a
    # runs/<id>/ run record (events.jsonl + BENCH summary.json)
    enabled: bool = False
    # base directory for run records
    run_dir: str = "runs"
    # serve the unified registry as JSON GET /metrics on this port from
    # tools/train.py (0 = off; tools/serve.py always exposes /metrics on
    # its own HTTP front end)
    metrics_port: int = 0
    # collect host-side spans (obs/trace.py) and export a chrome trace
    # into the run record on exit
    trace: bool = False
    trace_cap: int = 100_000     # span buffer bound (overflow counted)
    # on-demand profiler window (obs/profiler.py): capture a
    # profile_steps-step jax.profiler window starting at this GLOBAL
    # step (0 = never), rolled up into per-scope device-time tables
    profile_at_step: int = 0
    profile_steps: int = 3
    # where the window lands ("" = <run record dir>/profile)
    profile_dir: str = ""
    # arm SIGUSR2 as a live profiler toggle in the CLIs (kill -USR2 PID
    # starts a window, a second signal stops + rolls it up)
    sigusr2: bool = False
    # smoothing factor for the train.loss_ema gauge (per log window)
    loss_ema: float = 0.9
    # --- time-series plane (obs/timeseries.py) ---------------------------
    # ring-buffer sampler over the registry: counters→windowed rates,
    # gauges, exact windowed histogram percentiles; the substrate the
    # health engine and flight recorder read
    timeseries: bool = False
    sample_interval_s: float = 1.0   # sampler cadence
    ts_capacity: int = 600           # ring depth (samples)
    # --- SLO/health engine (obs/health.py) -------------------------------
    # evaluate the default rule set after every sample; publish
    # health.* gauges + runrec transitions + enriched /healthz
    health: bool = False
    health_window_s: float = 30.0    # default rule window (docs only —
    # the stock rules carry per-rule windows; kept as the knob custom
    # rule sets read)
    # --- flight recorder (obs/flightrec.py) ------------------------------
    # black-box dumps (runs/<id>/flight/) on crash / SIGTERM /
    # lock-watchdog trip / health-critical transition
    flight: bool = False
    flight_window_s: float = 120.0   # how much sample history a dump keeps
    flight_events: int = 512         # bounded event ring fed by runrec
    # --- cross-process collection (obs/collect.py) -----------------------
    # comma-separated /metrics URLs (host:port or full URL, optionally
    # name=url) merged into the labeled fleet view by tools/obs.py
    collect_urls: str = ""
    # --- distributed tracing (obs/trace.py distributed plane) ------------
    # head sampling probability for cross-host traces (0 = off: the
    # serve hot path pays one None-check and wire frames stay
    # bit-identical to the untraced layout — pinned by
    # tests/test_trace_distributed.py).  Deterministic fraction
    # accumulator, not a coin flip.
    trace_sample: float = 0.0
    trace_ring: int = 256            # kept span trees per process
    # forced tail retention: SERVED traces in the slowest percentile of
    # the recent window are kept alongside every non-SERVED/rerouted one
    trace_slow_pct: float = 99.0
    # obs.skew_ms.max drift alarm threshold (obs/health.py skew rule)
    skew_alarm_ms: float = 50.0


@dataclass(frozen=True)
class SimConfig:
    """TPU addition (no reference equivalent): policy knobs for the
    ``mx_rcnn_tpu/sim/`` fleet-at-scale simulator (docs/SIM.md) — a
    discrete-event virtual-time harness that runs the SHIPPED
    scheduler/health/router decision code over hundreds of simulated
    hosts.  Request-level semantics (batch size, shed watermark,
    deadline) are deliberately NOT duplicated here: the simulator reads
    ``cfg.serve`` and ``cfg.crosshost`` so a policy is gauntleted under
    the exact knobs it ships with.

    Same 3-level precedence as every section (hardcoded defaults <
    presets < ``--set sim__field=value`` CLI overrides).
    """

    hosts: int = 100            # simulated agent hosts (one registry each)
    duration_s: float = 240.0   # trace length in VIRTUAL seconds
    seed: int = 0               # root seed for every sim RNG substream
    # collector scrape / health / scheduler cadence in virtual seconds
    # (the sim analog of crosshost.scrape_interval_s, which is tuned for
    # wall-clock HTTP scraping and would be needless event pressure here)
    scrape_interval_s: float = 1.0
    # per-dispatch service time at the SMALLEST bucket (ms).  The engine
    # pads every micro-batch to serve.batch_size rows, so service cost
    # depends on the bucket, not the occupancy — 430 ms/batch-of-4
    # reproduces the ~9.3 img/s per-host rate CROSSHOST_r15 measured.
    # Larger buckets scale by pixel ratio.
    service_ms: float = 430.0
    service_jitter: float = 0.10   # lognormal sigma on service draws
    warmup_s: float = 5.0          # resize(+1) cold-join delay (vt)
    relaunch_s: float = 8.0        # host drain->relaunch dark time (vt)
    util: float = 0.65             # generators' base demand, as a
                                   # fraction of boot fleet capacity
    settle_s: float = 60.0         # post-trace drain budget before any
                                   # still-queued request counts lost


@dataclass(frozen=True)
class RolloutConfig:
    """TPU addition (no reference equivalent): knobs for the live-ops
    rollout plane (``serve/rollout.py``) — versioned export stores,
    per-host rolling updates, canary routing with the online paired
    gate, and first-class rollback (docs/SERVING.md "Rollout tier").

    Same 3-level precedence as every section (hardcoded defaults <
    presets < ``--set rollout__field=value`` CLI overrides).
    """

    # fraction of traffic the JSQ router sends down the canary version
    # lane while the gate observes (deterministic fraction accumulator,
    # not a coin flip — byte-reproducible under the simulator)
    canary_fraction: float = 0.25
    # online paired gate: equivalence budget on the shadow-score scale
    # (same CI-inside-±budget TOST judgment as tools/gauntlet.py
    # paired_compare) and the minimum paired samples before judging
    gate_budget: float = 0.02
    gate_min_pairs: int = 12
    # shadow-score every Nth sampled canary opportunity (live: every Nth
    # controller tick; sim: every Nth virtual gate tick)
    gate_sample_every: int = 4
    # canary dwell before rolling: the gate and HealthEngine observe at
    # least this long even if min_pairs is reached earlier
    bake_s: float = 10.0
    # per-host swap step bound: a host that stops answering mid-step is
    # skipped after this long and re-checked during FINALIZE (the
    # kill-mid-rollout convergence path)
    step_timeout_s: float = 60.0
    # hosts rolled concurrently (the wave width).  1 = strictly serial
    # per-host rolling (the live default); the 100-host sim scenario
    # overrides this to a wave, as a real fleet runbook would
    wave: int = 1
    # cadence of controller re-checks while waiting on pulls / warms /
    # drains (virtual seconds under the sim, wall seconds live)
    settle_s: float = 1.0
    # simulated store-pull latency (virtual seconds) for the sim port
    pull_s: float = 3.0
    # red-team arm: deterministic shadow-score damage applied to the v2
    # arm (sim + bench only; 0.0 = healthy).  The online-gate analog of
    # the gauntlet's _REDTEAM_NMS damaged arm.
    redteam_damage: float = 0.0


@dataclass(frozen=True)
class Config:
    train: TrainConfig = field(default_factory=TrainConfig)
    test: TestConfig = field(default_factory=TestConfig)
    network: NetworkConfig = field(default_factory=NetworkConfig)
    dataset: DatasetConfig = field(default_factory=DatasetConfig)
    default: DefaultConfig = field(default_factory=DefaultConfig)
    bucket: BucketConfig = field(default_factory=BucketConfig)
    data: DataConfig = field(default_factory=DataConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    crosshost: CrosshostConfig = field(default_factory=CrosshostConfig)
    bulk: BulkConfig = field(default_factory=BulkConfig)
    ft: FTConfig = field(default_factory=FTConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    elastic: ElasticConfig = field(default_factory=ElasticConfig)
    quant: QuantConfig = field(default_factory=QuantConfig)
    sim: SimConfig = field(default_factory=SimConfig)
    rollout: RolloutConfig = field(default_factory=RolloutConfig)

    @property
    def num_classes(self) -> int:
        return self.dataset.num_classes

    def replace(self, **kw: Any) -> "Config":
        return dataclasses.replace(self, **kw)

    def replace_in(self, section: str, **kw: Any) -> "Config":
        """Return a new Config with fields replaced inside one section,
        e.g. ``cfg.replace_in('train', batch_images=2)``."""
        return dataclasses.replace(
            self, **{section: dataclasses.replace(getattr(self, section), **kw)})


# ---------------------------------------------------------------------------
# Network / dataset presets (ref rcnn/config.py — generate_config)
# ---------------------------------------------------------------------------

_NETWORKS: Mapping[str, Mapping[str, Any]] = {
    "vgg": dict(
        name="vgg",
        depth=16,
        rcnn_pooled_size=(7, 7),
        # ref: VGG FIXED_PARAMS = ['conv1', 'conv2'] — freeze first two blocks
        fixed_params=("conv1", "conv2"),
        fixed_params_shared=("conv1", "conv2", "conv3", "conv4", "conv5"),
        image_stride=0,
    ),
    "resnet50": dict(name="resnet50", depth=50, rcnn_pooled_size=(14, 14)),
    "resnet101": dict(name="resnet101", depth=101, rcnn_pooled_size=(14, 14)),
    # test-only miniature network (see models/tiny.py); small anchors so
    # tiny test images still contain in-image anchors
    "tiny": dict(
        name="tiny", depth=0, rcnn_pooled_size=(7, 7),
        # 32/64/128-px anchors: cover both the 128-px unit-test canvases and
        # the synthetic dataset's 320x400 canvases (objects span 1/5..1/2 of
        # the canvas in data/synthetic.py)
        anchor_scales=(2, 4, 8), fixed_params=(),
        # tiny's whole backbone is conv1+conv2 — the alternate-training
        # shared-conv freeze must cover it for the combine to be valid
        fixed_params_shared=("conv1", "conv2"),
        compute_dtype="float32",
    ),
}

_DATASETS: Mapping[str, Mapping[str, Any]] = {
    "PascalVOC": dict(
        name="PascalVOC",
        image_set="2007_trainval",
        test_image_set="2007_test",
        dataset_path="data/VOCdevkit",
        num_classes=21,
    ),
    "coco": dict(
        name="coco",
        image_set="train2017",
        test_image_set="val2017",
        dataset_path="data/coco",
        num_classes=81,
    ),
    # download-free generated dataset (data/synthetic.py) — the end-to-end
    # train→eval gate runs on it; no reference equivalent
    "synthetic": dict(
        name="synthetic",
        image_set="train",
        test_image_set="test",
        dataset_path="data/synthetic",
        num_classes=4,
    ),
    # the accuracy gauntlet (data/synthetic.py — HardSyntheticDataset):
    # 8 fg classes, 200/100 images, scale/occlusion/crowding + distractors
    "synthetic_hard": dict(
        name="synthetic_hard",
        image_set="train",
        test_image_set="test",
        dataset_path="data/synthetic_hard",
        num_classes=9,
    ),
    # COCO-cardinality rehearsal set (data/synthetic.py —
    # StreamSyntheticDataset): 80 fg classes, 10k/1k images — sized so
    # input-plane claims are tested at production cardinality, not on
    # sets that fit in HBM (docs/DATA.md)
    "synthetic_stream": dict(
        name="synthetic_stream",
        image_set="train",
        test_image_set="test",
        dataset_path="data/synthetic_stream",
        num_classes=81,
    ),
}

# Per-dataset bucket presets (TPU addition): synthetic canvases are
# 320x400 (hard: 240x320), so resizing them to the VOC 600/1000 scale
# would only waste compute on interpolated pixels.
_DATASET_BUCKETS: Mapping[str, Mapping[str, Any]] = {
    "synthetic": dict(scale=320, max_size=416,
                      shapes=((320, 416), (416, 320))),
    "synthetic_hard": dict(scale=240, max_size=320,
                           shapes=((240, 320), (320, 240))),
    "synthetic_stream": dict(scale=240, max_size=320,
                             shapes=((240, 320), (320, 240))),
}


def generate_config(network: str = "resnet101", dataset: str = "PascalVOC",
                    **overrides: Any) -> Config:
    """Build an immutable Config from network+dataset presets.

    Reference: ``rcnn/config.py — generate_config(network, dataset)`` which
    mutates the global singleton; here a fresh Config is returned.
    ``overrides`` may address nested fields with a ``section__field`` key,
    e.g. ``generate_config('vgg', 'PascalVOC', train__batch_images=2)``.
    """
    if network not in _NETWORKS:
        raise KeyError(f"unknown network {network!r}; have {sorted(_NETWORKS)}")
    if dataset not in _DATASETS:
        raise KeyError(f"unknown dataset {dataset!r}; have {sorted(_DATASETS)}")
    cfg = Config(
        network=NetworkConfig(**_NETWORKS[network]),
        dataset=DatasetConfig(**_DATASETS[dataset]),
    )
    if dataset in _DATASET_BUCKETS:
        cfg = cfg.replace_in("bucket", **_DATASET_BUCKETS[dataset])
    by_section: dict = {}
    for key, val in overrides.items():
        if "__" not in key:
            raise KeyError(f"override {key!r} must be 'section__field'")
        section, fname = key.split("__", 1)
        by_section.setdefault(section, {})[fname] = val
    for section, kw in by_section.items():
        node = getattr(cfg, section, None)
        if node is not None:
            # resolved type objects (not strings): get_type_hints evaluates
            # the `from __future__ import annotations` strings against the
            # module namespace, so every Optional/Union spelling works
            try:
                declared = typing.get_type_hints(type(node))
            except Exception:  # unresolvable forward ref: fall back to cur
                declared = {}
            kw = {f: _coerce_override(getattr(node, f, None), v,
                                      f"{section}__{f}", declared.get(f))
                  for f, v in kw.items()}
        cfg = cfg.replace_in(section, **kw)
    return cfg


_BOOL_STRINGS = {"true": True, "yes": True, "1": True,
                 "false": False, "no": False, "0": False}


_DTYPE_STRINGS = ("float32", "bfloat16")


def validate_dtype_string(val: str, key: str) -> str:
    """Dtype-string config fields (``compute_dtype``, ``momentum_dtype``)
    accept exactly two spellings; anything else must FAIL loudly — a typo
    like 'bf16' silently falling back to float32 would erase the memory
    saving the user asked for with no signal."""
    if val not in _DTYPE_STRINGS:
        raise ValueError(
            f"{key} must be one of {_DTYPE_STRINGS}, got {val!r}")
    return val


def _synthetic_exemplar(tp: Any) -> Any:
    """An exemplar value of a field's RESOLVED declared type, used to drive
    coercion when the field's *current* value is None (advisor r3: keying
    coercion off a None value silently skipped all type checks).  ``tp``
    comes from ``typing.get_type_hints``, so Optional[X], Union[X, None]
    and ``X | None`` all arrive as unions and unwrap uniformly.  Returns
    None for types coercion doesn't handle."""
    origin = typing.get_origin(tp)
    union_kinds = (typing.Union, getattr(types, "UnionType", ()))
    if origin in union_kinds:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) != 1:
            return None  # genuinely multi-typed field: store as-is
        tp = args[0]
        origin = typing.get_origin(tp)
    if tp is tuple or origin is tuple:
        return ()
    return {bool: False, int: 0, float: 0.0, str: ""}.get(tp)


def _coerce_override(cur: Any, val: Any, key: str,
                     annotation: Any = None) -> Any:
    """Coerce a config override to the field's declared type.

    Frozen dataclasses do no type checking, and CLI ``--set`` values may
    arrive as strings (``--set train__shuffle=false``) — without coercion
    the string "false" would be stored and read as truthy.  Unknown fields
    (cur is None AND no annotation, because getattr missed) pass through so
    replace_in can raise its own error.  A known field whose current value
    is None coerces against its declared annotation instead, so None
    defaults still get type errors on bad literals.
    """
    if val is None:
        return val
    if cur is None:
        if annotation is None:
            return val
        cur = _synthetic_exemplar(annotation)
        if cur is None:  # un-coercible declared type: store as-is
            return val
    if isinstance(cur, bool):
        if isinstance(val, bool):
            return val
        if isinstance(val, int) and val in (0, 1):
            return bool(val)
        if isinstance(val, str) and val.lower() in _BOOL_STRINGS:
            return _BOOL_STRINGS[val.lower()]
        raise TypeError(f"{key} expects a bool, got {val!r}")
    if isinstance(cur, int):
        if isinstance(val, bool) or (isinstance(val, float)
                                     and not val.is_integer()):
            raise TypeError(f"{key} expects an int, got {val!r}")
        try:
            return int(val)
        except (TypeError, ValueError):
            raise TypeError(f"{key} expects an int, got {val!r}")
    if isinstance(cur, float):
        if isinstance(val, bool):
            raise TypeError(f"{key} expects a float, got {val!r}")
        try:
            return float(val)
        except (TypeError, ValueError):
            raise TypeError(f"{key} expects a float, got {val!r}")
    if isinstance(cur, tuple):
        if isinstance(val, (list, tuple)):
            # deep-convert so no mutable list nests inside the frozen
            # config (shapes etc. are tuples of tuples)
            return tuple(tuple(v) if isinstance(v, (list, tuple)) else v
                         for v in val)
        raise TypeError(f"{key} expects a tuple/list, got {val!r}")
    if isinstance(cur, str) and not isinstance(val, str):
        raise TypeError(f"{key} expects a string, got {val!r}")
    return val
