"""Fused RPN proposal generation: decode + clip + min-size + top-k + NMS + pad.

Reference: ``mx.symbol.Proposal`` (MXNet contrib C++/CUDA op) and its Python
twin ``rcnn/symbol/proposal.py — ProposalOperator`` — in the reference this
is a mid-graph CustomOp that copies RPN scores/deltas to the host, runs
NumPy + Cython NMS, and copies the ROIs back (the biggest per-step sync in
the reference hot loop, see SURVEY.md §3.1).

TPU-native design: a single jit-compatible function with **static shapes
end to end** — the variable-length survivor set of the reference becomes a
fixed ``(post_nms_top_n, 4)`` buffer plus a validity mask.  Invalid slots are
filled with the top surviving box so downstream ROI pooling always sees a
well-formed box; ``proposal_target`` masks them out via the validity flags
(padding boxes are never sampled as fg/bg — if they reach the sampled batch
as filler they are labelled -1/ignore and excluded from every loss).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.ops.boxes import bbox_pred, clip_boxes
from mx_rcnn_tpu.ops.nms import nms, nms_batch


def _decode_filter_topk(scores, bbox_deltas, anchors, im_info,
                        pre_nms_top_n: int, min_size: int):
    """Stages 1–3 of the proposal op for ONE image: decode + clip,
    min-size filter, pre-NMS top-k.  Shared by the per-image and batched
    paths so their pre-NMS candidate sets are identical by construction.

    Returns (top_boxes (pre, 4), top_scores (pre,), top_valid (pre,))."""
    n = scores.shape[0]
    scores = scores.astype(jnp.float32)
    # 1. decode + clip to the real image extent
    proposals = bbox_pred(anchors, bbox_deltas.astype(jnp.float32))
    proposals = clip_boxes(proposals, (im_info[0], im_info[1]))
    # 2. min-size filter at input scale (ref: min_size * im_info[2])
    ws = proposals[:, 2] - proposals[:, 0] + 1.0
    hs = proposals[:, 3] - proposals[:, 1] + 1.0
    min_sz = min_size * im_info[2]
    size_ok = (ws >= min_sz) & (hs >= min_sz)
    scores = jnp.where(size_ok, scores, -jnp.inf)
    # 3. pre-NMS top-k (cap at N — small images have fewer anchors)
    pre = min(pre_nms_top_n, n)
    top_scores, top_idx = jax.lax.top_k(scores, pre)
    return proposals[top_idx], top_scores, jnp.isfinite(top_scores)


def _compact_rois(top_boxes, top_scores, keep_idx, keep_valid):
    """Stage 5 for ONE image: gather NMS survivors into the fixed buffer,
    filling padded slots with the best surviving box (slot 0 survives NMS
    by construction whenever any valid proposal exists)."""
    safe_idx = jnp.maximum(keep_idx, 0)
    rois = top_boxes[safe_idx]
    roi_scores = jnp.where(keep_valid, top_scores[safe_idx], 0.0)
    rois = jnp.where(keep_valid[:, None], rois, rois[0][None, :])
    return rois, roi_scores, keep_valid


@functools.partial(
    jax.jit,
    static_argnames=("pre_nms_top_n", "post_nms_top_n", "nms_thresh", "min_size"),
)
def propose(
    scores: jnp.ndarray,
    bbox_deltas: jnp.ndarray,
    anchors: jnp.ndarray,
    im_info: jnp.ndarray,
    pre_nms_top_n: int = 6000,
    post_nms_top_n: int = 300,
    nms_thresh: float = 0.7,
    min_size: int = 16,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Generate ROIs from one image's RPN outputs.

    Args:
      scores: (N,) foreground probabilities, N = H*W*A (framework HWA order).
      bbox_deltas: (N, 4) RPN regression output.
      anchors: (N, 4) shifted anchors for this feature grid (constant).
      im_info: (3,) = (img_height, img_width, im_scale) of the real image
        content inside the padded bucket (ref ``im_info`` blob).
      pre_nms_top_n / post_nms_top_n / nms_thresh / min_size: ref Proposal op
        attrs (TRAIN: 12000/2000/0.7/16; TEST: 6000/300/0.7/16).

    Returns:
      rois: (post_nms_top_n, 4) clipped proposal boxes.
      roi_scores: (post_nms_top_n,) their fg scores.
      roi_valid: (post_nms_top_n,) bool — False for padded slots.
    """
    # stages 1–3 (decode+clip, min-size, top-k) shared with the batched path
    top_boxes, top_scores, top_valid = _decode_filter_topk(
        scores, bbox_deltas, anchors, im_info, pre_nms_top_n, min_size)
    # 4. NMS + fixed-size compaction
    keep_idx, keep_valid = nms(
        top_boxes, top_scores, nms_thresh, post_nms_top_n, valid=top_valid
    )
    # 5. fill padded slots (see _compact_rois)
    return _compact_rois(top_boxes, top_scores, keep_idx, keep_valid)


def propose_batch(
    scores: jnp.ndarray,
    bbox_deltas: jnp.ndarray,
    anchors: jnp.ndarray,
    im_info: jnp.ndarray,
    batched_nms: bool = True,
    **kw,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Batched :func:`propose` over a leading batch axis.

    scores (B, N), bbox_deltas (B, N, 4), im_info (B, 3); anchors shared.

    With ``batched_nms=True`` (the default — the r6 production path) the
    per-image stages (decode/top-k/compaction) run under vmap but the NMS
    sweep runs as ONE cross-image batched pass (:func:`nms_batch`),
    decision-exact vs ``vmap(propose)`` (pinned by
    ``tests/test_proposal.py``).  ``batched_nms=False`` restores the pure
    vmap-of-propose composition — kept as the A/B arm for
    ``tools/profile_step.py --nms_mode per_image``.
    """
    if not batched_nms:
        fn = functools.partial(propose, **kw)
        return jax.vmap(fn, in_axes=(0, 0, None, 0))(
            scores, bbox_deltas, anchors, im_info)
    pre_nms_top_n = kw.pop("pre_nms_top_n", 6000)
    post_nms_top_n = kw.pop("post_nms_top_n", 300)
    nms_thresh = kw.pop("nms_thresh", 0.7)
    min_size = kw.pop("min_size", 16)
    if kw:
        raise TypeError(f"unknown propose_batch kwargs {sorted(kw)}")
    top_boxes, top_scores, top_valid = jax.vmap(
        lambda s, d, i: _decode_filter_topk(s, d, anchors, i,
                                            pre_nms_top_n, min_size)
    )(scores, bbox_deltas, im_info)
    keep_idx, keep_valid = nms_batch(
        top_boxes, top_scores, nms_thresh, post_nms_top_n, valid=top_valid)
    return jax.vmap(_compact_rois)(top_boxes, top_scores, keep_idx,
                                   keep_valid)
