"""Fused RPN proposal generation: decode + clip + min-size + top-k + NMS + pad.

Reference: ``mx.symbol.Proposal`` (MXNet contrib C++/CUDA op) and its Python
twin ``rcnn/symbol/proposal.py — ProposalOperator`` — in the reference this
is a mid-graph CustomOp that copies RPN scores/deltas to the host, runs
NumPy + Cython NMS, and copies the ROIs back (the biggest per-step sync in
the reference hot loop, see SURVEY.md §3.1).

TPU-native design: a single jit-compatible function with **static shapes
end to end** — the variable-length survivor set of the reference becomes a
fixed ``(post_nms_top_n, 4)`` buffer plus a validity mask.  Invalid slots are
filled with the top surviving box so downstream ROI pooling always sees a
well-formed box; ``proposal_target`` masks them out via the validity flags
(padding boxes are never sampled as fg/bg — if they reach the sampled batch
as filler they are labelled -1/ignore and excluded from every loss).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.ops.boxes import bbox_pred, clip_boxes
from mx_rcnn_tpu.ops.nms import nms


@functools.partial(
    jax.jit,
    static_argnames=("pre_nms_top_n", "post_nms_top_n", "nms_thresh", "min_size"),
)
def propose(
    scores: jnp.ndarray,
    bbox_deltas: jnp.ndarray,
    anchors: jnp.ndarray,
    im_info: jnp.ndarray,
    pre_nms_top_n: int = 6000,
    post_nms_top_n: int = 300,
    nms_thresh: float = 0.7,
    min_size: int = 16,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Generate ROIs from one image's RPN outputs.

    Args:
      scores: (N,) foreground probabilities, N = H*W*A (framework HWA order).
      bbox_deltas: (N, 4) RPN regression output.
      anchors: (N, 4) shifted anchors for this feature grid (constant).
      im_info: (3,) = (img_height, img_width, im_scale) of the real image
        content inside the padded bucket (ref ``im_info`` blob).
      pre_nms_top_n / post_nms_top_n / nms_thresh / min_size: ref Proposal op
        attrs (TRAIN: 12000/2000/0.7/16; TEST: 6000/300/0.7/16).

    Returns:
      rois: (post_nms_top_n, 4) clipped proposal boxes.
      roi_scores: (post_nms_top_n,) their fg scores.
      roi_valid: (post_nms_top_n,) bool — False for padded slots.
    """
    n = scores.shape[0]
    scores = scores.astype(jnp.float32)
    # 1. decode + clip to the real image extent
    proposals = bbox_pred(anchors, bbox_deltas.astype(jnp.float32))
    proposals = clip_boxes(proposals, (im_info[0], im_info[1]))
    # 2. min-size filter at input scale (ref: min_size * im_info[2])
    ws = proposals[:, 2] - proposals[:, 0] + 1.0
    hs = proposals[:, 3] - proposals[:, 1] + 1.0
    min_sz = min_size * im_info[2]
    size_ok = (ws >= min_sz) & (hs >= min_sz)
    scores = jnp.where(size_ok, scores, -jnp.inf)
    # 3. pre-NMS top-k (cap at N — small images have fewer anchors than 12000)
    pre = min(pre_nms_top_n, n)
    top_scores, top_idx = jax.lax.top_k(scores, pre)
    top_boxes = proposals[top_idx]
    top_valid = jnp.isfinite(top_scores)
    # 4. NMS + fixed-size compaction
    keep_idx, keep_valid = nms(
        top_boxes, top_scores, nms_thresh, post_nms_top_n, valid=top_valid
    )
    safe_idx = jnp.maximum(keep_idx, 0)
    rois = top_boxes[safe_idx]
    roi_scores = jnp.where(keep_valid, top_scores[safe_idx], 0.0)
    # 5. fill padded slots with the best surviving box (slot 0 survives NMS
    #    by construction whenever any valid proposal exists)
    rois = jnp.where(keep_valid[:, None], rois, rois[0][None, :])
    return rois, roi_scores, keep_valid


def propose_batch(
    scores: jnp.ndarray,
    bbox_deltas: jnp.ndarray,
    anchors: jnp.ndarray,
    im_info: jnp.ndarray,
    **kw,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """vmap of :func:`propose` over a leading batch axis.

    scores (B, N), bbox_deltas (B, N, 4), im_info (B, 3); anchors shared.
    """
    fn = functools.partial(propose, **kw)
    return jax.vmap(fn, in_axes=(0, 0, None, 0))(scores, bbox_deltas, anchors, im_info)
