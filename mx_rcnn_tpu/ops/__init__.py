"""Device-side ops: the TPU-native replacements for the reference's
Cython/CUDA kernels (``rcnn/cython/``), NumPy geometry
(``rcnn/processing/``) and CustomOp graph layers (``rcnn/symbol/proposal*``,
``rcnn/io/rpn.py``, ``rcnn/io/rcnn.py``).

Everything in this package is pure-functional jnp, shape-static, and safe
inside ``jax.jit`` — one XLA program per training step, no host bounces.
"""

from mx_rcnn_tpu.ops.anchors import generate_anchors, generate_shifted_anchors  # noqa: F401
from mx_rcnn_tpu.ops.boxes import (  # noqa: F401
    bbox_overlaps,
    bbox_transform,
    bbox_pred,
    clip_boxes,
)
from mx_rcnn_tpu.ops.nms import (nms, nms_batch, nms_mask,  # noqa: F401
                                 nms_mask_batch)
from mx_rcnn_tpu.ops.proposal import propose, propose_batch  # noqa: F401
from mx_rcnn_tpu.ops.roi_pool import (roi_align, roi_align_batched,  # noqa: F401
                                      roi_align_blocked, roi_pool)
from mx_rcnn_tpu.ops.targets import anchor_target, proposal_target  # noqa: F401
from mx_rcnn_tpu.ops.losses import (  # noqa: F401
    smooth_l1,
    softmax_cross_entropy_with_ignore,
)
