"""Post-training quantization for the INFERENCE forward path (int8 / fp8).

No reference equivalent — the reference serves fp32.  This module applies
the standard post-training-quantization playbook (Jacob et al. 2018,
"Quantization and Training of Neural Networks for Efficient
Integer-Arithmetic-Only Inference") to the Faster R-CNN serving forward
(docs/PERF.md "Quantized inference"):

* **weights**: per-output-channel symmetric quantization (zero-point 0),
  scale = absmax / qmax per channel, computed from the fp32 checkpoint at
  trace time — no weight rewriting, checkpoints stay fp32 and load into
  the quantized model unchanged (same param names/shapes);
* **activations**: per-tensor symmetric quantization with scales from an
  offline calibration sweep over a held-out batch set
  (:func:`finalize_calibration` — ``absmax`` and ``percentile``
  estimators, both DETERMINISTIC given the calibration set: the sweep is
  pure jnp over a fixed batch order, pinned by ``tests/test_quant.py``);
* **two execution paths** behind ``QuantSpec.mode``:
  ``'sim'`` runs the quantized *integer values* in fp32 arithmetic (the
  fake-quant simulation — runs anywhere, including this CPU box), and
  ``'native'`` runs the real low-precision program: int8×int8 →
  **int32-accumulate** ``lax.dot_general`` / ``lax.conv_general_dilated``
  (fp8 e4m3 → fp32-accumulate), with ONE fp32 rescale at the end.  The
  two paths compute the same real-arithmetic value; they are pinned
  BIT-EQUAL at the tile level (contraction sizes where fp32 accumulation
  of integer products is exact, i.e. count·qmax² < 2²⁴) and allclose
  beyond it — the sim path is therefore a faithful accuracy proxy for
  the native program, which is what the gauntlet accuracy gate runs.

Quantized layers cover the backbone convs and the per-ROI head trunk
(``models/layers.py — QuantConv/QuantDense``); the RPN head and the
final ``cls_score``/``bbox_pred`` projections stay fp (first/last-layer
exemption, per the PTQ playbook).  The whole subsystem is OFF by default
and the fp path is bit-identical to a build without it (pinned by test).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Dict, Mapping, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_DTYPES = ("int8", "fp8")
_MODES = ("native", "sim")
_ESTIMATORS = ("absmax", "percentile")
_PHASES = ("apply", "calib")

# fp8 e4m3fn dynamic range (finite max); values are clipped here BEFORE
# the cast — e4m3fn saturates overflow to NaN, not to the max finite
FP8_MAX = 448.0

# keys a calibration-stats node carries (one per quantized layer)
_STAT_KEYS = frozenset({"amax", "psum", "pcnt"})


@dataclasses.dataclass(frozen=True)
class QuantSpec:
    """Static quantization recipe, threaded through the model modules as
    a flax field (frozen + hashable so module comparison/jit keying
    work).  Built from ``cfg.quant`` by ``models.build_model``."""

    dtype: str = "int8"        # 'int8' | 'fp8' (e4m3)
    mode: str = "native"       # 'native' low-precision program | 'sim'
    estimator: str = "absmax"  # activation-scale estimator
    percentile: float = 99.9   # for estimator='percentile'
    # effective integer bits for the int8 container, SHARED by the
    # weight channels and the activation grid (qmax = 2^(b-1)-1 for
    # both); 8 = production, lower values are the red-team
    # over-quantization arm the accuracy gate must catch
    # (tools/gauntlet.py quant_redteam)
    weight_bits: int = 8
    # 'apply' runs quantized; 'calib' runs the fp forward while
    # recording activation statistics into the 'quant_stats' collection
    phase: str = "apply"

    def __post_init__(self):
        if self.dtype not in _DTYPES:
            raise ValueError(f"quant dtype must be one of {_DTYPES}, "
                             f"got {self.dtype!r}")
        if self.mode not in _MODES:
            raise ValueError(f"quant mode must be one of {_MODES}, "
                             f"got {self.mode!r}")
        if self.estimator not in _ESTIMATORS:
            raise ValueError(f"quant estimator must be one of "
                             f"{_ESTIMATORS}, got {self.estimator!r}")
        if self.phase not in _PHASES:
            raise ValueError(f"quant phase must be one of {_PHASES}, "
                             f"got {self.phase!r}")
        if not 2 <= self.weight_bits <= 8:
            raise ValueError(f"quant weight_bits must be in [2, 8], "
                             f"got {self.weight_bits}")
        if self.dtype == "fp8" and self.weight_bits != 8:
            # qmax for fp8 is the format's own max — narrowing
            # weight_bits would be silently ignored, turning e.g. the
            # red-team over-quantization arm into a full-precision no-op
            raise ValueError("weight_bits only narrows the int8 "
                             "container; use dtype='int8' with "
                             f"weight_bits={self.weight_bits}")

    @property
    def qmax(self) -> float:
        """Largest representable magnitude of the quantized container."""
        if self.dtype == "fp8":
            return FP8_MAX
        return float(2 ** (self.weight_bits - 1) - 1)


def spec_from_config(qcfg: "QuantConfig", phase: str = "apply") -> QuantSpec:
    """``cfg.quant`` → :class:`QuantSpec` (validates every knob).  The
    string annotation is documentation + configlint's section anchor —
    ops/ stays import-light (no config.py import at runtime)."""
    return QuantSpec(dtype=qcfg.dtype, mode=qcfg.mode,
                     estimator=qcfg.estimator, percentile=qcfg.percentile,
                     weight_bits=qcfg.weight_bits, phase=phase)


# ---------------------------------------------------------------------------
# quantize / dequantize primitives
# ---------------------------------------------------------------------------

def _unit(est: jnp.ndarray, qmax: float) -> jnp.ndarray:
    """Quantization step size from an absmax-style estimate; floored so
    an all-zero channel/tensor divides by a representable epsilon
    instead of 0 (its quantized values are exactly 0 either way)."""
    return jnp.maximum(est.astype(jnp.float32), 1e-12) / qmax


def _quantize(x: jnp.ndarray, unit: jnp.ndarray, spec: QuantSpec
              ) -> jnp.ndarray:
    """Shared container mapping for weights and activations: scale by
    ``unit`` then clip/round/cast into the spec's container."""
    if spec.dtype == "fp8":
        return jnp.clip(x / unit, -FP8_MAX, FP8_MAX).astype(
            jnp.float8_e4m3fn)
    q = jnp.clip(jnp.round(x / unit), -spec.qmax, spec.qmax)
    return q.astype(jnp.int8 if spec.mode == "native" else jnp.float32)


def quantize_weight(w: jnp.ndarray, spec: QuantSpec
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-output-channel symmetric weight quantization.  ``w`` is an
    fp32 kernel whose LAST axis is the output-channel axis (HWIO conv
    kernels and (K, N) dense kernels both satisfy this).  Returns
    ``(q, unit)`` with ``unit`` shaped (out_channels,):
    dequantized = q * unit."""
    w = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w), axis=tuple(range(w.ndim - 1)))
    unit = _unit(absmax, spec.qmax)
    return _quantize(w, unit, spec), unit


def quantize_act(x: jnp.ndarray, est: jnp.ndarray, spec: QuantSpec
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-tensor symmetric activation quantization against the
    calibrated scale estimate ``est`` (a scalar from
    :func:`finalize_calibration`).  Returns ``(q, unit)``."""
    x = x.astype(jnp.float32)
    unit = _unit(est, spec.qmax)
    return _quantize(x, unit, spec), unit


def fake_quant(x: jnp.ndarray, est: jnp.ndarray, spec: QuantSpec
               ) -> jnp.ndarray:
    """Quantize-dequantize round trip (the classic fake-quant op):
    returns the fp32 values the quantized representation can express."""
    q, unit = quantize_act(x, est, spec)
    return q.astype(jnp.float32) * unit


# ---------------------------------------------------------------------------
# quantized contractions (the sim / native pair)
# ---------------------------------------------------------------------------

def _accum(qx: jnp.ndarray, qw: jnp.ndarray, spec: QuantSpec, conv_kw=None):
    """The low-precision contraction, one implementation per container:
    int8 native accumulates in **int32** (exact integer arithmetic); the
    sim path and fp8 accumulate in fp32.  ``conv_kw`` switches dot →
    conv."""
    if spec.dtype == "fp8" or spec.mode != "native":
        acc_t = jnp.float32
        if spec.dtype != "fp8":
            # sim: integer values carried in fp32 — keep them as-is
            qx, qw = qx.astype(jnp.float32), qw.astype(jnp.float32)
    else:
        acc_t = jnp.int32
    if conv_kw is None:
        y = lax.dot_general(qx, qw, (((qx.ndim - 1,), (0,)), ((), ())),
                            preferred_element_type=acc_t)
    else:
        y = lax.conv_general_dilated(
            qx, qw, conv_kw["strides"], conv_kw["padding"],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=acc_t)
    return y.astype(jnp.float32)


def qdot(x: jnp.ndarray, w: jnp.ndarray, act_est: jnp.ndarray,
         spec: QuantSpec) -> jnp.ndarray:
    """Quantized dense contraction: ``x (..., K) @ w (K, N) → fp32``.
    Real-arithmetic value = (qx·qw) · x_unit · w_unit[n]; the sim and
    native paths differ only in the accumulator (module docstring)."""
    qw, w_unit = quantize_weight(w, spec)
    qx, x_unit = quantize_act(x, act_est, spec)
    return _accum(qx, qw, spec) * (x_unit * w_unit)


def qconv(x: jnp.ndarray, kernel: jnp.ndarray, act_est: jnp.ndarray,
          spec: QuantSpec, strides: Tuple[int, int],
          padding) -> jnp.ndarray:
    """Quantized NHWC conv with an HWIO kernel → fp32.  Same contract as
    :func:`qdot` (per-output-channel weight units broadcast over the
    channel axis)."""
    qw, w_unit = quantize_weight(kernel, spec)
    qx, x_unit = quantize_act(x, act_est, spec)
    y = _accum(qx, qw, spec,
               conv_kw={"strides": tuple(strides), "padding": padding})
    return y * (x_unit * w_unit)


# ---------------------------------------------------------------------------
# calibration: stats sweep → activation scales → fingerprint
# ---------------------------------------------------------------------------

def record_act_stats(amax, psum, pcnt, x: jnp.ndarray,
                     spec: QuantSpec) -> None:
    """Update one layer's calibration accumulators (flax variables in
    the mutable ``quant_stats`` collection) from one calibration batch:
    running max of |x| plus the running sum/count of the per-batch
    ``spec.percentile`` of |x| — both estimators are always collected so
    the estimator choice is a finalize-time decision."""
    ax = jnp.abs(x.astype(jnp.float32))
    amax.value = jnp.maximum(amax.value, jnp.max(ax))
    psum.value = psum.value + jnp.percentile(ax, spec.percentile)
    pcnt.value = pcnt.value + 1.0


def finalize_calibration(stats, qcfg) -> Dict:
    """``quant_stats`` collection (from the calibration sweep) → the
    ``quant`` variables collection the apply-phase model reads: each
    layer's ``{amax, psum, pcnt}`` node becomes ``{act_scale}`` under
    the configured estimator.  Pure function of the stats — the
    determinism contract (same calibration set ⇒ identical scales) is
    pinned by ``tests/test_quant.py``."""
    def walk(node):
        if isinstance(node, Mapping) and _STAT_KEYS <= set(node):
            if qcfg.estimator == "percentile":
                est = node["psum"] / jnp.maximum(node["pcnt"], 1.0)
            else:
                est = node["amax"]
            return {"act_scale": jnp.asarray(est, jnp.float32)}
        if isinstance(node, Mapping):
            return {k: walk(v) for k, v in node.items()}
        return node
    return walk(_unfreeze(stats))


def _unfreeze(tree):
    try:
        from flax.core import unfreeze

        return unfreeze(tree)
    except Exception:
        return tree


def calibration_fingerprint(quant_col, qcfg) -> str:
    """Stable sha256-derived fingerprint of a calibration result: the
    quant knobs (dtype/estimator/percentile/weight_bits) plus every
    activation scale's path and exact bytes.  Two processes that
    calibrated identically agree; ANY drift (different calibration set,
    estimator, damage arm) disagrees — the export-store admission token
    (``serve/export.py``)."""
    h = hashlib.sha256()
    h.update(repr((qcfg.dtype, qcfg.estimator, float(qcfg.percentile),
                   int(qcfg.weight_bits))).encode())
    leaves = jax.tree_util.tree_flatten_with_path(quant_col)[0]
    for path, leaf in sorted(leaves, key=lambda kv: jax.tree_util.keystr(
            kv[0])):
        h.update(jax.tree_util.keystr(path).encode())
        h.update(np.asarray(leaf, np.float32).tobytes())
    return h.hexdigest()[:16]


def quant_program_tag(qcfg, fingerprint: str) -> str:
    """The program-cache / manifest tag that keeps quantized and fp
    programs un-mixable: a ``Predictor`` in quant mode prefixes every
    program key with this (``core/tester.py``), and the export-store
    manifest records the same fields (``serve/export.py``)."""
    return (f"quant[{qcfg.dtype}:{qcfg.mode}:{qcfg.estimator}"
            f":b{qcfg.weight_bits}:{fingerprint}]")


def quant_manifest_meta(qcfg, fingerprint: str) -> Dict[str, Any]:
    """The quant knobs an export-store manifest records — the admission
    contract: a replica whose own knobs (INCLUDING its locally derived
    calibration fingerprint) disagree must refuse the store."""
    return {
        "dtype": qcfg.dtype,
        "mode": qcfg.mode,
        "estimator": qcfg.estimator,
        "percentile": float(qcfg.percentile),
        "weight_bits": int(qcfg.weight_bits),
        "calibration_fingerprint": fingerprint,
    }
