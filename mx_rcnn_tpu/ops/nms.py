"""Greedy non-maximum suppression with static shapes, jit-safe.

Reference: ``rcnn/cython/cpu_nms.pyx``, ``rcnn/cython/gpu_nms.pyx`` +
``rcnn/cython/nms_kernel.cu`` (the classic triangular-bitmask CUDA kernel,
64-box blocks) and the wrapper selection in ``rcnn/processing/nms.py``
(``py_nms_wrapper`` / ``cpu_nms_wrapper`` / ``gpu_nms_wrapper``).

TPU-native design: the reference returns a *variable-length* keep list,
which XLA cannot express.  Here NMS is reformulated as a fixed-shape
computation:

1. sort boxes by score (descending; invalid boxes sink to the end),
2. tile-wise suppression sweep — for each tile of T sorted boxes, first
   suppress by the *final* survivors of earlier tiles, then resolve the
   within-tile greedy chain by fixed-point iteration (the suppressor of a
   suppressed box does not count).  This reproduces exact sequential greedy
   NMS semantics while doing O(K/T) vectorized (T, K) IoU sweeps on the VPU
   instead of a length-K sequential loop,
3. compact the survivors into a fixed-size index buffer with a cumsum
   scatter (padded with -1).

The whole thing lives inside the same XLA program as the network — no
device→host bounce like the reference's Python ``proposal`` CustomOp.

Batched variants (r6): :func:`nms_batch` / :func:`nms_mask_batch` run the
sweep for B images in ONE loop nest — each tile step is a single
(B·T, K) IoU sweep with per-image-blocked keep-mask updates instead of B
vmap-sliced (T, K) sweeps — decision-exact per image vs the per-image
sweep (the oracle), same auto-selection guards.  ``ops/proposal.py`` and
the eval postprocess (``core/tester.py``) feed these.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.ops.boxes import bbox_overlaps

# plain float, NOT jnp.float32: a module-level jnp constant would
# initialize the XLA backend at import time, breaking the
# jax.distributed.initialize ordering multi-host needs
_NEG = -1e10

# Suppression-sweep backend: the Pallas kernel (ops/nms_pallas.py) keeps the
# whole sweep in VMEM; the jnp sweep below is the oracle and the fallback.
# "auto" = Pallas on real TPU, jnp elsewhere (the kernel runs under
# interpret=True on CPU, which is only useful for testing).
_BACKEND = "auto"


def set_nms_backend(name: str) -> None:
    """Select 'auto' | 'pallas' | 'jnp' for subsequent traces.

    NOTE: jitted callers cache per static-arg signature; pass an explicit
    ``backend=`` to :func:`nms`/:func:`nms_mask` (as the tests do) to force
    a retrace rather than flipping this global mid-run.
    """
    global _BACKEND
    if name not in ("auto", "pallas", "jnp"):
        raise ValueError(f"unknown NMS backend {name!r}")
    _BACKEND = name


def _resolve_backend(backend: Optional[str], k: int, tile: int) -> str:
    b = backend or _BACKEND
    if b == "auto":
        # lane-alignment guard: the kernel's (1, K)/(T, K) blocks want K and
        # T in whole 128-lane registers; odd shapes fall back to jnp.
        # VMEM guard: the (T, K) fp32 IoU slab must fit comfortably —
        # 16 MB covers the production proposal shape (256 x 12032 ≈ 12.3 MB,
        # verified on v5e) with headroom for Mosaic temporaries.
        fits = tile * k * 4 <= 16 * 1024 * 1024
        b = "pallas" if (jax.default_backend() == "tpu" and fits
                         and tile % 128 == 0 and k % tile == 0) else "jnp"
    return b


def _chain_fixed_point(iou_self: jnp.ndarray, alive0: jnp.ndarray,
                       t: int) -> jnp.ndarray:
    """Resolve the within-tile greedy chain by fixed-point iteration: the
    suppressor of a suppressed box does not count.  ``iou_self`` is the
    (..., t, t) strictly-upper-triangular suppressor relation, ``alive0``
    the (..., t) candidates after suppression by earlier tiles.  Works
    batched: extra iterations past one row's fixed point leave that row
    unchanged (``alive0 & ~sup(alive)`` is stationary at a fixed point),
    so a joint loop over many images makes per-image decisions exactly.
    """

    def fix_cond(state):
        alive, prev, it = state
        return jnp.logical_and(jnp.any(alive != prev), it < t)

    def fix_body(state):
        alive, _, it = state
        sup = jnp.any(iou_self & alive[..., :, None], axis=-2)
        return alive0 & ~sup, alive, it + 1

    alive, _, _ = jax.lax.while_loop(
        fix_cond, fix_body, (alive0, jnp.zeros_like(alive0), 0)
    )
    return alive


def _suppression_sweep(
    boxes: jnp.ndarray,
    alive_init: jnp.ndarray,
    iou_threshold: float,
    tile_size: int,
) -> jnp.ndarray:
    """Exact greedy NMS over score-sorted ``boxes``; returns the keep mask.

    ``alive_init`` marks candidate boxes (invalid/padded boxes False).
    """
    k = boxes.shape[0]
    t = tile_size
    if k % t != 0:
        raise ValueError(f"padded box count {k} must be a multiple of tile {t}")
    num_tiles = k // t
    # Within-tile suppressor relation: strictly-earlier boxes only.
    tri = jnp.arange(t)[:, None] < jnp.arange(t)[None, :]  # tri[s, j]: s before j

    # Tile 0 is peeled out of the loop: it has no earlier tiles, so the
    # suppress-by-earlier-survivors term would be a (t, k) all-False
    # CONSTANT — XLA constant-folds the reduction over it at compile time,
    # which stalled >1 s per compile at eval-postprocess shapes
    # (MULTICHIP_r05 slow-operation alarms).  Peeling also skips the
    # useless (t, k−t) IoU block when k fits one tile.
    iou0 = bbox_overlaps(boxes[:t], boxes[:t]) > iou_threshold
    alive_first = _chain_fixed_point(iou0 & tri, alive_init[:t], t)
    keep = jax.lax.dynamic_update_slice(alive_init, alive_first, (0,))

    def tile_body(i, keep):
        start = i * t
        tile_boxes = jax.lax.dynamic_slice(boxes, (start, 0), (t, 4))
        tile_alive0 = jax.lax.dynamic_slice(keep, (start,), (t,))
        iou = bbox_overlaps(tile_boxes, boxes)  # (t, k)
        overlaps = iou > iou_threshold
        # (a) suppression by final survivors of earlier tiles
        earlier = (jnp.arange(k) < start) & keep
        sup_prev = jnp.any(overlaps & earlier[None, :], axis=1)
        alive0 = tile_alive0 & ~sup_prev
        # (b) within-tile greedy chain, fixed-point iteration
        iou_self = jax.lax.dynamic_slice(overlaps, (0, start), (t, t)) & tri
        alive = _chain_fixed_point(iou_self, alive0, t)
        return jax.lax.dynamic_update_slice(keep, alive, (start,))

    return jax.lax.fori_loop(1, num_tiles, tile_body, keep)


def _suppression_sweep_batched(
    boxes: jnp.ndarray,
    alive_init: jnp.ndarray,
    iou_threshold: float,
    tile_size: int,
) -> jnp.ndarray:
    """Exact greedy NMS over B images at once: boxes (B, K, 4) score-sorted
    per image, alive_init (B, K) → keep (B, K).

    The per-image sweep under ``vmap`` turns into B loop *states* advancing
    through one batched ``fori_loop`` × ``while_loop`` chain whose per-tile
    work is a stack of small (T, K) slabs; here the batch axis is folded
    into the sweep itself, so every tile step issues ONE (B·T, K) IoU
    sweep + keep-mask update on the VPU (blocked per image — cross-image
    IoUs are never formed) and the within-tile fixed point iterates
    jointly.  Decisions are exact per image (see ``_chain_fixed_point``);
    ``tests/test_nms.py`` pins equality against the per-image sweep.
    """
    b, k = alive_init.shape
    t = tile_size
    if k % t != 0:
        raise ValueError(f"padded box count {k} must be a multiple of tile {t}")
    num_tiles = k // t
    tri = jnp.arange(t)[:, None] < jnp.arange(t)[None, :]
    overlaps_of = jax.vmap(bbox_overlaps)  # (B, q, 4) x (B, k, 4) → (B, q, k)

    # tile 0 peeled, exactly like the per-image sweep (no all-False
    # constant term, no constant-folding stall)
    iou0 = overlaps_of(boxes[:, :t], boxes[:, :t]) > iou_threshold
    alive_first = _chain_fixed_point(iou0 & tri[None], alive_init[:, :t], t)
    keep = jnp.concatenate([alive_first, alive_init[:, t:]], axis=1)

    def tile_body(i, keep):
        start = i * t
        tile_boxes = jax.lax.dynamic_slice(boxes, (0, start, 0), (b, t, 4))
        tile_alive0 = jax.lax.dynamic_slice(keep, (0, start), (b, t))
        overlaps = overlaps_of(tile_boxes, boxes) > iou_threshold  # (B, t, k)
        earlier = (jnp.arange(k)[None, :] < start) & keep  # (B, k)
        sup_prev = jnp.any(overlaps & earlier[:, None, :], axis=2)
        alive0 = tile_alive0 & ~sup_prev
        iou_self = jax.lax.dynamic_slice(
            overlaps, (0, 0, start), (b, t, t)) & tri[None]
        alive = _chain_fixed_point(iou_self, alive0, t)
        return jax.lax.dynamic_update_slice(keep, alive, (0, start))

    return jax.lax.fori_loop(1, num_tiles, tile_body, keep)


def _mask_pad_sort(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    valid: Optional[jnp.ndarray],
    tile_size: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, int, int]:
    """Rank-generic sweep preamble shared by the per-image and batched
    paths: mask invalid scores, pad the box axis to a tile multiple, sort
    by descending score.  boxes (..., K, 4) / scores (..., K) →
    (boxes_sorted, order, alive0, pad, tile)."""
    k = scores.shape[-1]
    boxes = boxes.astype(jnp.float32)
    scores = scores.astype(jnp.float32)
    if valid is not None:
        scores = jnp.where(valid, scores, _NEG)
    t = min(tile_size, max(k, 1))
    pad = (-k) % t
    if pad:
        boxes = jnp.pad(boxes,
                        [(0, 0)] * (boxes.ndim - 2) + [(0, pad), (0, 0)])
        scores = jnp.pad(scores,
                         [(0, 0)] * (scores.ndim - 1) + [(0, pad)],
                         constant_values=_NEG)
    order = jnp.argsort(-scores, axis=-1)
    boxes_sorted = jnp.take_along_axis(boxes, order[..., None], axis=-2)
    alive0 = jnp.take_along_axis(scores, order, axis=-1) > _NEG / 2
    return boxes_sorted, order, alive0, pad, t


def _run_sweep(
    boxes_sorted: jnp.ndarray,
    alive0: jnp.ndarray,
    iou_threshold: float,
    t: int,
    backend: Optional[str],
) -> jnp.ndarray:
    """Backend resolution + sweep dispatch — the ONE copy of the Pallas
    tile-cap/VMEM-guard logic, shared by the per-image and batched paths
    (rank-dispatched: (K, 4) runs the per-image sweep, (B, K, 4) the
    cross-image batched one; the Pallas kernel is per-image either way,
    vmapped over the batch — the shape the chip measurements validated).
    """
    k = alive0.shape[-1]
    if _resolve_backend(backend, k, t) == "pallas":
        from mx_rcnn_tpu.ops.nms_pallas import suppression_sweep_pallas

        # the kernel's tile is capped at 128 independent of the padding
        # tile: at t=256 the (T, K) IoU slab alone is ~12.3 MB for the
        # production K=12032 and compiles within 48 KB of the 16 MB scoped
        # VMEM limit in some surrounding-graph contexts (observed under
        # jvp(vmap(...))); 128 halves the slab at the same total work.
        # Greedy NMS results are tile-size-invariant (exact sweep).
        tp = 128 if t % 128 == 0 else t

        def pallas_one(bx, al):
            return suppression_sweep_pallas(
                bx, al, iou_threshold, tp,
                interpret=jax.default_backend() != "tpu")

        if boxes_sorted.ndim == 3:
            return jax.vmap(pallas_one)(boxes_sorted, alive0)
        return pallas_one(boxes_sorted, alive0)
    if boxes_sorted.ndim == 3:
        return _suppression_sweep_batched(boxes_sorted, alive0,
                                          iou_threshold, t)
    return _suppression_sweep(boxes_sorted, alive0, iou_threshold, t)


def _sorted_survivors(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    valid: Optional[jnp.ndarray],
    iou_threshold: float,
    tile_size: int,
    backend: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, int, int]:
    """Shared preamble of all four entry points: mask invalid scores, pad
    to a tile multiple, sort by score, run the suppression sweep.

    Rank-generic — (K, ·) serves nms/nms_mask, (B, K, ·) serves
    nms_batch/nms_mask_batch (the sweep dispatch is per rank, see
    ``_run_sweep``).  Returns (order, keep, pad, tile) over the padded
    arrays, both in sorted order.  Keeping this in ONE place keeps the
    per-image and cross-image paths — and the training (nms) and eval
    (nms_mask) paths — identical by construction.
    """
    boxes_sorted, order, alive0, pad, t = _mask_pad_sort(
        boxes, scores, valid, tile_size)
    keep = _run_sweep(boxes_sorted, alive0, iou_threshold, t, backend)
    return order, keep, pad, t


@functools.partial(jax.jit, static_argnames=("iou_threshold", "max_output",
                                             "tile_size", "backend"))
def nms(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_threshold: float,
    max_output: int,
    valid: Optional[jnp.ndarray] = None,
    tile_size: int = 256,
    backend: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy NMS; returns up to ``max_output`` surviving indices by score.

    Args:
      boxes: (K, 4) in (x1, y1, x2, y2).
      scores: (K,).
      iou_threshold: suppression threshold (ref NMS_THRESH).
      max_output: static output size.
      valid: optional (K,) bool mask of real (non-padding) boxes.
    Returns:
      (indices, out_valid): indices (max_output,) int32 into the input arrays
      ordered by descending score, padded with -1; out_valid (max_output,)
      bool marks real outputs.
    """
    if boxes.shape[0] == 0:
        return (jnp.full((max_output,), -1, jnp.int32),
                jnp.zeros((max_output,), bool))
    order, keep, _, t = _sorted_survivors(boxes, scores, valid,
                                          iou_threshold, tile_size, backend)
    # Compact survivors (in score order) into a fixed buffer.
    pos = jnp.cumsum(keep) - 1
    emit = keep & (pos < max_output)
    out_idx = jnp.full((max_output,), -1, dtype=jnp.int32)
    out_idx = out_idx.at[jnp.where(emit, pos, max_output)].set(
        order.astype(jnp.int32), mode="drop"
    )
    out_valid = out_idx >= 0
    return out_idx, out_valid


@functools.partial(jax.jit, static_argnames=("iou_threshold", "tile_size",
                                             "backend"))
def nms_mask(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_threshold: float,
    valid: Optional[jnp.ndarray] = None,
    tile_size: int = 256,
    backend: Optional[str] = None,
) -> jnp.ndarray:
    """Greedy NMS returning a keep mask in the *original* box order.

    Used by the eval path (per-class NMS, ref ``rcnn/core/tester.py —
    pred_eval``) where all candidates are postprocessed host-side.
    """
    k = boxes.shape[0]
    if k == 0:
        return jnp.zeros((0,), bool)
    order, keep_sorted, pad, _ = _sorted_survivors(
        boxes, scores, valid, iou_threshold, tile_size, backend)
    keep = jnp.zeros((k + pad,), dtype=bool).at[order].set(keep_sorted)
    return keep[:k]


@functools.partial(jax.jit, static_argnames=("iou_threshold", "max_output",
                                             "tile_size", "backend"))
def nms_batch(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_threshold: float,
    max_output: int,
    valid: Optional[jnp.ndarray] = None,
    tile_size: int = 256,
    backend: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Cross-image batched :func:`nms`: boxes (B, K, 4), scores (B, K) →
    ((B, max_output) indices, (B, max_output) valid).

    Decision-exact per image against ``vmap(nms)`` (pinned by
    ``tests/test_nms.py``) but runs ONE tile-sweep loop nest whose steps
    process all images together — the per-image serialized
    ``fori_loop``×``while_loop`` chains under vmap become a single (B·T, K)
    sweep per tile step (see :func:`_suppression_sweep_batched`).
    """
    b, k = scores.shape
    if k == 0:
        return (jnp.full((b, max_output), -1, jnp.int32),
                jnp.zeros((b, max_output), bool))
    order, keep, _, t = _sorted_survivors(
        boxes, scores, valid, iou_threshold, tile_size, backend)
    pos = jnp.cumsum(keep, axis=1) - 1
    emit = keep & (pos < max_output)

    def compact(order_i, pos_i, emit_i):
        out = jnp.full((max_output,), -1, dtype=jnp.int32)
        return out.at[jnp.where(emit_i, pos_i, max_output)].set(
            order_i.astype(jnp.int32), mode="drop")

    out_idx = jax.vmap(compact)(order, pos, emit)
    return out_idx, out_idx >= 0


@functools.partial(jax.jit, static_argnames=("iou_threshold", "tile_size",
                                             "backend"))
def nms_mask_batch(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_threshold: float,
    valid: Optional[jnp.ndarray] = None,
    tile_size: int = 256,
    backend: Optional[str] = None,
) -> jnp.ndarray:
    """Cross-image batched :func:`nms_mask`: (B, K, ...) → (B, K) keep
    mask in original box order.  The eval postprocess flattens its
    (images × classes) double vmap into one (N·C, R) call so every
    per-class NMS in the batch shares a single sweep loop nest."""
    b, k = scores.shape
    if k == 0:
        return jnp.zeros((b, 0), bool)
    order, keep_sorted, pad, _ = _sorted_survivors(
        boxes, scores, valid, iou_threshold, tile_size, backend)
    keep = jax.vmap(
        lambda o, ks: jnp.zeros((k + pad,), dtype=bool).at[o].set(ks)
    )(order, keep_sorted)
    return keep[:, :k]
