"""Greedy non-maximum suppression with static shapes, jit-safe.

Reference: ``rcnn/cython/cpu_nms.pyx``, ``rcnn/cython/gpu_nms.pyx`` +
``rcnn/cython/nms_kernel.cu`` (the classic triangular-bitmask CUDA kernel,
64-box blocks) and the wrapper selection in ``rcnn/processing/nms.py``
(``py_nms_wrapper`` / ``cpu_nms_wrapper`` / ``gpu_nms_wrapper``).

TPU-native design: the reference returns a *variable-length* keep list,
which XLA cannot express.  Here NMS is reformulated as a fixed-shape
computation:

1. sort boxes by score (descending; invalid boxes sink to the end),
2. tile-wise suppression sweep — for each tile of T sorted boxes, first
   suppress by the *final* survivors of earlier tiles, then resolve the
   within-tile greedy chain by fixed-point iteration (the suppressor of a
   suppressed box does not count).  This reproduces exact sequential greedy
   NMS semantics while doing O(K/T) vectorized (T, K) IoU sweeps on the VPU
   instead of a length-K sequential loop,
3. compact the survivors into a fixed-size index buffer with a cumsum
   scatter (padded with -1).

The whole thing lives inside the same XLA program as the network — no
device→host bounce like the reference's Python ``proposal`` CustomOp.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.ops.boxes import bbox_overlaps

# plain float, NOT jnp.float32: a module-level jnp constant would
# initialize the XLA backend at import time, breaking the
# jax.distributed.initialize ordering multi-host needs
_NEG = -1e10

# Suppression-sweep backend: the Pallas kernel (ops/nms_pallas.py) keeps the
# whole sweep in VMEM; the jnp sweep below is the oracle and the fallback.
# "auto" = Pallas on real TPU, jnp elsewhere (the kernel runs under
# interpret=True on CPU, which is only useful for testing).
_BACKEND = "auto"


def set_nms_backend(name: str) -> None:
    """Select 'auto' | 'pallas' | 'jnp' for subsequent traces.

    NOTE: jitted callers cache per static-arg signature; pass an explicit
    ``backend=`` to :func:`nms`/:func:`nms_mask` (as the tests do) to force
    a retrace rather than flipping this global mid-run.
    """
    global _BACKEND
    if name not in ("auto", "pallas", "jnp"):
        raise ValueError(f"unknown NMS backend {name!r}")
    _BACKEND = name


def _resolve_backend(backend: Optional[str], k: int, tile: int) -> str:
    b = backend or _BACKEND
    if b == "auto":
        # lane-alignment guard: the kernel's (1, K)/(T, K) blocks want K and
        # T in whole 128-lane registers; odd shapes fall back to jnp.
        # VMEM guard: the (T, K) fp32 IoU slab must fit comfortably —
        # 16 MB covers the production proposal shape (256 x 12032 ≈ 12.3 MB,
        # verified on v5e) with headroom for Mosaic temporaries.
        fits = tile * k * 4 <= 16 * 1024 * 1024
        b = "pallas" if (jax.default_backend() == "tpu" and fits
                         and tile % 128 == 0 and k % tile == 0) else "jnp"
    return b


def _suppression_sweep(
    boxes: jnp.ndarray,
    alive_init: jnp.ndarray,
    iou_threshold: float,
    tile_size: int,
) -> jnp.ndarray:
    """Exact greedy NMS over score-sorted ``boxes``; returns the keep mask.

    ``alive_init`` marks candidate boxes (invalid/padded boxes False).
    """
    k = boxes.shape[0]
    t = tile_size
    if k % t != 0:
        raise ValueError(f"padded box count {k} must be a multiple of tile {t}")
    num_tiles = k // t
    # Within-tile suppressor relation: strictly-earlier boxes only.
    tri = jnp.arange(t)[:, None] < jnp.arange(t)[None, :]  # tri[s, j]: s before j

    def tile_body(i, keep):
        start = i * t
        tile_boxes = jax.lax.dynamic_slice(boxes, (start, 0), (t, 4))
        tile_alive0 = jax.lax.dynamic_slice(keep, (start,), (t,))
        iou = bbox_overlaps(tile_boxes, boxes)  # (t, k)
        overlaps = iou > iou_threshold
        # (a) suppression by final survivors of earlier tiles
        earlier = (jnp.arange(k) < start) & keep
        sup_prev = jnp.any(overlaps & earlier[None, :], axis=1)
        alive0 = tile_alive0 & ~sup_prev
        # (b) within-tile greedy chain, fixed-point iteration
        iou_self = jax.lax.dynamic_slice(overlaps, (0, start), (t, t)) & tri

        def fix_cond(state):
            alive, prev, it = state
            return jnp.logical_and(jnp.any(alive != prev), it < t)

        def fix_body(state):
            alive, _, it = state
            sup = jnp.any(iou_self & alive[:, None], axis=0)
            return alive0 & ~sup, alive, it + 1

        alive, _, _ = jax.lax.while_loop(
            fix_cond, fix_body, (alive0, jnp.zeros_like(alive0), 0)
        )
        return jax.lax.dynamic_update_slice(keep, alive, (start,))

    return jax.lax.fori_loop(0, num_tiles, tile_body, alive_init)


def _sorted_survivors(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    valid: Optional[jnp.ndarray],
    iou_threshold: float,
    tile_size: int,
    backend: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, int, int]:
    """Shared preamble of nms/nms_mask: mask invalid scores, pad to a tile
    multiple, sort by score, run the suppression sweep.

    Returns (order, keep, pad, tile) over the padded arrays, both in sorted
    order.  Keeping this in one place keeps the training path (nms) and the
    eval path (nms_mask) numerically identical.
    """
    k = boxes.shape[0]
    boxes = boxes.astype(jnp.float32)
    scores = scores.astype(jnp.float32)
    if valid is not None:
        scores = jnp.where(valid, scores, _NEG)
    t = min(tile_size, max(k, 1))
    pad = (-k) % t
    if pad:
        boxes = jnp.concatenate([boxes, jnp.zeros((pad, 4), jnp.float32)], axis=0)
        scores = jnp.concatenate([scores, jnp.full((pad,), _NEG)], axis=0)
    order = jnp.argsort(-scores)
    alive0 = scores[order] > _NEG / 2
    if _resolve_backend(backend, k + pad, t) == "pallas":
        from mx_rcnn_tpu.ops.nms_pallas import suppression_sweep_pallas

        # the kernel's tile is capped at 128 independent of the padding
        # tile: at t=256 the (T, K) IoU slab alone is ~12.3 MB for the
        # production K=12032 and compiles within 48 KB of the 16 MB scoped
        # VMEM limit in some surrounding-graph contexts (observed under
        # jvp(vmap(...))); 128 halves the slab at the same total work.
        # Greedy NMS results are tile-size-invariant (exact sweep).
        tp = 128 if t % 128 == 0 else t
        keep = suppression_sweep_pallas(
            boxes[order], alive0, iou_threshold, tp,
            interpret=jax.default_backend() != "tpu")
    else:
        keep = _suppression_sweep(boxes[order], alive0, iou_threshold, t)
    return order, keep, pad, t


@functools.partial(jax.jit, static_argnames=("iou_threshold", "max_output",
                                             "tile_size", "backend"))
def nms(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_threshold: float,
    max_output: int,
    valid: Optional[jnp.ndarray] = None,
    tile_size: int = 256,
    backend: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy NMS; returns up to ``max_output`` surviving indices by score.

    Args:
      boxes: (K, 4) in (x1, y1, x2, y2).
      scores: (K,).
      iou_threshold: suppression threshold (ref NMS_THRESH).
      max_output: static output size.
      valid: optional (K,) bool mask of real (non-padding) boxes.
    Returns:
      (indices, out_valid): indices (max_output,) int32 into the input arrays
      ordered by descending score, padded with -1; out_valid (max_output,)
      bool marks real outputs.
    """
    if boxes.shape[0] == 0:
        return (jnp.full((max_output,), -1, jnp.int32),
                jnp.zeros((max_output,), bool))
    order, keep, _, t = _sorted_survivors(boxes, scores, valid,
                                          iou_threshold, tile_size, backend)
    # Compact survivors (in score order) into a fixed buffer.
    pos = jnp.cumsum(keep) - 1
    emit = keep & (pos < max_output)
    out_idx = jnp.full((max_output,), -1, dtype=jnp.int32)
    out_idx = out_idx.at[jnp.where(emit, pos, max_output)].set(
        order.astype(jnp.int32), mode="drop"
    )
    out_valid = out_idx >= 0
    return out_idx, out_valid


@functools.partial(jax.jit, static_argnames=("iou_threshold", "tile_size",
                                             "backend"))
def nms_mask(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_threshold: float,
    valid: Optional[jnp.ndarray] = None,
    tile_size: int = 256,
    backend: Optional[str] = None,
) -> jnp.ndarray:
    """Greedy NMS returning a keep mask in the *original* box order.

    Used by the eval path (per-class NMS, ref ``rcnn/core/tester.py —
    pred_eval``) where all candidates are postprocessed host-side.
    """
    k = boxes.shape[0]
    if k == 0:
        return jnp.zeros((0,), bool)
    order, keep_sorted, pad, _ = _sorted_survivors(
        boxes, scores, valid, iou_threshold, tile_size, backend)
    keep = jnp.zeros((k + pad,), dtype=bool).at[order].set(keep_sorted)
    return keep[:k]
