"""Pallas TPU kernel for the greedy-NMS suppression sweep.

Reference: ``rcnn/cython/nms_kernel.cu`` — the classic triangular-bitmask
CUDA NMS (64-box blocks, device-wide bitmask, host-side final reduction).

This is the Pallas counterpart of ``ops/nms.py — _suppression_sweep`` (the
jnp fallback, which stays as the oracle): boxes arrive score-sorted, the
kernel walks tiles of T boxes through a sequential 1-D grid, and for each
tile (a) suppresses by the finalized survivors of all earlier tiles, then
(b) resolves the within-tile greedy chain by fixed-point iteration —
bit-identical decisions to sequential greedy NMS.

Why a kernel helps on TPU: the whole sweep runs out of VMEM — the (T, K)
IoU slab, the box coordinates, and the keep mask never round-trip to HBM
between tiles, and the keep mask accumulates in place across grid steps
(constant-index output block + input/output aliasing), where the XLA
version re-materializes masks per fori_loop iteration.

Numerics mirror ``ops/boxes.py — bbox_overlaps`` exactly (+1 pixel areas,
``union > 0`` guard, ``iou > threshold`` suppression), so the two backends
agree decision-for-decision, not just approximately.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _sweep_kernel(boxes_ref, boxes_t_ref, keep_in_ref, keep_ref, *,
                  tile: int, iou_threshold: float):
    """One grid step = one tile of ``tile`` sorted boxes.

    boxes_ref: (K, 4) fp32 score-sorted boxes (VMEM).
    boxes_t_ref: (4, K) the same boxes transposed (broadcast-friendly rows).
    keep_in_ref / keep_ref: (1, K) fp32 alive mask.  The input is aliased
      onto the output HBM buffer, but the output VMEM window is NOT
      guaranteed to hold the aliased input's contents before the first
      write — so program 0 explicitly seeds the output block from the input
      block; later grid steps read/write only ``keep_ref`` (constant-index
      block, resident in VMEM across the sequential grid).
    """
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _seed():
        keep_ref[:, :] = keep_in_ref[:, :]

    k = boxes_t_ref.shape[1]
    t = tile
    start = i * t

    tile_boxes = boxes_ref[pl.ds(start, t), :]          # (T, 4)
    tx1 = tile_boxes[:, 0:1]                            # (T, 1)
    ty1 = tile_boxes[:, 1:2]
    tx2 = tile_boxes[:, 2:3]
    ty2 = tile_boxes[:, 3:4]
    x1 = boxes_t_ref[0:1, :]                            # (1, K)
    y1 = boxes_t_ref[1:2, :]
    x2 = boxes_t_ref[2:3, :]
    y2 = boxes_t_ref[3:4, :]

    # IoU of the tile rows against every box — semantics of bbox_overlaps
    iw = jnp.maximum(jnp.minimum(tx2, x2) - jnp.maximum(tx1, x1) + 1.0, 0.0)
    ih = jnp.maximum(jnp.minimum(ty2, y2) - jnp.maximum(ty1, y1) + 1.0, 0.0)
    inter = iw * ih                                     # (T, K)
    area_t = (tx2 - tx1 + 1.0) * (ty2 - ty1 + 1.0)      # (T, 1)
    area_a = (x2 - x1 + 1.0) * (y2 - y1 + 1.0)          # (1, K)
    union = area_t + area_a - inter
    iou = jnp.where(union > 0, inter / jnp.maximum(union, 1e-12), 0.0)
    over = (iou > iou_threshold).astype(jnp.float32)    # (T, K)

    keep = keep_ref[0:1, :]                             # (1, K) 1.0/0.0
    col = jax.lax.broadcasted_iota(jnp.int32, (1, k), 1)
    earlier = jnp.where(col < start, keep, 0.0)         # finalized survivors
    sup_prev = jnp.max(over * earlier, axis=1, keepdims=True)  # (T, 1)
    tile_alive0 = keep_ref[0, pl.ds(start, t)].reshape(t, 1)
    alive0 = tile_alive0 * (1.0 - sup_prev)             # (T, 1)

    # within-tile greedy chain: strictly-earlier suppressors only.  The
    # (T, T) self-block is recomputed from ref slices (Mosaic does not lower
    # dynamic_slice of a computed value) — T² IoUs, negligible next to the
    # (T, K) slab above.
    sx1 = boxes_t_ref[0:1, pl.ds(start, t)]             # (1, T)
    sy1 = boxes_t_ref[1:2, pl.ds(start, t)]
    sx2 = boxes_t_ref[2:3, pl.ds(start, t)]
    sy2 = boxes_t_ref[3:4, pl.ds(start, t)]
    siw = jnp.maximum(jnp.minimum(tx2, sx2) - jnp.maximum(tx1, sx1) + 1.0,
                      0.0)
    sih = jnp.maximum(jnp.minimum(ty2, sy2) - jnp.maximum(ty1, sy1) + 1.0,
                      0.0)
    sinter = siw * sih                                  # (T, T)
    sarea = (sx2 - sx1 + 1.0) * (sy2 - sy1 + 1.0)       # (1, T)
    sunion = area_t + sarea - sinter
    siou = jnp.where(sunion > 0, sinter / jnp.maximum(sunion, 1e-12), 0.0)
    over_self = (siou > iou_threshold).astype(jnp.float32)
    row = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
    colt = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
    tri = (row < colt).astype(jnp.float32)
    chain = over_self * tri                             # chain[s, j]

    def fix_cond(state):
        alive, prev, it = state
        return jnp.logical_and(jnp.any(alive != prev), it < t)

    def fix_body(state):
        alive, _, it = state
        sup = jnp.max(chain * alive, axis=0).reshape(t, 1)  # (T, 1)
        return alive0 * (1.0 - sup), alive, it + 1

    alive, _, _ = jax.lax.while_loop(
        fix_cond, fix_body, (alive0, jnp.zeros_like(alive0), 0))
    keep_ref[0, pl.ds(start, t)] = alive.reshape(t)


@functools.partial(jax.jit, static_argnames=("iou_threshold", "tile_size",
                                             "interpret"))
def suppression_sweep_pallas(
    boxes: jnp.ndarray,
    alive_init: jnp.ndarray,
    iou_threshold: float,
    tile_size: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Drop-in Pallas replacement for ``ops/nms.py — _suppression_sweep``.

    Args:
      boxes: (K, 4) fp32 boxes sorted by descending score; K must be a
        multiple of ``tile_size`` (the callers pad).
      alive_init: (K,) bool candidate mask (padding slots False).
      iou_threshold: suppression threshold.
      interpret: run the kernel in interpreter mode (CPU testing).
    Returns:
      (K,) bool keep mask — exact sequential-greedy-NMS survivors.
    """
    k = boxes.shape[0]
    t = tile_size
    if k % t != 0:
        raise ValueError(f"padded box count {k} must be a multiple of {t}")
    boxes = boxes.astype(jnp.float32)
    keep0 = alive_init.reshape(1, k).astype(jnp.float32)
    kernel = functools.partial(_sweep_kernel, tile=t,
                               iou_threshold=float(iou_threshold))
    keep = pl.pallas_call(
        kernel,
        grid=(k // t,),
        in_specs=[
            pl.BlockSpec((k, 4), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((4, k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, k), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, k), jnp.float32),
        input_output_aliases={2: 0},
        interpret=interpret,
    )(boxes, boxes.T, keep0)
    return keep.reshape(k) > 0.5
