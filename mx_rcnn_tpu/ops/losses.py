"""Loss functions.

Reference: MXNet built-in C++ ops wired into the training symbols
(``rcnn/symbol/symbol_vgg.py — get_vgg_train`` / ``symbol_resnet.py``):

* ``mx.symbol.SoftmaxOutput(..., ignore_label=-1, use_ignore=True,
  normalization='valid')`` for RPN classification,
* ``mx.symbol.SoftmaxOutput(..., normalization='batch')`` for RCNN
  classification,
* ``mx.symbol.smooth_l1(scalar=sigma)`` wrapped in ``MakeLoss(grad_scale=
  1/RPN_BATCH_SIZE or 1/BATCH_ROIS)`` for the two box-regression losses.

On TPU these are three lines of jnp each; XLA fuses them into the backward
pass — no custom ops needed.  All functions accept/return float32 (losses
are accumulated in fp32 even when activations are bf16).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def smooth_l1(pred: jnp.ndarray, target: jnp.ndarray, sigma: float = 1.0) -> jnp.ndarray:
    """Elementwise smooth-L1 (Huber) loss.

    ``f(x) = 0.5 (sigma x)^2            if |x| < 1/sigma^2
             |x| - 0.5/sigma^2          otherwise``

    Reference: ``mx.symbol.smooth_l1(scalar=sigma)`` — RPN uses sigma=3,
    RCNN uses sigma=1 (see §3.5 of SURVEY.md).
    """
    sigma2 = sigma * sigma
    diff = (pred - target).astype(jnp.float32)
    abs_diff = jnp.abs(diff)
    return jnp.where(
        abs_diff < 1.0 / sigma2,
        0.5 * sigma2 * diff * diff,
        abs_diff - 0.5 / sigma2,
    )


def softmax_cross_entropy_with_ignore(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    ignore_label: int = -1,
    normalization: str = "valid",
) -> jnp.ndarray:
    """Softmax cross-entropy over the last axis with ignored labels.

    Args:
      logits: (..., C) raw scores.
      labels: (...) int labels; entries equal to ``ignore_label`` contribute
        zero loss and are excluded from 'valid' normalization.
      normalization: 'valid' — divide by the count of non-ignored labels
        (ref RPN loss); 'batch' — divide by the total label count (ref RCNN
        loss with normalization='batch'); 'null' — plain sum.

    Returns a scalar fp32 loss.
    """
    logits = logits.astype(jnp.float32)
    mask = (labels != ignore_label)
    safe_labels = jnp.where(mask, labels, 0).astype(jnp.int32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    nll = jnp.where(mask, nll, 0.0)
    total = jnp.sum(nll)
    if normalization == "valid":
        return total / jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
    if normalization == "batch":
        return total / float(labels.size)
    if normalization == "null":
        return total
    raise ValueError(f"unknown normalization {normalization!r}")


def weighted_smooth_l1(
    pred: jnp.ndarray,
    target: jnp.ndarray,
    weight: jnp.ndarray,
    sigma: float,
    grad_norm: float,
) -> jnp.ndarray:
    """``sum(weight * smooth_l1(pred - target)) / grad_norm`` — the
    ``smooth_l1 * bbox_weight → MakeLoss(grad_scale=1/N)`` pattern of the
    reference training symbols.  RPN: sigma=3, grad_norm=RPN_BATCH_SIZE;
    RCNN: sigma=1, grad_norm=BATCH_ROIS (per image).
    """
    loss = smooth_l1(pred, target, sigma) * weight.astype(jnp.float32)
    return jnp.sum(loss) / float(grad_norm)


def accuracy_with_ignore(
    logits: jnp.ndarray, labels: jnp.ndarray, ignore_label: int = -1
) -> jnp.ndarray:
    """Masked accuracy — the metric twin of the CE loss (ref
    ``rcnn/core/metric.py — RPNAccMetric / RCNNAccMetric``)."""
    mask = labels != ignore_label
    pred = jnp.argmax(logits, axis=-1)
    correct = jnp.where(mask, (pred == labels).astype(jnp.float32), 0.0)
    return jnp.sum(correct) / jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
