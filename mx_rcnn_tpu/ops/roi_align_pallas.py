"""Pallas TPU kernel for fused two-stage ROIAlign (batched, custom VJP).

Reference: ``mx.symbol.ROIPooling`` (CUDA gather kernel) — already
redesigned as two separable interpolation matmuls in ``ops/roi_pool.py``.
This kernel is the VMEM-fused version of those matmuls.

Why: the XLA einsum pair is FLOP-efficient (it batches all ROIs into one
big matmul) but materializes the inter-matmul intermediate in HBM —
(R, ·, ·, C) ≈ 280 MB in bf16 at the production shape (256 rois,
38x64x1024 feature map) — written and read back every step, in forward
AND backward.  Measured on chip (r5 stage table, N=16 chains): 5.84 ms of
a 26.44 ms train step for ~18 GFLOP of useful work (~2% MFU; a pure HBM
wall).  Fusing the two contractions in VMEM removes the intermediate
entirely: HBM traffic drops to the feature map, the tiny interpolation
matrices, and the pooled output.

**Measured outcome (r5, v5e), and why this is NOT the default**: isolated
the kernel wins the forward (3.8 vs 4.1 ms) and loses fwd+bwd by ~2 ms
(12.1 vs 10.1) against the einsum pair (after the design iterations
recorded in the kernel docstrings: per-(roi, s) tiny dots, per-roi
transposes, and a VMEM spill each cost 2x before the final shape).  Inside the FULL train step the
einsum pair still wins by ~13 ms (25.0 vs 38.6 ms): the opaque
custom-call boundary forces layout copies of the ~100 MB pooled and
cotangent tensors and blocks XLA fusion across the op — costs invisible
at op scope that dwarf the intermediate being saved.  Retained behind
``cfg.train.roi_align_backend='pallas'`` with parity + grad tests as
measured groundwork; revisit if the boundary tax shrinks (custom-call
layout negotiation) or R*C grows past the copy cost.

Design (forward):
* inputs are the PRE-BUILT per-ROI interpolation matrices ``wy`` / ``wx``
  (built in jnp — tiny one_hot machinery XLA handles fine; the SAME
  ``_interp_matrix`` as the einsum path, so the two backends share
  bilinear weights bit-for-bit) plus the feature maps ``(N, H, W, C)``,
* grid = (N, C/Cb, R/RB): ROI blocks innermost, so the feature block
  stays VMEM-resident across each image's whole ROI sweep,
* stage 1 is ONE MXU matmul per grid step — (RB*ph, H) @ (H, W*Cb) — the
  ROI-batched shape XLA itself uses, keeping MXU row occupancy high,
* stage 2 contracts W per ROI (ph unrolled (pw, W) @ (W, Cb) dots) out of
  the fp32 VMEM scratch; small matmuls, but only ~6.6 GFLOP total and
  entirely VMEM-resident.

The batch dimension is part of the GRID, not vmap: the backward kernel's
accumulator logic depends on ``program_id`` of the ROI axis, and a vmap
batching rule would silently renumber the axes.  The TRAIN path calls
this under ``shard_map`` (local dense arrays), where an opaque kernel
shards trivially; the GSPMD eval path keeps the einsum backend, which
XLA can partition (an opaque pallas_call would force a gather).

Backward (custom VJP; ROIs are non-differentiable data, exactly like the
reference ROIPooling which propagates no gradient to rois):
  dFeat = sum_r wy[r]^T @ (g[r] contracted with wx[r] over t)
* same grid with an (H, W*Cb) fp32 VMEM accumulator: zeroed at ROI block
  0, accumulated across ROI blocks (the wy^T contraction is again one
  ROI-batched MXU matmul per step), flushed on the last — dFeat hits HBM
  exactly once per (image, channel block).

VMEM at the production shape (RB=8, Cb=256): feature block 1.2 MB (bf16)
+ stage scratch (fp32) 7.3 MB + accumulator/out blocks ~2 MB + interp
blocks <0.2 MB ≈ 11 MB < 16 MB/core.  ``_pick_blocks`` shrinks Cb (or
keeps small C whole) for the tiny/VGG heads, which then trivially fit.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mx_rcnn_tpu.ops.roi_pool import interp_matrices


# raise the default 16 MiB scoped-VMEM cap: v5e has far more physical
# VMEM, and the backward's value chain (g block, its transpose, RB fat-dot
# results, da2, the fp32 accumulator) measured a 2x slowdown when Mosaic
# spilled it under the default cap.  (``TPUCompilerParams`` is the 0.4.x
# spelling of the same dataclass.)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
_COMPILER_PARAMS = _CompilerParams(vmem_limit_bytes=64 * 1024 * 1024)


def _pick_blocks(r: int, c: int) -> Tuple[int, int]:
    """(RB, Cb) block sizes: R is padded to a multiple of RB by the
    wrapper; Cb must divide C, falling back to full C for small heads."""
    rb = 8 if r >= 8 else max(r, 1)
    cb = 256 if c % 256 == 0 else c
    return rb, cb


def _fwd_kernel(wy_ref, feat_ref, wx_ref, out_ref, *,
                rb: int, ph: int, pw: int, h: int, w: int, cb: int):
    """One grid step = one image x one channel block x RB rois.

    wy_ref: (1, RB*ph, H); feat_ref: (1, H, W, Cb); wx_ref: (1, RB, pw, W);
    out_ref: (1, RB, ph, pw, Cb).

    Shape discipline learned by measurement (all on the r5 chip):
    per-(roi, s) tiny dots ≈ 28k sequential MXU ops per step (35.4 ms
    full step vs einsum's 25.5); per-roi transposes pay Mosaic's high
    fixed transpose cost RB times (39.1 ms).  This version does exactly
    TWO whole-block transposes per grid step and RB fat dots, everything
    as VMEM values (no scratch round-trips).
    """
    feat2d = feat_ref[0].reshape(h, w * cb)
    # stage 1: every ROI's row interpolation in ONE MXU matmul
    a = jnp.dot(wy_ref[0], feat2d,
                preferred_element_type=jnp.float32)  # (RB*ph, W*Cb)
    cdt = wx_ref.dtype
    # s-w axis swap between the contractions, once for the whole block
    at = jnp.swapaxes(a.reshape(rb * ph, w, cb), 0,
                      1).reshape(w, rb * ph * cb).astype(cdt)
    outs = [
        jnp.dot(wx_ref[0, r], at[:, r * ph * cb:(r + 1) * ph * cb],
                preferred_element_type=jnp.float32)  # (pw, ph*Cb)
        for r in range(rb)
    ]
    o = jnp.concatenate(outs, axis=0).reshape(rb, pw, ph, cb)
    # back-swap the whole block's output in the second transpose
    out_ref[0] = jnp.swapaxes(o, 1, 2).astype(out_ref.dtype)


def _bwd_kernel(wy_ref, wx_ref, g_ref, dfeat_ref, acc_ref, *,
                rb: int, ph: int, pw: int, h: int, w: int, cb: int):
    """dFeat for one (image, channel block), accumulated over ROI blocks.

    wy_ref: (1, RB*ph, H); wx_ref: (1, RB, pw, W); g_ref: (1, RB, ph, pw,
    Cb); dfeat_ref: (1, H, W, Cb); acc_ref: fp32 (H, W*Cb).

    Mirrors _fwd_kernel's shape discipline: two whole-block transposes,
    RB fat dots, one ROI-batched accumulate matmul — all values, the only
    stateful buffer is the fp32 accumulator (zeroed at ROI block 0,
    flushed to HBM once per (image, channel block)).
    """
    ri = pl.program_id(2)

    @pl.when(ri == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    cdt = wx_ref.dtype
    # block transpose 1: g (RB, ph, pw, Cb) -> (pw, RB*ph*Cb)
    gt = jnp.transpose(g_ref[0], (2, 0, 1, 3)).reshape(pw, rb * ph * cb)
    # stage 2 transposed, one fat dot per ROI:
    # da[(w), (s c)] = sum_t wx[r, t, w] g[r, s, t, c]
    das = [
        jnp.dot(wx_ref[0, r].T, gt[:, r * ph * cb:(r + 1) * ph * cb],
                preferred_element_type=jnp.float32).astype(cdt)  # (W, ph*Cb)
        for r in range(rb)
    ]
    # block transpose 2: collect to ((r s), (w c)) for the batched matmul
    da2 = jnp.transpose(
        jnp.concatenate(das, axis=1).reshape(w, rb, ph, cb),
        (1, 2, 0, 3)).reshape(rb * ph, w * cb)
    # stage 1 transposed, ROI-batched: acc += wy^T (H, RB*ph) @ da2
    acc_ref[:] += jnp.dot(wy_ref[0].T, da2,
                          preferred_element_type=jnp.float32)

    @pl.when(ri == pl.num_programs(2) - 1)
    def _flush():
        dfeat_ref[0] = acc_ref[:].reshape(h, w, cb).astype(dfeat_ref.dtype)


def _build_interp(rois: jnp.ndarray, ph: int, pw: int, h: int, w: int,
                  spatial_scale: float, sampling_ratio: int, dtype):
    """Per-ROI (wy, wx) for ONE image — the einsum path's own
    ``interp_matrices``, so backends agree bit-for-bit on weights."""
    wy, wx = interp_matrices(rois, ph, pw, h, w, spatial_scale,
                             sampling_ratio)
    return wy.astype(dtype), wx.astype(dtype)


def _specs(n, r_pad, ph, pw, h, w, c, rb, cb):
    grid = (n, c // cb, r_pad // rb)
    wy_spec = pl.BlockSpec((1, rb * ph, h),
                           lambda bi, ci, ri: (bi, ri, 0),
                           memory_space=pltpu.VMEM)
    wx_spec = pl.BlockSpec((1, rb, pw, w), lambda bi, ci, ri: (bi, ri, 0, 0),
                           memory_space=pltpu.VMEM)
    feat_spec = pl.BlockSpec((1, h, w, cb), lambda bi, ci, ri: (bi, 0, 0, ci),
                             memory_space=pltpu.VMEM)
    pooled_spec = pl.BlockSpec((1, rb, ph, pw, cb),
                               lambda bi, ci, ri: (bi, ri, 0, 0, ci),
                               memory_space=pltpu.VMEM)
    return grid, wy_spec, wx_spec, feat_spec, pooled_spec


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def roi_align_pallas(
    features: jnp.ndarray,
    rois: jnp.ndarray,
    output_size: Tuple[int, int] = (14, 14),
    spatial_scale: float = 1.0 / 16.0,
    sampling_ratio: int = 2,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused-VMEM ROIAlign over a batch.

    Args match ``ops.roi_pool.roi_align`` but BATCHED:
      features: (N, H, W, C); rois: (N, R, 4) in input coordinates.
    Returns (N, R, ph, pw, C) pooled features in ``features.dtype``.
    ``interpret=True`` runs the kernels in the Pallas interpreter so CPU
    tests can pin parity against the einsum oracle.
    """
    out, _ = _roi_align_fwd(features, rois, output_size, spatial_scale,
                            sampling_ratio, interpret)
    return out


def _roi_align_fwd(features, rois, output_size, spatial_scale,
                   sampling_ratio, interpret):
    ph, pw = output_size
    n, h, w, c = features.shape
    r = rois.shape[1]
    rb, cb = _pick_blocks(r, c)
    pad = (-r) % rb
    wy, wx = jax.vmap(
        lambda rs: _build_interp(rs, ph, pw, h, w, spatial_scale,
                                 sampling_ratio, features.dtype))(rois)
    if pad:
        wy = jnp.concatenate(
            [wy, jnp.zeros((n, pad) + wy.shape[2:], wy.dtype)], axis=1)
        wx = jnp.concatenate(
            [wx, jnp.zeros((n, pad) + wx.shape[2:], wx.dtype)], axis=1)
    r_pad = r + pad
    grid, wy_spec, wx_spec, feat_spec, pooled_spec = _specs(
        n, r_pad, ph, pw, h, w, c, rb, cb)
    kern = functools.partial(_fwd_kernel, rb=rb, ph=ph, pw=pw, h=h, w=w,
                             cb=cb)
    out = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[wy_spec, feat_spec, wx_spec],
        out_specs=pooled_spec,
        out_shape=jax.ShapeDtypeStruct((n, r_pad, ph, pw, c),
                                       features.dtype),
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(wy.reshape(n, r_pad * ph, h), features, wx)
    # rois ride the residuals only to shape the zero cotangent in bwd
    return out[:, :r], (wy, wx, rois, h, w, c)


def _roi_align_bwd(output_size, spatial_scale, sampling_ratio, interpret,
                   res, g):
    wy, wx, rois, h, w, c = res
    ph, pw = output_size
    n, r_pad = wy.shape[0], wy.shape[1]
    rb, cb = _pick_blocks(r_pad, c)
    pad = r_pad - g.shape[1]
    if pad:
        g = jnp.concatenate(
            [g, jnp.zeros((n, pad) + g.shape[2:], g.dtype)], axis=1)
    grid, wy_spec, wx_spec, feat_spec, pooled_spec = _specs(
        n, r_pad, ph, pw, h, w, c, rb, cb)
    kern = functools.partial(_bwd_kernel, rb=rb, ph=ph, pw=pw, h=h,
                             w=w, cb=cb)
    dfeat = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[wy_spec, wx_spec, pooled_spec],
        out_specs=feat_spec,
        out_shape=jax.ShapeDtypeStruct((n, h, w, c), g.dtype),
        scratch_shapes=[pltpu.VMEM((h, w * cb), jnp.float32)],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(wy.reshape(n, r_pad * ph, h), wx, g)
    # no gradient to rois: proposal boxes are data (ref ROIPooling
    # likewise propagates only to the feature map) — but the cotangent is
    # an explicit zeros array, not bare None: None-as-zero worked by
    # accident of the pytree check and fails opaquely at trace time the
    # moment anything differentiates w.r.t. rois (ADVICE r5)
    return dfeat, jnp.zeros_like(rois)


roi_align_pallas.defvjp(_roi_align_fwd, _roi_align_bwd)
