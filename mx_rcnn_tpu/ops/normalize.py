"""Device-side image normalization for raw uint8 batches.

The reference host loader subtracts PIXEL_MEANS on the CPU and ships fp32
tensors (``rcnn/io/image.py — transform``).  On TPU that is backwards: the
fp32 mean-subtract is a ~10 ms/image host memory sweep and quadruples the
host→device transfer, while on device the same subtract is a trivially
fused elementwise prologue to the first convolution.  So the TPU-native
loader ships uint8 (4x less PCIe/host bandwidth) and this op normalizes
in-graph.

Bit-exactness contract: ``normalize_images(u8_batch, im_info, means)``
produces the IDENTICAL float32 tensor the host path
(``data/image.py — load_and_transform``) would have produced — valid pixels
are float32(uint8) - float32(mean) (same operand types, same order), and
padding beyond each image's real (h, w) stays exactly 0.0 (the host path
zero-fills the bucket before subtracting into the valid region only).
``tests/test_data.py`` asserts this bitwise.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp


def normalize_images(images: jnp.ndarray, im_info: jnp.ndarray | None,
                     pixel_means: Sequence[float]) -> jnp.ndarray:
    """Normalize a raw uint8 image batch on device; fp32 passes through.

    Args:
      images: (N, H, W, 3) — either uint8 raw RGB (padded into the bucket
        with zeros) or float32 already mean-subtracted (host path).
      im_info: (N, 3) of (real_h, real_w, scale); required for uint8 input
        — the mask bounds.  The loader records the ACTUAL resized dims here,
        so the mask covers exactly the valid pixels.
      pixel_means: RGB means (ref PIXEL_MEANS).

    Returns (N, H, W, 3) float32, mean-subtracted, zero beyond (h_i, w_i).
    """
    if images.dtype != jnp.uint8:
        return images
    if im_info is None:
        raise ValueError("uint8 image batches need im_info to bound the "
                         "valid region during device-side normalization")
    n, h, w, _ = images.shape
    means = jnp.asarray(pixel_means, jnp.float32)
    x = images.astype(jnp.float32) - means
    # mask padding back to exactly 0.0: uint8 zero-padding minus the mean
    # would leave -mean at the borders, which the convolution's padding
    # would then see (the host path pads with true zeros)
    row = jax.lax.broadcasted_iota(jnp.int32, (1, h, w, 1), 1)
    col = jax.lax.broadcasted_iota(jnp.int32, (1, h, w, 1), 2)
    hi = im_info[:, 0].reshape(n, 1, 1, 1)
    wi = im_info[:, 1].reshape(n, 1, 1, 1)
    mask = (row < hi) & (col < wi)
    return jnp.where(mask, x, 0.0)
