"""Training-target assignment: RPN anchor targets and RCNN proposal targets.

Reference:
* ``rcnn/io/rpn.py — assign_anchor`` (the anchor_target layer; run on the
  **host** per batch inside ``AnchorLoader``, using Cython IoU),
* ``rcnn/symbol/proposal_target.py — ProposalTargetOperator`` +
  ``rcnn/io/rcnn.py — sample_rois`` (a mid-graph CustomOp that copies ROIs
  to the host, samples with global NumPy RNG, and copies back).

TPU-native design: both layers are pure jnp functions with **static
shapes**, living inside the single jitted train step (the reference's
host↔device bounces disappear; with 1 host core feeding 8 chips, host-side
assignment would be the bottleneck anyway).  Dynamic-size constructs in the
reference map to fixed-size equivalents:

* variable in-image anchor subsets      → boolean masks over all N anchors,
* ``npr.choice`` subsampling            → rank-of-uniform selection with a
                                          ``jax.random.PRNGKey`` (explicit,
                                          reproducible, per-image folds),
* variable fg/bg sample counts          → exactly-``batch_rois`` slots chosen
                                          by a priority top-k (fg first, then
                                          bg, then padding that can only be
                                          background).

Labels use the reference's conventions: RPN labels {1 fg, 0 bg, -1 ignore};
RCNN labels are class ids with 0 = background; bbox targets are
class-specific ``(4·num_classes)`` with inside-weights, normalized by
``BBOX_MEANS``/``BBOX_STDS``.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.ops.boxes import bbox_overlaps, bbox_transform

# plain float (module-level jnp constants initialize the backend at
# import time — see ops/nms.py)
_INF = 3.4e38


def _choose_k(key: jax.Array, mask: jnp.ndarray, k_max: int,
              quota) -> jnp.ndarray:
    """Uniformly choose min(quota, count(mask)) True elements.

    The same selection SET as ``_rank_of_uniform(key, mask) < quota``
    (identical uniforms, identical smallest-quota winners), but via
    ``top_k(k_max)`` instead of a full-array argsort: at the RPN's
    21 888 anchors the two argsorts were ~2.4 ms of the 26.4 ms train step
    (r5 N=16 stage table) for a 256-element draw.  ``k_max`` is static and
    bounds the traced ``quota``; used where only membership is needed
    (anchor_target) — proposal_target keeps rank-of-uniform because its
    priority keys consume the rank VALUES.

    Selection scatters True at the top_k *indices* with position < quota
    rather than thresholding on values (``r <= small[quota-1]`` kept
    quota+1 elements whenever two of the ~2^23 distinct fp32 uniforms
    collided exactly at the threshold — expected a few times per 21 888-
    anchor draw, ADVICE r5), so the count is exact even under ties.
    """
    # top_k demands k <= array size; toy grids (e.g. the 64x64 dryrun
    # canvas: 144 anchors) can be smaller than the 256-anchor RPN batch
    k_max = min(k_max, mask.shape[0])
    if k_max <= 0:
        return jnp.zeros_like(mask)
    r = jnp.where(mask, jax.random.uniform(key, mask.shape), _INF)
    neg_small, idx = jax.lax.top_k(-r, k_max)  # k_max smallest of r
    # position < quota wins; _INF sentinels (reached only when quota
    # exceeds count(mask)) never win
    take = (jnp.arange(k_max) < quota) & (-neg_small < _INF)
    return jnp.zeros_like(mask).at[idx].set(take)


def _rank_of_uniform(key: jax.Array, mask: jnp.ndarray) -> jnp.ndarray:
    """Random rank (0-based) of each True element among the True elements.

    The jit-safe equivalent of the reference's
    ``npr.choice(inds, size=k, replace=False)`` disable-the-excess pattern:
    element i of ``mask`` is "chosen into the first k" iff rank[i] < k.
    False elements get rank >= count(True).
    """
    r = jax.random.uniform(key, mask.shape)
    r = jnp.where(mask, r, _INF)
    order = jnp.argsort(r)
    ranks = jnp.zeros_like(order).at[order].set(jnp.arange(mask.shape[0]))
    return ranks


class AnchorTargets(NamedTuple):
    labels: jnp.ndarray        # (N,) int32 in {1, 0, -1}
    bbox_targets: jnp.ndarray  # (N, 4) fp32
    bbox_weights: jnp.ndarray  # (N, 4) fp32


@functools.partial(
    jax.jit,
    static_argnames=(
        "rpn_batch_size", "rpn_fg_fraction", "positive_overlap",
        "negative_overlap", "clobber_positives", "allowed_border",
        "bbox_weights",
    ),
)
def anchor_target(
    anchors: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_valid: jnp.ndarray,
    im_info: jnp.ndarray,
    key: jax.Array,
    rpn_batch_size: int = 256,
    rpn_fg_fraction: float = 0.5,
    positive_overlap: float = 0.7,
    negative_overlap: float = 0.3,
    clobber_positives: bool = False,
    allowed_border: int = 0,
    bbox_weights: Tuple[float, ...] = (1.0, 1.0, 1.0, 1.0),
) -> AnchorTargets:
    """RPN target assignment for one image (ref ``assign_anchor``).

    Args:
      anchors: (N, 4) all shifted anchors for the feature grid (constant).
      gt_boxes: (G, 4) padded ground-truth boxes (input-image coordinates).
      gt_valid: (G,) bool mask of real gt rows.
      im_info: (3,) = (height, width, scale) of real image content.
      key: per-image PRNG key for subsampling.
    """
    n = anchors.shape[0]
    gt = gt_boxes.astype(jnp.float32)

    # 1. keep only anchors inside the (real) image, ref allowed_border=0
    inside = (
        (anchors[:, 0] >= -allowed_border)
        & (anchors[:, 1] >= -allowed_border)
        & (anchors[:, 2] < im_info[1] + allowed_border)
        & (anchors[:, 3] < im_info[0] + allowed_border)
    )

    # 2. IoU vs valid gt boxes
    overlaps = bbox_overlaps(anchors, gt)  # (N, G)
    overlaps = jnp.where(gt_valid[None, :], overlaps, 0.0)
    max_overlap = jnp.max(overlaps, axis=1)
    argmax_gt = jnp.argmax(overlaps, axis=1)
    any_gt = jnp.any(gt_valid)

    # per-gt best anchors (all ties), only among inside anchors
    overlaps_in = jnp.where(inside[:, None], overlaps, -1.0)
    gt_best = jnp.max(overlaps_in, axis=0)  # (G,)
    is_gt_best = (
        (overlaps_in == gt_best[None, :]) & gt_valid[None, :] & (gt_best[None, :] > 0)
    ).any(axis=1)

    # 3. label assignment in the reference's order (CLOBBER_POSITIVES=False:
    #    negatives first, then gt-best, then threshold positives)
    neg = inside & (max_overlap < negative_overlap)
    pos = inside & (is_gt_best | (max_overlap >= positive_overlap)) & any_gt
    if clobber_positives:
        pos = pos & ~neg
    else:
        neg = neg & ~pos

    # 4. subsample to rpn_batch_size with <= rpn_fg_fraction positives
    kf, kb = jax.random.split(key)
    num_fg_quota = int(rpn_fg_fraction * rpn_batch_size)
    pos_kept = _choose_k(kf, pos, num_fg_quota, num_fg_quota)
    num_pos = jnp.sum(pos_kept.astype(jnp.int32))
    neg_kept = _choose_k(kb, neg, rpn_batch_size, rpn_batch_size - num_pos)

    labels = jnp.full((n,), -1, dtype=jnp.int32)
    labels = jnp.where(neg_kept, 0, labels)
    labels = jnp.where(pos_kept, 1, labels)

    # 5. regression targets toward each anchor's best gt
    matched_gt = gt[argmax_gt]
    targets = bbox_transform(anchors.astype(jnp.float32), matched_gt)
    w = jnp.asarray(bbox_weights, dtype=jnp.float32)
    weights = jnp.where(pos_kept[:, None], w[None, :], 0.0)
    targets = jnp.where(pos_kept[:, None], targets, 0.0)
    return AnchorTargets(labels, targets, weights)


class ProposalTargets(NamedTuple):
    rois: jnp.ndarray          # (batch_rois, 4) fp32
    labels: jnp.ndarray        # (batch_rois,) int32; 0 = background,
                               # -1 = ignore (filler slot when the valid
                               # fg+bg pool is smaller than batch_rois —
                               # excluded from the classification loss)
    bbox_targets: jnp.ndarray  # (batch_rois, 4*num_classes) fp32
    bbox_weights: jnp.ndarray  # (batch_rois, 4*num_classes) fp32
    fg_mask: jnp.ndarray       # (batch_rois,) bool


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_classes", "batch_rois", "fg_fraction", "fg_thresh",
        "bg_thresh_hi", "bg_thresh_lo", "bbox_means", "bbox_stds", "gt_append",
    ),
)
def proposal_target(
    rois: jnp.ndarray,
    roi_valid: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_classes: jnp.ndarray,
    gt_valid: jnp.ndarray,
    key: jax.Array,
    num_classes: int = 21,
    batch_rois: int = 128,
    fg_fraction: float = 0.25,
    fg_thresh: float = 0.5,
    bg_thresh_hi: float = 0.5,
    bg_thresh_lo: float = 0.0,
    bbox_means: Tuple[float, ...] = (0.0, 0.0, 0.0, 0.0),
    bbox_stds: Tuple[float, ...] = (0.1, 0.1, 0.2, 0.2),
    gt_append: bool = True,
) -> ProposalTargets:
    """Sample ROIs and build RCNN targets for one image.

    Reference: ``proposal_target`` CustomOp → ``sample_rois``.  Ground-truth
    boxes are appended to the candidate pool (as in the reference), sampling
    keeps at most ``fg_fraction·batch_rois`` foreground ROIs, and bbox
    regression targets are class-specific and mean/std-normalized
    (``BBOX_NORMALIZATION_PRECOMPUTED``).
    """
    gt = gt_boxes.astype(jnp.float32)
    if gt_append:
        all_rois = jnp.concatenate([rois.astype(jnp.float32), gt], axis=0)
        all_valid = jnp.concatenate([roi_valid, gt_valid], axis=0)
    else:
        all_rois = rois.astype(jnp.float32)
        all_valid = roi_valid
    # pad the candidate pool so the fixed-size top-k below is always legal
    short = batch_rois - all_rois.shape[0]
    if short > 0:
        all_rois = jnp.concatenate([all_rois, jnp.zeros((short, 4), jnp.float32)])
        all_valid = jnp.concatenate([all_valid, jnp.zeros((short,), bool)])

    overlaps = bbox_overlaps(all_rois, gt)
    overlaps = jnp.where(gt_valid[None, :], overlaps, 0.0)
    max_ov = jnp.max(overlaps, axis=1)
    argmax_gt = jnp.argmax(overlaps, axis=1)

    fg = all_valid & (max_ov >= fg_thresh)
    bg = all_valid & (max_ov < bg_thresh_hi) & (max_ov >= bg_thresh_lo)

    kf, kb = jax.random.split(key)
    fg_quota = int(round(fg_fraction * batch_rois))
    fg_rank = _rank_of_uniform(kf, fg)
    fg_sel = fg & (fg_rank < fg_quota)
    num_fg = jnp.sum(fg_sel.astype(jnp.int32))
    bg_rank = _rank_of_uniform(kb, bg)
    bg_sel = bg & (bg_rank < batch_rois - num_fg)

    # exactly batch_rois slots: selected fg first, then selected bg, then
    # filler (only when fg+bg < batch_rois; labelled -1 = ignore, zero box
    # weight).  Integer priority keys keep the three groups strictly ordered
    # for any pool size (a float epsilon tie-break would overflow group gaps
    # at the 2000-proposal training scale).
    pool = all_rois.shape[0]
    prio = jnp.where(
        fg_sel, 3 * pool - fg_rank,
        jnp.where(bg_sel, 2 * pool - bg_rank, pool - jnp.arange(pool)),
    )
    _, pick = jax.lax.top_k(prio, batch_rois)

    sel_rois = all_rois[pick]
    sel_fg = fg_sel[pick]
    sel_bg = bg_sel[pick]
    sel_gt = argmax_gt[pick]
    labels = jnp.where(
        sel_fg, gt_classes[sel_gt].astype(jnp.int32), jnp.where(sel_bg, 0, -1)
    )

    # normalized class-specific regression targets
    t = bbox_transform(sel_rois, gt[sel_gt])
    t = (t - jnp.asarray(bbox_means, jnp.float32)) / jnp.asarray(bbox_stds, jnp.float32)
    onehot = jax.nn.one_hot(labels, num_classes, dtype=jnp.float32)  # (B, C)
    targets = (onehot[:, :, None] * t[:, None, :]).reshape(batch_rois, 4 * num_classes)
    weights = jnp.broadcast_to(
        onehot[:, :, None] * sel_fg[:, None, None], (batch_rois, num_classes, 4)
    ).reshape(batch_rois, 4 * num_classes)
    targets = targets * (labels > 0)[:, None]
    weights = weights * (labels > 0)[:, None]
    return ProposalTargets(sel_rois, labels, targets, weights, sel_fg)
