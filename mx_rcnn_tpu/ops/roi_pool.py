"""ROI feature extraction: ROIAlign (primary) and ROIPooling (parity op).

Reference: ``mx.symbol.ROIPooling`` — a C++/CUDA MXNet op used by the
symbols (``rcnn/symbol/symbol_vgg.py`` ROIPooling 7x7 /16,
``symbol_resnet.py`` 14x14 /16).

TPU-native design: instead of translating the CUDA gather kernel, ROIAlign
is reformulated as **two small dense matmuls per ROI** — a (S_h, H)
row-interpolation matrix and a (S_w, W) column-interpolation matrix applied
around the (H, W, C) feature map:

    sampled[s, t, c] = W_y[s, h] · feat[h, w, c] · W_x[t, w]

Expressing the op as dense matmuls routes it onto the MXU systolic array
and lets XLA batch it over ROIs — far better than 4-point gathers, which
scatter into HBM-latency-bound loads.  The ROIAlign mean over the sr×sr
sample points per bin (standard semantics, aligned=True convention) is
folded into the interpolation matrices, so each matrix row carries the
averaged bilinear weights of a whole output bin: the contractions and the
(R, ·, ·, C) intermediate shrink by sr× each, and the op is
HBM-bandwidth-, not FLOP-, bound on TPU.

``roi_pool`` reproduces the reference's quantized max-pool semantics
(rounded ROI corners, ceil/floor bin edges, empty bins → 0) for numerical
parity runs; models default to ROIAlign which is uniformly better on mAP.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def _interp_matrix(starts: jnp.ndarray, bin_sizes: jnp.ndarray, num_bins: int,
                   sampling_ratio: int, size: int) -> jnp.ndarray:
    """Pooled bilinear sampling matrix (num_bins, size) for one axis.

    starts/bin_sizes: scalars (per-ROI, one axis).  Sample positions use the
    aligned=True convention: integer coordinate i is the center of pixel i.

    The ROIAlign mean over the ``sampling_ratio`` sample points per bin is
    folded INTO the matrix (mean of bilinear samples = matmul with averaged
    weights; the mean over an (sr, sr) sample grid factorizes exactly into
    per-axis means) — this shrinks both matmul contractions and the
    intermediate tensor by sr× each versus materializing every sample.
    """
    s = num_bins * sampling_ratio
    k = jnp.arange(s, dtype=jnp.float32)
    # position of each sample point in continuous pixel-center coordinates
    pos = starts + (k + 0.5) * (bin_sizes / sampling_ratio) - 0.5
    pos = jnp.clip(pos, 0.0, size - 1.0)
    lo = jnp.floor(pos)
    frac = pos - lo
    lo_i = lo.astype(jnp.int32)
    hi_i = jnp.minimum(lo_i + 1, size - 1)
    m = jax.nn.one_hot(lo_i, size, dtype=jnp.float32) * (1.0 - frac)[:, None]
    m = m + jax.nn.one_hot(hi_i, size, dtype=jnp.float32) * frac[:, None]
    return m.reshape(num_bins, sampling_ratio, size).mean(axis=1)


def interp_matrices(rois: jnp.ndarray, ph: int, pw: int, h: int, w: int,
                    spatial_scale: float, sampling_ratio: int):
    """Per-ROI (wy (R, ph, H), wx (R, pw, W)) fp32 interpolation matrices
    — the ONE place the ROI corner scaling / min-size clamp / bilinear
    weights are defined, shared by the einsum and Pallas backends so
    their weights cannot drift apart (parity tests pin them equal)."""
    x1 = rois[:, 0].astype(jnp.float32) * spatial_scale
    y1 = rois[:, 1].astype(jnp.float32) * spatial_scale
    x2 = rois[:, 2].astype(jnp.float32) * spatial_scale
    y2 = rois[:, 3].astype(jnp.float32) * spatial_scale
    roi_w = jnp.maximum(x2 - x1, 1.0)
    roi_h = jnp.maximum(y2 - y1, 1.0)
    wy = jax.vmap(lambda s, b: _interp_matrix(s, b, ph, sampling_ratio, h))(
        y1, roi_h / ph)
    wx = jax.vmap(lambda s, b: _interp_matrix(s, b, pw, sampling_ratio, w))(
        x1, roi_w / pw)
    return wy, wx


def _einsum_pair(features: jnp.ndarray, wy: jnp.ndarray, wx: jnp.ndarray,
                 rows_first: bool, prec: str) -> jnp.ndarray:
    """The two ROIAlign contractions — the ONE definition of contraction
    order and dtype rules, shared by the monolithic einsum path and the
    ROI-chunked blocked path so the backends are bit-equal by construction
    (each output element reduces over the same axis in the same order
    regardless of how many ROIs share the batch dimension)."""
    if rows_first:
        rows = jnp.einsum("rsh,hwc->rswc", wy, features, precision=prec)
        return jnp.einsum("rswc,rtw->rstc", rows, wx, precision=prec)
    cols = jnp.einsum("hwc,rtw->rhtc", features, wx, precision=prec)
    return jnp.einsum("rhtc,rsh->rstc", cols, wy, precision=prec)


@functools.partial(
    jax.jit, static_argnames=("output_size", "spatial_scale", "sampling_ratio")
)
def roi_align(
    features: jnp.ndarray,
    rois: jnp.ndarray,
    output_size: Tuple[int, int] = (14, 14),
    spatial_scale: float = 1.0 / 16.0,
    sampling_ratio: int = 2,
) -> jnp.ndarray:
    """ROIAlign over a single image's feature map.

    Args:
      features: (H, W, C) NHWC feature map.  fp32 features use exact fp32
        ('highest') arithmetic; bf16 features use native MXU bf16 passes
        with the inter-matmul intermediate also in bf16 (see test
        ``test_roi_align_bf16_close_to_fp32`` for the accuracy envelope).
      rois: (R, 4) boxes in input-image coordinates (x1, y1, x2, y2).
      output_size: (pooled_h, pooled_w).
      spatial_scale: 1 / feature stride (ref ROIPooling spatial_scale=1/16).
      sampling_ratio: bilinear sample points per bin edge.

    Returns:
      (R, pooled_h, pooled_w, C) pooled features, in ``features.dtype``.
    """
    ph, pw = output_size
    h, w, _ = features.shape
    dtype = features.dtype

    wy, wx = interp_matrices(rois, ph, pw, h, w, spatial_scale,
                             sampling_ratio)

    # Two batched matmuls on the MXU.  Compute stays in the feature dtype:
    # in bf16 the weight rounding costs <0.3% of a pixel's bilinear frac —
    # far below the feature quantization already present — while fp32
    # features get fp32 ('highest') arithmetic so the op is exact for
    # parity/eval runs.  The cheaper contraction runs first to minimize the
    # (R, ·, ·, C) intermediate that HBM bandwidth pays for.
    prec = "highest" if dtype == jnp.float32 else "default"
    wy = wy.astype(dtype)
    wx = wx.astype(dtype)
    pooled = _einsum_pair(features, wy, wx, ph * w <= h * pw, prec)
    return pooled.astype(dtype)


# ---------------------------------------------------------------------------
# ROI-chunked blocked ROIAlign (r6).
#
# The einsum pair above materializes the inter-matmul intermediate for ALL
# ROIs at once — (R, s|h, w|t, C), ~280 MB bf16 at the production shape
# (256 ROIs x 38x64x1024 features), measured 5.84 ms of the 26.44 ms train
# step at ~2% MFU (docs/PERF.md r5 stage table): HBM-bandwidth-bound, not
# compute-bound.  The Pallas kernel that fused the pair in VMEM LOST ~13 ms
# in the full step to custom-call-boundary layout copies (PERF.md "Fused
# ROIAlign kernel"), so this backend shrinks the intermediate while staying
# INSIDE the XLA program: a `lax.map` over ROI chunks runs the same einsum
# pair per chunk, so the live intermediate is chunk/R the size and XLA still
# fuses across the op — no opaque boundary, no layout copies.
#
# Bit-equality with the einsum pair holds by construction for the forward
# (both run `_einsum_pair`; the ROI axis is a batch axis, so chunking it
# cannot change any per-element reduction).  The backward is a custom VJP
# that blocks the transposed contractions the same way and accumulates the
# feature cotangent across chunks in fp32; chunked accumulation is the same
# sum in a different association, so backward parity is pinned exactly on
# reduction-order-insensitive test vectors and to float tolerance on random
# ones (tests/test_roi_pool.py).  Like the Pallas backend (and the reference
# ROIPooling), ROIs are treated as non-differentiable data: the VJP returns
# a zeros cotangent for them (training stop-gradients proposals anyway).
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _blocked_pool(features, wy_c, wx_c, rows_first: bool, prec: str):
    """features (H, W, C) + chunked fp-cast interp matrices
    wy_c (n, c, ph, H) / wx_c (n, c, pw, W) → pooled (n, c, ph, pw, C)."""
    return _blocked_pool_fwd(features, wy_c, wx_c, rows_first, prec)[0]


def _blocked_pool_fwd(features, wy_c, wx_c, rows_first, prec):
    def one_chunk(ws):
        wy_i, wx_i = ws
        return _einsum_pair(features, wy_i, wx_i, rows_first, prec)

    pooled = jax.lax.map(one_chunk, (wy_c, wx_c))
    return pooled, (features, wy_c, wx_c)


def _blocked_pool_bwd(rows_first, prec, res, g):
    features, wy_c, wx_c = res

    def body(acc, xs):
        wy_i, wx_i, g_i = xs
        # the transposed contractions of _einsum_pair, per chunk — the
        # backward's (R, ·, ·, C) cotangent intermediate shrinks by the
        # chunk factor exactly like the forward's
        if rows_first:
            d_rows = jnp.einsum("rstc,rtw->rswc", g_i, wx_i, precision=prec)
            part = jnp.einsum("rsh,rswc->hwc", wy_i, d_rows, precision=prec)
        else:
            d_cols = jnp.einsum("rstc,rsh->rhtc", g_i, wy_i, precision=prec)
            part = jnp.einsum("rhtc,rtw->hwc", d_cols, wx_i, precision=prec)
        return acc + part.astype(jnp.float32), None

    d_feat, _ = jax.lax.scan(
        body, jnp.zeros(features.shape, jnp.float32), (wy_c, wx_c, g))
    # interp matrices are non-differentiable data here (rois carry no
    # gradient — see the block comment above)
    return (d_feat.astype(features.dtype), jnp.zeros_like(wy_c),
            jnp.zeros_like(wx_c))


_blocked_pool.defvjp(_blocked_pool_fwd, _blocked_pool_bwd)


@functools.partial(
    jax.jit,
    static_argnames=("output_size", "spatial_scale", "sampling_ratio",
                     "chunk"),
)
def roi_align_blocked(
    features: jnp.ndarray,
    rois: jnp.ndarray,
    output_size: Tuple[int, int] = (14, 14),
    spatial_scale: float = 1.0 / 16.0,
    sampling_ratio: int = 2,
    chunk: int = 64,
) -> jnp.ndarray:
    """ROI-chunked blocked ROIAlign over a single image's feature map.

    Same contract as :func:`roi_align` (bit-equal forward, same dtype
    rules) with the (R, ·, ·, C) intermediate shrunk by the chunk factor;
    ``chunk`` is the ROI block size (``cfg.train.roi_align_chunk``).  The
    ROI count is padded to a chunk multiple with zero-boxes and sliced
    back, so odd counts are fine; gradients w.r.t. ``rois`` are zeros
    (see the block comment above).
    """
    ph, pw = output_size
    h, w, _ = features.shape
    dtype = features.dtype
    r = rois.shape[0]

    wy, wx = interp_matrices(rois, ph, pw, h, w, spatial_scale,
                             sampling_ratio)
    prec = "highest" if dtype == jnp.float32 else "default"
    wy = wy.astype(dtype)
    wx = wx.astype(dtype)

    c = max(1, min(int(chunk), r))
    pad = (-r) % c
    if pad:
        wy = jnp.concatenate(
            [wy, jnp.zeros((pad,) + wy.shape[1:], wy.dtype)], axis=0)
        wx = jnp.concatenate(
            [wx, jnp.zeros((pad,) + wx.shape[1:], wx.dtype)], axis=0)
    n = (r + pad) // c
    pooled = _blocked_pool(features,
                           wy.reshape((n, c) + wy.shape[1:]),
                           wx.reshape((n, c) + wx.shape[1:]),
                           ph * w <= h * pw, prec)
    pooled = pooled.reshape((n * c, ph, pw) + features.shape[-1:])
    return pooled[:r].astype(dtype)


def roi_align_batched(
    features: jnp.ndarray,
    rois: jnp.ndarray,
    output_size: Tuple[int, int] = (14, 14),
    spatial_scale: float = 1.0 / 16.0,
    sampling_ratio: int = 2,
    backend: str = None,
    chunk: int = 64,
) -> jnp.ndarray:
    """Batched ROIAlign with backend dispatch.

    features (N, H, W, C), rois (N, R, 4) → (N, R, ph, pw, C).

    ``backend``: 'jnp' (the einsum pair above, vmapped — the DEFAULT),
    'blocked' (the ROI-chunked :func:`roi_align_blocked`, bit-equal
    forward with the live intermediate shrunk by ``chunk``/R — the
    XLA-visible alternative to the custom-call kernel) or 'pallas' (the
    VMEM-fused kernel in ``ops/roi_align_pallas.py``).  All backends
    build their bilinear weights with the same ``_interp_matrix``, so
    they agree up to matmul rounding (the blocked backend agrees
    bit-for-bit).  ``chunk`` is only read by 'blocked'.

    Why jnp is the default even on TPU (r5, measured on a v5e): isolated,
    the fused kernel wins the forward (3.8 vs 4.1 ms) but still loses
    fwd+bwd by ~2 ms (12.1 vs 10.1, after fixing a VMEM-spill that
    initially made it 2x slower), and it loses
    ~13 ms inside the full train step (38.6 vs 25.0 ms) — the opaque
    custom-call boundary forces layout copies of the ~100 MB pooled /
    cotangent tensors and blocks XLA's fusion across the op, costing far
    more than the ~280 MB HBM intermediate it removes.  Kept behind the
    flag (cfg.train.roi_align_backend) with parity tests as measured
    groundwork; docs/PERF.md "Fused ROIAlign kernel" has the full record.
    """
    if backend is None:
        backend = "jnp"
    if backend == "pallas":
        from mx_rcnn_tpu.ops.roi_align_pallas import roi_align_pallas

        return roi_align_pallas(features, rois, output_size, spatial_scale,
                                sampling_ratio)
    if backend == "blocked":
        return jax.vmap(
            lambda f, r: roi_align_blocked(f, r, output_size, spatial_scale,
                                           sampling_ratio, chunk)
        )(features, rois)
    if backend != "jnp":
        raise ValueError(f"unknown roi_align backend {backend!r}")
    return jax.vmap(
        lambda f, r: roi_align(f, r, output_size, spatial_scale,
                               sampling_ratio)
    )(features, rois)


@functools.partial(jax.jit, static_argnames=("output_size", "spatial_scale"))
def roi_pool(
    features: jnp.ndarray,
    rois: jnp.ndarray,
    output_size: Tuple[int, int] = (7, 7),
    spatial_scale: float = 1.0 / 16.0,
) -> jnp.ndarray:
    """Reference-parity quantized max ROI pooling.

    Matches ``mx.symbol.ROIPooling`` semantics: ROI corners rounded at
    feature scale, width/height floored at 1, bin edges
    ``floor(p·rh/ph)``/``ceil((p+1)·rh/ph)``, max over each (possibly
    overlapping) bin, empty bins → 0.
    """
    ph, pw = output_size
    h, w, _ = features.shape
    dtype = features.dtype
    feat32 = features.astype(jnp.float32)
    neg = jnp.float32(-3.4e38)

    def one_roi(roi):
        # floor(x + 0.5), not jnp.round: C round() rounds half away from
        # zero while jnp.round rounds half to even, and ROI corners landing
        # on half-integer feature coords are common (multiples of 8 px).
        def rnd(v):
            return jnp.floor(v * spatial_scale + 0.5).astype(jnp.int32)

        x1, y1, x2, y2 = rnd(roi[0]), rnd(roi[1]), rnd(roi[2]), rnd(roi[3])
        rh = jnp.maximum(y2 - y1 + 1, 1).astype(jnp.float32)
        rw = jnp.maximum(x2 - x1 + 1, 1).astype(jnp.float32)

        p = jnp.arange(ph, dtype=jnp.float32)
        hstart = jnp.clip(jnp.floor(p * rh / ph).astype(jnp.int32) + y1, 0, h)
        hend = jnp.clip(jnp.ceil((p + 1) * rh / ph).astype(jnp.int32) + y1, 0, h)
        q = jnp.arange(pw, dtype=jnp.float32)
        wstart = jnp.clip(jnp.floor(q * rw / pw).astype(jnp.int32) + x1, 0, w)
        wend = jnp.clip(jnp.ceil((q + 1) * rw / pw).astype(jnp.int32) + x1, 0, w)

        hidx = jnp.arange(h)
        widx = jnp.arange(w)

        def col_bin(q_i):
            mask = (widx >= wstart[q_i]) & (widx < wend[q_i])  # (W,)
            return jnp.max(jnp.where(mask[None, :, None], feat32, neg), axis=1)

        tmp = jax.vmap(col_bin)(jnp.arange(pw))  # (pw, H, C)

        def row_bin(p_i):
            mask = (hidx >= hstart[p_i]) & (hidx < hend[p_i])  # (H,)
            return jnp.max(jnp.where(mask[None, :, None], tmp, neg), axis=1)

        out = jax.vmap(row_bin)(jnp.arange(ph))  # (ph, pw, C)
        return jnp.where(out <= neg / 2, 0.0, out)

    return jax.vmap(one_roi)(rois.astype(jnp.float32)).astype(dtype)
