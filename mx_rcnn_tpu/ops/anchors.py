"""Anchor generation.

Reference: ``rcnn/processing/generate_anchor.py — generate_anchors`` (the
py-faster-rcnn lineage enumeration: ratio-enumerated then scale-enumerated
windows around a base stride-16 box) and the shift-grid expansion inside
``rcnn/io/rpn.py — assign_anchor`` / ``rcnn/symbol/proposal.py``.

Anchors are pure constants for a given feature-grid shape, so they are
computed once in NumPy at trace time and closed over as a constant by the
jitted step — the TPU never recomputes them.

Layout convention (framework-wide): anchors are enumerated **row-major over
(H, W, A)** and flattened to ``(H*W*A, 4)``, matching the NHWC ``(..., A*k)``
channel layout of the RPN head outputs.  (The reference uses MXNet's NCHW
``(A, H, W)`` layout; we are free to differ because no reference checkpoints
are imported — consistency within this framework is what matters.)
Boxes are ``(x1, y1, x2, y2)`` inclusive corners, as in the reference.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np


def _whctrs(anchor: np.ndarray) -> Tuple[np.ndarray, ...]:
    """Box (x1,y1,x2,y2) -> (width, height, x_center, y_center)."""
    w = anchor[2] - anchor[0] + 1
    h = anchor[3] - anchor[1] + 1
    x_ctr = anchor[0] + 0.5 * (w - 1)
    y_ctr = anchor[1] + 0.5 * (h - 1)
    return w, h, x_ctr, y_ctr


def _mkanchors(ws: np.ndarray, hs: np.ndarray, x_ctr: float, y_ctr: float) -> np.ndarray:
    ws = ws[:, None]
    hs = hs[:, None]
    return np.hstack(
        (
            x_ctr - 0.5 * (ws - 1),
            y_ctr - 0.5 * (hs - 1),
            x_ctr + 0.5 * (ws - 1),
            y_ctr + 0.5 * (hs - 1),
        )
    )


def _ratio_enum(anchor: np.ndarray, ratios: np.ndarray) -> np.ndarray:
    w, h, x_ctr, y_ctr = _whctrs(anchor)
    size = w * h
    size_ratios = size / ratios
    ws = np.round(np.sqrt(size_ratios))
    hs = np.round(ws * ratios)
    return _mkanchors(ws, hs, x_ctr, y_ctr)


def _scale_enum(anchor: np.ndarray, scales: np.ndarray) -> np.ndarray:
    w, h, x_ctr, y_ctr = _whctrs(anchor)
    ws = w * scales
    hs = h * scales
    return _mkanchors(ws, hs, x_ctr, y_ctr)


def generate_anchors(
    base_size: int = 16,
    ratios: Sequence[float] = (0.5, 1.0, 2.0),
    scales: Sequence[int] = (8, 16, 32),
) -> np.ndarray:
    """Generate the (A, 4) base anchor windows around a base_size cell.

    Matches the reference numerics exactly (rounded ratio enumeration about
    the [0, 0, 15, 15] window), e.g. the canonical first anchor for the
    defaults is ``[-84, -40, 99, 55]``.
    """
    # fp64 on purpose: host-side trace-time constants matching the
    # reference's Cython anchor enumeration bit-for-bit; cast to fp32 below
    ratios = np.asarray(ratios, dtype=np.float64)  # graphlint: disable=GL401 reference-parity host constant
    scales = np.asarray(scales, dtype=np.float64)  # graphlint: disable=GL401 reference-parity host constant
    base_anchor = np.array([1, 1, base_size, base_size], dtype=np.float64) - 1  # graphlint: disable=GL401 reference-parity host constant
    ratio_anchors = _ratio_enum(base_anchor, ratios)
    anchors = np.vstack(
        [_scale_enum(ratio_anchors[i, :], scales) for i in range(ratio_anchors.shape[0])]
    )
    return anchors.astype(np.float32)


def generate_shifted_anchors(
    feat_height: int,
    feat_width: int,
    feat_stride: int = 16,
    ratios: Sequence[float] = (0.5, 1.0, 2.0),
    scales: Sequence[int] = (8, 16, 32),
) -> np.ndarray:
    """All anchors over an (H, W) feature grid, flattened to (H*W*A, 4).

    Reference: the shift-grid block at the top of ``rcnn/io/rpn.py —
    assign_anchor`` (and duplicated inside ``rcnn/symbol/proposal.py``):
    base anchors are translated by ``feat_stride`` per grid cell.

    Enumeration order is row-major (y, x, a): index = (y * W + x) * A + a.
    """
    base = generate_anchors(base_size=feat_stride, ratios=ratios, scales=scales)
    a = base.shape[0]
    shift_x = np.arange(feat_width, dtype=np.float32) * feat_stride
    shift_y = np.arange(feat_height, dtype=np.float32) * feat_stride
    sx, sy = np.meshgrid(shift_x, shift_y)  # (H, W)
    shifts = np.stack([sx, sy, sx, sy], axis=-1)  # (H, W, 4)
    anchors = shifts[:, :, None, :] + base[None, None, :, :]  # (H, W, A, 4)
    return anchors.reshape(-1, 4).astype(np.float32)
