"""Box geometry: IoU, encode/decode, clipping.

Reference: ``rcnn/processing/bbox_transform.py`` — ``bbox_overlaps`` (the
pure-NumPy twin of the Cython ``rcnn/cython/bbox.pyx — bbox_overlaps_cython``
hot loop), ``nonlinear_transform`` (a.k.a. ``bbox_transform``),
``nonlinear_pred`` (a.k.a. ``bbox_pred``) and ``clip_boxes``.

On TPU there is no reason for a native hot loop: the IoU matrix is a pair of
broadcast min/max ops that XLA fuses and vectorizes onto the VPU, and it runs
on HBM-resident data inside the same program as the conv net.

All functions are dtype-polymorphic jnp and jit/vmap-safe.  Boxes are
``(x1, y1, x2, y2)`` inclusive pixel corners (reference convention: width =
x2 - x1 + 1).
"""

from __future__ import annotations

import jax.numpy as jnp


def bbox_overlaps(boxes: jnp.ndarray, query_boxes: jnp.ndarray) -> jnp.ndarray:
    """Pairwise IoU.

    Args:
      boxes: (N, 4).
      query_boxes: (K, 4).
    Returns:
      (N, K) IoU matrix.  Degenerate (zero/negative-area) boxes yield 0.

    Reference: ``rcnn/processing/bbox_transform.py — bbox_overlaps`` /
    ``rcnn/cython/bbox.pyx — bbox_overlaps_cython``.
    """
    b = boxes[:, None, :]      # (N, 1, 4)
    q = query_boxes[None, :, :]  # (1, K, 4)
    iw = jnp.minimum(b[..., 2], q[..., 2]) - jnp.maximum(b[..., 0], q[..., 0]) + 1.0
    ih = jnp.minimum(b[..., 3], q[..., 3]) - jnp.maximum(b[..., 1], q[..., 1]) + 1.0
    iw = jnp.maximum(iw, 0.0)
    ih = jnp.maximum(ih, 0.0)
    inter = iw * ih
    area_b = (boxes[:, 2] - boxes[:, 0] + 1.0) * (boxes[:, 3] - boxes[:, 1] + 1.0)
    area_q = (query_boxes[:, 2] - query_boxes[:, 0] + 1.0) * (
        query_boxes[:, 3] - query_boxes[:, 1] + 1.0
    )
    union = area_b[:, None] + area_q[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-12), 0.0)


def bbox_transform(ex_rois: jnp.ndarray, gt_rois: jnp.ndarray) -> jnp.ndarray:
    """Encode gt boxes as regression deltas w.r.t. example (anchor/ROI) boxes.

    Args:
      ex_rois: (N, 4) anchors or proposals.
      gt_rois: (N, 4) matched ground-truth boxes.
    Returns:
      (N, 4) targets (dx, dy, dw, dh).

    Reference: ``rcnn/processing/bbox_transform.py — nonlinear_transform``.
    """
    ex_w = ex_rois[:, 2] - ex_rois[:, 0] + 1.0
    ex_h = ex_rois[:, 3] - ex_rois[:, 1] + 1.0
    ex_cx = ex_rois[:, 0] + 0.5 * (ex_w - 1.0)
    ex_cy = ex_rois[:, 1] + 0.5 * (ex_h - 1.0)

    gt_w = gt_rois[:, 2] - gt_rois[:, 0] + 1.0
    gt_h = gt_rois[:, 3] - gt_rois[:, 1] + 1.0
    gt_cx = gt_rois[:, 0] + 0.5 * (gt_w - 1.0)
    gt_cy = gt_rois[:, 1] + 0.5 * (gt_h - 1.0)

    # Reference adds 1e-14 to the denominators to dodge /0 on degenerate rois.
    dx = (gt_cx - ex_cx) / (ex_w + 1e-14)
    dy = (gt_cy - ex_cy) / (ex_h + 1e-14)
    dw = jnp.log(jnp.maximum(gt_w, 1.0) / jnp.maximum(ex_w, 1.0))
    dh = jnp.log(jnp.maximum(gt_h, 1.0) / jnp.maximum(ex_h, 1.0))
    return jnp.stack([dx, dy, dw, dh], axis=-1)


def bbox_pred(boxes: jnp.ndarray, box_deltas: jnp.ndarray) -> jnp.ndarray:
    """Decode regression deltas back into boxes (inverse of bbox_transform).

    Args:
      boxes: (N, 4) anchors or proposals.
      box_deltas: (N, 4*C) per-class deltas (C may be 1).
    Returns:
      (N, 4*C) decoded boxes.

    Reference: ``rcnn/processing/bbox_transform.py — nonlinear_pred``.
    """
    w = boxes[:, 2] - boxes[:, 0] + 1.0
    h = boxes[:, 3] - boxes[:, 1] + 1.0
    cx = boxes[:, 0] + 0.5 * (w - 1.0)
    cy = boxes[:, 1] + 0.5 * (h - 1.0)

    dx = box_deltas[:, 0::4]
    dy = box_deltas[:, 1::4]
    # Cap dw/dh: exp() of a wild early-training delta otherwise overflows
    # (py-faster-rcnn lineage caps at log(1000/16) ≈ 4.135).
    dw = jnp.minimum(box_deltas[:, 2::4], 4.135166556742356)
    dh = jnp.minimum(box_deltas[:, 3::4], 4.135166556742356)

    pred_cx = dx * w[:, None] + cx[:, None]
    pred_cy = dy * h[:, None] + cy[:, None]
    pred_w = jnp.exp(dw) * w[:, None]
    pred_h = jnp.exp(dh) * h[:, None]

    x1 = pred_cx - 0.5 * (pred_w - 1.0)
    y1 = pred_cy - 0.5 * (pred_h - 1.0)
    x2 = pred_cx + 0.5 * (pred_w - 1.0)
    y2 = pred_cy + 0.5 * (pred_h - 1.0)
    # interleave back to (N, 4*C)
    out = jnp.stack([x1, y1, x2, y2], axis=-1)  # (N, C, 4)
    return out.reshape(boxes.shape[0], -1)


def clip_boxes(boxes: jnp.ndarray, im_shape) -> jnp.ndarray:
    """Clip (N, 4*C) boxes to image bounds [0, W-1] x [0, H-1].

    Args:
      boxes: (N, 4*C).
      im_shape: (height, width) — python ints or traced scalars.

    Reference: ``rcnn/processing/bbox_transform.py — clip_boxes``.
    """
    h, w = im_shape[0], im_shape[1]
    n = boxes.shape[0]
    b = boxes.reshape(n, -1, 4)
    x1 = jnp.clip(b[..., 0], 0, w - 1.0)
    y1 = jnp.clip(b[..., 1], 0, h - 1.0)
    x2 = jnp.clip(b[..., 2], 0, w - 1.0)
    y2 = jnp.clip(b[..., 3], 0, h - 1.0)
    return jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1)
