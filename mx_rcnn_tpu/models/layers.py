"""Shared layers.

Reference: the BatchNorm convention of ``rcnn/symbol/symbol_resnet.py`` —
every BN runs with ``use_global_stats=True`` (frozen running statistics),
``eps=2e-5``, ``fix_gamma=False``, because detection fine-tuning uses batch
sizes of 1–2 images where batch statistics would be garbage.  The gamma/beta
of frozen stages are additionally excluded from the optimizer via
``FIXED_PARAMS`` (here: an optax mask built in ``core.optim``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any


class FrozenBatchNorm(nn.Module):
    """Inference-mode BatchNorm: y = gamma * (x - mean) / sqrt(var + eps) + beta.

    Running mean/var live in the ``batch_stats`` collection and are never
    updated (ref ``use_global_stats=True``); gamma/beta are params so that
    unfrozen stages can still learn an affine.
    """

    epsilon: float = 2e-5
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        mean = self.variable(
            "batch_stats", "mean", nn.initializers.zeros, None, (c,), jnp.float32
        )
        var = self.variable(
            "batch_stats", "var", nn.initializers.ones, None, (c,), jnp.float32
        )
        # fold into a single scale/shift in fp32, then cast once
        inv = scale / jnp.sqrt(var.value + self.epsilon)
        y = x.astype(jnp.float32) * inv + (bias - mean.value * inv)
        return y.astype(self.dtype)


def conv(
    features: int,
    kernel: Tuple[int, int] = (3, 3),
    strides: Tuple[int, int] = (1, 1),
    dtype: Dtype = jnp.float32,
    name: Optional[str] = None,
    use_bias: bool = True,
    padding: str | Sequence[Tuple[int, int]] = "SAME",
    kernel_init: Callable = nn.initializers.he_normal(),
) -> nn.Conv:
    """NHWC conv with fp32 params and configurable compute dtype."""
    return nn.Conv(
        features,
        kernel,
        strides=strides,
        padding=padding,
        use_bias=use_bias,
        dtype=dtype,
        param_dtype=jnp.float32,
        kernel_init=kernel_init,
        name=name,
    )
