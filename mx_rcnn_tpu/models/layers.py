"""Shared layers.

Reference: the BatchNorm convention of ``rcnn/symbol/symbol_resnet.py`` —
every BN runs with ``use_global_stats=True`` (frozen running statistics),
``eps=2e-5``, ``fix_gamma=False``, because detection fine-tuning uses batch
sizes of 1–2 images where batch statistics would be garbage.  The gamma/beta
of frozen stages are additionally excluded from the optimizer via
``FIXED_PARAMS`` (here: an optax mask built in ``core.optim``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from mx_rcnn_tpu.ops.quant import (QuantSpec, qconv, qdot, record_act_stats)

Dtype = Any


class FrozenBatchNorm(nn.Module):
    """Inference-mode BatchNorm: y = gamma * (x - mean) / sqrt(var + eps) + beta.

    Running mean/var live in the ``batch_stats`` collection and are never
    updated (ref ``use_global_stats=True``); gamma/beta are params so that
    unfrozen stages can still learn an affine.
    """

    epsilon: float = 2e-5
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        mean = self.variable(
            "batch_stats", "mean", nn.initializers.zeros, None, (c,), jnp.float32
        )
        var = self.variable(
            "batch_stats", "var", nn.initializers.ones, None, (c,), jnp.float32
        )
        # fold into a single scale/shift in fp32, then cast once
        inv = scale / jnp.sqrt(var.value + self.epsilon)
        y = x.astype(jnp.float32) * inv + (bias - mean.value * inv)
        return y.astype(self.dtype)


def conv(
    features: int,
    kernel: Tuple[int, int] = (3, 3),
    strides: Tuple[int, int] = (1, 1),
    dtype: Dtype = jnp.float32,
    name: Optional[str] = None,
    use_bias: bool = True,
    padding: str | Sequence[Tuple[int, int]] = "SAME",
    kernel_init: Callable = nn.initializers.he_normal(),
    quant: Optional[QuantSpec] = None,
) -> nn.Module:
    """NHWC conv with fp32 params and configurable compute dtype.

    ``quant=None`` (the default, and the only value when ``cfg.quant``
    is disabled) returns plain ``nn.Conv`` — the fp path is the
    UNCHANGED pre-quant module, bit-identical by construction and pinned
    by ``tests/test_quant.py``.  A :class:`~mx_rcnn_tpu.ops.quant.
    QuantSpec` swaps in :class:`QuantConv` with the SAME param
    names/shapes, so fp32 checkpoints load into the quantized model
    unmodified."""
    if quant is None:
        return nn.Conv(
            features,
            kernel,
            strides=strides,
            padding=padding,
            use_bias=use_bias,
            dtype=dtype,
            param_dtype=jnp.float32,
            kernel_init=kernel_init,
            name=name,
        )
    return QuantConv(
        features=features,
        kernel_size=tuple(kernel),
        strides=tuple(strides),
        padding=padding,
        use_bias=use_bias,
        dtype=dtype,
        kernel_init=kernel_init,
        spec=quant,
        name=name,
    )


def dense(
    features: int,
    dtype: Dtype = jnp.float32,
    name: Optional[str] = None,
    quant: Optional[QuantSpec] = None,
    kernel_init: Callable = nn.initializers.lecun_normal(),
) -> nn.Module:
    """fp32-param Dense with the same quant swap-in as :func:`conv`."""
    if quant is None:
        return nn.Dense(features, dtype=dtype, param_dtype=jnp.float32,
                        kernel_init=kernel_init, name=name)
    return QuantDense(features=features, dtype=dtype,
                      kernel_init=kernel_init, spec=quant, name=name)


def _record_calib_stats(mod: nn.Module, x: jnp.ndarray,
                        spec: QuantSpec) -> None:
    """Create this layer's ``quant_stats`` slots and fold the batch's
    activation statistics in (shared by QuantConv/QuantDense calib)."""
    amax = mod.variable("quant_stats", "amax",
                        lambda: jnp.zeros((), jnp.float32))
    psum = mod.variable("quant_stats", "psum",
                        lambda: jnp.zeros((), jnp.float32))
    pcnt = mod.variable("quant_stats", "pcnt",
                        lambda: jnp.zeros((), jnp.float32))
    record_act_stats(amax, psum, pcnt, x, spec)


class QuantConv(nn.Module):
    """Inference-only quantized NHWC conv (docs/PERF.md "Quantized
    inference"): fp32 ``kernel``/``bias`` params exactly like
    ``nn.Conv`` (checkpoint-compatible), weights quantized per output
    channel at trace time, input quantized per-tensor against the
    calibrated ``act_scale`` (``quant`` variables collection), contracted
    on the low-precision path (``ops/quant.qconv`` — int8→int32 native
    or the fp32 fake-quant sim), rescaled once to fp32.

    ``spec.phase='calib'`` instead runs the plain fp conv while
    recording activation statistics into the mutable ``quant_stats``
    collection (the calibration sweep — ``core/tester.py —
    quant_predictor``)."""

    features: int
    kernel_size: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    use_bias: bool = True
    dtype: Dtype = jnp.float32
    kernel_init: Callable = nn.initializers.he_normal()
    spec: QuantSpec = QuantSpec()

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cin = x.shape[-1]
        kernel = self.param("kernel", self.kernel_init,
                            self.kernel_size + (cin, self.features),
                            jnp.float32)
        bias = (self.param("bias", nn.initializers.zeros,
                           (self.features,), jnp.float32)
                if self.use_bias else None)
        if self.spec.phase == "calib":
            _record_calib_stats(self, x, self.spec)
            y = lax.conv_general_dilated(
                x.astype(self.dtype), kernel.astype(self.dtype),
                tuple(self.strides), self.padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        else:
            act_scale = self.variable(
                "quant", "act_scale",
                lambda: jnp.ones((), jnp.float32)).value
            y = qconv(x, kernel, act_scale, self.spec,
                      tuple(self.strides), self.padding)
        if bias is not None:
            y = y + bias.astype(y.dtype)
        return y.astype(self.dtype)


class QuantDense(nn.Module):
    """Inference-only quantized Dense — the :class:`QuantConv` contract
    for (…, K) @ (K, N) contractions (``ops/quant.qdot``)."""

    features: int
    dtype: Dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    spec: QuantSpec = QuantSpec()

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        k = x.shape[-1]
        kernel = self.param("kernel", self.kernel_init,
                            (k, self.features), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), jnp.float32)
        if self.spec.phase == "calib":
            _record_calib_stats(self, x, self.spec)
            y = x.astype(self.dtype) @ kernel.astype(self.dtype)
        else:
            act_scale = self.variable(
                "quant", "act_scale",
                lambda: jnp.ones((), jnp.float32)).value
            y = qdot(x, kernel, act_scale, self.spec)
        return (y + bias.astype(y.dtype)).astype(self.dtype)
