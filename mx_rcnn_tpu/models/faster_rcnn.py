"""The composite Faster R-CNN model.

Reference: the train/test symbol builders ``get_vgg_train/test`` and
``get_resnet_train/test`` (``rcnn/symbol/``).  The reference builds a
separate static graph per phase; here ONE flax module exposes the pieces —
``features`` (shared backbone), ``rpn_raw`` (RPN head), ``roi_head``
(per-ROI classifier/regressor) — and the phase pipelines are pure
functions: the training step (``core/train.py``) wires targets + losses, the
predictor (``core/tester.py``) wires proposal + detection decoding, both
around the same weights.

``__call__`` implements the full test-mode forward (the equivalent of the
reference test symbol): images → features → RPN → proposal → ROIAlign →
head → (rois, cls_prob, bbox_deltas), entirely inside one XLA program.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.models.resnet import ResNetBackbone, ResNetHead
from mx_rcnn_tpu.models.rpn import RPNHead
from mx_rcnn_tpu.models.vgg import VGGBackbone, VGGHead
from mx_rcnn_tpu.ops.anchors import generate_shifted_anchors
from mx_rcnn_tpu.ops.normalize import normalize_images
from mx_rcnn_tpu.ops.proposal import propose_batch
from mx_rcnn_tpu.ops.quant import QuantSpec
from mx_rcnn_tpu.ops.roi_pool import roi_align

Dtype = Any


class FasterRCNN(nn.Module):
    """Backbone + RPN + RCNN head with reference-matching hyperparameters.

    Fields mirror the per-network config block (ref ``rcnn/config.py``).
    """

    network: str = "resnet101"          # 'vgg' | 'resnet50' | 'resnet101'
    num_classes: int = 21
    anchor_scales: Tuple[int, ...] = (8, 16, 32)
    anchor_ratios: Tuple[float, ...] = (0.5, 1.0, 2.0)
    feat_stride: int = 16
    pooled_size: Tuple[int, int] = (14, 14)
    # test-time proposal params (ref config.TEST)
    test_pre_nms_top_n: int = 6000
    test_post_nms_top_n: int = 300
    test_nms_thresh: float = 0.7
    test_min_size: int = 16
    # ref PIXEL_MEANS — applied ON DEVICE when the loader ships raw uint8
    # batches (ops/normalize.py); fp32 host-normalized input passes through
    pixel_means: Tuple[float, ...] = (123.68, 116.779, 103.939)
    dtype: Dtype = jnp.float32
    # inference-only quantization recipe (ops/quant.py — cfg.quant):
    # covers the backbone convs and the head trunk; the RPN head and the
    # final cls_score/bbox_pred projections stay fp (first/last-layer
    # exemption, standard PTQ playbook).  None = the unchanged fp model.
    quant: Optional[QuantSpec] = None
    # backbone layout lever (docs/PERF.md "Quantized inference" —
    # layout levers): zero-pad the stem's input channels 3 -> this many
    # before conv0, aligning the channel axis for lane-friendly layouts.
    # Padded channels are exactly zero so every conv sum is unchanged —
    # output BIT-identical to the 3-channel model given the same first-3
    # kernel channels (pinned by tests/test_quant.py); param shapes DO
    # change (conv0 kernel grows an input channel), so this is a
    # profile_step A/B lever (--pad_stem), not a checkpoint-compatible
    # default.  0 = off.
    stem_channel_pad: int = 0

    @property
    def num_anchors(self) -> int:
        return len(self.anchor_scales) * len(self.anchor_ratios)

    def setup(self):
        if self.network == "vgg":
            self.backbone = VGGBackbone(dtype=self.dtype, quant=self.quant)
            self.head = VGGHead(dtype=self.dtype, quant=self.quant)
        elif self.network in ("resnet50", "resnet101"):
            depth = int(self.network.replace("resnet", ""))
            self.backbone = ResNetBackbone(depth=depth, dtype=self.dtype,
                                           quant=self.quant)
            self.head = ResNetHead(depth=depth, dtype=self.dtype,
                                   quant=self.quant)
        elif self.network == "tiny":  # test-only miniature (models/tiny.py)
            from mx_rcnn_tpu.models.tiny import TinyBackbone, TinyHead
            self.backbone = TinyBackbone(dtype=self.dtype, quant=self.quant)
            self.head = TinyHead(dtype=self.dtype, quant=self.quant)
        else:
            raise ValueError(f"unknown network {self.network!r}")
        head_out_init = nn.initializers.normal(0.01)
        self.rpn = RPNHead(num_anchors=self.num_anchors, dtype=self.dtype)
        # ref: cls_score Normal(0.01), bbox_pred Normal(0.001)
        self.cls_score = nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=jnp.float32,
            kernel_init=head_out_init, name="cls_score")
        self.bbox_pred = nn.Dense(
            4 * self.num_classes, dtype=self.dtype, param_dtype=jnp.float32,
            kernel_init=nn.initializers.normal(0.001), name="bbox_pred")

    # ---- pieces (used by the train step) ----------------------------------

    def features(self, images: jnp.ndarray,
                 im_info: jnp.ndarray = None) -> jnp.ndarray:
        """(N, H, W, 3) RGB → (N, H/16, W/16, C) backbone features.

        ``images`` is either fp32 mean-subtracted (host-normalized path) or
        raw uint8 (TPU-native path) — uint8 needs ``im_info`` so the
        on-device normalization masks padding back to exact zeros."""
        images = normalize_images(images, im_info, self.pixel_means)
        pad = self.stem_channel_pad - images.shape[-1]
        if pad > 0:  # layout lever: zero channels add exactly 0 per sum
            images = jnp.pad(images, [(0, 0)] * 3 + [(0, pad)])
        return self.backbone(images)

    def rpn_raw(self, feat: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """feat → ((N, H*W*A, 2) cls logits, (N, H*W*A, 4) deltas)."""
        return self.rpn(feat)

    def roi_head(self, pooled: jnp.ndarray, train: bool = False
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(R, ph, pw, C) pooled ROI features → ((R, classes) cls logits,
        (R, 4*classes) bbox deltas)."""
        x = self.head(pooled, train) if self.network == "vgg" else self.head(pooled)
        return self.cls_score(x), self.bbox_pred(x)

    def anchors_for(self, feat_h: int, feat_w: int) -> jnp.ndarray:
        """Constant (H*W*A, 4) anchor grid for a static feature shape."""
        return jnp.asarray(
            generate_shifted_anchors(
                feat_h, feat_w, self.feat_stride,
                self.anchor_ratios, self.anchor_scales,
            )
        )

    def rpn_proposals(self, images: jnp.ndarray, im_info: jnp.ndarray,
                      pre_nms_top_n: int = 6000, post_nms_top_n: int = 300
                      ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """RPN-only forward (ref ``get_*_rpn_test`` symbol): images →
        (rois, fg scores, valid) — used by generate_proposals in alternate
        training and by test_rpn."""
        feat = self.features(images, im_info)
        rpn_cls, rpn_box = self.rpn_raw(feat)
        _, fh, fw, _ = feat.shape
        anchors = self.anchors_for(fh, fw)
        fg = jax.nn.softmax(rpn_cls.astype(jnp.float32), axis=-1)[..., 1]
        return propose_batch(
            fg, rpn_box.astype(jnp.float32), anchors, im_info,
            pre_nms_top_n=pre_nms_top_n,
            post_nms_top_n=post_nms_top_n,
            nms_thresh=self.test_nms_thresh,
            min_size=self.test_min_size,
        )

    def detect_rois(self, images: jnp.ndarray, im_info: jnp.ndarray,
                    rois: jnp.ndarray, roi_valid: jnp.ndarray
                    ) -> Tuple[jnp.ndarray, ...]:
        """RCNN-only test forward on PRECOMPUTED proposals (ref the
        HAS_RPN=False test symbol consumed by ``rcnn/tools/test_rcnn.py``):
        skips the RPN entirely and classifies the given ROIs.

        Args:
          images: (N, H, W, 3) as in ``__call__``.
          im_info: (N, 3).
          rois: (N, R, 4) proposal boxes in INPUT (scaled) coordinates.
          roi_valid: (N, R) bool mask for padded proposal slots.
        Returns the same tuple as ``__call__`` so the eval postprocess is
        shared: (rois, roi_valid, cls_prob, bbox_deltas).
        """
        feat = self.features(images, im_info)
        n = feat.shape[0]

        def pool_one(feat_i, rois_i):
            return roi_align(feat_i, rois_i, self.pooled_size,
                             1.0 / self.feat_stride)

        pooled = jax.vmap(pool_one)(feat, rois)  # (N, R, ph, pw, C)
        r = pooled.shape[1]
        flat = pooled.reshape((n * r,) + pooled.shape[2:])
        cls_logits, deltas = self.roi_head(flat, train=False)
        cls_prob = jax.nn.softmax(cls_logits.astype(jnp.float32), axis=-1)
        return (
            rois,
            roi_valid,
            cls_prob.reshape(n, r, self.num_classes),
            deltas.astype(jnp.float32).reshape(n, r, 4 * self.num_classes),
        )

    # ---- full test-mode forward (ref get_*_test symbol) -------------------

    def __call__(self, images: jnp.ndarray, im_info: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, ...]:
        """Test forward for a batch.

        Args:
          images: (N, H, W, 3) mean-subtracted RGB, static bucket shape.
          im_info: (N, 3) = (real_h, real_w, scale) per image.
        Returns:
          rois (N, R, 4), roi_valid (N, R), cls_prob (N, R, classes),
          bbox_deltas (N, R, 4*classes) — R = test_post_nms_top_n.
        """
        feat = self.features(images, im_info)
        rpn_cls, rpn_box = self.rpn_raw(feat)
        n, fh, fw, _ = feat.shape
        anchors = self.anchors_for(fh, fw)
        fg_scores = jax.nn.softmax(rpn_cls.astype(jnp.float32), axis=-1)[..., 1]
        rois, _, roi_valid = propose_batch(
            fg_scores, rpn_box, anchors, im_info,
            pre_nms_top_n=self.test_pre_nms_top_n,
            post_nms_top_n=self.test_post_nms_top_n,
            nms_thresh=self.test_nms_thresh,
            min_size=self.test_min_size,
        )

        def pool_one(feat_i, rois_i):
            return roi_align(feat_i, rois_i, self.pooled_size,
                             1.0 / self.feat_stride)

        pooled = jax.vmap(pool_one)(feat, rois)  # (N, R, ph, pw, C)
        r = pooled.shape[1]
        flat = pooled.reshape((n * r,) + pooled.shape[2:])
        cls_logits, deltas = self.roi_head(flat, train=False)
        cls_prob = jax.nn.softmax(cls_logits.astype(jnp.float32), axis=-1)
        return (
            rois,
            roi_valid,
            cls_prob.reshape(n, r, self.num_classes),
            deltas.astype(jnp.float32).reshape(n, r, 4 * self.num_classes),
        )


def build_model(cfg: Config, quant_phase: str = "apply") -> FasterRCNN:
    """Construct the model from a Config (ref generate_config wiring).

    ``quant_phase`` only matters when ``cfg.quant.enabled``:
    ``'apply'`` builds the quantized-inference model (needs the
    calibrated ``quant`` variables collection — ``core/tester.py —
    quant_predictor``), ``'calib'`` builds the statistics-recording
    calibration twin.  With quant disabled (the default) the returned
    model is the UNCHANGED fp model, bit-identical to a build that
    predates the quant subsystem (pinned by tests/test_quant.py)."""
    from mx_rcnn_tpu.config import validate_dtype_string
    from mx_rcnn_tpu.ops.quant import spec_from_config

    validate_dtype_string(cfg.network.compute_dtype,
                          "network__compute_dtype")
    quant = (spec_from_config(cfg.quant, phase=quant_phase)
             if cfg.quant.enabled else None)
    return FasterRCNN(
        network=cfg.network.name,
        num_classes=cfg.num_classes,
        anchor_scales=cfg.network.anchor_scales,
        anchor_ratios=cfg.network.anchor_ratios,
        feat_stride=cfg.network.rpn_feat_stride,
        pooled_size=cfg.network.rcnn_pooled_size,
        test_pre_nms_top_n=cfg.test.rpn_pre_nms_top_n,
        test_post_nms_top_n=cfg.test.rpn_post_nms_top_n,
        test_nms_thresh=cfg.test.rpn_nms_thresh,
        test_min_size=cfg.test.rpn_min_size,
        pixel_means=tuple(cfg.network.pixel_means),
        dtype=jnp.bfloat16 if cfg.network.compute_dtype == "bfloat16" else jnp.float32,
        quant=quant,
        stem_channel_pad=cfg.network.stem_channel_pad,
    )
