"""A miniature backbone/head pair for tests and CI.

No reference equivalent — the reference "tests" by training VGG16 for hours
(SURVEY.md §4).  This framework's test pyramid instead exercises the full
end-to-end train step (targets → proposal → ROIAlign → losses → SGD) in
seconds on CPU with a 2-conv stride-16 backbone.  Everything outside the
backbone/head (the entire detection machinery) is identical to the real
networks, so pipeline bugs cannot hide behind model size.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from mx_rcnn_tpu.models.layers import conv, dense
from mx_rcnn_tpu.ops.quant import QuantSpec

Dtype = Any


class TinyBackbone(nn.Module):
    """Two strided convs → stride 16, 32 channels."""

    dtype: Dtype = jnp.float32
    # inference-only quantization recipe (ops/quant.py); None = the
    # unchanged fp path (bit-identical, pinned by tests/test_quant.py)
    quant: Optional[QuantSpec] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.astype(self.dtype)
        x = nn.relu(conv(16, (5, 5), (4, 4), dtype=self.dtype, name="conv1",
                         quant=self.quant)(x))
        x = nn.relu(conv(32, (3, 3), (4, 4), dtype=self.dtype, name="conv2",
                         quant=self.quant)(x))
        return x


class TinyHead(nn.Module):
    """Flatten → 64-unit dense."""

    dtype: Dtype = jnp.float32
    quant: Optional[QuantSpec] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        r = x.shape[0]
        x = x.astype(self.dtype).reshape(r, -1)
        return nn.relu(dense(64, dtype=self.dtype, name="fc",
                             quant=self.quant)(x))
