"""VGG16 backbone and fc6/fc7 head.

Reference: ``rcnn/symbol/symbol_vgg.py`` — ``get_vgg_conv`` (conv1_1 …
relu5_3, four 2x2 max-pools → stride 16, no pool5) and the fc6/fc7 (4096)
head applied to the flattened 7x7x512 ROI features in
``get_vgg_train/test``.  Dropout (0.5) follows fc6/fc7 at train time as in
the reference.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from mx_rcnn_tpu.models.layers import conv, dense
from mx_rcnn_tpu.ops.quant import QuantSpec

Dtype = Any

# (block name, num convs, filters); pool after blocks 1-4 only (stride 16)
_VGG16_BLOCKS = (
    ("conv1", 2, 64),
    ("conv2", 2, 128),
    ("conv3", 3, 256),
    ("conv4", 3, 512),
    ("conv5", 3, 512),
)


class VGGBackbone(nn.Module):
    """Shared conv1–conv5 features, stride 16 (ref ``get_vgg_conv``)."""

    dtype: Dtype = jnp.float32
    # inference-only quantization recipe (ops/quant.py); None = the
    # unchanged fp path (bit-identical, pinned by tests/test_quant.py)
    quant: Optional[QuantSpec] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.astype(self.dtype)
        for i, (name, n_convs, filters) in enumerate(_VGG16_BLOCKS):
            for j in range(n_convs):
                x = nn.relu(
                    conv(filters, (3, 3), dtype=self.dtype,
                         name=f"{name}_{j + 1}", quant=self.quant)(x)
                )
            if i < 4:  # no pool5 — conv5_3 stays at stride 16
                x = nn.max_pool(x, (2, 2), (2, 2))
        return x  # (N, H/16, W/16, 512)


class VGGHead(nn.Module):
    """Per-ROI fc6/fc7 head (ref get_vgg_train: flatten → fc6 4096 → relu →
    dropout .5 → fc7 4096 → relu → dropout .5)."""

    dtype: Dtype = jnp.float32
    dropout_rate: float = 0.5
    quant: Optional[QuantSpec] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        r = x.shape[0]
        x = x.astype(self.dtype).reshape(r, -1)
        x = nn.relu(dense(4096, dtype=self.dtype, name="fc6",
                          quant=self.quant)(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(dense(4096, dtype=self.dtype, name="fc7",
                          quant=self.quant)(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return x  # (R, 4096)
