"""Model zoo: Flax re-designs of the reference's symbolic graphs.

Reference: ``rcnn/symbol/symbol_vgg.py`` and ``rcnn/symbol/symbol_resnet.py``
build ``mx.symbol.Symbol`` graphs (get_*_train / get_*_test / get_*_rpn /
get_*_rcnn variants).  Here each network is a ``flax.linen`` module exposing
``features`` (shared conv backbone), and the composite
:class:`~mx_rcnn_tpu.models.faster_rcnn.FasterRCNN` wires backbone + RPN +
RCNN head; the train/test/rpn-only/rcnn-only "symbol variants" of the
reference become pure functions over the same module (see
``mx_rcnn_tpu.core.train`` / ``mx_rcnn_tpu.core.tester``), so there is one
set of weights and no graph duplication.

Everything is NHWC (TPU-native layout), params fp32, activations optionally
bfloat16 for the MXU.
"""

from mx_rcnn_tpu.models.faster_rcnn import FasterRCNN, build_model  # noqa: F401
from mx_rcnn_tpu.models.resnet import ResNetBackbone, ResNetHead  # noqa: F401
from mx_rcnn_tpu.models.rpn import RPNHead  # noqa: F401
from mx_rcnn_tpu.models.vgg import VGGBackbone, VGGHead  # noqa: F401
