"""ResNet-50/101 backbone and per-ROI head.

Reference: ``rcnn/symbol/symbol_resnet.py`` — ``residual_unit`` (pre-activation
bottleneck, BN eps 2e-5 with frozen statistics), ``get_resnet_conv`` (bn_data
→ conv0 7x7/2 → bn0 → pool → stages of [3, 4, 23] units for ResNet-101,
ending at stride 16), and the train/test symbols which run the final
2048-filter stage (3 units, first stride 2) **per ROI after ROIPooling**,
followed by global average pooling — the ResNet head has no fc6/fc7.

Split here into :class:`ResNetBackbone` (shared, stride 16) and
:class:`ResNetHead` (applied to pooled 14x14 ROI features).  NHWC, params
fp32, compute dtype configurable (bf16 for the MXU).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from mx_rcnn_tpu.models.layers import FrozenBatchNorm, conv
from mx_rcnn_tpu.ops.quant import QuantSpec

Dtype = Any

# units per stage, as in the reference's resnet() factory
STAGE_UNITS = {
    50: (3, 4, 6, 3),
    101: (3, 4, 23, 3),
    152: (3, 8, 36, 3),
}


class BottleneckUnit(nn.Module):
    """Pre-activation bottleneck (ref ``residual_unit`` with bottle_neck=True):
    bn→relu→1x1 conv(f/4) → bn→relu→3x3 conv(f/4, stride) → bn→relu→1x1
    conv(f), with a 1x1 projection shortcut from the first activation when
    shape changes."""

    filters: int
    stride: int = 1
    dim_match: bool = True
    dtype: Dtype = jnp.float32
    # inference-only quantization recipe (ops/quant.py); None = the
    # unchanged fp path (bit-identical, pinned by tests/test_quant.py)
    quant: Optional[QuantSpec] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        act1 = nn.relu(FrozenBatchNorm(dtype=self.dtype, name="bn1")(x))
        c1 = conv(self.filters // 4, (1, 1), dtype=self.dtype, use_bias=False,
                  name="conv1", quant=self.quant)(act1)
        act2 = nn.relu(FrozenBatchNorm(dtype=self.dtype, name="bn2")(c1))
        c2 = conv(self.filters // 4, (3, 3), (self.stride, self.stride),
                  dtype=self.dtype, use_bias=False, name="conv2",
                  quant=self.quant)(act2)
        act3 = nn.relu(FrozenBatchNorm(dtype=self.dtype, name="bn3")(c2))
        # zero-init the residual branch output: with frozen identity BN a
        # he-init conv3 doubles activation variance per unit (2^33 by the end
        # of ResNet-101) and from-scratch training NaNs on step one.  The
        # reference never hits this because it always starts from ImageNet
        # weights with real BN statistics; zero init makes random init sane
        # and is overwritten anyway when pretrained weights load.
        c3 = conv(self.filters, (1, 1), dtype=self.dtype, use_bias=False,
                  kernel_init=nn.initializers.zeros, name="conv3",
                  quant=self.quant)(act3)
        if self.dim_match:
            shortcut = x
        else:
            shortcut = conv(self.filters, (1, 1), (self.stride, self.stride),
                            dtype=self.dtype, use_bias=False, name="sc",
                            quant=self.quant)(act1)
        return c3 + shortcut


def _stage(x: jnp.ndarray, filters: int, units: int, stride: int,
           dtype: Dtype, name_prefix: str,
           quant: Optional[QuantSpec] = None) -> jnp.ndarray:
    for u in range(units):
        x = BottleneckUnit(
            filters=filters,
            stride=stride if u == 0 else 1,
            dim_match=False if u == 0 else True,
            dtype=dtype,
            quant=quant,
            name=f"{name_prefix}_unit{u + 1}",
        )(x)
    return x


class ResNetBackbone(nn.Module):
    """Shared conv feature extractor, stride 16 (ref ``get_resnet_conv``).

    Input: (N, H, W, 3) raw pixels minus PIXEL_MEANS (RGB).
    Output: (N, H/16, W/16, 1024).
    """

    depth: int = 101
    dtype: Dtype = jnp.float32
    quant: Optional[QuantSpec] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        units = STAGE_UNITS[self.depth]
        x = x.astype(self.dtype)
        # ref: bn_data (BatchNorm on raw input, fix_gamma=True)
        x = FrozenBatchNorm(dtype=self.dtype, name="bn_data")(x)
        x = conv(64, (7, 7), (2, 2), dtype=self.dtype, use_bias=False,
                 name="conv0", quant=self.quant)(x)
        x = nn.relu(FrozenBatchNorm(dtype=self.dtype, name="bn0")(x))
        x = nn.max_pool(x, (3, 3), (2, 2), padding=[(1, 1), (1, 1)])
        x = _stage(x, 256, units[0], 1, self.dtype, "stage1", self.quant)
        x = _stage(x, 512, units[1], 2, self.dtype, "stage2", self.quant)
        x = _stage(x, 1024, units[2], 2, self.dtype, "stage3", self.quant)
        return x


class ResNetHead(nn.Module):
    """Per-ROI head: the 2048-filter stage + global average pool
    (ref train/test symbols: stage applied after ROIPooling(14,14),
    first unit stride 2 → 7x7 → pool → (R, 2048))."""

    depth: int = 101
    dtype: Dtype = jnp.float32
    quant: Optional[QuantSpec] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        units = STAGE_UNITS[self.depth]
        x = x.astype(self.dtype)
        x = _stage(x, 2048, units[3], 2, self.dtype, "stage4", self.quant)
        # ref: bn1 + relu1 + global pool close the v2-style network
        x = nn.relu(FrozenBatchNorm(dtype=self.dtype, name="bn1")(x))
        return jnp.mean(x, axis=(1, 2))  # (R, 2048)
