"""Region Proposal Network head.

Reference: the RPN block repeated in ``rcnn/symbol/symbol_vgg.py`` /
``symbol_resnet.py``: ``rpn_conv_3x3`` (512ch) + relu → ``rpn_cls_score``
(1x1, 2A channels) and ``rpn_bbox_pred`` (1x1, 4A channels), initialized
Normal(0.01).

Output layout: NHWC with the per-cell anchors innermost — reshaped to
``(H*W*A, 2)`` scores and ``(H*W*A, 4)`` deltas, matching the framework's
HWA anchor enumeration (see ``ops/anchors.py``).
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp

from mx_rcnn_tpu.models.layers import conv

Dtype = Any


class RPNHead(nn.Module):
    num_anchors: int = 9
    mid_channels: int = 512
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, feat: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """feat (N, H, W, C) → (cls_logits (N, H*W*A, 2), deltas (N, H*W*A, 4))."""
        init = nn.initializers.normal(0.01)
        x = nn.relu(
            conv(self.mid_channels, (3, 3), dtype=self.dtype,
                 kernel_init=init, name="rpn_conv_3x3")(feat)
        )
        cls = conv(2 * self.num_anchors, (1, 1), dtype=self.dtype,
                   kernel_init=init, name="rpn_cls_score")(x)
        box = conv(4 * self.num_anchors, (1, 1), dtype=self.dtype,
                   kernel_init=init, name="rpn_bbox_pred")(x)
        n, h, w, _ = cls.shape
        cls = cls.reshape(n, h * w * self.num_anchors, 2)
        box = box.reshape(n, h * w * self.num_anchors, 4)
        return cls, box
