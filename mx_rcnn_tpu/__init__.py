"""mx_rcnn_tpu — a TPU-native Faster R-CNN training & evaluation framework.

A from-scratch JAX/XLA/Pallas rebuild with the capabilities of the MXNet
reference `wfxiang08/mx-rcnn` (see SURVEY.md at the repo root):

* Flax modules for the VGG16 / ResNet-50/101 backbones and the RPN / RCNN
  heads (reference: ``rcnn/symbol/symbol_vgg.py``, ``symbol_resnet.py``).
* Static-shape, jit-compatible re-designs of the reference's host-side /
  CustomOp layers: ``anchor_target`` (ref ``rcnn/io/rpn.py — assign_anchor``),
  ``proposal`` (ref ``rcnn/symbol/proposal.py`` + ``mx.symbol.Proposal``) and
  ``proposal_target`` (ref ``rcnn/symbol/proposal_target.py``) — everything
  from the input batch onward runs in ONE XLA program per step.
* TPU data parallelism via ``jax.sharding.Mesh`` + ``shard_map`` with
  ``lax.psum`` gradient sync over ICI (replacing MXNet ``kvstore='device'``).
* Pallas TPU kernels for the hot non-conv ops (ROI pooling, NMS) replacing
  the reference's CUDA/Cython kernels (ref ``rcnn/cython/``).
"""

__version__ = "0.1.0"

from mx_rcnn_tpu.config import Config, generate_config  # noqa: F401
