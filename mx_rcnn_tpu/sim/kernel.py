"""Discrete-event virtual-time kernel: deterministic heap + seeded RNG.

The whole simulator runs on ONE thread against this kernel; nothing in
``sim/`` ever reads a wall clock.  Events are ``(time, seq, fn)`` heap
entries — ``seq`` is a monotonic tiebreaker, so two events scheduled
for the same instant fire in scheduling order, every run, which is what
makes the decision log byte-reproducible.  Randomness comes only from
:meth:`SimKernel.rng` substreams: each named stream seeds a private
``np.random.RandomState`` from ``sha256(seed:name)``, so adding a new
consumer of randomness never perturbs the draws of existing ones (the
same trick ``ft/supervisor.py`` uses for deterministic backoff jitter).

:class:`VirtualClock` is a zero-argument callable returning the current
virtual time — exactly the shape of ``time.monotonic`` — so it plugs
straight into the clock seams of ``TimeSeriesStore``, ``HealthEngine``,
``SchedulerPolicy``, ``RestartPolicy`` and ``Collector``.
"""

from __future__ import annotations

import hashlib
import heapq
import itertools
from typing import Callable, List, Tuple

import numpy as np


class VirtualClock:
    """Monotonic virtual time as a ``time.monotonic``-shaped callable.

    Only the kernel advances it (monotonically, to each event's fire
    time); everything else just reads."""

    def __init__(self, t0: float = 0.0):
        self._now = float(t0)

    def __call__(self) -> float:
        return self._now

    @property
    def now(self) -> float:
        return self._now


class SimKernel:
    """Event heap + clock + named RNG substreams.

    ``at``/``after`` schedule, ``run_until`` drains.  A callback may
    schedule further events (including at the current instant — they
    fire later this drain, after everything already queued for it).
    """

    def __init__(self, seed: int = 0, t0: float = 0.0):
        self.seed = int(seed)
        self.clock = VirtualClock(t0)
        self._heap: List[Tuple[float, int, Callable[[], None]]] = []
        self._seq = itertools.count()
        self.fired = 0

    # -- randomness --------------------------------------------------------

    def rng(self, name: str) -> np.random.RandomState:
        """A private RandomState for one named consumer, derived from
        ``sha256(seed:name)`` — stable across runs and across unrelated
        code changes."""
        h = hashlib.sha256(f"{self.seed}:{name}".encode()).hexdigest()
        return np.random.RandomState(int(h[:8], 16))

    # -- scheduling --------------------------------------------------------

    def at(self, t: float, fn: Callable[[], None]) -> None:
        """Schedule ``fn`` at virtual time ``t`` (clamped to now — the
        past is not schedulable)."""
        heapq.heappush(self._heap,
                       (max(float(t), self.clock.now),
                        next(self._seq), fn))

    def after(self, dt: float, fn: Callable[[], None]) -> None:
        self.at(self.clock.now + max(float(dt), 0.0), fn)

    def __len__(self) -> int:
        return len(self._heap)

    # -- draining ----------------------------------------------------------

    def run_until(self, t_end: float) -> int:
        """Fire every event with time <= ``t_end`` in deterministic
        order, advancing the clock to each event's instant, then park
        the clock at ``t_end``.  Returns events fired this call."""
        t_end = float(t_end)
        n = 0
        while self._heap and self._heap[0][0] <= t_end:
            t, _, fn = heapq.heappop(self._heap)
            self.clock._now = t
            fn()
            n += 1
        self.clock._now = max(self.clock._now, t_end)
        self.fired += n
        return n

    def run_until_idle(self, t_cap: float) -> int:
        """Drain until the heap empties or the next event lies past
        ``t_cap`` — the post-trace settle pass."""
        n = 0
        while self._heap and self._heap[0][0] <= t_cap:
            n += self.run_until(self._heap[0][0])
        self.fired += 0  # counted inside run_until
        return n
