"""The harness: SHIPPED control-plane code in the loop, virtual time.

This module wires a :class:`~mx_rcnn_tpu.sim.cluster.SimCluster` to the
REAL production classes through their clock seams:

* a real ``Collector`` (one ``RegistrySource`` per simulated host, one
  ``head`` source) scrapes the hosts every virtual
  ``cfg.sim.scrape_interval_s`` and appends ``view_to_snapshot`` output
  into a real ``TimeSeriesStore`` — the exact path
  ``RemoteBacklogFeed`` / ``tools/obs.py check`` use in production;
* a real ``HealthEngine`` (:func:`sim_rules`) judges every sample;
  CRITICAL/WARN dwell time becomes the SLO-minutes score;
* a real ``FleetScheduler`` (the shipped ``SchedulerPolicy``) decides
  adds/drains off the store; actuation goes through :class:`SimAdmin`,
  which duck-types ``AgentAdmin`` (typed ``last_error``, None on a dead
  host) over ``SimCluster.resize``;
* a real ``RestartPolicy`` (``ft/supervisor.py``) paces crash-looping
  hosts' relaunches and delivers the give-up verdict;
* routing inside the cluster already runs the shipped ``jsq_key``.

The **mistuned red-team arm** (:data:`MISTUNED_OVERRIDES`) runs the
same code with sabotaged knobs: infinite action hysteresis (the deficit
and overload signals never fire), unreachable overload thresholds, a
zero-hysteresis drain with the floor inverted to one replica
fleet-wide.  The gauntlet requires it to measurably breach on at least
one scenario where the shipped tuning does not.

Nothing here reads a wall clock; the decision log is a list of plain
dicts whose canonical JSON bytes are reproducible for a given
(trace, seed) — ``tests/test_sim.py`` pins byte identity.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.ft.supervisor import RestartPolicy
from mx_rcnn_tpu.obs.collect import (Collector, RegistrySource,
                                     view_to_snapshot)
from mx_rcnn_tpu.obs.health import CRITICAL, WARN, HealthEngine, Rule
from mx_rcnn_tpu.obs.timeseries import TimeSeriesStore
from mx_rcnn_tpu.serve.rollout import (DONE, ROLLED_BACK,
                                       OnlinePairedGate,
                                       RolloutController, rollout_rules,
                                       version_label)
from mx_rcnn_tpu.serve.scheduler import AgentAdminError, FleetScheduler
from mx_rcnn_tpu.sim.cluster import READY, SimCluster
from mx_rcnn_tpu.sim.kernel import SimKernel
from mx_rcnn_tpu.sim.score import score_run
from mx_rcnn_tpu.sim.traffic import fleet_capacity_rps, rate_at

logger = logging.getLogger("mx_rcnn_tpu")

# the red-team policy: same code, sabotaged tuning (see module doc)
MISTUNED_OVERRIDES = {
    "crosshost__for_samples": 100_000,   # blind to deficit + overload
    "crosshost__up_shed_ratio": 9.0,     # unreachable (ratio <= 1)
    "crosshost__up_backlog": 1e9,
    "crosshost__idle_samples": 1,        # zero drain hysteresis
    "crosshost__cooldown_s": 0.0,
    "crosshost__min_replicas": 1,        # inverted drain floor
}


def apply_overrides(cfg: Config, overrides: Dict) -> Config:
    """``section__field`` dict → new Config (the trace/arm knob path).
    Values keep the declared field's type via dataclasses.replace."""
    by_section: Dict[str, Dict] = {}
    for key, val in overrides.items():
        section, fname = key.split("__", 1)
        cur = getattr(getattr(cfg, section), fname)
        if cur is not None and not isinstance(val, type(cur)):
            val = type(cur)(val)
        by_section.setdefault(section, {})[fname] = val
    for section, kw in by_section.items():
        cfg = cfg.replace_in(section, **kw)
    return cfg


def sim_rules(cfg: Config) -> List[Rule]:
    """The scenario SLO set over the head's fleet.* surface — the
    judgments production monitoring would page on.  Lost work and
    sustained shedding are CRITICAL; degraded capacity and a fat tail
    are WARN (capacity state, not user-facing damage — yet)."""
    w = cfg.crosshost.window_s
    deadline = cfg.serve.default_timeout_ms or 2000.0
    return [
        Rule("sim-lost-expired", "fleet.expired", "delta", ">", 0.0,
             window_s=w, severity=CRITICAL, for_samples=1,
             clear_samples=3),
        Rule("sim-lost-failed", "fleet.failed", "delta", ">", 0.0,
             window_s=w, severity=CRITICAL, for_samples=1,
             clear_samples=3),
        Rule("sim-shed-frac", "fleet.shed/fleet.submitted", "ratio",
             ">", 0.05, window_s=max(w, 20.0), severity=CRITICAL,
             for_samples=2, clear_samples=2),
        Rule("sim-degraded", "fleet.replicas_ready", "gauge", "<",
             float(cfg.crosshost.target_replicas), window_s=15.0,
             severity=WARN, for_samples=1, clear_samples=1),
        Rule("sim-p99-budget", "fleet.total_ms", "p99", ">",
             0.9 * deadline, window_s=w, severity=WARN),
    ]


class SimAdmin:
    """``AgentAdmin`` duck type over the cluster: ``resize`` + typed
    ``last_error``; a down host answers None exactly like a refused
    socket, and the next tick's deficit re-places on a live agent."""

    def __init__(self, cluster: SimCluster):
        self.cluster = cluster
        self.last_error: Optional[AgentAdminError] = None

    def resize(self, source: str, delta: int) -> Optional[Dict]:
        try:
            index = int(source.rsplit("-", 1)[1])
        except (IndexError, ValueError):
            self.last_error = AgentAdminError(f"bad source {source!r}")
            return None
        result = self.cluster.resize(index, int(delta))
        if result is None:
            self.last_error = AgentAdminError(f"{source} is down")
            return None
        self.last_error = None
        return result


class SimRolloutPort:
    """``RolloutController`` port over the simulated cluster — the
    virtual-time twin of ``AgentRolloutPort``.  Down hosts answer None
    on every verb (the controller's defer/re-converge machinery owns
    retries), and the shadow-pair quality model is deterministic from
    the kernel's ``shadow`` RNG substream: a healthy canary scores
    IDENTICALLY to base (the same program on the same canvas — paired
    deltas exactly zero), while the red-team arm's canary scores
    ``rollout.redteam_damage`` lower plus small jitter, which is
    exactly the kind of silent model damage only the paired gate can
    see (latency and failure metrics stay clean)."""

    def __init__(self, run: "SimRun", version: str, damage: float):
        self.run = run
        self.version = version
        self.damage = float(damage)
        self._rng = run.k.rng("shadow")

    @staticmethod
    def _index(source: str) -> int:
        return int(source.rsplit("-", 1)[1])

    def sources(self) -> List[str]:
        return [h.name for h in self.run.cluster.hosts]

    def pull(self, source: str, url: str, version: str):
        return self.run.cluster.pull_version(self._index(source),
                                             version)

    def versions(self, source: str):
        return self.run.cluster.host_versions(self._index(source))

    def swap_next(self, source: str, version: str):
        return self.run.cluster.swap_replica(self._index(source),
                                             version)

    def rollback(self, source: str):
        return self.run.cluster.rollback_host(self._index(source))

    def set_canary(self, version, fraction: float) -> None:
        self.run.cluster.set_canary(version, fraction)

    def shadow_pair(self):
        for h in self.run.cluster.hosts:
            if not h.up or self.version not in h.pulled:
                continue
            ready = [r for r in h.replicas if r.state == READY]
            if not (any(r.version == self.version for r in ready)
                    and any(r.version != self.version
                            for r in ready)):
                continue
            base = 0.8 + 0.05 * float(self._rng.standard_normal())
            if self.damage:
                canary = (base - self.damage
                          + 0.01 * float(self._rng.standard_normal()))
            else:
                canary = base
            return (round(base, 6), round(canary, 6))
        return None


class SimRun:
    """One arm of the gauntlet: one trace, one config, one seed."""

    def __init__(self, trace: Dict, cfg: Config, label: str = "shipped",
                 arm_overrides: Optional[Dict] = None):
        overrides = dict(trace.get("overrides") or {})
        overrides.update(arm_overrides or {})
        self.cfg = apply_overrides(cfg, overrides)
        self.trace = trace
        self.label = label
        self.k = SimKernel(seed=int(trace["seed"]))
        self.log: List[Dict] = []
        self.cluster = SimCluster(self.k, self.cfg, trace["hosts"],
                                  self._log)
        interval = self.cfg.sim.scrape_interval_s
        horizon = trace["duration_s"] + self.cfg.sim.settle_s
        self.store = TimeSeriesStore(
            capacity=int(horizon / interval) + 16,
            clock=self.k.clock)
        self.collector = Collector(
            [RegistrySource(h.name, h.resolve)
             for h in self.cluster.hosts]
            + [RegistrySource("head", lambda: (self.cluster.head, {}))],
            clock=self.k.clock)
        self._rollout_spec = trace.get("rollout")
        self.rollout: Optional[RolloutController] = None
        rules = sim_rules(self.cfg)
        if self._rollout_spec:
            # per-version canary SLO rules ride in the SAME engine the
            # scorer reads: a canary breach is an SLO breach
            rules = rules + rollout_rules(
                self.cfg, self._rollout_spec["version"])
        self.engine = HealthEngine(rules, self.store,
                                   clock=self.k.clock,
                                   on_transition=self._on_health)
        self.scheduler = FleetScheduler(self.store,
                                        SimAdmin(self.cluster),
                                        self.cfg, clock=self.k.clock)
        self._flap_policies: Dict[int, RestartPolicy] = {}
        self._arr_rng = self.k.rng("arrivals")
        self._bucket_rng = self.k.rng("buckets")
        weights = trace["bucket_weights"]
        self._bucket_shapes = [tuple(b) for b, _ in weights]
        self._bucket_cum = np.cumsum([w for _, w in weights])
        self._replica_rate = (
            fleet_capacity_rps(self.cfg, trace["hosts"])
            / (trace["hosts"]
               * max(int(self.cfg.crosshost.agent_replicas), 1)))
        self.critical_s = 0.0
        self.warn_s = 0.0
        self.wasted_replica_s = 0.0

    # -- logging -----------------------------------------------------------

    def _log(self, kind: str, **kw) -> None:
        entry = {"t": round(self.k.clock.now, 6), "kind": kind}
        entry.update(kw)
        self.log.append(entry)

    def _on_health(self, prev: str, new: str, verdict: Dict) -> None:
        self._log("health", prev=prev, verdict=new,
                  firing=list(verdict["firing"]))

    def _rollout_log(self, kind: str, **kw) -> None:
        kw.pop("t", None)  # the harness stamps its own virtual time
        self._log(f"rollout_{kind}", **kw)

    def _start_rollout(self) -> None:
        ro = self._rollout_spec
        port = SimRolloutPort(self, ro["version"],
                              self.cfg.rollout.redteam_damage)
        self.rollout = RolloutController(
            port, self.cfg, version=ro["version"],
            store_url=ro.get("store_url", "sim://store"),
            gate=OnlinePairedGate(
                budget=self.cfg.rollout.gate_budget,
                min_pairs=self.cfg.rollout.gate_min_pairs),
            health=self.engine, clock=self.k.clock,
            log=self._rollout_log)
        self.scheduler.rollout = self.rollout
        self.rollout.start()

    # -- the scrape/judge/act tick ----------------------------------------

    def _tick(self) -> None:
        now = self.k.clock.now
        self.cluster.refresh_gauges()
        view = self.collector.collect()
        self.store.append_snapshot(view_to_snapshot(view), ts=now)
        verdict = self.engine.evaluate()
        interval = self.cfg.sim.scrape_interval_s
        if verdict["verdict"] == CRITICAL:
            self.critical_s += interval
        elif verdict["verdict"] == WARN:
            self.warn_s += interval
        ready = self.cluster.ready_count()
        needed = max(rate_at(self.trace, now) / self._replica_rate,
                     float(self.cfg.crosshost.min_replicas))
        self.wasted_replica_s += interval * max(ready - needed, 0.0)
        action = self.scheduler.tick()
        if action is not None:
            entry = {k: action.get(k) for k in
                     ("action", "source", "reason", "ready", "target",
                      "corr")}
            result = action.get("result")
            entry["result"] = (dict(result)
                               if isinstance(result, dict) else result)
            if "error" in action:
                entry["error"] = action["error"]
            self._log("action", **entry)
        if (self.rollout is not None
                and self.rollout.phase not in (DONE, ROLLED_BACK)):
            self.rollout.step()
        nxt = now + interval
        if nxt <= self.trace["duration_s"] + self.cfg.sim.settle_s:
            self.k.at(nxt, self._tick)

    # -- arrivals ----------------------------------------------------------

    def _draw_bucket(self) -> Tuple[int, int]:
        u = self._bucket_rng.random_sample()
        i = int(np.searchsorted(self._bucket_cum, u, side="right"))
        return self._bucket_shapes[min(i, len(self._bucket_shapes) - 1)]

    def _arrive(self) -> None:
        now = self.k.clock.now
        if now < self.trace["duration_s"]:
            self.cluster.submit(self._draw_bucket())
        rate = rate_at(self.trace, self.k.clock.now)
        if rate <= 0.0:
            return
        gap = float(self._arr_rng.exponential(1.0 / rate))
        nxt = self.k.clock.now + gap
        if nxt < self.trace["duration_s"]:
            self.k.at(nxt, self._arrive)

    # -- trace events ------------------------------------------------------

    def _install_events(self) -> None:
        if self._rollout_spec:
            self.k.at(float(self._rollout_spec.get("t_start", 10.0)),
                      self._start_rollout)
        for ev in self.trace.get("events", []):
            kind, host = ev["kind"], int(ev["host"])
            if kind == "host_down":
                self.k.at(ev["t"],
                          lambda h=host: self.cluster.host_down(h))
            elif kind == "host_flap":
                self.k.at(ev["t"], lambda h=host: self._flap(h))
            elif kind == "drain_host":
                self.k.at(ev["t"],
                          lambda h=host: self.cluster.drain_host(h))
            else:
                raise ValueError(f"unknown trace event kind {kind!r}")

    def _flap(self, host: int) -> None:
        """One crash of a crash-looping host, paced and judged by the
        SHIPPED RestartPolicy (virtual clock through its seam)."""
        pol = self._flap_policies.get(host)
        if pol is None:
            pol = RestartPolicy(base_s=4.0, factor=2.0, cap_s=60.0,
                                give_up_after=3,
                                seed=int(self.trace["seed"]) + host,
                                registry=self.cluster.head,
                                clock=self.k.clock)
            self._flap_policies[host] = pol
        self.cluster.host_down(host)
        delay, give_up = pol.record(("preempt", host),
                                    made_progress=False)
        self._log("supervisor", host=f"agent-{host}",
                  failures=pol.failures, backoff_s=round(delay, 3),
                  give_up=give_up)
        if give_up:
            return  # crash-loop verdict: stays dead, deficit re-places
        self.k.at(pol.ready_at, lambda: self._flap_relaunch(host))

    def _flap_relaunch(self, host: int) -> None:
        self.cluster.host_up(host)
        # the flapper stays up briefly, then crashes the same way again
        self.k.after(6.0, lambda: self._flap(host))

    # -- run ---------------------------------------------------------------

    def run(self) -> Dict:
        self._install_events()
        self.k.at(0.0, self._tick)
        first_rate = rate_at(self.trace, 0.0)
        if first_rate > 0.0:
            self.k.at(float(self._arr_rng.exponential(1.0 / first_rate)),
                      self._arrive)
        horizon = self.trace["duration_s"] + self.cfg.sim.settle_s
        self.k.run_until(self.trace["duration_s"])
        self.k.run_until(horizon)
        stranded = self.cluster.pending()
        if stranded:
            self._log("settle_timeout", stranded=stranded)
            self.cluster.fail_pending()
        p99 = self.cluster.head.hist("fleet.total_ms")
        p99 = None if p99 is None else p99.percentile(99)
        score = score_run(self.cluster.stats, self.critical_s,
                          self.warn_s, self.wasted_replica_s,
                          self.cluster.wait_ms_max, p99, self.log)
        score["label"] = self.label
        score["events_fired"] = self.k.fired
        if self.rollout is not None:
            census: Dict[str, int] = {}
            for h in self.cluster.hosts:
                if not h.up:
                    continue
                for r in h.replicas:
                    if r.state == READY:
                        lbl = version_label(r.version)
                        census[lbl] = census.get(lbl, 0) + 1
            score["rollout"] = {
                "phase": self.rollout.phase,
                "reason": self.rollout._rollback_reason,
                "rollback_s": self.rollout.rollback_s,
                "gate": self.rollout.gate.verdict(),
                "final_versions": census,
                "per_version": {k: dict(v) for k, v in
                                sorted(self.cluster.ver_stats.items())},
            }
        return score
