"""Scoring rubric: what a control-plane policy is judged on.

Three axes, worst first:

* **lost requests** — EXPIRED + FAILED terminals.  A shed is NOT lost:
  a 429 at the watermark is the system keeping its latency promise
  under demand it cannot absorb; an expiry or a reroute-exhausted
  failure is a request the system accepted and then betrayed.
* **SLO-minutes breached** — virtual minutes the health engine's
  verdict sat at CRITICAL (WARN-minutes reported alongside).  Judged by
  the SHIPPED ``HealthEngine`` over the same samples the scheduler
  read, so "breached" means what production monitoring would have paged
  on.
* **capacity-seconds wasted** — integral of ready replicas above what
  the demand curve needed (per-replica service rate from the same
  measured distribution the hosts serve with), floored at the
  scenario's ``min_replicas``.  Over-provisioning is the cheapest
  failure, but it is still a failure — a policy could ace the first two
  axes by never draining anything.

Every number is derived from exact event-level integer counts (not
scraped gauges), so the hand-computed mini-trace in
``tests/test_sim.py`` can pin the scorer to the digit.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List


def decision_log_bytes(log: List[Dict]) -> bytes:
    """Canonical byte form of a decision log: one sorted-key JSON
    object per line.  Byte-identical across runs of the same trace +
    seed — the determinism contract ``tests/test_sim.py`` pins."""
    import json

    return ("\n".join(json.dumps(e, sort_keys=True) for e in log)
            + ("\n" if log else "")).encode()


def score_run(stats: Dict, critical_s: float, warn_s: float,
              wasted_replica_s: float, wait_ms_max: float,
              p99_ms, log: List[Dict]) -> Dict:
    """Collapse one run's exact counts into the BENCH-style score."""
    lost = int(stats["expired"]) + int(stats["failed"])
    blob = decision_log_bytes(log)
    return {
        "submitted": int(stats["submitted"]),
        "served": int(stats["served"]),
        "shed": int(stats["shed"]),
        "expired": int(stats["expired"]),
        "failed": int(stats["failed"]),
        "rerouted": int(stats["rerouted"]),
        "lost": lost,
        "slo_critical_minutes": round(critical_s / 60.0, 4),
        "slo_warn_minutes": round(warn_s / 60.0, 4),
        "capacity_wasted_replica_s": round(wasted_replica_s, 1),
        "wait_ms_max": round(float(wait_ms_max), 1),
        "served_p99_ms": (None if p99_ms is None
                          else round(float(p99_ms), 1)),
        "actions": sum(1 for e in log if e.get("kind") == "action"),
        "decision_log_entries": len(log),
        "decision_log_sha256": hashlib.sha256(blob).hexdigest(),
    }
